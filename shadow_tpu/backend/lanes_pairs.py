"""int32 pair arithmetic for the lane kernels.

TPU has no native int64: every i64 op lowers to X64Split/Combine custom
calls that cannot fuse, fragmenting the while body into tiny kernels whose
per-launch overhead dominates on the tunneled runtime.  All resident lane
state therefore uses (hi, lo) int32 pairs with value = hi * 2**31 + lo,
lo in [0, 2**31); (NEVER32, NEVER32) encodes the NEVER sentinel for
time-valued pairs.  Every helper here is exact within its documented
range and compiles to plain fusable int32 lanes.
"""

from __future__ import annotations

import jax.numpy as jnp

NEVER32 = 0x7FFFFFFF  # plain int: no device array at import time
MASK31 = 0x7FFFFFFF


def pair_lt(ahi, alo, bhi, blo):
    return (ahi < bhi) | ((ahi == bhi) & (alo < blo))


def pair_ge(ahi, alo, bhi, blo):
    return ~pair_lt(ahi, alo, bhi, blo)


def pair_min_lanes(hi, lo):
    """Lexicographic min over all elements of an (hi, lo) pair array."""
    mh = jnp.min(hi)
    ml = jnp.min(jnp.where(hi == mh, lo, NEVER32))
    return mh, ml


def pair_add32(hi, lo, x):
    """pair + x for 0 <= x < 2**31 (x int32 scalar or [N])."""
    t = lo + x  # may wrap into the sign bit: that IS the carry
    return hi + (t < 0).astype(jnp.int32), t & MASK31


def pair_sub32(hi, lo, x):
    """pair - x for 0 <= x < 2**31; caller guarantees pair >= x.
    t < 0 means the true low word is t + 2**31, whose int32 bit pattern
    is t & MASK31 (adding 2**31 just clears the sign bit mod 2**32)."""
    t = lo - x
    return hi - (t < 0).astype(jnp.int32), t & MASK31


def pair_add_pair(ahi, alo, bhi, blo):
    t = alo + blo
    return ahi + bhi + (t < 0).astype(jnp.int32), t & MASK31


def pair_max(ahi, alo, bhi, blo):
    a_wins = pair_ge(ahi, alo, bhi, blo)
    return jnp.where(a_wins, ahi, bhi), jnp.where(a_wins, alo, blo)


def pair_sel(c, ahi, alo, bhi, blo):
    return jnp.where(c, ahi, bhi), jnp.where(c, alo, blo)


def pair_sub_clamp(ahi, alo, bhi, blo, lim):
    """max(0, min(a - b, lim)) as int32 — exact whenever the true
    difference lies in [0, lim] (lim < 2**31)."""
    d = ahi - bhi
    raw = alo - blo  # in (-2**31, 2**31)
    ge = pair_ge(ahi, alo, bhi, blo)
    # d == 1 with raw < 0: value = 2**31 + raw = (raw + 1) + MASK31,
    # which cannot overflow because raw + 1 <= 0
    return jnp.where(
        ~ge,
        0,
        jnp.where(
            d == 0,
            jnp.minimum(raw, lim),
            jnp.where(
                (d == 1) & (raw < 0),
                jnp.minimum((raw + 1) + MASK31, lim),
                lim,
            ),
        ),
    )


def pair_sub_pair(ahi, alo, bhi, blo):
    """a - b as a pair, valid when a >= b (callers mask the a < b case)."""
    t = alo - blo
    borrow = (t < 0).astype(jnp.int32)
    return ahi - bhi - borrow, t & MASK31


def pair_abs_diff(ahi, alo, bhi, blo):
    """|a - b| as a pair (both subtractions computed, the valid one kept)."""
    ge = pair_ge(ahi, alo, bhi, blo)
    d1h, d1l = pair_sub_pair(ahi, alo, bhi, blo)
    d2h, d2l = pair_sub_pair(bhi, blo, ahi, alo)
    return pair_sel(ge, d1h, d1l, d2h, d2l)


def pair_div_pow2(hi, lo, k: int):
    """(hi, lo) >> k for static 1 <= k <= 30 (non-negative pairs)."""
    mask = (1 << k) - 1
    return hi >> k, ((hi & mask) << (31 - k)) + (lo >> k)


def pair_mul_small(hi, lo, c: int):
    """pair * c for a small static 1 <= c <= 7; caller guarantees the
    product fits the pair range (hi * c < 2**31).  Decomposes lo so every
    int32 intermediate stays in range: lo = lh*2**16 + ll, and
    lh*c = q*2**15 + s gives lo*c = q*2**31 + s*2**16 + ll*c.  The final
    sum can reach 2**31 + 65535*c, one carry past the low word: the int32
    wrap IS that carry (sign bit set), recovered exactly like
    pair_add32."""
    if not 1 <= c <= 7:
        raise ValueError(f"pair_mul_small: c={c} out of range")
    lh = lo >> 16
    ll = lo & 0xFFFF
    mid = lh * c
    q = mid >> 15
    s = mid & 0x7FFF
    t = (s << 16) + ll * c
    return hi * c + q + (t < 0).astype(jnp.int32), t & MASK31


# engine-guarded ceiling for pair_mod_small's modulus: every intermediate
# of the chunked reduction must fit int32 (see the derivation below)
MOD_SMALL_LIMIT = 1 << 22


def pair_mod_small(hi, lo, m: int):
    """``(hi * 2**31 + lo) % m`` for a STATIC modulus ``m < 2**22``, in pure
    int32 lanes — the X64-emulated int64 ``%`` breaks fusion and was the
    last custom call in the passive hot loop.

    Reduction: ``v % m = ((hi % m) * (2**31 % m) + lo % m) % m``; the
    product is folded 8 bits at a time with the STATIC chunks of
    ``M = 2**31 % m``, so every intermediate is ``< m*256 + m*255 < 2**31``
    when ``m < 2**22``."""
    if m >= MOD_SMALL_LIMIT:
        raise ValueError(f"pair_mod_small: modulus {m} >= {MOD_SMALL_LIMIT}")
    big_m = (1 << 31) % m
    a = hi % m
    r = jnp.zeros_like(a)
    for shift in (24, 16, 8, 0):
        chunk = (big_m >> shift) & 0xFF
        r = ((r << 8) + a * chunk) % m
    return (r + lo % m) % m
