"""CPU reference backend: the scalar implementation of docs/SEMANTICS.md.

Structural analog of the reference's Controller/Manager/Host round loop
(controller.rs:81-113, manager.rs:541-770, host.rs:762-830), collapsed into
one process: rounds advance all hosts over a conservative lookahead window;
cross-host packets land in the destination's event queue for later windows.
This backend is the determinism oracle the TPU lane backend is diffed
against, and the fallback for configs the lane vocabulary can't express yet.
"""

from __future__ import annotations

import dataclasses
import time as wall_time
from typing import Optional

from ..config.options import ConfigOptions
from ..core import rng as rng_mod
from ..core import time as stime
from ..core.event import Event, EventKind, Task
from ..core.event_queue import EventQueue
from ..models import phold as _phold  # noqa: F401  (register built-ins)
from ..models import tcpflow as _tcpflow  # noqa: F401
from ..models import tgen as _tgen  # noqa: F401
from ..models import tgen_tcp as _tgen_tcp  # noqa: F401
from ..models.base import create_model
from ..net.codel import CoDel
from ..net.graph import IpAssignment, NetworkGraph, RoutingInfo
from ..net.stack import TcpSegment as _TcpSegment
from ..net.token_bucket import (
    FRAME_OVERHEAD_BYTES,
    TokenBucket,
    bucket_params,
)
from ..obs import flowtrace as ftr

# event-log outcome codes (SEMANTICS.md)
DELIVERED = 0
DROP_LOSS = 1
DROP_CODEL = 2
DROP_QUEUE = 3

OUTCOME_NAMES = {0: "delivered", 1: "loss", 2: "codel", 3: "queue"}

# the loopback interface's fixed one-way delay (the reference gives every
# host a localhost/internet interface pair, namespace.rs:25-60; here lo
# is a latency-only serial law: no token buckets, no CoDel, no loss —
# self-addressed 127/8 traffic from managed stacks rides it)
LOOPBACK_LATENCY_NS = 10_000
LOOPBACK_IP = "127.0.0.1"


@dataclasses.dataclass
class LogRecord:
    time: int
    src: int
    dst: int
    seq: int
    size: int
    outcome: int

    def as_tuple(self) -> tuple[int, int, int, int, int, int]:
        return (self.time, self.src, self.dst, self.seq, self.size, self.outcome)


@dataclasses.dataclass
class Delivery:
    """Payload of a LOCAL delivery event (step 6 of the lifecycle).

    ``payload`` is opaque engine-side cargo (managed processes ride their
    datagram bytes + ports here); it never affects event ordering or the
    event log, which record sizes only."""

    src: int
    seq: int
    size: int
    payload: object = None


class Host:
    """Per-host state: queue, buckets, CoDel, RNG counters, app models."""

    def __init__(
        self,
        host_id: int,
        hostname: str,
        engine: "CpuEngine",
        bw_up_bps: int,
        bw_down_bps: int,
    ) -> None:
        self.host_id = host_id
        self.hostname = hostname
        self.engine = engine
        self.queue = EventQueue()
        up_rate, up_burst = bucket_params(bw_up_bps)
        dn_rate, dn_burst = bucket_params(bw_down_bps)
        self.up_bucket = TokenBucket(rate=up_rate, burst=up_burst)
        self.down_bucket = TokenBucket(rate=dn_rate, burst=dn_burst)
        self.codel = CoDel()
        self.pcap = None  # PcapWriter when HostOptions.pcap_enabled
        # cross-host packet inbox: worker threads of OTHER hosts append
        # here under the lock; drained into the queue at the round barrier
        # (the push_packet_to_host discipline, worker.rs:603-615)
        import threading

        self.inbox: list = []
        self.inbox_lock = threading.Lock()
        # per-host event-log buffer + min-used-latency, merged at the
        # barrier in host-id order so results are worker-count-invariant
        self.log_buf: list = []
        self.min_used_lat: Optional[int] = None
        self.send_seq = 0  # per-host packet counter (RNG counter + FIFO prio)
        self.local_seq = 0  # per-host local-event counter
        self.app_draws = 0  # APP_STREAM counter
        self.apps: list = []
        self.counters: dict[str, int] = {}
        self.now = 0  # current event time while executing
        self._net = None  # lazy HostNetStack (TCP tier)
        self._passive = None  # lazy: all apps passive_delivery (or no apps)

    # device-turn ledger accounting (obs/turns.py; class defaults keep
    # the hot path to one engine-flag check when the ledger is off):
    # _ledger_managed marks hosts whose sends a hybrid run would stage,
    # _ledger_sends is the thread-owned per-window staged-send count
    _ledger_managed = False
    _ledger_sends = 0

    # -- checkpoint pickling (engine/checkpoint.py) ------------------------
    # the inbox lock is the one unpicklable object in the engine's
    # transitive state graph; at a checkpoint boundary the inbox is
    # empty and no worker threads are live, so drop it and recreate

    def __getstate__(self) -> dict:
        d = self.__dict__.copy()
        d.pop("inbox_lock", None)
        return d

    def __setstate__(self, d: dict) -> None:
        import threading

        self.__dict__.update(d)
        self.inbox_lock = threading.Lock()

    # -- HostApi ----------------------------------------------------------

    @property
    def num_hosts(self) -> int:
        return len(self.engine.hosts)

    def send(self, dst: int, size_bytes: int, payload: object = None,
             loopback: bool = False, retx: bool = False) -> int:
        return self.engine.send_packet(self, dst, size_bytes, payload,
                                       loopback=loopback, retx=retx)

    def ft_giveup(self, dst: int) -> None:
        """Flowtrace hook: a stream retry budget exhausted toward ``dst``
        (oracle-only — the device's pump retries unboundedly, so this
        event is structurally absent from parity scenarios)."""
        ft = self.engine.flowtrace
        if ft is not None and ft.sampled(self.host_id, dst):
            ft.emit(self.host_id, self.now, self.engine.window_end,
                    ftr.FT_DROP, self.host_id, dst, -1, 0,
                    ftr.CAUSE_RETRY_GIVEUP)

    def set_timer(self, t_abs_ns: int) -> None:
        app = self._current_app

        def fire(h: "Host", a=app) -> None:
            h._current_app = a
            a.on_timer(h, h.now)

        # strictly future: a timer armed for "now" (or the past) would pop in
        # the same window at the same instant and can live-lock the round
        self.push_local(max(t_abs_ns, self.now + 1), Task(fire, label="timer"))

    def set_timer_relative(self, delta_ns: int) -> None:
        self.set_timer(self.now + delta_ns)

    def schedule_at(self, t_abs_ns: int, fn) -> None:
        """Exact-time local event (``fn(host)``), the scalar twin of the
        lane backend's arm channels: unlike ``set_timer`` it may land at
        the current instant (pump events pop later in the same window, in
        (time, kind, src, seq) order)."""
        self.push_local(max(t_abs_ns, self.now), Task(fn, label="app"))

    def resolve(self, hostname: str) -> int:
        return self.engine.resolve(hostname)

    def ip_of(self, host_id: int) -> str:
        return self.engine.ips.by_host[host_id]

    @property
    def hosts_file_path(self):
        return self.engine.hosts_file_path

    @property
    def passive_delivery(self) -> bool:
        """True when every app's delivery handling is counters-only (or the
        host has no apps): plain-model deliveries are then applied inline at
        packet arrival and the DELIVERY queue event is elided — identical
        elision on the lane backend keeps the backends bit-compatible."""
        if self._passive is None:
            self._passive = all(
                getattr(a, "passive_delivery", False) for a in self.apps
            )
        return self._passive

    @property
    def net(self):
        """The host's transport stack (TCP sockets over the packet path)."""
        if self._net is None:
            from ..net.stack import HostNetStack

            self._net = HostNetStack(self)
        return self._net

    @property
    def data_directory(self) -> str:
        return self.engine.cfg.general.data_directory

    @property
    def master_seed(self) -> int:
        return self.engine.seed

    def rand_u32(self) -> int:
        v = int(
            rng_mod.rand_u32(
                self.engine.seed,
                self.host_id | rng_mod.APP_STREAM,
                self.app_draws,
            )
        )
        self.app_draws += 1
        return v

    def count(self, key: str, n: int = 1) -> None:
        self.counters[key] = self.counters.get(key, 0) + n

    # -- engine side ------------------------------------------------------

    def push_local(self, t: int, task: Task) -> None:
        self.queue.push(
            Event(t, EventKind.LOCAL, src_host=self.host_id, seq=self.local_seq, data=task)
        )
        self.local_seq += 1

    def execute(self, until: int) -> None:
        """Pop and run all events < until (Host::execute, host.rs:762-803)."""
        pl = self.engine.perf_log
        if pl is not None:
            t0 = pl.timer()
            self._execute(until)
            pl.host_exec(self.hostname, pl.timer() - t0, until)
        else:
            self._execute(until)

    def _execute(self, until: int) -> None:
        no = self.engine.netobs
        pops = 0
        try:
            while True:
                ev = self.queue.peek()
                if ev is None or ev.time >= until:
                    return
                if ev.kind == EventKind.PACKET:
                    # PACKET pops only: wire arrivals are the one event
                    # class whose per-window counts are bit-identical
                    # across backends (LOCAL/DELIVERY decomposition
                    # differs: start anchors, delivery elision), so the
                    # netobs window histogram buckets them
                    pops += 1
                ev = self.queue.pop()
                self.now = ev.time
                self._dispatch(ev)
        finally:
            if no is not None and pops:
                # one thread-owned row write per execute call
                no.pops[self.host_id] += pops

    def _dispatch(self, ev) -> None:
        if ev.kind == EventKind.PACKET:
            self.engine.inbound(self, ev)
        elif ev.kind == EventKind.DELIVERY:
            data = ev.data
            if isinstance(data.payload, _TcpSegment):
                self.net.on_segment(ev.time, data.payload)
            else:
                for app in self.apps:
                    self._current_app = app
                    app.on_delivery(
                        self, ev.time, data.src, data.seq, data.size,
                        payload=data.payload,
                    )
        else:
            ev.data.execute(self)

    _current_app = None


class CpuEngine:
    """Build hosts from a config and run the round loop."""

    def __init__(self, cfg: ConfigOptions) -> None:
        cfg.validate()
        self.cfg = cfg
        self.seed = cfg.general.seed
        self.stop_time = cfg.general.stop_time
        self.bootstrap_end = cfg.general.bootstrap_end_time

        from .setup import build_world

        # kept whole for engines that layer on top (backend/hybrid.py
        # hands it to its TpuEngine so topology/routing build once)
        self.world = build_world(cfg)
        (
            self.graph,
            self.ips,
            self.dns,
            self.routing,
            bw_up_arr,
            bw_dn_arr,
            self.runahead,
        ) = self.world
        self.node_index = self.routing.host_node_index
        # dynamic runahead (runahead.rs:44-118): the window may widen to the
        # smallest latency actually used so far (>= the static minimum);
        # packets record their path latency as they are sent
        self.dynamic_runahead = cfg.experimental.use_dynamic_runahead
        self._min_used_lat: Optional[int] = None
        self._runahead_floor = max(cfg.experimental.runahead or 0, 1)
        self.hosts = [
            Host(hid, hopt.hostname, self, int(bw_up_arr[hid]), int(bw_dn_arr[hid]))
            for hid, hopt in enumerate(cfg.hosts)
        ]

        # app models scheduled at their start times
        from ..native.process import ManagedApp as _ManagedApp

        for hid, hopt in enumerate(cfg.hosts):
            host = self.hosts[hid]
            for p in hopt.processes:
                app = create_model(p.path, list(p.args), dict(p.environment))
                if hasattr(app, "set_congestion"):
                    app.set_congestion(hopt.congestion)
                host.apps.append(app)
                host.push_local(
                    p.start_time, Task(lambda h, a=app: _start_app(h, a), label="start")
                )
                if isinstance(app, _ManagedApp):
                    app.configure_lifecycle(p.expected_final_state, p.shutdown_signal)
                    if p.shutdown_time is not None:
                        host.push_local(
                            p.shutdown_time,
                            Task(
                                lambda h, a=app: a.deliver_shutdown(h),
                                label="shutdown",
                            ),
                        )

        # per-host pcap capture (interface.rs:45-75; host option
        # pcap_enabled, configuration.rs:602-612)
        if any(h.pcap_enabled for h in cfg.hosts):
            from pathlib import Path as _Path

            from ..utils.pcap import PcapWriter

            for hid, hopt in enumerate(cfg.hosts):
                if hopt.pcap_enabled:
                    self.hosts[hid].pcap = PcapWriter(
                        _Path(cfg.general.data_directory)
                        / "hosts" / hopt.hostname / "eth0.pcap",
                        snaplen=hopt.pcap_capture_size,
                    )

        # managed (real-binary) processes resolve simulated names through an
        # /etc/hosts-style file (the reference passes plugins a memfd hosts
        # file, dns.rs:130-190); written once per run, only when needed
        from pathlib import Path

        from ..native.process import ManagedApp

        self.hosts_file_path = None
        if any(isinstance(a, ManagedApp) for h in self.hosts for a in h.apps):
            self.hosts_file_path = self.dns.write_hosts_file(
                Path(cfg.general.data_directory) / "etc-hosts"
            )

        self.event_log: list[LogRecord] = []
        self.window_end = 0
        self.rounds = 0
        # netobs telemetry plane (obs/netobs.py): per-host network
        # counters + window-occupancy histogram.  Config-driven (worker
        # replicas of the multiprocess engines need it too); None = off
        # = zero overhead, the same contract as obs/perf_log
        self.netobs = None
        if cfg.experimental.netobs:
            from ..obs.netobs import NetObs

            self.netobs = NetObs(len(self.hosts))
        # flowtrace lifecycle plane (obs/flowtrace.py): per-event traces
        # of deterministically-sampled flows; None = off = zero overhead
        self.flowtrace = None
        if cfg.experimental.flowtrace:
            self.flowtrace = ftr.FlowTrace(
                len(self.hosts), cfg.general.seed,
                cfg.experimental.flowtrace_sample,
                cfg.experimental.flowtrace_capacity,
            )
        # [window-agg]/[host-exec-agg] telemetry sink (set by the facade
        # when experimental.perf_logging is on; None = zero overhead)
        self.perf_log = None
        # device-turn ledger send accounting (obs/turns.py): armed by
        # _ledger_enable when obs.turns is on; False = zero overhead
        self._turns_sends = False
        # obs Recorder (shadow_tpu/obs/): phase spans + metrics, set by
        # the facade when experimental.obs_* is on; None = zero overhead
        self.obs = None

        # fault schedule (shadow_tpu/faults/): versioned routing tables
        # installed in place at window boundaries; every event time is a
        # window-clamp epoch so fault replay is bit-identical
        self.faults = None
        if cfg.faults.events:
            from ..faults.overlay import build_fault_runtime

            self.faults = build_fault_runtime(cfg, self.graph, self.routing)

    # -- checkpointing (engine/checkpoint.py) ------------------------------
    # The engine's whole state graph is host-picklable (cloudpickle for
    # the app-closure Tasks in the event queue) except for facade-owned
    # attachments: obs and perf_log carry locks/streams and belong to
    # the *run*, not the simulation state — the facade re-attaches them
    # on resume.  run() performs no state reset, so a restored engine's
    # run() continues the simulation exactly where the checkpoint left
    # it (docs/robustness.md "resume law").

    def __getstate__(self) -> dict:
        d = self.__dict__.copy()
        d["obs"] = None
        d["perf_log"] = None
        return d

    def checkpoint_unsupported_reason(self) -> Optional[str]:
        """None when this engine's state is fully serializable; else the
        reason checkpoints must stay off (managed OS processes hold
        kernel state, pcap writers hold open streams)."""
        from ..native.process import ManagedApp

        if any(
            isinstance(a, ManagedApp) for h in self.hosts for a in h.apps
        ):
            return ("managed (real-binary) processes hold live OS state"
                    " that cannot be snapshotted")
        if any(h.pcap is not None for h in self.hosts):
            return "pcap capture streams cannot be snapshotted"
        return None

    def checkpoint_payload(self) -> bytes:
        """Serialize the complete simulation state (hosts, event queue,
        RNG counters, transport stacks, fault runtime, event log) as
        one cloudpickle blob."""
        import cloudpickle

        reason = self.checkpoint_unsupported_reason()
        if reason is not None:
            raise RuntimeError(f"checkpoint unsupported: {reason}")
        return cloudpickle.dumps(self)

    @staticmethod
    def from_checkpoint(blob: bytes) -> "CpuEngine":
        import cloudpickle

        engine = cloudpickle.loads(blob)
        if not isinstance(engine, CpuEngine):
            raise RuntimeError(
                f"checkpoint payload is {type(engine).__name__},"
                " not a CpuEngine"
            )
        return engine

    # -- netobs telemetry plane (obs/netobs.py) ----------------------------

    def netobs_snapshot(self):
        """The run's per-host telemetry in the canonical array schema
        (None when netobs is off).  Completes the accumulator's counters
        with the values only the engine can attribute: token-bucket
        throttles (the buckets live on the hosts), stream retransmit /
        retry-give-up counters (host counter dicts), queue/shed causes
        (structurally zero here: the oracle's queues are unbounded)."""
        no = self.netobs
        if no is None:
            return None
        arrays = no.base_arrays()
        for hid, h in enumerate(self.hosts):
            arrays["throttled"][hid] = (
                h.up_bucket.throttles + h.down_bucket.throttles
            )
            arrays["retransmits"][hid] = h.counters.get(
                "stream_retransmits", 0
            )
            arrays["retry_giveup"][hid] = h.counters.get(
                "stream_retry_drops", 0
            )
        return {
            "arrays": arrays,
            "window_hist": no.window_hist.copy(),
            "log_lost": 0,
        }

    def netobs_lines(self, host=None) -> list[str]:
        """Run-control ``netstats [host]`` answer from live state."""
        from ..obs import netobs as nom

        snap = self.netobs_snapshot()
        if snap is None:
            return ["netobs is not enabled (set experimental.netobs)"]
        names = [h.hostname for h in self.hosts]
        return nom.snapshot_lines(
            snap["arrays"], snap["window_hist"], names, host
        )

    # -- flowtrace plane (obs/flowtrace.py) --------------------------------

    def flowtrace_snapshot(self):
        """The run's raw flow events (None when flowtrace is off).  The
        oracle has no ring, so ``ring_lost`` is structurally 0; the
        device capacity law is applied at export by
        ``flowtrace.canonical_events``."""
        ft = self.flowtrace
        if ft is None:
            return None
        return {"raw": ft.raw_events(), "ring_lost": 0}

    def flowtrace_lines(self, host=None) -> list[str]:
        """Run-control ``flows [host]`` answer from live state."""
        snap = self.flowtrace_snapshot()
        if snap is None:
            return ["flowtrace is not enabled (set experimental.flowtrace)"]
        events, lost = ftr.canonical_events(
            snap["raw"], self.flowtrace.capacity
        )
        names = [h.hostname for h in self.hosts]
        return ftr.snapshot_lines(
            events, lost + snap["ring_lost"], names, host=host
        )

    def console_fault_sink(self, tokens: list[str]) -> str:
        """Run-control ``fault ...`` verb: schedule a fault at the current
        window boundary (effective for all subsequent sends).  Dynamic
        injection is interactive by nature — an in-process restart (``r``)
        rebuilds from the config and forgets console faults."""
        from ..faults.overlay import empty_fault_runtime
        from ..faults.schedule import parse_console_fault

        if self.faults is None:
            self.faults = empty_fault_runtime(self.cfg, self.graph, self.routing)
        ev = parse_console_fault(tokens, at=max(self.window_end, 1))
        self.faults.inject(ev)
        return f"fault {ev.kind} scheduled at {stime.fmt(ev.at)}"

    # -- DNS (network/dns.rs) ----------------------------------------------

    def resolve(self, hostname: str) -> int:
        return self.dns.resolve(hostname)

    # -- packet path (SEMANTICS.md lifecycle) ------------------------------

    def _packet_source_half(
        self, src_host: Host, dst: int, size_bytes: int, payload: object,
        retx: bool = False,
    ) -> tuple[int, Optional[int]]:
        """The source half of the packet lifecycle (steps 1-4: seq, up
        bucket, outbound pcap, dynamic-runahead record, Bernoulli loss,
        arrival-time bump).  Returns ``(seq, arrival_time)`` — arrival is
        ``None`` when the packet was lost.  Shared verbatim by the CPU
        push sink below and the hybrid backend's device-injection sink
        (backend/hybrid.py), so the law cannot drift between them.

        ``retx`` marks a retransmitted stream segment: the flowtrace
        send-stage event becomes FT_RETRANSMIT (same wire lifecycle
        otherwise)."""
        t = src_host.now
        seq = src_host.send_seq
        src_host.send_seq += 1
        s, d = src_host.host_id, dst
        no = self.netobs
        if no is not None:
            no.on_send(s, size_bytes)
        ft = self.flowtrace
        ft_on = ft is not None and ft.sampled(s, d)
        if ft_on:
            we = self.window_end
            ft.emit(s, t, we, ftr.FT_RETRANSMIT if retx else ftr.FT_SEND,
                    s, d, seq, size_bytes)

        bits = (size_bytes + FRAME_OVERHEAD_BYTES) * 8
        t_dep = src_host.up_bucket.charge(t, bits)
        if ft_on and t_dep != t:
            # the up bucket is charged before the loss draw on both
            # backends, so the wait event lands for lost sends too
            ft.emit(s, t_dep, we, ftr.FT_TB_WAIT, s, d, seq, size_bytes,
                    ftr.TB_UP)

        if src_host.pcap is not None:  # outbound capture at departure
            src_host.pcap.capture(
                stime.sim_to_emu(t_dep), self.ips.by_host[s],
                self.ips.by_host[d], size_bytes, payload,
                key=(1, s, d, seq),
            )

        # loss (skipped during bootstrap)
        lat_ns, thresh = self.routing.path(s, d)
        if self.dynamic_runahead and (
            src_host.min_used_lat is None or lat_ns < src_host.min_used_lat
        ):
            src_host.min_used_lat = lat_ns
        if t >= self.bootstrap_end and thresh > 0:
            u = int(rng_mod.rand_u32(self.seed, s | rng_mod.LOSS_STREAM, seq))
            if u < thresh:
                if no is not None:
                    no.on_loss(s)
                if ft_on:
                    ft.emit(s, t, we, ftr.FT_DROP, s, d, seq, size_bytes,
                            ftr.CAUSE_LOSS)
                src_host.log_buf.append(LogRecord(t, s, d, seq, size_bytes, DROP_LOSS))
                return seq, None

        arr = max(t_dep + lat_ns, self.window_end)
        if ft_on:
            ft.emit(s, arr, we, ftr.FT_QUEUE_ENTER, s, d, seq, size_bytes)
        return seq, arr

    def send_packet(
        self, src_host: Host, dst: int, size_bytes: int,
        payload: object = None, loopback: bool = False, retx: bool = False,
    ) -> int:
        if loopback:
            return self._loopback_send(src_host, size_bytes, payload)
        seq, arr = self._packet_source_half(src_host, dst, size_bytes, payload,
                                            retx=retx)
        if arr is None:
            return seq
        if self._turns_sends and src_host._ledger_managed:
            # the oracle analogue of a hybrid injection row: a managed
            # host's surviving non-loopback send (thread-owned bump)
            src_host._ledger_sends += 1
        ev = Event(
            arr, EventKind.PACKET, src_host=src_host.host_id, seq=seq,
            data=(size_bytes, payload),
        )
        dst_host = self.hosts[dst]
        if dst_host is src_host:
            dst_host.queue.push(ev)  # self-traffic never crosses threads
        else:
            with dst_host.inbox_lock:
                dst_host.inbox.append(ev)
        return seq

    def _loopback_send(self, host: Host, size_bytes: int,
                       payload: object) -> int:
        """The lo interface: self-addressed (127/8) traffic takes a
        dedicated serial lifecycle — fixed LOOPBACK_LATENCY_NS, no token
        buckets, no CoDel, no loss draw (the localhost half of the
        reference's per-host interface pair, namespace.rs:25-60).  The
        delivery never leaves the host, so it works identically under
        the threaded, multiprocessing, and hybrid engines."""
        seq = host.send_seq
        host.send_seq += 1
        t_deliver = host.now + LOOPBACK_LATENCY_NS
        no = self.netobs
        if no is not None:
            # lo is both halves on one host: a send and a delivery
            no.on_send(host.host_id, size_bytes)
            no.on_delivered(host.host_id, size_bytes)
        ft = self.flowtrace
        if ft is not None and ft.sampled(host.host_id, host.host_id):
            we = self.window_end
            h = host.host_id
            ft.emit(h, host.now, we, ftr.FT_SEND, h, h, seq, size_bytes)
            ft.emit(h, t_deliver, we, ftr.FT_DELIVERY, h, h, seq, size_bytes)
        host.log_buf.append(
            LogRecord(t_deliver, host.host_id, host.host_id, seq,
                      size_bytes, DELIVERED)
        )
        if host.pcap is not None:
            host.pcap.capture(
                stime.sim_to_emu(t_deliver), LOOPBACK_IP, LOOPBACK_IP,
                size_bytes, payload,
                key=(0, host.host_id, host.host_id, seq),
            )
        host.queue.push(
            Event(
                t_deliver,
                EventKind.DELIVERY,
                src_host=host.host_id,
                seq=seq,
                data=Delivery(host.host_id, seq, size_bytes, payload),
            )
        )
        return seq

    def inbound(self, dst_host: Host, ev: Event) -> None:
        """Steps 5a-5c: down bucket, CoDel, schedule delivery."""
        size_bytes, payload = ev.data
        bits = (size_bytes + FRAME_OVERHEAD_BYTES) * 8
        t_deliver = dst_host.down_bucket.charge(ev.time, bits)
        sojourn = t_deliver - ev.time
        no = self.netobs
        ft = self.flowtrace
        d = dst_host.host_id
        ft_on = ft is not None and ft.sampled(ev.src_host, d)
        if ft_on and t_deliver != ev.time:
            ft.emit(d, t_deliver, self.window_end, ftr.FT_TB_WAIT,
                    ev.src_host, d, ev.seq, size_bytes, ftr.TB_DN)
        if dst_host.codel.offer(t_deliver, sojourn):
            if no is not None:
                no.on_codel(dst_host.host_id)
            if ft_on:
                ft.emit(d, t_deliver, self.window_end, ftr.FT_DROP,
                        ev.src_host, d, ev.seq, size_bytes, ftr.CAUSE_CODEL)
            dst_host.log_buf.append(
                LogRecord(t_deliver, ev.src_host, dst_host.host_id, ev.seq, size_bytes, DROP_CODEL)
            )
            return
        if no is not None:
            no.on_delivered(dst_host.host_id, size_bytes)
        if ft_on:
            ft.emit(d, t_deliver, self.window_end, ftr.FT_DELIVERY,
                    ev.src_host, d, ev.seq, size_bytes)
        dst_host.log_buf.append(
            LogRecord(t_deliver, ev.src_host, dst_host.host_id, ev.seq, size_bytes, DELIVERED)
        )
        if dst_host.pcap is not None:  # inbound capture at delivery
            dst_host.pcap.capture(
                stime.sim_to_emu(t_deliver), self.ips.by_host[ev.src_host],
                self.ips.by_host[dst_host.host_id], size_bytes, payload,
                key=(0, ev.src_host, dst_host.host_id, ev.seq),
            )
        if payload is None and dst_host.passive_delivery:
            # passive fast path: counters apply now; no DELIVERY event.
            # now anchors at delivery time so even a contract-violating app
            # behaves like the queued path (the pop loop reassigns now per
            # event, so this is safe)
            dst_host.now = t_deliver
            for app in dst_host.apps:
                dst_host._current_app = app
                app.on_delivery(
                    dst_host, t_deliver, ev.src_host, ev.seq, size_bytes,
                    payload=None,
                )
            return
        dst_host.queue.push(
            Event(
                t_deliver,
                EventKind.DELIVERY,
                src_host=ev.src_host,
                seq=ev.seq,
                data=Delivery(ev.src_host, ev.seq, size_bytes, payload),
            )
        )

    # -- device-turn ledger (obs/turns.py) ---------------------------------

    def _ledger_enable(self) -> list[Host]:
        """Arm the oracle side of the device-turn ledger: mark the
        managed hosts (whose sends a hybrid run would stage for device
        injection) and enable the per-send counter.  Returns the managed
        hosts in host-id order."""
        from ..native.process import ManagedApp

        managed = [
            h for h in self.hosts
            if any(isinstance(a, ManagedApp) for a in h.apps)
        ]
        for h in managed:
            h._ledger_managed = True
            h._ledger_sends = 0
        self._turns_sends = True
        return managed

    @staticmethod
    def _ledger_participants(managed: list[Host], until: int) -> tuple:
        """Managed hosts with events inside the window — taken BEFORE
        execution mutates the queues (the same law the hybrid engines
        apply per device turn)."""
        return tuple(
            h.host_id for h in managed if h.queue.next_time() < until
        )

    @staticmethod
    def _ledger_take_sends(managed: list[Host]) -> int:
        """Drain the managed hosts' per-window staged-send counters
        (thread-owned bumps, swept post-barrier on the round loop)."""
        n = 0
        for h in managed:
            if h._ledger_sends:
                n += h._ledger_sends
                h._ledger_sends = 0
        return n

    # -- round loop (controller.rs:88-113 + manager.rs:541) ----------------

    def next_event_time(self) -> int:
        return min((h.queue.next_time() for h in self.hosts), default=stime.NEVER)

    def _barrier_merge(self) -> None:
        """Round barrier: drain cross-host inboxes into queues, merge
        per-host log buffers and min-used latencies — all in host-id order
        so any worker count produces identical results."""
        for h in self.hosts:
            if h.inbox:
                for ev in h.inbox:
                    h.queue.push(ev)
                h.inbox.clear()
            if h.log_buf:
                self.event_log.extend(h.log_buf)
                h.log_buf.clear()
            if h.min_used_lat is not None:
                if self._min_used_lat is None or h.min_used_lat < self._min_used_lat:
                    self._min_used_lat = h.min_used_lat
                h.min_used_lat = None

    def current_runahead(self) -> int:
        """Window width for the next round.  Static mode: the precomputed
        min possible latency.  Dynamic mode: the min latency of paths used
        so far (never below the configured floor) — wider windows while
        only slow paths carry traffic, exactly the reference's
        use_dynamic_runahead law (runahead.rs:44-57)."""
        if not self.dynamic_runahead or self._min_used_lat is None:
            return self.runahead
        return max(self._min_used_lat, self._runahead_floor, 1)

    def finalize(self) -> None:
        """End-of-simulation teardown: reap managed processes still parked
        past stop_time (the reference kills plugins at teardown too,
        manager.rs end-of-sim), then check every process's final state
        against expected_final_state (worker.rs:475-481)."""
        for h in self.hosts:
            for app in h.apps:
                shutdown = getattr(app, "shutdown", None)
                if shutdown is not None:
                    shutdown()
            if h.pcap is not None:
                h.pcap.close()
        self.process_errors = []
        for h in self.hosts:
            for app in h.apps:
                check = getattr(app, "final_state_matches", None)
                if check is not None:
                    err = check()
                    if err is not None:
                        self.process_errors.append(f"host {h.hostname}: {err}")

    def describe_next_window(self, until: int) -> list[tuple[str, int, list[int]]]:
        """Hosts with events before ``until`` + native PIDs of their managed
        processes — what the run-control console prints while paused so a
        debugger can attach (manager.rs:660-748)."""
        out = []
        for h in self.hosts:
            t = h.queue.next_time()
            if t < until:
                pids = [
                    app.proc.pid
                    for app in h.apps
                    if getattr(app, "proc", None) is not None
                    and app.proc.poll() is None
                ]
                out.append((h.hostname, t, pids))
        return out

    def run(self, on_window=None) -> "SimResult":
        """Round loop.  ``on_window(window_start, window_end,
        next_event_time)`` runs after every round — the seam where the
        facade hangs heartbeats, perf telemetry, and run-control pauses
        (and through which RestartRequest propagates)."""
        from ..engine.scheduler import HostScheduler
        from ..native.process import ManagedApp

        exp = self.cfg.experimental
        parallelism = self.cfg.general.parallelism
        if parallelism == 0 and exp.scheduler != "thread-per-host":
            # default "all cores" engages only where threads can help:
            # managed OS processes (futex waits release the GIL); pure
            # Python model hosts run serial to skip pool overhead
            has_managed = any(
                isinstance(a, ManagedApp) for h in self.hosts for a in h.apps
            )
            parallelism = 0 if has_managed else 1
        scheduler = HostScheduler(
            self.hosts,
            parallelism=parallelism,
            policy=exp.scheduler,
            pin_cpus=exp.use_cpu_pinning,
        )
        try:
            return self._run_rounds(scheduler, on_window)
        finally:
            scheduler.shutdown()

    def _run_rounds(self, scheduler, on_window) -> "SimResult":
        t0 = wall_time.perf_counter()
        try:
            return self._round_loop(scheduler, on_window, t0)
        except BaseException:
            # a failing round must still reap managed OS processes (and
            # their fork children) — no orphans outlive the simulation
            self.finalize()
            raise

    def _round_loop(self, scheduler, on_window, t0) -> "SimResult":
        obs = self.obs
        turns = obs.turns if obs is not None else None
        managed_hosts = self._ledger_enable() if turns is not None else None
        while True:
            start = self.next_event_time()
            if start >= self.stop_time or start == stime.NEVER:
                break
            swapped = False
            if self.faults is not None:
                # apply every fault epoch at or before this window's start,
                # then clamp the window at the next pending epoch: sends at
                # t >= epoch see the new tables, earlier sends never do —
                # the identical law the TPU engine's epoch segmentation
                # enforces, so windows (and logs) stay bit-identical
                prev_install = (
                    self.faults._installed_at if turns is not None else None
                )
                if obs is None:
                    self.faults.advance_to(start)
                else:
                    with obs.phase("fault_swap", window_start=start):
                        self.faults.advance_to(start)
                if turns is not None:
                    swapped = self.faults._installed_at != prev_install
            self.window_end = min(start + self.current_runahead(), self.stop_time)
            if self.faults is not None:
                self.window_end = min(
                    self.window_end, self.faults.window_bound(start)
                )
            pl = self.perf_log
            if pl is not None or obs is not None:
                active = sum(
                    1 for h in self.hosts if h.queue.next_time() < self.window_end
                )
            if turns is not None:
                parts = self._ledger_participants(
                    managed_hosts, self.window_end
                )
            if obs is None:
                scheduler.run_round(self.window_end)
                self._barrier_merge()
            else:
                with obs.phase(
                    "window_compute", window_end=self.window_end, active=active
                ):
                    scheduler.run_round(self.window_end)
                    self._barrier_merge()
            self.rounds += 1
            if self.netobs is not None:
                # one histogram entry per window (post-barrier, so every
                # pop of the round has landed)
                self.netobs.flush_window()
            if turns is not None:
                # the oracle ledger row: one window = one hypothetical
                # device turn, with the cause a hybrid run of this config
                # would have recorded (fault swap > staged managed sends
                # > managed participation > legal free-run)
                sends = self._ledger_take_sends(managed_hosts)
                if swapped:
                    cause = "fault_swap"
                elif sends:
                    cause = "injection"
                elif parts:
                    cause = "host_window"
                else:
                    cause = "free_run"
                turns.turn(
                    cause, start, self.window_end,
                    inject_rows=sends, participants=parts,
                )
            if obs is not None:
                m = obs.metrics
                m.count("windows")
                m.observe("window_active_hosts", active)
                m.observe("window_span_ns", self.window_end - start)
            if pl is not None or on_window is not None:
                next_ev = self.next_event_time()
                if pl is not None:
                    pl.window_agg(
                        active, start, self.window_end, min(next_ev, self.stop_time)
                    )
                if on_window is not None:
                    on_window(start, self.window_end, next_ev)
        self.finalize()
        wall = wall_time.perf_counter() - t0

        counters: dict[str, int] = {}
        for h in self.hosts:
            for k, v in h.counters.items():
                counters[k] = counters.get(k, 0) + v
        return SimResult(
            sim_time_ns=self.stop_time,
            wall_seconds=wall,
            rounds=self.rounds,
            event_log=self.event_log,
            counters=counters,
            per_host_counters=[dict(h.counters) for h in self.hosts],
            process_errors=list(getattr(self, "process_errors", [])),
        )


def _start_app(host: Host, app) -> None:
    host._current_app = app
    app.on_start(host)


@dataclasses.dataclass
class SimResult:
    sim_time_ns: int
    wall_seconds: float
    rounds: int
    event_log: list[LogRecord]
    counters: dict[str, int]
    per_host_counters: list[dict[str, int]]
    # expected_final_state mismatches; a non-empty list makes the CLI exit
    # nonzero (controller.rs:70-74)
    process_errors: list[str] = dataclasses.field(default_factory=list)

    def log_tuples(self) -> list[tuple[int, int, int, int, int, int]]:
        """Canonical ordered event log for determinism diffs."""
        return sorted(r.as_tuple() for r in self.event_log)

    @property
    def sim_seconds_per_wall_second(self) -> float:
        return (self.sim_time_ns / 1e9) / max(self.wall_seconds, 1e-9)
