"""TPU backend driver: config -> lane state -> device run -> SimResult.

The host-side counterpart of :mod:`shadow_tpu.backend.lanes`: builds the
device tables and the initial lane state from a :class:`ConfigOptions`
(mirroring ``CpuEngine``'s setup exactly — same host ordering, IPs, routing,
runahead, bucket parameters), runs the simulation on the selected JAX
backend, and reads the results back into the same :class:`SimResult` shape
the CPU engine produces, so the two backends are drop-in comparable.
"""

from __future__ import annotations

import time as wall_time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config.options import ConfigOptions
from ..core import time as stime
from ..models.base import create_model
from ..models.phold import Phold
from ..models.tcpflow import StreamClient, StreamServer
from ..models.tgen import Ping, TgenClient, TgenMesh, TgenServer
from ..net import codel as codel_mod
from ..net.token_bucket import bucket_params
from ..obs import flowtrace as ftr
from . import lanes
from . import lanes_stream as lstr_mod
from .cpu_engine import LogRecord, SimResult

NEVER = stime.NEVER


class LaneCompatError(ValueError):
    """Raised when a config can't run on the lane backend (fall back to
    ``experimental.network_backend: cpu``)."""


# NOTE on ``strict_capacity=False``: queue overflow on this backend evicts
# the *latest-keyed* events of the full lane (the merge keeps the earliest C)
# and burst arrivals past the cross block's width per iteration are shed in
# an order chosen by the (unstable) exchange sort network — deterministic
# for a compiled program but unspecified — whereas the CPU reference never
# drops (its queues are unbounded).  Non-strict runs are therefore NOT
# log-parity comparable once any lane overflows; strict mode (the default)
# raises instead of diverging silently.


class TpuEngine:
    def __init__(
        self,
        cfg: ConfigOptions,
        log_capacity: Optional[int] = None,
        strict_capacity: bool = True,
        external=None,
        inject_batch: Optional[int] = None,
        world=None,
        netobs: Optional[bool] = None,
        flowtrace: Optional[bool] = None,
    ) -> None:
        """``external``: optional [N] bool mask — marked hosts are
        EXTERNAL (hybrid backend, backend/hybrid.py): their apps run on
        the host CPU; the device keeps only their network dn-side (down
        bucket, CoDel, arrival queue) and exchanges traffic through the
        injection/egress machinery instead of model slots.

        ``world``: optional prebuilt ``backend.setup.build_world`` tuple —
        the hybrid engine passes its own so topology/routing are built
        once per run, not once per engine."""
        cfg.validate()
        self.cfg = cfg
        self.strict_capacity = strict_capacity
        if netobs is None:
            netobs = cfg.experimental.netobs
        self._netobs_on = bool(netobs)
        # populated by collect() when netobs is on: the device-side
        # telemetry snapshot (obs/netobs.py array schema)
        self._netobs_data = None
        if flowtrace is None:
            flowtrace = cfg.experimental.flowtrace
        self._flowtrace_on = bool(flowtrace)
        # populated by collect() when flowtrace is on: decoded device
        # ring events + ring-overflow loss count (obs/flowtrace.py)
        self._flowtrace_data = None
        if inject_batch is None:
            inject_batch = cfg.experimental.tpu_inject_batch
        n = len(cfg.hosts)
        ext_mask = (
            np.zeros(n, dtype=bool) if external is None
            else np.asarray(external, dtype=bool)
        )
        self._external = ext_mask

        # topology (single-sourced with CpuEngine via backend.setup)
        from .setup import build_world

        (
            self.graph,
            self.ips,
            self.dns,
            self.routing,
            bw_up,
            bw_dn,
            runahead,
        ) = world if world is not None else build_world(cfg)

        # --- per-lane model tables and initial events ---------------------
        model = np.zeros(n, dtype=np.int32)
        p_size = np.zeros(n, dtype=np.int32)
        p_interval = np.ones(n, dtype=np.int64)
        p_peer = np.zeros(n, dtype=np.int32)
        p_count = np.zeros(n, dtype=np.int64)
        p_stride = np.ones(n, dtype=np.int64)
        st_segs = np.zeros(n, dtype=np.int32)
        st_mss = np.zeros(n, dtype=np.int32)
        st_last = np.zeros(n, dtype=np.int32)
        st_cc = np.zeros(n, dtype=np.int32)
        init_events: list[tuple[int, int, int, int, int, int]] = []  # lane,t,kind,src,seq,size
        local_seq0 = np.ones(n, dtype=np.int64)

        recv_mult = np.zeros(n, dtype=np.int32)

        def assign_tgen(hid: int, a) -> None:
            """One source of truth for tgen model/table assignment —
            shared by the single-process and multi-process (driver)
            paths."""
            if isinstance(a, TgenMesh):
                model[hid] = lanes.M_TGEN_MESH
                p_size[hid] = a.size
                p_interval[hid] = a.interval
                p_stride[hid] = a.stride
            elif isinstance(a, TgenClient):
                model[hid] = lanes.M_TGEN_CLIENT
                p_size[hid] = a.size
                p_interval[hid] = a.interval
                p_peer[hid] = self._resolve(a.server, n)
            else:
                model[hid] = lanes.M_TGEN_SERVER

        # COLUMNAR configs (config/columnar.py): the scenario factory has
        # already built the per-lane model/param columns and the initial
        # event table as numpy arrays — skip the per-host Python loop
        # entirely (the 100k-host startup path, ROADMAP item 5)
        spec = getattr(cfg, "columnar", None)
        if spec is not None and ext_mask.any():
            raise LaneCompatError(
                "columnar configs are lane-only: the hybrid backend "
                "executes per-host process objects host-side; build the "
                "config without the columnar spec"
            )
        host_iter = () if spec is not None else enumerate(cfg.hosts)
        for hid, hopt in host_iter:
            # pcap: sends emit PCAP_TX records into the device log, and
            # collect() reconstructs per-host capture files byte-identical
            # to the CPU backend's (synthetic payloads either way)
            if ext_mask[hid]:
                # hybrid: the host side executes this host's apps; the
                # lane only runs its packet-arrival machinery
                model[hid] = lanes.M_NONE
                continue
            if not hopt.processes:
                model[hid] = lanes.M_NONE
                continue
            apps = [
                (p, create_model(p.path, list(p.args)))
                for p in hopt.processes
            ]
            for _, a in apps:
                if hasattr(a, "set_congestion"):
                    a.set_congestion(hopt.congestion)
            if len(apps) > 1:
                # MULTI-PROCESS hosts: supported for tgen mesh/client/
                # server combinations with at most one timer-driving
                # process — the lane's model id is the driver's, other
                # processes contribute start anchors and delivery
                # counting (recv_mult).  The CPU oracle dispatches every
                # delivery to every app, so k counting apps multiply the
                # recv accounting by k on both backends.
                trio = (TgenMesh, TgenClient, TgenServer)
                if not all(isinstance(a, trio) for _p, a in apps):
                    raise LaneCompatError(
                        f"host {hopt.hostname!r}: multi-process lane "
                        "hosts support tgen mesh/client/server "
                        "combinations only; use the cpu backend"
                    )
                drivers = [
                    (p, a) for p, a in apps
                    if isinstance(a, (TgenMesh, TgenClient))
                ]
                if len(drivers) > 1:
                    raise LaneCompatError(
                        f"host {hopt.hostname!r}: at most one "
                        "timer-driving process per lane host; use the "
                        "cpu backend"
                    )
                recv_mult[hid] = len(apps)
                driver = drivers[0] if drivers else apps[0]
                seq = 0
                for p, a in apps:
                    init_events.append((
                        hid, p.start_time, lanes.LOCAL, hid, seq,
                        -1 if a is driver[1] else lanes.SZ_ANCHOR,
                    ))
                    seq += 1
                local_seq0[hid] = seq
                assign_tgen(hid, driver[1])
                continue
            recv_mult[hid] = 1
            proc, app = apps[0]
            t0 = proc.start_time
            if isinstance(app, Phold):
                model[hid] = lanes.M_PHOLD
                p_size[hid] = app.size
                for i in range(app.messages):
                    init_events.append((hid, t0, lanes.LOCAL, hid, i, 0))
                local_seq0[hid] = max(app.messages, 1)
            elif isinstance(app, (TgenMesh, TgenClient, TgenServer)):
                assign_tgen(hid, app)
                init_events.append((hid, t0, lanes.LOCAL, hid, 0, -1))
            elif isinstance(app, StreamClient):
                model[hid] = lanes.M_STREAM_CLIENT
                p_peer[hid] = self._resolve(app.server, n)
                # int32/packed-payload magnitude guards: seq units ride a
                # 26-bit payload field and rx_bytes an int32 counter
                if app.fs.segs + 2 >= (1 << lstr_mod.PAY_SEQ_BITS):
                    raise LaneCompatError(
                        f"stream flow of {app.fs.segs} segments exceeds the "
                        f"lane backend's {lstr_mod.PAY_SEQ_BITS}-bit sequence "
                        "space; use the cpu backend"
                    )
                if app.size >= (1 << 31):
                    raise LaneCompatError(
                        "stream transfer size exceeds the lane backend's "
                        "int32 byte counter; use the cpu backend"
                    )
                st_segs[hid], st_last[hid] = app.fs.segs, app.fs.last_bytes
                st_mss[hid] = app.mss
                st_cc[hid] = app.fs.cc
                init_events.append((hid, t0, lanes.LOCAL, hid, 0, -1))
            elif isinstance(app, StreamServer):
                model[hid] = lanes.M_STREAM_SERVER
                # the start marker anchors window boundaries exactly like
                # the CPU engine's start task (flows open on the first SYN)
                init_events.append((hid, t0, lanes.LOCAL, hid, 0, -1))
            elif isinstance(app, Ping):
                if app.peer is None:
                    model[hid] = lanes.M_PING_SERVER
                else:
                    model[hid] = lanes.M_PING_CLIENT
                    p_peer[hid] = self._resolve(app.peer, n)
                    p_count[hid] = app.count_target
                    p_interval[hid] = app.interval
                p_size[hid] = app.size
                init_events.append((hid, t0, lanes.LOCAL, hid, 0, -1))
            else:  # pragma: no cover - registry and this list must stay in sync
                raise LaneCompatError(
                    f"model {proc.path!r} is not lane-compiled yet; use the cpu backend"
                )

        # fault schedule: versioned latency/loss gather tables re-uploaded
        # at epoch boundaries (shadow_tpu/faults/overlay.py); the run is
        # segmented per epoch so no window straddles a fault
        self._fault_overlay = None
        self._watchdog_timeout = cfg.faults.watchdog_timeout
        if cfg.faults.events:
            if ext_mask.any():
                # hybrid backend: backend_stall-only schedules are owned
                # by the hybrid window loop (backend/hybrid.py raises at
                # the stall epoch for the failover boundary to catch) —
                # no overlay tables to build.  Link/host fault schedules
                # stay gated off the device lane tables.
                if any(
                    ev.get("kind") != "backend_stall"
                    for ev in cfg.faults.events
                ):
                    raise LaneCompatError(
                        "link/host fault schedules are not supported on "
                        "the hybrid tpu backend; use the cpu backend"
                    )
            else:
                from ..faults.overlay import build_overlay

                self._fault_overlay = build_overlay(
                    cfg, self.graph, self.routing
                )

        if spec is not None:
            (
                model, p_size, p_interval, p_peer, p_count, p_stride,
                recv_mult, local_seq0,
            ) = spec.model_columns(n)
            init_cols = spec.event_columns()
        else:
            ev = (
                np.asarray(init_events, dtype=np.int64).reshape(-1, 6)
            )
            init_cols = tuple(ev[:, j] for j in range(6))
        # (lane, t, kind, src, seq, size) int64 columns — the columnar
        # initial-event table, consumed vectorized by initial_state()
        self._init_cols = init_cols

        capacity = cfg.experimental.tpu_lane_queue_capacity
        if cfg.experimental.tpu_cross_capacity < 0:
            raise LaneCompatError(
                f"tpu_cross_capacity={cfg.experimental.tpu_cross_capacity} "
                "must be >= 0 (0 = queue capacity)"
            )
        ev_lane = init_cols[0]
        max_init = (
            int(np.bincount(ev_lane, minlength=max(n, 1)).max())
            if ev_lane.size else 0
        )
        if capacity < max_init + 8:
            raise LaneCompatError(
                f"tpu_lane_queue_capacity={capacity} too small for {max_init} "
                "initial events per lane (+8 headroom)"
            )

        node_idx, lat, thresh = self.routing.device_tables()
        if log_capacity is None:
            log_capacity = 200_000

        # one-to-one stream pairing (every stream server is the peer of
        # exactly one client) only affects the POP rule now: flow state is
        # COMPACTED per flow slot either way (rows 0..S-1 = clients,
        # S..2S-1 = servers — lanes_stream.endpoint_cols), so the lane
        # layout no longer depends on the pairing shape
        client_ids = np.nonzero(model == lanes.M_STREAM_CLIENT)[0]
        server_ids = set(np.nonzero(model == lanes.M_STREAM_SERVER)[0].tolist())
        peer_counts: dict[int, int] = {}
        for cid in client_ids:
            peer_counts[int(p_peer[cid])] = peer_counts.get(int(p_peer[cid]), 0) + 1
        one_to_one = bool(client_ids.size) and all(
            peer_counts.get(sid, 0) == 1 for sid in server_ids
        ) and all(pid in server_ids for pid in peer_counts)
        # TIERED stream backend: one-to-one flows move to a dedicated
        # [2S]-row tier (docs/tpu-backend.md).  Hybrid (external) runs
        # keep the older split-exchange path: host injections land in
        # [N] rows, which the tier would orphan for stream lanes.
        # flowtrace instruments the [N] untiered path only: tracing a run
        # drops the tier (equivalent execution strategy, bit-identical
        # events, slower — fine for untimed evidence runs)
        tiered = bool(
            one_to_one
            and cfg.experimental.tpu_stream_tiered
            and not ext_mask.any()
            and not self._flowtrace_on
        )
        self._tiered = tiered

        # wide stream co-pop is sound only when every possible lookahead
        # window ends before RTO_MIN (DELIVERY pops then provably insert
        # nothing same-window); the dynamic window never exceeds the
        # largest link latency
        from ..net import ltcp as ltcp_mod

        max_lat = int(np.max(np.asarray(lat), initial=0))
        if self._fault_overlay is not None:
            # fault epochs can raise latencies mid-run; the wide-pop bound
            # must hold for every snapshot's tables
            max_lat = max(max_lat, self._fault_overlay.max_latency_ns())
        max_window = max(runahead, max_lat)
        stream_wide_pop = max_window < ltcp_mod.RTO_MIN

        lane_pcap = np.array([h.pcap_enabled for h in cfg.hosts], dtype=bool)
        # external lanes' pcap is written host-side (the host knows the
        # payload bytes); the device captures lane-model hosts only
        lane_pcap = lane_pcap & ~ext_mask
        pcap_any = bool(lane_pcap.any())
        if pcap_any and log_capacity == 0:
            raise LaneCompatError(
                "pcap capture on the lane backend rides the device event "
                "log; log_capacity=0 disables it — use the cpu backend or "
                "enable logging"
            )
        # pcap + stream works since round 4: stream sends emit PCAP_TX
        # records through their compacted channels at departure, and both
        # backends synthesize stream bodies from sizes alone

        ft_thresh, ft_all = ftr.sample_thresh(cfg.experimental.flowtrace_sample)
        self.params = lanes.LaneParams(
            n_lanes=n,
            capacity=capacity,
            pops_per_iter=cfg.experimental.tpu_events_per_round,
            log_capacity=log_capacity,
            seed=cfg.general.seed,
            stop_time=cfg.general.stop_time,
            bootstrap_end=cfg.general.bootstrap_end_time,
            runahead=runahead,
            models_present=tuple(int(x) for x in np.unique(model)),
            # fault epochs may introduce loss later in the run: the loss
            # draw must be compiled in from the start (the counter-based
            # RNG keys on send seq, so drawing on loss-free segments
            # cannot shift any stream)
            has_loss=bool(np.any(np.asarray(thresh) > 0))
            or (
                self._fault_overlay is not None and self._fault_overlay.any_loss()
            ),
            unroll=cfg.experimental.tpu_round_unroll,
            dynamic_runahead=bool(cfg.experimental.use_dynamic_runahead),
            runahead_floor=max(cfg.experimental.runahead or 0, 1),
            stream_one_to_one=one_to_one,
            stream_clients=tuple(int(c) for c in client_ids),
            stream_wide_pop=stream_wide_pop,
            pcap_any=pcap_any,
            stream_pcap=bool(
                client_ids.size
                and lane_pcap[
                    np.concatenate([client_ids,
                                    p_peer[client_ids]]).astype(np.int64)
                ].any()
            ),
            cross_capacity=cfg.experimental.tpu_cross_capacity,
            stream_tiered=tiered,
            stream_pops=cfg.experimental.tpu_stream_events_per_round,
            stream_capacity=cfg.experimental.tpu_stream_queue_capacity,
            netobs=self._netobs_on,
            flowtrace=self._flowtrace_on,
            flow_capacity=(
                cfg.experimental.flowtrace_capacity
                if self._flowtrace_on else 0
            ),
            flow_thresh=ft_thresh,
            flow_all=ft_all,
            flow_seed=cfg.general.seed,
            external_any=bool(ext_mask.any()),
            # worst case: every external lane pops a full slot row of
            # packets in one iteration; the egress buffer keeps at least
            # that much headroom so one iteration can never overflow it
            ext_per_iter=(
                int(ext_mask.sum()) * cfg.experimental.tpu_events_per_round
            ),
            egress_capacity=(
                max(1024, 4 * int(ext_mask.sum())
                    * cfg.experimental.tpu_events_per_round)
                if ext_mask.any() else 0
            ),
            inject_batch=inject_batch if ext_mask.any() else 0,
            inject_cross=capacity if ext_mask.any() else 0,
        )

        up = np.array([bucket_params(int(b)) for b in bw_up], dtype=np.int64)
        dn = np.array([bucket_params(int(b)) for b in bw_dn], dtype=np.int64)

        # int32 magnitude guards: the lane kernel's pair arithmetic is
        # exact only within these (generous) ranges — reject configs
        # beyond them instead of silently diverging
        interval = lanes.DEFAULT_INTERVAL_NS
        i32max = (1 << 31) - 1

        def _check(name, arr, limit):
            mx = int(np.max(arr)) if np.size(arr) else 0
            if mx > limit:
                raise LaneCompatError(
                    f"{name} {mx} exceeds the lane backend's int32 range "
                    f"({limit}); use the cpu backend"
                )

        if interval >= lanes.MOD_SMALL_LIMIT:
            raise LaneCompatError(
                f"bucket interval {interval} ns exceeds the chunked-mod "
                f"ceiling ({lanes.MOD_SMALL_LIMIT}); use the cpu backend"
            )
        # strictly below NEVER32: a latency equal to the sentinel would
        # read as "no sends yet" in the dynamic-runahead scalar
        _check("link latency (ns)", np.asarray(lat), i32max - 1)
        if self._fault_overlay is not None:
            _check(
                "fault-epoch link latency (ns)",
                np.asarray([self._fault_overlay.max_latency_ns()]),
                i32max - 1,
            )
        _check("runahead (ns)", np.asarray([runahead]), i32max)
        for side, b in (("up", up), ("dn", dn)):
            # the refill computes tokens + k*rate <= 2*burst + rate before
            # clamping to burst: THAT intermediate must fit int32
            _check(f"{side} bucket refill ceiling (2*burst + rate)",
                   2 * b[:, 1] + b[:, 0], i32max)
        _check("datagram size", p_size, 1 << 20)
        # one max-size packet's bucket wait must fit the int32 horizon:
        # w = ceil(bits/rate) intervals, w*interval < 2**31
        max_bits = (int(np.max(p_size, initial=0)) + 65536 + 38) * 8
        for side, b in (("up", up), ("dn", dn)):
            rates = b[:, 0][b[:, 0] > 0]
            if rates.size:
                w_max = -(-max_bits // int(rates.min()))
                if w_max * interval > i32max:
                    raise LaneCompatError(
                        f"{side} bandwidth {int(rates.min())} bits/interval is "
                        "too low for the lane backend's int32 wait horizon "
                        "(one packet would wait > 2.1 s for tokens); use the "
                        "cpu backend"
                    )

        def _kfull(b):
            rate = np.maximum(b[:, 0], 1)
            kf = b[:, 1] // rate + 1
            kfi = kf * interval
            _check("bucket full-refill horizon (ns)", kfi, i32max)
            return kf.astype(np.int32), kfi.astype(np.int32)

        up_kfull, up_kfi = _kfull(up)
        dn_kfull, dn_kfi = _kfull(dn)
        i32 = jnp.int32

        # COMPACTED stream-flow tables: [2S] endpoint rows (clients then
        # servers, flow order = ascending client lane) with everything
        # static per flow precomputed — peer, latency, loss threshold, and
        # the endpoint lane's up-bucket parameters — so the stream tier
        # touches no [N]- or [G, G]-shaped table at all.  [2]-placeholder
        # shapes when no stream models are present.
        self._s_flows = s_flows = int(client_ids.size)
        if s_flows:
            fcl = client_ids.astype(np.int32)
            fsv = p_peer[fcl].astype(np.int32)
            el_np = np.concatenate([fcl, fsv])
            peer_np = np.concatenate([fsv, fcl])
            lat_np = np.asarray(lat)
            thr_np = np.asarray(thresh)
            e_nodes = np.asarray(node_idx)[el_np]
            p_nodes = np.asarray(node_idx)[peer_np]
            flow_lat = lat_np[e_nodes, p_nodes].astype(np.int32)
            flow_thr = thr_np[e_nodes, p_nodes]
            flow_segs = np.concatenate(
                [st_segs[fcl], np.zeros(s_flows, dtype=np.int32)]
            )
            flow_mss = np.concatenate(
                [st_mss[fcl], np.zeros(s_flows, dtype=np.int32)]
            )
            flow_last = np.concatenate(
                [st_last[fcl], np.zeros(s_flows, dtype=np.int32)]
            )
            # CC follows the data sender (the client host's congestion
            # option); receiver endpoints stay CC_RENO like the scalar
            # StreamServer's default-constructed FlowState
            flow_cc = np.concatenate(
                [st_cc[fcl], np.zeros(s_flows, dtype=np.int32)]
            )
            flow_clid = np.concatenate([fcl, fcl])
        else:
            el_np = peer_np = np.zeros(2, dtype=np.int32)
            flow_lat = np.zeros(2, dtype=np.int32)
            flow_thr = np.zeros(2, dtype=np.int64)
            flow_segs = flow_mss = flow_last = np.zeros(2, dtype=np.int32)
            flow_cc = np.zeros(2, dtype=np.int32)
            flow_clid = np.zeros(2, dtype=np.int32)

        self.tables = lanes.LaneTables(
            node_of=jnp.asarray(node_idx, dtype=i32),
            lat=jnp.asarray(lat, dtype=i32),
            thresh_u32=jnp.asarray(
                (np.asarray(thresh) & 0xFFFFFFFF).astype(np.uint32)
            ),
            thresh_all=jnp.asarray(np.asarray(thresh) >= (1 << 32)),
            up_rate=jnp.asarray(up[:, 0], dtype=i32),
            up_burst=jnp.asarray(up[:, 1], dtype=i32),
            up_kfull=jnp.asarray(up_kfull),
            up_kfi=jnp.asarray(up_kfi),
            dn_rate=jnp.asarray(dn[:, 0], dtype=i32),
            dn_burst=jnp.asarray(dn[:, 1], dtype=i32),
            dn_kfull=jnp.asarray(dn_kfull),
            dn_kfi=jnp.asarray(dn_kfi),
            model=jnp.asarray(model),
            recv_mult=jnp.asarray(recv_mult),
            p_size=jnp.asarray(p_size),
            p_int_hi=jnp.asarray(p_interval >> 31, dtype=i32),
            p_int_lo=jnp.asarray(p_interval & lanes.MASK31, dtype=i32),
            p_peer=jnp.asarray(p_peer),
            p_count=jnp.asarray(np.minimum(p_count, i32max), dtype=i32),
            p_stride=jnp.asarray(p_stride, dtype=i32),
            codel_div=jnp.asarray(np.array(codel_mod.CODEL_DIV, dtype=np.int32)),
            flow_lanes=jnp.asarray(el_np),
            flow_peers=jnp.asarray(peer_np),
            flow_clid=jnp.asarray(flow_clid),
            flow_lat=jnp.asarray(flow_lat, dtype=i32),
            flow_thresh_u32=jnp.asarray(
                (flow_thr & 0xFFFFFFFF).astype(np.uint32)
            ),
            flow_thresh_all=jnp.asarray(flow_thr >= (1 << 32)),
            flow_segs=jnp.asarray(flow_segs, dtype=i32),
            flow_mss=jnp.asarray(flow_mss, dtype=i32),
            flow_last=jnp.asarray(flow_last, dtype=i32),
            flow_cc=jnp.asarray(flow_cc, dtype=i32),
            flow_up_rate=jnp.asarray(up[el_np, 0], dtype=i32),
            flow_up_burst=jnp.asarray(up[el_np, 1], dtype=i32),
            flow_up_kfull=jnp.asarray(up_kfull[el_np]),
            flow_up_kfi=jnp.asarray(up_kfi[el_np]),
            flow_pcap=jnp.asarray(lane_pcap[el_np]),
            lane_pcap=jnp.asarray(lane_pcap),
            lane_external=(
                jnp.asarray(ext_mask) if ext_mask.any() else ()
            ),
            flow_dn_rate=jnp.asarray(dn[el_np, 0], dtype=i32) if tiered else (),
            flow_dn_burst=jnp.asarray(dn[el_np, 1], dtype=i32) if tiered else (),
            flow_dn_kfull=jnp.asarray(dn_kfull[el_np]) if tiered else (),
            flow_dn_kfi=jnp.asarray(dn_kfi[el_np]) if tiered else (),
            lane_stream=(
                jnp.asarray(np.isin(np.arange(n), el_np)) if tiered else ()
            ),
        )
        self._local_seq0 = local_seq0
        self._el_np = el_np  # [2S] endpoint lanes (tiered routing/collect)
        self._peer_np = peer_np  # [2S] peer lanes (fault-epoch flow tables)
        self._node_idx = node_idx  # [N] host -> dense node index
        self._ep_of_lane = (
            {int(l): r for r, l in enumerate(el_np)} if tiered else {}
        )
        self._dn_params = dn  # [N, 2] (rate, burst) — tier init needs bursts
        self._up_params = up
        self._interval = lanes.DEFAULT_INTERVAL_NS
        # multi-chip plane (parallel/mesh.py): attach_mesh shards the
        # lane axis over a device mesh; None = single-device placement
        self._mesh = None
        self._run_fn = None
        self._compiled = None
        # [window-agg] telemetry sink (step mode only; set by the facade)
        self.perf_log = None
        # obs Recorder (shadow_tpu/obs/): device_turn spans per round in
        # step mode, one fused span in device mode; None = zero overhead
        self.obs = None

    def _resolve(self, hostname: str, n: int) -> int:
        return self.dns.resolve(hostname)

    # -- multi-chip plane (parallel/mesh.py) -------------------------------

    def attach_mesh(self, mesh) -> None:
        """Shard this engine's data plane over ``mesh``: subsequent
        ``run()`` / ``make_hybrid_fns()`` compiles split the lane axis
        across the mesh devices under the parallel/mesh.py sharding law
        (bit-identical results at any mesh shape).  Cached programs are
        invalidated — they were compiled for the previous placement."""
        if mesh is not None and self.params.n_lanes % mesh.devices.size:
            raise LaneCompatError(
                f"n_lanes={self.params.n_lanes} not divisible by mesh "
                f"size {mesh.devices.size} (negotiate_devices picks a "
                "dividing count)"
            )
        self._mesh = mesh
        self._run_fn = None
        self._compiled = None

    @property
    def mesh(self):
        return self._mesh

    def place_state(self, state: lanes.LaneState) -> lanes.LaneState:
        """Commit ``state`` to this engine's placement: sharded over the
        attached mesh, or unchanged when single-device."""
        if self._mesh is None:
            return state
        from .. import parallel

        return parallel.shard_state(state, self._mesh)

    def first_event_time(self) -> int:
        """Earliest initial-event epoch (NEVER when none) — the hybrid
        window loop's starting device bound."""
        t = self._init_cols[1]
        return int(t.min()) if t.size else NEVER

    def _next_event_np(self, state) -> int:
        """Host-side earliest-event readback (step-mode telemetry):
        queue rows are sorted, so column 0 is each queue's min — [N]
        lanes plus the [2S] tier block when tiered."""
        nxt = int(
            np.asarray(
                lanes.t_join(state.q_thi[:, 0], state.q_tlo[:, 0])
            ).min()
        )
        if self.params.stream_tiered:
            tq = state.stream.q
            nxt = min(nxt, int(np.asarray(lanes.t_join(
                tq[lstr_mod.TQ_THI, :, 0], tq[lstr_mod.TQ_TLO, :, 0]
            )).min()))
        return nxt

    def current_runahead(self) -> int:
        """Live window width (dynamic runahead reads the device scalar;
        static mode is the precomputed minimum) — the step driver's
        window predictor and run-control's host listing use this."""
        p = self.params
        if not p.dynamic_runahead:
            return p.runahead
        state = getattr(self, "_live_state", None)
        if state is None:
            return p.runahead
        used = int(state.min_used_lat)
        if used >= lanes.NEVER32:
            return p.runahead
        return max(used, max(p.runahead_floor, 1))

    # -- hybrid kernel variants --------------------------------------------

    def make_hybrid_fns(self, fuse_k: int = 1, ext_slots: int = 0):
        """The hybrid backend's jitted device entry points, built against
        this engine's params/tables: ``(turn_fn, inject_fn)``.

        ``fuse_k == 1`` returns the single-window law
        (:func:`lanes.make_hybrid_fn` signature); ``fuse_k >= 2`` returns
        the k-window fused variant (:func:`lanes.make_hybrid_fused_fn`,
        docs/hybrid.md "k-window fusion law") whose dispatch covers up to
        ``fuse_k`` participating windows against a host-peeked
        ``ext_slots``-wide event-time schedule.

        With a mesh attached the same entry points compile SHARDED
        (parallel.make_sharded_hybrid_fns): lane state split on the host
        axis, the injection/egress boundary replicated — same transfer
        counts, same bits."""
        if self._mesh is not None:
            from .. import parallel

            return parallel.make_sharded_hybrid_fns(
                self.params, self.tables, self._mesh,
                fuse_k=fuse_k, ext_slots=ext_slots,
            )
        inject_fn = lanes.make_inject_fn(self.params, self.tables)
        if fuse_k >= 2:
            return (
                lanes.make_hybrid_fused_fn(
                    self.params, self.tables, fuse_k, ext_slots
                ),
                inject_fn,
            )
        return lanes.make_hybrid_fn(self.params, self.tables), inject_fn

    # -- sweep kernel (shadow_tpu/sweep drives this) -----------------------

    def make_sweep_fn(self):
        """The sweep backend's jitted vmapped entry point, built against
        this engine's STATIC params (:func:`lanes.make_sweep_fn`): the
        per-scenario tables, stop bounds, and lane states are traced
        arguments, so one compile serves every congruent variant.  The
        returned wrapper's ``.traces`` attribute is the compile probe."""
        return lanes.make_sweep_fn(self.params)

    def sweep_tables(self, snap=None) -> lanes.LaneTables:
        """This engine's device tables as ONE SCENARIO ROW of a sweep
        batch: the traced ``seed_lo``/``seed_hi`` leaves are populated
        from the config seed (core.rng ``_split_seed`` semantics — the
        exact key words the static path compiles in), and ``snap`` (a
        faults Snapshot) re-gathers the epoch's latency/loss tables."""
        from ..core import rng as _rng

        tb = self.tables if snap is None else self._segment_tables(snap)
        s_lo, s_hi = _rng._split_seed(self.params.seed)
        return tb._replace(
            seed_lo=jnp.uint32(s_lo), seed_hi=jnp.uint32(s_hi)
        )

    # -- state construction ------------------------------------------------

    def initial_state(self) -> lanes.LaneState:
        p = self.params
        n, c = p.n_lanes, p.capacity
        q_time = np.full((n, c), NEVER, dtype=np.int64)
        q_auxh = np.zeros((n, c), dtype=np.int32)
        q_auxl = np.zeros((n, c), dtype=np.int32)
        q_size = np.zeros((n, c), dtype=np.int32)
        fill = np.zeros(n, dtype=np.int64)
        # tiered: stream endpoints' init events live in the tier queue
        c2 = p.stream_capacity
        s2 = 2 * self._s_flows
        if p.stream_tiered:
            tq_time = np.full((s2, c2), NEVER, dtype=np.int64)
            tq_auxh = np.zeros((s2, c2), dtype=np.int32)
            tq_auxl = np.zeros((s2, c2), dtype=np.int32)
            tq_size = np.zeros((s2, c2), dtype=np.int32)
            tfill = np.zeros(s2, dtype=np.int64)
        ev_lane, ev_t, ev_kind, ev_src, ev_seq, ev_size = self._init_cols
        if self._ep_of_lane:
            # tiered: stream endpoints' events route to tier rows — a
            # handful of compacted flows, the per-event loop is fine
            for lane, t, kind, src, seq, size in zip(
                ev_lane.tolist(), ev_t.tolist(), ev_kind.tolist(),
                ev_src.tolist(), ev_seq.tolist(), ev_size.tolist(),
            ):
                row = self._ep_of_lane.get(lane)
                if row is not None:
                    i = tfill[row]
                    tq_time[row, i] = t
                    tq_auxh[row, i] = (kind << lanes.AUX_KIND_SHIFT) | (
                        src << lanes.AUX_SRC_SHIFT
                    )
                    tq_auxl[row, i] = seq
                    tq_size[row, i] = size
                    tfill[row] += 1
                    continue
                i = fill[lane]
                q_time[lane, i] = t
                q_auxh[lane, i] = (kind << lanes.AUX_KIND_SHIFT) | (
                    src << lanes.AUX_SRC_SHIFT
                )
                q_auxl[lane, i] = seq
                q_size[lane, i] = size
                fill[lane] += 1
        elif ev_lane.size:
            # vectorized fill (the 100k-host startup path): stable-sort
            # events by lane and slot each into its per-lane cumcount
            # position — same per-lane event sets as the scalar loop, and
            # the per-row lexsort below normalizes slot order either way
            order = np.argsort(ev_lane, kind="stable")
            l_s = ev_lane[order]
            counts = np.bincount(l_s, minlength=n)
            starts = np.concatenate(([0], np.cumsum(counts)[:-1]))
            pos = np.arange(l_s.size) - np.repeat(starts, counts)
            q_time[l_s, pos] = ev_t[order]
            q_auxh[l_s, pos] = (ev_kind[order] << lanes.AUX_KIND_SHIFT) | (
                ev_src[order] << lanes.AUX_SRC_SHIFT
            )
            q_auxl[l_s, pos] = ev_seq[order]
            q_size[l_s, pos] = ev_size[order]
        # the round kernel keeps queue rows sorted by the 4-word key as an
        # invariant; establish it here (aux_lo before aux_hi: np.lexsort
        # takes the PRIMARY key last)
        order = np.lexsort((q_auxl, q_auxh, q_time), axis=1)
        q_time = np.take_along_axis(q_time, order, axis=1)
        q_auxh = np.take_along_axis(q_auxh, order, axis=1)
        q_auxl = np.take_along_axis(q_auxl, order, axis=1)
        q_size = np.take_along_axis(q_size, order, axis=1)
        never = q_time == NEVER
        q_thi = np.where(never, lanes.NEVER32, q_time >> 31).astype(np.int32)
        q_tlo = np.where(never, lanes.NEVER32, q_time & lanes.MASK31).astype(
            np.int32
        )

        # no stream tier -> no stream matrices AND no payload columns: the
        # while-loop carry pays a per-buffer cost every iteration on the
        # tunneled runtime, so dead zero arrays are real wall time.
        # Flow matrices are COMPACTED: [S, F] per endpoint side
        if p.stream_tiered:
            el = self._el_np
            stream0 = lstr_mod.init_tier_state(
                self._s_flows, c2,
                dn_tokens=self._dn_params[el, 1],
                up_tokens=self._up_params[el, 1],
                interval=self._interval,
            )
            # establish the tier rows' sorted invariant + initial local
            # seq counters (one start marker consumed per endpoint)
            order = np.lexsort((tq_auxl, tq_auxh, tq_time), axis=1)
            tq_time = np.take_along_axis(tq_time, order, axis=1)
            tq_auxh = np.take_along_axis(tq_auxh, order, axis=1)
            tq_auxl = np.take_along_axis(tq_auxl, order, axis=1)
            tq_size = np.take_along_axis(tq_size, order, axis=1)
            tnever = tq_time == NEVER
            tq = np.zeros((7, s2, c2), dtype=np.int32)
            tq[lstr_mod.TQ_THI] = np.where(
                tnever, lanes.NEVER32, tq_time >> 31
            )
            tq[lstr_mod.TQ_TLO] = np.where(
                tnever, lanes.NEVER32, tq_time & lanes.MASK31
            )
            tq[lstr_mod.TQ_AUXH] = tq_auxh
            tq[lstr_mod.TQ_AUXL] = tq_auxl
            tq[lstr_mod.TQ_SIZE] = tq_size
            v0 = np.asarray(stream0.v)
            v0 = v0.copy()
            v0[lstr_mod.TV_LOCAL_SEQ] = self._local_seq0[self._el_np]
            stream0 = stream0._replace(
                q=jnp.asarray(tq), v=jnp.asarray(v0)
            )
        elif p.stream_present:
            stream0 = lstr_mod.init_stream_state(self._s_flows)
        else:
            stream0 = ()

        up_burst = np.asarray(self.tables.up_burst)
        dn_burst = np.asarray(self.tables.dn_burst)
        i32 = jnp.int32
        z32 = np.zeros(n, dtype=np.int32)
        # bucket state: next_refill starts one interval in (grid-aligned),
        # last_depart at 0 — as pairs (hi, lo); CoDel first_above starts at
        # the UNSET sentinel (the int64 law's time-0 marker)
        return lanes.LaneState(
            q_thi=jnp.asarray(q_thi),
            q_tlo=jnp.asarray(q_tlo),
            q_auxh=jnp.asarray(q_auxh),
            q_auxl=jnp.asarray(q_auxl),
            q_size=jnp.asarray(q_size),
            q_phi=(
                jnp.zeros((n, c), dtype=jnp.int32)
                if p.lanes_have_payload else ()
            ),
            q_plo=(
                jnp.zeros((n, c), dtype=jnp.int32)
                if p.lanes_have_payload else ()
            ),
            stream=stream0,
            send_seq=jnp.asarray(z32),
            local_seq=jnp.asarray(self._local_seq0, dtype=i32),
            app_draws=jnp.asarray(z32),
            up_tokens=jnp.asarray(up_burst, dtype=i32),
            up_nr_hi=jnp.asarray(z32),
            up_nr_lo=jnp.full(n, self._interval, dtype=i32),
            up_ld_hi=jnp.asarray(z32),
            up_ld_lo=jnp.asarray(z32),
            dn_tokens=jnp.asarray(dn_burst, dtype=i32),
            dn_nr_hi=jnp.asarray(z32),
            dn_nr_lo=jnp.full(n, self._interval, dtype=i32),
            dn_ld_hi=jnp.asarray(z32),
            dn_ld_lo=jnp.asarray(z32),
            cd_fat_hi=jnp.full(n, lanes.CD_UNSET, dtype=i32),
            cd_fat_lo=jnp.asarray(z32),
            cd_dnext_hi=jnp.asarray(z32),
            cd_dnext_lo=jnp.asarray(z32),
            cd_drop_count=jnp.asarray(z32),
            cd_dropping=jnp.zeros(n, dtype=bool),
            m_sent=jnp.asarray(z32),
            m_peer_offset=jnp.asarray(z32),
            n_delivered=jnp.asarray(z32),
            n_loss=jnp.asarray(z32),
            n_codel=jnp.asarray(z32),
            n_queue=jnp.asarray(z32),
            recv_bytes=jnp.asarray(z32),
            n_sends=jnp.asarray(z32),
            n_hops=jnp.asarray(z32),
            log=jnp.zeros((max(self.params.log_capacity, 1), 6), dtype=jnp.int64),
            log_count=jnp.int32(0),
            log_lost=jnp.int32(0),
            rounds=jnp.int32(0),
            iters=jnp.int32(0),
            now_we_hi=jnp.int32(0),
            now_we_lo=jnp.int32(0),
            min_used_lat=jnp.int32(lanes.NEVER32),
            egress=(
                jnp.zeros((p.egress_capacity, 6), dtype=jnp.int64)
                if p.external_any else ()
            ),
            egress_count=jnp.int32(0) if p.external_any else (),
            egress_lost=jnp.int32(0) if p.external_any else (),
            egress_min_hi=jnp.int32(lanes.NEVER32) if p.external_any else (),
            egress_min_lo=jnp.int32(lanes.NEVER32) if p.external_any else (),
            nb_txb=jnp.asarray(z32) if p.netobs else (),
            nb_rxb=jnp.asarray(z32) if p.netobs else (),
            nb_thr=jnp.asarray(z32) if p.netobs else (),
            nb_shed=jnp.asarray(z32) if p.netobs else (),
            nb_hist=(
                jnp.zeros(lanes.NB_HIST_BUCKETS, dtype=i32)
                if p.netobs else ()
            ),
            nb_win=jnp.int32(0) if p.netobs else (),
            fl_buf=(
                jnp.zeros((p.flow_capacity, ftr.FT_COLS), dtype=i32)
                if p.flowtrace else ()
            ),
            fl_count=jnp.int32(0) if p.flowtrace else (),
            fl_lost=jnp.int32(0) if p.flowtrace else (),
        )

    # -- running -----------------------------------------------------------

    def run(
        self, mode: str = "device", precompile: bool = False, on_window=None,
        cache_salt: int = 0, resume_state=None, resume_epoch: int = 0,
        disarm_stalls: bool = False,
    ) -> SimResult:
        """``mode='device'``: one fused while_loop on the accelerator;
        ``mode='step'``: one device call per round (debuggable, pausable —
        ``on_window(window_start, window_end, next_event_time)`` runs after
        every round, the run-control/heartbeat seam).
        ``precompile``: AOT-compile before starting the wall-clock timer so
        ``wall_seconds`` measures only the steady-state device program.
        ``cache_salt``: nonzero writes the salt into an INERT queue slot
        (a NEVER-keyed empty slot's aux word — never popped, dropped by
        the first merge, zero effect on results) so repeat timings cannot
        be served from the tunneled runtime's cross-process execution
        cache, which keys on (program, input buffers).
        ``resume_state``/``resume_epoch``: continue from a checkpointed
        lane state (engine/checkpoint.py) — the lane pytree carries the
        whole simulation, so running it to stop_time reproduces the
        uninterrupted run's suffix exactly.  ``disarm_stalls`` skips
        injected ``backend_stall`` raises on the faulted path: the
        checkpoint-anchored failover resume must replay *through* the
        epoch that killed the first attempt."""
        if resume_state is not None and (precompile or cache_salt):
            raise LaneCompatError(
                "precompile/cache_salt are bench affordances; they are "
                "not supported together with checkpoint resume"
            )
        if self._fault_overlay is not None:
            if precompile or cache_salt:
                raise LaneCompatError(
                    "precompile/cache_salt are bench affordances; they are "
                    "not supported together with a fault schedule"
                )
            return self._run_faulted(
                mode, on_window=on_window, resume_state=resume_state,
                resume_epoch=resume_epoch, disarm_stalls=disarm_stalls,
            )
        state = (
            resume_state if resume_state is not None else self.initial_state()
        )
        self._iters_salt = 0
        if cache_salt:
            state = state._replace(
                q_auxl=state.q_auxl.at[0, -1].set(
                    int(cache_salt) & 0x7FFFFFFF
                )
            )
            # belt and braces: ALSO bias the iters bookkeeping counter by
            # the salt (subtracted at collect) — it is loop-carried
            # through every iteration, so no cached execution with a
            # different salt can serve this run even if the runtime's
            # cache key misses the inert queue-slot delta (observed once:
            # a 5-sim-s mixed run "completed" in 2 ms)
            self._iters_salt = int(cache_salt) & 0xFFFFF
            state = state._replace(iters=jnp.int32(self._iters_salt))
        # with a mesh attached, commit the state to its sharded placement
        # and compile the driver under the mesh (parallel/mesh.py)
        state = self.place_state(state)
        if mode == "device":
            # cache the program: repeat runs (bench best-of-N) must not
            # retrace/recompile
            run_fn = getattr(self, "_run_fn", None)
            if run_fn is None:
                if self._mesh is not None:
                    from .. import parallel

                    run_fn = self._run_fn = parallel.make_sharded_run_fn(
                        self.params, self.tables, self._mesh
                    )
                else:
                    run_fn = self._run_fn = lanes.make_run_fn(
                        self.params, self.tables
                    )
            if precompile and getattr(self, "_compiled", None) is None:
                # AOT-compile so the timed run is the steady-state program
                self._compiled = run_fn.lower(state).compile()
            if getattr(self, "_compiled", None) is not None:
                run_fn = self._compiled
            t0 = wall_time.perf_counter()
            if self.obs is None:
                state = jax.block_until_ready(run_fn(state))
            else:
                # the fused loop is one opaque device call: attribute it
                # as a single device_turn span (per-window spans need the
                # step driver — run-control/perf-logging select it)
                with self.obs.phase("device_turn", name="device_free_run"):
                    state = jax.block_until_ready(run_fn(state))
            wall = wall_time.perf_counter() - t0
        else:
            if self._mesh is not None:
                from .. import parallel

                round_fn = parallel.make_sharded_round_fn(
                    self.params, self.tables, self._mesh
                )
            else:
                round_fn = lanes.make_round_fn(self.params, self.tables)
            t0 = wall_time.perf_counter()
            state = self._drive_steps(round_fn, state, on_window, self.params)
            wall = wall_time.perf_counter() - t0
        result = self.collect(state, wall)
        if mode == "device" and self.obs is not None and self.obs.turns is not None:
            # the fused driver's whole run is ONE unforced dispatch: the
            # ledger's free-run baseline, with its actual free-run length
            # (the windows the dispatch covered — known at collect, no
            # extra transfer)
            self.obs.turns.turn(
                "free_run", 0, self.params.stop_time, windows=result.rounds
            )
        return result

    def checkpoint_payload(self):
        """The live lane state as a host-side (numpy) pytree — the whole
        simulation (queues, clocks, RNG counters, flows, device log) in
        one NamedTuple, directly picklable and directly feedable back
        into ``run(resume_state=...)``.  Only meaningful from the step
        driver's ``on_window`` seam, where the handle is post-round
        (see ``_drive_steps``)."""
        state = getattr(self, "_live_state", None)
        if state is None:
            raise RuntimeError(
                "no live lane state to checkpoint (the step driver has"
                " not completed a round yet)"
            )
        return jax.device_get(state)

    def _drive_steps(
        self, round_fn, state: lanes.LaneState, on_window, p: lanes.LaneParams,
        first_cause: str = "snapshot",
    ) -> lanes.LaneState:
        """The step driver's round loop (one device call per round) up to
        ``p.stop_time`` — shared by the plain run and every fault-epoch
        segment.  Each round is timed under the stall watchdog when
        ``faults.watchdog_timeout`` is configured.

        Ledger causes (obs/turns.py): the step driver exists exactly so
        run-control can pause at every boundary, so its window-advancing
        dispatches record as ``snapshot`` turns — except the first
        dispatch of a fault-epoch segment, which ``_run_faulted`` passes
        in as ``fault_swap``."""
        from ..faults.watchdog import RoundWatchdog

        wd = (
            RoundWatchdog(self._watchdog_timeout)
            if self._watchdog_timeout is not None
            else None
        )
        obs = self.obs
        turns = obs.turns if obs is not None else None
        turn_cause = first_cause
        while True:
            self._live_state = state
            if on_window is not None or self.perf_log is not None or obs is not None:
                # queue rows are sorted: column 0 is each lane's min
                lane_next = np.asarray(
                    lanes.t_join(state.q_thi[:, 0], state.q_tlo[:, 0])
                )
                start = self._next_event_np(state)
                we_pred = min(start + self.current_runahead(), p.stop_time)
                active = int((lane_next < we_pred).sum())
                if p.stream_tiered:
                    tq = state.stream.q
                    tier_next = np.asarray(lanes.t_join(
                        tq[lstr_mod.TQ_THI, :, 0],
                        tq[lstr_mod.TQ_TLO, :, 0],
                    ))
                    active += int((tier_next < we_pred).sum())
            t_round = wall_time.perf_counter()
            state, done = round_fn(state)
            done = bool(done)  # forces the device sync the timing needs
            # refresh the live-state handle POST-round: netobs_lines and
            # checkpoint capture both read it at on_window time, when the
            # obs accumulators already reflect this round — a stale
            # pre-round handle would desynchronize a checkpoint's lane
            # state from its obs state (one window double-counted on
            # resume)
            self._live_state = state
            t_done = wall_time.perf_counter()
            if wd is not None:
                wd.observe(t_done - t_round)
            if obs is not None:
                obs.record(
                    "device_turn", "device_round", t_round, t_done - t_round,
                    active=active,
                )
                m = obs.metrics
                m.count("device_turns")
                m.observe("window_active_hosts", active)
            if done:
                break
            if on_window is not None or self.perf_log is not None or obs is not None:
                window_end = int(
                    (int(state.now_we_hi) << 31) | int(state.now_we_lo)
                )
                next_ev = self._next_event_np(state)
                if turns is not None:
                    turns.turn(turn_cause, start, window_end)
                    turn_cause = "snapshot"
                if obs is not None:
                    obs.metrics.count("windows")
                    obs.metrics.observe("window_span_ns", window_end - start)
                if self.perf_log is not None:
                    self.perf_log.window_agg(
                        active, start, window_end,
                        min(next_ev, p.stop_time),
                    )
                if on_window is not None:
                    on_window(start, window_end, next_ev)
        return state

    # -- fault-epoch segmentation ------------------------------------------

    def _segment_tables(self, snap) -> lanes.LaneTables:
        """Re-upload the versioned gather tables for a fault epoch: the
        [G, G] latency/threshold tables plus the per-flow compactions the
        stream tier gathers from them."""
        import jax.numpy as _jnp

        lat_np = np.asarray(snap.latency_ns)
        thr_np = np.asarray(snap.loss_threshold)
        kw = dict(
            lat=_jnp.asarray(lat_np, dtype=_jnp.int32),
            thresh_u32=_jnp.asarray(
                (thr_np & 0xFFFFFFFF).astype(np.uint32)
            ),
            thresh_all=_jnp.asarray(thr_np >= (1 << 32)),
        )
        if self._s_flows:
            e_nodes = np.asarray(self._node_idx)[self._el_np]
            p_nodes = np.asarray(self._node_idx)[self._peer_np]
            flow_lat = lat_np[e_nodes, p_nodes].astype(np.int32)
            flow_thr = thr_np[e_nodes, p_nodes]
            kw.update(
                flow_lat=_jnp.asarray(flow_lat),
                flow_thresh_u32=_jnp.asarray(
                    (flow_thr & 0xFFFFFFFF).astype(np.uint32)
                ),
                flow_thresh_all=_jnp.asarray(flow_thr >= (1 << 32)),
            )
        return self.tables._replace(**kw)

    def _run_faulted(
        self, mode: str, on_window=None, resume_state=None,
        resume_epoch: int = 0, disarm_stalls: bool = False,
    ) -> SimResult:
        """Run the simulation segmented at fault epochs: each segment is
        an ordinary (fused or step-wise) run whose stop time is the next
        epoch, against that epoch's tables.  Windows therefore never
        straddle a fault — the identical clamp law the CPU engine applies
        — and the lane state (queues, buckets, RNG counters, flows)
        carries across segments untouched.

        Resume (engine/checkpoint.py): segments whose end lies at or
        before ``resume_epoch`` already happened inside ``resume_state``
        and are skipped; the first live segment continues from the
        resumed state mid-segment.  Its first ledger row records as
        ``snapshot`` — the segment's ``fault_swap`` row predates the
        checkpoint and lives in the restored ledger."""
        import dataclasses as _dc

        from ..faults.watchdog import BackendStallError

        ov = self._fault_overlay
        stop = self.params.stop_time
        # segment_plan owns the boundary law (and the padded no-op rows
        # the sweep path batches over — _fault_pad lets the padded-parity
        # test drive them through this serial loop too)
        plan = ov.segment_plan(stop, pad_to=getattr(self, "_fault_pad", 0))
        resumed = resume_state is not None
        state = resume_state if resumed else self.initial_state()
        self._iters_salt = 0
        fns = getattr(self, "_seg_fns", None)
        if fns is None:
            fns = self._seg_fns = {}
        t0 = wall_time.perf_counter()
        turns = self.obs.turns if self.obs is not None else None
        seg_rounds = int(np.asarray(state.rounds)) if resumed else 0
        first_live = True
        for seg_start, seg_end, snap in plan:
            if resumed and seg_end <= resume_epoch:
                continue  # the checkpoint already covers it
            if (
                0 < seg_start < seg_end
                and not disarm_stalls
                and ov.stall_at(seg_start)
            ):
                raise BackendStallError(
                    f"injected backend stall at {seg_start} ns "
                    "(fault schedule backend_stall event)"
                )
            tb = self.tables if snap is None else self._segment_tables(snap)
            p = _dc.replace(self.params, stop_time=seg_end)
            key = (seg_start, seg_end, mode)
            fn = fns.get(key)
            swap_cause = (
                "snapshot"
                if seg_start == 0 or (resumed and first_live)
                else "fault_swap"
            )
            first_live = False
            if mode == "device":
                if fn is None:
                    fn = fns[key] = lanes.make_run_fn(p, tb)
                state = jax.block_until_ready(fn(state))
                if turns is not None:
                    # one fused dispatch per epoch segment; the rounds
                    # delta is its measured free-run length (faulted runs
                    # are never the timed bench path, so this readback is
                    # ledger-only)
                    r = int(state.rounds)
                    turns.turn(
                        "free_run" if swap_cause == "snapshot"
                        else "fault_swap",
                        seg_start, seg_end, windows=r - seg_rounds,
                    )
                    seg_rounds = r
            else:
                if fn is None:
                    fn = fns[key] = lanes.make_round_fn(p, tb)
                state = self._drive_steps(
                    fn, state, on_window, p, first_cause=swap_cause,
                )
        wall = wall_time.perf_counter() - t0
        return self.collect(state, wall)

    def _write_pcaps(self, event_rows, pcap_rows) -> None:
        """Reconstruct per-host capture files from the device log:
        outbound = PCAP_TX records at bucket-departure time, inbound =
        DELIVERED records at delivery time — the same two capture points
        as the CPU backend (cpu_engine.send_packet / deliver), so the
        files diff byte-identical across backends."""
        from pathlib import Path as _Path

        from ..core import time as _stime
        from ..utils.pcap import PcapWriter

        # one sort per array, then per-host SLICES via searchsorted —
        # not a full-array mask per host (O(hosts x rows) otherwise)
        if pcap_rows.size:
            out_sorted = pcap_rows[np.argsort(pcap_rows[:, 1], kind="stable")]
            out_keys = out_sorted[:, 1]
        else:
            out_sorted = out_keys = np.zeros((0,), dtype=np.int64)
        delivered = (
            event_rows[event_rows[:, 5] == lanes.DELIVERED]
            if event_rows.size else event_rows
        )
        if delivered.size:
            in_sorted = delivered[np.argsort(delivered[:, 2], kind="stable")]
            in_keys = in_sorted[:, 2]
        else:
            in_sorted = in_keys = np.zeros((0,), dtype=np.int64)
        for hid, hopt in enumerate(self.cfg.hosts):
            if not hopt.pcap_enabled or self._external[hid]:
                # external (hybrid) hosts' pcap files are written by the
                # HOST side, which knows the payload bytes — rewriting
                # them here would clobber the richer capture
                continue
            # both backends write records sorted by (time, direction,
            # src, dst, seq) — PcapWriter buffers and sorts at close, so
            # the files are byte-identical even when bucket backlog makes
            # departure stamps non-monotone in processing order
            recs = []
            if out_keys.size:
                lo, hi = np.searchsorted(out_keys, [hid, hid + 1])
                for t, src, dst, seq, size, _o in out_sorted[lo:hi]:
                    recs.append((int(t), 1, int(src), int(dst), int(seq),
                                 int(size)))
            if in_keys.size:
                lo, hi = np.searchsorted(in_keys, [hid, hid + 1])
                for t, src, dst, seq, size, _o in in_sorted[lo:hi]:
                    recs.append((int(t), 0, int(src), int(dst), int(seq),
                                 int(size)))
            w = PcapWriter(
                _Path(self.cfg.general.data_directory)
                / "hosts" / hopt.hostname / "eth0.pcap",
                snaplen=hopt.pcap_capture_size,
            )
            for t, dirn, src, dst, seq, size in recs:
                w.capture(
                    _stime.sim_to_emu(t), self.ips.by_host[src],
                    self.ips.by_host[dst], size, None,
                    key=(dirn, src, dst, seq),
                )
            w.close()

    def collect(self, s: lanes.LaneState, wall: float) -> SimResult:
        # int32 counter honesty: every per-lane counter is monotone, so a
        # wrap past 2**31 shows as a negative value — raise instead of
        # reporting garbage (2e9 events per lane is unreachable in any
        # realistic run)
        wrap_check = ["send_seq", "local_seq", "n_delivered", "n_sends",
                      "recv_bytes", "m_peer_offset"]
        if self.params.netobs:
            wrap_check += ["nb_txb", "nb_rxb", "nb_thr"]
        for fname in wrap_check:
            if int(np.asarray(getattr(s, fname)).min(initial=0)) < 0:
                raise RuntimeError(
                    f"lane counter {fname} wrapped past 2**31; this run "
                    "exceeds the lane backend's int32 counter range"
                )
        # tiered stream backend: fold the [2S] tier's compact counters
        # into the lane totals (the tier owns stream endpoints' network
        # accounting)
        tv = (
            np.asarray(s.stream.v) if self.params.stream_tiered else None
        )
        if tv is not None and int(tv[lstr_mod.TV_SEND_SEQ].min(initial=0)) < 0:
            raise RuntimeError(
                "tier counter send_seq wrapped past 2**31; this run "
                "exceeds the lane backend's int32 counter range"
            )

        def tier_sum(row: int) -> int:
            return int(tv[row].sum()) if tv is not None else 0

        n_queue_drops = int(np.asarray(s.n_queue).sum()) + tier_sum(
            lstr_mod.TV_N_QUEUE
        )
        if n_queue_drops and self.strict_capacity:
            raise RuntimeError(
                f"{n_queue_drops} events dropped on lane-queue overflow; raise "
                "experimental.tpu_lane_queue_capacity (results would silently "
                "diverge from the cpu backend)"
            )
        log_count = int(s.log_count)
        log_lost = int(s.log_lost)
        if log_lost:
            # surface the overflow as a metrics-registry counter BEFORE
            # raising: failed runs still flush partial obs artifacts
            # (engine/sim.py's finally), so the loss is machine-visible
            # in METRICS_*.json instead of only a crash string
            if self.obs is not None:
                self.obs.metrics.count("device_log_lost", log_lost)
                self.obs.metrics.gauge("device_log_overflowed", True)
            raise RuntimeError(
                f"device event log overflowed ({log_lost} records lost); "
                "raise log_capacity or disable logging"
            )
        rows = np.asarray(s.log[: min(log_count, self.params.log_capacity)])
        if self.params.pcap_any:
            pcap_rows = rows[rows[:, 5] == lanes.PCAP_TX] if rows.size else rows
            rows = rows[rows[:, 5] != lanes.PCAP_TX] if rows.size else rows
            self._write_pcaps(rows, pcap_rows)
        event_log = [
            LogRecord(int(t), int(src), int(dst), int(seq), int(size), int(out))
            for t, src, dst, seq, size, out in rows
        ]
        model = np.asarray(self.tables.model)
        recv_bytes = np.asarray(s.recv_bytes)
        delivered = np.asarray(s.n_delivered)
        counters: dict[str, int] = {}

        def add(key: str, val: int) -> None:
            if val:
                counters[key] = counters.get(key, 0) + int(val)

        tgen_mask = np.isin(model, [lanes.M_TGEN_MESH, lanes.M_TGEN_CLIENT, lanes.M_TGEN_SERVER])
        add("tgen_recv_bytes", int(recv_bytes[tgen_mask].sum()))
        hops = np.asarray(s.n_hops)
        add("phold_hops", int(hops[model == lanes.M_PHOLD].sum()))
        add("lane_iters", int(s.iters) - getattr(self, "_iters_salt", 0))
        add("lane_delivered", int(delivered.sum()) + tier_sum(lstr_mod.TV_N_DEL))
        add("lane_drop_loss", int(np.asarray(s.n_loss).sum())
            + tier_sum(lstr_mod.TV_N_LOSS))
        add("lane_drop_codel", int(np.asarray(s.n_codel).sum())
            + tier_sum(lstr_mod.TV_N_CODEL))
        add("lane_drop_queue", n_queue_drops)
        add("lane_sends", int(np.asarray(s.n_sends).sum())
            + tier_sum(lstr_mod.TV_N_SENDS))

        if self.params.stream_present:
            # compacted flow matrices: every cl row is a client endpoint,
            # every sv row its server endpoint
            flows = (
                s.stream.flows if self.params.stream_tiered else s.stream
            )
            cl_m = np.asarray(flows.cl)
            sv_m = np.asarray(flows.sv)
            done = cl_m[:, lstr_mod.C_COMPLETED] != 0
            if done.any():
                # tx/retransmit totals count at completion, like the CPU
                # _track — including zero-valued keys (counter-set parity)
                counters["stream_complete"] = int(done.sum())
                counters["stream_tx_segs"] = int(
                    cl_m[done, lstr_mod.C_TX_SEGS].sum()
                )
                counters["stream_retransmits"] = int(
                    cl_m[done, lstr_mod.C_RETRANS].sum()
                )
            add("stream_rx_bytes", int(sv_m[:, lstr_mod.C_RX_BYTES].sum()))
            add("stream_rx_segs", int(sv_m[:, lstr_mod.C_RX_SEGS].sum()))
            add(
                "stream_flows_done",
                int((sv_m[:, lstr_mod.C_COMPLETED] != 0).sum()),
            )

        if self.params.netobs:
            self._netobs_data = self._netobs_collect(s, tv)
        if self.params.flowtrace:
            self._flowtrace_data = self._flowtrace_collect(s)

        return SimResult(
            sim_time_ns=self.params.stop_time,
            wall_seconds=wall,
            rounds=int(s.rounds),
            event_log=event_log,
            counters=counters,
            per_host_counters=[],
        )

    # -- netobs telemetry plane (obs/netobs.py) ----------------------------

    def _netobs_collect(self, s: lanes.LaneState, tv) -> dict:
        """Fold the device-resident telemetry block into the canonical
        per-host array schema (obs.netobs).  Piggybacks the collect
        readback — no extra device sync beyond the arrays already
        fetched at end-of-run."""
        from ..obs import netobs as nom

        n = self.params.n_lanes

        def fold(lane_arr, tv_row=None):
            out = np.asarray(lane_arr).astype(np.int64).copy()
            if tv is not None and tv_row is not None:
                # tier rows are per endpoint; scatter-add back to lanes
                np.add.at(out, self._el_np, tv[tv_row].astype(np.int64))
            return out

        from . import lanes_stream as lstr

        arrays = {
            "sent": fold(s.n_sends, lstr.TV_N_SENDS),
            "delivered": fold(s.n_delivered, lstr.TV_N_DEL),
            "tx_bytes": fold(s.nb_txb, lstr.TV_NB_TXB),
            "rx_bytes": fold(s.nb_rxb, lstr.TV_NB_RXB),
            "drop_loss": fold(s.n_loss, lstr.TV_N_LOSS),
            "drop_codel": fold(s.n_codel, lstr.TV_N_CODEL),
            "drop_queue": fold(s.n_queue, lstr.TV_N_QUEUE)
            - np.asarray(s.nb_shed).astype(np.int64),
            "drop_cross_shed": fold(s.nb_shed),
            "throttled": fold(s.nb_thr, lstr.TV_NB_THR),
            "retransmits": np.zeros(n, dtype=np.int64),
            "retry_giveup": np.zeros(n, dtype=np.int64),
        }
        if self.params.stream_present:
            # retransmit attribution mirrors the CPU _track: counted at
            # the CLIENT lane, for completed flows only
            flows = (
                s.stream.flows if self.params.stream_tiered else s.stream
            )
            cl_m = np.asarray(flows.cl)
            done = cl_m[:, lstr.C_COMPLETED] != 0
            cl_lanes = np.asarray(self.params.stream_clients, dtype=np.int64)
            if cl_lanes.size:
                np.add.at(
                    arrays["retransmits"], cl_lanes,
                    np.where(done, cl_m[:, lstr.C_RETRANS], 0).astype(
                        np.int64
                    ),
                )
        hist = np.asarray(s.nb_hist).astype(np.int64).copy()
        # trailing window: its occupancy was never followed by a window
        # advance, so flush it here (host-side, same bucket law)
        tail = int(s.nb_win)
        if tail > 0:
            hist[nom.hist_bucket(tail)] += 1
        return {"arrays": arrays, "window_hist": hist, "log_lost": 0}

    def netobs_snapshot(self):
        """The device telemetry snapshot of the last collected run (None
        when netobs is off or no run has completed)."""
        return self._netobs_data

    def netobs_lines(self, host: Optional[str] = None) -> list[str]:
        """Run-control ``netstats`` answer: summarize the LIVE device
        counters (step driver — ``_live_state`` is refreshed per round;
        reading it here is a snapshot-epoch fetch, not a new per-window
        sync)."""
        from ..obs import netobs as nom

        if not self.params.netobs:
            return ["netobs is not enabled (set experimental.netobs)"]
        state = getattr(self, "_live_state", None)
        if state is None:
            return ["no live device state yet (step driver only)"]
        tv = (
            np.asarray(state.stream.v)
            if self.params.stream_tiered else None
        )
        snap = self._netobs_collect(state, tv)
        names = [h.hostname for h in self.cfg.hosts]
        return nom.snapshot_lines(snap["arrays"], snap["window_hist"],
                                  names, host)

    # -- flowtrace plane (obs/flowtrace.py) --------------------------------

    def _flowtrace_collect(self, s: lanes.LaneState) -> dict:
        """Decode the device flow ring into event tuples.  The ring never
        wraps, so the kept rows are the contiguous prefix; overflow only
        bumps ``fl_lost``.  Piggybacks the collect readback — no extra
        device sync."""
        kept = min(int(s.fl_count), self.params.flow_capacity)
        rows = np.asarray(s.fl_buf)[:kept]
        return {
            "raw": ftr.rows_to_events(rows),
            "ring_lost": int(s.fl_lost),
        }

    def flowtrace_snapshot(self):
        """Decoded flow events of the last collected run (None when
        flowtrace is off or no run has completed)."""
        return self._flowtrace_data

    def flowtrace_lines(self, host: Optional[str] = None) -> list[str]:
        """Run-control ``flows`` answer from the LIVE device ring (step
        driver; snapshot-epoch fetch like netobs_lines)."""
        if not self.params.flowtrace:
            return ["flowtrace is not enabled (set experimental.flowtrace)"]
        state = getattr(self, "_live_state", None)
        if state is None:
            return ["no live device state yet (step driver only)"]
        snap = self._flowtrace_collect(state)
        events, lost = ftr.canonical_events(
            snap["raw"], self.params.flow_capacity
        )
        names = [h.hostname for h in self.cfg.hosts]
        return ftr.snapshot_lines(
            events, lost + snap["ring_lost"], names, host=host
        )
