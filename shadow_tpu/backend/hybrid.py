"""Hybrid backend: managed (real-binary) hosts riding the TPU data plane.

This is BASELINE.json's literal design — "keep syscall emulation on host
CPU, offload the per-round packet-scheduling hot path" — applied to this
framework's engines: hosts whose processes are real managed binaries (or
any host-only app) execute on the host CPU exactly as in
:class:`~shadow_tpu.backend.cpu_engine.CpuEngine`, while the network data
plane — per-lane arrival queues, latency/loss lookup, token buckets,
CoDel, and every lane-model host — runs on the device
(:mod:`~shadow_tpu.backend.lanes`).  The seam mirrors the reference's
``Worker::send_packet`` offload target (worker.rs:330-404):

- a managed host's **send** runs the source half of the packet lifecycle
  host-side (up bucket, pcap, loss draw — identical law to
  ``CpuEngine.send_packet``) and stages the PACKET arrival event for
  device injection (``lanes._inject_merge``), with the payload bytes
  parked host-side keyed by ``(src, seq)``;
- the device advances windows over ALL lanes; deliveries destined to
  external lanes exit through the egress buffer at their exact
  ``t_deliver`` (down bucket + CoDel applied on device — the dst half of
  the lifecycle) and are queued host-side as DELIVERY events carrying the
  parked payload;
- the window law stays global and bit-identical to the scalar oracle:
  the device folds the host side's next event time into every window
  start (``lanes._build_hybrid_run``), free-runs windows the host has no
  events in, and returns after completing any window the host
  participates in — one device call per host sync instead of per round.

Event logs diff bit-identical against ``CpuEngine`` on the same config
(tests/test_hybrid.py), which is the determinism contract the reference's
determinism suite checks (src/test/determinism/).
"""

from __future__ import annotations

import time as wall_time
from typing import Optional

import jax
import numpy as np

from ..config.options import ConfigOptions
from ..core import time as stime
from ..core.event import Event, EventKind
from ..core.event_queue import EventQueue
from . import lanes
from .cpu_engine import DELIVERED, CpuEngine, Delivery, Host, SimResult

NEVER = stime.NEVER


def config_has_managed(cfg: ConfigOptions) -> bool:
    """True when any process path is not a registered built-in model —
    i.e. a real binary that must execute host-side under the shim."""
    from ..models.base import _REGISTRY

    return any(
        p.path not in _REGISTRY for h in cfg.hosts for p in h.processes
    )


class HybridEngine(CpuEngine):
    """CpuEngine for the external (managed) hosts; TPU lanes for the rest.

    Construction reuses ``CpuEngine.__init__`` wholesale (hosts, apps,
    pcap, hosts file, routing — one source of truth), then strips the
    lane-covered hosts' host-side state and builds the device engine with
    those hosts marked external."""

    def __init__(
        self, cfg: ConfigOptions, log_capacity: Optional[int] = None
    ) -> None:
        super().__init__(cfg)
        from ..native.process import ManagedApp
        from .tpu_engine import LaneCompatError, TpuEngine

        ext = np.array(
            [any(isinstance(a, ManagedApp) for a in h.apps) for h in self.hosts],
            dtype=bool,
        )
        if not ext.any():
            raise LaneCompatError(
                "no managed hosts in config; use the plain tpu backend"
            )
        self.external_mask = ext
        self.external_hosts: list[Host] = [
            h for h, e in zip(self.hosts, ext) if e
        ]
        for h, e in zip(self.hosts, ext):
            if e:
                h.staged = []  # sends awaiting device injection
            else:
                # lane-covered: the device runs this host; drop its
                # host-side apps, start events, and pcap writer (the
                # device log reconstructs lane pcaps at collect)
                h.apps = []
                h.queue = EventQueue()
                h.pcap = None
        self.device = TpuEngine(
            cfg, log_capacity=log_capacity, external=ext, world=self.world
        )
        # parked payloads for in-flight packets, keyed (src_host, seq) —
        # popped when the device egresses the delivery
        self._parked: dict = {}
        self._staged_merged: list = []
        self._dev_min_used: Optional[int] = None
        self.host_rounds = 0

    # -- host-side packet source half (the law IS CpuEngine's) -------------

    def send_packet(self, src_host, dst, size_bytes, payload=None,
                    loopback=False):
        """The shared source half (``CpuEngine._packet_source_half``: up
        bucket, outbound pcap, dynamic-runahead record, Bernoulli loss)
        with a device-injection sink: the surviving packet is STAGED for
        the device instead of pushed into a host queue — the dst half
        (down bucket, CoDel, delivery) runs on the device for every lane,
        external ones included.  Loopback traffic never touches the
        device: the lo interface is host-local by definition."""
        if loopback:
            return self._loopback_send(src_host, size_bytes, payload)
        seq, arr = self._packet_source_half(src_host, dst, size_bytes, payload)
        if arr is None:
            return seq
        s = src_host.host_id
        if payload is not None:
            self._parked[(s, seq)] = payload
        src_host.staged.append((arr, s, seq, size_bytes, dst))
        return seq

    def inbound(self, dst_host, ev):  # pragma: no cover - defensive
        raise AssertionError(
            "hybrid host queues never hold PACKET events (the device owns "
            "the dst half of the lifecycle)"
        )

    # -- barrier (external hosts only; lane hosts have no host state) ------

    def next_event_time(self) -> int:
        return min(
            (h.queue.next_time() for h in self.external_hosts), default=NEVER
        )

    def _barrier_merge(self) -> None:
        staged = self._staged_merged
        for h in self.external_hosts:
            if h.staged:
                staged.extend(h.staged)
                h.staged = []
            if h.log_buf:
                self.event_log.extend(h.log_buf)
                h.log_buf.clear()
            if h.min_used_lat is not None:
                if self._min_used_lat is None or h.min_used_lat < self._min_used_lat:
                    self._min_used_lat = h.min_used_lat
                h.min_used_lat = None

    def current_runahead(self) -> int:
        """The global dynamic-runahead law: min over BOTH sides' smallest
        used latency (the device scalar is read back after every device
        turn; between turns it cannot change)."""
        if not self.dynamic_runahead:
            return self.runahead
        vals = [
            v for v in (self._min_used_lat, self._dev_min_used)
            if v is not None
        ]
        if not vals:
            return self.runahead
        return max(min(vals), self._runahead_floor, 1)

    # -- egress application -------------------------------------------------

    def _apply_egress(self, rows) -> None:
        """Queue device-egressed deliveries as host-side DELIVERY events
        at their exact t_deliver (down bucket + CoDel already applied on
        device; the DELIVERED/DROP_CODEL log records live in the device
        log).  Mirrors the oracle's passive-delivery elision: an external
        host whose apps are all passive consumes the delivery inline."""
        for t, src, dst, seq, size, outcome in rows:
            t, src, dst, seq, size = int(t), int(src), int(dst), int(seq), int(size)
            h = self.hosts[dst]
            payload = self._parked.pop((src, seq), None)
            if int(outcome) != DELIVERED:
                continue  # device-side drop: payload released, no event
            if h.pcap is not None:  # inbound capture at delivery
                h.pcap.capture(
                    stime.sim_to_emu(t), self.ips.by_host[src],
                    self.ips.by_host[dst], size, payload,
                    key=(0, src, dst, seq),
                )
            if payload is None and h.passive_delivery:
                h.now = t
                for app in h.apps:
                    h._current_app = app
                    app.on_delivery(h, t, src, seq, size, payload=None)
                continue
            h.queue.push(
                Event(
                    t, EventKind.DELIVERY, src_host=src, seq=seq,
                    data=Delivery(src, seq, size, payload),
                )
            )

    # -- device turn --------------------------------------------------------

    def _inj_block(self, staged, b: int):
        """Pack staged sends into the fixed-size injection block."""
        import jax.numpy as jnp

        valid = np.zeros(b, dtype=bool)
        dst = np.zeros(b, dtype=np.int32)
        thi = np.full(b, lanes.NEVER32, dtype=np.int32)
        tlo = np.full(b, lanes.NEVER32, dtype=np.int32)
        auxh = np.zeros(b, dtype=np.int32)
        auxl = np.zeros(b, dtype=np.int32)
        size = np.zeros(b, dtype=np.int32)
        for i, (arr, src, seq, sz, d) in enumerate(staged):
            valid[i] = True
            dst[i] = d
            thi[i] = arr >> 31
            tlo[i] = arr & lanes.MASK31
            auxh[i] = (lanes.PACKET << lanes.AUX_KIND_SHIFT) | (
                src << lanes.AUX_SRC_SHIFT
            )
            auxl[i] = seq
            size[i] = sz
        return {
            "valid": jnp.asarray(valid), "dst": jnp.asarray(dst),
            "thi": jnp.asarray(thi), "tlo": jnp.asarray(tlo),
            "auxh": jnp.asarray(auxh), "auxl": jnp.asarray(auxl),
            "size": jnp.asarray(size),
        }

    def _read_egress(self, state) -> list:
        count = int(state.egress_count)
        if int(state.egress_lost):
            raise RuntimeError(
                "hybrid egress buffer overflowed despite the headroom "
                "guard (device invariant violation)"
            )
        if count == 0:
            return []
        # pad the slice length to a power of two: distinct slice sizes
        # compile distinct device programs, so this caps churn at log2(E)
        cap = self.device.params.egress_capacity
        span = 1
        while span < count:
            span <<= 1
        span = min(span, cap)
        return np.asarray(state.egress[:span])[:count].tolist()

    def _device_turn(self, state, hybrid_fn, inject_fn, host_next):
        """Inject staged sends, run the device free-run loop, and apply
        egress — retrying while the device paused mid-window to drain a
        low egress buffer."""
        p = self.device.params
        b = p.inject_batch
        staged = self._staged_merged
        self._staged_merged = []
        while len(staged) > b:
            state = inject_fn(state, self._inj_block(staged[:b], b))
            staged = staged[b:]
        inj = self._inj_block(staged, b)
        ext_used = (
            lanes.NEVER32 if self._min_used_lat is None else self._min_used_lat
        )
        while True:
            eh, el = (
                (lanes.NEVER32, lanes.NEVER32)
                if host_next >= NEVER
                else (host_next >> 31, host_next & lanes.MASK31)
            )
            state, lane_min = hybrid_fn(state, eh, el, ext_used, inj)
            state = jax.block_until_ready(state)
            lane_min = int(lane_min)
            we_hi, we_lo, dev_used = jax.device_get(
                (state.now_we_hi, state.now_we_lo, state.min_used_lat)
            )
            dev_we = (int(we_hi) << 31) | int(we_lo)
            self._dev_min_used = (
                None if int(dev_used) >= lanes.NEVER32 else int(dev_used)
            )
            self._apply_egress(self._read_egress(state))
            if lane_min >= dev_we:
                return state, lane_min, dev_we
            # mid-window pause (egress headroom): drain and resume
            inj = self._inj_block([], b)
            host_next = self.next_event_time()

    # -- the hybrid round loop ----------------------------------------------

    def run(self, on_window=None) -> SimResult:
        from ..engine.scheduler import HostScheduler

        exp = self.cfg.experimental
        scheduler = HostScheduler(
            self.external_hosts,
            parallelism=self.cfg.general.parallelism,
            policy=exp.scheduler,
            pin_cpus=exp.use_cpu_pinning,
        )
        try:
            return self._run_hybrid(scheduler, on_window)
        finally:
            scheduler.shutdown()

    def _run_hybrid(self, scheduler, on_window) -> SimResult:
        t0 = wall_time.perf_counter()
        try:
            return self._hybrid_loop(scheduler, on_window, t0)
        except BaseException:
            self.finalize()
            raise

    def _hybrid_loop(self, scheduler, on_window, t0) -> SimResult:
        dev = self.device
        state = dev.initial_state()
        hybrid_fn = lanes.make_hybrid_fn(dev.params, dev.tables)
        inject_fn = lanes.make_inject_fn(dev.params, dev.tables)
        dev_next = min(
            (t for (_lane, t, *_rest) in dev._init_events), default=NEVER
        )
        while True:
            host_next = self.next_event_time()
            staged_min = min(
                (e[0] for e in self._staged_merged), default=NEVER
            )
            dev_eff = min(dev_next, staged_min)
            start = min(host_next, dev_eff)
            if start >= self.stop_time or start == NEVER:
                break
            end = min(start + self.current_runahead(), self.stop_time)
            if self._staged_merged or dev_eff < end:
                # device turn: complete every window up to (and including)
                # the first one the host participates in
                state, dev_next, dev_we = self._device_turn(
                    state, hybrid_fn, inject_fn, host_next
                )
                next_host = self.next_event_time()
                if next_host < dev_we:
                    # host part of the device-completed window
                    self.window_end = dev_we
                    scheduler.run_round(dev_we)
                    self._barrier_merge()
                    if on_window is not None:
                        on_window(start, dev_we, self.next_event_time())
                continue
            # host-only window (device idle beyond it, nothing staged)
            self.window_end = end
            scheduler.run_round(end)
            self._barrier_merge()
            self.host_rounds += 1
            if on_window is not None:
                on_window(start, end, self.next_event_time())
        self.finalize()
        wall = wall_time.perf_counter() - t0

        dev_result = self.device.collect(state, wall)
        counters: dict[str, int] = dict(dev_result.counters)
        for h in self.hosts:
            for k, v in h.counters.items():
                counters[k] = counters.get(k, 0) + v
        return SimResult(
            sim_time_ns=self.stop_time,
            wall_seconds=wall,
            rounds=dev_result.rounds + self.host_rounds,
            event_log=dev_result.event_log + self.event_log,
            counters=counters,
            per_host_counters=[dict(h.counters) for h in self.hosts],
            process_errors=list(getattr(self, "process_errors", [])),
        )
