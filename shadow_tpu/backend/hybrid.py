"""Hybrid backend: managed (real-binary) hosts riding the TPU data plane.

This is BASELINE.json's literal design — "keep syscall emulation on host
CPU, offload the per-round packet-scheduling hot path" — applied to this
framework's engines: hosts whose processes are real managed binaries (or
any host-only app) execute on the host CPU exactly as in
:class:`~shadow_tpu.backend.cpu_engine.CpuEngine`, while the network data
plane — per-lane arrival queues, latency/loss lookup, token buckets,
CoDel, and every lane-model host — runs on the device
(:mod:`~shadow_tpu.backend.lanes`).  The seam mirrors the reference's
``Worker::send_packet`` offload target (worker.rs:330-404):

- a managed host's **send** runs the source half of the packet lifecycle
  host-side (up bucket, pcap, loss draw — identical law to
  ``CpuEngine.send_packet``) and stages the PACKET arrival event for
  device injection (``lanes._inject_merge``), with the payload bytes
  parked host-side keyed by ``(src, seq)``;
- the device advances windows over ALL lanes; deliveries destined to
  external lanes exit through the egress buffer at their exact
  ``t_deliver`` (down bucket + CoDel applied on device — the dst half of
  the lifecycle) and are queued host-side as DELIVERY events carrying the
  parked payload;
- the window law stays global and bit-identical to the scalar oracle:
  the device folds the host side's next event time into every window
  start (``lanes._build_hybrid_run``), free-runs windows the host has no
  events in, and returns after completing any window the host
  participates in — one device call per host sync instead of per round.

Two engines drive that seam:

- :class:`HybridEngine` — the serial driver: one process services every
  managed host's syscall plane (threads only help managed futex waits);
- :class:`MpHybridEngine` — PARALLEL syscall servicing: N spawned worker
  processes each own a partition of the external hosts (the analog of the
  reference's thread-per-core syscall workers, thread_per_core.rs:17-50,
  which its 6.38x headline used at parallelism 16) and run their syscall
  plane concurrently, while the parent owns the device and the window
  law.  Staged sends and egressed deliveries ride the worker pipes at
  round barriers, so the host<->device boundary stays one injection
  block + one egress drain per device turn regardless of worker count.

Event ordering is worker-count-invariant by construction: event queues
order by the total (time, kind, src, seq) key, injection decomposition is
order-invariant (the device queue merge sorts on the full key), and logs
and counters merge at barriers in deterministic (worker-id, host-id)
order.  Event logs diff bit-identical against ``CpuEngine`` on the same
config at any worker count (tests/test_hybrid.py, tests/test_hybrid_mp.py)
— the determinism contract the reference's determinism suite checks
(src/test/determinism/).

The host<->device sync-cost accounting (``sync_stats``: per-turn transfer
counts/bytes, blocking device-sync seconds, syscall-service seconds) is
always on — the counters are a handful of Python ints per window — and is
surfaced per window through the perf-log plumbing when
``experimental.perf_logging`` is set (docs/hybrid.md).
"""

from __future__ import annotations

import os
import time as wall_time
from typing import Optional

import jax
import numpy as np

from ..config.options import ConfigOptions
from ..core import time as stime
from ..core.event import Event, EventKind
from ..core.event_queue import EventQueue
from . import lanes
from .cpu_engine import DELIVERED, CpuEngine, Delivery, Host, SimResult

NEVER = stime.NEVER


def config_has_managed(cfg: ConfigOptions) -> bool:
    """True when any process path is not a registered built-in model —
    i.e. a real binary that must execute host-side under the shim."""
    from ..models.base import _REGISTRY

    return any(
        p.path not in _REGISTRY for h in cfg.hosts for p in h.processes
    )


class _HostSideHybrid(CpuEngine):
    """The host-side half of the hybrid seam, shared by the serial engine
    and the multiprocess syscall workers: external-host bookkeeping, the
    staging send sink, and the delivery-application law.  Construction
    reuses ``CpuEngine.__init__`` wholesale (hosts, apps, pcap, hosts
    file, routing — one source of truth); ``_hybrid_host_init`` then
    strips the lane-covered hosts' host-side state."""

    def _hybrid_host_init(self) -> None:
        from ..native.process import ManagedApp
        from .tpu_engine import LaneCompatError

        ext = np.array(
            [any(isinstance(a, ManagedApp) for a in h.apps) for h in self.hosts],
            dtype=bool,
        )
        if not ext.any():
            raise LaneCompatError(
                "no managed hosts in config; use the plain tpu backend"
            )
        self.external_mask = ext
        self.external_hosts: list[Host] = [
            h for h, e in zip(self.hosts, ext) if e
        ]
        for h, e in zip(self.hosts, ext):
            if e:
                h.staged = []  # sends awaiting device injection
            else:
                # lane-covered: the device runs this host; drop its
                # host-side apps, start events, and pcap writer (the
                # device log reconstructs lane pcaps at collect)
                h.apps = []
                h.queue = EventQueue()
                h.pcap = None
        # hosts whose queues feed next_event_time() and whose buffers the
        # barrier sweeps: every external host for the serial engine; a
        # worker narrows this to its owned partition
        self._next_hosts: list[Host] = self.external_hosts
        self._staged_merged: list = []
        self.host_rounds = 0

    # -- host-side packet source half (the law IS CpuEngine's) -------------

    def send_packet(self, src_host, dst, size_bytes, payload=None,
                    loopback=False):
        """The shared source half (``CpuEngine._packet_source_half``: up
        bucket, outbound pcap, dynamic-runahead record, Bernoulli loss)
        with a device-injection sink: the surviving packet is STAGED for
        the device instead of pushed into a host queue — the dst half
        (down bucket, CoDel, delivery) runs on the device for every lane,
        external ones included.  Loopback traffic never touches the
        device: the lo interface is host-local by definition."""
        if loopback:
            return self._loopback_send(src_host, size_bytes, payload)
        seq, arr = self._packet_source_half(src_host, dst, size_bytes, payload)
        if arr is None:
            return seq
        src_host.staged.append(
            (arr, src_host.host_id, seq, size_bytes, dst, payload)
        )
        return seq

    def inbound(self, dst_host, ev):  # pragma: no cover - defensive
        raise AssertionError(
            "hybrid host queues never hold PACKET events (the device owns "
            "the dst half of the lifecycle)"
        )

    # -- barrier (external hosts only; lane hosts have no host state) ------

    def next_event_time(self) -> int:
        return min(
            (h.queue.next_time() for h in self._next_hosts), default=NEVER
        )

    def _barrier_merge(self) -> None:
        staged = self._staged_merged
        for h in self._next_hosts:
            if h.staged:
                staged.extend(h.staged)
                h.staged = []
            if h.log_buf:
                self.event_log.extend(h.log_buf)
                h.log_buf.clear()
            if h.min_used_lat is not None:
                if self._min_used_lat is None or h.min_used_lat < self._min_used_lat:
                    self._min_used_lat = h.min_used_lat
                h.min_used_lat = None

    # -- delivery application ----------------------------------------------

    def _apply_delivery_row(self, t, src, dst, seq, size, payload) -> None:
        """Queue one device-egressed delivery as a host-side DELIVERY
        event at its exact t_deliver (down bucket + CoDel already applied
        on device; the DELIVERED/DROP_CODEL log records live in the
        device log).  Mirrors the oracle's passive-delivery elision: an
        external host whose apps are all passive consumes the delivery
        inline."""
        h = self.hosts[dst]
        if h.pcap is not None:  # inbound capture at delivery
            h.pcap.capture(
                stime.sim_to_emu(t), self.ips.by_host[src],
                self.ips.by_host[dst], size, payload,
                key=(0, src, dst, seq),
            )
        if payload is None and h.passive_delivery:
            h.now = t
            for app in h.apps:
                h._current_app = app
                app.on_delivery(h, t, src, seq, size, payload=None)
            return
        h.queue.push(
            Event(
                t, EventKind.DELIVERY, src_host=src, seq=seq,
                data=Delivery(src, seq, size, payload),
            )
        )


class _HybridWorker(_HostSideHybrid):
    """A syscall-servicing worker's world replica: the host-side hybrid
    half restricted to an owned partition of the external hosts.  Spawned
    by :class:`MpHybridEngine`; deterministic construction makes every
    replica identical, and a managed OS process launches only when its
    host's start task executes — which happens in exactly one worker."""

    def __init__(self, cfg: ConfigOptions, owned: list[int]) -> None:
        super().__init__(cfg)
        self._hybrid_host_init()
        owned_set = set(owned)
        self.owned_hosts = [
            h for h in self.external_hosts if h.host_id in owned_set
        ]
        self._next_hosts = self.owned_hosts


def _hybrid_worker_main(
    cfg: ConfigOptions, owned: list[int], record_turns: bool, conn
) -> None:
    """Worker loop: apply shipped deliveries, execute the owned hosts'
    window (syscall servicing — the parallel hot path), sweep staged
    sends back to the parent.  Protocol mirrors cpu_mp._worker_main.
    Perf-log lines buffer locally and ride the round reply to the
    parent's locked sink (one coherent stream per run).  When the
    device-turn ledger is on, the reply also carries the owned hosts
    participating in this window (events < window_end, taken after the
    shipped deliveries land and before execution — the identical law the
    serial engine applies, so the parent's ledger is worker-count
    invariant)."""
    engine = _HybridWorker(cfg, owned)
    if cfg.experimental.perf_logging:
        from ..engine.run_control import BufferedPerfLog

        engine.perf_log = BufferedPerfLog()
    finished = False
    try:
        while True:
            msg = conn.recv()
            if msg[0] == "round":
                _, window_end, rows = msg
                engine.window_end = window_end
                for t, src, dst, seq, size, payload in rows:
                    engine._apply_delivery_row(t, src, dst, seq, size, payload)
                wparts = ()
                if record_turns:
                    wparts = tuple(
                        h.host_id for h in engine.owned_hosts
                        if h.queue.next_time() < window_end
                    )
                for h in engine.owned_hosts:
                    h.execute(window_end)
                engine._barrier_merge()
                staged = engine._staged_merged
                engine._staged_merged = []
                conn.send((
                    engine.next_event_time(),
                    staged,
                    engine._min_used_lat,
                    engine.perf_log.drain()
                    if engine.perf_log is not None else (),
                    wparts,
                ))
            elif msg[0] == "finish":
                engine.finalize()
                finished = True
                counters: dict[str, int] = {}
                for h in engine.owned_hosts:
                    for k, v in h.counters.items():
                        counters[k] = counters.get(k, 0) + v
                conn.send((
                    engine.event_log,
                    counters,
                    {h.host_id: dict(h.counters) for h in engine.owned_hosts},
                    list(getattr(engine, "process_errors", [])),
                    # netobs host-side arrays (owned hosts only executed)
                    engine.netobs_snapshot(),
                ))
                return
            else:  # pragma: no cover - protocol error
                return
    finally:
        if not finished:
            # abnormal teardown (parent died / raised): still reap the
            # managed OS processes this worker launched — no orphans
            try:
                engine.finalize()
            except Exception:
                pass
        conn.close()


class HybridEngine(_HostSideHybrid):
    """CpuEngine for the external (managed) hosts; TPU lanes for the rest.

    Owns the device, the window law, and the batched host<->device
    boundary: one injection block in, one packed scalar vector + one
    egress drain out per device turn (``sync_stats`` records the exact
    transfer counts/bytes)."""

    def __init__(
        self, cfg: ConfigOptions, log_capacity: Optional[int] = None
    ) -> None:
        super().__init__(cfg)
        from .tpu_engine import TpuEngine

        self._hybrid_host_init()
        self.device = TpuEngine(
            cfg, log_capacity=log_capacity, external=self.external_mask,
            world=self.world,
        )
        # parked payloads for in-flight packets, keyed (src_host, seq) —
        # popped when the device egresses the delivery
        self._parked: dict = {}
        self._dev_min_used: Optional[int] = None
        # reused host-side injection staging buffers (allocated once) and
        # the cached device-resident empty block: turns that stage nothing
        # (mid-window egress-drain retries) transfer nothing
        self._inj_np = None
        self._empty_inj = None
        # host<->device sync-cost accounting (docs/hybrid.md): cheap
        # Python counters, always on; perf_logging surfaces them per
        # window through PerfLog.hybrid_agg
        self.sync_stats: dict = {
            "device_turns": 0,      # hybrid_fn calls (windows batched per)
            "device_sync_s": 0.0,   # blocking scalar-readback wall time
            "syscall_service_s": 0.0,  # host-side window execution wall
            "scalar_reads": 0,      # D2H transfers: packed scalar vectors
            "inject_blocks": 0,     # H2D transfers: injection blocks
            "inject_rows": 0,       # staged sends carried by those blocks
            "inject_bytes": 0,      # H2D bytes (7 arrays x B rows)
            "egress_reads": 0,      # D2H transfers: egress buffer slices
            "egress_rows": 0,       # delivery rows carried by those reads
            "egress_bytes": 0,      # D2H bytes (padded [span, 6] int64)
        }
        # device-turn ledger plumbing (obs/turns.py; all inert when
        # obs/turns are off): per-turn dispatch records buffered between
        # _device_turn and the window law, the round's participant set,
        # and the pending syscall_service->device_turn trace-flow anchor
        self._ledger_dispatches = None
        self._last_participants: tuple = ()
        self._flow_pending = None
        self._flow_seq = 0

    # -- dynamic runahead ---------------------------------------------------

    def current_runahead(self) -> int:
        """The global dynamic-runahead law: min over BOTH sides' smallest
        used latency (the device scalar is read back after every device
        turn; between turns it cannot change)."""
        if not self.dynamic_runahead:
            return self.runahead
        vals = [
            v for v in (self._min_used_lat, self._dev_min_used)
            if v is not None
        ]
        if not vals:
            return self.runahead
        return max(min(vals), self._runahead_floor, 1)

    # -- egress application -------------------------------------------------

    def _apply_egress(self, rows) -> None:
        for t, src, dst, seq, size, outcome in rows:
            t, src, dst, seq, size = int(t), int(src), int(dst), int(seq), int(size)
            payload = self._parked.pop((src, seq), None)
            if int(outcome) != DELIVERED:
                continue  # device-side drop: payload released, no event
            self._route_delivery(t, src, dst, seq, size, payload)

    def _route_delivery(self, t, src, dst, seq, size, payload) -> None:
        self._apply_delivery_row(t, src, dst, seq, size, payload)

    # -- device turn --------------------------------------------------------

    def _inj_block(self, staged, b: int):
        """Pack staged sends into the fixed-size injection block, reusing
        the host-side staging arrays across turns (one H2D transfer per
        block; payloads are parked here, keyed (src, seq))."""
        import jax.numpy as jnp

        if self._inj_np is None:
            self._inj_np = {
                "valid": np.zeros(b, dtype=bool),
                "dst": np.zeros(b, dtype=np.int32),
                "thi": np.full(b, lanes.NEVER32, dtype=np.int32),
                "tlo": np.full(b, lanes.NEVER32, dtype=np.int32),
                "auxh": np.zeros(b, dtype=np.int32),
                "auxl": np.zeros(b, dtype=np.int32),
                "size": np.zeros(b, dtype=np.int32),
            }
        buf = self._inj_np
        buf["valid"][:] = False
        buf["thi"][:] = lanes.NEVER32
        buf["tlo"][:] = lanes.NEVER32
        for i, (arr, src, seq, sz, d, payload) in enumerate(staged):
            if payload is not None:
                self._parked[(src, seq)] = payload
            buf["valid"][i] = True
            buf["dst"][i] = d
            buf["thi"][i] = arr >> 31
            buf["tlo"][i] = arr & lanes.MASK31
            buf["auxh"][i] = (lanes.PACKET << lanes.AUX_KIND_SHIFT) | (
                src << lanes.AUX_SRC_SHIFT
            )
            buf["auxl"][i] = seq
            buf["size"][i] = sz
        st = self.sync_stats
        st["inject_blocks"] += 1
        st["inject_rows"] += len(staged)
        st["inject_bytes"] += b * (1 + 6 * 4)
        # jnp.array COPIES (asarray may zero-copy-alias the numpy buffer
        # on the CPU backend, and the overflow path repacks these same
        # buffers while the previous block's dispatch is still in flight)
        return {k: jnp.array(v) for k, v in buf.items()}

    def _empty_block(self):
        """The no-op injection block, built on device ONCE: egress-drain
        retries and zero-staged turns re-use it without any H2D hop."""
        if self._empty_inj is None:
            import jax.numpy as jnp

            b = self.device.params.inject_batch
            self._empty_inj = {
                "valid": jnp.zeros(b, dtype=bool),
                "dst": jnp.zeros(b, dtype=jnp.int32),
                "thi": jnp.full(b, lanes.NEVER32, dtype=jnp.int32),
                "tlo": jnp.full(b, lanes.NEVER32, dtype=jnp.int32),
                "auxh": jnp.zeros(b, dtype=jnp.int32),
                "auxl": jnp.zeros(b, dtype=jnp.int32),
                "size": jnp.zeros(b, dtype=jnp.int32),
            }
        return self._empty_inj

    def _read_egress(self, state, count: int, lost: int) -> list:
        if lost:
            raise RuntimeError(
                "hybrid egress buffer overflowed despite the headroom "
                "guard (device invariant violation)"
            )
        if count == 0:
            return []
        # pad the slice length to a power of two: distinct slice sizes
        # compile distinct device programs, so this caps churn at log2(E)
        cap = self.device.params.egress_capacity
        span = 1
        while span < count:
            span <<= 1
        span = min(span, cap)
        st = self.sync_stats
        st["egress_reads"] += 1
        st["egress_rows"] += count
        st["egress_bytes"] += span * 6 * 8
        return np.asarray(state.egress[:span])[:count].tolist()

    def _device_turn(self, state, hybrid_fn, inject_fn, next_host_fn):
        """Inject staged sends, run the device free-run loop, and apply
        egress — retrying while the device paused mid-window to drain a
        low egress buffer.  Per completed turn the boundary costs exactly
        one injection block H2D (zero when nothing staged), one packed
        scalar D2H, and one egress slice D2H (zero when nothing
        egressed).

        When the device-turn ledger is on (obs.turns), every dispatch is
        buffered as ``(dev_we, inject_rows, egress_rows, is_retry)`` for
        the window law to record with its cause — derived purely from
        values this loop reads anyway, zero extra transfers."""
        p = self.device.params
        b = p.inject_batch
        st = self.sync_stats
        obs = self.obs
        turns = obs.turns if obs is not None else None
        dispatches = [] if turns is not None else None
        staged = self._staged_merged
        self._staged_merged = []
        # oversized staging: overflow blocks dispatch eagerly — JAX's
        # async dispatch overlaps their H2D + queue merge with the
        # host-side packing of the next block.  The injection span covers
        # packing + dispatch; the transfer itself overlaps the device call
        t_inj = wall_time.perf_counter() if obs is not None else 0.0
        n_staged = len(staged)
        while len(staged) > b:
            state = inject_fn(state, self._inj_block(staged[:b], b))
            staged = staged[b:]
        inj = self._inj_block(staged, b) if staged else self._empty_block()
        if obs is not None and n_staged:
            obs.record(
                "injection", None, t_inj,
                wall_time.perf_counter() - t_inj, rows=n_staged,
            )
        ext_used = (
            lanes.NEVER32 if self._min_used_lat is None else self._min_used_lat
        )
        host_next = next_host_fn()
        first_dispatch = True
        while True:
            eh, el = (
                (lanes.NEVER32, lanes.NEVER32)
                if host_next >= NEVER
                else (host_next >> 31, host_next & lanes.MASK31)
            )
            t0 = wall_time.perf_counter()
            state, scalars = hybrid_fn(state, eh, el, ext_used, inj)
            sc = jax.device_get(scalars)  # the one blocking readback
            t1 = wall_time.perf_counter()
            st["device_sync_s"] += t1 - t0
            st["device_turns"] += 1
            st["scalar_reads"] += 1
            lane_min = int(sc[lanes.HYB_LANE_MIN])
            dev_we = int(sc[lanes.HYB_DEV_WE])
            dev_used = int(sc[lanes.HYB_MIN_USED])
            self._dev_min_used = (
                None if dev_used >= lanes.NEVER32 else dev_used
            )
            if obs is not None:
                obs.record(
                    "device_turn", None, t0, t1 - t0, window_end=dev_we
                )
                obs.metrics.count("device_turns")
                if (
                    first_dispatch
                    and self._flow_pending is not None
                    and turns is not None
                    and obs.tracer is not None
                ):
                    # trace-flow arrow: the syscall-service span that
                    # forced this blocking turn -> the turn's span
                    fid, anchor = self._flow_pending
                    self._flow_pending = None
                    tr = obs.tracer
                    tr.flow("s", fid, "turn_cause", "turn_flow", anchor)
                    tr.flow(
                        "f", fid, "turn_cause", "turn_flow",
                        t0 + (t1 - t0) / 2,
                    )
            egress_count = int(sc[lanes.HYB_EGRESS_COUNT])
            if obs is None or egress_count == 0:
                # empty egress is a no-op read: no span (symmetric with
                # the injection record, and no tracer-capacity burn)
                self._apply_egress(self._read_egress(
                    state, egress_count, int(sc[lanes.HYB_EGRESS_LOST]),
                ))
            else:
                with obs.phase("egress", rows=egress_count):
                    self._apply_egress(self._read_egress(
                        state, egress_count, int(sc[lanes.HYB_EGRESS_LOST]),
                    ))
                obs.metrics.count("egress_rows", egress_count)
            if self.perf_log is not None:
                self.perf_log.hybrid_agg(
                    "device", dev_we, self.sync_stats
                )
            if dispatches is not None:
                dispatches.append((
                    dev_we,
                    n_staged if first_dispatch else 0,
                    egress_count,
                    not first_dispatch,
                ))
            if lane_min >= dev_we:
                if dispatches is not None:
                    self._ledger_dispatches = dispatches
                return state, lane_min, dev_we
            # mid-window pause (egress headroom): drain and resume —
            # the cached empty block keeps the retry transfer-free
            inj = self._empty_block()
            host_next = next_host_fn()
            first_dispatch = False

    # -- device-turn ledger (obs/turns.py) -----------------------------------

    def _record_turn_rows(self, turns, t_start: int, host_in: bool) -> None:
        """Record the buffered dispatches of one completed device turn
        with their causes (docs/observability.md taxonomy): the first
        dispatch carries the turn's primary cause — ``injection`` when it
        carried staged rows, else ``host_window`` when the completed
        window has managed participation, else ``free_run`` — and every
        egress-headroom resumption is its own ``egress_drain`` row.
        Participants attach after the host round (the mp engine learns
        them from the worker replies)."""
        dispatches = self._ledger_dispatches
        self._ledger_dispatches = None
        if not dispatches:  # pragma: no cover - defensive
            return
        for dev_we, inj_rows, egr_rows, is_retry in dispatches:
            if is_retry:
                cause = "egress_drain"
            elif inj_rows:
                cause = "injection"
            elif host_in:
                cause = "host_window"
            else:
                cause = "free_run"
            turns.turn(
                cause, t_start, dev_we,
                inject_rows=inj_rows, egress_rows=egr_rows,
            )

    # -- the hybrid round loop ----------------------------------------------

    def _service_round(self, scheduler, until: int) -> None:
        """One host-side syscall-service round + barrier, timed into
        sync_stats (and per-window through the perf log / obs spans)."""
        t0 = wall_time.perf_counter()
        obs = self.obs
        if obs is not None and obs.turns is not None:
            # the turn ledger's participant set, taken BEFORE execution
            # mutates the queues: managed hosts with events inside the
            # window — the identical law the mp workers apply, so the
            # ledger is bit-identical at any worker count
            self._last_participants = tuple(
                h.host_id for h in self._next_hosts
                if h.queue.next_time() < until
            )
        scheduler.run_round(until)
        self._barrier_merge()
        t1 = wall_time.perf_counter()
        self.sync_stats["syscall_service_s"] += t1 - t0
        if obs is not None:
            obs.record(
                "syscall_service", None, t0, t1 - t0, window_end=until
            )
            if obs.turns is not None and obs.tracer is not None:
                self._flow_seq += 1
                self._flow_pending = (
                    self._flow_seq, t0 + (t1 - t0) / 2,
                )
        if self.perf_log is not None:
            self.perf_log.hybrid_agg("host", until, self.sync_stats)

    def run(self, on_window=None) -> SimResult:
        from ..engine.scheduler import HostScheduler

        exp = self.cfg.experimental
        scheduler = HostScheduler(
            self.external_hosts,
            parallelism=self.cfg.general.parallelism,
            policy=exp.scheduler,
            pin_cpus=exp.use_cpu_pinning,
        )
        try:
            return self._run_hybrid(scheduler, on_window)
        finally:
            scheduler.shutdown()

    def _run_hybrid(self, scheduler, on_window) -> SimResult:
        t0 = wall_time.perf_counter()
        try:
            return self._hybrid_loop(scheduler, on_window, t0)
        except BaseException:
            self.finalize()
            raise

    def _window_loop(self, run_round, on_window):
        """The hybrid window law, shared verbatim by the serial engine
        and the multiprocess controller: only the round executor differs
        (``run_round(until)`` = threaded scheduler round vs worker-pipe
        round).  Returns the final device state for collection."""
        dev = self.device
        state = dev.initial_state()
        hybrid_fn = lanes.make_hybrid_fn(dev.params, dev.tables)
        inject_fn = lanes.make_inject_fn(dev.params, dev.tables)
        dev_next = min(
            (t for (_lane, t, *_rest) in dev._init_events), default=NEVER
        )
        turns = self.obs.turns if self.obs is not None else None
        while True:
            host_next = self.next_event_time()
            staged_min = min(
                (e[0] for e in self._staged_merged), default=NEVER
            )
            dev_eff = min(dev_next, staged_min)
            start = min(host_next, dev_eff)
            if start >= self.stop_time or start == NEVER:
                return state
            end = min(start + self.current_runahead(), self.stop_time)
            if self._staged_merged or dev_eff < end:
                # device turn: complete every window up to (and including)
                # the first one the host participates in
                state, dev_next, dev_we = self._device_turn(
                    state, hybrid_fn, inject_fn, self.next_event_time
                )
                host_in = self.next_event_time() < dev_we
                if turns is not None:
                    self._record_turn_rows(turns, start, host_in)
                if host_in:
                    # host part of the device-completed window
                    self.window_end = dev_we
                    run_round(dev_we)
                    if turns is not None:
                        turns.attach_participants(self._last_participants)
                    if on_window is not None:
                        on_window(start, dev_we, self.next_event_time())
                continue
            # host-only window (device idle beyond it, nothing staged)
            self.window_end = end
            run_round(end)
            if turns is not None:
                turns.host_round()
            self.host_rounds += 1
            if on_window is not None:
                on_window(start, end, self.next_event_time())

    def netobs_snapshot(self):
        """The combined telemetry plane: host-side counters (managed
        hosts' sends, loopback, throttles) summed with the device-side
        counters (every dst half, lane-model hosts' sends).  The window
        histogram is the device's: ALL packet arrivals pop on the lane
        plane on this backend (``inbound`` asserts host queues never
        hold PACKET events), so there is no host-plane arrival
        histogram to report."""
        host = super().netobs_snapshot()
        dev = self.device.netobs_snapshot()
        if host is None or dev is None:
            return None
        from ..obs import netobs as nom

        arrays = nom.merge_arrays(
            {k: v.copy() for k, v in dev["arrays"].items()},
            host["arrays"],
        )
        return {
            "arrays": arrays,
            "window_hist": dev["window_hist"],
            "log_lost": 0,
        }

    def _hybrid_loop(self, scheduler, on_window, t0) -> SimResult:
        state = self._window_loop(
            lambda until: self._service_round(scheduler, until), on_window
        )
        self.finalize()
        wall = wall_time.perf_counter() - t0

        dev_result = self.device.collect(state, wall)
        counters: dict[str, int] = dict(dev_result.counters)
        for h in self.hosts:
            for k, v in h.counters.items():
                counters[k] = counters.get(k, 0) + v
        return SimResult(
            sim_time_ns=self.stop_time,
            wall_seconds=wall,
            rounds=dev_result.rounds + self.host_rounds,
            event_log=dev_result.event_log + self.event_log,
            counters=counters,
            per_host_counters=[dict(h.counters) for h in self.hosts],
            process_errors=list(getattr(self, "process_errors", [])),
        )


class MpHybridEngine(HybridEngine):
    """Hybrid backend with PARALLEL syscall servicing: N spawned worker
    processes own disjoint partitions of the external (managed) hosts and
    execute their syscall plane concurrently (real OS-process parallelism,
    no GIL), while the parent owns the device and the window law.

    The parent is the Controller: it folds the workers' next-event times
    (plus in-flight egressed deliveries), computes every window, ships
    delivery rows to the owners and collects staged sends at each round
    barrier — one pipe message per worker per round, so the host<->device
    boundary stays as batched as the serial engine's.  Determinism is
    worker-count-invariant (see the module docstring); ``workers=1``
    degenerates to the serial engine (no pipe overhead, same results)."""

    def __init__(
        self, cfg: ConfigOptions, workers: int = 0,
        log_capacity: Optional[int] = None,
    ) -> None:
        for hopt in cfg.hosts:
            if hopt.pcap_enabled:
                raise ValueError(
                    "MpHybridEngine does not support pcap capture (every "
                    "worker replica would open the capture files); use "
                    "the serial hybrid engine"
                )
        super().__init__(cfg, log_capacity=log_capacity)
        n_ext = len(self.external_hosts)
        self.workers = workers if workers > 0 else (os.cpu_count() or 1)
        self.workers = max(1, min(self.workers, n_ext))
        self._eff_next: Optional[list[int]] = None
        self._pending_rows: Optional[list[list]] = None
        self._owner_of: dict[int, int] = {}

    # -- controller-side bookkeeping ---------------------------------------

    def next_event_time(self) -> int:
        if self._eff_next is not None:
            return min(self._eff_next, default=NEVER)
        return super().next_event_time()

    def _route_delivery(self, t, src, dst, seq, size, payload) -> None:
        """Ship the delivery to the worker owning ``dst`` at the next
        round message; fold its time into the owner's effective next-event
        time unless the replica consumes it inline (passive elision makes
        no queue event — the parent's replica knows which hosts are
        passive, construction being deterministic)."""
        if self._eff_next is None:
            # workers==1 degenerate run: the serial loop executes hosts
            # in-process, so deliveries apply directly
            super()._route_delivery(t, src, dst, seq, size, payload)
            return
        w = self._owner_of[dst]
        self._pending_rows[w].append((t, src, dst, seq, size, payload))
        if not (payload is None and self.hosts[dst].passive_delivery):
            if t < self._eff_next[w]:
                self._eff_next[w] = t

    def _mp_round(self, window_end: int) -> None:
        """One parallel syscall-service round: ship (window_end, delivery
        rows) to every worker, collect (next_t, staged sends, min-used
        latency) — a single pipe message each way per worker.  Workers
        execute concurrently between the two loops; staged sends merge in
        (worker-id, host-id) order, which the device queue merge's total
        key makes order-invariant anyway."""
        t0 = wall_time.perf_counter()
        obs = self.obs
        conns, _procs = self._mp
        for w, conn in enumerate(conns):
            conn.send(("round", window_end, self._pending_rows[w]))
            self._pending_rows[w] = []
        t_ship = wall_time.perf_counter()
        staged = self._staged_merged
        perf_lines: list[str] = []
        parts_all: list[int] = []
        for w, conn in enumerate(conns):
            next_t, out, mul, wlines, wparts = conn.recv()
            self._eff_next[w] = next_t
            if mul is not None and (
                self._min_used_lat is None or mul < self._min_used_lat
            ):
                self._min_used_lat = mul
            staged.extend(out)
            if wlines:
                perf_lines.extend(wlines)
            if wparts:
                parts_all.extend(wparts)
        t1 = wall_time.perf_counter()
        self.sync_stats["syscall_service_s"] += t1 - t0
        if obs is not None and obs.turns is not None:
            # the partition interleaves host ids round-robin across
            # workers; sorting normalizes the union to the serial
            # engine's host-id order (ledger worker-count invariance)
            self._last_participants = tuple(sorted(parts_all))
            if obs.tracer is not None:
                self._flow_seq += 1
                self._flow_pending = (
                    self._flow_seq, t_ship + (t1 - t_ship) / 2,
                )
        if obs is not None:
            # disjoint attribution (same law as cpu_mp): worker_pipe is
            # the ship leg, syscall_service the collect leg — the barrier
            # wait that IS the workers' syscall execution wall.  The two
            # tile the round exactly, so phase sums never double-count
            # (sync_stats' syscall_service_s keeps covering the whole
            # round, ship included — the legacy [hybrid-agg] counter)
            obs.record("worker_pipe", "pipe_ship", t0, t_ship - t0)
            obs.record(
                "syscall_service", None, t_ship, t1 - t_ship,
                window_end=window_end,
            )
            obs.metrics.count("pipe_messages", 2 * len(conns))
        # worker-process perf lines route through the parent's locked
        # sink, in (round, worker-id) order — one coherent stream
        if perf_lines and self.perf_log is not None:
            self.perf_log.emit_many(perf_lines)
        if self.perf_log is not None:
            self.perf_log.hybrid_agg("host", window_end, self.sync_stats)

    def netobs_snapshot(self):
        """Worker-merged host arrays + device arrays (the window
        histogram is the device's — see HybridEngine.netobs_snapshot)."""
        wnb = getattr(self, "_worker_nb", None)
        if wnb is None:
            # serial / degenerate (workers == 1) path ran in-process
            return super().netobs_snapshot()
        dev = self.device.netobs_snapshot()
        if dev is None:
            return None
        from ..obs import netobs as nom

        arrays = nom.merge_arrays(
            {k: v.copy() for k, v in dev["arrays"].items()}, wnb
        )
        return {
            "arrays": arrays,
            "window_hist": dev["window_hist"],
            "log_lost": 0,
        }

    # -- run ---------------------------------------------------------------

    def run(self, on_window=None) -> SimResult:
        if self.workers == 1:
            # degenerate case (single-core box): spawning one worker only
            # adds pipe overhead — run in-process, same results
            return super().run(on_window=on_window)
        from .cpu_mp import _partition, spawn_cpu_workers

        ext_ids = [h.host_id for h in self.external_hosts]
        parts = [
            [ext_ids[i] for i in p]
            for p in _partition(len(ext_ids), self.workers)
        ]
        self._owner_of = {
            hid: w for w, part in enumerate(parts) for hid in part
        }
        record_turns = self.obs is not None and self.obs.turns is not None
        conns, procs = spawn_cpu_workers(
            _hybrid_worker_main,
            [(self.cfg, owned, record_turns) for owned in parts],
        )
        self._mp = (conns, procs)
        self._pending_rows = [[] for _ in range(self.workers)]
        # initial next-event times from the parent replica (identical
        # deterministic construction — no startup round trip needed)
        self._eff_next = [
            min((self.hosts[i].queue.next_time() for i in owned),
                default=NEVER)
            for owned in parts
        ]
        t0 = wall_time.perf_counter()
        try:
            return self._mp_loop(on_window, t0)
        finally:
            self._eff_next = None
            for conn in conns:
                conn.close()
            for p in procs:
                p.join(timeout=10)
                if p.is_alive():
                    p.terminate()

    def _mp_loop(self, on_window, t0) -> SimResult:
        conns, _procs = self._mp
        state = self._window_loop(self._mp_round, on_window)

        event_log: list = []
        counters: dict[str, int] = {}
        per_host: list[dict] = [{} for _ in range(len(self.hosts))]
        process_errors: list[str] = []
        self._worker_nb = None
        for conn in conns:
            conn.send(("finish",))
        for conn in conns:
            log, cnt, per, errs, wsnap = conn.recv()
            event_log.extend(log)
            for k, v in cnt.items():
                counters[k] = counters.get(k, 0) + v
            for hid, c in per.items():
                per_host[hid] = c
            process_errors.extend(errs)
            if wsnap is not None:
                from ..obs import netobs as nom

                if self._worker_nb is None:
                    self._worker_nb = nom.empty_arrays(len(self.hosts))
                nom.merge_arrays(self._worker_nb, wsnap["arrays"])
        wall = wall_time.perf_counter() - t0

        dev_result = self.device.collect(state, wall)
        for k, v in dev_result.counters.items():
            counters[k] = counters.get(k, 0) + v
        return SimResult(
            sim_time_ns=self.stop_time,
            wall_seconds=wall,
            rounds=dev_result.rounds + self.host_rounds,
            event_log=dev_result.event_log + event_log,
            counters=counters,
            per_host_counters=per_host,
            process_errors=process_errors,
        )
