"""Hybrid backend: managed (real-binary) hosts riding the TPU data plane.

This is BASELINE.json's literal design — "keep syscall emulation on host
CPU, offload the per-round packet-scheduling hot path" — applied to this
framework's engines: hosts whose processes are real managed binaries (or
any host-only app) execute on the host CPU exactly as in
:class:`~shadow_tpu.backend.cpu_engine.CpuEngine`, while the network data
plane — per-lane arrival queues, latency/loss lookup, token buckets,
CoDel, and every lane-model host — runs on the device
(:mod:`~shadow_tpu.backend.lanes`).  The seam mirrors the reference's
``Worker::send_packet`` offload target (worker.rs:330-404):

- a managed host's **send** runs the source half of the packet lifecycle
  host-side (up bucket, pcap, loss draw — identical law to
  ``CpuEngine.send_packet``) and stages the PACKET arrival event for
  device injection (``lanes._inject_merge``), with the payload bytes
  parked host-side keyed by ``(src, seq)``;
- the device advances windows over ALL lanes; deliveries destined to
  external lanes exit through the egress buffer at their exact
  ``t_deliver`` (down bucket + CoDel applied on device — the dst half of
  the lifecycle) and are queued host-side as DELIVERY events carrying the
  parked payload;
- the window law stays global and bit-identical to the scalar oracle:
  the device folds the host side's next event time into every window
  start (``lanes._build_hybrid_run``), free-runs windows the host has no
  events in, and returns after completing any window the host
  participates in — one device call per host sync instead of per round.

Two engines drive that seam:

- :class:`HybridEngine` — the serial driver: one process services every
  managed host's syscall plane (threads only help managed futex waits);
- :class:`MpHybridEngine` — PARALLEL syscall servicing: N spawned worker
  processes each own a partition of the external hosts (the analog of the
  reference's thread-per-core syscall workers, thread_per_core.rs:17-50,
  which its 6.38x headline used at parallelism 16) and run their syscall
  plane concurrently, while the parent owns the device and the window
  law.  Staged sends and egressed deliveries ride the worker pipes at
  round barriers, so the host<->device boundary stays one injection
  block + one egress drain per device turn regardless of worker count.

Event ordering is worker-count-invariant by construction: event queues
order by the total (time, kind, src, seq) key, injection decomposition is
order-invariant (the device queue merge sorts on the full key), and logs
and counters merge at barriers in deterministic (worker-id, host-id)
order.  Event logs diff bit-identical against ``CpuEngine`` on the same
config at any worker count (tests/test_hybrid.py, tests/test_hybrid_mp.py)
— the determinism contract the reference's determinism suite checks
(src/test/determinism/).

The host<->device sync-cost accounting (``sync_stats``: per-turn transfer
counts/bytes, blocking device-sync seconds, syscall-service seconds) is
always on — the counters are a handful of Python ints per window — and is
surfaced per window through the perf-log plumbing when
``experimental.perf_logging`` is set (docs/hybrid.md).
"""

from __future__ import annotations

import logging
import os
import time as wall_time
from typing import Optional

import jax
import numpy as np

from ..config.options import ConfigOptions
from ..core import time as stime
from ..core.event import Event, EventKind
from ..core.event_queue import EventQueue
from ..engine.supervisor import recv_with_deadline, worker_recv
from . import lanes
from .cpu_engine import DELIVERED, CpuEngine, Delivery, Host, SimResult

NEVER = stime.NEVER

log = logging.getLogger("shadow_tpu.hybrid")


def config_has_managed(cfg: ConfigOptions) -> bool:
    """True when any process path is not a registered built-in model —
    i.e. a real binary that must execute host-side under the shim."""
    from ..models.base import _REGISTRY

    return any(
        p.path not in _REGISTRY for h in cfg.hosts for p in h.processes
    )


class _HostSideHybrid(CpuEngine):
    """The host-side half of the hybrid seam, shared by the serial engine
    and the multiprocess syscall workers: external-host bookkeeping, the
    staging send sink, and the delivery-application law.  Construction
    reuses ``CpuEngine.__init__`` wholesale (hosts, apps, pcap, hosts
    file, routing — one source of truth); ``_hybrid_host_init`` then
    strips the lane-covered hosts' host-side state."""

    # -- fused-turn peek/validation primitives (shared with the mp
    # -- syscall workers; docs/hybrid.md "k-window fusion law") -----------

    def _peek_head_horizon(self, slots: int, hosts=None,
                           floor_t: int = 0):
        """The next ``slots - 1`` DISTINCT event times (>= ``floor_t``)
        across this side's hosts (or an explicit host iterable — the mp
        parent uses its replica of a worker's partition), plus the
        horizon — the first time the list does NOT cover (NEVER when
        exhaustive).  The device must never free-run past the horizon:
        an uncovered external event could start a window there.  ONE
        definition shared by the serial dispatch peek, the worker-reply
        peeks, and the parent's initial partition replicas — the
        schedules agreeing across replicas is a determinism
        invariant."""
        if hosts is None:
            hosts = self._next_hosts
        seen = {
            ev.time for h in hosts for ev in h.queue._heap
            if ev.time >= floor_t
        }
        times = sorted(seen)
        head = tuple(times[: slots - 1])
        horizon = times[slots - 1] if len(times) >= slots else NEVER
        return head, horizon

    def _range_count(self, lo: int, hi: int) -> int:
        """Number of queued events with ``lo <= t < hi`` — the covered
        rounds' cleanliness probe: execution only pops events below the
        window end, so a post-round change in this count means the round
        CREATED an event inside the still-covered fused span (a window
        boundary the device could not have known -> rollback).

        Both probes scan the raw heaps — O(total queued events) per
        covered round, a few hundred entries at the measured scales
        (syscall service sits at ~5% of wall; see docs/hybrid.md).  If
        managed hosts ever hold deep timer queues, replace with
        incremental range counters maintained at push/pop."""
        n = 0
        for h in self._next_hosts:
            for ev in h.queue._heap:
                if lo <= ev.time < hi:
                    n += 1
        return n

    def _hybrid_host_init(self) -> None:
        from ..native.process import ManagedApp
        from .tpu_engine import LaneCompatError

        ext = np.array(
            [any(isinstance(a, ManagedApp) for a in h.apps) for h in self.hosts],
            dtype=bool,
        )
        if not ext.any():
            raise LaneCompatError(
                "no managed hosts in config; use the plain tpu backend"
            )
        self.external_mask = ext
        self.external_hosts: list[Host] = [
            h for h, e in zip(self.hosts, ext) if e
        ]
        for h, e in zip(self.hosts, ext):
            if e:
                h.staged = []  # sends awaiting device injection
            else:
                # lane-covered: the device runs this host; drop its
                # host-side apps, start events, and pcap writer (the
                # device log reconstructs lane pcaps at collect)
                h.apps = []
                h.queue = EventQueue()
                h.pcap = None
        # hosts whose queues feed next_event_time() and whose buffers the
        # barrier sweeps: every external host for the serial engine; a
        # worker narrows this to its owned partition
        self._next_hosts: list[Host] = self.external_hosts
        self._staged_merged: list = []
        self.host_rounds = 0

    # -- host-side packet source half (the law IS CpuEngine's) -------------

    def send_packet(self, src_host, dst, size_bytes, payload=None,
                    loopback=False, retx=False):
        """The shared source half (``CpuEngine._packet_source_half``: up
        bucket, outbound pcap, dynamic-runahead record, Bernoulli loss)
        with a device-injection sink: the surviving packet is STAGED for
        the device instead of pushed into a host queue — the dst half
        (down bucket, CoDel, delivery) runs on the device for every lane,
        external ones included.  Loopback traffic never touches the
        device: the lo interface is host-local by definition."""
        if loopback:
            return self._loopback_send(src_host, size_bytes, payload)
        seq, arr = self._packet_source_half(src_host, dst, size_bytes, payload,
                                            retx=retx)
        if arr is None:
            return seq
        src_host.staged.append(
            (arr, src_host.host_id, seq, size_bytes, dst, payload)
        )
        return seq

    def inbound(self, dst_host, ev):  # pragma: no cover - defensive
        raise AssertionError(
            "hybrid host queues never hold PACKET events (the device owns "
            "the dst half of the lifecycle)"
        )

    # -- barrier (external hosts only; lane hosts have no host state) ------

    def next_event_time(self) -> int:
        return min(
            (h.queue.next_time() for h in self._next_hosts), default=NEVER
        )

    def _barrier_merge(self) -> None:
        staged = self._staged_merged
        for h in self._next_hosts:
            if h.staged:
                staged.extend(h.staged)
                h.staged = []
            if h.log_buf:
                self.event_log.extend(h.log_buf)
                h.log_buf.clear()
            if h.min_used_lat is not None:
                if self._min_used_lat is None or h.min_used_lat < self._min_used_lat:
                    self._min_used_lat = h.min_used_lat
                h.min_used_lat = None

    # -- delivery application ----------------------------------------------

    def _apply_delivery_row(self, t, src, dst, seq, size, payload) -> None:
        """Queue one device-egressed delivery as a host-side DELIVERY
        event at its exact t_deliver (down bucket + CoDel already applied
        on device; the DELIVERED/DROP_CODEL log records live in the
        device log).  Mirrors the oracle's passive-delivery elision: an
        external host whose apps are all passive consumes the delivery
        inline."""
        h = self.hosts[dst]
        if h.pcap is not None:  # inbound capture at delivery
            h.pcap.capture(
                stime.sim_to_emu(t), self.ips.by_host[src],
                self.ips.by_host[dst], size, payload,
                key=(0, src, dst, seq),
            )
        if payload is None and h.passive_delivery:
            h.now = t
            for app in h.apps:
                h._current_app = app
                app.on_delivery(h, t, src, seq, size, payload=None)
            return
        h.queue.push(
            Event(
                t, EventKind.DELIVERY, src_host=src, seq=seq,
                data=Delivery(src, seq, size, payload),
            )
        )


class _HybridWorker(_HostSideHybrid):
    """A syscall-servicing worker's world replica: the host-side hybrid
    half restricted to an owned partition of the external hosts.  Spawned
    by :class:`MpHybridEngine`; deterministic construction makes every
    replica identical, and a managed OS process launches only when its
    host's start task executes — which happens in exactly one worker."""

    def __init__(self, cfg: ConfigOptions, owned: list[int]) -> None:
        super().__init__(cfg)
        self._hybrid_host_init()
        owned_set = set(owned)
        self.owned_hosts = [
            h for h in self.external_hosts if h.host_id in owned_set
        ]
        self._next_hosts = self.owned_hosts


def _hybrid_worker_main(
    cfg: ConfigOptions, owned: list[int], record_turns: bool,
    peek_slots: int, conn
) -> None:
    """Worker loop: apply shipped deliveries, execute the owned hosts'
    window (syscall servicing — the parallel hot path), sweep staged
    sends back to the parent.  Protocol mirrors cpu_mp._worker_main.
    Perf-log lines buffer locally and ride the round reply to the
    parent's locked sink (one coherent stream per run).  When the
    device-turn ledger is on, the reply also carries the owned hosts
    participating in this window (events < window_end, taken after the
    shipped deliveries land and before execution — the identical law the
    serial engine applies, so the parent's ledger is worker-count
    invariant).  When k-window fusion is on (``peek_slots > 0``), the
    reply additionally carries the cleanliness flag for the shipped
    validation range (did this round create an event inside the
    still-covered fused span?) and the partition's refreshed peek
    schedule, so the parent can bound the next dispatch's k before any
    further round trip (docs/hybrid.md "k-window fusion law")."""
    engine = _HybridWorker(cfg, owned)
    if cfg.experimental.perf_logging:
        from ..engine.run_control import BufferedPerfLog

        engine.perf_log = BufferedPerfLog()
    finished = False
    try:
        while True:
            # poll-sliced recv: a dead/vanished parent EOFs instead of
            # blocking forever, so the finally below still reaps the
            # managed OS processes this worker launched (no orphans)
            msg = worker_recv(conn)
            if msg[0] == "round":
                _, window_end, rows, we_final = msg
                engine.window_end = window_end
                for t, src, dst, seq, size, payload in rows:
                    engine._apply_delivery_row(t, src, dst, seq, size, payload)
                probe = we_final > window_end
                pre_range = (
                    engine._range_count(window_end, we_final)
                    if probe else 0
                )
                wparts = ()
                if record_turns:
                    wparts = tuple(
                        h.host_id for h in engine.owned_hosts
                        if h.queue.next_time() < window_end
                    )
                for h in engine.owned_hosts:
                    h.execute(window_end)
                engine._barrier_merge()
                clean = (
                    not probe
                    or engine._range_count(window_end, we_final) == pre_range
                )
                staged = engine._staged_merged
                engine._staged_merged = []
                conn.send((
                    engine.next_event_time(),
                    staged,
                    engine._min_used_lat,
                    engine.perf_log.drain()
                    if engine.perf_log is not None else (),
                    wparts,
                    clean,
                    engine._peek_head_horizon(peek_slots)
                    if peek_slots else (),
                ))
            elif msg[0] == "finish":
                engine.finalize()
                finished = True
                counters: dict[str, int] = {}
                for h in engine.owned_hosts:
                    for k, v in h.counters.items():
                        counters[k] = counters.get(k, 0) + v
                conn.send((
                    engine.event_log,
                    counters,
                    {h.host_id: dict(h.counters) for h in engine.owned_hosts},
                    list(getattr(engine, "process_errors", [])),
                    # netobs host-side arrays (owned hosts only executed)
                    engine.netobs_snapshot(),
                    # flowtrace host-side events (each managed send's
                    # source half is emitted by exactly one worker)
                    (
                        engine.flowtrace.raw_events()
                        if engine.flowtrace is not None else None
                    ),
                ))
                return
            else:  # pragma: no cover - protocol error
                return
    except (EOFError, OSError):
        # parent tore the pipe down (normal teardown after an error on
        # its side, or parent death): exit quietly — the finally reaps
        return
    finally:
        if not finished:
            # abnormal teardown (parent died / raised): still reap the
            # managed OS processes this worker launched — no orphans
            try:
                engine.finalize()
            except Exception:
                pass
        conn.close()


class HybridEngine(_HostSideHybrid):
    """CpuEngine for the external (managed) hosts; TPU lanes for the rest.

    Owns the device, the window law, and the batched host<->device
    boundary: one injection block in, one packed scalar vector + one
    egress drain out per device turn (``sync_stats`` records the exact
    transfer counts/bytes)."""

    def __init__(
        self, cfg: ConfigOptions, log_capacity: Optional[int] = None
    ) -> None:
        super().__init__(cfg)
        from .tpu_engine import TpuEngine

        self._hybrid_host_init()
        self.device = TpuEngine(
            cfg, log_capacity=log_capacity, external=self.external_mask,
            world=self.world,
        )
        # multi-chip data plane (parallel/mesh.py): a negotiated mesh
        # shards the lane axis; the window loops then compile the hybrid
        # kernels under it — ≤2 transfers per turn and the sync_stats
        # byte accounting are unchanged (tests/test_multichip.py)
        from .. import parallel

        n_dev = parallel.negotiate_from_config(cfg, len(cfg.hosts))
        if n_dev > 1:
            self.device.attach_mesh(parallel.make_mesh(n_dev))
        # parked payloads for in-flight packets, keyed (src_host, seq) —
        # popped when the device egresses the delivery
        self._parked: dict = {}
        self._dev_min_used: Optional[int] = None
        # reused host-side injection staging buffers (allocated once) and
        # the cached device-resident empty block: turns that stage nothing
        # (mid-window egress-drain retries) transfer nothing
        self._inj_np = None
        self._empty_inj = None
        # host<->device sync-cost accounting (docs/hybrid.md): cheap
        # Python counters, always on; perf_logging surfaces them per
        # window through PerfLog.hybrid_agg
        self.sync_stats: dict = {
            "device_turns": 0,      # hybrid_fn calls (windows batched per)
            "device_sync_s": 0.0,   # blocking scalar-readback wall time
            "syscall_service_s": 0.0,  # host-side window execution wall
            "scalar_reads": 0,      # D2H transfers: packed scalar vectors
            "inject_blocks": 0,     # H2D transfers: injection blocks
            "inject_rows": 0,       # staged sends carried by those blocks
            "inject_bytes": 0,      # H2D bytes (7 arrays x B rows)
            "egress_reads": 0,      # D2H transfers: egress buffer slices
            "egress_rows": 0,       # delivery rows carried by those reads
            "egress_bytes": 0,      # D2H bytes (padded [span, 6] int64)
            # k-window fusion + async dispatch (docs/hybrid.md):
            "fused_dispatches": 0,  # dispatches covering >= 2 validated windows
            "fused_windows": 0,     # validated windows those covered
            "turns_saved": 0,       # blocking dispatches fusion eliminated, net
            "fuse_rollbacks": 0,    # prefix-rebuild dispatches (mispredictions)
            "async_dispatch_hits": 0,    # eager dispatches adopted at the barrier
            "async_dispatch_misses": 0,  # eager dispatches discarded (inputs diverged)
            "dispatch_retries": 0,  # failed fused dispatches re-dispatched
        }
        # k-window free-run fusion knobs (docs/hybrid.md "k-window fusion
        # law"): fuse_k == 1 keeps the PR 7 one-dispatch-per-participating-
        # window law bit-for-bit; >= 2 selects the fused kernel variant.
        exp = cfg.experimental
        # dispatch retry-with-backoff law (docs/robustness.md): a failed
        # fused device dispatch re-dispatches from the pre-turn device
        # checkpoint (purity makes the retry bit-identical) up to this
        # many times before escalating to the watchdog/failover boundary
        self._dispatch_retry_max = max(0, int(exp.dispatch_retry_max))
        # injected backend_stall support (docs/faults.md): the hybrid
        # window loop raises BackendStallError when the sim clock crosses
        # the earliest scheduled stall — the facade's failover boundary
        # then replays on the CPU engine (managed hosts run there
        # natively).  Other fault kinds stay gated off this backend.
        self._stall_after = NEVER
        if cfg.faults.events:
            from .tpu_engine import LaneCompatError

            sched = cfg.faults.schedule()
            stalls = [
                ev.at for ev in sched.events if ev.kind == "backend_stall"
            ]
            if len(stalls) != len(sched.events):
                raise LaneCompatError(
                    "only backend_stall fault events are supported on the "
                    "hybrid tpu backend; use the cpu backend for "
                    "link/host fault schedules"
                )
            if stalls:
                self._stall_after = min(stalls)
        self._fuse_k = max(1, int(exp.hybrid_fuse_k))
        self._fuse_on = self._fuse_k >= 2
        self._async_on = self._fuse_on and bool(exp.hybrid_async_dispatch)
        # peeked-schedule width: enough slots that multi-event windows do
        # not exhaust the schedule mid-span (last slot = the horizon)
        self._ext_slots = max(2 * self._fuse_k, 9)
        self._fuse_we_final = None  # covered-round validation range end
        self._round_clean = True    # set by _service_round/_mp_round
        self._eager = None          # double-buffered speculative dispatch
        # 2-bit saturating adoption predictor for the eager dispatch:
        # issue for real at >= 2, otherwise record a PHANTOM speculation
        # (inputs only, no device work) whose would-have-hit outcome
        # keeps training the predictor — so a cold predictor can re-arm.
        # Purely an efficiency device: adopted results are bit-equal to
        # the blocking dispatch, misses are discarded, so the predictor
        # cannot affect any observable output
        self._eager_pred = 2
        # the provable external lookahead (the Chandy-Misra per-source
        # bound, docs/hybrid.md): the min latency on any edge OUT of a
        # managed host's node.  A send staged while servicing a covered
        # window departs inside that window and cannot arrive earlier
        # than departure + this bound, so a dispatch may cover about
        # L_ext / runahead windows before speculation even begins
        self._ext_min_lat: Optional[int] = None
        if self._fuse_on:
            from ..net.graph import _UNREACHABLE

            idx = self.node_index
            ext_nodes = sorted({idx[h.host_id] for h in self.external_hosts})
            all_nodes = sorted(set(idx.values()))
            lat = self.graph.latency_ns[np.ix_(ext_nodes, all_nodes)]
            ok = lat != _UNREACHABLE
            if ok.any():
                self._ext_min_lat = int(lat[ok].min())
        # device-turn ledger plumbing (obs/turns.py; all inert when
        # obs/turns are off): per-turn dispatch records buffered between
        # _device_turn and the window law, the round's participant set,
        # and the pending syscall_service->device_turn trace-flow anchor
        self._ledger_dispatches = None
        self._last_participants: tuple = ()
        self._flow_pending = None
        self._flow_seq = 0

    # -- dynamic runahead ---------------------------------------------------

    def current_runahead(self) -> int:
        """The global dynamic-runahead law: min over BOTH sides' smallest
        used latency (the device scalar is read back after every device
        turn; between turns it cannot change)."""
        if not self.dynamic_runahead:
            return self.runahead
        vals = [
            v for v in (self._min_used_lat, self._dev_min_used)
            if v is not None
        ]
        if not vals:
            return self.runahead
        return max(min(vals), self._runahead_floor, 1)

    # -- egress application -------------------------------------------------

    def _apply_egress(self, rows) -> None:
        for t, src, dst, seq, size, outcome in rows:
            t, src, dst, seq, size = int(t), int(src), int(dst), int(seq), int(size)
            payload = self._parked.pop((src, seq), None)
            if int(outcome) != DELIVERED:
                continue  # device-side drop: payload released, no event
            self._route_delivery(t, src, dst, seq, size, payload)

    def _route_delivery(self, t, src, dst, seq, size, payload) -> None:
        self._apply_delivery_row(t, src, dst, seq, size, payload)

    # -- device turn --------------------------------------------------------

    def _inj_block(self, staged, b: int):
        """Pack staged sends into the fixed-size injection block, reusing
        the host-side staging arrays across turns (one H2D transfer per
        block; payloads are parked here, keyed (src, seq))."""
        import jax.numpy as jnp

        if self._inj_np is None:
            self._inj_np = {
                "valid": np.zeros(b, dtype=bool),
                "dst": np.zeros(b, dtype=np.int32),
                "thi": np.full(b, lanes.NEVER32, dtype=np.int32),
                "tlo": np.full(b, lanes.NEVER32, dtype=np.int32),
                "auxh": np.zeros(b, dtype=np.int32),
                "auxl": np.zeros(b, dtype=np.int32),
                "size": np.zeros(b, dtype=np.int32),
            }
        buf = self._inj_np
        buf["valid"][:] = False
        buf["thi"][:] = lanes.NEVER32
        buf["tlo"][:] = lanes.NEVER32
        for i, (arr, src, seq, sz, d, payload) in enumerate(staged):
            if payload is not None:
                self._parked[(src, seq)] = payload
            buf["valid"][i] = True
            buf["dst"][i] = d
            buf["thi"][i] = arr >> 31
            buf["tlo"][i] = arr & lanes.MASK31
            buf["auxh"][i] = (lanes.PACKET << lanes.AUX_KIND_SHIFT) | (
                src << lanes.AUX_SRC_SHIFT
            )
            buf["auxl"][i] = seq
            buf["size"][i] = sz
        st = self.sync_stats
        st["inject_blocks"] += 1
        st["inject_rows"] += len(staged)
        st["inject_bytes"] += b * (1 + 6 * 4)
        # jnp.array COPIES (asarray may zero-copy-alias the numpy buffer
        # on the CPU backend, and the overflow path repacks these same
        # buffers while the previous block's dispatch is still in flight)
        return {k: jnp.array(v) for k, v in buf.items()}

    def _empty_block(self):
        """The no-op injection block, built on device ONCE: egress-drain
        retries and zero-staged turns re-use it without any H2D hop."""
        if self._empty_inj is None:
            import jax.numpy as jnp

            b = self.device.params.inject_batch
            self._empty_inj = {
                "valid": jnp.zeros(b, dtype=bool),
                "dst": jnp.zeros(b, dtype=jnp.int32),
                "thi": jnp.full(b, lanes.NEVER32, dtype=jnp.int32),
                "tlo": jnp.full(b, lanes.NEVER32, dtype=jnp.int32),
                "auxh": jnp.zeros(b, dtype=jnp.int32),
                "auxl": jnp.zeros(b, dtype=jnp.int32),
                "size": jnp.zeros(b, dtype=jnp.int32),
            }
        return self._empty_inj

    def _read_egress(self, state, count: int, lost: int) -> list:
        if lost:
            raise RuntimeError(
                "hybrid egress buffer overflowed despite the headroom "
                "guard (device invariant violation)"
            )
        if count == 0:
            return []
        # pad the slice length to a power of two: distinct slice sizes
        # compile distinct device programs, so this caps churn at log2(E)
        cap = self.device.params.egress_capacity
        span = 1
        while span < count:
            span <<= 1
        span = min(span, cap)
        st = self.sync_stats
        st["egress_reads"] += 1
        st["egress_rows"] += count
        st["egress_bytes"] += span * 6 * 8
        return np.asarray(state.egress[:span])[:count].tolist()

    def _read_egress_obs(self, state, count: int, lost: int,
                         apply: bool = False):
        """Egress readback wrapped in the obs ``egress`` span — the span
        covers the D2H read, plus delivery application when ``apply``
        (the unfused law's combined semantics, docs/observability.md);
        the fused walk applies lazily per validated window and passes
        ``apply=False``.  Empty egress is a no-op read with no span
        (symmetric with the injection record, no tracer-capacity
        burn)."""
        obs = self.obs
        if obs is None or count == 0:
            rows = self._read_egress(state, count, lost)
            if apply:
                self._apply_egress(rows)
            return rows
        with obs.phase("egress", rows=count):
            rows = self._read_egress(state, count, lost)
            if apply:
                self._apply_egress(rows)
        obs.metrics.count("egress_rows", count)
        return rows

    def _build_inj(self, staged, inject_fn, state):
        """Pack staged sends into the injection block.  Oversized
        staging: overflow blocks dispatch eagerly — JAX's async dispatch
        overlaps their H2D + queue merge with the host-side packing of
        the next block.  The injection span covers packing + dispatch;
        the transfer itself overlaps the device call."""
        b = self.device.params.inject_batch
        obs = self.obs
        t_inj = wall_time.perf_counter() if obs is not None else 0.0
        n_staged = len(staged)
        while len(staged) > b:
            state = inject_fn(state, self._inj_block(staged[:b], b))
            staged = staged[b:]
        inj = self._inj_block(staged, b) if staged else self._empty_block()
        if obs is not None and n_staged:
            obs.record(
                "injection", None, t_inj,
                wall_time.perf_counter() - t_inj, rows=n_staged,
            )
        return state, inj, n_staged

    def _device_turn(self, state, hybrid_fn, inject_fn, next_host_fn):
        """Inject staged sends, run the device free-run loop, and apply
        egress — retrying while the device paused mid-window to drain a
        low egress buffer.  Per completed turn the boundary costs exactly
        one injection block H2D (zero when nothing staged), one packed
        scalar D2H, and one egress slice D2H (zero when nothing
        egressed).

        When the device-turn ledger is on (obs.turns), every dispatch is
        buffered as ``(dev_we, inject_rows, egress_rows, is_retry)`` for
        the window law to record with its cause — derived purely from
        values this loop reads anyway, zero extra transfers."""
        st = self.sync_stats
        obs = self.obs
        turns = obs.turns if obs is not None else None
        dispatches = [] if turns is not None else None
        staged = self._staged_merged
        self._staged_merged = []
        state, inj, n_staged = self._build_inj(staged, inject_fn, state)
        ext_used = (
            lanes.NEVER32 if self._min_used_lat is None else self._min_used_lat
        )
        host_next = next_host_fn()
        first_dispatch = True
        while True:
            eh, el = (
                (lanes.NEVER32, lanes.NEVER32)
                if host_next >= NEVER
                else (host_next >> 31, host_next & lanes.MASK31)
            )
            t0 = wall_time.perf_counter()
            state, scalars = hybrid_fn(state, eh, el, ext_used, inj)
            sc = jax.device_get(scalars)  # the one blocking readback
            t1 = wall_time.perf_counter()
            st["device_sync_s"] += t1 - t0
            st["device_turns"] += 1
            st["scalar_reads"] += 1
            lane_min = int(sc[lanes.HYB_LANE_MIN])
            dev_we = int(sc[lanes.HYB_DEV_WE])
            dev_used = int(sc[lanes.HYB_MIN_USED])
            self._dev_min_used = (
                None if dev_used >= lanes.NEVER32 else dev_used
            )
            if obs is not None:
                obs.record(
                    "device_turn", None, t0, t1 - t0, window_end=dev_we
                )
                obs.metrics.count("device_turns")
                if (
                    first_dispatch
                    and self._flow_pending is not None
                    and turns is not None
                    and obs.tracer is not None
                ):
                    # trace-flow arrow: the syscall-service span that
                    # forced this blocking turn -> the turn's span
                    fid, anchor = self._flow_pending
                    self._flow_pending = None
                    tr = obs.tracer
                    tr.flow("s", fid, "turn_cause", "turn_flow", anchor)
                    tr.flow(
                        "f", fid, "turn_cause", "turn_flow",
                        t0 + (t1 - t0) / 2,
                    )
            egress_count = int(sc[lanes.HYB_EGRESS_COUNT])
            self._read_egress_obs(
                state, egress_count, int(sc[lanes.HYB_EGRESS_LOST]),
                apply=True,
            )
            if self.perf_log is not None:
                self.perf_log.hybrid_agg(
                    "device", dev_we, self.sync_stats
                )
            if dispatches is not None:
                dispatches.append((
                    dev_we,
                    n_staged if first_dispatch else 0,
                    egress_count,
                    not first_dispatch,
                ))
            if lane_min >= dev_we:
                if dispatches is not None:
                    self._ledger_dispatches = dispatches
                return state, lane_min, dev_we
            # mid-window pause (egress headroom): drain and resume —
            # the cached empty block keeps the retry transfer-free
            inj = self._empty_block()
            host_next = next_host_fn()
            first_dispatch = False

    # -- device-turn ledger (obs/turns.py) -----------------------------------

    def _record_turn_rows(self, turns, t_start: int, host_in: bool) -> None:
        """Record the buffered dispatches of one completed device turn
        with their causes (docs/observability.md taxonomy): the first
        dispatch carries the turn's primary cause — ``injection`` when it
        carried staged rows, else ``host_window`` when the completed
        window has managed participation, else ``free_run`` — and every
        egress-headroom resumption is its own ``egress_drain`` row.
        Participants attach after the host round (the mp engine learns
        them from the worker replies)."""
        dispatches = self._ledger_dispatches
        self._ledger_dispatches = None
        if not dispatches:  # pragma: no cover - defensive
            return
        for dev_we, inj_rows, egr_rows, is_retry in dispatches:
            if is_retry:
                cause = "egress_drain"
            elif inj_rows:
                cause = "injection"
            elif host_in:
                cause = "host_window"
            else:
                cause = "free_run"
            turns.turn(
                cause, t_start, dev_we,
                inject_rows=inj_rows, egress_rows=egr_rows,
            )

    # -- k-window fused turns (docs/hybrid.md "k-window fusion law") ---------

    def _ext_pairs(self, times):
        """Encode a peeked-time schedule as device (hi, lo) int32 pairs
        (NEVER maps to the (NEVER32, NEVER32) sentinel pair)."""
        import jax.numpy as jnp

        t = np.asarray(times, dtype=np.int64)
        inf = t >= NEVER
        hi = np.where(inf, lanes.NEVER32, t >> 31).astype(np.int32)
        lo = np.where(inf, lanes.NEVER32, t & lanes.MASK31).astype(np.int32)
        # jnp.array COPIES (same aliasing hazard as _inj_block)
        return jnp.array(hi), jnp.array(lo)

    def _peek_ext_times(self, floor_t: int = 0) -> list:
        """The fused dispatch's external-event schedule: the next
        ``_ext_slots - 1`` distinct host-side event times (>= floor_t),
        padded with the horizon in the trailing slots (ascending, so the
        device's pointer-advance law stays a prefix count)."""
        es = self._ext_slots
        head, horizon = self._peek_head_horizon(es, floor_t=floor_t)
        head = list(head)
        return head + [horizon] * (es - len(head))

    def _drop_eager(self) -> None:
        if self._eager is not None:
            if self._eager["sc"] is not None:
                self.sync_stats["async_dispatch_misses"] += 1
            self._eager = None
            self._eager_pred = max(self._eager_pred - 1, 0)

    def _fuse_depth(self) -> int:
        """The per-dispatch fusion depth: the provable external-lookahead
        bound (windows the law covers before any speculation: a managed
        send departing in covered window 1 arrives >= L_ext past its
        start, i.e. about L_ext/runahead windows later) PLUS one
        speculative window, floored at 3 and capped by
        ``hybrid_fuse_k``.  The floor is statistical, not provable: the
        ledger measured ~half of covered rounds staging nothing and
        staged arrivals landing >= 1.3 windows out (TCP segments ride
        multi-hop latencies, not the global-min edge), so two windows of
        speculation pay for their occasional rollback; the validation
        law makes any depth safe, this only tunes the waste.  Recomputed
        per dispatch: dynamic runahead moves the bound."""
        k = self._fuse_k
        if self._ext_min_lat is not None:
            ra = self.current_runahead()
            k = min(k, max(3, self._ext_min_lat // ra + 1))
        return k

    def _issue_eager(self, fused_fn, state, lane_min: int,
                     floor_t: int) -> None:
        """Double-buffered async dispatch: while the covered rounds are
        serviced host-side, eagerly dispatch the NEXT fused turn under
        the speculation that they stage nothing and create no event the
        peek (taken at the covered span's end) does not show.  Resolved
        at the next dispatch barrier: adopted only when the real
        dispatch inputs match the speculated ones bit-exact — the
        provably-empty-injection condition that makes the (otherwise
        unsound, docs/hybrid.md) double-buffering a pure overlap."""
        ext = self._peek_ext_times(floor_t)
        host_next = ext[0]
        start = min(host_next, lane_min)  # staged-empty speculation
        if start >= self.stop_time or start == NEVER:
            return
        end = min(start + self.current_runahead(), self.stop_time)
        if lane_min >= end:
            return  # next window would be host-only: nothing to overlap
        used_enc = (
            lanes.NEVER32 if self._min_used_lat is None
            else self._min_used_lat
        )
        k_eff = self._fuse_depth()
        if self._eager_pred < 2:
            # cold predictor: record the speculation's inputs WITHOUT
            # device work — its would-have-hit outcome re-trains the
            # predictor at the next dispatch
            self._eager = {
                "base": state, "ext": ext, "used": used_enc, "k": k_eff,
                "state": None, "sc": None, "t0": 0.0,
            }
            return
        ehi, elo = self._ext_pairs(ext)
        t0 = wall_time.perf_counter()
        state2, scalars = fused_fn(
            state, ehi, elo, used_enc, self._empty_block(),
            np.int32(k_eff),
        )
        self._eager = {
            "base": state, "ext": ext, "used": used_enc, "k": k_eff,
            "state": state2, "sc": scalars, "t0": t0,
        }

    def _dispatch_fused(self, state, fused_fn, ext, used_enc, inj,
                        n_staged: int, k_eff: int):
        """Dispatch (or adopt the eagerly dispatched) fused device call
        and block on its packed readback.  Adoption requires the real
        inputs to equal the speculated ones bit-exact: same base state
        object, same peeked schedule, same dynamic-runahead fold, and an
        empty injection — then the eager result IS the dispatch result
        by functional purity, and the readback blocks only for whatever
        device compute the overlapped syscall servicing did not hide."""
        st = self.sync_stats
        e = self._eager
        state2 = scalars = None
        t0 = 0.0
        if e is not None:
            self._eager = None
            match = (
                e["base"] is state and e["ext"] == ext
                and e["used"] == used_enc and e["k"] == k_eff
                and n_staged == 0
            )
            self._eager_pred = min(self._eager_pred + 1, 3) if match \
                else max(self._eager_pred - 1, 0)
            if e["sc"] is None:
                pass  # phantom speculation: predictor trained, no result
            elif match:
                st["async_dispatch_hits"] += 1
                t0 = e["t0"]
                state2, scalars = e["state"], e["sc"]
            else:
                st["async_dispatch_misses"] += 1
        if scalars is None:
            ehi, elo = self._ext_pairs(ext)
            t0 = wall_time.perf_counter()
            state2, scalars = fused_fn(
                state, ehi, elo, used_enc, inj, np.int32(k_eff)
            )
        t_b0 = wall_time.perf_counter()
        sc = jax.device_get(scalars)  # the one blocking readback
        t1 = wall_time.perf_counter()
        st["device_sync_s"] += t1 - t_b0
        st["device_turns"] += 1
        st["scalar_reads"] += 1
        return state2, sc, t0, t1

    def _dispatch_retrying(self, checkpoint, fused_fn, ext, used_enc, inj,
                           n_staged: int, k_eff: int):
        """The dispatch retry-with-backoff law (docs/robustness.md): a
        failed fused dispatch (device runtime error raised at dispatch or
        at the blocking readback) re-dispatches from the pre-turn device
        checkpoint — ``fused_fn`` is pure, so a successful retry is
        bit-identical to a first-try success — with exponential backoff,
        up to ``experimental.dispatch_retry_max`` times.  Exhausted
        retries escalate to the watchdog/failover boundary as
        :class:`BackendStallError`; an injected stall passes through
        untouched (retrying an injected fault would defeat the test)."""
        from ..faults.watchdog import BackendStallError

        attempt = 0
        while True:
            try:
                return self._dispatch_fused(
                    checkpoint, fused_fn, ext, used_enc, inj, n_staged,
                    k_eff,
                )
            except BackendStallError:
                raise
            except Exception as e:
                attempt += 1
                # any outstanding speculation rode the failed timeline
                self._drop_eager()
                if attempt > self._dispatch_retry_max:
                    raise BackendStallError(
                        f"fused device dispatch failed after "
                        f"{attempt - 1} retr"
                        f"{'y' if attempt - 1 == 1 else 'ies'}: "
                        f"{type(e).__name__}: {e}"
                    ) from e
                self.sync_stats["dispatch_retries"] += 1
                backoff = min(0.05 * 2 ** (attempt - 1), 1.0)
                log.warning(
                    "fused dispatch failed (%s: %s); re-dispatching from "
                    "the pre-turn checkpoint in %.2fs (attempt %d/%d)",
                    type(e).__name__, e, backoff, attempt,
                    self._dispatch_retry_max,
                )
                wall_time.sleep(backoff)

    def _fused_turn(self, state, fused_fn, inject_fn, run_round,
                    on_window, t_start: int):
        """One FUSED device turn: dispatch up to ``hybrid_fuse_k``
        consecutive participating windows in one device call, then
        service the covered syscall rounds window-by-window under the
        arrival-frontier validation law:

        - the frontier F starts unbounded; each covered round lowers it
          to its earliest staged-send arrival, and to its own window end
          when the round created an event inside the still-covered span
          or moved the dynamic-runahead fold;
        - window j+1 is accepted only while ``we_{j+1} <= F`` — a staged
          arrival at or past the span's remaining windows cannot change
          their boundaries or contents (it merges at the next dispatch,
          before the window containing it is computed), so the accepted
          prefix is bit-identical to the unfused law by construction;
        - on a misprediction the device ROLLS BACK: one rebuild dispatch
          from the pre-turn state with ``k_eff`` = the validated prefix
          reproduces exactly the accepted windows (pure function, same
          inputs), and the staged injection rides the next turn.

        Egress rows apply lazily per accepted window so a rollback never
        double-applies a delivery or double-pops a parked payload; the
        rebuild's egress buffer (all rows below the validated frontier,
        already applied) is deliberately never read back.  Returns
        (state, dev_next) like the unfused turn + round sequence."""
        st = self.sync_stats
        obs = self.obs
        turns = obs.turns if obs is not None else None
        staged = self._staged_merged
        self._staged_merged = []
        state, inj, n_staged = self._build_inj(staged, inject_fn, state)
        is_retry = False
        prev_we = t_start
        while True:
            k_eff = self._fuse_depth()
            ext = self._peek_ext_times()
            used_enc = (
                lanes.NEVER32 if self._min_used_lat is None
                else self._min_used_lat
            )
            checkpoint = state
            state, sc, t0, t1 = self._dispatch_retrying(
                state, fused_fn, ext, used_enc, inj, n_staged, k_eff
            )
            lane_min = int(sc[lanes.HYB_LANE_MIN])
            dev_we = int(sc[lanes.HYB_DEV_WE])
            dev_used = int(sc[lanes.HYB_MIN_USED])
            self._dev_min_used = (
                None if dev_used >= lanes.NEVER32 else dev_used
            )
            k_done = int(sc[lanes.HYB_K_DONE])
            we_list = [
                int(sc[lanes.HYB_WE_BASE + i]) for i in range(k_done)
            ]
            if obs is not None:
                obs.record(
                    "device_turn", None, t0, t1 - t0, window_end=dev_we
                )
                obs.metrics.count("device_turns")
                if (
                    not is_retry
                    and self._flow_pending is not None
                    and turns is not None
                    and obs.tracer is not None
                ):
                    fid, anchor = self._flow_pending
                    self._flow_pending = None
                    tr = obs.tracer
                    tr.flow("s", fid, "turn_cause", "turn_flow", anchor)
                    tr.flow(
                        "f", fid, "turn_cause", "turn_flow",
                        t0 + (t1 - t0) / 2,
                    )
            egress_count = int(sc[lanes.HYB_EGRESS_COUNT])
            rows = self._read_egress_obs(
                state, egress_count, int(sc[lanes.HYB_EGRESS_LOST])
            )
            retry = lane_min < dev_we  # mid-window egress-headroom pause
            if self._async_on and not retry and we_list:
                self._issue_eager(fused_fn, state, lane_min, we_list[-1])
            # ---- the validated servicing walk --------------------------
            w_valid = 0
            rounds_run = 0
            frontier = NEVER
            pend = rows
            parts_buf = []
            for j, we_j in enumerate(we_list):
                if we_j > frontier:
                    break  # a staged arrival lands inside this window
                apply_now = [r for r in pend if int(r[0]) < we_j]
                if apply_now:
                    pend = [r for r in pend if int(r[0]) >= we_j]
                    self._apply_egress(apply_now)
                if self.next_event_time() < we_j:
                    rounds_run += 1
                    pre_len = len(self._staged_merged)
                    pre_mul = self._min_used_lat
                    self.window_end = we_j
                    self._fuse_we_final = we_list[-1]
                    try:
                        run_round(we_j)
                    finally:
                        self._fuse_we_final = None
                    if turns is not None:
                        parts_buf.append(self._last_participants)
                    new = self._staged_merged[pre_len:]
                    if new:
                        a = min(int(e[0]) for e in new)
                        if a < frontier:
                            frontier = a
                    if not self._round_clean or (
                        pre_mul != self._min_used_lat
                    ):
                        # the round created an event inside the covered
                        # span, or moved the dynamic-runahead fold:
                        # later window boundaries are unreproducible
                        frontier = min(frontier, we_j)
                w_valid = j + 1
                if on_window is not None:
                    on_window(prev_we, we_j, self.next_event_time())
                prev_we = we_j
            rolled = w_valid < k_done
            if rolled:
                # misprediction: rebuild the validated prefix from the
                # checkpoint (same inputs + k_eff = prefix -> the prefix
                # windows reproduce bit-identically); the original
                # dispatch's unapplied egress rows are discarded (the
                # rows its invalidated windows generated must not land)
                # and the staged injection rides the next turn
                if self._eager is not None:
                    # the eager speculation rode the invalidated
                    # timeline — discard it without training the
                    # predictor: its miss signals "rollback", not "the
                    # next injection will not be empty"
                    if self._eager["sc"] is not None:
                        st["async_dispatch_misses"] += 1
                    self._eager = None
                st["fuse_rollbacks"] += 1
                if w_valid >= 2:
                    st["fused_dispatches"] += 1
                    st["fused_windows"] += w_valid
                st["turns_saved"] += w_valid - 2
                # the rebuild dispatch goes through the same timed
                # dispatch/readback bookkeeping as a primary dispatch
                # (the eager buffer was dropped above, so no adoption)
                state, sc_r, t0r, t1r = self._dispatch_retrying(
                    checkpoint, fused_fn, ext, used_enc, inj, n_staged,
                    w_valid,
                )
                assert int(sc_r[lanes.HYB_K_DONE]) == w_valid, (
                    "fused prefix rebuild diverged from the original "
                    "dispatch (determinism violation)"
                )
                lane_min = int(sc_r[lanes.HYB_LANE_MIN])
                dev_we = int(sc_r[lanes.HYB_DEV_WE])
                dev_used = int(sc_r[lanes.HYB_MIN_USED])
                self._dev_min_used = (
                    None if dev_used >= lanes.NEVER32 else dev_used
                )
                if obs is not None:
                    obs.record(
                        "device_turn", None, t0r, t1r - t0r,
                        window_end=dev_we,
                    )
                    obs.metrics.count("device_turns")
                # the rebuild regenerated the validated prefix
                # bit-identically, so its egress buffer holds exactly
                # the prefix-generated rows; those at or past the last
                # validated window end never passed the walk's apply
                # filter (down-bucket/CoDel queueing delays t_deliver
                # into the invalidated span) — apply them now, like the
                # validated path's trailing pend rows.  Invalidated-
                # window rows exist only in the original buffer and
                # stay dropped: the rebuilt device state still carries
                # their packets in flight
                egr_r = int(sc_r[lanes.HYB_EGRESS_COUNT])
                rows_r = self._read_egress_obs(
                    state, egr_r, int(sc_r[lanes.HYB_EGRESS_LOST])
                )
                late = [
                    r for r in rows_r
                    if int(r[0]) >= we_list[w_valid - 1]
                ]
                if late:
                    self._apply_egress(late)
                if turns is not None:
                    self._ledger_fused_rows(
                        turns, t_start, dev_we, w_valid, n_staged,
                        egress_count, is_retry, parts_buf, rollback=True,
                        rollback_egr=egr_r, rounds_run=rounds_run,
                    )
                return state, lane_min
            # ---- span fully validated ----------------------------------
            if k_done >= 2:
                st["fused_dispatches"] += 1
                st["fused_windows"] += k_done
                st["turns_saved"] += k_done - 1
            if turns is not None:
                self._ledger_fused_rows(
                    turns, t_start, dev_we, w_valid, n_staged,
                    egress_count, is_retry, parts_buf, rollback=False,
                    rounds_run=rounds_run,
                )
            if pend:
                # trailing rows: deliveries of the in-progress (retry) or
                # post-span windows — host events the next dispatch's
                # peek schedule folds
                self._apply_egress(pend)
            if self.perf_log is not None:
                self.perf_log.hybrid_agg("device", dev_we, self.sync_stats)
            if not retry:
                return state, lane_min
            # drain continuation: the device paused mid-window for
            # egress headroom; covered rounds may have staged — repack
            # and resume (the cached empty block keeps a stage-free
            # resume transfer-free)
            staged = self._staged_merged
            self._staged_merged = []
            state, inj, n_staged = self._build_inj(staged, inject_fn, state)
            is_retry = True
            t_start = prev_we

    def _ledger_fused_rows(self, turns, t_start, t_end, w_valid,
                           inj_rows, egr_rows, is_retry, parts_buf,
                           rollback, rollback_egr=0, rounds_run=0):
        """Record one fused dispatch's ledger rows (docs/observability.md)
        under the PR 11 cause precedence (injection > host_window >
        free_run): a dispatch that carried staged rows is an
        ``injection`` row even when fused — the unfused law would have
        blocked for it, and labeling it ``free_run`` would inflate
        ``strict_free_turns`` and the remaining free-run headroom the
        ``hybrid_fuse_warn_fraction`` soft check compares against; an
        injection-free dispatch covering >= 2 validated windows is a
        ``free_run`` row.  Either way ``windows`` carries the coverage
        (the fused accounting keys off it, not the cause).
        Single-window dispatches keep the full PR 11 law —
        ``host_window`` only when the window's round actually ran,
        matching the unfused law's ``host_in`` test (a passive-inline
        delivery consumes no round and stays a strict ``free_run``); a
        prefix rebuild adds a ``rollback`` row with ``windows=0`` so the
        conservation law counts every dispatch while the implied-unfused
        accounting counts covered windows once."""
        if inj_rows:
            cause = "injection"
        elif w_valid >= 2:
            cause = "free_run"
        elif w_valid == 1 and rounds_run:
            cause = "host_window"
        elif is_retry and not w_valid:
            cause = "egress_drain"
        else:
            cause = "free_run"
        turns.turn(
            cause, t_start, t_end, windows=max(w_valid, 1),
            inject_rows=inj_rows, egress_rows=egr_rows,
        )
        for parts in parts_buf:
            if parts:
                turns.attach_participants(parts)
        if rollback:
            # the rebuild's egress re-read (prefix rows re-fetched to
            # recover post-span deliveries) rides the rollback row so
            # ledger egress_rows_total keeps matching the engine's
            # D2H row accounting
            turns.turn(
                "rollback", t_start, t_end, windows=0,
                egress_rows=rollback_egr,
            )

    # -- the hybrid round loop ----------------------------------------------

    def _service_round(self, scheduler, until: int) -> None:
        """One host-side syscall-service round + barrier, timed into
        sync_stats (and per-window through the perf log / obs spans).
        Inside a fused span (``_fuse_we_final`` set past the window) the
        round also runs the cleanliness probe: a changed event count in
        ``[until, we_final)`` means the round created an event inside the
        still-covered span — the fused-turn walk rolls back there."""
        t0 = wall_time.perf_counter()
        obs = self.obs
        wf = self._fuse_we_final
        probe = wf is not None and wf > until
        pre_range = self._range_count(until, wf) if probe else 0
        if obs is not None and obs.turns is not None:
            # the turn ledger's participant set, taken BEFORE execution
            # mutates the queues: managed hosts with events inside the
            # window — the identical law the mp workers apply, so the
            # ledger is bit-identical at any worker count
            self._last_participants = tuple(
                h.host_id for h in self._next_hosts
                if h.queue.next_time() < until
            )
        scheduler.run_round(until)
        self._barrier_merge()
        self._round_clean = (
            not probe or self._range_count(until, wf) == pre_range
        )
        t1 = wall_time.perf_counter()
        self.sync_stats["syscall_service_s"] += t1 - t0
        if obs is not None:
            obs.record(
                "syscall_service", None, t0, t1 - t0, window_end=until
            )
            if obs.turns is not None and obs.tracer is not None:
                self._flow_seq += 1
                self._flow_pending = (
                    self._flow_seq, t0 + (t1 - t0) / 2,
                )
        if self.perf_log is not None:
            self.perf_log.hybrid_agg("host", until, self.sync_stats)

    def run(self, on_window=None) -> SimResult:
        from ..engine.scheduler import HostScheduler

        exp = self.cfg.experimental
        scheduler = HostScheduler(
            self.external_hosts,
            parallelism=self.cfg.general.parallelism,
            policy=exp.scheduler,
            pin_cpus=exp.use_cpu_pinning,
        )
        try:
            return self._run_hybrid(scheduler, on_window)
        finally:
            scheduler.shutdown()

    def _run_hybrid(self, scheduler, on_window) -> SimResult:
        t0 = wall_time.perf_counter()
        try:
            return self._hybrid_loop(scheduler, on_window, t0)
        except BaseException:
            self.finalize()
            raise

    def _maybe_stall(self, start: int) -> None:
        """Raise the injected ``backend_stall`` once the sim clock
        crosses its epoch (same law as the TPU step driver): the facade's
        failover boundary catches it and replays on the CPU engine, where
        the managed hosts run natively."""
        if start >= self._stall_after:
            from ..faults.watchdog import BackendStallError

            epoch = self._stall_after
            self._stall_after = NEVER  # raise once
            self._drop_eager()
            raise BackendStallError(
                f"injected backend stall at {epoch} ns "
                "(fault schedule backend_stall event)"
            )

    def _window_loop(self, run_round, on_window):
        """The hybrid window law, shared verbatim by the serial engine
        and the multiprocess controller: only the round executor differs
        (``run_round(until)`` = threaded scheduler round vs worker-pipe
        round).  Returns the final device state for collection.

        ``hybrid_fuse_k >= 2`` swaps in the k-window fused law
        (docs/hybrid.md); at 1 this loop IS the PR 7 law, bit-for-bit,
        including the transfer pattern."""
        if self._fuse_on:
            return self._window_loop_fused(run_round, on_window)
        dev = self.device
        state = dev.place_state(dev.initial_state())
        hybrid_fn, inject_fn = dev.make_hybrid_fns()
        dev_next = dev.first_event_time()
        turns = self.obs.turns if self.obs is not None else None
        while True:
            host_next = self.next_event_time()
            staged_min = min(
                (e[0] for e in self._staged_merged), default=NEVER
            )
            dev_eff = min(dev_next, staged_min)
            start = min(host_next, dev_eff)
            if start >= self.stop_time or start == NEVER:
                return state
            self._maybe_stall(start)
            end = min(start + self.current_runahead(), self.stop_time)
            if self._staged_merged or dev_eff < end:
                # device turn: complete every window up to (and including)
                # the first one the host participates in
                state, dev_next, dev_we = self._device_turn(
                    state, hybrid_fn, inject_fn, self.next_event_time
                )
                host_in = self.next_event_time() < dev_we
                if turns is not None:
                    self._record_turn_rows(turns, start, host_in)
                if host_in:
                    # host part of the device-completed window
                    self.window_end = dev_we
                    run_round(dev_we)
                    if turns is not None:
                        turns.attach_participants(self._last_participants)
                    if on_window is not None:
                        on_window(start, dev_we, self.next_event_time())
                continue
            # host-only window (device idle beyond it, nothing staged)
            self.window_end = end
            run_round(end)
            if turns is not None:
                turns.host_round()
            self.host_rounds += 1
            if on_window is not None:
                on_window(start, end, self.next_event_time())

    def _window_loop_fused(self, run_round, on_window):
        """The k-window fused hybrid window law: the same outer loop as
        ``_window_loop`` with device turns delegated to ``_fused_turn``
        (one dispatch covers up to ``hybrid_fuse_k`` participating
        windows; covered rounds are serviced and validated post-hoc) and
        the double-buffered eager dispatch resolving at adoption
        barriers.  Host-only windows, the dynamic-runahead law, and the
        staged-send fold are untouched — the fusion is a pure scheduling
        change (tests/test_hybrid_fusion.py pins bit-parity with the CPU
        oracle and the unfused engine)."""
        dev = self.device
        state = dev.place_state(dev.initial_state())
        fused_fn, inject_fn = dev.make_hybrid_fns(
            self._fuse_k, self._ext_slots
        )
        dev_next = dev.first_event_time()
        turns = self.obs.turns if self.obs is not None else None
        while True:
            host_next = self.next_event_time()
            staged_min = min(
                (e[0] for e in self._staged_merged), default=NEVER
            )
            dev_eff = min(dev_next, staged_min)
            start = min(host_next, dev_eff)
            if start >= self.stop_time or start == NEVER:
                self._drop_eager()
                return state
            self._maybe_stall(start)
            end = min(start + self.current_runahead(), self.stop_time)
            if self._staged_merged or dev_eff < end:
                state, dev_next = self._fused_turn(
                    state, fused_fn, inject_fn, run_round, on_window,
                    start,
                )
                continue
            # host-only window (device idle beyond it, nothing staged):
            # an outstanding eager dispatch assumed a device window next
            # and cannot match — discard before the round runs
            self._drop_eager()
            self.window_end = end
            run_round(end)
            if turns is not None:
                turns.host_round()
            self.host_rounds += 1
            if on_window is not None:
                on_window(start, end, self.next_event_time())

    def _check_fusion_accounting(self) -> None:
        """End-of-run ledger cross-check (ISSUE 13 satellite): the
        fused-turn accounting must conserve — ``turns + turns_saved``
        equals the unfused turn count implied by the cause rows — and
        the achieved collapse is compared against the ledger's remaining
        free-run headroom prediction (warn, never fail, below the
        configured fraction)."""
        obs = self.obs
        if obs is None or obs.turns is None:
            return
        from ..obs import turns as tmod

        tmod.check_fusion_accounting(
            obs.turns, self.sync_stats,
            warn_fraction=(
                self.cfg.experimental.hybrid_fuse_warn_fraction
                if self._fuse_on else None
            ),
        )

    def netobs_snapshot(self):
        """The combined telemetry plane: host-side counters (managed
        hosts' sends, loopback, throttles) summed with the device-side
        counters (every dst half, lane-model hosts' sends).  The window
        histogram is the device's: ALL packet arrivals pop on the lane
        plane on this backend (``inbound`` asserts host queues never
        hold PACKET events), so there is no host-plane arrival
        histogram to report."""
        host = super().netobs_snapshot()
        dev = self.device.netobs_snapshot()
        if host is None or dev is None:
            return None
        from ..obs import netobs as nom

        arrays = nom.merge_arrays(
            {k: v.copy() for k, v in dev["arrays"].items()},
            host["arrays"],
        )
        return {
            "arrays": arrays,
            "window_hist": dev["window_hist"],
            "log_lost": 0,
        }

    def flowtrace_snapshot(self):
        """The combined flow-event stream: host-side events (managed
        sends' source half, loopback) concatenated with the device ring
        (arrival halves, lane-model hosts' full lifecycles).  Each
        lifecycle stage is emitted by exactly one side, so the
        concatenation + canonical sort is the complete stream.  Drained
        here only — at collect — never per turn, so ``sync_stats``
        transfer counts are untouched by tracing."""
        host = super().flowtrace_snapshot()
        dev = self.device.flowtrace_snapshot()
        if host is None or dev is None:
            return None
        return {
            "raw": list(host["raw"]) + list(dev["raw"]),
            "ring_lost": host["ring_lost"] + dev["ring_lost"],
        }

    def _hybrid_loop(self, scheduler, on_window, t0) -> SimResult:
        state = self._window_loop(
            lambda until: self._service_round(scheduler, until), on_window
        )
        self._check_fusion_accounting()
        self.finalize()
        wall = wall_time.perf_counter() - t0

        dev_result = self.device.collect(state, wall)
        counters: dict[str, int] = dict(dev_result.counters)
        for h in self.hosts:
            for k, v in h.counters.items():
                counters[k] = counters.get(k, 0) + v
        return SimResult(
            sim_time_ns=self.stop_time,
            wall_seconds=wall,
            rounds=dev_result.rounds + self.host_rounds,
            event_log=dev_result.event_log + self.event_log,
            counters=counters,
            per_host_counters=[dict(h.counters) for h in self.hosts],
            process_errors=list(getattr(self, "process_errors", [])),
        )


class MpHybridEngine(HybridEngine):
    """Hybrid backend with PARALLEL syscall servicing: N spawned worker
    processes own disjoint partitions of the external (managed) hosts and
    execute their syscall plane concurrently (real OS-process parallelism,
    no GIL), while the parent owns the device and the window law.

    The parent is the Controller: it folds the workers' next-event times
    (plus in-flight egressed deliveries), computes every window, ships
    delivery rows to the owners and collects staged sends at each round
    barrier — one pipe message per worker per round, so the host<->device
    boundary stays as batched as the serial engine's.  Determinism is
    worker-count-invariant (see the module docstring); ``workers=1``
    degenerates to the serial engine (no pipe overhead, same results)."""

    def __init__(
        self, cfg: ConfigOptions, workers: int = 0,
        log_capacity: Optional[int] = None,
    ) -> None:
        for hopt in cfg.hosts:
            if hopt.pcap_enabled:
                raise ValueError(
                    "MpHybridEngine does not support pcap capture (every "
                    "worker replica would open the capture files); use "
                    "the serial hybrid engine"
                )
        super().__init__(cfg, log_capacity=log_capacity)
        n_ext = len(self.external_hosts)
        self.workers = workers if workers > 0 else (os.cpu_count() or 1)
        self.workers = max(1, min(self.workers, n_ext))
        self._eff_next: Optional[list[int]] = None
        self._pending_rows: Optional[list[list]] = None
        self._owner_of: dict[int, int] = {}
        # supervision (engine/supervisor.py): deadline-bounded pipe reads
        # so a dead or hung worker surfaces as a diagnostic
        # WorkerDiedError instead of an indefinite hang.  No respawn on
        # this backend — workers hold live managed OS processes whose
        # kernel state cannot be resnapshotted — so a worker death
        # escalates straight to the facade's failover boundary.
        self._heartbeat_s = float(cfg.experimental.worker_heartbeat_s)
        self._round_no = 0

    # -- controller-side bookkeeping ---------------------------------------

    def next_event_time(self) -> int:
        if self._eff_next is not None:
            return min(self._eff_next, default=NEVER)
        return super().next_event_time()

    def _route_delivery(self, t, src, dst, seq, size, payload) -> None:
        """Ship the delivery to the worker owning ``dst`` at the next
        round message; fold its time into the owner's effective next-event
        time unless the replica consumes it inline (passive elision makes
        no queue event — the parent's replica knows which hosts are
        passive, construction being deterministic)."""
        if self._eff_next is None:
            # workers==1 degenerate run: the serial loop executes hosts
            # in-process, so deliveries apply directly
            super()._route_delivery(t, src, dst, seq, size, payload)
            return
        w = self._owner_of[dst]
        self._pending_rows[w].append((t, src, dst, seq, size, payload))
        if not (payload is None and self.hosts[dst].passive_delivery):
            if t < self._eff_next[w]:
                self._eff_next[w] = t

    def _mp_round(self, window_end: int) -> None:
        """One parallel syscall-service round: ship (window_end, delivery
        rows, validation range) to every worker, collect (next_t, staged
        sends, min-used latency, cleanliness, peeked schedule) — a single
        pipe message each way per worker.  Workers execute concurrently
        between the two loops; staged sends merge in (worker-id, host-id)
        order, which the device queue merge's total key makes
        order-invariant anyway.  Inside a fused span the workers run the
        cleanliness probe over their owned partition and ship their
        refreshed peek schedules, so the parent's next-event folds arrive
        early enough to bound the next dispatch's k."""
        t0 = wall_time.perf_counter()
        obs = self.obs
        conns, procs = self._mp
        self._round_no += 1
        wf = self._fuse_we_final
        for w, conn in enumerate(conns):
            conn.send((
                "round", window_end, self._pending_rows[w],
                wf if wf is not None else window_end,
            ))
            self._pending_rows[w] = []
        t_ship = wall_time.perf_counter()
        staged = self._staged_merged
        perf_lines: list[str] = []
        parts_all: list[int] = []
        clean = True
        for w, conn in enumerate(conns):
            next_t, out, mul, wlines, wparts, wclean, wpeek = (
                recv_with_deadline(
                    conn, procs[w], self._heartbeat_s, w, self._round_no,
                    "round",
                )
            )
            self._eff_next[w] = next_t
            if mul is not None and (
                self._min_used_lat is None or mul < self._min_used_lat
            ):
                self._min_used_lat = mul
            staged.extend(out)
            if wlines:
                perf_lines.extend(wlines)
            if wparts:
                parts_all.extend(wparts)
            clean = clean and wclean
            if wpeek:
                self._worker_peeks[w] = wpeek
        self._round_clean = clean
        t1 = wall_time.perf_counter()
        self.sync_stats["syscall_service_s"] += t1 - t0
        if obs is not None and obs.turns is not None:
            # the partition interleaves host ids round-robin across
            # workers; sorting normalizes the union to the serial
            # engine's host-id order (ledger worker-count invariance)
            self._last_participants = tuple(sorted(parts_all))
            if obs.tracer is not None:
                self._flow_seq += 1
                self._flow_pending = (
                    self._flow_seq, t_ship + (t1 - t_ship) / 2,
                )
        if obs is not None:
            # disjoint attribution (same law as cpu_mp): worker_pipe is
            # the ship leg, syscall_service the collect leg — the barrier
            # wait that IS the workers' syscall execution wall.  The two
            # tile the round exactly, so phase sums never double-count
            # (sync_stats' syscall_service_s keeps covering the whole
            # round, ship included — the legacy [hybrid-agg] counter)
            obs.record("worker_pipe", "pipe_ship", t0, t_ship - t0)
            obs.record(
                "syscall_service", None, t_ship, t1 - t_ship,
                window_end=window_end,
            )
            obs.metrics.count("pipe_messages", 2 * len(conns))
        # worker-process perf lines route through the parent's locked
        # sink, in (round, worker-id) order — one coherent stream
        if perf_lines and self.perf_log is not None:
            self.perf_log.emit_many(perf_lines)
        if self.perf_log is not None:
            self.perf_log.hybrid_agg("host", window_end, self.sync_stats)

    def _peek_partition(self, owned):
        """A worker partition's initial (head, horizon) peek from the
        parent replica — literally the worker's ``_peek_head_horizon``
        law over its owned hosts (deterministic construction makes the
        replicas agree)."""
        return self._peek_head_horizon(
            self._ext_slots, [self.hosts[i] for i in owned]
        )

    def _peek_ext_times(self, floor_t: int = 0) -> list:
        """Merge the workers' shipped peek schedules: distinct times
        below the tightest worker horizon, padded with the merged
        horizon.  A worker's horizon marks where ITS schedule knowledge
        ends; beyond the min of all horizons the parent knows nothing,
        so the merged schedule must stop there too.

        Deliveries the parent has APPLIED but not yet shipped (trailing
        egress rows queued in ``_pending_rows`` for the next round
        message) are events the workers' schedules cannot know about yet
        — fold their times in directly, or the fused dispatch could
        free-run past a pending host event the serial law (which reads
        the queues) would have bounded."""
        if self._eff_next is None:
            return super()._peek_ext_times(floor_t)
        es = self._ext_slots
        merged: set = set()
        wh = NEVER
        for head, hz in self._worker_peeks:
            for t in head:
                if t >= floor_t:
                    merged.add(t)
            if hz < wh:
                wh = hz
        for rows in self._pending_rows:
            for t, _src, dst, _seq, _size, payload in rows:
                if t >= floor_t and not (
                    payload is None and self.hosts[dst].passive_delivery
                ):
                    merged.add(t)
        times = sorted(t for t in merged if t < wh)
        head = times[: es - 1]
        horizon = times[es - 1] if len(times) >= es else wh
        return head + [horizon] * (es - len(head))

    def netobs_snapshot(self):
        """Worker-merged host arrays + device arrays (the window
        histogram is the device's — see HybridEngine.netobs_snapshot)."""
        wnb = getattr(self, "_worker_nb", None)
        if wnb is None:
            # serial / degenerate (workers == 1) path ran in-process
            return super().netobs_snapshot()
        dev = self.device.netobs_snapshot()
        if dev is None:
            return None
        from ..obs import netobs as nom

        arrays = nom.merge_arrays(
            {k: v.copy() for k, v in dev["arrays"].items()}, wnb
        )
        return {
            "arrays": arrays,
            "window_hist": dev["window_hist"],
            "log_lost": 0,
        }

    def flowtrace_snapshot(self):
        """Worker-merged host events + device ring events (see
        HybridEngine.flowtrace_snapshot for the split law)."""
        wft = getattr(self, "_worker_ft", None)
        if wft is None:
            # serial / degenerate (workers == 1) path ran in-process
            return super().flowtrace_snapshot()
        dev = self.device.flowtrace_snapshot()
        if dev is None:
            return None
        return {
            "raw": list(wft) + list(dev["raw"]),
            "ring_lost": dev["ring_lost"],
        }

    # -- run ---------------------------------------------------------------

    def run(self, on_window=None) -> SimResult:
        if self.workers == 1:
            # degenerate case (single-core box): spawning one worker only
            # adds pipe overhead — run in-process, same results
            return super().run(on_window=on_window)
        from .cpu_mp import _partition, spawn_cpu_workers

        ext_ids = [h.host_id for h in self.external_hosts]
        parts = [
            [ext_ids[i] for i in p]
            for p in _partition(len(ext_ids), self.workers)
        ]
        self._owner_of = {
            hid: w for w, part in enumerate(parts) for hid in part
        }
        record_turns = self.obs is not None and self.obs.turns is not None
        peek_slots = self._ext_slots if self._fuse_on else 0
        conns, procs = spawn_cpu_workers(
            _hybrid_worker_main,
            [(self.cfg, owned, record_turns, peek_slots)
             for owned in parts],
        )
        self._mp = (conns, procs)
        self._pending_rows = [[] for _ in range(self.workers)]
        # initial next-event times from the parent replica (identical
        # deterministic construction — no startup round trip needed);
        # same for the fused path's initial per-worker peek schedules
        self._eff_next = [
            min((self.hosts[i].queue.next_time() for i in owned),
                default=NEVER)
            for owned in parts
        ]
        if self._fuse_on:
            self._worker_peeks = [
                self._peek_partition(owned) for owned in parts
            ]
        t0 = wall_time.perf_counter()
        try:
            return self._mp_loop(on_window, t0)
        finally:
            self._eff_next = None
            for conn in conns:
                conn.close()
            for p in procs:
                p.join(timeout=10)
                if p.is_alive():
                    p.terminate()

    def _mp_loop(self, on_window, t0) -> SimResult:
        conns, procs = self._mp
        state = self._window_loop(self._mp_round, on_window)
        self._check_fusion_accounting()

        event_log: list = []
        counters: dict[str, int] = {}
        per_host: list[dict] = [{} for _ in range(len(self.hosts))]
        process_errors: list[str] = []
        self._worker_nb = None
        self._worker_ft = None
        for conn in conns:
            conn.send(("finish",))
        for w, conn in enumerate(conns):
            wlog, cnt, per, errs, wsnap, wflows = recv_with_deadline(
                conn, procs[w], self._heartbeat_s, w, self._round_no,
                "finish",
            )
            event_log.extend(wlog)
            for k, v in cnt.items():
                counters[k] = counters.get(k, 0) + v
            for hid, c in per.items():
                per_host[hid] = c
            process_errors.extend(errs)
            if wsnap is not None:
                from ..obs import netobs as nom

                if self._worker_nb is None:
                    self._worker_nb = nom.empty_arrays(len(self.hosts))
                nom.merge_arrays(self._worker_nb, wsnap["arrays"])
            if wflows is not None:
                if self._worker_ft is None:
                    self._worker_ft = []
                self._worker_ft.extend(tuple(e) for e in wflows)
        wall = wall_time.perf_counter() - t0

        dev_result = self.device.collect(state, wall)
        for k, v in dev_result.counters.items():
            counters[k] = counters.get(k, 0) + v
        return SimResult(
            sim_time_ns=self.stop_time,
            wall_seconds=wall,
            rounds=dev_result.rounds + self.host_rounds,
            event_log=dev_result.event_log + event_log,
            counters=counters,
            per_host_counters=per_host,
            process_errors=process_errors,
        )
