"""Simulation configuration: YAML + programmatic, with typed units.

User-facing parity with the reference's three-layer config system
(src/main/core/configuration.rs): the same YAML document shape —

    general:    { stop_time, seed, parallelism, bootstrap_end_time, ... }
    network:    { graph: { type: gml|1_gbit_switch, file|inline }, ... }
    experimental: { runahead, use_dynamic_runahead, ... }
    host_option_defaults: { ... }
    hosts:
      <hostname>:
        network_node_id: 0
        processes: [ { path, args, start_time, ... } ]

— parsed into plain dataclasses.  CLI overrides merge on top of the YAML
values (the reference uses the `merge` crate for this; here
:func:`ConfigOptions.apply_overrides` takes dotted keys).

TPU-specific addition: ``experimental.network_backend`` selects ``cpu``
(host reference implementation) or ``tpu`` (batched JAX lane backend), the
analog of the reference's ``use_new_tcp``-style backend switches.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Any, Optional

import yaml

from ..core import time as stime
from . import units


class ConfigError(ValueError):
    pass


# socket buffer defaults, single-sourced for the config dataclass, the shim
# shared-memory block, and the managed-process manager
SOCKET_SEND_BUFFER_DEFAULT = 131072
SOCKET_RECV_BUFFER_DEFAULT = 174760


@dataclasses.dataclass
class GeneralOptions:
    stop_time: int = 0  # ns; required > 0
    seed: int = 1
    parallelism: int = 0  # 0 = all cores
    bootstrap_end_time: int = 0  # ns; loss-free warm-up window (worker.rs:335)
    data_directory: str = "shadow.data"
    template_directory: Optional[str] = None
    log_level: str = "info"
    heartbeat_interval: Optional[int] = stime.NANOS_PER_SEC
    progress: bool = False
    model_unblocked_syscall_latency: bool = False


@dataclasses.dataclass
class GraphOptions:
    type: str = "1_gbit_switch"  # "gml" | "1_gbit_switch"
    file_path: Optional[str] = None
    inline: Optional[str] = None


@dataclasses.dataclass
class NetworkOptions:
    graph: GraphOptions = dataclasses.field(default_factory=GraphOptions)
    use_shortest_path: bool = True


@dataclasses.dataclass
class ExperimentalOptions:
    # PDES window control
    runahead: Optional[int] = stime.NANOS_PER_MILLI  # lower bound, ns
    use_dynamic_runahead: bool = False
    # scheduling (cpu backend)
    scheduler: str = "thread-per-core"  # | "thread-per-host"
    use_cpu_pinning: bool = True
    use_worker_spinning: bool = True
    # transport knobs
    use_new_tcp: bool = False
    socket_send_buffer: int = SOCKET_SEND_BUFFER_DEFAULT  # bytes
    socket_recv_buffer: int = SOCKET_RECV_BUFFER_DEFAULT
    interface_qdisc: str = "fifo"  # | "round-robin"
    # strace-style logging
    strace_logging_mode: str = "off"  # off | standard | deterministic
    # managed-process interposition backstops (the reference's seccomp
    # SIGSYS trap, shim_seccomp.c, and vDSO patching, patch_vdso.c):
    # catch raw syscalls and vDSO-direct time reads that bypass LD_PRELOAD
    use_seccomp: bool = True
    use_vdso_patching: bool = True
    # fork features: interactive run-control console (pause/step/restart at
    # window boundaries) and [window-agg]/[host-exec-agg] telemetry
    run_control: bool = False
    perf_logging: bool = False
    # observability (shadow_tpu/obs/, docs/observability.md): per-phase
    # wall metrics -> METRICS_*.json, span tracing -> Chrome-trace JSON,
    # optional JSONL event stream and jax.profiler annotation
    # pass-through.  All default off = zero overhead; event ordering is
    # bit-identical with everything on (docs/determinism.md)
    obs_metrics: bool = False
    obs_trace: bool = False
    obs_jsonl: bool = False
    obs_jax_annotations: bool = False
    obs_dir: Optional[str] = None  # None = general.data_directory
    # device-turn ledger (obs/turns.py): causal per-turn accounting
    # (cause taxonomy + conservation law) and fusable-run-length
    # measurement, exported as TURNS_<backend>-seed<N>.json.  Rows derive
    # from data the host side already holds per turn — zero new
    # host<->device transfers — and are bit-identical at any hybrid
    # worker count
    obs_turns: bool = False
    # simulated-network telemetry plane (obs/netobs.py): per-host
    # sent/delivered/bytes counters, drop-cause accounting, and the
    # burst-window histogram, exported as NETOBS_<backend>-seed<N>.json.
    # Device-side the counters live in the lane kernels (zero new
    # host<->device syncs; LaneParams.netobs compiles them away when
    # off); the CPU oracle accumulates the identical counters so the
    # parity suite can diff them per host
    netobs: bool = False
    # per-flow packet-lifecycle tracing (obs/flowtrace.py): lifecycle
    # events (send / tb-wait / queue-enter / drop+cause / retransmit /
    # delivery) for deterministically-sampled flows, exported as
    # FLOWS_<backend>-seed<N>.json with a burst attribution report.
    # Device-side the events land in a bounded ring inside the lane
    # kernels (drained only at snapshot epochs / end-of-run — zero new
    # host<->device transfers; LaneParams.flowtrace compiles the plane
    # away when off); the CPU oracle emits the identical stream so the
    # parity suite can diff them event-for-event
    flowtrace: bool = False
    flowtrace_capacity: int = 65536  # device ring rows; never wraps
    flowtrace_sample: float = 1.0  # fraction of flows traced (seeded hash)
    # --- TPU-native extensions -------------------------------------------
    network_backend: str = "cpu"  # "cpu" | "tpu"
    tpu_lane_queue_capacity: int = 64  # per-host in-flight packet slots
    tpu_events_per_round: int = 8  # max pops per lane per inner step
    tpu_round_unroll: int = 1  # fused-loop steps per device loop trip
    # cross-lane receive block width per iteration (0 = queue capacity);
    # narrower is faster when per-iteration fan-in is bounded — overflow
    # is counted and strict mode raises, exactly like queue overflow
    tpu_cross_capacity: int = 0
    tpu_mesh_shape: Optional[tuple[int, ...]] = None  # None = all devices
    # multi-chip sharded lane plane (shadow_tpu/parallel/,
    # docs/multichip.md): shard the per-host lane state over up to this
    # many devices on a 1-D ``Mesh(("hosts",))``.  0 = off
    # (single-device); the actual count is NEGOTIATED down to the largest
    # value that divides the host count and does not exceed the available
    # devices (transparent fallback — never an error).  Results are
    # bit-identical at any mesh shape.  A 1-D ``tpu_mesh_shape`` tuple is
    # the older alias for the same request.
    mesh_devices: int = 0
    # TIERED stream backend (one-to-one stream configs): stream endpoints
    # run on a dedicated [2S]-row tier with their own queue block and pop
    # rate, keeping the [N]-wide machinery stream-free (docs/tpu-backend.md)
    tpu_stream_tiered: bool = True
    tpu_stream_events_per_round: int = 8  # tier pops per iteration (K_s)
    tpu_stream_queue_capacity: int = 64  # tier queue width (C2)
    # HYBRID backend (backend/hybrid.py): syscall-servicing worker
    # processes for the managed hosts while their packets ride the TPU
    # lanes.  1 = serial in-process servicing; 0 = one worker per core;
    # N > 1 = exactly N spawned workers.  Results are bit-identical at
    # any worker count (tests/test_hybrid_mp.py).
    hybrid_workers: int = 1
    # injection block rows per device turn (B): staged managed-host sends
    # coalesce into blocks of this size for the host->device hop
    tpu_inject_batch: int = 512
    # k-window free-run fusion on the hybrid path (docs/hybrid.md
    # "k-window fusion law"): one device dispatch may cover up to this
    # many consecutive host-participating windows, with the covered
    # syscall rounds serviced post-hoc under the arrival-frontier
    # validation law (rollback to the validated prefix on a late staged
    # injection).  1 disables fusion — the exact PR 7 one-dispatch-per-
    # participating-window law, bit-for-bit.
    hybrid_fuse_k: int = 8
    # double-buffered async dispatch (hybrid, requires fusion): when the
    # next fused turn's injection is provably empty so far, dispatch it
    # eagerly and overlap syscall servicing with device compute,
    # resolving (adopt or discard) at the readback barrier.  The
    # UNCONDITIONAL version is unsound (docs/hybrid.md); this one only
    # adopts a result whose inputs were validated bit-exact.
    hybrid_async_dispatch: bool = True
    # fusion-effectiveness floor: warn (never fail) when the achieved
    # turn collapse falls below this fraction of the ledger's remaining
    # kfusion_headroom_freerun prediction (obs_turns runs only)
    hybrid_fuse_warn_fraction: float = 0.5
    # --- crash safety (engine/checkpoint.py, docs/robustness.md) ---------
    # write an on-disk checkpoint every N window-clamp boundaries
    # (0 = checkpointing off); pure-lane backends only (cpu, cpu_mp, tpu)
    checkpoint_every_windows: int = 0
    # checkpoint directory (None = <data_directory>/checkpoints)
    checkpoint_dir: Optional[str] = None
    # bounded retention: keep the newest N checkpoints of a run
    checkpoint_keep: int = 3
    # resume a run from this checkpoint file (the --resume CLI flag);
    # the resumed run is bit-identical to the uninterrupted one
    resume_from: Optional[str] = None
    # worker supervision (engine/supervisor.py): reply deadline for
    # multiprocess workers (wall seconds) — a worker that misses it is
    # diagnosed dead/hung instead of blocking the parent forever
    worker_heartbeat_s: float = 30.0
    # respawn+replay budget: consecutive failures of one worker before
    # escalating to the serial engine (0 = supervision off: a dead
    # worker raises WorkerDiedError)
    worker_restart_max: int = 2
    # hybrid device path: fused-dispatch retries (from the pre-turn
    # device checkpoint, exponential backoff) before the failure
    # escalates to the watchdog/failover boundary
    dispatch_retry_max: int = 2
    # --- fleet sweeps (shadow_tpu/sweep/, docs/sweep.md) -----------------
    # batch S scenario instances into ONE vmapped lane kernel.  With no
    # sweep_spec, sweep_size > 1 runs the seed grid general.seed ..
    # general.seed + sweep_size - 1; 0/1 = sweeps off (serial run)
    sweep_size: int = 0
    # path to a sweep-spec YAML (seeds / faults / overrides axes —
    # docs/sweep.md schema); overrides sweep_size when set
    sweep_spec: Optional[str] = None


@dataclasses.dataclass
class FaultOptions:
    """The ``faults:`` config section (shadow_tpu/faults/): a declarative
    fault schedule plus the graceful-degradation knobs.

    ``failover=None`` means auto: TPU->CPU failover is armed exactly when
    a fault schedule exists.  Set it explicitly to arm failover for real
    backend errors without scheduling any faults (``faults: {failover:
    true}``) or to make injected failures fatal (``failover: false``).
    """

    failover: Optional[bool] = None
    watchdog_timeout: Optional[float] = None  # wall seconds, tpu step driver
    events: list = dataclasses.field(default_factory=list)  # raw event dicts

    @property
    def failover_enabled(self) -> bool:
        if self.failover is not None:
            return bool(self.failover)
        return bool(self.events)

    def schedule(self):
        """Parse ``events`` into a validated FaultSchedule (raises
        shadow_tpu.faults.FaultConfigError on malformed entries)."""
        from ..faults.schedule import FaultSchedule

        return FaultSchedule.parse(self.events)


@dataclasses.dataclass
class ProcessOptions:
    path: str = ""
    args: list[str] = dataclasses.field(default_factory=list)
    environment: dict[str, str] = dataclasses.field(default_factory=dict)
    start_time: int = 0  # ns
    shutdown_time: Optional[int] = None
    shutdown_signal: str = "SIGTERM"
    expected_final_state: Any = "exited"  # {"exited": code}|"running"|{"signaled": sig}


@dataclasses.dataclass
class HostOptions:
    hostname: str = ""
    network_node_id: int = 0
    ip_addr: Optional[str] = None
    bandwidth_down: Optional[int] = None  # bits/sec; falls back to graph node
    bandwidth_up: Optional[int] = None
    processes: list[ProcessOptions] = dataclasses.field(default_factory=list)
    log_level: Optional[str] = None
    pcap_enabled: bool = False
    pcap_capture_size: int = 65535
    # TCP congestion-control algorithm for this host's flows (the
    # reference's pluggable tcp_cong.c interface: tcp_cong_reno.c and the
    # CUBIC analog here); applies to both the byte-stream stack and the
    # lane/ltcp stream tier (data-sender side)
    congestion: str = "reno"  # "reno" | "cubic"
    count: int = 1  # convenience host multiplier (hostname gets a suffix)


@dataclasses.dataclass
class ConfigOptions:
    general: GeneralOptions = dataclasses.field(default_factory=GeneralOptions)
    network: NetworkOptions = dataclasses.field(default_factory=NetworkOptions)
    experimental: ExperimentalOptions = dataclasses.field(
        default_factory=ExperimentalOptions
    )
    faults: FaultOptions = dataclasses.field(default_factory=FaultOptions)
    hosts: list[HostOptions] = dataclasses.field(default_factory=list)
    # columnar table spec (config/columnar.py ColumnarSpec), set by the
    # columnar factories only — never parsed from YAML.  When present,
    # TpuEngine adopts the per-lane tables/initial events wholesale and
    # skips its per-host model walk (the 100k-host startup path).
    columnar: Optional[Any] = None

    # -- parsing ----------------------------------------------------------

    @classmethod
    def from_yaml_file(cls, path: str | Path) -> "ConfigOptions":
        with open(path) as f:
            return cls.from_dict(yaml.safe_load(f))

    @classmethod
    def from_yaml(cls, text: str) -> "ConfigOptions":
        return cls.from_dict(yaml.safe_load(text))

    @classmethod
    def from_dict(cls, doc: dict[str, Any]) -> "ConfigOptions":
        if not isinstance(doc, dict):
            raise ConfigError("config must be a mapping")
        unknown = set(doc) - {
            "general",
            "network",
            "experimental",
            "faults",
            "host_option_defaults",
            "hosts",
        }
        if unknown:
            raise ConfigError(f"unknown top-level config keys: {sorted(unknown)}")

        gen_doc = dict(doc.get("general", {}))
        general = GeneralOptions(
            stop_time=units.parse_time(_require(gen_doc, "stop_time", "general")),
            seed=int(gen_doc.pop("seed", 1)),
            parallelism=int(gen_doc.pop("parallelism", 0)),
            bootstrap_end_time=units.parse_time(gen_doc.pop("bootstrap_end_time", 0)),
            data_directory=str(gen_doc.pop("data_directory", "shadow.data")),
            template_directory=gen_doc.pop("template_directory", None),
            log_level=str(gen_doc.pop("log_level", "info")),
            heartbeat_interval=_opt_time(gen_doc.pop("heartbeat_interval", "1s")),
            progress=bool(gen_doc.pop("progress", False)),
            model_unblocked_syscall_latency=bool(
                gen_doc.pop("model_unblocked_syscall_latency", False)
            ),
        )
        gen_doc.pop("stop_time", None)
        if gen_doc:
            raise ConfigError(f"unknown general options: {sorted(gen_doc)}")

        net_doc = dict(doc.get("network", {}))
        graph_doc = dict(net_doc.pop("graph", {"type": "1_gbit_switch"}))
        gtype = graph_doc.pop("type", "gml")
        graph = GraphOptions(type=gtype)
        if gtype == "gml":
            sources = [k for k in ("file", "inline", "path") if k in graph_doc]
            if len(sources) > 1:
                raise ConfigError(
                    f"gml graph has conflicting sources: {sources}; give one"
                )
            if "file" in graph_doc:
                fd = graph_doc.pop("file")
                graph.file_path = fd["path"] if isinstance(fd, dict) else str(fd)
            elif "inline" in graph_doc:
                graph.inline = str(graph_doc.pop("inline"))
            elif "path" in graph_doc:
                graph.file_path = str(graph_doc.pop("path"))
            else:
                raise ConfigError("gml graph needs 'file' or 'inline'")
        elif gtype != "1_gbit_switch":
            raise ConfigError(f"unknown graph type {gtype!r}")
        if graph_doc:
            raise ConfigError(f"unknown network.graph options: {sorted(graph_doc)}")
        network = NetworkOptions(
            graph=graph,
            use_shortest_path=bool(net_doc.pop("use_shortest_path", True)),
        )
        if net_doc:
            raise ConfigError(f"unknown network options: {sorted(net_doc)}")

        exp_doc = dict(doc.get("experimental", {}))
        experimental = ExperimentalOptions()
        for f in dataclasses.fields(ExperimentalOptions):
            if f.name in exp_doc:
                v = exp_doc.pop(f.name)
                if f.name == "runahead":
                    v = _opt_time(v)
                elif f.name == "tpu_mesh_shape" and v is not None:
                    v = tuple(int(x) for x in v)
                elif f.name in ("socket_send_buffer", "socket_recv_buffer"):
                    v = units.parse_bytes(v)
                setattr(experimental, f.name, v)
        if exp_doc:
            raise ConfigError(f"unknown experimental options: {sorted(exp_doc)}")

        f_doc = dict(doc.get("faults", {}) or {})
        failover = f_doc.pop("failover", None)
        wd = f_doc.pop("watchdog_timeout", None)
        faults = FaultOptions(
            failover=None if failover is None else bool(failover),
            watchdog_timeout=None if wd is None else float(wd),
            events=list(f_doc.pop("events", []) or []),
        )
        if f_doc:
            raise ConfigError(f"unknown faults options: {sorted(f_doc)}")

        defaults = dict(doc.get("host_option_defaults", {}))
        hosts: list[HostOptions] = []
        hosts_doc = doc.get("hosts", {})
        if not isinstance(hosts_doc, dict) or not hosts_doc:
            raise ConfigError("config must define at least one host")
        for name, h in sorted(hosts_doc.items()):
            merged = {**defaults, **(h or {})}
            count = int(merged.pop("count", 1))
            if count > 1 and merged.get("ip_addr") is not None:
                raise ConfigError(
                    f"host {name!r}: ip_addr cannot be combined with count > 1 "
                    "(the replicas would collide on the same IP)"
                )
            base = _parse_host(name, merged)
            if count == 1:
                hosts.append(base)
            else:
                for i in range(1, count + 1):
                    hi = dataclasses.replace(
                        base,
                        hostname=f"{name}{i}",
                        processes=[
                            dataclasses.replace(
                                p, args=list(p.args), environment=dict(p.environment)
                            )
                            for p in base.processes
                        ],
                    )
                    hosts.append(hi)
        return cls(
            general=general,
            network=network,
            experimental=experimental,
            faults=faults,
            hosts=hosts,
        )

    # -- overrides (CLI layer) -------------------------------------------

    _TIME_FIELDS = {"stop_time", "bootstrap_end_time", "runahead", "heartbeat_interval"}
    _BYTE_FIELDS = {"socket_send_buffer", "socket_recv_buffer", "pcap_capture_size"}

    def apply_overrides(self, overrides: dict[str, Any]) -> None:
        """Apply dotted-key overrides, e.g. {'general.seed': 7,
        'experimental.network_backend': 'tpu'} — the CLI merge layer.
        Values are coerced to the target field's type (CLI values arrive as
        strings)."""
        for key, value in overrides.items():
            section, _, field = key.partition(".")
            target = getattr(self, section, None)
            if target is None or not dataclasses.is_dataclass(target):
                raise ConfigError(f"unknown config option {key!r}")
            fields = {f.name: f for f in dataclasses.fields(target)}
            if field not in fields:
                raise ConfigError(f"unknown config option {key!r}")
            if value is not None:
                if field in self._TIME_FIELDS:
                    value = units.parse_time(value)
                elif field in self._BYTE_FIELDS:
                    value = units.parse_bytes(value)
                elif field == "tpu_mesh_shape":
                    if isinstance(value, str):
                        value = tuple(int(x) for x in value.split(",") if x)
                    else:
                        value = tuple(int(x) for x in value)
                else:
                    current = getattr(target, field)
                    if isinstance(current, bool):
                        value = (
                            value
                            if isinstance(value, bool)
                            else str(value).lower() in ("1", "true", "yes", "on")
                        )
                    elif isinstance(current, int):
                        value = int(value)
                    elif isinstance(current, float):
                        value = float(value)
            setattr(target, field, value)

    def validate(self) -> None:
        if self.general.stop_time <= 0:
            raise ConfigError("general.stop_time must be > 0")
        if self.experimental.network_backend not in ("cpu", "tpu"):
            raise ConfigError("experimental.network_backend must be cpu|tpu")
        if self.experimental.scheduler not in (
            "thread-per-core",
            "thread-per-host",
        ):
            raise ConfigError(
                "experimental.scheduler must be thread-per-core|thread-per-host"
            )
        names = [h.hostname for h in self.hosts]
        if len(set(names)) != len(names):
            raise ConfigError("duplicate hostnames")
        for h in self.hosts:
            if h.congestion not in ("reno", "cubic"):
                raise ConfigError(
                    f"host {h.hostname!r}: congestion must be reno|cubic, "
                    f"got {h.congestion!r}"
                )
        if self.experimental.hybrid_fuse_k < 1:
            raise ConfigError("experimental.hybrid_fuse_k must be >= 1")
        if not 0.0 <= self.experimental.hybrid_fuse_warn_fraction <= 1.0:
            raise ConfigError(
                "experimental.hybrid_fuse_warn_fraction must be in [0, 1]"
            )
        if self.experimental.checkpoint_every_windows < 0:
            raise ConfigError(
                "experimental.checkpoint_every_windows must be >= 0"
            )
        if self.experimental.checkpoint_keep < 1:
            raise ConfigError("experimental.checkpoint_keep must be >= 1")
        if self.experimental.worker_heartbeat_s <= 0:
            raise ConfigError(
                "experimental.worker_heartbeat_s must be > 0 (wall seconds)"
            )
        if self.experimental.worker_restart_max < 0:
            raise ConfigError("experimental.worker_restart_max must be >= 0")
        if self.experimental.dispatch_retry_max < 0:
            raise ConfigError("experimental.dispatch_retry_max must be >= 0")
        if self.experimental.flowtrace_capacity < 1:
            raise ConfigError("experimental.flowtrace_capacity must be >= 1")
        if self.experimental.sweep_size < 0:
            raise ConfigError("experimental.sweep_size must be >= 0")
        if self.experimental.mesh_devices < 0:
            raise ConfigError(
                "experimental.mesh_devices must be >= 0 (0 = single-device)"
            )
        if (
            self.experimental.sweep_spec is not None
            and not str(self.experimental.sweep_spec).strip()
        ):
            raise ConfigError(
                "experimental.sweep_spec must be a spec file path (or unset)"
            )
        if not 0.0 <= self.experimental.flowtrace_sample <= 1.0:
            raise ConfigError("experimental.flowtrace_sample must be in [0, 1]")
        if self.experimental.interface_qdisc not in ("fifo", "round-robin"):
            raise ConfigError(
                "experimental.interface_qdisc must be fifo|round-robin, "
                f"got {self.experimental.interface_qdisc!r}"
            )
        if self.faults.watchdog_timeout is not None and (
            self.faults.watchdog_timeout <= 0
        ):
            raise ConfigError("faults.watchdog_timeout must be > 0 (wall seconds)")
        if self.faults.events:
            from ..faults.schedule import FaultConfigError

            try:
                sched = self.faults.schedule()
            except FaultConfigError as e:
                raise ConfigError(f"faults.events: {e}")
            for ev in sched.events:
                if ev.at < self.general.bootstrap_end_time:
                    raise ConfigError(
                        f"faults.events: {ev.kind} at {ev.at} ns lies inside "
                        "the loss-free bootstrap window "
                        f"(bootstrap_end_time={self.general.bootstrap_end_time} "
                        "ns); fault drops would be silently exempted"
                    )


def _require(doc: dict[str, Any], key: str, section: str) -> Any:
    if key not in doc:
        raise ConfigError(f"{section}.{key} is required")
    return doc[key]


def _opt_time(v: Any) -> Optional[int]:
    return None if v is None else units.parse_time(v)


def _parse_final_state(v: Any, host: str) -> Any:
    """Validate/normalize expected_final_state at parse time: "running",
    {exited: code}, or {signaled: SIG} (signal normalized like
    shutdown_signal) — a typo must fail the config, not the whole run."""
    if v in ("running", "exited"):
        return v
    if isinstance(v, dict) and len(v) == 1:
        if "exited" in v:
            return {"exited": int(v["exited"])}
        if "signaled" in v:
            return {"signaled": _parse_signal(v["signaled"], host)}
        if "running" in v:
            return "running"
    raise ConfigError(
        f"host {host!r}: expected_final_state must be 'running', "
        f"{{exited: CODE}}, or {{signaled: SIG}}; got {v!r}"
    )


def _parse_signal(v: Any, host: str) -> str:
    """Validate a signal name (or number) at parse time — a typo'd
    shutdown_signal must not silently become SIGTERM."""
    import signal as _sig

    if isinstance(v, int):
        try:
            return _sig.Signals(v).name
        except ValueError:
            raise ConfigError(f"host {host!r}: unknown signal number {v}")
    name = str(v).upper()
    if not name.startswith("SIG"):
        name = "SIG" + name
    if not hasattr(_sig, name) or not isinstance(getattr(_sig, name), _sig.Signals):
        raise ConfigError(f"host {host!r}: unknown shutdown_signal {v!r}")
    return name


def _parse_host(name: str, doc: dict[str, Any]) -> HostOptions:
    doc = dict(doc)
    procs = []
    for p in doc.pop("processes", []):
        p = dict(p)
        args = p.pop("args", [])
        if isinstance(args, str):
            args = args.split()
        procs.append(
            ProcessOptions(
                path=str(p.pop("path")),
                args=[str(a) for a in args],
                environment={str(k): str(v) for k, v in p.pop("environment", {}).items()},
                start_time=units.parse_time(p.pop("start_time", 0)),
                shutdown_time=_opt_time(p.pop("shutdown_time", None)),
                shutdown_signal=_parse_signal(p.pop("shutdown_signal", "SIGTERM"), name),
                expected_final_state=_parse_final_state(
                    p.pop("expected_final_state", {"exited": 0}), name
                ),
            )
        )
        if p:
            raise ConfigError(f"unknown process options on host {name!r}: {sorted(p)}")
    bw_down = doc.pop("bandwidth_down", None)
    bw_up = doc.pop("bandwidth_up", None)
    host = HostOptions(
        hostname=name,
        network_node_id=int(doc.pop("network_node_id", 0)),
        ip_addr=doc.pop("ip_addr", None),
        bandwidth_down=units.parse_bandwidth(bw_down) if bw_down is not None else None,
        bandwidth_up=units.parse_bandwidth(bw_up) if bw_up is not None else None,
        processes=procs,
        log_level=doc.pop("log_level", None),
        pcap_enabled=bool(doc.pop("pcap_enabled", False)),
        pcap_capture_size=units.parse_bytes(doc.pop("pcap_capture_size", 65535)),
        congestion=str(doc.pop("congestion", "reno")),
        count=1,
    )
    if doc:
        raise ConfigError(f"unknown host options on {name!r}: {sorted(doc)}")
    return host
