"""Managed-process scenario factories (real OS binaries under the shim).

The BASELINE.md evaluation ladder's config #5 is a Tor-shaped relay
topology (the reference's 500-relay chutney networks,
docs/getting_started_tor.md, src/test/tor/minimal/); this module builds
the self-contained analog from the repo's own native apps — no external
tools — so the bench and the scale gate measure the MANAGED path (the
workload class the reference's 6.38x was measured on,
/root/reference/MyTest/SUMMARY.md:5-9):

- an origin host running ``tcpecho server`` (epoll echo);
- ``chains`` three-relay chains (guard -> middle -> exit -> origin) of
  ``relay`` processes (poll-based TCP forwarding, the minimal Tor relay
  shape);
- per chain, ``clients_per_chain`` ``tcpecho hclient`` clients that
  resolve their guard by name and pump ``rounds`` echo round-trips of
  ``size`` bytes through the full chain;
- ``peers`` tgen-mesh model hosts keeping background datagram load on
  the same graph.
"""

from __future__ import annotations

from pathlib import Path

from .options import ConfigOptions

REPO = Path(__file__).resolve().parents[2]
BUILD = REPO / "native" / "build"


def managed_chain_config(
    data_dir: str | Path,
    chains: int = 8,
    clients_per_chain: int = 2,
    peers: int = 40,
    sim_seconds: int = 30,
    rounds: int = 20,
    size: int = 4096,
    gap_ms: int = 50,
    seed: int = 42,
    parallelism: int = 1,
    backend: str = "cpu",
    hybrid_workers: int = 1,
) -> ConfigOptions:
    """Relay-chain scenario config.  Managed process count =
    ``1 + 3*chains + chains*clients_per_chain``; host count adds
    ``peers`` model hosts.

    ``backend="tpu"`` selects the HYBRID engine (managed hosts' syscall
    plane on host CPU, every packet on the TPU lanes);
    ``hybrid_workers`` then picks the syscall-servicing parallelism
    (1 = serial, 0 = one worker per core, N = exactly N workers)."""
    n_clients = chains * clients_per_chain
    hosts = [
        f"""
  origin:
    network_node_id: 0
    processes:
      - path: {BUILD / 'tcpecho'}
        args: [server, "8080", "{n_clients}"]
        expected_final_state: {{exited: 0}}
"""
    ]
    for c in range(chains):
        hosts.append(f"""
  exit{c}:
    network_node_id: 1
    processes:
      - path: {BUILD / 'relay'}
        args: ["9000", origin, "8080"]
        start_time: 500ms
        expected_final_state: running
  middle{c}:
    network_node_id: 2
    processes:
      - path: {BUILD / 'relay'}
        args: ["9000", exit{c}, "9000"]
        start_time: 700ms
        expected_final_state: running
  guard{c}:
    network_node_id: 2
    processes:
      - path: {BUILD / 'relay'}
        args: ["9000", middle{c}, "9000"]
        start_time: 900ms
        expected_final_state: running
""")
        for k in range(clients_per_chain):
            hosts.append(f"""
  client{c}x{k}:
    network_node_id: 3
    processes:
      - path: {BUILD / 'tcpecho'}
        args: [hclient, guard{c}, "9000", "{rounds}", "{size}", "{gap_ms}"]
        start_time: {1500 + 400 * k + 97 * c}ms
        expected_final_state: {{exited: 0}}
""")
    if peers:
        hosts.append(f"""
  peer:
    count: {peers}
    network_node_id: 1
    processes:
      - path: tgen-mesh
        args: [--interval, 50ms, --size, "600"]
        start_time: 0 s
""")
    return ConfigOptions.from_yaml(f"""
general:
  stop_time: {sim_seconds}s
  seed: {seed}
  data_directory: {data_dir}
  heartbeat_interval: null
  parallelism: {parallelism}
experimental:
  network_backend: {backend}
  hybrid_workers: {hybrid_workers}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        node [ id 1 host_bandwidth_up "50 Mbit" host_bandwidth_down "50 Mbit" ]
        node [ id 2 host_bandwidth_up "50 Mbit" host_bandwidth_down "50 Mbit" ]
        node [ id 3 host_bandwidth_up "20 Mbit" host_bandwidth_down "20 Mbit" ]
        edge [ source 0 target 0 latency "1 ms" ]
        edge [ source 1 target 1 latency "2 ms" ]
        edge [ source 2 target 2 latency "3 ms" ]
        edge [ source 3 target 3 latency "2 ms" ]
        edge [ source 0 target 1 latency "8 ms" ]
        edge [ source 1 target 2 latency "15 ms" ]
        edge [ source 2 target 3 latency "10 ms" ]
      ]
hosts:
{''.join(hosts)}
""")


def managed_proc_count(chains: int, clients_per_chain: int) -> int:
    return 1 + 3 * chains + chains * clients_per_chain


def managed_relay_chains_large(
    data_dir: str | Path,
    chains: int = 25,
    clients_per_chain: int = 3,
    peers: int = 1000,
    sim_seconds: int = 10,
    rounds: int = 8,
    size: int = 2048,
    hybrid_workers: int = 0,
    seed: int = 42,
) -> ConfigOptions:
    """The HYBRID flagship scenario (BENCH_r06 `hybrid_*` keys, ROADMAP
    open item 1): 100+ managed OS processes (default 151 = 25 three-relay
    chains + 75 clients + origin) whose syscall plane runs across
    ``hybrid_workers`` processes, over 1k+ lane hosts (default 1000 tgen
    peers) whose data plane — and every managed packet — rides the TPU
    lanes.  This is the workload class the reference's 6.38x headline was
    measured on, at the reference's own scale point."""
    return managed_chain_config(
        data_dir,
        chains=chains,
        clients_per_chain=clients_per_chain,
        peers=peers,
        sim_seconds=sim_seconds,
        rounds=rounds,
        size=size,
        seed=seed,
        backend="tpu",
        hybrid_workers=hybrid_workers,
    )


def managed_relay_chains_gate(
    data_dir: str | Path,
    hybrid_workers: int = 2,
    sim_seconds: int = 8,
    backend: str = "tpu",
    seed: int = 42,
) -> ConfigOptions:
    """The SHADOW_TPU_SCALE-gated small sibling of
    :func:`managed_relay_chains_large`: the same shape at 16 managed
    processes over 60 lane hosts, sized so the gate exercises the full
    hybrid seam (parallel syscall servicing included) on the CPU JAX
    platform — no TPU time needed (tests/test_hybrid_mp.py)."""
    return managed_chain_config(
        data_dir,
        chains=3,
        clients_per_chain=2,
        peers=60,
        sim_seconds=sim_seconds,
        rounds=3,
        size=1024,
        seed=seed,
        backend=backend,
        hybrid_workers=hybrid_workers,
    )
