"""Columnar scenario construction — the 100k-host startup path.

The classic factories (presets.py) describe every host as YAML that
``ConfigOptions.from_dict`` expands into per-host ``HostOptions`` objects,
and ``TpuEngine.__init__`` then walks host-by-host, instantiating a model
object per host to fill the per-lane parameter tables.  At 10^5 hosts that
Python loop — not the device program — dominates startup (ROADMAP item 5).

This module replaces both loops with NumPy table construction:

* ``ColumnarSpec`` carries the per-lane model/parameter columns and the
  initial-event table as arrays; ``TpuEngine`` adopts them wholesale
  (``cfg.columnar``) and skips its per-host walk entirely;
* ``ColumnarHosts`` is a lazy ``Sequence[HostOptions]`` — hostname/DNS/
  bandwidth consumers (``backend.setup.build_world``, ``validate``)
  iterate materialized rows on demand, but no 100k-object list is ever
  held, and each group's ``ProcessOptions`` list is shared, so a
  columnar config remains a complete, classic-readable description of
  the same scenario (tests/test_multichip.py pins table equality
  against the classic factory).

Columnar configs are lane-only: the hybrid backend executes real process
objects host-side, which is exactly the per-host work this path deletes.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np

from ..models.base import create_model
from .options import ConfigOptions, HostOptions, ProcessOptions

__all__ = ["ColumnarHosts", "ColumnarSpec", "columnar_mesh_config"]

# lanes.py model/kind constants, restated here to keep this module
# importable without JAX (tests assert they match lanes')
M_TGEN_MESH = 2
EV_LOCAL = 1


@dataclasses.dataclass(frozen=True)
class ColumnarSpec:
    """Per-lane model tables + initial events as columns.

    Model columns (all ``[n]``): ``model``/``p_size``/``p_peer``/
    ``recv_mult`` int32; ``p_interval``/``p_count``/``p_stride``/
    ``local_seq0`` int64.  Event columns (all ``[E]`` int64):
    ``(lane, t, kind, src, seq, size)`` — the exact rows the classic
    per-host walk would have appended to ``init_events``.
    """

    model: np.ndarray
    p_size: np.ndarray
    p_interval: np.ndarray
    p_peer: np.ndarray
    p_count: np.ndarray
    p_stride: np.ndarray
    recv_mult: np.ndarray
    local_seq0: np.ndarray
    ev_lane: np.ndarray
    ev_t: np.ndarray
    ev_kind: np.ndarray
    ev_src: np.ndarray
    ev_seq: np.ndarray
    ev_size: np.ndarray

    def model_columns(self, n: int):
        """The 8 per-lane columns, shape-checked against the host count
        (the order matches TpuEngine.__init__'s local table names)."""
        i32 = {"model", "p_size", "p_peer", "recv_mult"}
        cols = []
        for name in (
            "model", "p_size", "p_interval", "p_peer", "p_count",
            "p_stride", "recv_mult", "local_seq0",
        ):
            a = np.asarray(
                getattr(self, name),
                dtype=np.int32 if name in i32 else np.int64,
            )
            if a.shape != (n,):
                raise ValueError(
                    f"columnar column {name!r} has shape {a.shape}, "
                    f"config has {n} hosts"
                )
            cols.append(a)
        return tuple(cols)

    def event_columns(self):
        """The 6 initial-event columns as int64 arrays."""
        cols = tuple(
            np.asarray(getattr(self, name), dtype=np.int64)
            for name in (
                "ev_lane", "ev_t", "ev_kind", "ev_src", "ev_seq", "ev_size"
            )
        )
        e = cols[0].shape
        for name, a in zip(("ev_t", "ev_kind", "ev_src", "ev_seq",
                            "ev_size"), cols[1:]):
            if a.shape != e:
                raise ValueError(
                    f"columnar event column {name!r} has shape {a.shape}, "
                    f"ev_lane has {e}"
                )
        return cols


class ColumnarHosts(Sequence):
    """Lazy ``HostOptions`` rows for columnar configs.

    ``groups`` is a list of ``(count, prefix, node_id, processes)``; row
    ``i`` of a group materializes as ``HostOptions(hostname=f"{prefix}
    {i+1}", ...)`` on access — the same naming the classic ``count:``
    expansion produces — sharing the group's ``ProcessOptions`` list
    rather than deep-copying it per host."""

    def __init__(self, groups):
        self._groups = []
        base = 0
        for count, prefix, node_id, procs in groups:
            self._groups.append((base, int(count), prefix, node_id, procs))
            base += int(count)
        self._len = base

    def __len__(self) -> int:
        return self._len

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(self._len))]
        if i < 0:
            i += self._len
        if not 0 <= i < self._len:
            raise IndexError(i)
        for base, count, prefix, node_id, procs in self._groups:
            if i < base + count:
                return HostOptions(
                    hostname=f"{prefix}{i - base + 1}",
                    network_node_id=node_id,
                    processes=procs,
                )
        raise IndexError(i)  # pragma: no cover


def columnar_mesh_config(
    n_hosts: int,
    sim_seconds: int = 10,
    latency: str = "10 ms",
    interval: str = "10ms",
    size: int = 1428,
    queue_capacity: int | None = None,
    pops_per_round: int | None = None,
    mesh_devices: int = 0,
    seed: int = 1,
) -> ConfigOptions:
    """The flagship tgen all-to-all mesh (presets.flagship_mesh_config's
    pure-UDP shape) built columnar: same hosts, same tables, same events
    — but O(1) Python objects instead of O(n_hosts).  This is the
    100k-host multi-chip bench scenario (scripts/bench.py ``multichip_*``
    keys); ``mesh_devices`` presets ``experimental.mesh_devices``."""
    cfg = ConfigOptions.from_yaml(f"""
general:
  stop_time: {sim_seconds} s
  seed: {seed}
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0  host_bandwidth_up "1 Gbit"  host_bandwidth_down "1 Gbit" ]
        edge [ source 0  target 0  latency "{latency}" ]
      ]
experimental:
  network_backend: tpu
hosts:
  placeholder: {{}}
""")
    args = ["--interval", str(interval), "--size", str(size)]
    # ONE model instance parses the args — the per-host loop's source of
    # truth for interval/size/stride stays authoritative
    m = create_model("tgen-mesh", list(args))
    procs = [ProcessOptions(path="tgen-mesh", args=args, start_time=0)]
    cfg.hosts = ColumnarHosts([(n_hosts, "peer", 0, procs)])

    n = n_hosts
    hid = np.arange(n, dtype=np.int64)
    cfg.columnar = ColumnarSpec(
        model=np.full(n, M_TGEN_MESH, dtype=np.int32),
        p_size=np.full(n, m.size, dtype=np.int32),
        p_interval=np.full(n, m.interval, dtype=np.int64),
        p_peer=np.zeros(n, dtype=np.int32),
        p_count=np.zeros(n, dtype=np.int64),
        p_stride=np.full(n, m.stride, dtype=np.int64),
        recv_mult=np.ones(n, dtype=np.int32),
        local_seq0=np.ones(n, dtype=np.int64),
        # one LOCAL start marker per host at t=0 (size -1 = timer driver)
        ev_lane=hid,
        ev_t=np.zeros(n, dtype=np.int64),
        ev_kind=np.full(n, EV_LOCAL, dtype=np.int64),
        ev_src=hid,
        ev_seq=np.zeros(n, dtype=np.int64),
        ev_size=np.full(n, -1, dtype=np.int64),
    )
    if queue_capacity is not None:
        cfg.experimental.tpu_lane_queue_capacity = queue_capacity
    if pops_per_round is not None:
        cfg.experimental.tpu_events_per_round = pops_per_round
    if mesh_devices:
        cfg.experimental.mesh_devices = mesh_devices
    return cfg
