"""Typed SI quantities for configuration values.

User-facing parity with the reference's ``utility/units.rs``: config fields
accept strings like ``"10 ms"``, ``"1 Gbit"``, ``"16 MiB"`` (space optional)
or bare numbers.  Everything normalizes to integers — nanoseconds, bits/sec,
bytes — because integer quantities are the determinism currency of the whole
simulator (see core/time.py).
"""

from __future__ import annotations

import re

from ..core import time as stime

_NUM_RE = re.compile(r"^\s*([0-9]+(?:\.[0-9]+)?)\s*([a-zA-Zμ]*)\s*$")

_TIME_UNITS = {
    "": stime.NANOS_PER_SEC,  # bare numbers in time positions mean seconds
    "ns": 1,
    "nsec": 1,
    "us": stime.NANOS_PER_MICRO,
    "usec": stime.NANOS_PER_MICRO,
    "μs": stime.NANOS_PER_MICRO,
    "ms": stime.NANOS_PER_MILLI,
    "msec": stime.NANOS_PER_MILLI,
    "s": stime.NANOS_PER_SEC,
    "sec": stime.NANOS_PER_SEC,
    "second": stime.NANOS_PER_SEC,
    "seconds": stime.NANOS_PER_SEC,
    "m": stime.NANOS_PER_MIN,
    "min": stime.NANOS_PER_MIN,
    "h": stime.NANOS_PER_HOUR,
    "hr": stime.NANOS_PER_HOUR,
    "hour": stime.NANOS_PER_HOUR,
}

_SI = {"": 1, "K": 10**3, "M": 10**6, "G": 10**9, "T": 10**12}
_IEC = {"Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40}
# "k" is the canonical lowercase SI kilo ("300kB"); accept it everywhere K is
for _d in (_SI, _IEC):
    for _k in [k for k in _d if k.startswith("K")]:
        _d["k" + _k[1:]] = _d[_k]


def _bit_units() -> dict[str, int]:
    units: dict[str, int] = {}
    for p, mult in _SI.items():
        units[p + "bit"] = mult
        units[p + "b"] = mult
    for p, mult in _IEC.items():
        units[p + "bit"] = mult
        units[p + "b"] = mult
    return units


def _byte_units() -> dict[str, int]:
    units: dict[str, int] = {}
    for p, mult in _SI.items():
        units[p + "B"] = mult
        if p:
            units[p + "byte"] = mult
            units[p + "bytes"] = mult
    for p, mult in _IEC.items():
        units[p + "B"] = mult
        units[p + "byte"] = mult
        units[p + "bytes"] = mult
    units["B"] = 1
    units["byte"] = 1
    units["bytes"] = 1
    return units


_BIT_UNITS = _bit_units()
_BYTE_UNITS = _byte_units()


class UnitError(ValueError):
    pass


def _split(value: str) -> tuple[float, str]:
    m = _NUM_RE.match(value)
    if not m:
        raise UnitError(f"cannot parse quantity {value!r}")
    return float(m.group(1)), m.group(2)


def parse_time(value: str | int | float) -> int:
    """Parse a time quantity to integer nanoseconds.  Bare numbers are
    seconds (matching the reference's config convention, e.g. ``stop_time:
    10s`` / ``10``)."""
    if isinstance(value, (int, float)):
        return stime.from_secs(value)
    num, unit = _split(value)
    # case-sensitivity doesn't matter for time units; normalize (but keep μ)
    unit_l = unit.lower() if unit != "μs" else unit
    if unit_l not in _TIME_UNITS:
        raise UnitError(f"unknown time unit {unit!r} in {value!r}")
    scale = _TIME_UNITS[unit_l]
    if isinstance(num, float) and num != int(num):
        return round(num * scale)
    return int(num) * scale


def parse_bandwidth(value: str | int) -> int:
    """Parse a bandwidth quantity to bits/second.  Accepts ``"1 Gbit"``
    (per-second implied, as in the reference's host bandwidth fields) and
    explicit ``"10 Mbit"`` etc.; bare integers are bits/second."""
    if isinstance(value, int):
        return value
    num, unit = _split(value)
    if unit.endswith("ps"):  # "Mbps" -> "Mb", "bps" -> "b"
        unit = unit[:-2]
    if unit not in _BIT_UNITS:
        raise UnitError(f"unknown bandwidth unit {unit!r} in {value!r}")
    scale = _BIT_UNITS[unit]
    if isinstance(num, float) and num != int(num):
        return round(num * scale)
    return int(num) * scale


def parse_bytes(value: str | int) -> int:
    """Parse a size quantity to bytes (``"16 MiB"``, ``"1500 B"``, bare
    numbers — int or digit string — are bytes)."""
    if isinstance(value, int):
        return value
    num, unit = _split(value)
    if unit == "":
        return round(num)
    if unit not in _BYTE_UNITS:
        raise UnitError(f"unknown size unit {unit!r} in {value!r}")
    scale = _BYTE_UNITS[unit]
    if isinstance(num, float) and num != int(num):
        return round(num * scale)
    return int(num) * scale
