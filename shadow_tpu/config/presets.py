"""Canonical workload presets shared by the bench and the driver entry
points, so the program the driver compile-checks is the one the bench times
(BASELINE.md north-star configs)."""

from __future__ import annotations

from .options import ConfigOptions


def flagship_mesh_config(
    n_hosts: int,
    sim_seconds: int = 10,
    latency: str = "10 ms",
    interval: str = "10ms",
    size: int = 1428,
    queue_capacity: int | None = None,
    pops_per_round: int | None = None,
    stream_pairs: int = 0,
    stream_bytes: int = 50_000_000,
    backend: str = "tpu",
    seed: int = 1,
) -> ConfigOptions:
    """The tgen all-to-all mesh over a single switch (BASELINE config #4):
    every host sends a ``size``-byte datagram every ``interval`` to a
    round-robin peer; lookahead window = link ``latency``.

    ``stream_pairs`` > 0 makes it the MIXED TCP/UDP mesh of the north-star
    config: that many stream-client -> stream-server lane-TCP flows
    (handshake, NewReno, RTO — lanes_stream.py on device) run alongside
    the UDP mesh, each streaming ``stream_bytes``; the mesh's round-robin
    spray crosses the stream lanes, which must ignore it exactly like the
    CPU oracle does."""
    k = stream_pairs
    if 2 * k >= n_hosts:
        raise ValueError("stream_pairs must leave room for mesh hosts")
    hosts = [
        f"""
  peer:
    count: {n_hosts - 2 * k}
    network_node_id: 0
    processes:
      - path: tgen-mesh
        args: --interval {interval} --size {size}
        start_time: 0 s
"""
    ]
    for i in range(k):
        hosts.append(
            f"""
  sc{i:05d}:
    network_node_id: 0
    processes:
      - path: stream-client
        args: --server ss{i:05d} --size {stream_bytes}
        start_time: 0 s
  ss{i:05d}:
    network_node_id: 0
    processes:
      - path: stream-server
        start_time: 0 s
"""
        )
    cfg = ConfigOptions.from_yaml(
        f"""
general:
  stop_time: {sim_seconds} s
  seed: {seed}
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0  host_bandwidth_up "1 Gbit"  host_bandwidth_down "1 Gbit" ]
        edge [ source 0  target 0  latency "{latency}" ]
      ]
experimental:
  network_backend: {backend}
hosts:
{''.join(hosts)}
"""
    )
    if queue_capacity is not None:
        cfg.experimental.tpu_lane_queue_capacity = queue_capacity
    if pops_per_round is not None:
        cfg.experimental.tpu_events_per_round = pops_per_round
    return cfg


def transfer_pair_config(
    size_bytes: int = 50_000_000, sim_seconds: int = 60,
    backend: str = "tpu", seed: int = 1,
) -> ConfigOptions:
    """BASELINE config #1: a 2-host client->server transfer over one link
    (the reference's examples/docs/basic-file-transfer shape), as a
    lane-TCP stream flow."""
    return ConfigOptions.from_yaml(f"""
general:
  stop_time: {sim_seconds} s
  seed: {seed}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
        node [ id 1 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
        edge [ source 0 target 1 latency "10 ms" ]
      ]
experimental:
  network_backend: {backend}
  tpu_lane_queue_capacity: 128
hosts:
  c:
    network_node_id: 0
    processes:
      - path: stream-client
        args: --server s --size {size_bytes}
  s:
    network_node_id: 1
    processes:
      - path: stream-server
""")


def udp_star_config(
    n_hosts: int = 100,
    sim_seconds: int = 10,
    interval: str = "10ms",
    size: int = 1428,
    backend: str = "tpu",
    seed: int = 1,
) -> ConfigOptions:
    """BASELINE config #2: a UDP-only tgen star — n-1 clients send fixed
    datagrams to one server host (single switch, no TCP state).  The
    server lane's queue must hold every in-flight client datagram, so
    capacity scales with the fan-in (the clients all fire each interval)."""
    capacity = max(64, 2 * n_hosts)
    return ConfigOptions.from_yaml(f"""
general:
  stop_time: {sim_seconds} s
  seed: {seed}
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
        edge [ source 0 target 0 latency "5 ms" ]
      ]
experimental:
  network_backend: {backend}
  tpu_lane_queue_capacity: {capacity}
hosts:
  srv:
    network_node_id: 0
    processes:
      - path: tgen-server
  cli:
    count: {n_hosts - 1}
    network_node_id: 0
    processes:
      - path: tgen-client
        args: --server srv --interval {interval} --size {size}
""")


def mixed_flagship_config(
    n_hosts: int, sim_seconds: int = 5, backend: str = "tpu",
    seed: int = 1,
) -> ConfigOptions:
    """The MIXED TCP/UDP mesh at its north-star tuning (the bench's and
    the probe/HLO scripts' single source of truth): 1 stream pair per 100
    hosts streaming 2 MB across the datagram mesh.

    Tuning (measured on v5e, round-5 probes — UTIL_r05.json is the
    ground truth): with the TIERED stream backend the [N] side needs
    only the pure mesh's queue shape (capacity 16, 2 pops/iter — the
    pre-tier 48/4 was paying ~46% extra per iteration), and the tier
    drains at 16 events/iter (8 left ~60% more iterations per window;
    24 made each iteration dearer than the iterations it saved)."""
    cfg = flagship_mesh_config(
        n_hosts, sim_seconds=sim_seconds, queue_capacity=16,
        pops_per_round=2, stream_pairs=max(n_hosts // 100, 1),
        stream_bytes=2_000_000, backend=backend, seed=seed,
    )
    # one-to-one pairing puts stream arrivals on the split exchange, so
    # the main cross block only carries the mesh's permutation spray
    # (strict mode would raise if this ever overflowed)
    cfg.experimental.tpu_cross_capacity = 8
    cfg.experimental.tpu_stream_events_per_round = 16
    return cfg
