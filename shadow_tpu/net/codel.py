"""CoDel active queue management (RFC 8289 shaped, all-integer).

Scalar reference implementation of the spec in docs/SEMANTICS.md; the TPU
lane backend runs the identical arithmetic vectorized.  Counterpart of the
reference's router CoDel queue (src/main/network/router/codel_queue.rs:20-34,
TARGET=10ms / INTERVAL=100ms).

The RFC's ``interval / sqrt(drop_count)`` control law is realized through a
precomputed integer table so both backends divide identically (no device
float sqrt in the control path).
"""

from __future__ import annotations

import dataclasses
import math

from ..core.time import NANOS_PER_MILLI

TARGET_NS = 10 * NANOS_PER_MILLI
INTERVAL_NS = 100 * NANOS_PER_MILLI

#: CODEL_DIV[k] = round(INTERVAL / sqrt(k)) for k in 0..=1024 (k=0 unused);
#: drop_count beyond 1024 clamps to the last entry.
DIV_TABLE_SIZE = 1025


def _build_div_table() -> list[int]:
    table = [INTERVAL_NS]  # k=0 placeholder
    for k in range(1, DIV_TABLE_SIZE):
        table.append(round(INTERVAL_NS / math.sqrt(k)))
    return table


CODEL_DIV: list[int] = _build_div_table()


@dataclasses.dataclass
class CoDel:
    """Per-host inbound AQM state (see SEMANTICS.md for the exact law)."""

    first_above_time: int = 0
    drop_next: int = 0
    drop_count: int = 0
    dropping: bool = False

    def offer(self, t_deliver: int, sojourn_ns: int) -> bool:
        """Process one inbound packet (in arrival order); True = drop it."""
        ok_to_drop = False
        if sojourn_ns < TARGET_NS:
            self.first_above_time = 0
        else:
            if self.first_above_time == 0:
                self.first_above_time = t_deliver + INTERVAL_NS
            elif t_deliver >= self.first_above_time:
                ok_to_drop = True

        if self.dropping:
            if not ok_to_drop:
                self.dropping = False
            elif t_deliver >= self.drop_next:
                self.drop_count += 1
                self.drop_next += CODEL_DIV[min(self.drop_count, DIV_TABLE_SIZE - 1)]
                return True
        elif ok_to_drop and (
            t_deliver - self.drop_next < INTERVAL_NS
            or t_deliver - self.first_above_time >= INTERVAL_NS
        ):
            self.dropping = True
            if self.drop_count > 2 and t_deliver - self.drop_next < INTERVAL_NS:
                self.drop_count = 2
            else:
                self.drop_count = 1
            self.drop_next = t_deliver + CODEL_DIV[self.drop_count]
            return True
        return False
