"""Minimal GML (Graph Modelling Language) parser.

Parses the subset of GML that network graphs use (the reference ships a
dedicated ``gml-parser`` crate for the same purpose): nested ``key [ ... ]``
records, string/int/float scalars, and the conventional top-level shape

    graph [ directed 0  node [ id 0 ... ]  edge [ source 0 target 0 ... ] ]

Returns plain dicts; interpretation (units, validation) happens in
:mod:`shadow_tpu.net.graph`.
"""

from __future__ import annotations

import re
from typing import Any

_TOKEN_RE = re.compile(
    r"""
    \s*(?:
        (?P<comment>\#[^\n]*)
      | (?P<string>"(?:[^"\\]|\\.)*")
      | (?P<lbrack>\[)
      | (?P<rbrack>\])
      | (?P<number>[+-]?(?:\d+\.\d*|\.\d+|\d+)(?:[eE][+-]?\d+)?)
      | (?P<key>[A-Za-z_][A-Za-z0-9_]*)
    )
    """,
    re.VERBOSE,
)


class GmlError(ValueError):
    pass


def _tokenize(text: str):
    pos = 0
    n = len(text)
    while pos < n:
        m = _TOKEN_RE.match(text, pos)
        if not m:
            if text[pos:].strip() == "":
                return
            raise GmlError(f"bad GML syntax at offset {pos}: {text[pos:pos+40]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "comment":
            continue
        yield kind, m.group(kind)
    return


class _Parser:
    def __init__(self, text: str) -> None:
        self.tokens = list(_tokenize(text))
        self.i = 0

    def peek(self):
        return self.tokens[self.i] if self.i < len(self.tokens) else (None, None)

    def next(self):
        tok = self.peek()
        self.i += 1
        return tok

    def parse_record(self) -> dict[str, Any]:
        """Parse a ``[ key value ... ]`` body into a dict.  Repeated keys
        (``node``, ``edge``) accumulate into lists."""
        out: dict[str, Any] = {}
        while True:
            kind, val = self.next()
            if kind is None:
                raise GmlError("unexpected end of input: unbalanced '['")
            if kind == "rbrack":
                return out
            if kind != "key":
                raise GmlError(f"expected key, got {val!r}")
            key = val
            vkind, vval = self.next()
            if vkind == "lbrack":
                value: Any = self.parse_record()
            elif vkind == "string":
                value = vval[1:-1].replace('\\"', '"').replace("\\\\", "\\")
            elif vkind == "number":
                value = float(vval) if any(c in vval for c in ".eE") else int(vval)
            else:
                raise GmlError(f"expected value for key {key!r}, got {vval!r}")
            if key in ("node", "edge"):
                out.setdefault(key + "s", []).append(value)
            else:
                out[key] = value


def parse_gml(text: str) -> dict[str, Any]:
    """Parse GML text; returns the ``graph`` record as a dict with ``nodes``
    and ``edges`` lists."""
    p = _Parser(text)
    kind, val = p.next()
    if kind != "key" or val != "graph":
        raise GmlError("GML must start with 'graph ['")
    kind, _ = p.next()
    if kind != "lbrack":
        raise GmlError("expected '[' after 'graph'")
    g = p.parse_record()
    g.setdefault("nodes", [])
    g.setdefault("edges", [])
    return g
