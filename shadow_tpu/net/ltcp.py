"""Lane TCP ("ltcp"): the fixed-size, segment-counting TCP law.

The transport tier that runs **inside the TPU lane program** (SURVEY §7
step 6: "fixed-size per-connection state records so TCP state can later
live in HBM lanes").  This module is the *scalar* form of the law — the
CPU-backend oracle that the vectorized twin in ``backend/lanes.py`` is
diffed against, exactly like ``net/codel.py`` / ``net/token_bucket.py``.

Relation to the reference: the full sans-I/O byte-stream TCP
(``transport/tcp.py``, rebuilding src/lib/tcp + tcp_cong_reno.c) serves
managed processes and byte-accurate workloads on the CPU backend; *this*
tier trades byte granularity for a fixed-size integer state record per
flow so that thousands of connections advance as masked vector arithmetic
on device.  It is still a real TCP: 3-way handshake, cumulative ACKs,
flow control by a fixed receive window, slow start, congestion avoidance,
fast retransmit / NewReno fast recovery (tcp_cong_reno.c's laws in
segment units), RFC 6298 RTO with exponential backoff and Karn's rule,
and FIN teardown.  Simplifications (documented in docs/SEMANTICS.md):
sequence numbers count MSS-sized *segments*, the receiver accepts only
in-order segments (go-back-N; no SACK/reassembly buffer), every data
segment is ACKed immediately (no delayed ACK), and the receive window is
a constant.

All arithmetic is integer; every decision is a pure function of the flow
record — the vector form applies the same updates under masks.

Sequence-unit space of a flow transferring ``segs`` data segments:

    0            SYN            (client) / SYN-ACK (server)
    1..segs      data           (client only; server's unit 1 is its FIN)
    segs+1       FIN            (client)

Wire segments carry ``(flags, seq, ack)``; ACKs are cumulative in the
peer's unit space.  Control segments cost HDR_BYTES on the wire; data
segment ``i`` costs ``HDR_BYTES + mss`` (the final one
``HDR_BYTES + last_bytes``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..core.time import NEVER

# -- wire flags -------------------------------------------------------------
F_SYN = 1
F_ACK = 2
F_FIN = 4
F_DATA = 8

# -- states (one enum for both roles) ---------------------------------------
CLOSED = 0  # client: not opened yet; server: LISTEN
SYN_SENT = 1  # client sent SYN
SYN_RCVD = 2  # server sent SYN-ACK
ESTAB = 3
FIN_WAIT = 4  # client sent FIN, waits for its ACK + server FIN
LAST_ACK = 5  # server sent FIN, waits for final ACK
DONE = 6

# -- roles ------------------------------------------------------------------
SENDER = 0  # active opener, streams data
RECEIVER = 1  # passive opener, sinks data

# -- congestion control constants (integer, fixed-point cwnd) ---------------
FP = 1024  # cwnd fixed-point: FP units = 1 segment
INIT_CWND_FP = 10 * FP  # RFC 6928 initial window, segment units
INIT_SSTHRESH_FP = 1 << 30
MIN_SSTHRESH_FP = 2 * FP
DUP_THRESH = 3

# -- congestion control algorithms (tcp_cong.c's pluggable interface,
# realized as a per-flow selector so the vector form stays branch-free) -----
CC_RENO = 0
CC_CUBIC = 1
CC_BY_NAME = {"reno": CC_RENO, "cubic": CC_CUBIC}

# CUBIC (RFC 9438 / tcp_cubic.c) as pure int32-safe fixed point.  The
# window law is W(t) = C*(t-K)^3 + W_origin with C = 0.4 segs/s^3 and
# beta = 0.3.  Time is measured in "q units" of 2**20 ns (~1.05 ms) and a
# second is approximated as 2**30 ns (a documented 7.4% stretch: the law
# is DEFINED by this fixed-point algorithm, identically in the scalar and
# vector twins, not by real-valued CUBIC):
CUBIC_BETA_MUL = 717  # ~0.70 * 1024: multiplicative decrease on loss
CUBIC_FC_MUL = 870  # ~0.85 * 1024 = (2-beta)/2: fast-convergence shrink
CUBIC_C_MUL = 410  # ~0.40 * 1024: the C coefficient of the cubic term
# K in q units satisfies K_q^3 = diff_fp * 2**20 / 0.4 = diff_fp * 64*40960,
# so K_q = 4 * icbrt32(diff_fp * 40960); diff_fp <= MAX_CWND_FP keeps the
# argument inside int32 (49152 * 40960 < 2**31)
CUBIC_K_MUL = 40960
CUBIC_D_MAX = 8192  # epoch-age clamp, q units (~8.8 s; window saturates
# far earlier: the cubic term at D_MAX is ~205 segments)
# Constant advertised receive window.  Sized so one full flight (plus
# cross-traffic and timer arms) fits the lane backend's default bounded
# queue capacity with headroom: every in-flight segment is a resident
# event in the receiver's fixed-shape lane queue.  At the simulated
# RTTs this is the per-flow throughput cap (24 * MSS / RTT).
RWND_SEGS = 24
MAX_CWND_FP = 2 * RWND_SEGS * FP  # growth past the window is pointless
# Transmission-opportunity budget: every stimulus ends with an epilogue
# that transmits up to this many window-permitted units (real stacks
# likewise burst the permitted window per ACK).  At RWND_SEGS the window
# always exhausts before the budget, so a same-instant pump event is
# never queued — the lane backend's wide event co-pop relies on that.
PUMP_BURST = RWND_SEGS

# -- RTO constants (RFC 6298, ns) ------------------------------------------
RTO_INIT = 1_000_000_000  # 1 s
RTO_MIN = 200_000_000  # 200 ms (Linux's floor)
RTO_MAX = 60_000_000_000  # 60 s
# Give-up bound (Linux's tcp_retries2 analog): after this many CONSECUTIVE
# timeouts with no forward progress the flow aborts (state -> DONE,
# Emit.aborted) instead of retransmitting forever into a dead link — the
# fault-injection subsystem makes permanently-dark paths a first-class
# scenario.  The backoff counter resets on any new-data ACK.  NOTE: the
# vectorized lane twin (backend/lanes_stream.py) retains unbounded retries;
# the laws diverge only after MAX_RTO_BACKOFFS consecutive timeouts (over
# two minutes of cumulative RTO under the doubling law), far beyond the
# lane backend's supported windows — documented in docs/faults.md.
MAX_RTO_BACKOFFS = 8

HDR_BYTES = 40  # IP (20) + TCP (20) wire overhead per segment


@dataclasses.dataclass
class FlowState:
    """One TCP flow's fixed-size record (every field an integer — the
    vector form stores each as an [N, F] array column)."""

    role: int = SENDER
    state: int = CLOSED
    # transfer shape (static per flow)
    segs: int = 0  # number of data segments (sender side)
    mss: int = 1448
    last_bytes: int = 1448  # payload of the final data segment
    # sequence state (segment units)
    snd_una: int = 0
    snd_nxt: int = 0
    rcv_nxt: int = 0
    # congestion control
    cc: int = CC_RENO  # CC_RENO | CC_CUBIC (static per flow)
    cwnd_fp: int = INIT_CWND_FP
    ssthresh_fp: int = INIT_SSTHRESH_FP
    dup_acks: int = 0
    # CUBIC state (inert under CC_RENO)
    w_max_fp: int = 0  # window size at the last loss event
    cub_origin_fp: int = 0  # the epoch's plateau (W_origin)
    cub_epoch: int = NEVER  # epoch start, ns (NEVER = no epoch yet)
    cub_k_q: int = 0  # K in q units (2**20 ns)
    in_rec: bool = False  # fast recovery (until ack >= recover)
    recover: int = 0  # snd_nxt at loss detection
    max_sent: int = 0  # highest unit ever transmitted + 1 (retransmit marker)
    # RTT estimation (RFC 6298; srtt < 0 = no sample yet)
    srtt: int = -1
    rttvar: int = 0
    rto: int = RTO_INIT
    rtt_seq: int = -1  # unit being timed (-1 = none; Karn's rule)
    rtt_ts: int = 0
    # retransmission timer
    rto_deadline: int = NEVER  # when the pending data times out
    rto_evt: int = NEVER  # time of the queued RTO event (dedup law)
    backoffs: int = 0  # consecutive timeouts since the last new-data ACK
    # stats
    tx_segs: int = 0
    rx_segs: int = 0
    rx_bytes: int = 0
    retransmits: int = 0


@dataclasses.dataclass
class Emit:
    """What one stimulus produces (the scalar form of the lane channels):
    at most one control segment plus a burst of up to PUMP_BURST data
    segments (every handler ends with the transmission-opportunity
    epilogue), plus pump/RTO local-event arms."""

    sends: list = dataclasses.field(default_factory=list)  # (flags, seq, ack, size)
    # parallel to ``sends``: True for retransmitted units (flowtrace's
    # FT_RETRANSMIT send-stage marker; pure ACKs are always False)
    retx: list = dataclasses.field(default_factory=list)
    arm_pump: bool = False  # queue a pump event at the current time
    arm_rto: Optional[int] = None  # queue an RTO event at this time
    completed: bool = False  # flow reached DONE on this stimulus
    aborted: bool = False  # gave up after MAX_RTO_BACKOFFS timeouts

    @property
    def send(self):  # first send (compat accessor for single-send paths)
        return self.sends[0] if self.sends else None


# ---------------------------------------------------------------------------
# law helpers (each maps to a masked vector expression in lanes.py)
# ---------------------------------------------------------------------------


def seg_wire_size(fs: FlowState, unit: int) -> int:
    """Wire size of the segment carrying sequence unit ``unit``."""
    if 1 <= unit <= fs.segs:
        payload = fs.last_bytes if unit == fs.segs else fs.mss
        return HDR_BYTES + payload
    return HDR_BYTES  # SYN / FIN / pure control


def seg_flags(fs: FlowState, unit: int) -> int:
    """Flags of the segment carrying unit ``unit`` (role-dependent)."""
    if unit == 0:
        return F_SYN if fs.role == SENDER else (F_SYN | F_ACK)
    if fs.role == SENDER and 1 <= unit <= fs.segs:
        return F_DATA | F_ACK
    return F_FIN | F_ACK  # sender unit segs+1, receiver unit 1


def icbrt32(x: int) -> int:
    """floor(cbrt(x)) for 0 <= x < 2**31 by the classic bitwise method —
    11 fixed iterations; the vector twin (lanes_stream._icbrt32_vec)
    unrolls the identical loop."""
    y = 0
    for s in range(30, -1, -3):
        y += y
        b = 3 * y * (y + 1) + 1
        if (x >> s) >= b:
            x -= b << s
            y += 1
    return y


def cc_on_loss(fs: FlowState) -> None:
    """Multiplicative decrease at loss detection (fast-retransmit entry
    and RTO): set ssthresh by the flow's algorithm; CUBIC additionally
    records W_max (with fast convergence) and resets its epoch."""
    if fs.cc == CC_CUBIC:
        if fs.cwnd_fp < fs.w_max_fp:  # fast convergence
            fs.w_max_fp = (fs.cwnd_fp * CUBIC_FC_MUL) >> 10
        else:
            fs.w_max_fp = fs.cwnd_fp
        fs.cub_epoch = NEVER
        fs.ssthresh_fp = max(
            (fs.cwnd_fp * CUBIC_BETA_MUL) >> 10, MIN_SSTHRESH_FP
        )
    else:
        fs.ssthresh_fp = max(flight(fs) * FP // 2, MIN_SSTHRESH_FP)


def cc_grow_ca(fs: FlowState, now: int) -> None:
    """Congestion-avoidance growth for one new ACK (cwnd >= ssthresh).
    Reno: +1/cwnd per ACK.  CUBIC: advance toward the cubic target."""
    if fs.cc != CC_CUBIC:
        fs.cwnd_fp += max(1, (FP * FP) // fs.cwnd_fp)
        return
    if fs.cub_epoch == NEVER:  # new epoch starts at the first CA ACK
        fs.cub_epoch = now
        if fs.cwnd_fp < fs.w_max_fp:
            fs.cub_origin_fp = fs.w_max_fp
            fs.cub_k_q = 4 * icbrt32((fs.w_max_fp - fs.cwnd_fp) * CUBIC_K_MUL)
        else:
            fs.cub_origin_fp = fs.cwnd_fp
            fs.cub_k_q = 0
    d_q = min((now - fs.cub_epoch) >> 20, CUBIC_D_MAX)
    offs = d_q - fs.cub_k_q
    neg = offs < 0
    if neg:
        offs = -offs
    if offs > CUBIC_D_MAX:
        offs = CUBIC_D_MAX
    delta_fp = (((((offs * offs) >> 10) * offs) >> 10) * CUBIC_C_MUL) >> 10
    target_fp = (
        fs.cub_origin_fp - delta_fp if neg else fs.cub_origin_fp + delta_fp
    )
    if target_fp > fs.cwnd_fp:
        fs.cwnd_fp += max(1, (target_fp - fs.cwnd_fp) * FP // fs.cwnd_fp)
    else:  # at/above the curve: minimal probing growth (~1%/ACK)
        fs.cwnd_fp += max(1, (FP * FP) // (100 * fs.cwnd_fp))


def cwnd_segs(fs: FlowState) -> int:
    return fs.cwnd_fp // FP


def flight(fs: FlowState) -> int:
    return fs.snd_nxt - fs.snd_una


def can_send_new(fs: FlowState) -> bool:
    """May this flow transmit its next new sequence unit right now?"""
    if fs.role != SENDER or fs.state != ESTAB:
        return False
    if fs.snd_nxt > fs.segs + 1:  # everything (incl. FIN) already sent
        return False
    return flight(fs) < min(cwnd_segs(fs), RWND_SEGS)


def _rtt_sample(fs: FlowState, now: int) -> None:
    """RFC 6298 integer update from the timed unit's ACK."""
    r = now - fs.rtt_ts
    if r < 0:
        r = 0
    if fs.srtt < 0:
        fs.srtt = r
        fs.rttvar = r // 2
    else:
        delta = fs.srtt - r
        if delta < 0:
            delta = -delta
        fs.rttvar = (3 * fs.rttvar + delta) // 4
        fs.srtt = (7 * fs.srtt + r) // 8
    rto = fs.srtt + max(4 * fs.rttvar, 1_000_000)  # 1 ms granularity floor
    fs.rto = min(max(rto, RTO_MIN), RTO_MAX)


def _restart_rto(fs: FlowState, now: int, em: Emit) -> None:
    """(Re)start the retransmission timer for outstanding data.

    Event dedup law: ``rto_evt`` is the time of the single *owning* queued
    RTO event.  A new event is queued only when there is none, or when the
    live deadline moved **earlier** than the owner (an RTT sample shrank
    the RTO) — the superseded event becomes stale and is ignored by the
    ownership check in :func:`on_rto_event`.  An owner that pops before
    the live deadline re-arms itself at the then-current deadline."""
    fs.rto_deadline = now + fs.rto
    if fs.rto_evt == NEVER or fs.rto_deadline < fs.rto_evt:
        fs.rto_evt = fs.rto_deadline
        em.arm_rto = fs.rto_deadline


def _emit_unit(fs: FlowState, unit: int, em: Emit, retransmit: bool) -> None:
    em.sends.append(
        (seg_flags(fs, unit), unit, fs.rcv_nxt, seg_wire_size(fs, unit))
    )
    em.retx.append(retransmit)
    fs.tx_segs += 1
    if retransmit:
        fs.retransmits += 1
        if fs.rtt_seq >= 0 and unit <= fs.rtt_seq:
            fs.rtt_seq = -1  # Karn: never time a retransmitted unit
    elif fs.rtt_seq < 0:
        fs.rtt_seq = unit
    if unit + 1 > fs.max_sent:
        fs.max_sent = unit + 1


def _pull_back(fs: FlowState, now: int, em: Emit) -> None:
    """Go-back-N loss response: rewind ``snd_nxt`` to the hole, retransmit
    it, and let the epilogue pump re-stream everything after it (the
    receiver discarded all out-of-order units anyway)."""
    fs.snd_nxt = fs.snd_una + 1
    if fs.role == SENDER and fs.state == FIN_WAIT:
        fs.state = ESTAB  # the FIN will be re-sent when the stream re-walks
    _emit_unit(fs, fs.snd_una, em, retransmit=True)
    _restart_rto(fs, now, em)


def _pump_units(fs: FlowState, now: int, em: Emit, budget: int) -> None:
    """The transmission-opportunity epilogue: transmit up to ``budget``
    window-permitted units (new data or go-back-N re-stream below
    ``max_sent``), re-arm the pump only if room remains — with
    budget == PUMP_BURST the window always exhausts first, so the re-arm
    never fires (see PUMP_BURST)."""
    sent = 0
    while sent < budget and can_send_new(fs):
        unit = fs.snd_nxt
        fs.snd_nxt += 1
        retransmit = unit < fs.max_sent
        if not retransmit and fs.rtt_seq < 0:
            fs.rtt_ts = now
        _emit_unit(fs, unit, em, retransmit=retransmit)
        if unit == fs.segs + 1:
            fs.state = FIN_WAIT
        _restart_rto(fs, now, em)
        sent += 1
    if can_send_new(fs):
        em.arm_pump = True


# ---------------------------------------------------------------------------
# stimulus handlers
# ---------------------------------------------------------------------------


def open_flow(fs: FlowState, now: int) -> Emit:
    """Active open (client start): send SYN, arm the timer."""
    em = Emit()
    fs.state = SYN_SENT
    fs.snd_nxt = 1
    _emit_unit(fs, 0, em, retransmit=False)
    fs.rtt_ts = now
    _restart_rto(fs, now, em)
    _pump_units(fs, now, em, PUMP_BURST)  # no-op in SYN_SENT (uniform law)
    return em


def on_pump(fs: FlowState, now: int) -> Emit:
    """A transmission-opportunity event: burst up to PUMP_BURST permitted
    units (kept for law completeness — with the epilogue on every
    stimulus, pump events are no longer queued)."""
    em = Emit()
    _pump_units(fs, now, em, PUMP_BURST)
    return em


def on_rto_event(fs: FlowState, now: int) -> Emit:
    """A queued RTO event fired.  Ownership law: only the event at time
    ``rto_evt`` speaks for the timer (others were superseded by an earlier
    re-arm).  Staleness law: if the live deadline moved later, re-arm
    there; if no data is outstanding, lapse.  Processing always moves
    ``rto_evt`` off ``now``, so a coincidentally-reused time cannot
    double-fire.  Ends with the uniform transmission-opportunity epilogue
    (a no-op on the stale/lapse/re-arm paths: those change no send
    state)."""
    em = _on_rto_inner(fs, now)
    _pump_units(fs, now, em, PUMP_BURST)
    return em


def _on_rto_inner(fs: FlowState, now: int) -> Emit:
    em = Emit()
    if now != fs.rto_evt:
        return em  # stale (superseded) event
    fs.rto_evt = NEVER
    if fs.rto_deadline == NEVER or flight(fs) <= 0:
        return em
    if now < fs.rto_deadline:
        fs.rto_evt = fs.rto_deadline
        em.arm_rto = fs.rto_deadline
        return em
    # timeout: give up after MAX_RTO_BACKOFFS consecutive expiries (the
    # path is dead — e.g. a fault-schedule link_down with no reroute);
    # otherwise collapse the window, back off (the exponential growth is
    # hard-capped at RTO_MAX), and go-back-N from the hole
    fs.backoffs += 1
    if fs.backoffs > MAX_RTO_BACKOFFS:
        fs.state = DONE
        fs.rto_deadline = NEVER
        em.aborted = True
        return em
    cc_on_loss(fs)
    fs.cwnd_fp = FP
    fs.dup_acks = 0
    fs.in_rec = False
    fs.rto = min(fs.rto * 2, RTO_MAX)
    _pull_back(fs, now, em)
    return em


def on_segment(
    fs: FlowState, now: int, flags: int, seq: int, ack: int, size: int = HDR_BYTES
) -> Emit:
    """An inbound wire segment for this flow.  ``size`` is the wire size
    (engine delivery size); data payload is ``size - HDR_BYTES`` so neither
    side needs the peer's transfer-shape tables.  Like every stimulus, ends
    with the transmission-opportunity epilogue (burst pump)."""
    em = _on_segment_inner(fs, now, flags, seq, ack, size)
    _pump_units(fs, now, em, PUMP_BURST)
    return em


def _on_segment_inner(
    fs: FlowState, now: int, flags: int, seq: int, ack: int, size: int
) -> Emit:
    em = Emit()
    if fs.state == DONE:
        # dup FIN from a peer that missed our final ACK: re-ACK it
        if fs.role == SENDER and flags & F_FIN:
            em.sends.append((F_ACK, fs.snd_nxt, fs.rcv_nxt, HDR_BYTES))
            em.retx.append(False)
        return em

    # -- passive open -------------------------------------------------------
    if fs.role == RECEIVER and fs.state == CLOSED:
        if not (flags & F_SYN) or flags & F_ACK:
            return em  # not a connection attempt; ignore
        fs.state = SYN_RCVD
        fs.rcv_nxt = 1
        fs.snd_nxt = 1
        _emit_unit(fs, 0, em, retransmit=False)
        fs.rtt_ts = now
        _restart_rto(fs, now, em)
        return em
    if fs.role == RECEIVER and fs.state == SYN_RCVD and flags & F_SYN and not (flags & F_ACK):
        # retransmitted SYN: our SYN-ACK was lost or is in flight; resend
        _emit_unit(fs, 0, em, retransmit=True)
        _restart_rto(fs, now, em)
        return em

    # -- ACK processing (every post-handshake segment carries one) ----------
    if flags & F_ACK:
        if ack > fs.snd_una:
            acked = ack - fs.snd_una
            fs.snd_una = ack
            fs.backoffs = 0  # forward progress: the retry budget refills
            if fs.snd_nxt < fs.snd_una:
                # a delayed ACK (sent before a spurious RTO's go-back-N
                # rewind) may cover units above the rewound snd_nxt; clamp
                # so flight() can't go negative and the pump can't
                # re-stream units the receiver already acknowledged
                fs.snd_nxt = fs.snd_una
            if fs.state == SYN_SENT:
                fs.state = ESTAB
                fs.rcv_nxt = 1  # the SYN-ACK consumed the peer's unit 0
            elif fs.state == SYN_RCVD:
                fs.state = ESTAB
            if fs.in_rec:
                if ack >= fs.recover:  # full ack: leave recovery, deflate
                    fs.cwnd_fp = fs.ssthresh_fp
                    fs.in_rec = False
                    fs.dup_acks = 0
                # partial ack: stay in recovery, the pump is re-streaming
            else:
                fs.dup_acks = 0
                if fs.cwnd_fp < fs.ssthresh_fp:  # slow start (byte counting)
                    fs.cwnd_fp += acked * FP
                else:  # congestion avoidance (per-algorithm growth)
                    cc_grow_ca(fs, now)
                fs.cwnd_fp = min(fs.cwnd_fp, MAX_CWND_FP)
            if fs.rtt_seq >= 0 and ack > fs.rtt_seq:
                _rtt_sample(fs, now)
                fs.rtt_seq = -1
            if flight(fs) > 0:
                _restart_rto(fs, now, em)
            else:
                fs.rto_deadline = NEVER
        elif ack == fs.snd_una and flight(fs) > 0 and not (flags & (F_DATA | F_SYN | F_FIN)):
            # pure duplicate ACK
            if fs.in_rec:
                fs.cwnd_fp += FP  # fast-recovery inflation
            else:
                fs.dup_acks += 1
                if fs.dup_acks == DUP_THRESH:
                    fs.in_rec = True
                    fs.recover = fs.snd_nxt
                    cc_on_loss(fs)
                    fs.cwnd_fp = fs.ssthresh_fp + DUP_THRESH * FP
                    _pull_back(fs, now, em)

    # -- sender-side teardown ----------------------------------------------
    if fs.role == SENDER:
        if flags & F_FIN and fs.snd_una == fs.segs + 2:
            # server's FIN (its unit 1), and everything of ours (incl. our
            # FIN) is acked — by this segment or earlier
            fs.rcv_nxt = 2
            em.sends.append((F_ACK, fs.snd_nxt, fs.rcv_nxt, HDR_BYTES))
            em.retx.append(False)
            fs.state = DONE
            fs.rto_deadline = NEVER
            em.completed = True
        # a window opened by this ACK is streamed by the epilogue pump
        return em

    # -- receiver-side data path -------------------------------------------
    if fs.state in (SYN_RCVD, ESTAB) and flags & F_SYN and flags & F_ACK:
        return em  # stray SYN-ACK (we are the receiver); ignore
    if fs.state == ESTAB or fs.state == SYN_RCVD:
        if flags & F_DATA:
            if seq == fs.rcv_nxt:
                fs.rcv_nxt += 1
                fs.rx_segs += 1
                fs.rx_bytes += size - HDR_BYTES
            # ACK everything (in-order advance or duplicate for OOO)
            em.sends.append((F_ACK, fs.snd_nxt, fs.rcv_nxt, HDR_BYTES))
            em.retx.append(False)
        elif flags & F_FIN:
            if seq == fs.rcv_nxt:
                # client's FIN in order: consume it, answer with our FIN+ACK
                fs.rcv_nxt += 1
                unit = fs.snd_nxt
                fs.snd_nxt += 1
                if fs.rtt_seq < 0:
                    fs.rtt_ts = now
                _emit_unit(fs, unit, em, retransmit=False)
                fs.state = LAST_ACK
                _restart_rto(fs, now, em)
            else:
                em.sends.append((F_ACK, fs.snd_nxt, fs.rcv_nxt, HDR_BYTES))
                em.retx.append(False)
    elif fs.state == LAST_ACK:
        if fs.snd_una >= 2:
            # the final ACK arrived (processed above): teardown complete
            fs.state = DONE
            fs.rto_deadline = NEVER
            em.completed = True
        elif (flags & (F_DATA | F_FIN)) and seq < fs.rcv_nxt:
            # stale retransmission: the peer missed our FIN+ACK (or its
            # cumulative ack); resend it so the flow can't deadlock
            _emit_unit(fs, fs.snd_una, em, retransmit=True)
            _restart_rto(fs, now, em)
    return em


def segs_for_size(size_bytes: int, mss: int) -> tuple[int, int]:
    """Split a transfer size into (segments, last_segment_bytes)."""
    if size_bytes <= 0:
        return 0, mss
    segs = -(-size_bytes // mss)
    last = size_bytes - (segs - 1) * mss
    return segs, last
