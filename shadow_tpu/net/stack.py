"""Per-host network stack: TCP sockets over the simulated packet path.

The socket layer between apps and the engine's packet lifecycle — the
rebuild of the reference's NetworkInterface port-association table
(host/network/interface.rs:118-163), InetSocket demultiplex
(descriptor/socket/inet/mod.rs:630), and the TcpSocket wrapper around the
sans-I/O state machine (inet/tcp.rs).  One :class:`HostNetStack` per
simulated host:

- **demux**: inbound TCP segments route by exact 4-tuple to a connection,
  else by destination port to a listener (SYN), else answer RST — the
  same resolution order as the reference's association lookup;
- **sockets**: :class:`SimTcpSocket` wraps a ``transport.tcp.TcpState``
  and surfaces one ``on_event(sock, now)`` callback after every state
  change (app models then read ``poll()``);
- **timers**: each socket's ``next_timeout`` is armed as a host-local
  event; stale fires are filtered by deadline comparison (the reference's
  Timer re-arm discipline, host/timer.rs:13);
- **egress**: every generated segment is charged through the host's
  normal packet path (``host.send``) so TCP rides the same token buckets,
  loss draw, latency lookup, and CoDel as every other packet.

Determinism: connection iteration is sorted, ISS and ephemeral ports come
from the host's seeded streams, and all scheduling flows through the
host's ordered event queue.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

from ..core.event import Task
from ..transport.tcp import (
    PollState,
    TcpConfig,
    TcpFlags,
    TcpHeader,
    TcpListener,
    TcpState,
)

IP_HEADER_BYTES = 20
TCP_HEADER_BYTES = 20  # simulated wire overhead per segment
EPHEMERAL_PORT_START = 49152
LOOPBACK_U32 = 0x7F000001  # 127.0.0.1 (the simulated lo interface)


def is_loopback_u32(ip_u32: int) -> bool:
    """Any 127/8 address rides the simulated lo interface (the single
    predicate every tier uses — stack routing, managed connect/sendto)."""
    return (ip_u32 >> 24) == 127


@dataclasses.dataclass
class TcpSegment:
    """Engine-payload wrapper distinguishing TCP segments from datagram
    payloads on the shared packet path."""

    hdr: TcpHeader
    data: bytes

    @property
    def wire_size(self) -> int:
        return IP_HEADER_BYTES + TCP_HEADER_BYTES + len(self.data)


class SimTcpSocket:
    """A connected (or connecting) TCP socket bound to one host."""

    def __init__(self, stack: "HostNetStack", tcp: TcpState) -> None:
        self.stack = stack
        self.tcp = tcp
        self.on_event: Optional[Callable[["SimTcpSocket", int], None]] = None
        self._armed_deadline: Optional[int] = None
        # peer host id, resolved once (connect/accept); every segment of a
        # connection goes to the same host — no per-segment DNS lookups
        self.dst_host: Optional[int] = None

    # -- app API -----------------------------------------------------------

    def send(self, data: bytes) -> int:
        n = self.tcp.send(data)
        self.stack.flush_socket(self)
        return n

    def recv(self, max_len: int) -> bytes:
        out = self.tcp.recv(max_len)
        if out:
            self.stack.flush_socket(self)  # window update may need to go out
        return out

    def peek(self, max_len: int) -> bytes:
        """MSG_PEEK: read without consuming (no window update)."""
        return self.tcp.peek(max_len)

    def close(self) -> None:
        self.tcp.close(self.stack.host.now)
        self.stack.flush_socket(self)

    def poll(self) -> PollState:
        return self.tcp.poll()

    @property
    def key(self) -> tuple[int, int, int, int]:
        return self.tcp.four_tuple()


class SimTcpListener:
    """A listening socket; accepted children become SimTcpSockets."""

    def __init__(self, stack: "HostNetStack", listener: TcpListener, port: int):
        self.stack = stack
        self.listener = listener
        self.port = port
        # called as on_accept(sock, now) for each newly-established child
        self.on_accept: Optional[Callable[[SimTcpSocket, int], None]] = None

    def close(self) -> None:
        self.listener.close()
        self.stack.tcp_listeners.pop(self.port, None)


class HostNetStack:
    """All transport state of one host (TCP tier; UDP rides the managed-
    process port table for now)."""

    def __init__(self, host) -> None:
        self.host = host  # backend Host (cpu_engine.Host duck type)
        self.tcp_conns: dict[tuple[int, int, int, int], SimTcpSocket] = {}
        self.tcp_listeners: dict[int, SimTcpListener] = {}
        self._embryonic: dict[tuple[int, int, int, int], SimTcpSocket] = {}
        self._next_ephemeral = EPHEMERAL_PORT_START

    # -- ports -------------------------------------------------------------

    def _alloc_port(self) -> int:
        used = {k[1] for k in self.tcp_conns} | set(self.tcp_listeners)
        p = self._next_ephemeral
        while p in used:
            p += 1
        self._next_ephemeral = p + 1
        return p

    def _my_ip(self) -> int:
        import socket as pysocket

        ip = self.host.ip_of(self.host.host_id)
        return int.from_bytes(pysocket.inet_aton(ip), "big")

    # -- socket creation ---------------------------------------------------

    def connect(
        self,
        dst_host: int,
        dst_port: int,
        src_port: Optional[int] = None,
        config: Optional[TcpConfig] = None,
        loopback: bool = False,
    ) -> SimTcpSocket:
        """Active open to (dst_host, dst_port); segments start flowing now.
        ``loopback`` addresses the connection 127.0.0.1 -> 127.0.0.1 (both
        ends, like Linux) and rides the lo interface lifecycle."""
        import socket as pysocket

        if loopback:
            dst_ip = LOOPBACK_U32
            local = (LOOPBACK_U32, src_port or self._alloc_port())
        else:
            dst_ip = int.from_bytes(
                pysocket.inet_aton(self.host.ip_of(dst_host)), "big"
            )
            local = (self._my_ip(), src_port or self._alloc_port())
        tcp = TcpState(config or self._default_config())
        iss = self.host.rand_u32()
        tcp.connect(local, (dst_ip, dst_port), iss=iss, now=self.host.now)
        sock = SimTcpSocket(self, tcp)
        sock.dst_host = dst_host
        self.tcp_conns[tcp.four_tuple()] = sock
        self.flush_socket(sock)
        return sock

    def listen(
        self,
        port: int,
        backlog: int = 128,
        config: Optional[TcpConfig] = None,
    ) -> SimTcpListener:
        if port in self.tcp_listeners:
            raise OSError(f"port {port} already listening (EADDRINUSE)")
        tl = TcpListener(
            (self._my_ip(), port), backlog, config or self._default_config()
        )
        lst = SimTcpListener(self, tl, port)
        self.tcp_listeners[port] = lst
        return lst

    def _default_config(self) -> TcpConfig:
        cfg = self.host.engine.cfg
        return TcpConfig(
            send_buffer=cfg.experimental.socket_send_buffer,
            recv_buffer=cfg.experimental.socket_recv_buffer,
            congestion=cfg.hosts[self.host.host_id].congestion,
        )

    # -- inbound demux (interface.rs association lookup order) -------------

    def on_segment(self, now: int, seg: TcpSegment) -> None:
        hdr = seg.hdr
        key = (hdr.dst_ip, hdr.dst_port, hdr.src_ip, hdr.src_port)
        sock = self.tcp_conns.get(key) or self._embryonic.get(key)
        if sock is not None:
            sock.tcp.push_packet(now, hdr, seg.data)
            self._post_activity(sock, now)
            return
        lst = self.tcp_listeners.get(hdr.dst_port)
        if (
            lst is not None
            and hdr.flags & TcpFlags.SYN
            and not hdr.flags & TcpFlags.ACK
        ):
            child = lst.listener.push_syn(now, hdr, iss=self.host.rand_u32())
            if child is None:
                self.host.count("tcp_backlog_drops")
                return
            sock = SimTcpSocket(self, child)
            sock.dst_host = self._host_for_ip(hdr.src_ip)
            self._embryonic[child.four_tuple()] = sock
            self.flush_socket(sock)
            return
        self.host.count("tcp_unmatched_segments")
        self._send_rst_for(hdr, len(seg.data))

    def _send_rst_for(self, hdr: TcpHeader, seg_len: int) -> None:
        """Answer an unmatched non-RST segment with RST (connection refused
        — the behavior tests rely on for fast failure)."""
        if hdr.flags & TcpFlags.RST:
            return
        from ..transport.tcp import seq_add

        if hdr.flags & TcpFlags.ACK:
            rst = TcpHeader(
                src_ip=hdr.dst_ip, src_port=hdr.dst_port,
                dst_ip=hdr.src_ip, dst_port=hdr.src_port,
                seq=hdr.ack, ack=0, flags=TcpFlags.RST, window=0,
            )
        else:
            ack = seq_add(hdr.seq, seg_len + (1 if hdr.flags & TcpFlags.SYN else 0))
            rst = TcpHeader(
                src_ip=hdr.dst_ip, src_port=hdr.dst_port,
                dst_ip=hdr.src_ip, dst_port=hdr.src_port,
                seq=0, ack=ack, flags=TcpFlags.RST | TcpFlags.ACK, window=0,
            )
        self._transmit(rst, b"")

    # -- egress ------------------------------------------------------------

    def _transmit(
        self, hdr: TcpHeader, data: bytes, dst: Optional[int] = None
    ) -> None:
        seg = TcpSegment(hdr, data)
        if dst is None:  # only the unmatched-segment RST path resolves
            dst = self._host_for_ip(hdr.dst_ip)
        if dst is None:
            self.host.count("tcp_no_route_drops")
            return
        self.host.send(dst, seg.wire_size, payload=seg,
                       loopback=is_loopback_u32(hdr.dst_ip))

    def _host_for_ip(self, ip_u32: int) -> Optional[int]:
        if is_loopback_u32(ip_u32):  # the lo interface
            return self.host.host_id
        import socket as pysocket

        ip = pysocket.inet_ntoa(ip_u32.to_bytes(4, "big"))
        return self.host.engine.dns.host_for_ip(ip)

    # -- socket pumping ----------------------------------------------------

    def flush_socket(self, sock: SimTcpSocket) -> None:
        """Drain pending segments, re-arm the timer, reap closed state."""
        tcp = sock.tcp
        now = self.host.now
        while tcp.wants_to_send():
            out = tcp.pop_packet(now)
            if out is None:
                break
            hdr, data = out
            self._transmit(hdr, data, sock.dst_host)
        self._rearm_timer(sock)
        if tcp.is_closed():
            self.tcp_conns.pop(sock.key, None)
            self._embryonic.pop(sock.key, None)
            # an embryonic child that died must leave the backlog too
            lst = self.tcp_listeners.get(tcp.local_port)
            if lst is not None:
                lst.listener.children.pop((tcp.remote_ip, tcp.remote_port), None)

    def _post_activity(self, sock: SimTcpSocket, now: int) -> None:
        """After inbound processing: promote embryonic sockets, pump
        output, deliver the app callback."""
        from ..transport.tcp import State

        tcp = sock.tcp
        key = sock.key
        if key in self._embryonic and tcp.state in (
            State.ESTABLISHED,
            State.CLOSE_WAIT,
        ):
            self._embryonic.pop(key, None)
            self.tcp_conns[key] = sock
            # the child leaves the listener backlog; app gets the accept
            lst = self.tcp_listeners.get(tcp.local_port)
            if lst is not None:
                lst.listener.children.pop((tcp.remote_ip, tcp.remote_port), None)
                if lst.on_accept is not None:
                    lst.on_accept(sock, now)
        self.flush_socket(sock)
        if sock.on_event is not None:
            sock.on_event(sock, now)

    # -- timers ------------------------------------------------------------

    def _rearm_timer(self, sock: SimTcpSocket) -> None:
        deadline = sock.tcp.next_timeout()
        if deadline is None:
            sock._armed_deadline = None
            return
        if sock._armed_deadline is not None and sock._armed_deadline <= deadline:
            return  # an armed event already covers this deadline
        sock._armed_deadline = deadline
        key = sock.key

        def fire(host, stack=self, key=key, deadline=deadline) -> None:
            stack._timer_fired(key, deadline, host.now)

        self.host.push_local(max(deadline, self.host.now + 1), Task(fire, label="tcp-timer"))

    def _timer_fired(self, key, armed_deadline: int, now: int) -> None:
        sock = self.tcp_conns.get(key) or self._embryonic.get(key)
        if sock is None:
            return  # connection gone
        if sock._armed_deadline != armed_deadline:
            return  # stale fire: a newer arm superseded this one
        sock._armed_deadline = None
        sock.tcp.on_timer(now)
        self._post_activity(sock, now)
