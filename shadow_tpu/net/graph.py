"""Network topology graph, shortest-path routing, and IP assignment.

Behavior parity with the reference's ``src/main/network/graph/mod.rs``:

- GML graphs with ``node [id, host_bandwidth_up/down]`` and ``edge [source,
  target, latency, packet_loss]``; undirected graphs use each edge in both
  directions; a self-loop edge supplies the path properties between two hosts
  attached to the same node (graph/mod.rs:228-286).
- Edge latency must be > 0; packet loss must be in [0, 1].
- Path properties combine: latency adds, reliability multiplies
  (``1-(1-a)(1-b)``, graph/mod.rs:321-322); shortest paths minimize latency
  first, then loss (graph/mod.rs:301-303).
- Routing can be all-pairs shortest paths or direct-edges-only
  (graph/mod.rs:181,228).
- IPs are auto-assigned from 11.0.0.0/8 (graph/mod.rs:348).

TPU-first difference: routing resolves to **dense device-ready tables** —
``latency_ns[G,G]`` int64 and ``loss_threshold[G,G]`` int64 (u64-domain
Bernoulli thresholds, see ``core.rng.loss_threshold``) — because on the TPU
backend every per-packet (latency, loss) lookup is a gather into these
arrays.  The min latency feeds the lookahead window (runahead).
"""

from __future__ import annotations

import dataclasses
import lzma
import math
from pathlib import Path
from typing import Any, Optional

import numpy as np

from ..config import units
from ..core.rng import loss_threshold
from . import gml as gml_mod

#: Built-in one-node graph (config ``type: 1_gbit_switch``), as upstream.
ONE_GBIT_SWITCH_GML = """
graph [
  node [
    id 0
    host_bandwidth_up "1 Gbit"
    host_bandwidth_down "1 Gbit"
  ]
  edge [
    source 0
    target 0
    latency "1 ms"
  ]
]
"""

_UNREACHABLE = -1


class GraphError(ValueError):
    pass


@dataclasses.dataclass
class GraphNode:
    node_id: int
    bandwidth_up_bps: Optional[int]  # bits/sec, None if not set on the node
    bandwidth_down_bps: Optional[int]


@dataclasses.dataclass
class GraphEdge:
    source: int
    target: int
    latency_ns: int
    packet_loss: float


class NetworkGraph:
    """Parsed + validated topology with compiled routing tables."""

    def __init__(
        self,
        nodes: list[GraphNode],
        edges: list[GraphEdge],
        directed: bool,
        use_shortest_path: bool = True,
    ) -> None:
        if not nodes:
            raise GraphError("graph has no nodes")
        self.directed = directed
        self.nodes = nodes
        self.edges = edges
        # graph node ids can be sparse; map to dense indices
        self.node_ids = [n.node_id for n in nodes]
        if len(set(self.node_ids)) != len(self.node_ids):
            raise GraphError("duplicate node ids")
        self.id_to_index = {nid: i for i, nid in enumerate(self.node_ids)}
        for e in edges:
            # finiteness first: NaN slips through range comparisons (every
            # NaN comparison is False, so ``0.0 <= nan <= 1.0`` rejects it
            # only by accident of the chained form — be explicit), and an
            # inf latency would poison the shortest-path accumulation
            if isinstance(e.latency_ns, float) and not math.isfinite(e.latency_ns):
                raise GraphError(
                    f"edge {e.source}->{e.target}: latency must be a finite "
                    f"value, got {e.latency_ns!r}"
                )
            if e.latency_ns <= 0:
                raise GraphError(f"edge {e.source}->{e.target}: latency must be > 0")
            if not math.isfinite(e.packet_loss):
                raise GraphError(
                    f"edge {e.source}->{e.target}: packet_loss must be a "
                    f"finite value, got {e.packet_loss!r}"
                )
            if not (0.0 <= e.packet_loss <= 1.0):
                raise GraphError(
                    f"edge {e.source}->{e.target}: packet_loss not in [0,1]"
                )
            if e.source not in self.id_to_index or e.target not in self.id_to_index:
                raise GraphError(f"edge {e.source}->{e.target}: unknown node id")
        self._compile_routes(use_shortest_path)

    # -- construction -----------------------------------------------------

    @classmethod
    def from_gml(cls, text: str, use_shortest_path: bool = True) -> "NetworkGraph":
        g = gml_mod.parse_gml(text)
        directed = bool(g.get("directed", 0))
        nodes = []
        for n in g["nodes"]:
            if "id" not in n:
                raise GraphError("node without id")
            up = n.get("host_bandwidth_up")
            down = n.get("host_bandwidth_down")
            nodes.append(
                GraphNode(
                    node_id=int(n["id"]),
                    bandwidth_up_bps=units.parse_bandwidth(up) if up is not None else None,
                    bandwidth_down_bps=units.parse_bandwidth(down)
                    if down is not None
                    else None,
                )
            )
        edges = []
        for e in g["edges"]:
            if "source" not in e or "target" not in e:
                raise GraphError("edge without source/target")
            if "latency" not in e:
                raise GraphError("edge 'latency' was not provided")
            if not isinstance(e["latency"], str):
                # the reference requires a unit string here; a bare number is
                # ambiguous (ns? s?) and floats would truncate silently
                raise GraphError(
                    f"edge {e['source']}->{e['target']}: 'latency' must be a "
                    f"unit string like \"10 ms\", got {e['latency']!r}"
                )
            edges.append(
                GraphEdge(
                    source=int(e["source"]),
                    target=int(e["target"]),
                    latency_ns=units.parse_time(e["latency"]),
                    packet_loss=float(e.get("packet_loss", 0.0)),
                )
            )
        return cls(nodes, edges, directed, use_shortest_path)

    @classmethod
    def from_file(cls, path: str | Path, use_shortest_path: bool = True) -> "NetworkGraph":
        p = Path(path)
        raw = p.read_bytes()
        if p.suffix == ".xz" or raw[:6] == b"\xfd7zXZ\x00":
            raw = lzma.decompress(raw)
        return cls.from_gml(raw.decode(), use_shortest_path)

    @classmethod
    def one_gbit_switch(cls) -> "NetworkGraph":
        return cls.from_gml(ONE_GBIT_SWITCH_GML)

    # -- routing ----------------------------------------------------------

    def _compile_routes(self, use_shortest_path: bool) -> None:
        g = len(self.nodes)
        lat = np.full((g, g), _UNREACHABLE, dtype=np.int64)
        loss = np.zeros((g, g), dtype=np.float64)
        # direct edges (off-diagonal) and self-loops (diagonal)
        for e in self.edges:
            s, t = self.id_to_index[e.source], self.id_to_index[e.target]
            pairs = [(s, t)] if (self.directed or s == t) else [(s, t), (t, s)]
            for a, b in pairs:
                if lat[a, b] != _UNREACHABLE:
                    raise GraphError(
                        f"more than one edge connecting node {e.source} to {e.target}"
                    )
                lat[a, b] = e.latency_ns
                loss[a, b] = e.packet_loss

        if use_shortest_path and g > 1:
            lat, loss = self._all_pairs_shortest(lat, loss)

        self.latency_ns = lat
        self.packet_loss = loss
        # u64-domain thresholds for the device tables (int64 holds 2**32 fine;
        # vectorized mirror of core.rng.loss_threshold)
        self.loss_threshold = np.where(
            loss <= 0.0,
            np.int64(0),
            np.where(
                loss >= 1.0,
                np.int64(1) << 32,
                (loss * 4294967296.0).astype(np.int64),
            ),
        )

    def _all_pairs_shortest(
        self, direct_lat: np.ndarray, direct_loss: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray]:
        """All-pairs shortest paths minimizing (latency, then loss).

        Lossless graphs (the overwhelmingly common case) go through scipy's
        C Dijkstra on exact integer latencies (float64 is exact below 2**53
        ns ≈ 104 days) with predecessor reconstruction, so no float error
        reaches the tables.  Graphs with lossy edges use an exact
        tuple-weight ``(latency, -log reliability)`` Dijkstra so latency
        ties genuinely break on loss — a float "epsilon" composite cannot
        represent a sub-ns perturbation at ms latencies.
        """
        if (direct_loss > 0.0).any():
            return self._all_pairs_shortest_lossy(direct_lat, direct_loss)

        from scipy.sparse import csr_matrix
        from scipy.sparse.csgraph import dijkstra

        g = direct_lat.shape[0]
        rows, cols, w = [], [], []
        for i in range(g):
            for j in range(g):
                if i != j and direct_lat[i, j] != _UNREACHABLE:
                    rows.append(i)
                    cols.append(j)
                    w.append(float(direct_lat[i, j]))
        mat = csr_matrix((w, (rows, cols)), shape=(g, g))
        dist, pred = dijkstra(mat, directed=True, return_predecessors=True)

        lat = np.full((g, g), _UNREACHABLE, dtype=np.int64)
        order = np.argsort(dist, axis=1, kind="stable")
        for s in range(g):
            # accumulate exact edge latencies in increasing-distance order,
            # so predecessors are always finalized first
            for v in order[s]:
                if v == s or not np.isfinite(dist[s, v]):
                    continue
                p = pred[s, v]
                if p < 0:
                    continue
                base_lat = 0 if p == s else lat[s, p]
                lat[s, v] = base_lat + direct_lat[p, v]
        loss = np.zeros((g, g), dtype=np.float64)
        # keep self-loop (diagonal) direct properties: they model same-node
        # host-to-host paths and are not part of shortest-path routing
        np.fill_diagonal(lat, np.diag(direct_lat))
        np.fill_diagonal(loss, np.diag(direct_loss))
        return lat, loss

    def _all_pairs_shortest_lossy(
        self, direct_lat: np.ndarray, direct_loss: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray]:
        """Exact (latency, then loss) Dijkstra with tuple weights."""
        import heapq

        g = direct_lat.shape[0]
        adj: list[list[tuple[int, int, float]]] = [[] for _ in range(g)]
        for i in range(g):
            for j in range(g):
                if i != j and direct_lat[i, j] != _UNREACHABLE:
                    logloss = -math.log(max(1.0 - direct_loss[i, j], 1e-300))
                    adj[i].append((j, int(direct_lat[i, j]), logloss))

        lat = np.full((g, g), _UNREACHABLE, dtype=np.int64)
        loss = np.zeros((g, g), dtype=np.float64)
        for s in range(g):
            best: dict[int, tuple[int, float]] = {s: (0, 0.0)}
            done: set[int] = set()
            heap: list[tuple[int, float, int]] = [(0, 0.0, s)]
            while heap:
                d_lat, d_log, u = heapq.heappop(heap)
                if u in done:
                    continue
                done.add(u)
                for v, w_lat, w_log in adj[u]:
                    cand = (d_lat + w_lat, d_log + w_log)
                    if v not in best or cand < best[v]:
                        best[v] = cand
                        heapq.heappush(heap, (cand[0], cand[1], v))
            for v, (d_lat, d_log) in best.items():
                if v != s:
                    lat[s, v] = d_lat
                    loss[s, v] = 1.0 - math.exp(-d_log)
        np.fill_diagonal(lat, np.diag(direct_lat))
        np.fill_diagonal(loss, np.diag(direct_loss))
        return lat, loss

    def install_tables(
        self,
        latency_ns: np.ndarray,
        packet_loss: np.ndarray,
        loss_threshold: np.ndarray,
    ) -> None:
        """Swap the compiled pair tables in place — the fault-epoch seam
        (shadow_tpu/faults/overlay.py): RoutingInfo reads these arrays on
        every ``path()``, so installing a snapshot redirects all
        subsequent sends without rebuilding hosts or routing."""
        g = len(self.nodes)
        for name, arr in (
            ("latency_ns", latency_ns),
            ("packet_loss", packet_loss),
            ("loss_threshold", loss_threshold),
        ):
            if arr.shape != (g, g):
                raise GraphError(
                    f"install_tables: {name} has shape {arr.shape}, want {(g, g)}"
                )
        self.latency_ns = latency_ns
        self.packet_loss = packet_loss
        self.loss_threshold = loss_threshold

    # -- queries ----------------------------------------------------------

    def path(self, src_node_id: int, dst_node_id: int) -> tuple[int, float]:
        """(latency_ns, packet_loss) between two graph nodes; raises if the
        pair is unroutable (including a missing self-loop for same-node
        pairs, as in the reference)."""
        s = self.id_to_index[src_node_id]
        t = self.id_to_index[dst_node_id]
        l = int(self.latency_ns[s, t])
        if l == _UNREACHABLE:
            if s == t:
                raise GraphError(
                    f"node {src_node_id} hosts multiple endpoints but has no "
                    "self-loop edge to define the path between them"
                )
            raise GraphError(f"no path from node {src_node_id} to {dst_node_id}")
        return l, float(self.packet_loss[s, t])

    def min_latency_ns(self) -> int:
        """Smallest routable latency — the conservative lookahead bound
        (graph/mod.rs:472-474, runahead.rs:14)."""
        mask = self.latency_ns != _UNREACHABLE
        if not mask.any():
            raise GraphError("graph has no routable paths")
        return int(self.latency_ns[mask].min())

    def node_bandwidth(self, node_id: int) -> tuple[Optional[int], Optional[int]]:
        n = self.nodes[self.id_to_index[node_id]]
        return n.bandwidth_up_bps, n.bandwidth_down_bps


@dataclasses.dataclass
class IpAssignment:
    """Sequential auto-assignment from 11.0.0.0/8, skipping .0/.255 octets
    (mirrors graph/mod.rs:348's auto-IP block choice)."""

    _next: int = (11 << 24) + 1
    by_ip: dict[str, int] = dataclasses.field(default_factory=dict)  # ip -> host_id
    by_host: dict[int, str] = dataclasses.field(default_factory=dict)

    def assign(self, host_id: int, requested_ip: Optional[str] = None) -> str:
        if requested_ip is not None:
            if requested_ip in self.by_ip:
                raise GraphError(f"duplicate IP {requested_ip}")
            self.by_ip[requested_ip] = host_id
            self.by_host[host_id] = requested_ip
            return requested_ip
        while True:
            ip_int = self._next
            self._next += 1
            last = ip_int & 0xFF
            if last in (0, 255):
                continue
            if (ip_int >> 24) != 11:
                raise GraphError("11.0.0.0/8 exhausted")
            ip = ".".join(str((ip_int >> s) & 0xFF) for s in (24, 16, 8, 0))
            if ip in self.by_ip:
                continue
            self.by_ip[ip] = host_id
            self.by_host[host_id] = ip
            return ip

    def host_for_ip(self, ip: str) -> Optional[int]:
        return self.by_ip.get(ip)


class RoutingInfo:
    """Pairwise path lookup between *hosts* plus packet counters
    (graph/mod.rs:428-470), backed by the dense node tables.

    ``host_nodes`` maps host_id -> dense node index; the device tables are
    exactly ``latency_ns`` / ``loss_threshold`` gathered through this map.
    """

    def __init__(self, graph: NetworkGraph, host_to_node_id: dict[int, int]) -> None:
        self.graph = graph
        self.host_to_node_id = dict(host_to_node_id)
        self.host_node_index = {
            h: graph.id_to_index[nid] for h, nid in host_to_node_id.items()
        }
        self.packet_counts: dict[tuple[int, int], int] = {}
        # validate all pairs are routable up-front (reference computes paths
        # for the used node set during setup and errors early)
        from collections import Counter

        used = sorted(set(self.host_node_index.values()))
        counts = Counter(self.host_node_index.values())
        multi = {n for n, c in counts.items() if c > 1}
        for s in used:
            for t in used:
                if s == t and s not in multi:
                    continue
                if graph.latency_ns[s, t] == _UNREACHABLE:
                    raise GraphError(
                        f"hosts are assigned to nodes without a route "
                        f"({graph.node_ids[s]} -> {graph.node_ids[t]})"
                    )

    def path(self, src_host: int, dst_host: int) -> tuple[int, int]:
        """(latency_ns, loss_threshold) for a host pair; counts the packet."""
        s = self.host_node_index[src_host]
        t = self.host_node_index[dst_host]
        key = (src_host, dst_host)
        self.packet_counts[key] = self.packet_counts.get(key, 0) + 1
        return int(self.graph.latency_ns[s, t]), int(self.graph.loss_threshold[s, t])

    def min_used_latency_ns(self) -> int:
        """Min latency over node pairs actually used by hosts — the dynamic
        runahead bound (runahead.rs:60-118)."""
        used = sorted(set(self.host_node_index.values()))
        lat = self.graph.latency_ns[np.ix_(used, used)]
        mask = lat != _UNREACHABLE
        if not mask.any():
            raise GraphError("no routable path between any pair of used nodes")
        return int(lat[mask].min())

    def device_tables(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(host_node_index[N], latency_ns[G,G], loss_threshold[G,G]) ready
        to ship to the TPU backend."""
        n = max(self.host_node_index) + 1
        idx = np.zeros(n, dtype=np.int32)
        for h, i in self.host_node_index.items():
            idx[h] = i
        return idx, self.graph.latency_ns, self.graph.loss_threshold
