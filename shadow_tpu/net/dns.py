"""DNS: the simulation-wide name <-> IP <-> host-id registry.

Rebuild of the reference's DNS subsystem (network/dns.rs:86-190): a static
registry built before the simulation starts (every host registers its
hostname and IP), answering forward lookups (hostname -> host), reverse
lookups (IP -> host), and emitting an ``/etc/hosts``-style file that managed
plugins resolve against — the reference passes that file to plugins as a
memfd so unmodified libc resolvers see the simulated names; here the path
travels in the plugin environment (``SHADOW_TPU_HOSTS_FILE``) and the shim's
``getaddrinfo`` reads it locally, no channel hop.

Lookup accepts three spellings (single-sourced for both backends so model
configs behave identically on cpu and tpu): a registered hostname, a dotted
IPv4 string, or a bare numeric host id (model-config convenience).
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional


class DnsError(ValueError):
    pass


class Dns:
    """Static pre-sim registry; immutable once the engines start."""

    def __init__(self) -> None:
        self._by_name: dict[str, int] = {}
        self._by_ip: dict[str, int] = {}
        self._name_of: dict[int, str] = {}
        self._ip_of: dict[int, str] = {}

    def register(self, host_id: int, hostname: str, ip: str) -> None:
        if hostname in self._by_name:
            raise DnsError(f"duplicate hostname {hostname!r}")
        if ip in self._by_ip:
            raise DnsError(f"duplicate IP {ip}")
        if host_id in self._name_of:
            raise DnsError(f"host id {host_id} registered twice")
        self._by_name[hostname] = host_id
        self._by_ip[ip] = host_id
        self._name_of[host_id] = hostname
        self._ip_of[host_id] = ip

    # -- lookups -----------------------------------------------------------

    def __len__(self) -> int:
        return len(self._name_of)

    def resolve(self, name: str) -> int:
        """hostname | IPv4 string | numeric host id -> host id."""
        hid = self.try_resolve(name)
        if hid is None:
            raise DnsError(f"unknown hostname {name!r}")
        return hid

    def try_resolve(self, name: str) -> Optional[int]:
        hid = self._by_name.get(name)
        if hid is not None:
            return hid
        hid = self._by_ip.get(name)
        if hid is not None:
            return hid
        try:
            hid = int(name)
        except ValueError:
            return None
        return hid if 0 <= hid < len(self._name_of) else None

    def ip_of(self, host_id: int) -> str:
        return self._ip_of[host_id]

    def name_of(self, host_id: int) -> str:
        return self._name_of[host_id]

    def host_for_ip(self, ip: str) -> Optional[int]:
        return self._by_ip.get(ip)

    # -- hosts-file emission (dns.rs:130-190) ------------------------------

    def hosts_file(self) -> str:
        """``/etc/hosts``-style text: loopback first, then every simulated
        host in id order (deterministic byte-for-byte)."""
        lines = ["127.0.0.1 localhost\n"]
        for hid in sorted(self._name_of):
            lines.append(f"{self._ip_of[hid]} {self._name_of[hid]}\n")
        return "".join(lines)

    def write_hosts_file(self, path: str | Path) -> Path:
        """Atomic (tmp + rename): MpCpuEngine worker replicas all write
        this file concurrently while other workers' managed processes may
        be resolving through it — a truncate-then-write would expose an
        empty file mid-write.  Every replica writes identical bytes, so
        the last rename is a no-op content-wise."""
        import os

        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_name(f".{path.name}.{os.getpid()}.tmp")
        tmp.write_text(self.hosts_file())
        os.replace(tmp, path)
        return path
