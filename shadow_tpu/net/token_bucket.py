"""Integer token bucket for bandwidth enforcement.

Scalar reference implementation of the spec in docs/SEMANTICS.md (the TPU
lane backend implements the identical arithmetic as a ``lax.scan``).
Behavioral counterpart of the reference's relay token bucket
(src/main/network/relay/token_bucket.rs:6-40): refill ``rate`` bits every
``interval`` ns up to ``burst``, serialize departures.
"""

from __future__ import annotations

import dataclasses

from ..core.time import NANOS_PER_MILLI

#: default refill interval (the reference refills once per ms)
DEFAULT_INTERVAL_NS = NANOS_PER_MILLI

#: per-packet wire framing overhead in bytes (Ethernet-ish), charged on top
#: of the IP packet size
FRAME_OVERHEAD_BYTES = 24


def bucket_params(bits_per_sec: int, interval_ns: int = DEFAULT_INTERVAL_NS) -> tuple[int, int]:
    """(rate_bits_per_interval, burst_bits) for a configured bandwidth.

    Burst is one refill's worth but at least one full-size frame so that a
    single MTU packet can always depart (the reference sizes the bucket
    likewise from the configured bandwidth).
    """
    rate = max(1, (bits_per_sec * interval_ns) // 1_000_000_000)
    burst = max(rate, 12_000 + FRAME_OVERHEAD_BYTES * 8)  # ≥ one 1500B frame
    return rate, burst


@dataclasses.dataclass
class TokenBucket:
    """State: (tokens, next_refill, last_depart).  ``rate == 0`` means
    unlimited."""

    rate: int  # bits added per interval
    burst: int  # max tokens
    interval: int = DEFAULT_INTERVAL_NS
    tokens: int = -1  # set to burst in __post_init__
    next_refill: int = -1
    last_depart: int = 0
    # telemetry: charges that had to wait for a refill (tokens short
    # after the refill step) — the netobs "throttled" cause.  A pure
    # function of the charge sequence, so it is deterministic and the
    # lane kernels' wait mask counts the identical instants.
    throttles: int = 0

    def __post_init__(self) -> None:
        if self.tokens < 0:
            self.tokens = self.burst
        if self.next_refill < 0:
            self.next_refill = self.interval

    def charge(self, t: int, bits: int) -> int:
        """Charge ``bits`` at time ``t`` (non-decreasing across calls);
        returns the departure time.

        FIFO law: the charge clock is ``max(t, last_depart)`` — a packet
        that queued for a future refill moves the whole line behind it,
        so leftover tokens earned *at* that refill cannot let a later
        packet depart before an earlier one (departures are monotone)."""
        if self.rate == 0:
            return t
        t = max(t, self.last_depart)
        if t >= self.next_refill:
            k = (t - self.next_refill) // self.interval + 1
            self.tokens = min(self.burst, self.tokens + k * self.rate)
            self.next_refill += k * self.interval
        if self.tokens >= bits:
            self.tokens -= bits
            self.last_depart = t
            return t
        self.throttles += 1
        need = bits - self.tokens
        w = -(-need // self.rate)  # ceil
        depart = self.next_refill + (w - 1) * self.interval
        self.tokens = max(0, min(self.burst, self.tokens + w * self.rate) - bits)
        self.next_refill += w * self.interval
        self.last_depart = depart
        return depart
