"""Simulation time primitives.

All simulation time is integer nanoseconds. There are two clocks, mirroring
the reference's ``SimulationTime`` / ``EmulatedTime`` split
(shadow-shim-helper-rs/src/simulation_time.rs:22,
shadow-shim-helper-rs/src/emulated_time.rs:18-46):

- ``SimTime``: nanoseconds since the start of the simulation (t=0).
- ``EmuTime``: the wall-clock time the managed world observes; the epoch is
  2000-01-01T00:00:00Z, so programs see plausible dates that never collide
  with real time.

Times are plain ``int`` on the host and ``int64`` lanes on the device; the
sentinel ``NEVER`` (max int64) means "no event pending".  Integer-only time is
a hard design rule: it is what makes the CPU reference backend and the TPU
lane backend bit-identical (no float rounding anywhere in event ordering).
"""

from __future__ import annotations

NANOS_PER_MICRO = 1_000
NANOS_PER_MILLI = 1_000_000
NANOS_PER_SEC = 1_000_000_000
NANOS_PER_MIN = 60 * NANOS_PER_SEC
NANOS_PER_HOUR = 3600 * NANOS_PER_SEC

#: max int64; "no pending event" sentinel, compares greater than any real time.
NEVER: int = (1 << 63) - 1

#: EmuTime of simulation start: seconds between the Unix epoch and
#: 2000-01-01T00:00:00Z (the reference's ``EMUTIME_SIMULATION_START``).
SIM_START_EMU: int = 946_684_800 * NANOS_PER_SEC


def sim_to_emu(sim_ns: int) -> int:
    """Convert simulation-relative time to the emulated wall clock."""
    if sim_ns == NEVER:
        return NEVER
    return SIM_START_EMU + sim_ns


def emu_to_sim(emu_ns: int) -> int:
    """Convert an emulated wall-clock time to simulation-relative time."""
    if emu_ns == NEVER:
        return NEVER
    return emu_ns - SIM_START_EMU


def from_secs(s: float | int) -> int:
    """Seconds -> integer ns.  Accepts ints exactly; floats are rounded."""
    if isinstance(s, int):
        return s * NANOS_PER_SEC
    return round(s * NANOS_PER_SEC)


def from_millis(ms: float | int) -> int:
    if isinstance(ms, int):
        return ms * NANOS_PER_MILLI
    return round(ms * NANOS_PER_MILLI)


def from_micros(us: float | int) -> int:
    if isinstance(us, int):
        return us * NANOS_PER_MICRO
    return round(us * NANOS_PER_MICRO)


def fmt(ns: int) -> str:
    """Human-readable time for logs: ``12.345678901s`` style."""
    if ns == NEVER:
        return "never"
    sign = "-" if ns < 0 else ""
    ns = abs(ns)
    return f"{sign}{ns // NANOS_PER_SEC}.{ns % NANOS_PER_SEC:09d}s"
