"""Counter-based deterministic RNG (Threefry-2x32).

The reference gives every host its own ``Xoshiro256PlusPlus`` stream seeded
from the master seed (sim_config.rs:50-51, host.rs:658).  A stateful
sequential generator cannot be replayed out-of-order, which is exactly what a
batched TPU backend needs to do — so we use a *counter-based* generator
instead: Threefry-2x32 (the same cipher JAX's PRNG is built on), keyed by
``(master_seed, stream)`` and indexed by a 64-bit counter.

One implementation, written against the array-API surface shared by ``numpy``
and ``jax.numpy``, is used by both the CPU reference backend and the TPU lane
backend; the bit-identical outputs are what make cross-backend deterministic
replay possible (the property the reference gates with its determinism tests,
src/test/determinism/CMakeLists.txt:1-45).

Stream-id conventions (keep in one place so backends can't disagree):

- ``stream = host_id | LOSS_STREAM``   : per-packet Bernoulli loss decisions
- ``stream = host_id | APP_STREAM``    : application-model draws (phold peer
  picks, payload sizes, think times)
- ``stream = host_id | PORT_STREAM``   : ephemeral port allocation
- counter = the per-host monotonically increasing draw sequence number for
  that stream (each stream counts independently).
"""

from __future__ import annotations

from typing import Any, Tuple

import numpy as np

# High bits or'd into the stream id to separate draw purposes.
LOSS_STREAM = 1 << 30
APP_STREAM = 2 << 30
PORT_STREAM = 3 << 30

_ROTATIONS = ((13, 15, 26, 6), (17, 29, 16, 24))
_PARITY = 0x1BD11BDA


def _rotl(x: Any, d: int, xp: Any) -> Any:
    u32 = xp.uint32
    return ((x << u32(d)) | (x >> u32(32 - d))).astype(u32)


def threefry2x32(k0: Any, k1: Any, c0: Any, c1: Any, xp: Any = np) -> Tuple[Any, Any]:
    """Threefry-2x32, 20 rounds.  All inputs uint32 arrays (or scalars);
    returns two uint32 arrays of the broadcast shape."""
    if xp is np:
        # Wrapping u32 arithmetic is the point; silence numpy's scalar
        # overflow warning (arrays wrap silently, 0-d scalars warn).
        with np.errstate(over="ignore"):
            return _threefry2x32_impl(k0, k1, c0, c1, xp)
    return _threefry2x32_impl(k0, k1, c0, c1, xp)


def _threefry2x32_impl(k0: Any, k1: Any, c0: Any, c1: Any, xp: Any) -> Tuple[Any, Any]:
    u32 = xp.uint32
    ks0 = xp.asarray(k0, dtype=u32)
    ks1 = xp.asarray(k1, dtype=u32)
    ks2 = (ks0 ^ ks1 ^ u32(_PARITY)).astype(u32)
    x0 = (xp.asarray(c0, dtype=u32) + ks0).astype(u32)
    x1 = (xp.asarray(c1, dtype=u32) + ks1).astype(u32)

    schedule = (
        (_ROTATIONS[0], ks1, ks2),
        (_ROTATIONS[1], ks2, ks0),
        (_ROTATIONS[0], ks0, ks1),
        (_ROTATIONS[1], ks1, ks2),
        (_ROTATIONS[0], ks2, ks0),
    )
    for i, (rots, add0, add1) in enumerate(schedule):
        for r in rots:
            x0 = (x0 + x1).astype(u32)
            x1 = _rotl(x1, r, xp)
            x1 = (x1 ^ x0).astype(u32)
        x0 = (x0 + add0).astype(u32)
        x1 = (x1 + add1 + u32(i + 1)).astype(u32)
    return x0, x1


def _split_seed(seed: int) -> Tuple[int, int]:
    seed &= (1 << 64) - 1
    return seed & 0xFFFFFFFF, (seed >> 32) & 0xFFFFFFFF


def rand_u32(seed: int, stream: Any, counter: Any, xp: Any = np) -> Any:
    """One uniform uint32 per (stream, counter) pair; shapes broadcast."""
    return rand_u32_pair(seed, stream, counter, xp)[0]


def rand_u32_pair(seed: int, stream: Any, counter: Any, xp: Any = np) -> Tuple[Any, Any]:
    s_lo, s_hi = _split_seed(seed)
    u32 = xp.uint32
    k0 = u32(s_lo)
    k1 = (xp.asarray(stream, dtype=u32) ^ u32(s_hi)).astype(u32)
    counter = xp.asarray(counter)
    c0 = counter.astype(xp.uint64).astype(u32)
    c1 = (counter.astype(xp.uint64) >> xp.uint64(32)).astype(u32)
    return threefry2x32(k0, k1, c0, c1, xp)


def u32_below(u: Any, n: Any, xp: Any = np) -> Any:
    """Map a uniform uint32 to ``[0, n)`` by the multiply-shift trick.

    Slightly biased for huge ``n`` but branch-free and bit-identical across
    backends, which is what matters here.
    """
    u64 = xp.uint64
    return ((xp.asarray(u, dtype=u64) * xp.asarray(n, dtype=u64)) >> u64(32)).astype(
        xp.uint32
    )


def loss_threshold(packet_loss: float) -> int:
    """Convert a loss probability to the Bernoulli drop threshold:
    drop iff ``uint64(rand_u32) < threshold``.

    The comparison domain is **u64**, not u32: ``packet_loss=1.0`` maps to
    ``2**32``, which must always drop and is unrepresentable in u32 (it would
    wrap to "never drop").  Backends store loss tables in int64/uint64 lanes
    and widen the draw before comparing.
    """
    if packet_loss <= 0.0:
        return 0
    if packet_loss >= 1.0:
        return 1 << 32  # > any u32 draw: always drop
    return int(packet_loss * 4294967296.0)


def host_seed(master_seed: int, host_id: int) -> int:
    """Per-host 64-bit sub-seed (analog of ``seed ^ hostname_hash``,
    sim_config.rs:242) — used for host-local sequential draws on the CPU
    path where a cheap stateful stream is handy."""
    x = (master_seed ^ (host_id * 0x9E3779B97F4A7C15)) & ((1 << 64) - 1)
    # splitmix64 finalizer
    x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9 & ((1 << 64) - 1)
    x = (x ^ (x >> 27)) * 0x94D049BB133111EB & ((1 << 64) - 1)
    return x ^ (x >> 31)
