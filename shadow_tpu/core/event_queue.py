"""Per-host min-heap event queue.

Host-side analog of the reference's ``EventQueue``
(src/main/core/work/event_queue.rs:11): a binary heap ordered by the total
event order of :mod:`shadow_tpu.core.event`.  Unlike the reference we do not
need a panicking-ord wrapper — Python tuple comparison is total on ints.

The queue also tracks ``next_time`` cheaply for the manager's per-round
min-next-event-time reduction (manager.rs:570-601).
"""

from __future__ import annotations

import heapq
from typing import Iterator, Optional

from .event import Event
from .time import NEVER


class EventQueue:
    __slots__ = ("_heap",)

    def __init__(self) -> None:
        self._heap: list[Event] = []

    def __len__(self) -> int:
        return len(self._heap)

    def push(self, ev: Event) -> None:
        heapq.heappush(self._heap, ev)

    def peek(self) -> Optional[Event]:
        return self._heap[0] if self._heap else None

    def next_time(self) -> int:
        """Time of the earliest event, or ``NEVER`` when empty."""
        return self._heap[0].time if self._heap else NEVER

    def pop(self) -> Event:
        return heapq.heappop(self._heap)

    def pop_until(self, until: int) -> Iterator[Event]:
        """Pop events with ``time < until`` in total order (the body of
        ``Host::execute`` — host.rs:769-803)."""
        while self._heap and self._heap[0].time < until:
            yield heapq.heappop(self._heap)

    def drain(self) -> Iterator[Event]:
        while self._heap:
            yield heapq.heappop(self._heap)
