"""Events and their total deterministic order.

The reference derives simulation determinism from a *total* order on events
(src/main/core/work/event.rs:84-130): events are ordered by

  1. time (ns),
  2. event-kind discriminant (packet events sort before local/task events at
     the same instant),
  3. source host id,
  4. per-source monotonically increasing event id.

We keep exactly that rule.  The order key is four integers, which both the
host-side binary heap and the device-side multi-key ``lax.sort`` can order
lexicographically, so CPU and TPU backends agree bit-for-bit on execution
order.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Callable, Optional


class EventKind(enum.IntEnum):
    """Discriminant part of the event order (packet < local, as in the
    reference where ``EventData::Packet`` sorts first).

    DELIVERY is a third kind (not in the reference, which uses closures):
    post-bandwidth datagram deliveries to the app layer.  It has its own
    discriminant so its keys — ``(time, DELIVERY, packet_src, packet_seq)``
    — live in a separate space from timer/task keys ``(time, LOCAL,
    self_host, local_seq)``; on a self-send the two spaces could otherwise
    collide and make the total order ambiguous, which the TPU backend's
    ``lax.sort`` replay cannot reproduce."""

    PACKET = 0
    LOCAL = 1
    DELIVERY = 2


@dataclasses.dataclass(frozen=True)
class OrderKey:
    """The 4-tuple total order.  ``sort_key()`` gives a plain tuple usable by
    ``heapq``; the device packs the same fields into sort operands."""

    time: int
    kind: int
    src_host: int
    seq: int

    def sort_key(self) -> tuple[int, int, int, int]:
        return (self.time, self.kind, self.src_host, self.seq)


@dataclasses.dataclass
class Event:
    """A scheduled occurrence on one host.

    ``data`` is either a :class:`~shadow_tpu.net.packet.Packet` (for
    ``EventKind.PACKET``) or a callable task ``fn(host) -> None`` (for
    ``EventKind.LOCAL``), mirroring the reference's
    ``EventData::{Packet, Local}`` (core/work/event.rs:10).
    """

    time: int
    kind: EventKind
    src_host: int
    seq: int
    data: Any = None
    label: str = ""

    def key(self) -> tuple[int, int, int, int]:
        return (self.time, int(self.kind), self.src_host, self.seq)

    def __lt__(self, other: "Event") -> bool:
        return self.key() < other.key()


TaskFn = Callable[..., None]


@dataclasses.dataclass
class Task:
    """Refcounted-closure analog of the reference ``TaskRef``
    (core/work/task.rs): a host-local callback plus a debug label."""

    fn: TaskFn
    label: str = ""

    def execute(self, host: Any) -> None:
        self.fn(host)
