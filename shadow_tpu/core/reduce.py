"""Canonical order-independent float reduction (the host-side seam).

Naive ``sum()`` over floats rounds after every addition, so the result
depends on accumulation order — which is exactly the kind of hidden
ordering dependence the determinism contract forbids (shadowlint SL105).
The sanctioned spelling is :func:`fsum` (:func:`math.fsum`): exactly
rounded, so ANY accumulation order produces the same bits — no
canonical pre-sort is needed or useful.

Device-side (jaxpr) reductions have their own seam: keep them integral
or exactly representable (shadowlint SL205, docs/analysis.md).
"""

from __future__ import annotations

import math

fsum = math.fsum
