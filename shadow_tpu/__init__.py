"""shadow_tpu — a TPU-native discrete-event network simulator.

A ground-up rebuild of the capabilities of Shadow (reference:
``iiins0mn1a/shadow-gen``): a deterministic discrete-event simulation of an
IPv4 network (latency/loss graph, CoDel router queues, bandwidth token
buckets, simulated TCP/UDP transports) driving managed applications, with the
per-round packet-scheduling hot path implemented as a batched JAX/XLA program
— one lane per simulated host — behind a ``network-backend={cpu,tpu}`` switch
with bit-identical event ordering between backends.

Package layout:

- ``core``      time, events, queues, counter-based RNG (the determinism core)
- ``config``    typed-unit options, YAML config
- ``net``       graph/routing, packets, CoDel, token buckets, DNS
- ``transport`` sans-I/O UDP/TCP state machines
- ``engine``    controller/manager round loop, hosts, workers
- ``backend``   the cpu reference backend and the TPU lane backend
- ``models``    built-in workloads (phold, tgen-style traffic, ping)
- ``ops``       pallas kernels for the hot ops
- ``parallel``  device-mesh sharding of host lanes
- ``utils``     counters, pcap, logging, sim-stats

64-bit JAX mode is required: all simulation time is int64 nanoseconds (see
``core.time``).  Importing this package enables it; import ``shadow_tpu``
before the first ``jax`` trace.
"""

from jax import config as _jax_config

_jax_config.update("jax_enable_x64", True)

__version__ = "0.1.0"
