"""Sans-I/O TCP: the simulated transport state machine.

Rebuild of the reference's TCP capability — the sans-I/O Rust crate
(src/lib/tcp/src/{lib,states,connection,seq,window_scaling,buffer}.rs:
typestate machine Init/Listen/SynSent/SynReceived/Established/FinWait1/
FinWait2/Closing/TimeWait/CloseWait/LastAck/Rst/Closed, push_packet /
pop_packet / send / recv / poll API) plus the Reno congestion control the
reference keeps in its legacy C stack (src/main/host/descriptor/tcp.c,
tcp_cong_reno.c) — re-designed for this framework:

- **sans-I/O and sans-clock**: no timers are registered anywhere; every
  time-dependent entry point takes ``now`` (int ns) explicitly, and
  :meth:`TcpState.next_timeout` exposes the earliest deadline for the host
  event loop to schedule.  (The reference abstracts the clock behind a
  ``Dependencies`` trait, lib.rs:10-47; an explicit integer clock is the
  same idea with a TPU-friendly shape.)
- **fixed-size integer state record**: every field of the protocol state
  (sequence space, windows, Reno, RTO) is a plain integer, so the lane
  backend can hold the same machine as an ``[N]``-array column each
  (backend/lanes.py, later milestone); byte buffers live host-side only.
- one segment timed for RTT at a time (Karn's rule: no samples from
  retransmitted data), RFC 6298 integer smoothing, exponential RTO backoff.

Intentional deviations (documented for the parity harness):

- no delayed ACK and no Nagle: every push that consumes data or a control
  flag triggers an immediate ACK; interactive-traffic coalescing is a
  wall-clock heuristic that hurts a discrete-event simulation's
  determinism budget and hides send/recv causality.
- loss recovery is NewReno + SACK (RFC 2018 receiver blocks from the
  reassembly stash, an RFC 6675-style sender scoreboard walking un-SACKed
  holes, ack-paced) — the capability of the reference's C++
  tcp_retransmit_tally.cc range bookkeeping.  SACK option bytes are not
  charged to the simulated wire size (documented simplification).
- no TCP timestamps / PAWS; simulated sequence spaces never wrap within a
  connection's lifetime at simulated bandwidths.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional

from ..net import ltcp

SEQ_MASK = 0xFFFFFFFF
NANOS_PER_SEC = 1_000_000_000

# -- wrapping 32-bit sequence arithmetic (seq.rs) ---------------------------


def seq_add(a: int, n: int) -> int:
    return (a + n) & SEQ_MASK


def seq_sub(a: int, b: int) -> int:
    """Distance a - b in sequence space (mod 2^32)."""
    return (a - b) & SEQ_MASK


def seq_lt(a: int, b: int) -> bool:
    """a < b in wrapping sequence order."""
    d = (b - a) & SEQ_MASK
    return 0 < d < 0x80000000


def seq_le(a: int, b: int) -> bool:
    return a == b or seq_lt(a, b)


def seq_gt(a: int, b: int) -> bool:
    return seq_lt(b, a)


def seq_ge(a: int, b: int) -> bool:
    return a == b or seq_lt(b, a)


def seq_max(a: int, b: int) -> int:
    return a if seq_ge(a, b) else b


def _merge_ranges(rel: list) -> list:
    """Fold sorted-or-not relative [a, b) ranges into a merged ascending
    list (shared by the receiver's SACK blocks and the sender scoreboard —
    one algorithm, one adjacency rule)."""
    merged: list[list[int]] = []
    for a, b in sorted(rel):
        if merged and a <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], b)
        else:
            merged.append([a, b])
    return merged


# -- wire vocabulary --------------------------------------------------------


class TcpFlags(enum.IntFlag):
    FIN = 0x01
    SYN = 0x02
    RST = 0x04
    PSH = 0x08
    ACK = 0x10


@dataclasses.dataclass(frozen=True)
class TcpHeader:
    """One simulated TCP segment header (lib.rs:679 TcpHeader).  Addresses
    are (ip_u32, port) pairs; ``wscale`` is the window-scale option, present
    only on SYN segments (window_scaling.rs)."""

    src_ip: int
    src_port: int
    dst_ip: int
    dst_port: int
    seq: int
    ack: int
    flags: TcpFlags
    window: int  # as transmitted (already scaled down by the sender)
    wscale: Optional[int] = None  # SYN-only option
    sack_ok: bool = False  # SYN-only option: SACK permitted (RFC 2018)
    sack: tuple = ()  # up to 3 (start, end-exclusive) SACK blocks

    HEADER_BYTES = 20  # simulated wire size of the TCP header

    def src(self) -> tuple[int, int]:
        return (self.src_ip, self.src_port)

    def dst(self) -> tuple[int, int]:
        return (self.dst_ip, self.dst_port)


class State(enum.IntEnum):
    """states.rs:23-120 typestate set, as a plain enum: the lane backend
    stores this as an int column, and transitions become table lookups."""

    INIT = 0
    LISTEN = 1
    SYN_SENT = 2
    SYN_RECEIVED = 3
    ESTABLISHED = 4
    FIN_WAIT_1 = 5
    FIN_WAIT_2 = 6
    CLOSING = 7
    TIME_WAIT = 8
    CLOSE_WAIT = 9
    LAST_ACK = 10
    RST = 11
    CLOSED = 12


class PollState(enum.IntFlag):
    """lib.rs:602 PollState bits."""

    READABLE = 0x01
    WRITABLE = 0x02
    READY_TO_ACCEPT = 0x04
    ERROR = 0x08
    CLOSED = 0x10
    CONNECTING = 0x20
    RECV_CLOSED = 0x40
    SEND_CLOSED = 0x80


class TcpError(enum.IntEnum):
    NONE = 0
    RESET = 1
    TIMED_OUT = 2
    REFUSED = 3


@dataclasses.dataclass
class TcpConfig:
    """lib.rs:646 TcpConfig + the Reno/RTO knobs of the legacy C stack."""

    mss: int = 1460
    send_buffer: int = 131072  # reference experimental.socket_send_buffer
    recv_buffer: int = 174760  # reference experimental.socket_recv_buffer
    window_scaling: bool = True
    max_wscale: int = 8
    rto_initial: int = NANOS_PER_SEC  # RFC 6298 initial RTO
    rto_min: int = 200_000_000  # Linux's 200 ms floor
    rto_max: int = 60 * NANOS_PER_SEC
    syn_retries: int = 6
    data_retries: int = 15
    time_wait: int = 60 * NANOS_PER_SEC  # 2*MSL
    init_cwnd_segments: int = 10  # Linux IW10
    sack: bool = True  # RFC 2018/6675 selective acknowledgment
    congestion: str = "reno"  # "reno" | "cubic" (tcp_cong.c's registry)


def _icbrt(x: int) -> int:
    """floor(cbrt(x)) for arbitrary non-negative Python ints (Newton)."""
    if x <= 0:
        return 0
    y = 1 << ((x.bit_length() + 2) // 3)
    while True:
        y2 = (2 * y + x // (y * y)) // 3
        if y2 >= y:
            while y * y * y > x:
                y -= 1
            return y
        y = y2


class CongestionControl:
    """The pluggable congestion-control operations of the reference's
    tcp_cong.c (tcp_cong_reno.c is one registered instance), byte units.
    ``grow_ca`` advances cwnd for one new ACK in congestion avoidance;
    ``on_loss`` sets ssthresh at loss detection (fast-retransmit entry
    and RTO) and updates any algorithm state."""

    name = "?"

    def grow_ca(self, tcp: "TcpState", now: int) -> None:
        raise NotImplementedError

    def on_loss(self, tcp: "TcpState", now: int) -> None:
        raise NotImplementedError


class RenoCC(CongestionControl):
    """NewReno (tcp_cong_reno.c): AIMD, +mss²/cwnd per ACK, halve on loss."""

    name = "reno"

    def grow_ca(self, tcp: "TcpState", now: int) -> None:
        mss = tcp.cfg.mss
        tcp.cwnd += max(mss * mss // max(tcp.cwnd, 1), 1)

    def on_loss(self, tcp: "TcpState", now: int) -> None:
        tcp.ssthresh = max(tcp._outstanding() // 2, 2 * tcp.cfg.mss)


class CubicCC(CongestionControl):
    """CUBIC (RFC 9438) in bytes with the same fixed-point time algebra
    as the lane tier's law (net/ltcp.py, whose CUBIC_* constants this
    class shares): q units of 2**20 ns, a second approximated as 2**30
    ns, C = CUBIC_C_MUL/1024, beta = 0.3.  Scalar-only stack on plain
    Python ints, so — unlike the int32 lane twin — no epoch/offset
    clamps: windows here are bounded by buffers, not by RWND_SEGS, and
    the unclamped cubic must keep advancing for arbitrarily large
    W_max - cwnd gaps and epoch ages."""

    name = "cubic"

    def __init__(self) -> None:
        self.w_max = 0  # bytes
        self.epoch: Optional[int] = None  # ns
        self.origin = 0  # bytes
        self.k_q = 0

    def grow_ca(self, tcp: "TcpState", now: int) -> None:
        mss = tcp.cfg.mss
        if self.epoch is None:
            self.epoch = now
            if tcp.cwnd < self.w_max:
                self.origin = self.w_max
                # K_q^3 = (w_max - cwnd)/mss / 0.4 * 2**30  (exact 2.5x)
                self.k_q = _icbrt(
                    (self.w_max - tcp.cwnd) * 5 * (1 << 30) // (2 * mss)
                )
            else:
                self.origin = tcp.cwnd
                self.k_q = 0
        d_q = (now - self.epoch) >> 20
        offs = d_q - self.k_q
        neg = offs < 0
        if neg:
            offs = -offs
        # delta bytes = C * (offs/1024 s)^3 * mss = offs^3*mss*C_MUL >> 40
        delta = (offs * offs * offs * mss * ltcp.CUBIC_C_MUL) >> 40
        target = self.origin - delta if neg else self.origin + delta
        if target > tcp.cwnd:
            tcp.cwnd += max((target - tcp.cwnd) * mss // tcp.cwnd, 1)
        else:  # at/above the curve: minimal probing growth
            tcp.cwnd += max(mss * mss // (100 * max(tcp.cwnd, 1)), 1)

    def on_loss(self, tcp: "TcpState", now: int) -> None:
        if tcp.cwnd < self.w_max:  # fast convergence
            self.w_max = (tcp.cwnd * ltcp.CUBIC_FC_MUL) >> 10
        else:
            self.w_max = tcp.cwnd
        self.epoch = None
        tcp.ssthresh = max(
            (tcp.cwnd * ltcp.CUBIC_BETA_MUL) >> 10, 2 * tcp.cfg.mss
        )


CC_REGISTRY = {"reno": RenoCC, "cubic": CubicCC}


def make_cc(name: str) -> CongestionControl:
    try:
        return CC_REGISTRY[name]()
    except KeyError:
        raise ValueError(f"unknown congestion-control algorithm {name!r}")


class TcpState:
    """One TCP connection endpoint (lib.rs:244 TcpState).

    Usage: construct, then ``connect`` (active) or arrive via
    :class:`TcpListener` (passive).  Feed inbound segments with
    ``push_packet(now, header, payload)``; drain outbound segments with
    ``pop_packet(now)`` while ``wants_to_send()``; exchange app bytes with
    ``send``/``recv``; drive timeouts by calling ``on_timer(now)`` whenever
    ``next_timeout()`` expires."""

    def __init__(self, config: Optional[TcpConfig] = None) -> None:
        self.cfg = config or TcpConfig()
        self.state = State.INIT
        self.error = TcpError.NONE
        # addressing (set by connect/listener)
        self.local_ip = 0
        self.local_port = 0
        self.remote_ip = 0
        self.remote_port = 0
        # send sequence space (RFC 793): una <= nxt
        self.iss = 0
        self.snd_una = 0
        self.snd_nxt = 0  # next new byte to transmit (rewound on RTO)
        self.snd_max = 0  # highest sequence ever transmitted
        self.snd_wnd = self.cfg.mss  # peer-advertised, scaled up
        self.snd_wl1 = 0
        self.snd_wl2 = 0
        self.snd_wscale = 0  # shift applied to windows the peer advertises
        # receive sequence space
        self.irs = 0
        self.rcv_nxt = 0
        self.rcv_wscale = 0  # shift we advertise (and divide our window by)
        self.rcv_fin_seq: Optional[int] = None  # peer FIN position, if seen
        # congestion state (tcp_cong.c; the algorithm object carries any
        # per-connection extra state, e.g. CUBIC's epoch)
        self.cc = make_cc(self.cfg.congestion)
        self.cwnd = 0
        self.ssthresh = 1 << 30
        self.dup_acks = 0
        self.recover = 0  # NewReno recovery point
        self.in_recovery = False
        # RTO state (RFC 6298, integer ns)
        self.srtt = 0
        self.rttvar = 0
        self.rto = self.cfg.rto_initial
        self.rto_deadline: Optional[int] = None
        self.retries = 0
        self.time_wait_deadline: Optional[int] = None
        # RTT sampling: one timed segment at a time (Karn)
        self.ts_seq: Optional[int] = None
        self.ts_time = 0
        self.ts_retransmitted = False
        # buffers: send bytes snd_una..(snd_una+len(_snd_buf)); recv in-order
        self._snd_buf = bytearray()
        self._rcv_buf = bytearray()
        self._ooo: dict[int, bytes] = {}  # seq -> payload (reassembly)
        # SACK (RFC 2018 receiver blocks + RFC 6675-style sender holes):
        # negotiated on the SYN exchange; the scoreboard holds merged
        # (start, end-exclusive) ranges the peer reported holding, always
        # above snd_una; the cursor walks un-SACKed holes during recovery
        self.sack_enabled = False
        self._sacked: list[tuple[int, int]] = []
        self._rexmit_cursor = 0
        self._last_ooo: Optional[int] = None  # most recent stash (block 1)
        # control-signal latches
        self.syn_pending = False  # need to emit SYN / SYN-ACK
        self.fin_pending = False  # app closed; FIN not yet sent
        self.fin_seq: Optional[int] = None  # our FIN's sequence number
        self.ack_pending = False  # need to emit at least a pure ACK
        self.rexmit_pending = False  # head-of-line retransmit requested
        self.recv_shutdown = False

    # ------------------------------------------------------------------ api

    def connect(
        self,
        local: tuple[int, int],
        remote: tuple[int, int],
        iss: int,
        now: int,
    ) -> None:
        """Active open (lib.rs:285): emit SYN, go SYN_SENT.  ``iss`` comes
        from the host's deterministic RNG stream."""
        if self.state != State.INIT:
            raise ValueError(f"connect in state {self.state.name}")
        self.local_ip, self.local_port = local
        self.remote_ip, self.remote_port = remote
        self._set_iss(iss)
        if self.cfg.window_scaling:
            self.rcv_wscale = self._pick_wscale()
        self.state = State.SYN_SENT
        self.syn_pending = True
        self._arm_rto(now)

    def _set_iss(self, iss: int) -> None:
        self.iss = iss & SEQ_MASK
        self.snd_una = self.iss
        self.snd_nxt = self.iss  # SYN consumes one; accounted at emit
        self.snd_max = self.iss
        self.cwnd = self.cfg.init_cwnd_segments * self.cfg.mss

    def _pick_wscale(self) -> int:
        w = 0
        while (self.cfg.recv_buffer >> w) > 0xFFFF and w < self.cfg.max_wscale:
            w += 1
        return w

    def send(self, data: bytes) -> int:
        """Queue app bytes; returns accepted count (0 = would block)."""
        if self.state in (
            State.INIT,
            State.LISTEN,
            State.RST,
            State.CLOSED,
            State.TIME_WAIT,
        ):
            raise BrokenPipeError("send in non-sending state")
        if self.fin_pending or self.fin_seq is not None:
            raise BrokenPipeError("send after shutdown")
        room = self.cfg.send_buffer - len(self._snd_buf)
        take = min(room, len(data))
        if take > 0:
            self._snd_buf.extend(data[:take])
        return take

    def available(self) -> int:
        """Bytes recv() would return right now (FIONREAD)."""
        return len(self._rcv_buf)

    def peek(self, max_len: int) -> bytes:
        """Read in-order received bytes without consuming them (MSG_PEEK:
        no buffer drain, so no window update either)."""
        return bytes(self._rcv_buf[:max_len])

    def recv(self, max_len: int) -> bytes:
        """Drain in-order received bytes (empty = would block or EOF;
        distinguish via poll())."""
        out = bytes(self._rcv_buf[:max_len])
        del self._rcv_buf[:max_len]
        if out:
            # freeing buffer space opens the advertised window
            self.ack_pending = True
        return out

    def close(self, now: int) -> None:
        """Full close (lib.rs:266): queue FIN after pending data."""
        if self.state in (State.INIT, State.LISTEN):
            self.state = State.CLOSED
            return
        if self.state in (State.RST, State.CLOSED, State.TIME_WAIT):
            return
        if self.fin_pending or self.fin_seq is not None:
            return
        self.fin_pending = True
        self._arm_rto(now)

    def shutdown_recv(self) -> None:
        self.recv_shutdown = True
        self._rcv_buf.clear()

    def abort(self) -> None:
        """RST out (socket closed with data pending, or refused)."""
        self.state = State.RST if self.error != TcpError.NONE else State.CLOSED

    # ------------------------------------------------------------- inbound

    def push_packet(self, now: int, hdr: TcpHeader, payload: bytes = b"") -> None:
        """Process one inbound segment (lib.rs:309)."""
        if self.state in (State.CLOSED, State.RST):
            return
        if hdr.flags & TcpFlags.RST:
            self._on_rst(hdr)
            return
        if self.state == State.SYN_SENT:
            self._push_syn_sent(now, hdr)
            return
        # ---- RFC 793 sequence acceptability ------------------------------
        seg_len = len(payload)
        if not self._seq_acceptable(hdr.seq, seg_len, hdr.flags):
            self.ack_pending = True  # resynchronizing ACK
            return
        if hdr.flags & TcpFlags.SYN and self.state == State.SYN_RECEIVED:
            # duplicate SYN (our SYN-ACK was lost): re-ack
            self.syn_pending = True
            return
        if hdr.flags & TcpFlags.ACK:
            self._process_ack(now, hdr, seg_len)
        if seg_len:
            self._process_data(hdr.seq, payload)
        if hdr.flags & TcpFlags.FIN:
            self._process_fin(now, seq_add(hdr.seq, seg_len))

    def _push_syn_sent(self, now: int, hdr: TcpHeader) -> None:
        if not hdr.flags & TcpFlags.SYN:
            return
        self.irs = hdr.seq
        self.rcv_nxt = seq_add(hdr.seq, 1)
        if hdr.flags & TcpFlags.ACK and hdr.ack == seq_add(self.iss, 1):
            # normal open: SYN-ACK
            self.snd_una = hdr.ack
            self.snd_nxt = hdr.ack
            if hdr.wscale is not None and self.cfg.window_scaling:
                self.snd_wscale = hdr.wscale
            else:
                self.snd_wscale = 0
                self.rcv_wscale = 0  # peer didn't negotiate: both sides off
            self.sack_enabled = self.cfg.sack and hdr.sack_ok
            self.snd_wnd = hdr.window << self.snd_wscale
            self.snd_wl1 = hdr.seq
            self.snd_wl2 = hdr.ack
            self.state = State.ESTABLISHED
            self.ack_pending = True
            self.retries = 0
            # the SYN<->SYN-ACK exchange is an RTT sample (Karn applies)
            if self.ts_seq is not None and not self.ts_retransmitted:
                self._rtt_sample(now - self.ts_time)
            self.ts_seq = None
            self._disarm_rto_if_idle(now)
        else:
            # simultaneous open
            self.state = State.SYN_RECEIVED
            self.syn_pending = True

    def _seq_acceptable(self, seq: int, seg_len: int, flags: TcpFlags) -> bool:
        rcv_wnd = self._recv_window()
        seg_end = seq_add(seq, max(seg_len - 1, 0))
        if seg_len == 0:
            if rcv_wnd == 0:
                return seq == self.rcv_nxt
            return seq_le(self.rcv_nxt, seq) and seq_lt(
                seq, seq_add(self.rcv_nxt, rcv_wnd)
            ) or seq == self.rcv_nxt or seq_lt(seq, self.rcv_nxt)
        if rcv_wnd == 0:
            return False
        in_wnd = lambda s: seq_le(self.rcv_nxt, s) and seq_lt(
            s, seq_add(self.rcv_nxt, rcv_wnd)
        )
        # accept partly-old segments (retransmits overlapping rcv_nxt)
        return in_wnd(seq) or in_wnd(seg_end) or (
            seq_lt(seq, self.rcv_nxt) and seq_ge(seg_end, self.rcv_nxt)
        )

    def _on_rst(self, hdr: TcpHeader) -> None:
        if self.state == State.SYN_SENT:
            if hdr.flags & TcpFlags.ACK and hdr.ack == seq_add(self.iss, 1):
                self.error = TcpError.REFUSED
                self.state = State.RST
            return
        # window check: only in-window RSTs take effect
        if seq_lt(hdr.seq, self.rcv_nxt) or (
            self._recv_window() > 0
            and seq_ge(hdr.seq, seq_add(self.rcv_nxt, self._recv_window()))
        ):
            if hdr.seq != self.rcv_nxt:
                return
        self.error = TcpError.RESET
        self.state = State.RST
        self._snd_buf.clear()
        self._rcv_buf.clear()
        self.rto_deadline = None

    def _process_ack(self, now: int, hdr: TcpHeader, seg_len: int) -> None:
        ack = hdr.ack
        if seq_gt(ack, self.snd_max):
            self.ack_pending = True  # acks data we never sent
            return
        if self.sack_enabled and hdr.sack:
            self._sack_merge(hdr.sack)
        # window update (RFC 793 SND.WL1/WL2 discipline)
        if seq_lt(self.snd_wl1, hdr.seq) or (
            self.snd_wl1 == hdr.seq and seq_le(self.snd_wl2, ack)
        ):
            self.snd_wnd = hdr.window << self.snd_wscale
            self.snd_wl1 = hdr.seq
            self.snd_wl2 = ack

        if seq_gt(ack, self.snd_una):
            newly = seq_sub(ack, self.snd_una)
            self._advance_send_space(now, ack, newly)
        elif (
            ack == self.snd_una
            and self._outstanding() > 0
            and seg_len == 0
            and not hdr.flags & TcpFlags.FIN
            and not hdr.flags & TcpFlags.SYN
        ):
            self._on_dup_ack(now)

        self._maybe_transition_on_ack(now, ack)

    def _advance_send_space(self, now: int, ack: int, newly: int) -> None:
        """Cumulative ACK advanced: trim buffer, sample RTT, grow cwnd."""
        mss = self.cfg.mss
        # RTT sample (Karn: only if the timed segment wasn't retransmitted)
        if (
            self.ts_seq is not None
            and seq_gt(ack, self.ts_seq)
            and not self.ts_retransmitted
        ):
            self._rtt_sample(now - self.ts_time)
        if self.ts_seq is not None and seq_gt(ack, self.ts_seq):
            self.ts_seq = None

        data_acked = newly
        # the SYN consumes a sequence number but no buffer byte
        if seq_le(self.snd_una, self.iss) and seq_gt(ack, self.iss):
            data_acked -= 1
        # so does our FIN
        if self.fin_seq is not None and seq_gt(ack, self.fin_seq):
            data_acked -= 1
        if data_acked > 0:
            del self._snd_buf[:data_acked]
        self.snd_una = ack
        if self._sacked:
            self._sack_trim()
        if seq_gt(ack, self.snd_nxt):
            # a cumulative ACK past an RTO rewind point: everything up to it
            # is delivered, skip re-sending (go-back-N with snd_max memory)
            self.snd_nxt = ack

        if self.in_recovery:
            if seq_ge(ack, self.recover):
                # full recovery: deflate (NewReno)
                self.in_recovery = False
                self.cwnd = self.ssthresh
                self.dup_acks = 0
            else:
                # partial ack: retransmit next hole, stay in recovery
                self._rexmit_cursor = self.snd_una
                self.rexmit_pending = True
                self.cwnd = max(self.cwnd - newly + mss, mss)
        else:
            self.dup_acks = 0
            if self.cwnd < self.ssthresh:
                self.cwnd += min(newly, mss)  # slow start
            else:
                self.cc.grow_ca(self, now)  # per-algorithm CA growth
        self.retries = 0
        if self._outstanding() > 0 or self.fin_pending or self.syn_pending:
            self._arm_rto(now)
        else:
            self.rto_deadline = None
            self.rto = self._computed_rto()

    def _on_dup_ack(self, now: int) -> None:
        mss = self.cfg.mss
        self.dup_acks += 1
        if self.in_recovery:
            self.cwnd += mss  # inflate per extra dup-ack
            if self._holes_remain():
                # SACK: each returning dup-ack clocks out the next hole
                # instead of waiting for a partial ack per hole (the
                # go-back-N stall the scoreboard exists to avoid)
                self.rexmit_pending = True
        elif self.dup_acks == 3:
            # fast retransmit (tcp_cong.c entry: per-algorithm ssthresh)
            self.cc.on_loss(self, now)
            self.recover = self.snd_max
            self.in_recovery = True
            self.cwnd = self.ssthresh + 3 * mss
            self._rexmit_cursor = self.snd_una
            self.rexmit_pending = True

    def _maybe_transition_on_ack(self, now: int, ack: int) -> None:
        fin_acked = self.fin_seq is not None and seq_gt(ack, self.fin_seq)
        if self.state == State.SYN_RECEIVED and seq_gt(ack, self.iss):
            self.state = State.ESTABLISHED
            self.retries = 0
        if self.state == State.FIN_WAIT_1 and fin_acked:
            self.state = State.FIN_WAIT_2
            self.rto_deadline = None
        elif self.state == State.CLOSING and fin_acked:
            self._enter_time_wait(now)
        elif self.state == State.LAST_ACK and fin_acked:
            self.state = State.CLOSED
            self.rto_deadline = None

    def _process_data(self, seq: int, payload: bytes) -> None:
        # clip the old prefix of partly-duplicate segments
        if seq_lt(seq, self.rcv_nxt):
            skip = seq_sub(self.rcv_nxt, seq)
            if skip >= len(payload):
                self.ack_pending = True
                return
            payload = payload[skip:]
            seq = self.rcv_nxt
        if self.recv_shutdown:
            self.ack_pending = True
            return
        room = self._recv_room()
        if seq == self.rcv_nxt:
            take = min(len(payload), room)
            if take:
                self._rcv_buf.extend(payload[:take])
                self.rcv_nxt = seq_add(self.rcv_nxt, take)
                self._drain_ooo()
        elif room > 0 and len(self._ooo) < 256:
            self._ooo.setdefault(seq, payload)
            self._last_ooo = seq
        self.ack_pending = True

    def _drain_ooo(self) -> None:
        # purge stashes made fully obsolete by the in-order advance
        for s in [
            s
            for s, p in self._ooo.items()
            if seq_le(seq_add(s, len(p)), self.rcv_nxt)
        ]:
            del self._ooo[s]
        while True:
            nxt = self._ooo.pop(self.rcv_nxt, None)
            if nxt is None:
                # also handle overlapping stashes
                hit = None
                for s, p in self._ooo.items():
                    if seq_le(s, self.rcv_nxt) and seq_gt(
                        seq_add(s, len(p)), self.rcv_nxt
                    ):
                        hit = s
                        break
                if hit is None:
                    return
                p = self._ooo.pop(hit)
                nxt = p[seq_sub(self.rcv_nxt, hit):]
            take = min(len(nxt), self._recv_room())
            if take <= 0:
                return
            self._rcv_buf.extend(nxt[:take])
            self.rcv_nxt = seq_add(self.rcv_nxt, take)

    def _process_fin(self, now: int, fin_seq: int) -> None:
        if fin_seq != self.rcv_nxt:
            # FIN beyond a hole: remember, ack what we have
            self.rcv_fin_seq = fin_seq
            self.ack_pending = True
            return
        self.rcv_fin_seq = fin_seq
        self.rcv_nxt = seq_add(self.rcv_nxt, 1)
        self.ack_pending = True
        if self.state in (State.ESTABLISHED, State.SYN_RECEIVED):
            self.state = State.CLOSE_WAIT
        elif self.state == State.FIN_WAIT_1:
            # our FIN not yet acked -> simultaneous close
            self.state = State.CLOSING
        elif self.state == State.FIN_WAIT_2:
            self._enter_time_wait(now)

    def _enter_time_wait(self, now: int) -> None:
        self.state = State.TIME_WAIT
        self.rto_deadline = None
        self.time_wait_deadline = now + self.cfg.time_wait

    # ------------------------------------------------------------ outbound

    def wants_to_send(self) -> bool:
        """lib.rs:333 — does pop_packet have a segment to emit?"""
        if self.state in (State.INIT, State.LISTEN, State.CLOSED, State.RST):
            return False
        if self.syn_pending or self.ack_pending or self.rexmit_pending:
            return True
        if self._sendable_data() > 0:
            return True
        if self.fin_pending and len(self._snd_buf) == self._unsent_offset():
            return True
        return False

    def pop_packet(self, now: int) -> Optional[tuple[TcpHeader, bytes]]:
        """Emit the next outbound segment (lib.rs:318), or None."""
        if self.state in (State.INIT, State.LISTEN, State.CLOSED, State.RST):
            return None
        if self.syn_pending:
            return self._emit_syn(now)
        if self.rexmit_pending:
            return self._emit_retransmit(now)
        if self._sendable_data() > 0:
            return self._emit_data(now)
        if self.fin_pending and self._unsent_offset() == len(self._snd_buf):
            return self._emit_fin(now)
        if self.ack_pending:
            self.ack_pending = False
            return (self._header(TcpFlags.ACK, self.snd_nxt), b"")
        return None

    def _header(
        self, flags: TcpFlags, seq: int, wscale: Optional[int] = None
    ) -> TcpHeader:
        sack = ()
        if self.sack_enabled and self._ooo and not flags & TcpFlags.SYN:
            sack = self._sack_blocks()
        return TcpHeader(
            src_ip=self.local_ip,
            src_port=self.local_port,
            dst_ip=self.remote_ip,
            dst_port=self.remote_port,
            seq=seq,
            ack=self.rcv_nxt,
            flags=flags,
            window=self._advertised_window(),
            wscale=wscale,
            sack=sack,
        )

    def _sack_blocks(self) -> tuple:
        """RFC 2018 blocks from the reassembly stash: merged above-window
        ranges, the block containing the most recent arrival first, the
        rest ascending, at most 3 (the option-space limit)."""
        merged = _merge_ranges([
            [seq_sub(q, self.rcv_nxt), seq_sub(q, self.rcv_nxt) + len(p)]
            for q, p in self._ooo.items()
        ])
        blocks = [
            (seq_add(self.rcv_nxt, a), seq_add(self.rcv_nxt, b))
            for a, b in merged
        ]
        if self._last_ooo is not None:
            lr = seq_sub(self._last_ooo, self.rcv_nxt)
            for i, (a, b) in enumerate(merged):
                if a <= lr < b and i != 0:
                    blocks.insert(0, blocks.pop(i))
                    break
        return tuple(blocks[:3])

    def _emit_syn(self, now: int) -> tuple[TcpHeader, bytes]:
        self.syn_pending = False
        self.ack_pending = False
        wscale = self.rcv_wscale if self.cfg.window_scaling else None
        if self.state == State.SYN_SENT:
            flags = TcpFlags.SYN
        else:  # SYN_RECEIVED: SYN-ACK
            flags = TcpFlags.SYN | TcpFlags.ACK
        hdr = self._header(flags, self.iss, wscale=wscale)
        hdr = dataclasses.replace(hdr, sack_ok=self.cfg.sack)
        if self.snd_nxt == self.iss:
            self.snd_nxt = seq_add(self.iss, 1)
        self.snd_max = seq_max(self.snd_max, self.snd_nxt)
        self._arm_rto(now)
        if self.ts_seq is None:
            self.ts_seq = self.iss
            self.ts_time = now
            self.ts_retransmitted = False
        return (hdr, b"")

    def _unsent_offset(self) -> int:
        """Bytes of _snd_buf already sent (between snd_una and snd_nxt),
        excluding SYN/FIN sequence slots."""
        sent = seq_sub(self.snd_nxt, self.snd_una)
        if seq_le(self.snd_una, self.iss) and seq_ge(self.snd_nxt, seq_add(self.iss, 1)):
            sent -= 1  # SYN slot still unacked
        if self.fin_seq is not None and seq_gt(self.snd_nxt, self.fin_seq):
            sent -= 1
        return sent

    def _flight(self) -> int:
        """Window-gating flight: bytes between the cumulative-ack point and
        the *current* transmit cursor."""
        return seq_sub(self.snd_nxt, self.snd_una)

    def _outstanding(self) -> int:
        """Loss-bookkeeping flight: bytes ever sent and not yet acked
        (survives the RTO rewind of snd_nxt)."""
        return seq_sub(self.snd_max, self.snd_una)

    def _send_window(self) -> int:
        return min(self.snd_wnd, self.cwnd)

    def _sendable_data(self) -> int:
        if self.state not in (
            State.ESTABLISHED,
            State.CLOSE_WAIT,
            State.FIN_WAIT_1,  # rewound pre-FIN bytes retransmit from here
            State.CLOSING,
            State.LAST_ACK,
        ):
            return 0
        # every byte in _snd_buf is pre-FIN by construction (send() raises
        # after shutdown), so an RTO rewind may legitimately re-send them
        # even with the FIN outstanding
        unsent = len(self._snd_buf) - self._unsent_offset()
        wnd_room = self._send_window() - self._flight()
        return max(min(unsent, wnd_room), 0)

    def _emit_data(self, now: int) -> tuple[TcpHeader, bytes]:
        off = self._unsent_offset()
        n = min(self._sendable_data(), self.cfg.mss)
        payload = bytes(self._snd_buf[off : off + n])
        seq = self.snd_nxt
        flags = TcpFlags.ACK
        if off + n == len(self._snd_buf):
            flags |= TcpFlags.PSH
        self.snd_nxt = seq_add(self.snd_nxt, n)
        self.snd_max = seq_max(self.snd_max, self.snd_nxt)
        self.ack_pending = False
        if self.ts_seq is None:
            self.ts_seq = seq
            self.ts_time = now
            self.ts_retransmitted = False
        self._arm_rto_if_unarmed(now)
        return (self._header(flags, seq), payload)

    def _emit_fin(self, now: int) -> tuple[TcpHeader, bytes]:
        self.fin_pending = False
        self.fin_seq = self.snd_nxt
        self.snd_nxt = seq_add(self.snd_nxt, 1)
        self.snd_max = seq_max(self.snd_max, self.snd_nxt)
        self.ack_pending = False
        if self.state in (State.ESTABLISHED, State.SYN_RECEIVED):
            self.state = State.FIN_WAIT_1
        elif self.state == State.CLOSE_WAIT:
            self.state = State.LAST_ACK
        self._arm_rto(now)
        return (self._header(TcpFlags.FIN | TcpFlags.ACK, self.fin_seq), b"")

    def _sack_merge(self, blocks) -> None:
        """Fold reported blocks into the scoreboard (merged, above
        snd_una, relative ordering via wrapping distance from snd_una)."""
        base = self.snd_una
        rel = []
        for a, b in list(self._sacked) + [list(x) for x in blocks]:
            ra, rb = seq_sub(a, base), seq_sub(b, base)
            if rb == 0 or rb > 0x7FFFFFFF:
                continue  # entirely below the cumulative ack (or garbage)
            if ra > 0x7FFFFFFF:
                ra = 0  # straddles the ack point: clip to it
            if ra < rb:
                rel.append([ra, rb])
        merged = _merge_ranges(rel)
        self._sacked = [
            (seq_add(base, a), seq_add(base, b)) for a, b in merged
        ]

    def _sack_trim(self) -> None:
        self._sack_merge(())  # re-normalizing against the new snd_una

    def _next_hole(self, cursor: int) -> tuple[int, int]:
        """(hole_start, hole_limit) of the first un-SACKed range at/after
        ``cursor`` (skipping scoreboard ranges); limit caps the hole's
        length at the next SACKed range.  Falls back to (cursor, huge)
        when the scoreboard is empty — plain NewReno head retransmit."""
        base = self.snd_una
        pos = seq_sub(cursor, base)
        if pos > 0x7FFFFFFF:
            pos = 0
        for a, b in ((seq_sub(x, base), seq_sub(y, base))
                     for x, y in self._sacked):
            if pos < a:
                return (seq_add(base, pos), a - pos)
            if pos < b:
                pos = b
        return (seq_add(base, pos), 1 << 30)

    def _holes_remain(self) -> bool:
        """Un-SACKed, un-retransmitted sequence space below snd_max?
        Only meaningful WITH a scoreboard: on a non-SACK connection the
        empty-scoreboard fallback would claim a hole at the cursor and
        every dup-ack would blind-resend the next in-flight segment —
        data the receiver provably already holds."""
        if not self.in_recovery or not self._sacked:
            return False
        hole, _ = self._next_hole(seq_max(self._rexmit_cursor, self.snd_una))
        return seq_lt(hole, self.snd_max)

    def _emit_retransmit(self, now: int) -> tuple[TcpHeader, bytes]:
        """Head-of-line retransmission (fast retransmit / RTO / partial ack)."""
        self.rexmit_pending = False
        self.ack_pending = False
        if self.ts_seq is not None:
            self.ts_retransmitted = True
        # SYN / SYN-ACK retransmit
        if seq_le(self.snd_una, self.iss):
            wscale = self.rcv_wscale if self.cfg.window_scaling else None
            flags = (
                TcpFlags.SYN
                if self.state == State.SYN_SENT
                else TcpFlags.SYN | TcpFlags.ACK
            )
            self.snd_nxt = seq_max(self.snd_nxt, seq_add(self.iss, 1))
            self.snd_max = seq_max(self.snd_max, self.snd_nxt)
            self._arm_rto(now)
            hdr = dataclasses.replace(
                self._header(flags, self.iss, wscale=wscale),
                sack_ok=self.cfg.sack,
            )
            return (hdr, b"")
        # FIN retransmit
        if self.fin_seq is not None and self.snd_una == self.fin_seq:
            self.snd_nxt = seq_max(self.snd_nxt, seq_add(self.fin_seq, 1))
            self.snd_max = seq_max(self.snd_max, self.snd_nxt)
            self._arm_rto(now)
            return (self._header(TcpFlags.FIN | TcpFlags.ACK, self.fin_seq), b"")
        # data retransmit: the lowest un-SACKed hole (RFC 6675 NextSeg;
        # with an empty scoreboard this is the NewReno head at snd_una)
        cur = self.snd_una
        if self.in_recovery:
            cur = seq_max(self._rexmit_cursor, self.snd_una)
        hole, limit = self._next_hole(cur)
        if seq_ge(hole, self.snd_max):
            hole, limit = self._next_hole(self.snd_una)
        if self.fin_seq is not None and hole == self.fin_seq:
            # every data hole is SACKed/acked; the lost segment is the FIN
            self.snd_nxt = seq_max(self.snd_nxt, seq_add(self.fin_seq, 1))
            self.snd_max = seq_max(self.snd_max, self.snd_nxt)
            self._rexmit_cursor = seq_add(self.fin_seq, 1)
            self._arm_rto(now)
            return (self._header(TcpFlags.FIN | TcpFlags.ACK, self.fin_seq), b"")
        off = seq_sub(hole, self.snd_una)
        n = min(self.cfg.mss, limit, len(self._snd_buf) - off)
        if n <= 0:
            # stale cursor (e.g. everything above was just SACKed): head
            hole = self.snd_una
            off = 0
            n = min(self.cfg.mss, len(self._snd_buf))
        payload = bytes(self._snd_buf[off : off + n])
        self._rexmit_cursor = seq_add(hole, n)
        self.snd_nxt = seq_max(self.snd_nxt, seq_add(hole, n))
        self.snd_max = seq_max(self.snd_max, self.snd_nxt)
        self._arm_rto(now)
        return (self._header(TcpFlags.ACK, hole), payload)

    # -------------------------------------------------------------- timers

    def next_timeout(self) -> Optional[int]:
        """Earliest deadline; the host schedules a timer event for it."""
        deadlines = [
            d for d in (self.rto_deadline, self.time_wait_deadline) if d is not None
        ]
        return min(deadlines) if deadlines else None

    def on_timer(self, now: int) -> None:
        """Fire expired deadlines (retransmission timeout / 2MSL)."""
        if (
            self.time_wait_deadline is not None
            and now >= self.time_wait_deadline
        ):
            self.time_wait_deadline = None
            if self.state == State.TIME_WAIT:
                self.state = State.CLOSED
        if self.rto_deadline is not None and now >= self.rto_deadline:
            self.rto_deadline = None
            self._on_rto(now)

    def _on_rto(self, now: int) -> None:
        if (
            self._outstanding() == 0
            and not self.syn_pending
            and not self.fin_pending
        ):
            return
        in_handshake = self.state in (State.SYN_SENT, State.SYN_RECEIVED)
        limit = self.cfg.syn_retries if in_handshake else self.cfg.data_retries
        self.retries += 1
        if self.retries > limit:
            self.error = (
                TcpError.REFUSED if in_handshake else TcpError.TIMED_OUT
            )
            self.state = State.RST
            return
        mss = self.cfg.mss
        # RTO response: collapse to one segment; per-algorithm ssthresh
        self.cc.on_loss(self, now)
        self.cwnd = mss
        self.in_recovery = False
        self.dup_acks = 0
        # go-back-N: rewind transmission to the cumulative-ack point
        # (conservative RFC 2018 stance: drop the scoreboard so the
        # re-walk is a plain linear resend)
        self._sacked = []
        self._rexmit_cursor = self.snd_una
        self.snd_nxt = self.snd_una
        if self.fin_seq is not None and seq_lt(self.snd_una, self.fin_seq):
            # data ahead of the FIN rewound too: re-queue the FIN to be
            # re-emitted after the data (its old slot is now unreachable)
            self.fin_seq = None
            self.fin_pending = True
        self.rexmit_pending = True
        self.rto = min(self.rto * 2, self.cfg.rto_max)  # exponential backoff
        self._arm_rto(now)

    def _rtt_sample(self, r: int) -> None:
        r = max(r, 1)
        if self.srtt == 0:
            self.srtt = r
            self.rttvar = r // 2
        else:
            err = abs(self.srtt - r)
            self.rttvar = (3 * self.rttvar + err) // 4
            self.srtt = (7 * self.srtt + r) // 8
        self.rto = self._computed_rto()

    def _computed_rto(self) -> int:
        if self.srtt == 0:
            return self.cfg.rto_initial
        return max(
            min(self.srtt + max(4 * self.rttvar, 1_000_000), self.cfg.rto_max),
            self.cfg.rto_min,
        )

    def _arm_rto(self, now: int) -> None:
        self.rto_deadline = now + self.rto

    def _arm_rto_if_unarmed(self, now: int) -> None:
        if self.rto_deadline is None:
            self._arm_rto(now)

    def _disarm_rto_if_idle(self, now: int) -> None:
        if self._outstanding() == 0 and not self.fin_pending:
            self.rto_deadline = None

    # ------------------------------------------------------------- windows

    def _recv_room(self) -> int:
        return max(self.cfg.recv_buffer - len(self._rcv_buf), 0)

    def _recv_window(self) -> int:
        # round down to the advertisable granularity so both ends agree
        return (self._recv_room() >> self.rcv_wscale) << self.rcv_wscale

    def _advertised_window(self) -> int:
        return min(self._recv_room() >> self.rcv_wscale, 0xFFFF)

    # --------------------------------------------------------------- state

    def poll(self) -> PollState:
        """lib.rs:328 — readiness bits for poll/epoll integration."""
        ps = PollState(0)
        if self.error != TcpError.NONE:
            ps |= PollState.ERROR
        if self.state in (State.CLOSED, State.RST):
            ps |= PollState.CLOSED
            if self._rcv_buf:
                ps |= PollState.READABLE
            return ps
        if self.state in (State.SYN_SENT, State.SYN_RECEIVED):
            return ps | PollState.CONNECTING
        if self._rcv_buf or self._at_eof():
            ps |= PollState.READABLE
        if (
            self.state in (State.ESTABLISHED, State.CLOSE_WAIT)
            and not self.fin_pending
            and self.fin_seq is None
            and len(self._snd_buf) < self.cfg.send_buffer
        ):
            ps |= PollState.WRITABLE
        if self._at_eof():
            ps |= PollState.RECV_CLOSED
        if self.fin_seq is not None or self.fin_pending:
            ps |= PollState.SEND_CLOSED
        return ps

    def _at_eof(self) -> bool:
        """True when the peer's FIN has been fully consumed: reads past the
        in-order buffer return EOF."""
        return (
            self.rcv_fin_seq is not None
            and self.rcv_nxt == seq_add(self.rcv_fin_seq, 1)
            and not self._ooo
        )

    def at_eof(self) -> bool:
        return self._at_eof() and not self._rcv_buf

    def is_closed(self) -> bool:
        return self.state in (State.CLOSED, State.RST)

    def four_tuple(self) -> tuple[int, int, int, int]:
        return (self.local_ip, self.local_port, self.remote_ip, self.remote_port)


class TcpListener:
    """Passive open (states.rs ListenState): owns the backlog of embryonic
    and accept-ready children.  The demultiplexer (socket layer) routes
    SYNs for the listening port here; everything else goes to the child
    matching the 4-tuple."""

    def __init__(
        self,
        local: tuple[int, int],
        backlog: int = 128,
        config: Optional[TcpConfig] = None,
    ) -> None:
        self.local = local
        self.backlog = max(backlog, 1)
        self.cfg = config or TcpConfig()
        # embryonic + established children by (peer_ip, peer_port)
        self.children: dict[tuple[int, int], TcpState] = {}
        self.closed = False

    def push_syn(self, now: int, hdr: TcpHeader, iss: int) -> Optional[TcpState]:
        """Handle an inbound SYN: create (or re-ack) the embryonic child.
        Returns the child owning the segment, or None if dropped."""
        if self.closed:
            return None
        key = hdr.src()
        child = self.children.get(key)
        if child is not None:
            child.push_packet(now, hdr)
            return child
        if len(self.children) >= self.backlog:
            return None  # SYN dropped; the client's RTO will retry
        child = TcpState(dataclasses.replace(self.cfg))
        # the child's local address is the SYN's destination (a listener
        # on INADDR_ANY accepts on whichever interface the SYN targeted —
        # so loopback connections get a 127.0.0.1 local end, like Linux)
        child.local_ip, child.local_port = hdr.dst_ip, hdr.dst_port
        child.remote_ip, child.remote_port = key
        child._set_iss(iss)
        if child.cfg.window_scaling and hdr.wscale is not None:
            child.rcv_wscale = child._pick_wscale()
            child.snd_wscale = hdr.wscale
        else:
            child.rcv_wscale = 0
            child.snd_wscale = 0
        child.irs = hdr.seq
        child.rcv_nxt = seq_add(hdr.seq, 1)
        child.sack_enabled = child.cfg.sack and hdr.sack_ok
        child.snd_wnd = hdr.window  # unscaled until SYN negotiation done
        child.snd_wl1 = hdr.seq
        child.snd_wl2 = child.iss
        child.state = State.SYN_RECEIVED
        child.syn_pending = True
        child._arm_rto(now)
        self.children[key] = child
        return child

    def accept(self) -> Optional[TcpState]:
        """Pop one ESTABLISHED child (lib.rs:294), connection order by
        (peer_ip, peer_port) for determinism."""
        for key in sorted(self.children):
            child = self.children[key]
            if child.state in (State.ESTABLISHED, State.CLOSE_WAIT):
                del self.children[key]
                return child
        return None

    def has_ready(self) -> bool:
        return any(
            c.state in (State.ESTABLISHED, State.CLOSE_WAIT)
            for c in self.children.values()
        )

    def poll(self) -> PollState:
        return PollState.READY_TO_ACCEPT if self.has_ready() else PollState(0)

    def close(self) -> None:
        self.closed = True
        self.children.clear()
