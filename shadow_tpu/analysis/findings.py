"""Finding model and the shadowlint rule registry.

Every hazard class has a stable rule ID.  SL1xx rules are the AST pass
(:mod:`.astlint`), SL2xx rules are the jaxpr pass (:mod:`.jaxpr_audit`).
The registry is the single source of truth: the CLI's ``--list-rules``,
the baseline validator, and ``docs/analysis.md`` all derive from it.
"""

from __future__ import annotations

import dataclasses
import hashlib

# rule id -> (title, rationale).  The rationale states the determinism
# contract the hazard breaks — shown by ``--list-rules`` and the docs.
RULES: dict[str, tuple[str, str]] = {
    "SL101": (
        "wall-clock read",
        "time.time/datetime.now/perf_counter feed wall time into code that "
        "must depend only on sim time; bench/metrics timing must go through "
        "the `import time as wall_time` alias (or a listed bench module) so "
        "intent is explicit and reviewable.",
    ),
    "SL102": (
        "unseeded global RNG",
        "global random.*/np.random.* draws (and np.random.default_rng() "
        "with no seed) are seeded from the OS; all simulation randomness "
        "must come from the counter-based core.rng streams.",
    ),
    "SL103": (
        "unordered set iteration",
        "iterating a set (or building a list/tuple from one) in an "
        "ordering-sensitive module lets hash-seed layout pick the event "
        "order; wrap the iterable in sorted().",
    ),
    "SL104": (
        "id()-based ordering",
        "CPython id() is an address: sorting or comparing by it makes the "
        "event order depend on allocator layout.",
    ),
    "SL105": (
        "float accumulation outside the canonical reduction helpers",
        "builtin sum() over floats rounds per-step, so the result depends "
        "on accumulation order; route through core.reduce.fsum (exactly "
        "rounded, order-independent) or keep the arithmetic integral.",
    ),
    "SL106": (
        "environment/filesystem read in an engine step path",
        "os.environ/os.getenv/open() inside the round loop imports host "
        "state into the simulation; read configuration once at setup time "
        "and thread it through.",
    ),
    "SL201": (
        "float64 in a traced kernel",
        "x64 mode is enabled for int64 sim time only; an f64 aval in the "
        "lane program is almost always a leaked Python float and doubles "
        "the HBM cost of whatever carries it.",
    ),
    "SL202": (
        "weak-type float in a traced kernel",
        "a weakly-typed float scalar promotes differently per backend "
        "(host axis vs device) — pin the dtype at the literal.",
    ),
    "SL203": (
        "unstable sort in a traced kernel",
        "lax.sort(is_stable=False) may order equal keys differently across "
        "backends/XLA versions; every kernel sort must be stable or use a "
        "total key.",
    ),
    "SL204": (
        "host callback inside a jitted region",
        "io_callback/debug.callback/pure_callback execute host Python "
        "mid-kernel with unordered effects — hoist to window boundaries.",
    ),
    "SL205": (
        "non-associative float reduction off the fixed-order seam",
        "a float reduce/cumsum/dot changes value with XLA's reduction "
        "order unless the values are exactly representable (e.g. one-hot "
        "counts in f32 below 2**24) — keep reductions integral, exact, or "
        "on the fixed-order reduction seam, and baseline the proven-exact "
        "ones per entry.",
    ),
}


def rule_doc(rule: str) -> str:
    title, rationale = RULES[rule]
    return f"{rule} {title}: {rationale}"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One hazard at one location.

    ``path``/``line`` locate AST findings; jaxpr findings use the kernel
    label as the path and line 0, with ``detail`` carrying the primitive
    and aval signature.  ``fingerprint`` is stable across unrelated edits
    (it hashes content, not line numbers) so baseline entries survive
    rebases.
    """

    rule: str
    path: str
    line: int
    col: int
    message: str
    detail: str = ""
    # nth identical (rule, path, detail) hazard in the file, in line
    # order — so a second textually identical hazard line gets its OWN
    # fingerprint instead of riding an existing baseline entry.  0 is
    # excluded from the hash so single-occurrence fingerprints (and the
    # shipped baseline) are unchanged.
    occurrence: int = 0

    @property
    def fingerprint(self) -> str:
        parts = (self.rule, self.path, self.detail or self.message)
        if self.occurrence:
            parts += (str(self.occurrence),)
        h = hashlib.sha256("\x1f".join(parts).encode())
        return h.hexdigest()[:16]

    def render(self) -> str:
        loc = f"{self.path}:{self.line}:{self.col}" if self.line else self.path
        return f"{loc}: {self.rule} {self.message}  [{self.fingerprint}]"
