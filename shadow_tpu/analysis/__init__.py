"""shadowlint — static determinism & lane-parity analysis for shadow_tpu.

The determinism contract (bit-identical event ordering across runs and
across backends, PAPER.md) is enforced *dynamically* by
:mod:`shadow_tpu.engine.determinism` — a run-twice diff that finds a
wall-clock leak or an unstable iteration order hours after it lands and
says nothing about *where*.  This package catches the hazards statically,
on the diff, in CI:

- **Pass 1** (:mod:`.astlint`) walks the package source flagging
  nondeterminism hazards — wall-clock reads, unseeded global RNG,
  unordered set iteration in ordering-sensitive modules, ``id()``-based
  ordering, float accumulation outside the canonical reduction helpers,
  and environment/filesystem reads inside engine step paths — each with
  a rule ID and a precise location.
- **Pass 2** (:mod:`.jaxpr_audit`) traces the lane/stream kernels with
  ``jax.make_jaxpr`` and audits the jaxpr for parity hazards: f64 leaks,
  weak-type promotion, unstable sorts, non-associative float reductions,
  and host callbacks inside jitted regions.

CLI: ``python -m shadow_tpu.analysis`` / ``make lint-determinism``
(exit 0 = clean, 1 = findings, 2 = usage/internal error).  Pre-existing
findings can be suppressed by the versioned baseline file
(:mod:`.baseline`) or inline ``# shadowlint: disable=SLxxx`` comments.

See ``docs/analysis.md`` for the rule catalog and how to add a rule.
"""

from .findings import Finding, RULES, rule_doc
from .astlint import lint_paths, lint_source
from .baseline import Baseline, load_baseline

__all__ = [
    "Finding",
    "RULES",
    "rule_doc",
    "lint_paths",
    "lint_source",
    "Baseline",
    "load_baseline",
]
