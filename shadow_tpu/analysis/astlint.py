"""Pass 1: AST determinism linter over the ``shadow_tpu`` source tree.

Pure :mod:`ast` — no imports of the linted modules, so a syntax-valid
file can always be linted even when its imports need a TPU runtime.

Scope model (see ``docs/analysis.md``):

- every module is checked for SL101/SL102/SL104/SL106-by-scope;
- *ordering-sensitive* modules (``engine/``, ``backend/``, ``net/``,
  ``faults/``, ``core/``, ``obs/``) additionally get SL103 (unordered
  set iteration) and SL105 (float accumulation);
- *step-path* scope for SL106 is any function in ``engine/``/
  ``backend/``/``obs/`` whose name — or an enclosing function's name —
  matches ``STEP_NAME_RE`` (the round loop's vocabulary: step/iter/
  round/window/advance/tick/pop/drive/body; ``obs/`` is in scope
  because its emit paths run inside those rounds).

Intent escapes, in order of preference:

1. fix the hazard (sorted() wrapper, core.rng stream, wall_time alias);
2. inline ``# shadowlint: disable=SLxxx`` on the offending line (or a
   standalone comment on the line above) with a justifying comment;
3. a justified entry in the versioned baseline file (:mod:`.baseline`).

The ``import time as wall_time`` alias is the package's declared-intent
convention for bench/metrics wall timing (it predates this linter —
``backend/tpu_engine.py`` et al.); SL101 trusts any ``wall_*`` alias and
flags the rest.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Iterable, Optional

from .findings import Finding

ORDERING_SENSITIVE = (
    "engine", "backend", "net", "faults", "core", "obs", "sweep",
)
STEP_PATH_DIRS = ("engine", "backend", "obs", "sweep")
STEP_NAME_RE = re.compile(
    r"(step|iter|round|window|advance|tick|pop|drive|body)"
)

# wall-clock callables by canonical dotted name (after import resolution)
WALL_CLOCK_FNS = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns", "time.clock_gettime",
    "time.clock_gettime_ns", "time.process_time", "time.process_time_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
}

# global-draw functions on the stdlib `random` module and `numpy.random`
GLOBAL_RNG_FNS = {
    "seed", "random", "randint", "randrange", "randbytes", "getrandbits",
    "choice", "choices", "shuffle", "sample", "uniform", "triangular",
    "betavariate", "expovariate", "gammavariate", "gauss", "normalvariate",
    "lognormvariate", "vonmisesvariate", "paretovariate", "weibullvariate",
    "permutation", "rand", "randn", "standard_normal", "bytes",
}

SUPPRESS_RE = re.compile(r"#\s*shadowlint:\s*disable=([A-Z0-9,\s]+)")


def _module_flags(relpath: str) -> tuple[bool, bool]:
    parts = Path(relpath).parts
    return (
        any(p in ORDERING_SENSITIVE for p in parts),
        any(p in STEP_PATH_DIRS for p in parts),
    )


def _suppressions(src: str) -> dict[int, set[str]]:
    """line number -> rule ids disabled there.  A standalone suppression
    comment also covers the next line."""
    out: dict[int, set[str]] = {}
    for i, line in enumerate(src.splitlines(), start=1):
        m = SUPPRESS_RE.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        out.setdefault(i, set()).update(rules)
        if line.lstrip().startswith("#"):  # comment-only line: covers next
            out.setdefault(i + 1, set()).update(rules)
    return out


class _ImportMap:
    """Local name -> canonical dotted prefix, from the module's imports."""

    def __init__(self) -> None:
        self.names: dict[str, str] = {}

    def visit(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.names[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.names[a.asname or a.name] = f"{node.module}.{a.name}"

    def resolve(self, node: ast.expr) -> Optional[str]:
        """Canonical dotted name of an attribute/name chain, or None."""
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.names.get(node.id, node.id)
        return ".".join([root] + list(reversed(parts)))

    def alias_of(self, node: ast.expr) -> Optional[str]:
        """The local root name of an attribute chain (the import alias)."""
        while isinstance(node, ast.Attribute):
            node = node.value
        return node.id if isinstance(node, ast.Name) else None


def _is_setish(node: ast.expr, set_names: set[str]) -> bool:
    """Conservatively: does this expression evaluate to a set?"""
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Name):
        return node.id in set_names
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Name) and f.id in ("set", "frozenset"):
            return True
        if isinstance(f, ast.Attribute) and f.attr in (
            "union", "intersection", "difference", "symmetric_difference",
        ):
            return _is_setish(f.value, set_names)
    if isinstance(node, ast.BinOp) and isinstance(
        node.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)
    ):
        return _is_setish(node.left, set_names) or _is_setish(
            node.right, set_names
        )
    return False


def _set_names_in_scope(scope: ast.AST) -> set[str]:
    """Names whose visible assignments in this scope are all set-valued.
    Nested function bodies are separate scopes and are skipped."""
    assigns: dict[str, list[ast.expr]] = {}

    def record(node: ast.AST) -> None:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    assigns.setdefault(t.id, []).append(node.value)
        elif isinstance(node, ast.AnnAssign) and isinstance(
            node.target, ast.Name
        ):
            ann = ast.unparse(node.annotation)
            if ann.startswith(("set", "Set", "frozenset", "FrozenSet")):
                assigns.setdefault(node.target.id, []).append(ast.Set(elts=[]))
            elif node.value is not None:
                assigns.setdefault(node.target.id, []).append(node.value)

    def collect(node: ast.AST) -> None:
        record(node)
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue  # nested scope
            collect(child)

    for stmt in getattr(scope, "body", []):
        if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            collect(stmt)
    # two classification passes so one-hop aliases (b = a) resolve
    out: set[str] = set()
    for _ in range(2):
        for name, values in assigns.items():
            if values and all(_is_setish(v, out) for v in values):
                out.add(name)
    return out


def _contains_floatish(node: ast.expr) -> bool:
    """Syntactic float signals: float literal, float() call, true division."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, float):
            return True
        if (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Name)
            and sub.func.id == "float"
        ):
            return True
        if isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.Div):
            return True
    return False


def _is_id_key(node: ast.expr) -> bool:
    """key=id / key=hash / key=lambda x: id(x)-shaped argument."""
    if isinstance(node, ast.Name) and node.id in ("id", "hash"):
        return True
    if isinstance(node, ast.Lambda):
        for sub in ast.walk(node.body):
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Name)
                and sub.func.id in ("id", "hash")
            ):
                return True
    return False


class _Linter(ast.NodeVisitor):
    def __init__(
        self,
        path: str,
        src: str,
        ordering_sensitive: bool,
        step_path_module: bool,
    ) -> None:
        self.path = path
        self.lines = src.splitlines()
        self.ordering_sensitive = ordering_sensitive
        self.step_path_module = step_path_module
        self.imports = _ImportMap()
        self.findings: list[Finding] = []
        self._scope_sets: list[set[str]] = []
        self._func_stack: list[str] = []
        # comprehensions consumed by an order-free reducer (all/any/...)
        self._order_free: set[int] = set()

    # -- helpers -----------------------------------------------------------

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 0)
        snippet = (
            self.lines[line - 1].strip() if 0 < line <= len(self.lines) else ""
        )
        self.findings.append(
            Finding(
                rule=rule,
                path=self.path,
                line=line,
                col=getattr(node, "col_offset", 0),
                message=message,
                detail=snippet,
            )
        )

    def _set_names(self) -> set[str]:
        names: set[str] = set()
        for s in self._scope_sets:
            names |= s
        return names

    def _in_step_path(self) -> bool:
        return self.step_path_module and any(
            STEP_NAME_RE.search(n) for n in self._func_stack
        )

    # -- scope tracking ----------------------------------------------------

    def lint(self, tree: ast.Module) -> list[Finding]:
        self.imports.visit(tree)
        self._scope_sets.append(_set_names_in_scope(tree))
        self.generic_visit(tree)
        return self.findings

    def _visit_func(self, node) -> None:
        self._func_stack.append(node.name)
        self._scope_sets.append(_set_names_in_scope(node))
        self.generic_visit(node)
        self._scope_sets.pop()
        self._func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    # -- SL103: unordered iteration ---------------------------------------

    def _check_iterable(self, it: ast.expr) -> None:
        if self.ordering_sensitive and _is_setish(it, self._set_names()):
            self._emit(
                "SL103",
                it,
                "iteration over a set is hash-order dependent; wrap in "
                "sorted(...)",
            )

    def visit_For(self, node: ast.For) -> None:
        self._check_iterable(node.iter)
        self.generic_visit(node)

    def _visit_comp(self, node) -> None:
        if id(node) not in self._order_free:
            for gen in node.generators:
                self._check_iterable(gen.iter)
        self.generic_visit(node)

    visit_ListComp = _visit_comp
    visit_GeneratorExp = _visit_comp
    visit_DictComp = _visit_comp

    def visit_SetComp(self, node: ast.SetComp) -> None:
        # building a set FROM a set is order-free; don't descend the
        # generators with the set-iteration check
        self.generic_visit(node)

    # -- SL104: id()/hash() ordering in comparisons ------------------------

    def visit_Compare(self, node: ast.Compare) -> None:
        if any(isinstance(op, (ast.Lt, ast.LtE, ast.Gt, ast.GtE)) for op in node.ops):
            for side in [node.left] + node.comparators:
                if (
                    isinstance(side, ast.Call)
                    and isinstance(side.func, ast.Name)
                    and side.func.id in ("id", "hash")
                ):
                    self._emit(
                        "SL104",
                        node,
                        f"ordering by {side.func.id}() depends on allocator/"
                        "hash-seed layout",
                    )
                    break
        self.generic_visit(node)

    # -- calls: SL101/SL102/SL103(list/tuple)/SL104(key=)/SL105/SL106 ------

    def visit_Call(self, node: ast.Call) -> None:
        name = self.imports.resolve(node.func)
        alias = self.imports.alias_of(node.func)

        # all()/any()/min()/max()/len(), set constructors, and sorted()
        # consume their iterable order-independently — a set argument is
        # not a hazard there (sorted() IS the prescribed remediation, in
        # any spelling: sorted(s), sorted(x for x in s), sorted(list(s)))
        if isinstance(node.func, ast.Name) and node.func.id in (
            "all", "any", "min", "max", "len", "set", "frozenset", "sorted",
        ):
            for arg in node.args:
                if isinstance(
                    arg, (ast.GeneratorExp, ast.SetComp, ast.ListComp)
                ):
                    self._order_free.add(id(arg))
                elif (
                    node.func.id == "sorted"
                    and isinstance(arg, ast.Call)
                    and isinstance(arg.func, ast.Name)
                    and arg.func.id in ("list", "tuple", "iter")
                ):
                    self._order_free.add(id(arg))

        if name in WALL_CLOCK_FNS and not (alias or "").startswith("wall_"):
            self._emit(
                "SL101",
                node,
                f"wall-clock read {name}() outside the wall_time alias "
                "convention — sim code must use core.time; bench timing "
                "must import `time as wall_time`",
            )

        if name is not None:
            parts = name.split(".")
            if (
                parts[0] == "random"
                and len(parts) == 2
                and parts[1] in GLOBAL_RNG_FNS
            ) or (
                parts[0] in ("numpy", "np")
                and len(parts) == 3
                and parts[1] == "random"
                and parts[2] in GLOBAL_RNG_FNS
            ):
                self._emit(
                    "SL102",
                    node,
                    f"global RNG draw {name}() — use a seeded "
                    "core.rng stream (or a local random.Random(seed))",
                )
            if (
                parts[-2:] == ["random", "default_rng"]
                and not node.args
                and not node.keywords
            ):
                self._emit(
                    "SL102",
                    node,
                    "np.random.default_rng() without a seed draws OS "
                    "entropy",
                )

        if (
            isinstance(node.func, ast.Name)
            and node.func.id in ("list", "tuple", "enumerate", "iter")
            and node.args
            and id(node) not in self._order_free
        ):
            self._check_iterable(node.args[0])
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "join"
            and node.args
        ):
            self._check_iterable(node.args[0])

        is_sorted_call = (
            isinstance(node.func, ast.Name) and node.func.id == "sorted"
        ) or (isinstance(node.func, ast.Attribute) and node.func.attr == "sort")
        if is_sorted_call:
            for kw in node.keywords:
                if kw.arg == "key" and _is_id_key(kw.value):
                    self._emit(
                        "SL104",
                        node,
                        "sort key uses id()/hash(): ordering depends on "
                        "allocator/hash-seed layout",
                    )

        if (
            self.ordering_sensitive
            and isinstance(node.func, ast.Name)
            and node.func.id == "sum"
            and node.args
            and _contains_floatish(node.args[0])
        ):
            self._emit(
                "SL105",
                node,
                "float accumulation with builtin sum() rounds per-step; "
                "use core.reduce.fsum (exactly rounded) or keep it integral",
            )

        if self._in_step_path():
            if name in ("os.getenv",) or (
                isinstance(node.func, ast.Name) and node.func.id == "open"
            ):
                self._emit(
                    "SL106",
                    node,
                    f"{name or 'open'}() inside an engine step path reads "
                    "host state mid-round; hoist to setup",
                )

        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if self._in_step_path():
            name = self.imports.resolve(node)
            if name == "os.environ":
                self._emit(
                    "SL106",
                    node,
                    "os.environ inside an engine step path reads host "
                    "state mid-round; hoist to setup",
                )
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        # the from-import spelling: `from os import environ` makes every
        # later use a bare Name, never an os.environ Attribute chain
        if (
            self._in_step_path()
            and self.imports.names.get(node.id) == "os.environ"
        ):
            self._emit(
                "SL106",
                node,
                "os.environ inside an engine step path reads host "
                "state mid-round; hoist to setup",
            )
        self.generic_visit(node)


def lint_source(
    src: str,
    path: str = "<string>",
    *,
    ordering_sensitive: Optional[bool] = None,
    step_path_module: Optional[bool] = None,
) -> list[Finding]:
    """Lint one module's source.  Scope flags default from ``path``."""
    auto_os, auto_step = _module_flags(path)
    if ordering_sensitive is None:
        ordering_sensitive = auto_os
    if step_path_module is None:
        step_path_module = auto_step
    tree = ast.parse(src, filename=path)
    linter = _Linter(path, src, ordering_sensitive, step_path_module)
    findings = linter.lint(tree)
    # number textually identical hazards (same rule+detail) in line order
    # so each gets a distinct fingerprint — a new duplicate of a
    # baselined line must surface, not ride the existing entry.  Number
    # BEFORE dropping inline-suppressed ones, with trailing comments
    # stripped from the key, so suppressing the first duplicate (which
    # edits that line's text) does not shift the survivors' fingerprints.
    counts: dict[tuple[str, str], int] = {}
    numbered = []
    for f in sorted(findings, key=lambda f: (f.line, f.col, f.rule)):
        code = (f.detail or f.message).split("#", 1)[0].strip()
        key = (f.rule, code)
        n = counts.get(key, 0)
        counts[key] = n + 1
        numbered.append(dataclasses.replace(f, occurrence=n) if n else f)
    supp = _suppressions(src)
    return [f for f in numbered if f.rule not in supp.get(f.line, ())]


def module_paths(root: Path, rel_to: Optional[Path] = None) -> list[tuple[Path, str]]:
    """(file, repo-relative path) for every ``*.py`` under ``root``."""
    root = Path(root)
    rel_to = Path(rel_to) if rel_to is not None else root.parent
    files: Iterable[Path] = (
        [root] if root.is_file() else sorted(root.rglob("*.py"))
    )
    return [(f, f.relative_to(rel_to).as_posix()) for f in files]


def lint_paths(root: Path, rel_to: Optional[Path] = None) -> list[Finding]:
    """Lint every ``*.py`` under ``root`` (a package dir or one file)."""
    findings: list[Finding] = []
    for f, rel in module_paths(root, rel_to):
        findings.extend(lint_source(f.read_text(), rel))
    return findings
