"""Versioned baseline-suppression file for shadowlint.

A baseline entry suppresses exactly one finding by fingerprint and MUST
carry a human justification — the acceptance bar is "baseline file empty
or justified per-entry", so an empty ``reason`` is a hard load error.
The file is JSON so diffs review cleanly:

.. code-block:: json

    {
      "version": 1,
      "suppressions": [
        {"fingerprint": "0123abcd0123abcd",
         "rule": "SL205",
         "path": "kernel:flagship/iter",
         "reason": "one-hot histogram matmul: counts < 2**24, exact in f32"}
      ]
    }

``--write-baseline`` regenerates the file from the current findings,
with ``reason: "TODO: justify"`` placeholders for NEW entries only —
existing justifications are preserved by fingerprint and out-of-scope
entries are carried over verbatim.  The loader rejects TODO reasons, so
a freshly written baseline fails CI until each entry is justified or
the hazard is fixed.  Stale entries (fingerprints no longer reported)
are flagged so the baseline only ever shrinks.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Iterable

from .findings import RULES, Finding

BASELINE_VERSION = 1
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"
TODO_REASON = "TODO: justify"


class BaselineError(ValueError):
    """Malformed or unjustified baseline file."""


@dataclasses.dataclass
class Baseline:
    path: Path
    suppressions: dict[str, dict]  # fingerprint -> entry
    matched: set = dataclasses.field(default_factory=set)

    def suppresses(self, f: Finding) -> bool:
        entry = self.suppressions.get(f.fingerprint)
        if entry is None or entry["rule"] != f.rule:
            return False
        self.matched.add(f.fingerprint)
        return True

    def stale_entries(self, audited_paths: Iterable[str] | None = None) -> list[dict]:
        """Entries whose finding no longer exists — to be deleted.

        ``audited_paths`` scopes the check to what this run actually
        looked at (a ``--no-jaxpr`` run must not call kernel entries
        stale, and a single-file lint must not condemn the rest)."""
        audited = None if audited_paths is None else set(audited_paths)
        return [
            e
            for fp, e in sorted(self.suppressions.items())
            if fp not in self.matched
            and (audited is None or e["path"] in audited)
        ]


def load_baseline(path: Path | None = None) -> Baseline:
    path = Path(path) if path is not None else DEFAULT_BASELINE
    if not path.exists():
        return Baseline(path=path, suppressions={})
    try:
        data = json.loads(path.read_text())
    except json.JSONDecodeError as e:
        raise BaselineError(f"{path}: not valid JSON: {e}") from None
    if data.get("version") != BASELINE_VERSION:
        raise BaselineError(
            f"{path}: unsupported baseline version {data.get('version')!r} "
            f"(this tool writes version {BASELINE_VERSION})"
        )
    sup: dict[str, dict] = {}
    for i, e in enumerate(data.get("suppressions", [])):
        for key in ("fingerprint", "rule", "path", "reason"):
            if not isinstance(e.get(key), str) or not e.get(key):
                raise BaselineError(
                    f"{path}: suppression #{i} missing/empty {key!r}"
                )
        if e["rule"] not in RULES:
            raise BaselineError(
                f"{path}: suppression #{i} names unknown rule {e['rule']!r}"
            )
        if e["reason"].strip() == TODO_REASON:
            raise BaselineError(
                f"{path}: suppression #{i} ({e['rule']} at {e['path']}) is "
                "not justified — replace the TODO reason or fix the hazard"
            )
        if e["fingerprint"] in sup:
            raise BaselineError(
                f"{path}: duplicate fingerprint {e['fingerprint']}"
            )
        sup[e["fingerprint"]] = e
    return Baseline(path=path, suppressions=sup)


def write_baseline(
    path: Path,
    findings: Iterable[Finding],
    audited_paths: Iterable[str] | None = None,
) -> int:
    """Serialize ``findings`` as a fresh baseline; returns the entry count.

    Existing entries are never destroyed blindly: justifications are
    preserved by fingerprint, and entries whose ``path`` was NOT audited
    by this run (``audited_paths``, e.g. a ``--no-jaxpr`` or explicit-
    path run never looked at the kernels) are carried over verbatim —
    only entries the run actually re-checked can be dropped as fixed."""
    old_entries: list[dict] = []
    if Path(path).exists():
        try:
            data = json.loads(Path(path).read_text())
            old_entries = [
                e for e in data.get("suppressions", [])
                if isinstance(e.get("fingerprint"), str)
            ]
        except (json.JSONDecodeError, AttributeError) as e:
            # refusing beats silently replacing hand-written
            # justifications with TODOs (load_baseline hard-errors on
            # the same input; regeneration must not destroy more)
            raise BaselineError(
                f"{path}: existing baseline is unreadable ({e}); fix or "
                "delete it before --write-baseline"
            ) from None
    old_reasons = {
        e["fingerprint"]: e["reason"]
        for e in old_entries
        if isinstance(e.get("reason"), str)
    }
    audited = None if audited_paths is None else set(audited_paths)
    entries = []
    seen: set[str] = set()
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        if f.fingerprint in seen:
            continue
        seen.add(f.fingerprint)
        entries.append(
            {
                "fingerprint": f.fingerprint,
                "rule": f.rule,
                "path": f.path,
                "line": f.line,
                "message": f.message,
                "detail": f.detail,
                "reason": old_reasons.get(f.fingerprint, TODO_REASON),
            }
        )
    # entries this run did not re-check survive verbatim: out-of-scope
    # paths when a scope was given, ALL old entries when none was (a
    # caller that never said what it audited may not drop anything)
    for e in old_entries:
        if e["fingerprint"] not in seen and (
            audited is None or e.get("path") not in audited
        ):
            seen.add(e["fingerprint"])
            entries.append(e)
    Path(path).write_text(
        json.dumps(
            {"version": BASELINE_VERSION, "suppressions": entries}, indent=1
        )
        + "\n"
    )
    return len(entries)
