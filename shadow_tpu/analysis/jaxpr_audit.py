"""Pass 2: jaxpr parity auditor for the lane/stream/hybrid kernels.

Traces the real device programs with ``jax.make_jaxpr`` — no device run,
no compile — and audits every equation (recursing into while/cond/scan/
pjit sub-jaxprs) for the hazards that break cross-backend bit parity:

- SL201 float64 avals (x64 mode exists for int64 sim time; a traced f64
  is almost always a leaked Python float),
- SL202 weak-type float scalars (backend-dependent promotion),
- SL203 ``lax.sort`` with ``is_stable=False``,
- SL204 host callbacks inside the jitted region,
- SL205 non-associative float reductions (reduce_sum/cumsum/dot/psum on
  inexact dtypes) off the fixed-order reduction seam.  The lane kernel's
  one sanctioned float op — the one-hot histogram matmul, exact in f32
  for counts < 2**24 (``lanes._merge_append``) — carries a justified
  entry in the baseline file rather than an invisible in-code exemption.

Findings use ``kernel:<name>/<entry>`` as their path and a primitive/
dtype/shape signature as the fingerprint detail, so they are stable
across retraces and unrelated kernel edits.

The representative configs in :data:`KERNELS` are chosen to cover the
distinct program shapes: the pure-lane tier (phold), the passive packet
tier with loss (tgen UDP), and the compacted stream-TCP tier.  Adding a
new kernel family to the repo should add an entry here — the CLI audits
all of them by default.
"""

from __future__ import annotations

from typing import Iterable, Optional

from .findings import Finding

# reductions whose float result depends on XLA's accumulation order.
# max/min/argmax are order-independent; integer ops are exact; and
# reduce_precision is elementwise rounding (no accumulation at all).
_NONASSOC_REDUCE_PRIMS = {
    "reduce_sum", "reduce_prod", "cumsum", "cumprod", "cumlogsumexp",
    "dot_general", "add_any", "psum", "reduce_window_sum",
}

_CALLBACK_PRIMS = {"io_callback", "pure_callback", "debug_callback"}

KERNELS = {
    # pure lane tier: self-loop phold ring, the PDES classic
    "phold": """
general: {stop_time: 200ms, seed: 1}
experimental: {network_backend: tpu}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
        edge [ source 0 target 0 latency "5 ms" ]
      ]
hosts:
  p: {count: 8, network_node_id: 0, processes: [{path: phold, args: [--messages, "3"]}]}
""",
    # passive packet tier with loss sampling (counter RNG on-device)
    "tgen_udp": """
general: {stop_time: 100ms, seed: 3}
experimental: {network_backend: tpu}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "10 Mbit" host_bandwidth_down "10 Mbit" ]
        node [ id 1 host_bandwidth_up "10 Mbit" host_bandwidth_down "10 Mbit" ]
        edge [ source 0 target 1 latency "10 ms" packet_loss 0.2 ]
      ]
hosts:
  tx: {network_node_id: 0, processes: [{path: tgen-client, args: [--server, rx, --interval, 5ms, --size, "600"]}]}
  rx: {network_node_id: 1, processes: [{path: tgen-server}]}
""",
    # compacted stream-TCP tier (handshake/Reno/RTO law)
    "stream_tcp": """
general: {stop_time: 500ms, seed: 1}
experimental: {network_backend: tpu, tpu_lane_queue_capacity: 64}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "20 Mbit" host_bandwidth_down "20 Mbit" ]
        node [ id 1 host_bandwidth_up "20 Mbit" host_bandwidth_down "20 Mbit" ]
        edge [ source 0 target 0 latency "1 ms" ]
        edge [ source 0 target 1 latency "40 ms" packet_loss 0.02 ]
        edge [ source 1 target 1 latency "1 ms" ]
      ]
hosts:
  client: {count: 2, network_node_id: 0, processes: [{path: stream-client, args: [--server, server, --size, 64KiB]}]}
  server: {network_node_id: 1, processes: [{path: stream-server}]}
""",
}


def _aval_sig(v) -> str:
    aval = getattr(v, "aval", None)
    if aval is None or not hasattr(aval, "dtype"):
        return "?"
    weak = "w" if getattr(aval, "weak_type", False) else ""
    shape = "x".join(str(d) for d in getattr(aval, "shape", ()))
    return f"{aval.dtype.name}{weak}[{shape}]"


def _is_float(v) -> bool:
    aval = getattr(v, "aval", None)
    dtype = getattr(aval, "dtype", None)
    return dtype is not None and dtype.kind == "f"


def audit_jaxpr(closed_jaxpr, label: str) -> list[Finding]:
    """Audit one (closed) jaxpr; ``label`` becomes the finding path."""
    import jax.core  # noqa: F401  (jax import deferred to call time)

    findings: dict[str, Finding] = {}
    # number repeated identical signatures, mirroring the AST pass: a
    # SECOND equation with the same primitive/dtype/shape signature is a
    # distinct hazard needing its own baseline entry, not a free rider
    sig_counts: dict[tuple[str, str], int] = {}

    def emit(rule: str, message: str, detail: str) -> None:
        key = (rule, detail)
        n = sig_counts.get(key, 0)
        sig_counts[key] = n + 1
        f = Finding(
            rule=rule, path=label, line=0, col=0,
            message=message, detail=detail, occurrence=n,
        )
        findings[f.fingerprint] = f

    def walk(jaxpr) -> None:
        jaxpr = getattr(jaxpr, "jaxpr", jaxpr)  # ClosedJaxpr -> Jaxpr
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            in_sigs = ",".join(_aval_sig(v) for v in eqn.invars)
            out_sigs = ",".join(_aval_sig(v) for v in eqn.outvars)
            sig = f"{prim}({in_sigs})->{out_sigs}"
            if prim == "sort":
                sig += (
                    f"{{num_keys={eqn.params.get('num_keys')},"
                    f"dim={eqn.params.get('dimension')}}}"
                )

            for v in list(eqn.outvars) + list(eqn.invars):
                aval = getattr(v, "aval", None)
                dtype = getattr(aval, "dtype", None)
                if dtype is None:
                    continue
                if dtype.name == "float64":
                    emit(
                        "SL201",
                        f"float64 aval in `{prim}` — leaked Python float? "
                        "pin an explicit narrow dtype",
                        sig,
                    )
                    break
            for v in list(eqn.outvars) + list(eqn.invars):
                aval = getattr(v, "aval", None)
                if (
                    _is_float(v)
                    and getattr(aval, "weak_type", False)
                ):
                    emit(
                        "SL202",
                        f"weak-type float in `{prim}` promotes "
                        "backend-dependently — pin the dtype at the literal",
                        sig,
                    )
                    break

            if prim == "sort" and not eqn.params.get("is_stable", True):
                emit(
                    "SL203",
                    "unstable lax.sort — equal keys may reorder across "
                    "backends; pass is_stable=True or a total key",
                    sig,
                )

            if prim in _CALLBACK_PRIMS or "callback" in prim:
                emit(
                    "SL204",
                    f"host callback `{prim}` inside the jitted kernel — "
                    "hoist to a window boundary",
                    sig,
                )

            if prim in _NONASSOC_REDUCE_PRIMS and any(
                _is_float(v) for v in eqn.invars
            ):
                emit(
                    "SL205",
                    f"float `{prim}` — accumulation order changes the "
                    "bits unless the values are exactly representable; "
                    "keep it integral or baseline with a proof",
                    sig,
                )

            for sub in _sub_jaxprs(eqn.params):
                walk(sub)

    walk(closed_jaxpr)
    return sorted(
        findings.values(), key=lambda f: (f.rule, f.detail)
    )


def _sub_jaxprs(params: dict):
    """Yield every Jaxpr/ClosedJaxpr nested in an eqn's params."""
    for v in params.values():
        for item in v if isinstance(v, (list, tuple)) else (v,):
            if hasattr(item, "eqns") or hasattr(item, "jaxpr"):
                yield item


def trace_kernel(name: str, yaml_src: str) -> list[tuple[str, object]]:
    """Build the TPU engine for a config and trace its device entry
    points.  Returns ``[(label, closed_jaxpr), ...]``."""
    import jax

    from ..backend import lanes
    from ..backend.tpu_engine import TpuEngine
    from ..config.options import ConfigOptions

    cfg = ConfigOptions.from_yaml(yaml_src)
    eng = TpuEngine(cfg)
    state = eng.initial_state()
    round_fn = lanes._build_round(eng.params, eng.tables)
    full_fn = lanes._build_full_run(eng.params, eng.tables)
    return [
        (f"kernel:{name}/round", jax.make_jaxpr(round_fn)(state)),
        (f"kernel:{name}/full_run", jax.make_jaxpr(full_fn)(state)),
    ]


def audit_kernels(names: Optional[Iterable[str]] = None) -> list[Finding]:
    """Trace and audit the representative kernels (all by default)."""
    findings: list[Finding] = []
    for name in names if names is not None else KERNELS:
        yaml_src = KERNELS[name]
        for label, jaxpr in trace_kernel(name, yaml_src):
            findings.extend(audit_jaxpr(jaxpr, label))
    return findings
