"""shadowlint CLI: ``python -m shadow_tpu.analysis``.

Exit codes: 0 = clean (all findings fixed, suppressed inline, or
baselined), 1 = findings (or stale baseline entries), 2 = usage or
internal error.  ``make lint-determinism`` runs this over the package
with both passes; ``make gate`` includes it.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Optional

from .astlint import lint_paths, module_paths
from .baseline import (
    DEFAULT_BASELINE,
    BaselineError,
    load_baseline,
    write_baseline,
)
from .findings import RULES, Finding

PACKAGE_ROOT = Path(__file__).resolve().parents[1]  # shadow_tpu/


def _rel_base(root: Path) -> Optional[Path]:
    """Base for repo-relative finding paths of an explicit CLI path.

    A path inside the repo keeps its repo-relative prefix
    (``shadow_tpu/engine/foo.py``), so the scope-dependent rules
    (SL103/SL105/SL106) and baseline fingerprints match the default
    whole-package run exactly.  A path outside the repo falls back to
    :func:`module_paths`' default (relative to the lint root's parent).
    """
    repo = PACKAGE_ROOT.parent
    try:
        root.resolve().relative_to(repo)
    except ValueError:
        return None
    return repo


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="shadow_tpu.analysis",
        description="shadowlint: static determinism & lane-parity analysis "
        "(pass 1: AST linter; pass 2: jaxpr parity auditor)",
    )
    p.add_argument(
        "paths",
        nargs="*",
        help="files or directories to lint (default: the shadow_tpu package)",
    )
    p.add_argument(
        "--no-jaxpr",
        action="store_true",
        help="skip pass 2 (kernel tracing); AST pass only.  Pass 2 is "
        "also skipped automatically when explicit paths are given "
        "without --kernel (an on-the-diff lint)",
    )
    p.add_argument(
        "--kernel",
        action="append",
        default=None,
        metavar="NAME",
        help="audit only this representative kernel (repeatable)",
    )
    p.add_argument(
        "--baseline",
        default=None,
        metavar="FILE",
        help=f"baseline suppression file (default: {DEFAULT_BASELINE.name} "
        "next to the package)",
    )
    p.add_argument(
        "--write-baseline",
        action="store_true",
        help="write current findings to the baseline file with TODO "
        "reasons (each must be justified before the gate passes) and exit",
    )
    p.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    p.add_argument(
        "--format",
        choices=("text", "json"),
        default="text",
        help="finding output format",
    )
    return p


def collect_findings(
    ns: argparse.Namespace,
) -> tuple[list[Finding], set[str]]:
    """Run the requested passes.  Returns (findings, audited paths) — the
    latter scopes baseline staleness to what this run actually checked."""
    # usage validation up front — none of these may pay for (or silently
    # skip) any lint work: a typo'd path would check nothing and pass, a
    # typo'd kernel is tool misuse, and --no-jaxpr --kernel contradicts
    # itself (the requested audit would be skipped with a green result)
    missing = [p for p in ns.paths if not Path(p).exists()]
    if missing:
        print(f"shadowlint: no such path(s): {missing}", file=sys.stderr)
        raise SystemExit(2)
    if ns.kernel and ns.no_jaxpr:
        print(
            "shadowlint: --kernel requests a pass-2 audit that --no-jaxpr "
            "disables; drop one of the flags",
            file=sys.stderr,
        )
        raise SystemExit(2)
    run_jaxpr = not ns.no_jaxpr and (not ns.paths or bool(ns.kernel))
    if ns.kernel:
        # the name set is static and importable without jax
        from .jaxpr_audit import KERNELS

        unknown = [n for n in ns.kernel if n not in KERNELS]
        if unknown:
            print(
                f"shadowlint: unknown kernel(s) {unknown}; "
                f"have {sorted(KERNELS)}",
                file=sys.stderr,
            )
            raise SystemExit(2)

    findings: list[Finding] = []
    audited: set[str] = set()
    roots = (
        [(Path(p).resolve(), _rel_base(Path(p))) for p in ns.paths]
        if ns.paths
        else [(PACKAGE_ROOT, PACKAGE_ROOT.parent)]
    )
    for root, rel_to in roots:
        for _f, rel in module_paths(root, rel_to):
            audited.add(rel)
        findings.extend(lint_paths(root, rel_to))
    # pass 2 runs on the default whole-package gate or on explicit
    # --kernel request; an on-the-diff lint of explicit AST paths should
    # not pay for three engine builds + six kernel traces (the
    # audited-paths staleness scoping keeps the baseline honest either way)
    if run_jaxpr:
        # tracing needs jax on a CPU backend; the container may pin a TPU
        # plugin at interpreter start, so override before first use
        import jax

        jax.config.update("jax_platforms", "cpu")
        from .jaxpr_audit import KERNELS, audit_kernels

        names = ns.kernel
        for name in names if names else KERNELS:
            audited.add(f"kernel:{name}/round")
            audited.add(f"kernel:{name}/full_run")
        findings.extend(audit_kernels(names))
    return findings, audited


def _augment_audited(
    ns: argparse.Namespace, baseline, audited: set[str]
) -> set[str]:
    """Claim scope over baseline entries whose SUBJECT no longer exists.

    A default (no explicit paths) run audits the whole package
    namespace, so an entry for a since-deleted file is in scope and must
    go stale — its path is absent from the enumerated file set only
    because the file is gone.  Symmetrically, a full pass-2 run (no
    --kernel filter) audits the whole KERNELS registry, so entries for
    removed/renamed kernels must go stale too."""
    audited = set(audited)
    entry_paths = {e["path"] for e in baseline.suppressions.values()}
    if not ns.paths:
        audited |= {p for p in entry_paths if not p.startswith("kernel:")}
        if not ns.no_jaxpr and not ns.kernel:
            audited |= {p for p in entry_paths if p.startswith("kernel:")}
    return audited


def main(argv: list[str] | None = None) -> int:
    ns = build_parser().parse_args(argv)
    if ns.list_rules:
        for rule in sorted(RULES):
            title, rationale = RULES[rule]
            print(f"{rule}  {title}\n       {rationale}")
        return 0

    baseline_path = Path(ns.baseline) if ns.baseline else DEFAULT_BASELINE
    try:
        findings, audited = collect_findings(ns)
    except SystemExit:
        raise
    except Exception as e:  # tracing/config errors are tool errors, not lint
        print(f"shadowlint: internal error: {e}", file=sys.stderr)
        return 2

    if ns.write_baseline:
        try:
            n = write_baseline(
                baseline_path, findings, audited_paths=audited
            )
        except BaselineError as e:
            print(f"shadowlint: {e}", file=sys.stderr)
            return 2
        print(
            f"shadowlint: wrote {n} suppression(s) to {baseline_path}; "
            "justify each reason before the gate will pass"
        )
        return 0

    try:
        baseline = load_baseline(baseline_path)
    except BaselineError as e:
        print(f"shadowlint: {e}", file=sys.stderr)
        return 2

    live = [f for f in findings if not baseline.suppresses(f)]
    stale = baseline.stale_entries(_augment_audited(ns, baseline, audited))

    if ns.format == "json":
        print(
            json.dumps(
                {
                    "findings": [
                        {
                            "rule": f.rule,
                            "path": f.path,
                            "line": f.line,
                            "col": f.col,
                            "message": f.message,
                            "fingerprint": f.fingerprint,
                        }
                        for f in live
                    ],
                    "stale_baseline": stale,
                },
                indent=1,
            )
        )
    else:
        for f in sorted(live, key=lambda f: (f.path, f.line, f.rule)):
            print(f.render())
        for e in stale:
            print(
                f"{baseline_path.name}: stale suppression "
                f"{e['fingerprint']} ({e['rule']} at {e['path']}) — "
                "the finding is gone; delete the entry"
            )
        if not live and not stale:
            n = len(findings)
            suppressed = n - len(live)
            print(
                "shadowlint: clean "
                f"({suppressed} baselined finding(s))"
                if suppressed
                else "shadowlint: clean"
            )

    return 1 if live or stale else 0
