"""Span tracer with Chrome-trace (Perfetto-loadable) JSON export.

Spans are *complete* events (``ph: "X"``) on the Chrome trace-event
timeline: wall-clock ``ts``/``dur`` in microseconds relative to tracer
start, one ``tid`` row per emitting thread, simulation context (window
end, row counts) in ``args``.  The exported file loads directly in
Perfetto (https://ui.perfetto.dev) or ``chrome://tracing``.

Recording is bounded: past ``capacity`` events new spans are counted as
dropped instead of growing without limit, so tracing a long run degrades
to truncation, never to an OOM.  Every mutation happens under one lock —
worker threads (host execution, schedulers) may emit concurrently.
"""

from __future__ import annotations

import json
import threading
import time as wall_time
from pathlib import Path
from typing import Optional

DEFAULT_CAPACITY = 500_000


class Tracer:
    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        self.capacity = capacity
        self.events: list[dict] = []
        self.dropped = 0
        self.enabled = True  # run-control `trace on|off` toggles this
        self._lock = threading.Lock()
        self._tids: dict[str, int] = {}
        self.t0 = wall_time.perf_counter()

    # -- recording ---------------------------------------------------------

    def _tid(self) -> int:
        name = threading.current_thread().name
        tid = self._tids.get(name)
        if tid is None:
            tid = self._tids[name] = len(self._tids) + 1
        return tid

    def complete(
        self,
        name: str,
        cat: str,
        t0_abs: float,
        dur_s: float,
        args: Optional[dict] = None,
    ) -> None:
        """Record one complete span.  ``t0_abs`` is a
        ``wall_time.perf_counter()`` stamp (the same clock as ``self.t0``)."""
        if not self.enabled:
            return
        ev = {
            "name": name,
            "cat": cat,
            "ph": "X",
            "ts": (t0_abs - self.t0) * 1e6,
            "dur": dur_s * 1e6,
            "pid": 1,
        }
        if args:
            ev["args"] = args
        with self._lock:
            ev["tid"] = self._tid()
            if len(self.events) >= self.capacity:
                self.dropped += 1
            else:
                self.events.append(ev)

    def flow(
        self,
        phase: str,
        flow_id: int,
        name: str,
        cat: str,
        t_abs: float,
    ) -> None:
        """Record one flow event (``ph: "s"`` start / ``"f"`` finish),
        binding by enclosure to the slice containing ``t_abs`` on the
        emitting thread's row — the arrows that link a blocking device
        turn back to the syscall-service span that forced it
        (obs/turns.py).  ``t_abs`` is a ``wall_time.perf_counter()``
        stamp, like :meth:`complete`."""
        if not self.enabled:
            return
        ev = {
            "name": name,
            "cat": cat,
            "ph": phase,
            "id": flow_id,
            "ts": (t_abs - self.t0) * 1e6,
            "pid": 1,
        }
        if phase == "f":
            ev["bp"] = "e"  # bind the finish to its enclosing slice
        with self._lock:
            ev["tid"] = self._tid()
            if len(self.events) >= self.capacity:
                self.dropped += 1
            else:
                self.events.append(ev)

    def instant(self, name: str, cat: str, args: Optional[dict] = None) -> None:
        """Record an instant marker (``ph: "i"``) at the current wall time."""
        if not self.enabled:
            return
        ev = {
            "name": name,
            "cat": cat,
            "ph": "i",
            "s": "t",
            "ts": (wall_time.perf_counter() - self.t0) * 1e6,
            "pid": 1,
        }
        if args:
            ev["args"] = args
        with self._lock:
            ev["tid"] = self._tid()
            if len(self.events) >= self.capacity:
                self.dropped += 1
            else:
                self.events.append(ev)

    # -- introspection / export --------------------------------------------

    def span_count(self) -> int:
        with self._lock:
            return len(self.events)

    def phase_wall_s(self) -> dict[str, float]:
        """Summed span wall per category, seconds — the cross-check against
        the metrics registry's per-phase totals (they are fed from the
        same measurements, so the sums agree exactly up to float repr)."""
        out: dict[str, float] = {}
        with self._lock:
            events = list(self.events)
        for ev in events:
            if ev["ph"] != "X":
                continue
            out[ev["cat"]] = out.get(ev["cat"], 0.0) + ev["dur"] / 1e6
        return out

    def export(self, path: str | Path, extra: Optional[dict] = None) -> Path:
        """Write the Chrome-trace JSON document.  Thread-name metadata
        events make the Perfetto rows readable."""
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        with self._lock:
            events = list(self.events)
            tids = dict(self._tids)
            dropped = self.dropped
        meta = [
            {
                "name": "thread_name",
                "ph": "M",
                "pid": 1,
                "tid": tid,
                "args": {"name": name},
            }
            for name, tid in sorted(tids.items(), key=lambda kv: kv[1])
        ]
        doc = {
            "traceEvents": meta + events,
            "displayTimeUnit": "ms",
            "otherData": {"producer": "shadow_tpu.obs", "dropped": dropped},
        }
        if extra:
            doc["otherData"].update(extra)
        path.write_text(json.dumps(doc) + "\n")
        return path
