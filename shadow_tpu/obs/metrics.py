"""Metrics registry: counters, gauges, timers, histograms, phase walls.

One registry per run.  Everything mutates under a single lock (worker
threads emit concurrently); reads for reports take a consistent snapshot.
Surfaces:

- **counters** — monotone ints (``count("windows")``);
- **gauges** — last-written values (``gauge("hybrid_workers", 2)``);
- **histograms** — streaming min/max/count/total plus a bounded,
  deterministic sample (the FIRST ``SAMPLE_CAP`` observations) for
  percentiles: per-window distributions (active hosts, window span)
  ride these;
- **phase walls** — the per-phase wall-time attribution
  (``phase_add("device_turn", dt)``), the numbers the Chrome-trace spans
  are cross-checked against;
- an optional **JSONL stream** (one record per span/mark, locked
  writes) for external consumers that want events, not aggregates.

``report()`` aggregates everything into the ``METRICS_*.json`` document
(schema in docs/observability.md) that ``bench.py`` reads its per-phase
wall-breakdown keys from.
"""

from __future__ import annotations

import json
import threading
import time as wall_time
from pathlib import Path
from typing import Optional

from ..core.reduce import fsum

SAMPLE_CAP = 65536  # deterministic histogram sample: first N observations

SCHEMA_VERSION = 1


class _Hist:
    __slots__ = ("count", "total", "vmin", "vmax", "sample")

    def __init__(self) -> None:
        self.count = 0
        self.total = 0.0
        self.vmin: Optional[float] = None
        self.vmax: Optional[float] = None
        self.sample: list[float] = []

    def add(self, v: float) -> None:
        self.count += 1
        self.total += v
        if self.vmin is None or v < self.vmin:
            self.vmin = v
        if self.vmax is None or v > self.vmax:
            self.vmax = v
        if len(self.sample) < SAMPLE_CAP:
            self.sample.append(v)

    def summary(self) -> dict:
        if not self.count:
            return {"count": 0}
        s = sorted(self.sample)

        def pct(q: float) -> float:
            return s[min(int(q * len(s)), len(s) - 1)]

        return {
            "count": self.count,
            "min": self.vmin,
            "max": self.vmax,
            "mean": self.total / self.count,
            "p50": pct(0.50),
            "p90": pct(0.90),
            "p99": pct(0.99),
        }


class MetricsRegistry:
    def __init__(
        self, run_id: str = "run", jsonl_path: Optional[str | Path] = None
    ) -> None:
        self.run_id = run_id
        self._lock = threading.Lock()
        self._counters: dict[str, int] = {}
        self._gauges: dict[str, object] = {}
        self._hists: dict[str, _Hist] = {}
        # phase -> [span_count, total_wall_s]
        self._phases: dict[str, list] = {}
        self._t0 = wall_time.perf_counter()
        self._jsonl_f = None
        self.jsonl_path: Optional[Path] = None
        if jsonl_path is not None:
            self.jsonl_path = Path(jsonl_path)
            self.jsonl_path.parent.mkdir(parents=True, exist_ok=True)
            self._jsonl_f = open(self.jsonl_path, "w")

    # -- write side --------------------------------------------------------

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def gauge(self, name: str, value) -> None:
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = _Hist()
            h.add(value)

    def phase_add(self, phase: str, dur_s: float) -> None:
        with self._lock:
            p = self._phases.get(phase)
            if p is None:
                self._phases[phase] = [1, dur_s]
            else:
                p[0] += 1
                p[1] += dur_s

    def timer(self, name: str) -> "_Timer":
        """``with metrics.timer("collect"):`` — observes the block's wall
        seconds into the histogram of the same name."""
        return _Timer(self, name)

    def stream(self, record: dict) -> None:
        """Append one JSONL record (no-op when streaming is off).  The
        write happens under the registry lock so concurrent emitters
        produce whole lines."""
        f = self._jsonl_f
        if f is None:
            return
        with self._lock:
            f.write(json.dumps(record) + "\n")

    # -- read side ---------------------------------------------------------

    def counters(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def phase_wall_s(self) -> dict[str, float]:
        """phase -> total wall seconds (the bench breakdown keys)."""
        with self._lock:
            return {k: p[1] for k, p in self._phases.items()}

    def phase_report(self) -> dict[str, dict]:
        with self._lock:
            return {
                k: {"spans": p[0], "wall_s": p[1]}
                for k, p in sorted(self._phases.items())
            }

    def report(self, extra: Optional[dict] = None) -> dict:
        """The aggregated METRICS document (docs/observability.md)."""
        with self._lock:
            phases = {
                k: {"spans": p[0], "wall_s": p[1]}
                for k, p in sorted(self._phases.items())
            }
            doc = {
                "schema": SCHEMA_VERSION,
                "run_id": self.run_id,
                "recorder_wall_s": wall_time.perf_counter() - self._t0,
                "phase_wall_s": {k: v["wall_s"] for k, v in phases.items()},
                "phase_wall_total_s": fsum(
                    v["wall_s"] for v in phases.values()
                ),
                "phases": phases,
                "counters": dict(sorted(self._counters.items())),
                "gauges": dict(sorted(self._gauges.items())),
                "histograms": {
                    k: h.summary() for k, h in sorted(self._hists.items())
                },
            }
        if extra:
            doc.update(extra)
        return doc

    def write_report(
        self, path: str | Path, extra: Optional[dict] = None
    ) -> Path:
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(json.dumps(self.report(extra), indent=2) + "\n")
        return path

    def snapshot_lines(self) -> list[str]:
        """Human-readable snapshot (the run-control ``stats`` verb)."""
        with self._lock:
            phases = {k: (p[0], p[1]) for k, p in sorted(self._phases.items())}
            counters = dict(sorted(self._counters.items()))
            gauges = dict(sorted(self._gauges.items()))
        lines = []
        if phases:
            lines.append("phase walls:")
            for k, (n, s) in phases.items():
                lines.append(f"  {k}: {s:.6f}s over {n} span(s)")
        if counters:
            lines.append(
                "counters: "
                + " ".join(f"{k}={v}" for k, v in counters.items())
            )
        if gauges:
            lines.append(
                "gauges: " + " ".join(f"{k}={v}" for k, v in gauges.items())
            )
        if not lines:
            lines.append("no metrics recorded yet")
        return lines

    # -- checkpoint state (engine/checkpoint.py) ---------------------------
    # Counters/gauges/hists/phases are the resumable accumulator state;
    # the lock, wall t0, and JSONL stream belong to the live run and are
    # never serialized.  restore replaces (not merges): a resumed run's
    # registry starts from exactly the checkpointed accumulators so the
    # final deterministic counters byte-match the uninterrupted run.

    def checkpoint_state(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "hists": {
                    k: (h.count, h.total, h.vmin, h.vmax, list(h.sample))
                    for k, h in self._hists.items()
                },
                "phases": {k: list(p) for k, p in self._phases.items()},
            }

    def restore_checkpoint_state(self, st: dict) -> None:
        with self._lock:
            self._counters = dict(st.get("counters", {}))
            self._gauges = dict(st.get("gauges", {}))
            self._hists = {}
            for k, (count, total, vmin, vmax, sample) in st.get(
                "hists", {}
            ).items():
                h = _Hist()
                h.count, h.total = count, total
                h.vmin, h.vmax = vmin, vmax
                h.sample = list(sample)
                self._hists[k] = h
            self._phases = {k: list(p) for k, p in st.get("phases", {}).items()}

    def reset_accumulators(self) -> None:
        """Zero every accumulator: the escalate-to-serial replay starts
        the run over from t=0, so the registry must too (otherwise the
        abandoned parallel prefix double-counts)."""
        with self._lock:
            self._counters = {}
            self._gauges = {}
            self._hists = {}
            self._phases = {}

    def close(self) -> None:
        f = self._jsonl_f
        if f is not None:
            self._jsonl_f = None
            f.close()


class _Timer:
    __slots__ = ("_m", "_name", "_t0")

    def __init__(self, m: MetricsRegistry, name: str) -> None:
        self._m = m
        self._name = name

    def __enter__(self) -> "_Timer":
        self._t0 = wall_time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self._m.observe(self._name, wall_time.perf_counter() - self._t0)
