"""Structured observability: span tracing + metrics (docs/observability.md).

The instrument every perf PR reads from.  One :class:`Recorder` per run
owns a :class:`~shadow_tpu.obs.metrics.MetricsRegistry` (counters,
gauges, timers, per-window histograms, per-phase wall attribution) and —
when tracing is enabled — a :class:`~shadow_tpu.obs.tracer.Tracer`
(Chrome-trace/Perfetto span export).  Engines hold ``self.obs`` exactly
like ``self.perf_log``: ``None`` (the default) is zero overhead — every
hook is behind an ``if obs is not None`` branch — and the facade
(:mod:`shadow_tpu.engine.sim`) sets it from
``experimental.obs_metrics`` / ``obs_trace``.

The determinism contract (docs/determinism.md) is absolute: obs reads
wall clocks (through the ``import time as wall_time`` alias shadowlint
SL101 prescribes) and engine counters, and writes only to its own
artifacts — it never feeds a value back into the simulation, so event
ordering is bit-identical with obs fully enabled.
"""

from __future__ import annotations

from .metrics import MetricsRegistry
from .recorder import PHASES, Recorder
from .tracer import Tracer
from .turns import TurnLedger

__all__ = ["MetricsRegistry", "PHASES", "Recorder", "Tracer", "TurnLedger"]
