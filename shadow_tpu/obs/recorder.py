"""The per-run obs facade: phase spans feeding metrics AND the tracer.

A phase span is the unit of wall attribution::

    with obs.phase("device_turn", window_end=we):
        ...

On exit the measured duration lands in the metrics registry's per-phase
wall totals and — when tracing is on — as one Chrome-trace complete
event, from the *same* ``perf_counter`` pair, so the trace's summed span
wall per phase and the METRICS report's ``phase_wall_s`` agree by
construction (the acceptance cross-check in tests/test_obs.py).

The engine-facing phase vocabulary (docs/observability.md):

- ``window_compute``  — host-side window execution + barrier (cpu; the
  parent's collect wall on cpu_mp, which IS the workers' execution);
- ``device_turn``     — one blocking device call + packed-scalar
  readback (tpu step driver, hybrid; the whole fused call in device
  mode);
- ``injection``       — staged-send block packing + H2D dispatch
  (hybrid; the transfer itself overlaps the next device call under JAX
  async dispatch);
- ``egress``          — egress-slice D2H read + delivery application
  (hybrid);
- ``syscall_service`` — managed hosts' syscall-plane round, barrier
  included (hybrid; on the multiprocess engine this is the collect leg
  of the round — the barrier wait that IS the workers' execution wall);
- ``worker_pipe``     — the pipe ship (broadcast) leg of a multiprocess
  round (cpu_mp, hybrid mp); disjoint from the collect-leg phase, so
  phase walls tile the round without double-counting;
- ``fault_swap``      — fault-table epoch application at a window
  boundary (cpu backend).

``jax_annotations=True`` additionally wraps every span in
``jax.profiler.TraceAnnotation`` so the same phase names appear inside
device profiles captured with ``jax.profiler.trace`` — a pass-through,
not a second measurement.
"""

from __future__ import annotations

import time as wall_time
from pathlib import Path
from typing import Optional

from .metrics import MetricsRegistry
from .tracer import Tracer

PHASES = (
    "window_compute",
    "device_turn",
    "injection",
    "egress",
    "syscall_service",
    "worker_pipe",
    "fault_swap",
)


class _PhaseSpan:
    __slots__ = ("_rec", "phase", "name", "args", "_t0", "_ann")

    def __init__(
        self, rec: "Recorder", phase: str, name: Optional[str], args: dict
    ) -> None:
        self._rec = rec
        self.phase = phase
        self.name = name or phase
        self.args = args
        self._ann = None

    def __enter__(self) -> "_PhaseSpan":
        rec = self._rec
        if rec._annotate is not None:
            self._ann = rec._annotate(self.name)
            self._ann.__enter__()
        self._t0 = wall_time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        t0 = self._t0
        dur = wall_time.perf_counter() - t0
        if self._ann is not None:
            self._ann.__exit__(*exc)
        self._rec._record(self.phase, self.name, t0, dur, self.args)


class Recorder:
    """Owns one run's metrics registry and (optionally) tracer.

    Engines carry ``self.obs: Optional[Recorder] = None`` and guard every
    hook with ``if obs is not None`` — disabled means zero overhead, the
    same contract as ``perf_log``."""

    def __init__(
        self,
        run_id: str = "run",
        out_dir: Optional[str | Path] = None,
        trace: bool = False,
        jsonl: bool = False,
        jax_annotations: bool = False,
        trace_capacity: Optional[int] = None,
        turns: bool = False,
    ) -> None:
        self.run_id = run_id
        self.out_dir = Path(out_dir) if out_dir is not None else None
        jsonl_path = (
            self.out_dir / f"metrics_{run_id}.jsonl"
            if (jsonl and self.out_dir is not None)
            else None
        )
        self.metrics = MetricsRegistry(run_id=run_id, jsonl_path=jsonl_path)
        self.tracer: Optional[Tracer] = None
        if trace:
            self.tracer = (
                Tracer() if trace_capacity is None else Tracer(trace_capacity)
            )
        # device-turn ledger (obs/turns.py): causal turn accounting +
        # fusion-headroom measurement; None = off = zero engine calls
        self.turns: Optional["TurnLedger"] = None
        if turns:
            from .turns import TurnLedger

            self.turns = TurnLedger()
        self._annotate = None
        if jax_annotations:
            try:
                from jax.profiler import TraceAnnotation

                self._annotate = TraceAnnotation
            except Exception:  # profiler unavailable: annotations are
                self._annotate = None  # best-effort pass-through only
        self.finalized: Optional[dict] = None
        # queued JSON artifacts (name -> payload), written at finalize —
        # the subsystem-report seam (sweep/report.py's SWEEP_* files ride
        # the same lifecycle as METRICS_*/TURNS_*)
        self.artifacts: dict = {}

    # -- span API ----------------------------------------------------------

    def add_artifact(self, name: str, payload: dict) -> None:
        """Queue a JSON artifact for finalize: written into ``out_dir``
        as ``<name>.json`` (deterministically serialized — sorted keys,
        fixed separators) alongside the METRICS report."""
        self.artifacts[name] = payload

    def phase(self, phase: str, name: Optional[str] = None, **args):
        return _PhaseSpan(self, phase, name, args)

    def record(
        self,
        phase: str,
        name: Optional[str],
        t0: float,
        dur_s: float,
        **args,
    ) -> None:
        """Record an already-measured span (``t0`` from
        ``wall_time.perf_counter()``): the hook for code that timed the
        block anyway (sync_stats, watchdogs) — one clock pair, no second
        measurement."""
        self._record(phase, name or phase, t0, dur_s, args)

    def _record(
        self, phase: str, name: str, t0: float, dur_s: float, args: dict
    ) -> None:
        m = self.metrics
        m.phase_add(phase, dur_s)
        if m.jsonl_path is not None:
            rec = {"ev": "span", "phase": phase, "name": name,
                   "ts_s": t0 - m._t0, "dur_s": dur_s}
            if args:
                rec["args"] = args
            m.stream(rec)
        if self.tracer is not None:
            self.tracer.complete(name, phase, t0, dur_s, args or None)

    def mark(self, name: str, **args) -> None:
        """Instant marker: trace instant event + JSONL record."""
        if self.tracer is not None:
            self.tracer.instant(name, "mark", args or None)
        self.metrics.stream({"ev": "mark", "name": name, **args})

    # -- checkpoint state (engine/checkpoint.py) ---------------------------

    def checkpoint_state(self) -> dict:
        """The resumable observability state: metrics accumulators plus
        the device-turn ledger (plain-data, picklable).  Trace spans are
        wall-clock artifacts and deliberately excluded — a resumed run's
        trace covers the resumed segment only.  The ledger is deep-copied
        so the checkpoint is a true snapshot even when the payload is
        held in memory while the live ledger keeps accumulating."""
        import copy

        return {
            "metrics": self.metrics.checkpoint_state(),
            "turns": copy.deepcopy(self.turns),
        }

    def restore_checkpoint_state(self, st: dict) -> None:
        self.metrics.restore_checkpoint_state(st.get("metrics", {}))
        if st.get("turns") is not None and self.turns is not None:
            self.turns = st["turns"]

    def reset_for_replay(self) -> None:
        """Zero the accumulators for a from-t=0 replay (serial
        escalation, checkpoint-less failover): the replay re-earns every
        count, so the abandoned prefix must not linger."""
        self.metrics.reset_accumulators()
        if self.turns is not None:
            from .turns import TurnLedger

            self.turns = TurnLedger()

    # -- finalize ----------------------------------------------------------

    def finalize(self, extra: Optional[dict] = None) -> dict:
        """Write the run artifacts (``METRICS_<run_id>.json`` and, when
        tracing, ``trace_<run_id>.json``) into ``out_dir`` and return
        ``{"report": ..., "metrics_path": ..., "trace_path": ...}``.
        Idempotent per recorder: the second call returns the first
        result."""
        if self.finalized is not None:
            return self.finalized
        out: dict = {}
        report_extra = dict(extra or {})
        if self.tracer is not None:
            report_extra.setdefault("trace_spans", self.tracer.span_count())
            report_extra.setdefault("trace_dropped", self.tracer.dropped)
        if self.turns is not None:
            # the METRICS report carries the ledger aggregates; the
            # per-turn rows live in the TURNS artifact written below
            self.turns.finish()  # close the trailing fusable run first
            report_extra.setdefault("device_turn_ledger", self.turns.summary())
        if self.out_dir is not None:
            self.out_dir.mkdir(parents=True, exist_ok=True)
            if self.tracer is not None:
                out["trace_path"] = str(
                    self.tracer.export(
                        self.out_dir / f"trace_{self.run_id}.json",
                        extra={"run_id": self.run_id},
                    )
                )
            if self.turns is not None:
                from .turns import write_report as _write_turns

                out["turns_path"] = str(
                    _write_turns(
                        self.out_dir / f"TURNS_{self.run_id}.json",
                        self.turns.report(self.run_id),
                    )
                )
            out["metrics_path"] = str(
                self.metrics.write_report(
                    self.out_dir / f"METRICS_{self.run_id}.json",
                    extra=report_extra,
                )
            )
            if self.artifacts:
                import json as _json

                paths = []
                for aname in sorted(self.artifacts):
                    p = self.out_dir / f"{aname}.json"
                    p.write_text(
                        _json.dumps(
                            self.artifacts[aname], sort_keys=True,
                            indent=2, separators=(",", ": "),
                        )
                        + "\n"
                    )
                    paths.append(str(p))
                out["artifact_paths"] = paths
        out["report"] = self.metrics.report(extra=report_extra)
        self.metrics.close()
        self.finalized = out
        return out
