"""Flowtrace: deterministic per-flow packet-lifecycle tracing.

PR 10's netobs counts *what* the simulated network did; PR 11's turn
ledger accounts for *why the device dispatched*; this layer records
*which flows* did it: per-event lifecycle traces — send, token-bucket
wait, queue-enter, drop (with cause), retransmit, delivery — for a
deterministically-sampled subset of flows, emitted bit-identically by
the CPU oracle (plain Python hooks on the packet path) and by the lane
kernels (a device-resident bounded event ring drained only at snapshot
epochs and end-of-run).

The event schema is eight integers::

    (t_ns, window_end_ns, kind, src, dst, seq, size, aux)

``kind`` is one of the ``FT_*`` lifecycle codes below; ``aux`` carries
the drop cause for ``FT_DROP`` and the bucket direction for
``FT_TB_WAIT``.  ``seq`` is the engine send sequence — unique per wire
packet per source host — so lifecycle stages of one packet join on
``(src, dst, seq)`` exactly (a retransmitted lTCP unit is a *new* wire
packet with a new seq; it carries ``FT_RETRANSMIT`` instead of
``FT_SEND`` as its send-stage event).

Sampling law (docs/observability.md): a flow ``(src, dst)`` is sampled
iff ``flow_hash(src, dst, fid, seed) < thresh_u32`` where ``thresh_u32
= floor(sample * 2**32)`` (``sample >= 1.0`` short-circuits to
all-pass).  The hash is a pure u32 mix both sides evaluate
identically — Python ints here, ``jnp.uint32`` lanes on the device
(``backend.lanes.flow_hash_lane``) — so device and oracle select the
same flows with no coordination.  ``fid`` is the flow-id term reserved
for sub-(src,dst) flow keys; the packet plane passes 0.

Exported as ``FLOWS_<backend>-seed<N>.json`` through the PR 9 Recorder:
integer-only, canonically ordered (full-tuple sort), so run-twice
artifacts diff byte-identical and device↔oracle streams compare with
``==``.  The report's **burst attribution** section ranks which flow
classes (hostname with its trailing digits stripped, e.g. ``client12 ->
client``) populate which ``mixed_window_hist`` buckets — the instrument
that sizes ROADMAP item 3's coalescing change.
"""

from __future__ import annotations

import json
import re
from pathlib import Path
from typing import Optional

from .netobs import HIST_BUCKETS, hist_bucket

SCHEMA_VERSION = 1

# -- lifecycle event kinds --------------------------------------------------

FT_SEND = 0         # wire send accepted at the source (stamped at stimulus t)
FT_TB_WAIT = 1      # token-bucket deferral (stamped at bucket departure)
FT_QUEUE_ENTER = 2  # packet committed to the wire (stamped at arrival time)
FT_DROP = 3         # dropped; aux = cause (stamped per the cause's log law)
FT_RETRANSMIT = 4   # send stage of a retransmitted stream segment
FT_DELIVERY = 5     # delivered at the destination (stamped at delivery time)

KIND_NAMES = {
    FT_SEND: "send",
    FT_TB_WAIT: "tb_wait",
    FT_QUEUE_ENTER: "queue_enter",
    FT_DROP: "drop",
    FT_RETRANSMIT: "retransmit",
    FT_DELIVERY: "delivery",
}

# -- FT_DROP aux: the drop-cause taxonomy (matches netobs.DROP_CAUSES) ------

CAUSE_LOSS = 0
CAUSE_CODEL = 1
CAUSE_QUEUE = 2
CAUSE_CROSS_SHED = 3
CAUSE_RETRY_GIVEUP = 4

CAUSE_NAMES = {
    CAUSE_LOSS: "loss",
    CAUSE_CODEL: "codel",
    CAUSE_QUEUE: "queue",
    CAUSE_CROSS_SHED: "cross_shed",
    CAUSE_RETRY_GIVEUP: "retry_giveup",
}

# -- FT_TB_WAIT aux: which bucket deferred --------------------------------

TB_UP = 0
TB_DN = 1

#: columns of one device ring row ([capacity, FT_COLS] int32); times and
#: window stamps travel as the lane kernels' (hi, lo) bit-31 pairs
FT_COLS = 10

#: the device rings' (hi, lo) join law — bit-31 split, lo in [0, 2**31)
_PAIR_BASE = 1 << 31

_MASK32 = 0xFFFFFFFF
# Knuth/xxhash-style odd multipliers for the mix, murmur3 fmix32 finalizer
_M_SRC = 2654435761
_M_DST = 2246822519
_M_FID = 3266489917
_M_SEED = 668265263


def flow_hash(src: int, dst: int, fid: int, seed: int) -> int:
    """u32 flow-sampling hash; the Python twin of
    ``backend.lanes.flow_hash_lane`` (bit-identical for any int32
    inputs — both reduce mod 2**32 at every step)."""
    h = (src * _M_SRC + dst * _M_DST + fid * _M_FID + seed * _M_SEED) & _MASK32
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _MASK32
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _MASK32
    h ^= h >> 16
    return h


def sample_thresh(sample: float) -> tuple[int, bool]:
    """``(thresh_u32, all_pass)`` for a sampling fraction.  ``sample >=
    1.0`` is the all-pass fast path (no hash evaluated anywhere);
    ``sample <= 0`` samples nothing."""
    if sample >= 1.0:
        return 0, True
    if sample <= 0.0:
        return 0, False
    return int(sample * float(1 << 32)) & _MASK32, False


class FlowTrace:
    """Host-side (oracle) flowtrace accumulator.

    Thread-safety by ownership, exactly the ``Host.log_buf`` law: every
    per-host event list is appended only by the thread executing that
    host, and the export path runs after the final barrier.  Buffers are
    unbounded here (the oracle has no ring); the device's capacity law
    is applied at export by :func:`canonical_events`, so both sides
    surface the same ``events_lost`` accounting."""

    def __init__(
        self, n_hosts: int, seed: int, sample: float, capacity: int
    ) -> None:
        self.n_hosts = n_hosts
        self.seed = seed
        self.sample = sample
        self.capacity = capacity
        self.thresh, self.all_pass = sample_thresh(sample)
        self.events: list[list[tuple]] = [[] for _ in range(n_hosts)]

    def sampled(self, src: int, dst: int) -> bool:
        if self.all_pass:
            return True
        if self.thresh == 0:
            return False
        return flow_hash(src, dst, 0, self.seed) < self.thresh

    def emit(
        self, owner: int, t: int, we: int, kind: int,
        src: int, dst: int, seq: int, size: int, aux: int = 0,
    ) -> None:
        """Append one event to ``owner``'s thread-owned buffer.  The
        caller has already applied the sampling gate."""
        self.events[owner].append(
            (int(t), int(we), kind, src, dst, int(seq), int(size), aux)
        )

    def raw_events(self) -> list[tuple]:
        out: list[tuple] = []
        for buf in self.events:
            out.extend(buf)
        return out

    def merge_raw(self, events) -> None:
        """Fold a worker's shipped event list into host 0's buffer
        (canonicalization at export makes placement irrelevant)."""
        if events:
            self.events[0].extend(tuple(e) for e in events)


def rows_to_events(rows) -> list[tuple]:
    """Decode device ring rows ([n, FT_COLS] int32, hi/lo pair times)
    into canonical event tuples."""
    out = []
    for r in rows:
        (t_hi, t_lo, we_hi, we_lo, kind, src, dst, seq, size, aux) = (
            int(v) for v in r
        )
        out.append((
            t_hi * _PAIR_BASE + t_lo,
            we_hi * _PAIR_BASE + we_lo,
            kind, src, dst, seq, size, aux,
        ))
    return out


def canonical_events(raw, capacity: int) -> tuple[list[tuple], int]:
    """The export law: full-tuple sort, then truncate at ``capacity``
    counting the excess into ``events_lost`` — the oracle twin of the
    device ring's never-wrap overflow law.  With no overflow on either
    side the streams are bit-identical; once either side loses events
    the two retention orders differ (the ring keeps append order, this
    keeps sort order), so parity is asserted only at ``events_lost ==
    0`` (docs/observability.md)."""
    ev = sorted(tuple(e) for e in raw)
    lost = max(0, len(ev) - capacity)
    return (ev[:capacity] if lost else ev), lost


def window_index(events) -> tuple[list[int], dict[int, int]]:
    """Dense window indexing: the sorted distinct window stamps present
    in the (canonical) event stream, plus the stamp -> index map.  Both
    backends derive it from the events themselves, so identical streams
    get identical indices."""
    stamps = sorted({e[1] for e in events})
    return stamps, {we: i for i, we in enumerate(stamps)}


def host_class(hostname: str) -> str:
    """Flow-class key: the hostname with its replica digits stripped
    (``client12`` -> ``client``)."""
    return re.sub(r"\d+$", "", hostname) or hostname


def _agg(values: list[int]) -> dict:
    return {
        "count": len(values),
        "sum": sum(values),
        "min": min(values) if values else 0,
        "max": max(values) if values else 0,
    }


TOP_CLASSES = 5


def build_report(
    run_id: str,
    backend: str,
    seed: int,
    hostnames: list[str],
    events: list[tuple],
    events_lost: int,
    thresh: int,
    all_pass: bool,
    capacity: int,
    extra: Optional[dict] = None,
) -> dict:
    """The FLOWS document (schema in docs/observability.md): the
    canonical event stream, per-flow lifecycle breakdowns, and the
    burst-attribution ranking.  Integer content only, deterministic
    ordering — run-twice artifacts must diff byte-identical."""
    windows, widx = window_index(events)

    def name(h: int) -> str:
        return hostnames[h] if 0 <= h < len(hostnames) else f"host{h}"

    # -- per-flow lifecycle joins on (src, dst, seq) ----------------------
    flows: dict[tuple[int, int], dict] = {}
    stages: dict[tuple[int, int, int], dict[int, int]] = {}
    for t, we, kind, src, dst, seq, size, aux in events:
        fl = flows.get((src, dst))
        if fl is None:
            fl = flows[(src, dst)] = {
                "sends": 0, "retransmits": 0, "delivered": 0,
                "bytes": 0,
                "drops": {c: 0 for c in CAUSE_NAMES.values()},
            }
        if kind in (FT_SEND, FT_RETRANSMIT):
            fl["sends"] += 1
            fl["bytes"] += size
            if kind == FT_RETRANSMIT:
                fl["retransmits"] += 1
        elif kind == FT_DELIVERY:
            fl["delivered"] += 1
        elif kind == FT_DROP:
            fl["drops"][CAUSE_NAMES.get(aux, "loss")] += 1
        st = stages.setdefault((src, dst, seq), {})
        # one event per (packet, kind) except TB_WAIT (up vs dn): key
        # the wait stages by direction so the joins below stay exact
        st[(kind, aux) if kind == FT_TB_WAIT else (kind, 0)] = t
    per_flow_lat: dict[tuple[int, int], list[int]] = {}
    per_flow_qd: dict[tuple[int, int], list[int]] = {}
    per_flow_tbw: dict[tuple[int, int], list[int]] = {}
    for (src, dst, seq), st in stages.items():
        send_t = st.get((FT_SEND, 0), st.get((FT_RETRANSMIT, 0)))
        deliv_t = st.get((FT_DELIVERY, 0))
        enter_t = st.get((FT_QUEUE_ENTER, 0))
        if send_t is not None and deliv_t is not None:
            per_flow_lat.setdefault((src, dst), []).append(deliv_t - send_t)
        if enter_t is not None and deliv_t is not None:
            per_flow_qd.setdefault((src, dst), []).append(deliv_t - enter_t)
        up_t = st.get((FT_TB_WAIT, TB_UP))
        if up_t is not None and send_t is not None:
            per_flow_tbw.setdefault((src, dst), []).append(up_t - send_t)
        dn_t = st.get((FT_TB_WAIT, TB_DN))
        if dn_t is not None and enter_t is not None:
            per_flow_tbw.setdefault((src, dst), []).append(dn_t - enter_t)
    flow_docs = {}
    for (src, dst), fl in sorted(flows.items()):
        flow_docs[f"{name(src)}->{name(dst)}"] = {
            "src": src,
            "dst": dst,
            "class": f"{host_class(name(src))}->{host_class(name(dst))}",
            **fl,
            "latency_ns": _agg(per_flow_lat.get((src, dst), [])),
            "queue_delay_ns": _agg(per_flow_qd.get((src, dst), [])),
            "tb_wait_ns": _agg(per_flow_tbw.get((src, dst), [])),
        }

    # -- burst attribution: flow classes per window-occupancy bucket ------
    # Arrival events (delivery | codel drop) are the flowtrace twin of
    # netobs's PACKET pops: exactly one per arrived packet.  Buckets use
    # the same log2 law; with sample < 1 the counts (hence buckets) are
    # of the sampled subpopulation — exact attribution needs sample=1.
    win_counts: dict[int, int] = {}
    win_class: dict[int, dict[str, int]] = {}
    for t, we, kind, src, dst, seq, size, aux in events:
        if kind == FT_DELIVERY or (kind == FT_DROP and aux == CAUSE_CODEL):
            w = widx[we]
            win_counts[w] = win_counts.get(w, 0) + 1
            cls = f"{host_class(name(src))}->{host_class(name(dst))}"
            cc = win_class.setdefault(w, {})
            cc[cls] = cc.get(cls, 0) + 1
    bucket_windows: dict[int, int] = {}
    bucket_class: dict[int, dict[str, int]] = {}
    for w, cnt in win_counts.items():
        b = hist_bucket(cnt)
        bucket_windows[b] = bucket_windows.get(b, 0) + 1
        bc = bucket_class.setdefault(b, {})
        for cls, n in win_class[w].items():
            bc[cls] = bc.get(cls, 0) + n
    buckets = []
    for b in range(HIST_BUCKETS):
        if b not in bucket_windows:
            continue
        ranked = sorted(
            bucket_class[b].items(), key=lambda kv: (-kv[1], kv[0])
        )
        buckets.append({
            "bucket": b,
            "windows": bucket_windows[b],
            "top_classes": [
                {"class": cls, "arrivals": n}
                for cls, n in ranked[:TOP_CLASSES]
            ],
        })

    kinds = {}
    for e in events:
        k = KIND_NAMES.get(e[2], str(e[2]))
        kinds[k] = kinds.get(k, 0) + 1
    doc: dict = {
        "schema": SCHEMA_VERSION,
        "run_id": run_id,
        "backend": backend,
        "seed": int(seed),
        "sample_thresh": int(thresh),
        "sample_all": bool(all_pass),
        "capacity": int(capacity),
        "events_lost": int(events_lost),
        "num_events": len(events),
        "events_by_kind": kinds,
        "num_flows": len(flows),
        "windows": [int(w) for w in windows],
        "events": [list(e) for e in events],
        "flows": flow_docs,
        "burst_attribution": {
            "scheme": "log2-packet-arrivals",
            "buckets": buckets,
        },
    }
    if extra:
        doc.update(extra)
    return doc


def write_report(path: str | Path, report: dict) -> Path:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(report, indent=2, sort_keys=True) + "\n")
    return path


def render_flows(tracer, events, hostnames: list[str]) -> int:
    """Chrome-trace flow arrows (``Tracer.flow``): one s->f arrow per
    delivered sampled packet, placed on the simulated-time axis (1 sim
    ns = 1e-9 trace seconds, so Perfetto shows sim microseconds).
    Returns the number of arrows emitted."""
    sends: dict[tuple[int, int, int], int] = {}
    for t, we, kind, src, dst, seq, size, aux in events:
        if kind in (FT_SEND, FT_RETRANSMIT):
            sends[(src, dst, seq)] = t
    n = 0
    for t, we, kind, src, dst, seq, size, aux in events:
        if kind != FT_DELIVERY:
            continue
        t0 = sends.get((src, dst, seq))
        if t0 is None:
            continue
        def name(h):
            return hostnames[h] if 0 <= h < len(hostnames) else f"host{h}"
        label = f"{name(src)}->{name(dst)}#{seq}"
        fid = flow_hash(src, dst, seq, 0)
        tracer.flow("s", fid, label, "flowtrace", tracer.t0 + t0 * 1e-9)
        tracer.flow("f", fid, label, "flowtrace", tracer.t0 + t * 1e-9)
        n += 1
    return n


def summary_line(events, events_lost: int) -> str:
    """The one-line run-control summary (``stats`` fold + ``flows``
    verb header)."""
    pairs = {(e[3], e[4]) for e in events}
    sends = sum(1 for e in events if e[2] in (FT_SEND, FT_RETRANSMIT))
    deliv = sum(1 for e in events if e[2] == FT_DELIVERY)
    drops = sum(1 for e in events if e[2] == FT_DROP)
    return (
        f"flows: sampled_pairs={len(pairs)} events={len(events)}"
        f" sends={sends} delivered={deliv} drops={drops}"
        f" events_lost={events_lost}"
    )


def snapshot_lines(
    events, events_lost: int, hostnames: list[str],
    limit: int = 10, host: Optional[str] = None,
) -> list[str]:
    """Human-readable snapshot (the run-control ``flows`` verb): the
    summary line plus the busiest sampled flows.  ``host`` restricts the
    flow listing to pairs touching that hostname."""
    lines = [summary_line(events, events_lost)]
    per_pair: dict[tuple[int, int], int] = {}
    for e in events:
        per_pair[(e[3], e[4])] = per_pair.get((e[3], e[4]), 0) + 1
    ranked = sorted(per_pair.items(), key=lambda kv: (-kv[1], kv[0]))

    def name(h):
        return hostnames[h] if 0 <= h < len(hostnames) else f"host{h}"

    if host is not None:
        ranked = [
            kv for kv in ranked
            if host in (name(kv[0][0]), name(kv[0][1]))
        ]
    for (src, dst), n in ranked[:limit]:
        lines.append(f"  {name(src)}->{name(dst)}: {n} events")
    return lines
