"""Simulated-network telemetry plane (docs/observability.md).

PR 9 instrumented the *engine* (phase walls, METRICS_*.json); this module
observes the *simulation content*: what the simulated network did.  The
reference fork ships the same layer as its host tracker / heartbeat
counters (interface.rs, utility/pcap_writer.rs) and per-window perf
logging (manager.rs / host.rs); here it is a per-host counter catalog
with **drop-cause accounting** and a **burst-window histogram**:

- per host: packets ``sent`` / ``delivered``, bytes by direction
  (``tx_bytes`` / ``rx_bytes``), drops by cause (``loss`` — the
  Bernoulli link table, ``codel`` — the CoDel law's drop decision,
  ``queue`` — lane-queue overflow, ``cross_shed`` — exchange-width shed
  (both device-only: the CPU oracle's queues are unbounded),
  ``retry_giveup`` — lTCP MAX_RTO_BACKOFFS abandonment), token-bucket
  ``throttled`` events (charges that had to wait for a refill — the
  bucket never drops, so throttle is a deferral cause, not a loss), and
  ``retransmits`` (completed stream flows, the CPU ``_track`` law);
- per run: a fixed-bucket histogram of per-window PACKET-arrival
  occupancy (bucket b = windows whose popped packet count has
  floor(log2) == b; packet-free windows are skipped) — the burst
  evidence ROADMAP open item 3 asks for.  Packets only, because wire
  arrivals are the one event class whose per-window counts are
  bit-identical across backends (LOCAL/DELIVERY decomposition differs:
  start anchors, delivery elision).

The device side accumulates the identical counters inside the lane
kernels (``backend/lanes.py``, ``LaneParams.netobs``) with **zero new
host↔device transfers**: counters stay device-resident and are fetched
only at run-control snapshot epochs and end-of-run, piggybacking the
existing collect readback.  The CPU oracle accumulates them in plain
Python through this module's :class:`NetObs`, so a parity gate can
assert device == oracle per counter per host (tests/test_telemetry.py).

The ``NETOBS_<backend>-seed<N>.json`` artifact is written through the
PR 9 Recorder lifecycle (engine/sim.py) and is **integer-only** — no
wall-clock values — so run-twice artifacts diff byte-identical (the
determinism contract of docs/determinism.md).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Optional

import numpy as np

SCHEMA_VERSION = 1

#: must match backend.lanes.NB_HIST_BUCKETS (imported there would cycle)
HIST_BUCKETS = 24

#: the canonical per-host counter catalog, in report order
COUNTERS = (
    "sent",
    "delivered",
    "tx_bytes",
    "rx_bytes",
    "drop_loss",
    "drop_codel",
    "drop_queue",
    "drop_cross_shed",
    "throttled",
    "retransmits",
    "retry_giveup",
)

#: the drop-cause taxonomy (docs/observability.md)
DROP_CAUSES = ("loss", "codel", "queue", "cross_shed", "retry_giveup")

TOP_TALKERS = 10
#: per-host breakdown is embedded only up to this host count (top
#: talkers and totals carry the signal at larger scales)
PER_HOST_CAP = 1024


def hist_bucket(count: int) -> int:
    """floor(log2(count)) clamped to the fixed bucket range (count >= 1).
    The identical law to the device's ``ilog2_i32`` path."""
    return min(max(int(count), 1).bit_length() - 1, HIST_BUCKETS - 1)


def empty_arrays(n_hosts: int) -> dict[str, np.ndarray]:
    """A fresh all-zero counter-array schema."""
    return {k: np.zeros(n_hosts, dtype=np.int64) for k in COUNTERS}


class NetObs:
    """Host-side (oracle) accumulator of the per-host counters and the
    window histogram.

    Thread-safety by ownership, matching the engines' execution model:
    every array row is written only by the thread executing that host
    (sends touch the source row from the source host's thread, arrivals
    the destination row from the destination host's thread), and the
    window flush runs on the round loop after the barrier.  No locks on
    the hot path."""

    def __init__(self, n_hosts: int) -> None:
        self.n_hosts = n_hosts
        self.sent = np.zeros(n_hosts, dtype=np.int64)
        self.delivered = np.zeros(n_hosts, dtype=np.int64)
        self.tx_bytes = np.zeros(n_hosts, dtype=np.int64)
        self.rx_bytes = np.zeros(n_hosts, dtype=np.int64)
        self.drop_loss = np.zeros(n_hosts, dtype=np.int64)
        self.drop_codel = np.zeros(n_hosts, dtype=np.int64)
        # PACKET pops per host (cumulative); the round flush sums the
        # delta into the window histogram
        self.pops = np.zeros(n_hosts, dtype=np.int64)
        self.window_hist = np.zeros(HIST_BUCKETS, dtype=np.int64)
        self._pops_taken = 0

    # -- hot-path hooks (each touches one thread-owned row) ----------------

    def on_send(self, src: int, size_bytes: int) -> None:
        self.sent[src] += 1
        self.tx_bytes[src] += size_bytes

    def on_loss(self, src: int) -> None:
        self.drop_loss[src] += 1

    def on_delivered(self, dst: int, size_bytes: int) -> None:
        self.delivered[dst] += 1
        self.rx_bytes[dst] += size_bytes

    def on_codel(self, dst: int) -> None:
        self.drop_codel[dst] += 1

    # -- window flush (round loop, post-barrier) ---------------------------

    def take_round_pops(self) -> int:
        """Pops since the last take — a multiprocess worker ships this
        in its round reply so the parent can flush the global window."""
        total = int(self.pops.sum())
        delta = total - self._pops_taken
        self._pops_taken = total
        return delta

    def flush_window(self, count: Optional[int] = None) -> None:
        """Fold one finished window's event occupancy into the histogram
        (``count=None`` = this accumulator's own pop delta)."""
        if count is None:
            count = self.take_round_pops()
        if count > 0:
            self.window_hist[hist_bucket(count)] += 1

    # -- snapshot ----------------------------------------------------------

    def base_arrays(self) -> dict[str, np.ndarray]:
        """The accumulator's counters in the canonical schema (copies).
        Engine snapshots fill the remaining keys (``throttled`` from the
        token buckets, ``retransmits``/``retry_giveup`` from host
        counters, queue/shed from the device side)."""
        arrays = empty_arrays(self.n_hosts)
        arrays["sent"] = self.sent.copy()
        arrays["delivered"] = self.delivered.copy()
        arrays["tx_bytes"] = self.tx_bytes.copy()
        arrays["rx_bytes"] = self.rx_bytes.copy()
        arrays["drop_loss"] = self.drop_loss.copy()
        arrays["drop_codel"] = self.drop_codel.copy()
        return arrays


def merge_arrays(
    into: dict[str, np.ndarray], other: dict[str, np.ndarray]
) -> dict[str, np.ndarray]:
    """Elementwise-sum ``other`` into ``into`` (schema keys only)."""
    for k in COUNTERS:
        if k in other:
            into[k] = into[k] + np.asarray(other[k], dtype=np.int64)
    return into


def totals(arrays: dict[str, np.ndarray]) -> dict[str, int]:
    return {k: int(arrays[k].sum()) for k in COUNTERS}


def build_report(
    run_id: str,
    backend: str,
    seed: int,
    hostnames: list[str],
    arrays: dict[str, np.ndarray],
    window_hist,
    host_window_hist=None,
    log_lost: int = 0,
    extra: Optional[dict] = None,
) -> dict:
    """The NETOBS document (schema in docs/observability.md).  Integer
    content only, deterministic ordering — run-twice artifacts must diff
    byte-identical."""
    n = len(hostnames)
    tot = totals(arrays)
    drops = {
        "loss": tot["drop_loss"],
        "codel": tot["drop_codel"],
        "queue": tot["drop_queue"],
        "cross_shed": tot["drop_cross_shed"],
        "retry_giveup": tot["retry_giveup"],
    }
    hist = [int(v) for v in np.asarray(window_hist)]
    # top talkers: most tx bytes, then most packets, host id breaks ties
    order = sorted(
        range(n),
        key=lambda i: (
            -int(arrays["tx_bytes"][i]), -int(arrays["sent"][i]), i
        ),
    )
    talkers = [
        {
            "host": hostnames[i],
            "sent": int(arrays["sent"][i]),
            "tx_bytes": int(arrays["tx_bytes"][i]),
            "delivered": int(arrays["delivered"][i]),
            "rx_bytes": int(arrays["rx_bytes"][i]),
        }
        for i in order[:TOP_TALKERS]
        if int(arrays["sent"][i]) or int(arrays["tx_bytes"][i])
    ]
    wire_drops = (
        tot["drop_loss"] + tot["drop_codel"] + tot["drop_queue"]
        + tot["drop_cross_shed"]
    )
    doc: dict = {
        "schema": SCHEMA_VERSION,
        "run_id": run_id,
        "backend": backend,
        "seed": int(seed),
        "num_hosts": n,
        "totals": tot,
        "drops_by_cause": drops,
        "drop_total": sum(drops.values()),
        # conservation: sent == delivered + wire drops + in flight at
        # stop_time (packets whose arrival lies past the end of the run)
        "in_flight": tot["sent"] - tot["delivered"] - wire_drops,
        "log_lost": int(log_lost),
        "window_hist": {
            "scheme": "log2-packet-arrivals",
            "buckets": hist,
            "windows": sum(hist),
        },
        "top_talkers": talkers,
    }
    if host_window_hist is not None:
        hh = [int(v) for v in np.asarray(host_window_hist)]
        doc["host_window_hist"] = {
            "scheme": "log2-packet-arrivals",
            "buckets": hh,
            "windows": sum(hh),
        }
    if n <= PER_HOST_CAP:
        doc["per_host"] = {
            hostnames[i]: {k: int(arrays[k][i]) for k in COUNTERS}
            for i in range(n)
        }
    if extra:
        doc.update(extra)
    return doc


def write_report(path: str | Path, report: dict) -> Path:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(report, indent=2, sort_keys=True) + "\n")
    return path


def snapshot_lines(
    arrays: dict[str, np.ndarray],
    window_hist,
    hostnames: list[str],
    host: Optional[str] = None,
) -> list[str]:
    """Human-readable snapshot (the run-control ``netstats`` verb)."""
    tot = totals(arrays)
    lines = [
        "net totals: "
        + " ".join(f"{k}={tot[k]}" for k in (
            "sent", "delivered", "tx_bytes", "rx_bytes"))
    ]
    lines.append(
        "drops: "
        + " ".join(f"{k}={tot[k]}" for k in (
            "drop_loss", "drop_codel", "drop_queue", "drop_cross_shed",
            "retry_giveup"))
        + f" throttled={tot['throttled']} retransmits={tot['retransmits']}"
    )
    hist = [int(v) for v in np.asarray(window_hist)]
    top = max((i for i, v in enumerate(hist) if v), default=-1)
    lines.append(
        "window hist (log2 packet arrivals): "
        + (" ".join(f"b{i}={hist[i]}" for i in range(top + 1))
           if top >= 0 else "no windows yet")
    )
    if host is not None:
        if host not in hostnames:
            lines.append(f"unknown host {host!r}")
        else:
            i = hostnames.index(host)
            lines.append(
                f"{host}: "
                + " ".join(f"{k}={int(arrays[k][i])}" for k in COUNTERS)
            )
    return lines
