"""Device-turn ledger: causal turn accounting + fusion-headroom evidence.

PR 9 measured *how long* the hybrid path's blocking device turns take
(``device_turn`` = 92.8% of wall on ``managed_relay_chains_large``,
BENCH_r07); this module records *why each turn exists* and *how many
consecutive windows could legally have been fused into one dispatch* —
the instrument ROADMAP open item 1 (k-window device free-run,
speculative pipelining) designs against, the same way PR 10's
burst-window histogram instruments item 3.

One :class:`TurnLedger` per run (owned by the obs
:class:`~shadow_tpu.obs.recorder.Recorder`, slot pattern: ``None`` = off
= zero calls).  A **row** is one blocking device dispatch on the device
backends — hybrid ``hybrid_fn`` call, tpu step-driver round, or the tpu
fused driver's whole free-run — and one window round on the CPU oracle,
where the "device" is hypothetical and the ledger answers *what a
device run of this config could legally have fused*.

The **turn-cause taxonomy** — one primary cause per row, decided in
priority order ``fault_swap`` > ``egress_drain`` > ``injection`` >
``host_window`` > ``snapshot``/``free_run``:

- ``fault_swap``   — first dispatch against a freshly swapped fault
  table (epoch-segmented tpu runs; CPU windows where the fault runtime
  installed a snapshot);
- ``egress_drain`` — mid-window resumption after the device paused on
  low egress-buffer headroom (hybrid only; always empty-injection);
- ``injection``    — the dispatch carried a non-empty injection block
  (managed-host sends staged since the previous turn; on the CPU oracle:
  the window staged >= 1 managed, non-loopback, surviving send);
- ``host_window``  — a managed host participates in the turn's completed
  window (the conservative clamp forces the device to return there);
- ``snapshot``     — a run-control snapshot epoch: the pausable tpu step
  driver dispatches one device call per round exactly so the console can
  pause/inspect at every boundary;
- ``free_run``     — nothing forced the dispatch to block: the device
  free-ran to drain/stop with no managed participation (the tpu fused
  driver's whole run is one such row — the comparison baseline).

The **conservation law** ``turns == sum(cause_counts.values())`` holds
by construction and is asserted on every exported artifact
(``make turns-smoke``).

The **fusable-run accounting** is the headroom instrument.  A row is
*fusable* iff its injection block was **provably empty** — nothing from
the host side had to enter the device before the dispatch ran.  The
conservative window law's only hard dependency chain is
``device(W) -> host(W) -> device(W+1)`` *through the injection*
(docs/hybrid.md): a dispatch whose injection is empty could have been
absorbed into its predecessor's free-run by a law able to prove that
emptiness — item 1(a) extended by the provably-empty-injection
condition of item 1(b), and every window such a dispatch covers has no
managed participation the device had to stop for.  Maximal runs of
consecutive fusable rows accumulate into a log2 run-length histogram
plus deterministic percentiles; an injecting turn closes the current
run.  Run lengths count the rows' ``windows`` (1 per dispatch on
hybrid/step, the measured free-run length on the fused driver), so the
CPU oracle's histogram reads directly as *the legal free-run length
distribution of this scenario* — the dispatch-collapse item 1 would
realize.

Two headroom estimates close the loop (``summary()``/bench keys):

- ``kfusion_headroom`` = turns / (turns - fusable turns): the ceiling
  of the fusable-run collapse — every empty-injection dispatch merges
  into its predecessor;
- ``kfusion_headroom_freerun`` = turns / (turns - strict free turns):
  the narrower, provable-without-any-host-knowledge 1(a) collapse —
  only rows with NO managed participation at all (``egress_drain`` /
  ``free_run`` causes) merge.

Determinism contract: the ledger stores **integers only** (causes are
fixed strings, times are sim-ns, participants are host ids) and never
feeds a value back into the simulation, so ``TURNS_<run_id>.json`` diffs
byte-identical run-twice and bit-identical across hybrid worker counts
(tests/test_turns.py).  Rows derive exclusively from data the host side
already holds per turn — recording adds **zero host<->device
transfers** (the hybrid ``sync_stats`` transfer counts are asserted
unchanged with the ledger on).
"""

from __future__ import annotations

import json
import logging
from pathlib import Path
from typing import Optional

log = logging.getLogger(__name__)

from .netobs import HIST_BUCKETS as _NETOBS_HIST_BUCKETS
from .netobs import hist_bucket as _hist_bucket

SCHEMA_VERSION = 1

#: the turn-cause taxonomy, in report order (docs/observability.md).
#: ``rollback`` (PR 13) marks a fused-prefix rebuild dispatch: a k-window
#: fused turn whose speculation failed validation re-ran its validated
#: prefix from the checkpoint — the dispatch is real (counted by the
#: conservation law) but covers no windows the primary row did not
#: already account for (``windows=0``)
CAUSES = (
    "host_window",
    "injection",
    "egress_drain",
    "snapshot",
    "fault_swap",
    "free_run",
    "rollback",
)

#: causes carrying NO managed participation at all — the strict 1(a)
#: free-run rows (fusable without even proving injection emptiness)
STRICT_FREE_CAUSES = ("egress_drain", "free_run")

#: log2 run-length histogram width (bucket b = runs of [2^b, 2^(b+1))
#: windows) — the netobs burst-window histogram's scheme, reused so the
#: two bucketing laws can never drift apart
RUN_HIST_BUCKETS = _NETOBS_HIST_BUCKETS

#: per-turn rows kept verbatim; past this the rows list stops growing
#: (aggregates keep counting) and ``rows_dropped`` records the loss
DEFAULT_CAPACITY = 1 << 18

#: deterministic percentile sample: the FIRST N run lengths (the same
#: bounded-sample law as obs.metrics)
SAMPLE_CAP = 65536


def run_bucket(length: int) -> int:
    """floor(log2(length)) clamped to the histogram range (length >= 1)
    — the identical law to the netobs window histogram."""
    return _hist_bucket(length)


class TurnLedger:
    """Single-threaded by ownership: every engine records turns from its
    round/window loop (the controller thread), never from workers —
    worker processes ship participant sets over the round pipes and the
    parent records.  No locks needed."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        self.capacity = capacity
        # rows: [cause, t_start, t_end, windows, inject_rows,
        #        egress_rows, [participant host ids...]]
        self.rows: list[list] = []
        self.rows_dropped = 0
        self.turns = 0
        self.cause_counts: dict[str, int] = {c: 0 for c in CAUSES}
        self.host_rounds = 0
        self.inject_rows_total = 0
        self.egress_rows_total = 0
        self.empty_injection_turns = 0
        # rows with no managed participation at all (strict 1(a) rows);
        # attach_participants retro-corrects the most recent PRIMARY row
        # (egress_drain resumptions cover participation-free partial
        # windows and stay strict regardless)
        self.strict_free_turns = 0
        self._last_primary_idx: Optional[int] = None
        self._last_primary_strict = False
        # host id -> number of turns whose completed window it
        # participated in
        self.participation: dict[int, int] = {}
        # fusable-run accounting (closed runs of empty-injection rows)
        self.run_hist = [0] * RUN_HIST_BUCKETS
        self.run_count = 0
        self.run_windows_total = 0
        self.run_max = 0
        self._run_sample: list[int] = []
        self._open_run = 0
        self._finished = False
        # realized-fusion accounting (PR 13): windows_covered_total is
        # the unfused turn count the rows imply (every non-rollback row
        # counts max(windows, 1)); fused rows are dispatches that
        # covered >= 2 validated windows
        self.windows_covered_total = 0
        self.fused_turns = 0
        self.fused_windows_total = 0

    # -- recording ---------------------------------------------------------

    def turn(
        self,
        cause: str,
        t_start: int,
        t_end: int,
        windows: int = 1,
        inject_rows: int = 0,
        egress_rows: int = 0,
        participants: tuple = (),
    ) -> None:
        """Record one blocking device dispatch (or oracle window)."""
        if cause not in self.cause_counts:
            raise ValueError(f"unknown turn cause {cause!r}")
        self.turns += 1
        self.cause_counts[cause] += 1
        self.inject_rows_total += inject_rows
        self.egress_rows_total += egress_rows
        if cause != "rollback":
            # rollback rebuilds re-run windows their primary row already
            # covers: they count as turns (conservation) but neither as
            # fusable evidence nor toward the implied-unfused total
            self.windows_covered_total += max(int(windows), 1)
            if int(windows) >= 2:
                self.fused_turns += 1
                self.fused_windows_total += int(windows)
        if inject_rows == 0 and cause != "rollback":
            self.empty_injection_turns += 1
        for hid in participants:
            self.participation[int(hid)] = (
                self.participation.get(int(hid), 0) + 1
            )
        stored = len(self.rows) < self.capacity
        if stored:
            self.rows.append([
                cause, int(t_start), int(t_end), int(windows),
                int(inject_rows), int(egress_rows),
                [int(h) for h in participants],
            ])
        else:
            self.rows_dropped += 1
        if cause in STRICT_FREE_CAUSES and not participants:
            self.strict_free_turns += 1
            strict = True
        else:
            strict = False
        if cause not in ("egress_drain", "rollback"):
            # a turn's PRIMARY row (resumptions and rollback rebuilds
            # are never primary): attach_participants retro-corrects
            # this one
            self._last_primary_idx = len(self.rows) - 1 if stored else None
            self._last_primary_strict = strict
        if inject_rows == 0:
            # fusable: nothing from the host entered the device before
            # this dispatch — a fusion law proving that emptiness could
            # have absorbed it into the previous dispatch
            self._open_run += max(int(windows), 0)
        else:
            self._close_run()

    def attach_participants(self, participants) -> None:
        """Amend the most recent turn's PRIMARY row with the managed
        hosts that participated in its completed window (the
        multiprocess hybrid engine learns the set from the worker round
        replies, *after* the turn rows are recorded; egress-drain
        resumption and rollback rows cover participation-free or
        re-run windows and are never amended).  A fused turn attaches
        once per covered round: the row accumulates the sorted union.
        Participation retro-corrects the strict free-turn count; the
        fusable (empty-injection) run is unaffected — participation
        alone does not force an injection."""
        participants = tuple(int(h) for h in participants)
        if not participants:
            return
        for hid in participants:
            self.participation[hid] = self.participation.get(hid, 0) + 1
        if self._last_primary_idx is not None:
            row = self.rows[self._last_primary_idx]
            row[6] = sorted(set(row[6]) | set(participants))
        if self._last_primary_strict:
            self.strict_free_turns -= 1
            self._last_primary_strict = False

    def host_round(self) -> None:
        """A host-only window (no device dispatch) ran.  Bookkeeping
        only: if it staged sends, the NEXT dispatch's injection cause
        closes the fusable run; if not, the device free-run could have
        continued straight through it."""
        self.host_rounds += 1

    def _close_run(self) -> None:
        n = self._open_run
        if n <= 0:
            return
        self._open_run = 0
        self.run_hist[run_bucket(n)] += 1
        self.run_count += 1
        self.run_windows_total += n
        if n > self.run_max:
            self.run_max = n
        if len(self._run_sample) < SAMPLE_CAP:
            self._run_sample.append(n)

    def finish(self) -> None:
        """Close the trailing fusable run (idempotent; called by the
        Recorder at finalize, before export)."""
        if not self._finished:
            self._finished = True
            self._close_run()

    # -- read side ---------------------------------------------------------

    def fusable_percentiles(self) -> dict[str, int]:
        s = sorted(self._run_sample)  # one sort serves all quantiles

        def pct(q: float) -> int:
            if not s:
                return 0
            return s[min(int(q * len(s)), len(s) - 1)]

        return {
            "p50": pct(0.50),
            "p90": pct(0.90),
            "p99": pct(0.99),
            "max": self.run_max,
        }

    def kfusion_headroom(self) -> float:
        """Turn-collapse ceiling of the fusable-run law (ROADMAP item
        1a+1b): every empty-injection dispatch merges into its
        predecessor once injection emptiness is provable."""
        if not self.turns:
            return 1.0
        return round(
            self.turns / max(self.turns - self.empty_injection_turns, 1), 4
        )

    def kfusion_headroom_freerun(self) -> float:
        """Conservative, strict-1(a) collapse: only rows with no managed
        participation at all merge into their predecessor's dispatch."""
        if not self.turns:
            return 1.0
        return round(
            self.turns / max(self.turns - self.strict_free_turns, 1), 4
        )

    def turns_saved(self) -> int:
        """Blocking dispatches the realized fusion eliminated, NET of
        rollback rebuilds: the unfused law would have spent one dispatch
        per covered window (``windows_covered_total``); the fused run
        spent ``turns`` (rebuilds included).  0 on unfused runs."""
        return self.windows_covered_total - self.turns

    def achieved_fusion(self) -> float:
        """The realized turn collapse: implied unfused turns per actual
        dispatch — the achieved counterpart of the kfusion_headroom
        predictions (1.0 when fusion is off or ineffective)."""
        if not self.turns:
            return 1.0
        return round(self.windows_covered_total / self.turns, 4)

    def summary(self) -> dict:
        """Aggregates only (live-safe: includes the open run without
        closing it) — what bench.py and the ``turns`` verb read."""
        pct = self.fusable_percentiles()
        return {
            "turns": self.turns,
            "cause_counts": dict(self.cause_counts),
            "host_rounds": self.host_rounds,
            "inject_rows_total": self.inject_rows_total,
            "egress_rows_total": self.egress_rows_total,
            "empty_injection_turns": self.empty_injection_turns,
            "strict_free_turns": self.strict_free_turns,
            "fusable_runs": self.run_count + (1 if self._open_run else 0),
            "fusable_windows_total": (
                self.run_windows_total + self._open_run
            ),
            "fusable_run_p50": pct["p50"],
            "fusable_run_p90": pct["p90"],
            "fusable_run_p99": pct["p99"],
            "fusable_run_max": max(self.run_max, self._open_run),
            "kfusion_headroom": self.kfusion_headroom(),
            "kfusion_headroom_freerun": self.kfusion_headroom_freerun(),
            "fused_turns": self.fused_turns,
            "fused_windows_total": self.fused_windows_total,
            "implied_unfused_turns": self.windows_covered_total,
            "turns_saved": self.turns_saved(),
            "achieved_fusion": self.achieved_fusion(),
            "rollbacks": self.cause_counts["rollback"],
        }

    def report(self, run_id: str) -> dict:
        """The TURNS document (schema in docs/observability.md).
        Integer-only content, deterministic ordering — run-twice
        artifacts must diff byte-identical."""
        self.finish()
        assert self.turns == sum(self.cause_counts.values()), (
            "turn-cause conservation violated"
        )
        pct = self.fusable_percentiles()
        return {
            "schema": SCHEMA_VERSION,
            "run_id": run_id,
            "turns": self.turns,
            "cause_counts": dict(self.cause_counts),
            "host_rounds": self.host_rounds,
            "inject_rows_total": self.inject_rows_total,
            "egress_rows_total": self.egress_rows_total,
            "empty_injection_turns": self.empty_injection_turns,
            "strict_free_turns": self.strict_free_turns,
            "participation": {
                str(hid): n for hid, n in sorted(self.participation.items())
            },
            "fusable": {
                "scheme": "log2-run-windows",
                "buckets": list(self.run_hist),
                "runs": self.run_count,
                "windows_total": self.run_windows_total,
                "p50": pct["p50"],
                "p90": pct["p90"],
                "p99": pct["p99"],
                "max": self.run_max,
            },
            "kfusion_headroom": self.kfusion_headroom(),
            "kfusion_headroom_freerun": self.kfusion_headroom_freerun(),
            "fused": {
                "turns": self.fused_turns,
                "windows_total": self.fused_windows_total,
                "implied_unfused_turns": self.windows_covered_total,
                "turns_saved": self.turns_saved(),
                "achieved_fusion": self.achieved_fusion(),
                "rollbacks": self.cause_counts["rollback"],
            },
            "rows_dropped": self.rows_dropped,
            "rows": [list(r) for r in self.rows],
        }

    def snapshot_lines(self) -> list[str]:
        """Human-readable snapshot (the run-control ``turns`` verb)."""
        s = self.summary()
        lines = [
            f"turns: {s['turns']} "
            + " ".join(
                f"{c}={s['cause_counts'][c]}"
                for c in CAUSES
                if s["cause_counts"][c]
            ),
            f"host_rounds={s['host_rounds']} "
            f"inject_rows={s['inject_rows_total']} "
            f"egress_rows={s['egress_rows_total']} "
            f"empty_injection_turns={s['empty_injection_turns']}",
            f"fusable runs: {s['fusable_runs']} covering "
            f"{s['fusable_windows_total']} window(s), "
            f"p50={s['fusable_run_p50']} p99={s['fusable_run_p99']} "
            f"max={s['fusable_run_max']}",
            f"k-fusion headroom: {s['kfusion_headroom']}x speculative "
            f"(empty injection), {s['kfusion_headroom_freerun']}x "
            "provable (free-run)",
            f"fused runs: {s['fused_turns']} dispatch(es) covering "
            f"{s['fused_windows_total']} window(s), "
            f"{s['turns_saved']} turn(s) saved, "
            f"{s['rollbacks']} rollback(s); achieved "
            f"{s['achieved_fusion']}x collapse",
        ]
        if not s["turns"]:
            return ["no device turns recorded yet"]
        return lines


def write_report(path: str | Path, report: dict) -> Path:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(report, indent=2, sort_keys=True) + "\n")
    return path


def load_report(path: str | Path) -> dict:
    return json.loads(Path(path).read_text())


def check_fusion_accounting(
    ledger: "TurnLedger", sync_stats: dict,
    warn_fraction: Optional[float] = None,
) -> None:
    """The fused-turn conservation cross-check (ISSUE 13 satellite),
    run by the hybrid engines at end of run when the ledger is on:

    1. HARD: the engine's independently-counted ``turns_saved`` must
       agree with the ledger aggregates, and ``turns`` plus that engine
       count must equal the unfused turn count recomputed from the
       cause rows themselves — the aggregate ``turns + turns_saved ==
       implied`` identity holds by construction (``turns_saved`` IS
       ``windows_covered - turns``), so the engine counter and the
       per-row recompute are the two independent sides that can
       actually catch a mis-recorded dispatch;
    2. SOFT: the achieved collapse should reach ``warn_fraction`` of the
       ledger's REMAINING free-run headroom prediction — if fusion
       silently disengages, rows revert to the unfused pattern, the
       remaining headroom climbs while achieved collapses to 1.0, and
       this warns (never fails)."""
    saved = ledger.turns_saved()
    engine_saved = sync_stats.get("turns_saved", 0)
    if engine_saved != saved:
        raise AssertionError(
            "fused-turn accounting drift: engine counted "
            f"turns_saved={engine_saved} but the ledger aggregates "
            f"imply {saved}"
        )
    if not ledger.rows_dropped:
        # recompute the implied-unfused total from the rows themselves —
        # independent of both the aggregate counters and the engine's
        # turns_saved, so a dispatch recorded with a drifted
        # windows/cause value cannot self-consistently hide
        implied_rows = sum(
            max(r[3], 1) for r in ledger.rows if r[0] != "rollback"
        )
        if ledger.turns + engine_saved != implied_rows:
            raise AssertionError(
                f"fused-turn conservation violated: turns="
                f"{ledger.turns} + engine turns_saved={engine_saved} "
                f"!= {implied_rows} unfused turns implied by the rows"
            )
    if warn_fraction:
        predicted = ledger.kfusion_headroom_freerun()
        achieved = ledger.achieved_fusion()
        if achieved < warn_fraction * predicted:
            log.warning(
                "k-window fusion underperforming: achieved %.2fx "
                "collapse vs %.2fx remaining free-run headroom "
                "(floor fraction %.2f) — check hybrid_fuse_k and the "
                "scenario's external lookahead",
                achieved, predicted, warn_fraction,
            )


def check_conservation(report: dict) -> Optional[str]:
    """Validate the conservation law on an exported artifact; returns an
    error string or None (``make turns-smoke``, tests)."""
    total = sum(report.get("cause_counts", {}).values())
    if report.get("turns") != total:
        return (
            f"turns={report.get('turns')} != sum(cause_counts)={total}"
        )
    rows = report.get("rows", [])
    if len(rows) + report.get("rows_dropped", 0) != report.get("turns"):
        return (
            f"rows({len(rows)}) + dropped({report.get('rows_dropped')}) "
            f"!= turns({report.get('turns')})"
        )
    return None
