"""Fault schedules compiled into versioned routing tables.

A :class:`FaultOverlay` turns a validated :class:`FaultSchedule` into one
``(latency_ns, packet_loss, loss_threshold)`` snapshot per *fault epoch*
(each distinct event time).  Snapshots are cumulative: the state at epoch
``t`` reflects every event with ``at <= t``.  Computation is entirely
deterministic — re-running the all-pairs shortest-path compile of
:class:`~shadow_tpu.net.graph.NetworkGraph` over the surviving edge set —
so the same schedule + seed always yields the same tables.

Semantics (docs/faults.md):

- ``link_down`` removes the edge from the route compile.  Pairs that keep
  an alternative path reroute (their latency/loss change accordingly);
  pairs that become unreachable keep their *base* latency but get a
  loss threshold of 1.0 — every packet between them is dropped at the
  source with the ordinary ``loss`` outcome.  Keeping the base latency
  (rather than a sentinel) matters only for the dynamic-runahead
  bookkeeping, which both backends apply identically.
- ``partition`` / ``host_crash`` act at the *pair* level after the route
  compile: affected pairs drop everything, routing elsewhere is
  untouched.
- Fault-induced drops obey the same bootstrap exemption as configured
  loss; config validation therefore rejects events inside the bootstrap
  window (the exemption would silently defeat them).

The CPU engine installs snapshots **in place** into its live graph at
window boundaries (:class:`FaultRuntime`); the TPU engine re-uploads them
as fresh device gather tables at epoch boundaries
(``TpuEngine._run_faulted``).  Both clamp round windows at epoch times,
which keeps the window sequence — and the event log — bit-identical
across backends.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from ..core.time import NEVER
from ..net.graph import _UNREACHABLE, GraphEdge, NetworkGraph
from .schedule import FaultConfigError, FaultEvent, FaultSchedule

FULL_THRESHOLD = np.int64(1) << 32  # loss = 1.0 in the u64 Bernoulli domain


@dataclasses.dataclass
class _EdgeOverride:
    down: bool = False
    latency_ns: Optional[int] = None
    loss: Optional[float] = None

    def clear(self) -> bool:
        """True when the override is back to base (droppable)."""
        return not self.down and self.latency_ns is None and self.loss is None


@dataclasses.dataclass(frozen=True)
class Snapshot:
    at: int
    latency_ns: np.ndarray  # [G, G] int64 (base latency kept on down pairs)
    packet_loss: np.ndarray  # [G, G] float64
    loss_threshold: np.ndarray  # [G, G] int64 (2**32 = drop everything)
    stall: bool  # a backend_stall event fires at this epoch


class FaultOverlay:
    """Schedule -> per-epoch table snapshots over a compiled base graph."""

    def __init__(
        self,
        schedule: FaultSchedule,
        graph: NetworkGraph,
        host_node_index: dict[int, int],
        hostnames: list[str],
        use_shortest_path: bool = True,
        bootstrap_end: int = 0,
    ) -> None:
        self.schedule = schedule
        self.base = graph
        self.use_shortest_path = use_shortest_path
        self.bootstrap_end = bootstrap_end
        self._host_node_index = dict(host_node_index)
        self._host_by_name = {name: hid for hid, name in enumerate(hostnames)}
        self._node_host_count: dict[int, int] = {}
        for idx in host_node_index.values():
            self._node_host_count[idx] = self._node_host_count.get(idx, 0) + 1
        self._snapshots: list[Snapshot] = []
        self._recompute()

    # -- event -> mutable fault state ---------------------------------------

    def _edge_index(self, ev: FaultEvent) -> int:
        for i, e in enumerate(self.base.edges):
            if (e.source, e.target) == (ev.source, ev.target):
                return i
            if not self.base.directed and (e.target, e.source) == (
                ev.source,
                ev.target,
            ):
                return i
        raise FaultConfigError(
            f"{ev.kind} at {ev.at} ns: no edge {ev.source}->{ev.target} in the graph"
        )

    def _node_index(self, node_id: int, ev: FaultEvent) -> int:
        idx = self.base.id_to_index.get(node_id)
        if idx is None:
            raise FaultConfigError(
                f"{ev.kind} at {ev.at} ns: unknown graph node id {node_id}"
            )
        return idx

    def _crash_node(self, ev: FaultEvent) -> int:
        hid = self._host_by_name.get(ev.host)
        if hid is None:
            raise FaultConfigError(
                f"{ev.kind} at {ev.at} ns: unknown host {ev.host!r}"
            )
        idx = self._host_node_index[hid]
        if ev.kind == "host_crash" and self._node_host_count.get(idx, 0) > 1:
            raise FaultConfigError(
                f"host_crash at {ev.at} ns: host {ev.host!r} shares graph "
                f"node {self.base.node_ids[idx]} with other hosts — crash "
                "isolation is per graph node; give the host its own node"
            )
        return idx

    def _validate(self, ev: FaultEvent) -> None:
        if ev.at < self.bootstrap_end:
            raise FaultConfigError(
                f"{ev.kind} at {ev.at} ns lies inside the loss-free bootstrap "
                f"window (bootstrap_end_time={self.bootstrap_end} ns); fault "
                "drops would be silently exempted — schedule it later"
            )
        if ev.kind in ("link_down", "link_up", "loss", "latency"):
            self._edge_index(ev)
        elif ev.kind == "partition":
            for g in ev.groups:
                for nid in g:
                    self._node_index(nid, ev)
        elif ev.kind in ("host_crash", "host_restart"):
            self._crash_node(ev)

    def _recompute(self) -> None:
        """Walk the schedule in time order, compiling one cumulative
        snapshot per distinct event time."""
        for ev in self.schedule.events:
            self._validate(ev)
        over: dict[int, _EdgeOverride] = {}
        partition: Optional[tuple[tuple[int, ...], ...]] = None
        crashed: set[int] = set()
        snapshots: list[Snapshot] = []
        events = self.schedule.events
        i = 0
        while i < len(events):
            t = events[i].at
            stall = False
            while i < len(events) and events[i].at == t:
                ev = events[i]
                i += 1
                if ev.kind == "backend_stall":
                    stall = True
                    continue
                if ev.kind in ("link_down", "link_up", "loss", "latency"):
                    ei = self._edge_index(ev)
                    o = over.setdefault(ei, _EdgeOverride())
                    if ev.kind == "link_down":
                        o.down = True
                    elif ev.kind == "link_up":
                        over.pop(ei, None)
                    elif ev.kind == "loss":
                        o.loss = ev.loss
                    else:
                        o.latency_ns = ev.latency_ns
                elif ev.kind == "partition":
                    partition = tuple(
                        tuple(self._node_index(nid, ev) for nid in g)
                        for g in ev.groups
                    )
                elif ev.kind == "heal":
                    partition = None
                elif ev.kind == "host_crash":
                    crashed.add(self._crash_node(ev))
                elif ev.kind == "host_restart":
                    crashed.discard(self._crash_node(ev))
            lat, loss, thr = self._compile(over, partition, crashed)
            snapshots.append(Snapshot(t, lat, loss, thr, stall))
        self._snapshots = snapshots

    # -- table compilation ---------------------------------------------------

    def _compile(
        self,
        over: dict[int, _EdgeOverride],
        partition: Optional[tuple[tuple[int, ...], ...]],
        crashed: set[int],
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        base = self.base
        g = len(base.nodes)
        edges = []
        for idx, e in enumerate(base.edges):
            o = over.get(idx)
            if o is not None and o.down:
                continue
            edges.append(
                GraphEdge(
                    source=e.source,
                    target=e.target,
                    latency_ns=(
                        o.latency_ns
                        if o is not None and o.latency_ns is not None
                        else e.latency_ns
                    ),
                    packet_loss=(
                        o.loss if o is not None and o.loss is not None else e.packet_loss
                    ),
                )
            )
        if edges:
            g2 = NetworkGraph(
                list(base.nodes), edges, base.directed, self.use_shortest_path
            )
            lat2, loss2, thr2 = g2.latency_ns, g2.packet_loss, g2.loss_threshold
        else:  # every edge down: nothing is routable
            lat2 = np.full((g, g), _UNREACHABLE, dtype=np.int64)
            loss2 = np.zeros((g, g), dtype=np.float64)
            thr2 = np.zeros((g, g), dtype=np.int64)

        base_reach = base.latency_ns != _UNREACHABLE
        # pairs that LOST their route (reachable in base, not now)
        down = (lat2 == _UNREACHABLE) & base_reach
        for n in crashed:
            down[n, :] = True
            down[:, n] = True
        if partition is not None:
            for ai, ga in enumerate(partition):
                for gb in partition[ai + 1 :]:
                    for a in ga:
                        for b in gb:
                            down[a, b] = True
                            down[b, a] = True
        # down pairs keep a usable latency (base fallback where the route
        # vanished) and drop everything via the threshold
        lat = np.where(lat2 == _UNREACHABLE, base.latency_ns, lat2)
        loss = np.where(down, 1.0, loss2)
        thr = np.where(down, FULL_THRESHOLD, thr2)
        return lat, loss, thr

    # -- queries -------------------------------------------------------------

    def epoch_times(self) -> list[int]:
        return [s.at for s in self._snapshots]

    def snapshot_at(self, t: int) -> Optional[Snapshot]:
        """Latest snapshot with ``at <= t`` (None = base tables apply)."""
        best = None
        for s in self._snapshots:
            if s.at <= t:
                best = s
            else:
                break
        return best

    def stall_at(self, t: int) -> bool:
        for s in self._snapshots:
            if s.at == t:
                return s.stall
        return False

    def max_latency_ns(self) -> int:
        """Max routable latency over the base and every snapshot (the
        conservative bound for the stream tier's wide-pop soundness)."""
        mx = int(np.max(self.base.latency_ns, initial=0))
        for s in self._snapshots:
            mx = max(mx, int(np.max(s.latency_ns, initial=0)))
        return mx

    def any_loss(self) -> bool:
        if bool(np.any(self.base.loss_threshold > 0)):
            return True
        return any(bool(np.any(s.loss_threshold > 0)) for s in self._snapshots)

    def segment_plan(
        self, stop_time: int, pad_to: int = 0
    ) -> list[tuple[int, int, Optional[Snapshot]]]:
        """The run's epoch segmentation as ``(seg_start, seg_end,
        snapshot)`` rows: segment boundaries at every epoch time inside
        ``(0, stop_time)``, each row carrying the snapshot whose tables
        govern it (None = base tables).

        ``pad_to`` appends NO-OP rows — zero-length ``(stop_time,
        stop_time, last_snapshot)`` segments — until the plan has that
        many rows.  This is the documented padded-epoch representation
        (docs/sweep.md): schedules of different lengths batch into one
        static shape without retracing.  Padding is bit-safe ONLY in
        this trailing zero-length form: at ``seg_start == seg_end ==
        stop_time`` every queue min is already >= the stop bound, so the
        run loop admits no pops and no window advances — whereas a
        mid-run zero-length segment would still clamp a window at its
        boundary and shift the netobs window sequence."""
        stop = int(stop_time)
        bounds = [t for t in self.epoch_times() if 0 < t < stop] + [stop]
        plan: list[tuple[int, int, Optional[Snapshot]]] = []
        seg_start = 0
        for seg_end in bounds:
            snap = self.snapshot_at(seg_start) if seg_start > 0 else None
            plan.append((seg_start, seg_end, snap))
            seg_start = seg_end
        last = plan[-1][2]
        while len(plan) < pad_to:
            plan.append((stop, stop, last))
        return plan

    def add_event(self, ev: FaultEvent) -> None:
        """Dynamic (console) injection: validate, insert, recompute."""
        self._validate(ev)
        self.schedule.add(ev)
        self._recompute()


class FaultRuntime:
    """The CPU engine's window-boundary applier.

    ``advance_to(start)`` installs the newest snapshot at or before the
    round's window start into the live graph (in place — RoutingInfo
    reads the graph's tables on every ``path()``); ``window_bound(start)``
    returns the next epoch strictly after ``start`` so the round loop can
    clamp the window there.  Both are O(#epochs) scans over a list that
    is tiny by construction.
    """

    def __init__(self, overlay: FaultOverlay) -> None:
        self.overlay = overlay
        self._installed_at: Optional[int] = None

    def advance_to(self, start: int) -> None:
        snap = self.overlay.snapshot_at(start)
        if snap is None or snap.at == self._installed_at:
            return
        self.overlay.base.install_tables(
            snap.latency_ns, snap.packet_loss, snap.loss_threshold
        )
        self._installed_at = snap.at

    def window_bound(self, start: int) -> int:
        for t in self.overlay.epoch_times():
            if t > start:
                return t
        return NEVER

    def inject(self, ev: FaultEvent) -> None:
        """Console injection; forces a re-install at the next boundary."""
        self.overlay.add_event(ev)
        self._installed_at = None


def build_overlay(cfg, graph: NetworkGraph, routing) -> Optional[FaultOverlay]:
    """Overlay for a config's fault schedule (None when no events)."""
    fo = getattr(cfg, "faults", None)
    if fo is None:
        return None
    schedule = fo.schedule()
    if not len(schedule):
        return None
    return FaultOverlay(
        schedule,
        graph,
        routing.host_node_index,
        [h.hostname for h in cfg.hosts],
        use_shortest_path=cfg.network.use_shortest_path,
        bootstrap_end=cfg.general.bootstrap_end_time,
    )


def build_fault_runtime(cfg, graph: NetworkGraph, routing) -> Optional[FaultRuntime]:
    overlay = build_overlay(cfg, graph, routing)
    return None if overlay is None else FaultRuntime(overlay)


def empty_fault_runtime(cfg, graph: NetworkGraph, routing) -> FaultRuntime:
    """A runtime with no scheduled events — the console-injection seam for
    runs whose config carries no ``faults:`` section."""
    overlay = FaultOverlay(
        FaultSchedule([]),
        graph,
        routing.host_node_index,
        [h.hostname for h in cfg.hosts],
        use_shortest_path=cfg.network.use_shortest_path,
        bootstrap_end=cfg.general.bootstrap_end_time,
    )
    return FaultRuntime(overlay)
