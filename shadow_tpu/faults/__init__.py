"""Deterministic fault injection and graceful backend degradation.

The subsystem has three layers, mirroring how the reference fork stress-
tests consensus networks inside the simulation rather than around it:

- :mod:`shadow_tpu.faults.schedule` — the declarative, validated fault
  schedule (the ``faults:`` config section): link down/up, per-edge
  loss/latency changes, network bipartitions, host crash/restart, and
  injected backend stalls, each pinned to a simulated time.
- :mod:`shadow_tpu.faults.overlay` — the schedule compiled into versioned
  routing tables: one ``(latency_ns, packet_loss, loss_threshold)``
  snapshot per fault epoch, derived from the base
  :class:`~shadow_tpu.net.graph.NetworkGraph` by re-running the
  shortest-path compile over the surviving edges.  The CPU engine installs
  snapshots in place at window boundaries; the TPU engine re-uploads them
  as fresh gather tables at epoch boundaries.  Both backends clamp round
  windows at fault epochs, so the window sequence — and therefore the
  event log — is bit-identical across backends and across runs.
- :mod:`shadow_tpu.faults.watchdog` — the graceful-degradation boundary:
  a per-round stall watchdog for the TPU step driver and the
  :class:`FailoverRequest`/:class:`BackendStallError` signals the
  simulation facade converts into a deterministic CPU replay.
"""

from .schedule import FaultConfigError, FaultEvent, FaultSchedule
from .watchdog import BackendStallError, FailoverRequest, RoundWatchdog

__all__ = [
    "FaultConfigError",
    "FaultEvent",
    "FaultSchedule",
    "BackendStallError",
    "FailoverRequest",
    "RoundWatchdog",
]
