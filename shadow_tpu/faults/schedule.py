"""Declarative fault schedules: parsing + validation.

The ``faults.events`` config list is parsed into typed
:class:`FaultEvent` records at config-validation time, so a typo'd kind
or an out-of-range loss fails the config — never the run.  Event kinds:

========================  =====================================================
``link_down``             remove the GML edge ``source``/``target`` from
                          routing (traffic reroutes if an alternative path
                          exists; otherwise the pair drops every packet)
``link_up``               restore the edge to its base properties (clears any
                          loss/latency override too)
``loss``                  set the edge's ``packet_loss`` to ``loss``
``latency``               set the edge's ``latency`` to ``latency``
``partition``             bipartition (or k-partition) the graph:
                          ``groups: [[0], [1, 2]]`` lists graph node ids;
                          pairs in *different* groups drop every packet;
                          nodes not listed are unaffected.  A new partition
                          replaces the previous one.
``heal``                  clear the active partition
``host_crash``            isolate ``host`` from the network entirely (every
                          packet to or from it drops); the host's own graph
                          node must not be shared with other hosts
``host_restart``          undo a ``host_crash``
``backend_stall``         inject a simulated backend failure: the TPU engine
                          raises at this epoch (exercising CPU failover);
                          the CPU engine — being the failover target —
                          treats it as a window-boundary no-op
========================  =====================================================

Every event has an ``at:`` simulated time (unit string or bare seconds).
All times become deterministic *window-clamp epochs* on both backends:
no round window ever straddles a fault, which is what makes fault replay
bit-identical (docs/faults.md).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional

from ..config import units


class FaultConfigError(ValueError):
    pass


LINK_KINDS = ("link_down", "link_up", "loss", "latency")
HOST_KINDS = ("host_crash", "host_restart")
KINDS = LINK_KINDS + HOST_KINDS + ("partition", "heal", "backend_stall")


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    """One schedule entry.  Unused fields keep their neutral defaults so
    the record stays a plain, hashable value object."""

    at: int  # ns, > 0
    kind: str
    source: int = -1  # graph node id (link kinds)
    target: int = -1
    loss: float = -1.0  # [0,1] (kind == "loss")
    latency_ns: int = 0  # > 0 (kind == "latency")
    groups: tuple[tuple[int, ...], ...] = ()  # kind == "partition"
    host: str = ""  # hostname (host kinds)


def _parse_groups(v: Any) -> tuple[tuple[int, ...], ...]:
    if not isinstance(v, (list, tuple)) or len(v) < 2:
        raise FaultConfigError(
            "partition 'groups' must list at least two groups of graph "
            f"node ids, e.g. [[0], [1, 2]]; got {v!r}"
        )
    groups = []
    seen: set[int] = set()
    for g in v:
        if not isinstance(g, (list, tuple)) or not g:
            raise FaultConfigError(f"partition group must be a non-empty list, got {g!r}")
        ids = tuple(int(x) for x in g)
        dup = seen.intersection(ids)
        if dup or len(set(ids)) != len(ids):
            raise FaultConfigError(
                f"partition groups must be disjoint (node {sorted(dup or set(ids))[0]} repeats)"
            )
        seen.update(ids)
        groups.append(ids)
    return tuple(groups)


def parse_event(doc: dict[str, Any]) -> FaultEvent:
    if not isinstance(doc, dict):
        raise FaultConfigError(f"fault event must be a mapping, got {doc!r}")
    doc = dict(doc)
    if "at" not in doc:
        raise FaultConfigError("fault event needs an 'at' time")
    at = units.parse_time(doc.pop("at"))
    if at <= 0:
        raise FaultConfigError(
            f"fault event 'at' must be > 0 (initial conditions belong in the "
            f"graph itself), got {at} ns"
        )
    kind = str(doc.pop("kind", ""))
    if kind not in KINDS:
        raise FaultConfigError(
            f"unknown fault kind {kind!r}; expected one of {sorted(KINDS)}"
        )
    ev = {"at": at, "kind": kind}
    if kind in LINK_KINDS:
        for k in ("source", "target"):
            if k not in doc:
                raise FaultConfigError(f"{kind} event needs '{k}' (a graph node id)")
            ev[k] = int(doc.pop(k))
        if kind == "loss":
            if "loss" not in doc:
                raise FaultConfigError("loss event needs a 'loss' value in [0, 1]")
            loss = float(doc.pop("loss"))
            if not math.isfinite(loss) or not (0.0 <= loss <= 1.0):
                raise FaultConfigError(
                    f"loss event: 'loss' must be a finite value in [0, 1], got {loss!r}"
                )
            ev["loss"] = loss
        elif kind == "latency":
            if "latency" not in doc:
                raise FaultConfigError(
                    'latency event needs a \'latency\' unit string like "20 ms"'
                )
            lat = units.parse_time(doc.pop("latency"))
            if lat <= 0:
                raise FaultConfigError("latency event: 'latency' must be > 0")
            ev["latency_ns"] = lat
    elif kind == "partition":
        ev["groups"] = _parse_groups(doc.pop("groups", None))
    elif kind in HOST_KINDS:
        host = doc.pop("host", None)
        if not host:
            raise FaultConfigError(f"{kind} event needs a 'host' (hostname)")
        ev["host"] = str(host)
    # heal / backend_stall take no extra fields
    if doc:
        raise FaultConfigError(
            f"unknown keys on {kind} fault event: {sorted(doc)}"
        )
    return FaultEvent(**ev)


class FaultSchedule:
    """An ordered, validated list of fault events.

    Events are kept in ``(at, listed-order)`` order: same-instant events
    apply in the order the config lists them, which makes the cumulative
    fault state — and every table snapshot — deterministic.
    """

    def __init__(self, events: list[FaultEvent]) -> None:
        self.events = sorted(
            events, key=lambda e: e.at
        )  # Python sort is stable: listed order breaks ties

    @classmethod
    def parse(cls, raw: list) -> "FaultSchedule":
        if raw is None:
            raw = []
        if not isinstance(raw, (list, tuple)):
            raise FaultConfigError(
                f"faults.events must be a list of event mappings, got {raw!r}"
            )
        return cls([parse_event(e) for e in raw])

    def __len__(self) -> int:
        return len(self.events)

    def epoch_times(self) -> list[int]:
        """Sorted unique event times — the window-clamp epochs."""
        return sorted({e.at for e in self.events})

    def events_at(self, t: int) -> list[FaultEvent]:
        return [e for e in self.events if e.at == t]

    def add(self, ev: FaultEvent) -> None:
        """Insert a (console-injected) event, keeping the order invariant."""
        self.events = sorted(self.events + [ev], key=lambda e: e.at)


# -- run-control console grammar --------------------------------------------

_CONSOLE_USAGE = (
    "fault link_down S T | fault link_up S T | fault loss S T P | "
    "fault latency S T DUR | fault partition A,B|C,... | fault heal | "
    "fault crash HOST | fault restart HOST"
)


def parse_console_fault(tokens: list[str], at: int) -> FaultEvent:
    """Parse a run-control ``fault ...`` command into an event effective at
    ``at`` (the current window boundary).  Grammar::

        fault link_down 0 1
        fault link_up 0 1
        fault loss 0 1 0.3
        fault latency 0 1 20ms
        fault partition 0|1,2
        fault heal
        fault crash relay1
        fault restart relay1
    """
    if not tokens:
        raise FaultConfigError(f"empty fault command; usage: {_CONSOLE_USAGE}")
    verb, args = tokens[0], tokens[1:]
    alias = {"crash": "host_crash", "restart": "host_restart"}
    kind = alias.get(verb, verb)
    # ``at`` arrives in ns; spell it out so parse_time's bare-seconds
    # convention cannot misread it
    doc: dict[str, Any] = {"at": f"{at} ns", "kind": kind}
    try:
        if kind in ("link_down", "link_up"):
            doc["source"], doc["target"] = int(args[0]), int(args[1])
        elif kind == "loss":
            doc["source"], doc["target"] = int(args[0]), int(args[1])
            doc["loss"] = float(args[2])
        elif kind == "latency":
            doc["source"], doc["target"] = int(args[0]), int(args[1])
            doc["latency"] = args[2]
        elif kind == "partition":
            doc["groups"] = [
                [int(x) for x in grp.split(",") if x] for grp in args[0].split("|")
            ]
        elif kind in ("host_crash", "host_restart"):
            doc["host"] = args[0]
        elif kind == "heal":
            pass
        else:
            raise FaultConfigError(
                f"unknown fault verb {verb!r}; usage: {_CONSOLE_USAGE}"
            )
    except (IndexError, ValueError) as e:
        if isinstance(e, FaultConfigError):
            raise
        raise FaultConfigError(
            f"bad arguments for 'fault {verb}': {' '.join(args)!r}; "
            f"usage: {_CONSOLE_USAGE}"
        )
    return parse_event(doc)
