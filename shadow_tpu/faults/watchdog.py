"""Backend watchdog: the TPU->CPU graceful-degradation boundary.

Two signals cross it:

- :class:`BackendStallError` — the TPU engine detected (or was injected
  with) a stalled/failed device round: a ``backend_stall`` schedule event
  fired, or a step-mode round exceeded ``faults.watchdog_timeout`` wall
  seconds.
- :class:`FailoverRequest` — an explicit demand to degrade, raised by the
  run-control ``failover`` verb from a window boundary.

The simulation facade (engine/sim.py) catches both — plus any other
exception escaping the TPU path while ``faults.failover`` is enabled —
and **replays deterministically from the newest valid state**
(docs/robustness.md).  When checkpointing is on and a valid checkpoint
exists, only the suffix past its epoch replays (a fresh TPU engine
resumes the lane state with injected stalls disarmed; the recovered
prefix is reported as ``restart_work_saved``); otherwise the whole run
replays on the CPU engine from t=0.  Replay is the recovery mechanism
because determinism makes it exact: the replayed run executes the
identical window sequence and event order the failed run would have
produced (the cross-backend parity contract), so the run completes with
the event log an unfaulted CPU run of the same config yields.  No device
state needs to survive the failure for the result to be correct.
"""

from __future__ import annotations

import time as wall_time
from typing import Optional


class BackendStallError(RuntimeError):
    """A TPU round stalled, failed, or was injected to fail."""


class FailoverRequest(Exception):
    """Unwound out of the round loop to force a CPU failover."""

    def __init__(self, reason: str = "failover requested") -> None:
        self.reason = reason
        super().__init__(reason)


class RoundWatchdog:
    """Wall-clock stall detector for the step driver: feed it each round's
    duration; it raises :class:`BackendStallError` when a single device
    round exceeds the timeout.  (The fused device run is one opaque call —
    a stall there surfaces as the device runtime's own error, which the
    same failover boundary catches.)"""

    def __init__(self, timeout_seconds: Optional[float]) -> None:
        self.timeout = timeout_seconds
        self.rounds = 0
        self.worst = 0.0

    def observe(self, elapsed_seconds: float) -> None:
        self.rounds += 1
        if elapsed_seconds > self.worst:
            self.worst = elapsed_seconds
        if self.timeout is not None and elapsed_seconds > self.timeout:
            raise BackendStallError(
                f"device round {self.rounds} took {elapsed_seconds:.3f}s "
                f"(watchdog_timeout={self.timeout:.3f}s)"
            )

    def timed(self, fn, *args):
        """Run ``fn(*args)``, observe its duration, return its result."""
        t0 = wall_time.perf_counter()
        out = fn(*args)
        self.observe(wall_time.perf_counter() - t0)
        return out
