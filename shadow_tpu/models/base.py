"""Application models (the simulated workloads).

The reference runs real Linux binaries under syscall interposition; the
built-in *models* here are the TPU-friendly first tier: each model is a
small state machine over the host API below, restricted enough that the TPU
lane backend can run the identical logic vectorized on-device (one lane per
host).  Real-binary execution via the native shim plugs into the same engine
as a host-resident app (later milestone).

A model reacts to three stimuli, always at a definite simulation time:

- ``on_start(api)``        — process start (config ``start_time``)
- ``on_timer(api, t)``     — a timer it armed fired
- ``on_delivery(api, t, src, seq, size)`` — a datagram arrived

and acts through the :class:`HostApi`: ``send``, ``set_timer``,
``rand_u32`` (deterministic APP_STREAM draws), and counters.
"""

from __future__ import annotations

from typing import Any, Callable, Protocol


class HostApi(Protocol):
    """What a model may do to its host (both backends provide this)."""

    host_id: int
    num_hosts: int

    def send(self, dst: int, size_bytes: int) -> int:
        """Send a datagram (IP size incl. 28 header bytes) at current time;
        returns its per-host sequence number."""

    def set_timer(self, t_abs_ns: int) -> None:
        """Arm a timer local event at absolute sim time."""

    def set_timer_relative(self, delta_ns: int) -> None:
        """Arm a timer ``delta_ns`` after the current time."""

    def schedule_at(self, t_abs_ns: int, fn) -> None:
        """Queue an exact-time local event calling ``fn(host)`` (may land
        at the current instant; pops in event-key order)."""

    def resolve(self, hostname: str) -> int:
        """DNS: hostname -> host id (also accepts a numeric id string)."""

    def rand_u32(self) -> int:
        """Next deterministic app-stream draw (u32)."""

    def count(self, key: str, n: int = 1) -> None:
        """Bump a named per-host counter (merged into sim stats)."""


class AppModel(Protocol):
    def on_start(self, api: HostApi) -> None: ...

    def on_timer(self, api: HostApi, t: int) -> None: ...

    def on_delivery(self, api: HostApi, t: int, src: int, seq: int, size: int, payload=None) -> None: ...


_REGISTRY: dict[str, Callable[..., AppModel]] = {}


def register_model(name: str):
    def deco(cls):
        _REGISTRY[name] = cls
        return cls

    return deco


def create_model(
    path: str, args: list[str], environment: dict | None = None
) -> AppModel:
    """Instantiate an app from a process ``path`` + ``args`` (config-
    compatible with the reference's process entries).  A registered model
    name selects the built-in (lane-compilable) tier; an executable path
    selects the native-shim tier — a real Linux binary run under syscall
    interposition, as the reference does for every process."""
    if path in _REGISTRY:
        return _REGISTRY[path].from_args(args)  # type: ignore[attr-defined]
    import os

    if os.path.isfile(path) and os.access(path, os.X_OK):
        from ..native.process import ManagedApp

        return ManagedApp([path, *args], environment)
    raise ValueError(
        f"unknown app model {path!r}: neither a built-in model "
        f"({sorted(_REGISTRY)}) nor an executable file"
    )


def parse_kv_args(args: list[str], known: set[str] | None = None) -> dict[str, str]:
    """Parse ``--key value`` / ``--key=value`` model args.  When ``known``
    is given, unknown keys are rejected (typos must not silently fall back
    to defaults)."""
    out: dict[str, str] = {}
    i = 0
    while i < len(args):
        a = args[i]
        if not a.startswith("--"):
            raise ValueError(f"model args must be --key value pairs, got {a!r}")
        if "=" in a:
            k, _, v = a[2:].partition("=")
            out[k] = v
            i += 1
        else:
            if i + 1 >= len(args):
                raise ValueError(f"missing value for model arg {a!r}")
            out[a[2:]] = args[i + 1]
            i += 2
    if known is not None:
        unknown = set(out) - known
        if unknown:
            raise ValueError(
                f"unknown model args {sorted('--' + k for k in unknown)} "
                f"(known: {sorted('--' + k for k in known)})"
            )
    return out
