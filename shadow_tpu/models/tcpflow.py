"""Stream tier: TCP flows under the lane-TCP law (net/ltcp.py).

``stream-client --server H --size B [--mss M]`` opens one ltcp flow to the
server host at start time and streams B bytes as MSS-sized segments through
the full law — handshake, Reno/NewReno congestion control, RTO, teardown —
over the engine's normal packet path (token buckets, loss draw, latency,
CoDel).  ``stream-server`` sinks any number of flows.

This is the CPU-oracle form of the vectorized TCP tier the lane backend
runs on device (backend/lanes.py); determinism tests diff the two event
logs bit-for-bit.  The byte-accurate sans-I/O stack (transport/tcp.py,
models/tgen_tcp.py) remains the managed-process tier; reference analog:
src/test/tgen fixed_size workloads over src/lib/tcp.

Counters: ``stream_tx_segs`` / ``stream_retransmits`` / ``stream_complete``
(client), ``stream_rx_segs`` / ``stream_rx_bytes`` / ``stream_flows_done``
(server).
"""

from __future__ import annotations

import dataclasses

from ..config import units
from ..net import ltcp
from .base import HostApi, parse_kv_args, register_model


@dataclasses.dataclass
class StreamSeg:
    """Engine payload of one ltcp wire segment.  ``client``/``conn`` name
    the flow (the client host owns the namespace); contents never enter
    the event log — parity is behavioral, via times/sizes/outcomes."""

    client: int
    conn: int
    flags: int
    seq: int
    ack: int


class _FlowDriver:
    """Shared stimulus plumbing: apply an Emit to the host (send the
    segment, arm pump/RTO events at exact times).  ``client`` is the flow
    namespace (the client host's id) regardless of which end is sending."""

    def _apply(self, api, fs: ltcp.FlowState, em: ltcp.Emit, peer: int,
               client: int, conn: int):
        for (flags, seq, ack, size), rx in zip(em.sends, em.retx):
            api.send(peer, size, payload=StreamSeg(client, conn, flags, seq, ack),
                     retx=rx)
        if em.arm_pump:
            api.schedule_at(api.now, self._pump_cb(fs, peer, client, conn))
        if em.arm_rto is not None:
            api.schedule_at(em.arm_rto, self._rto_cb(fs, peer, client, conn))
        if em.aborted:
            # the ltcp give-up law fired (MAX_RTO_BACKOFFS consecutive
            # timeouts — a dead path); surfaced in sim-stats
            # packet_outcomes as "retry_drop" (engine/sim.py)
            api.count("stream_retry_drops")
            ft = getattr(api, "ft_giveup", None)
            if ft is not None:
                ft(peer)
        return em

    def _pump_cb(self, fs, peer, client, conn):
        def fire(host):
            em = ltcp.on_pump(fs, host.now)
            self._apply(host, fs, em, peer, client, conn)

        return fire

    def _rto_cb(self, fs, peer, client, conn):
        def fire(host):
            em = ltcp.on_rto_event(fs, host.now)
            self._apply(host, fs, em, peer, client, conn)

        return fire


@register_model("stream-client")
class StreamClient(_FlowDriver):
    """One ltcp flow: connect at start, stream ``--size`` bytes, close."""

    def __init__(self, server: str, size: int, mss: int = 1448) -> None:
        self.server = server
        self.size = size
        self.mss = mss
        self.fs = ltcp.FlowState(role=ltcp.SENDER, mss=mss)
        self.fs.segs, self.fs.last_bytes = ltcp.segs_for_size(size, mss)
        self._peer = -1
        self._conn = 0  # per-host process index, set at start
        self._done_counted = False

    @classmethod
    def from_args(cls, args: list[str]) -> "StreamClient":
        kv = parse_kv_args(args, known={"server", "size", "mss"})
        return cls(
            server=kv.pop("server", "server"),
            size=units.parse_bytes(kv.pop("size", "1 MiB")),
            mss=int(kv.pop("mss", 1448)),
        )

    def set_congestion(self, name: str) -> None:
        """Engine hook: the host's ``congestion`` option selects this
        flow's algorithm (CC follows the data sender; the server end's
        receiver role never grows a window)."""
        self.fs.cc = ltcp.CC_BY_NAME[name]

    def on_start(self, api: HostApi) -> None:
        self._peer = api.resolve(self.server)
        # conn id = this process's index on its host: two stream-clients on
        # one host to the same server stay distinct flows at the server
        apps = getattr(api, "apps", None)
        self._conn = apps.index(self) if apps is not None else 0
        em = ltcp.open_flow(self.fs, api.now)
        self._track(api, self._apply(api, self.fs, em, self._peer,
                                     api.host_id, self._conn))

    def on_timer(self, api: HostApi, t: int) -> None:
        pass

    def on_delivery(self, api, t, src, seq, size, payload=None) -> None:
        if not isinstance(payload, StreamSeg) or src != self._peer:
            return
        if payload.client != api.host_id or payload.conn != self._conn:
            return
        em = ltcp.on_segment(
            self.fs, t, payload.flags, payload.seq, payload.ack, size
        )
        self._track(api, self._apply(api, self.fs, em, self._peer,
                                     api.host_id, self._conn))

    def _track(self, api, em: ltcp.Emit) -> None:
        if em.completed and not self._done_counted:
            self._done_counted = True
            api.count("stream_complete")
            api.count("stream_tx_segs", self.fs.tx_segs)
            api.count("stream_retransmits", self.fs.retransmits)


@register_model("stream-server")
class StreamServer(_FlowDriver):
    """Sink any number of ltcp flows (one record per (client, conn))."""

    def __init__(self) -> None:
        self.flows: dict[tuple[int, int], ltcp.FlowState] = {}

    @classmethod
    def from_args(cls, args: list[str]) -> "StreamServer":
        parse_kv_args(args, known=set())
        return cls()

    def on_start(self, api: HostApi) -> None:
        pass

    def on_timer(self, api: HostApi, t: int) -> None:
        pass

    def on_delivery(self, api, t, src, seq, size, payload=None) -> None:
        if not isinstance(payload, StreamSeg) or payload.client != src:
            return  # only client->server segments open/advance server flows
        key = (payload.client, payload.conn)
        fs = self.flows.get(key)
        if fs is None:
            fs = ltcp.FlowState(role=ltcp.RECEIVER)
            self.flows[key] = fs
        pre_rx = fs.rx_bytes
        pre_segs = fs.rx_segs
        em = ltcp.on_segment(fs, t, payload.flags, payload.seq, payload.ack, size)
        self._apply(api, fs, em, src, payload.client, payload.conn)
        if fs.rx_bytes > pre_rx:
            api.count("stream_rx_bytes", fs.rx_bytes - pre_rx)
        if fs.rx_segs > pre_segs:
            api.count("stream_rx_segs", fs.rx_segs - pre_segs)
        if em.completed:
            api.count("stream_flows_done")
