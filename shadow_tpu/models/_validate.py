"""Shared model-arg validation helpers."""

from __future__ import annotations


def positive_interval(interval_ns: int, model: str) -> int:
    if interval_ns <= 0:
        raise ValueError(
            f"{model}: --interval must be > 0 (a zero interval would fire "
            "the timer at the same instant forever)"
        )
    return interval_ns
