"""tgen-style TCP workloads: fixed-size transfers over the simulated stack.

The TCP analog of the reference's tgen fixed_size integration workload
(src/test/tgen/fixed_size): each client opens one TCP connection to a
server, streams ``--size`` bytes through the full simulated stack
(handshake, Reno congestion control, loss recovery, flow control — all of
transport/tcp.py over the packet path of net/stack.py), then closes; the
server accepts any number of connections and counts received bytes.

Counters: ``tcp_tx_bytes`` / ``tcp_rx_bytes`` (payload), ``tcp_complete``
(client transfers fully sent+closed), ``tcp_accepted`` /
``tcp_conns_closed`` (server side), ``tcp_refused`` (connect errors).
CPU backend (host tier); the lane backend carries the vectorized stream
tier instead.
"""

from __future__ import annotations

from ..config import units
from ..transport.tcp import PollState
from .base import HostApi, parse_kv_args, register_model

CHUNK = 65536
DEFAULT_PORT = 80


@register_model("tgen-tcp-client")
class TgenTcpClient:
    """``--server H --size B [--port P]``: connect, stream B bytes, close."""

    def __init__(self, server: str, size: int, port: int = DEFAULT_PORT) -> None:
        self.server = server
        self.size = size
        self.port = port
        self._remaining = size
        self._sock = None
        self._done = False
        self._established = False

    @classmethod
    def from_args(cls, args: list[str]) -> "TgenTcpClient":
        kv = parse_kv_args(args, known={"server", "size", "port"})
        return cls(
            server=kv.pop("server", "server"),
            size=units.parse_bytes(kv.pop("size", "1 MiB")),
            port=int(kv.pop("port", DEFAULT_PORT)),
        )

    def on_start(self, api: HostApi) -> None:
        dst = api.resolve(self.server)
        self._sock = api.net.connect(dst, self.port)
        self._sock.on_event = self._event

    def on_timer(self, api: HostApi, t: int) -> None:
        pass

    def on_delivery(self, api, t, src, seq, size, payload=None) -> None:
        pass

    def _event(self, sock, now: int) -> None:
        api = sock.stack.host
        ps = sock.poll()
        if ps & PollState.ERROR:
            if not self._done:
                self._done = True
                # refused = error before the handshake ever completed;
                # aborted = an established connection died mid-transfer
                api.count("tcp_refused" if not self._established else "tcp_aborted")
                sock.close()
            return
        if ps & PollState.WRITABLE:
            # only a completed handshake makes the socket writable; a
            # timer event in SYN_SENT (e.g. a SYN-retransmit) must not
            # mark the flow established or a later failure would count
            # as tcp_aborted instead of tcp_refused
            self._established = True
        while self._remaining > 0 and ps & PollState.WRITABLE:
            n = sock.send(bytes(min(self._remaining, CHUNK)))
            if n == 0:
                break
            self._remaining -= n
            api.count("tcp_tx_bytes", n)
            ps = sock.poll()
        if self._remaining == 0 and not self._done:
            self._done = True
            sock.close()
            api.count("tcp_complete")


@register_model("tgen-tcp-server")
class TgenTcpServer:
    """``[--port P]``: accept connections, count bytes until peer EOF."""

    def __init__(self, port: int = DEFAULT_PORT) -> None:
        self.port = port

    @classmethod
    def from_args(cls, args: list[str]) -> "TgenTcpServer":
        kv = parse_kv_args(args, known={"port"})
        return cls(port=int(kv.pop("port", DEFAULT_PORT)))

    def on_start(self, api: HostApi) -> None:
        lst = api.net.listen(self.port)
        lst.on_accept = self._accept

    def on_timer(self, api: HostApi, t: int) -> None:
        pass

    def on_delivery(self, api, t, src, seq, size, payload=None) -> None:
        pass

    def _accept(self, sock, now: int) -> None:
        sock.stack.host.count("tcp_accepted")
        sock.on_event = self._event
        self._event(sock, now)

    def _event(self, sock, now: int) -> None:
        api = sock.stack.host
        while True:
            data = sock.recv(CHUNK)
            if not data:
                break
            api.count("tcp_rx_bytes", len(data))
        if (
            sock.tcp.at_eof()
            and not sock.tcp.is_closed()
            and not sock.poll() & PollState.SEND_CLOSED  # not already closing
        ):
            sock.close()
            api.count("tcp_conns_closed")
