"""PHOLD — the classic PDES benchmark workload.

Mirrors the role of the reference's phold stress test
(src/test/phold/test_phold.c): a fixed population of messages bounces
between hosts over UDP; every delivery triggers one new send to a uniformly
random peer.  Message count is conserved (absent network loss), which makes
it both a load generator and a correctness check.

Deterministic: peer choices come from the host's APP_STREAM threefry
counters, so replays (and the TPU lane backend) pick identical peers.
"""

from __future__ import annotations

from ..core.rng import u32_below
from .base import HostApi, parse_kv_args, register_model


@register_model("phold")
class Phold:
    """``--messages M`` initial messages per host, ``--size B`` datagram
    size in bytes (IP size incl. headers, default 256)."""

    def __init__(self, messages: int = 1, size: int = 256) -> None:
        self.messages = messages
        self.size = size

    @classmethod
    def from_args(cls, args: list[str]) -> "Phold":
        kv = parse_kv_args(args, known={"messages", "size"})
        return cls(
            messages=int(kv.pop("messages", 1)),
            size=int(kv.pop("size", 256)),
        )

    def _pick_peer(self, api: HostApi) -> int:
        """Uniform peer among the *other* hosts (self excluded) — matches
        the lane backend's vectorized formula."""
        if api.num_hosts == 1:
            return api.host_id
        r = int(u32_below(api.rand_u32(), api.num_hosts - 1))
        return (api.host_id + 1 + r) % api.num_hosts

    def on_start(self, api: HostApi) -> None:
        for _ in range(self.messages):
            api.send(self._pick_peer(api), self.size)

    def on_timer(self, api: HostApi, t: int) -> None:  # pragma: no cover
        pass

    def on_delivery(self, api: HostApi, t: int, src: int, seq: int, size: int, payload=None) -> None:
        api.count("phold_hops")
        api.send(self._pick_peer(api), self.size)
