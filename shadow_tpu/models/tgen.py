"""tgen-style traffic generation models.

Behavioral stand-ins for the reference's tgen integration workloads
(src/test/tgen/{fixed_duration,fixed_size}): generators push datagram
streams through the simulated network while sinks count bytes.  These are
the workloads behind the BASELINE configs (100-host star, 1k/10k-host
all-to-all mesh).

``tgen-mesh`` — every host sends a ``--size`` B datagram every
``--interval`` to its peers (round-robin over all other hosts, or
``--peer-stride`` for sparser patterns), and counts whatever it receives:
the all-to-all mesh load.

``tgen-client`` / ``tgen-server`` — fixed-rate client streams to one named
server (star topologies, basic 2-host transfer).
"""

from __future__ import annotations

from ..config import units
from ._validate import positive_interval
from .base import HostApi, parse_kv_args, register_model


@register_model("tgen-mesh")
class TgenMesh:
    # delivery handling is counters-only: the engine may apply it inline at
    # packet arrival and skip the DELIVERY queue event (both backends elide
    # identically, keeping event logs bit-identical)
    passive_delivery = True

    def __init__(self, interval_ns: int, size: int = 1428, stride: int = 1) -> None:
        self.interval = interval_ns
        self.size = size
        self.stride = stride
        self._next_peer_offset = 0

    @classmethod
    def from_args(cls, args: list[str]) -> "TgenMesh":
        kv = parse_kv_args(args, known={"interval", "size", "peer-stride"})
        return cls(
            interval_ns=positive_interval(units.parse_time(kv.pop("interval", "10 ms")), "tgen-mesh"),
            size=int(kv.pop("size", 1428)),
            stride=int(kv.pop("peer-stride", 1)),
        )

    def on_start(self, api: HostApi) -> None:
        api.set_timer_relative(self.interval)

    def on_timer(self, api: HostApi, t: int) -> None:
        if api.num_hosts > 1:
            off = self._next_peer_offset % (api.num_hosts - 1)
            dst = (api.host_id + 1 + off) % api.num_hosts
            self._next_peer_offset += self.stride
            api.send(dst, self.size)
            api.count("tgen_sent_bytes", self.size)
        api.set_timer_relative(self.interval)

    def on_delivery(self, api: HostApi, t: int, src: int, seq: int, size: int, payload=None) -> None:
        api.count("tgen_recv_bytes", size)


@register_model("tgen-client")
class TgenClient:
    """``--server H`` destination host id (or hostname resolved by the
    engine), ``--interval``, ``--size``."""

    passive_delivery = True

    def __init__(self, server: str, interval_ns: int, size: int = 1428) -> None:
        self.server = server
        self.interval = interval_ns
        self.size = size
        self._dst: int | None = None

    @classmethod
    def from_args(cls, args: list[str]) -> "TgenClient":
        kv = parse_kv_args(args, known={"server", "interval", "size"})
        return cls(
            server=kv.pop("server", "server"),
            interval_ns=positive_interval(units.parse_time(kv.pop("interval", "10 ms")), "tgen-client"),
            size=int(kv.pop("size", 1428)),
        )

    def on_start(self, api: HostApi) -> None:
        self._dst = api.resolve(self.server)
        api.set_timer_relative(self.interval)

    def on_timer(self, api: HostApi, t: int) -> None:
        assert self._dst is not None
        api.send(self._dst, self.size)
        api.count("tgen_sent_bytes", self.size)
        api.set_timer_relative(self.interval)

    def on_delivery(self, api: HostApi, t: int, src: int, seq: int, size: int, payload=None) -> None:
        api.count("tgen_recv_bytes", size)


@register_model("tgen-server")
class TgenServer:
    passive_delivery = True

    @classmethod
    def from_args(cls, args: list[str]) -> "TgenServer":
        parse_kv_args(args, known=set())  # accepts no args
        return cls()

    def on_start(self, api: HostApi) -> None:
        pass

    def on_timer(self, api: HostApi, t: int) -> None:
        pass

    def on_delivery(self, api: HostApi, t: int, src: int, seq: int, size: int, payload=None) -> None:
        api.count("tgen_recv_bytes", size)


@register_model("ping")
class Ping:
    """``--peer H --count K --interval I --size B``: send K echo requests;
    a peerless instance is the echo server.  Counters: ping_sent /
    ping_echoed / ping_recv."""

    def __init__(self, peer: str | None, count: int, interval_ns: int, size: int) -> None:
        self.peer = peer
        self.count_target = count
        self.interval = interval_ns
        self.size = size
        self.sent = 0
        self._dst: int | None = None

    @classmethod
    def from_args(cls, args: list[str]) -> "Ping":
        kv = parse_kv_args(args, known={"peer", "count", "interval", "size"})
        return cls(
            peer=kv.pop("peer", None),
            count=int(kv.pop("count", 10)),
            interval_ns=positive_interval(units.parse_time(kv.pop("interval", "1s")), "ping"),
            size=int(kv.pop("size", 84)),
        )

    def on_start(self, api: HostApi) -> None:
        if self.peer is not None:
            self._dst = api.resolve(self.peer)
            api.set_timer_relative(self.interval)

    def on_timer(self, api: HostApi, t: int) -> None:
        assert self._dst is not None
        if self.sent < self.count_target:
            api.send(self._dst, self.size)
            self.sent += 1
            api.count("ping_sent")
            api.set_timer_relative(self.interval)

    def on_delivery(self, api: HostApi, t: int, src: int, seq: int, size: int, payload=None) -> None:
        if self.peer is None:
            # echo server: bounce straight back
            api.send(src, size)
            api.count("ping_echoed")
        else:
            api.count("ping_recv")
