"""Helper tooling for driving simulations from Python and the shell.

The shadowtools analog (reference ``shadowtools/``): typed config builders
(``shadowtools.config``'s TypedDicts) and a streamlined one-shot runner
(``shadowtools.shadow_exec``).

- :mod:`shadow_tpu.tools.config` — TypedDicts mirroring the YAML document
  shape, for generating configs from Python with IDE/type-checker support.
- :func:`shadow_tpu.tools.shadow_exec` — run one command (or model) in a
  single-host simulation and get its stdout back, like the reference's
  ``shadow-exec date`` giving ``Sat Jan  1 00:00:00 GMT 2000``.
- :class:`shadow_tpu.tools.SimData` — typed access to a finished run's
  data directory (sim-stats, per-host stdout/strace/pcap/counters).
"""

from .config import (
    ConfigDict,
    GeneralDict,
    GraphDict,
    HostDict,
    NetworkDict,
    ProcessDict,
    make_config,
)
from .exec import ExecResult, SimData, shadow_exec

__all__ = [
    "ConfigDict",
    "GeneralDict",
    "GraphDict",
    "HostDict",
    "NetworkDict",
    "ProcessDict",
    "make_config",
    "ExecResult",
    "SimData",
    "shadow_exec",
]
