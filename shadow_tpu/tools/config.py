"""TypedDicts for the simulation config document.

The shadowtools.config analog: the YAML document shape as Python types,
so configs can be generated dynamically with type-checker support and fed
straight to :class:`shadow_tpu.config.options.ConfigOptions.from_dict`.

Example::

    from shadow_tpu.tools import make_config, HostDict, ProcessDict
    from shadow_tpu.config.options import ConfigOptions

    doc = make_config(
        stop_time="10s",
        hosts={
            "client": HostDict(
                network_node_id=0,
                processes=[ProcessDict(path="ping", args=["--peer", "server"])],
            ),
            "server": HostDict(network_node_id=0, processes=[ProcessDict(path="ping")]),
        },
    )
    cfg = ConfigOptions.from_dict(doc)
"""

from __future__ import annotations

from typing import Any, Optional, TypedDict


class ProcessDict(TypedDict, total=False):
    path: str
    args: list[str]
    environment: dict[str, str]
    start_time: str | int
    shutdown_time: str | int
    shutdown_signal: str
    expected_final_state: Any


class HostDict(TypedDict, total=False):
    network_node_id: int
    ip_addr: str
    bandwidth_down: str | int
    bandwidth_up: str | int
    processes: list[ProcessDict]
    log_level: str
    pcap_enabled: bool
    pcap_capture_size: str | int
    count: int


class GeneralDict(TypedDict, total=False):
    stop_time: str | int
    seed: int
    parallelism: int
    bootstrap_end_time: str | int
    data_directory: str
    log_level: str
    heartbeat_interval: Optional[str | int]
    progress: bool
    model_unblocked_syscall_latency: bool


class GraphDict(TypedDict, total=False):
    type: str  # "gml" | "1_gbit_switch"
    file: str
    inline: str


class NetworkDict(TypedDict, total=False):
    graph: GraphDict
    use_shortest_path: bool


class ExperimentalDict(TypedDict, total=False):
    runahead: str | int
    use_dynamic_runahead: bool
    scheduler: str
    use_cpu_pinning: bool
    use_worker_spinning: bool
    use_new_tcp: bool
    socket_send_buffer: str | int
    socket_recv_buffer: str | int
    interface_qdisc: str
    strace_logging_mode: str
    run_control: bool
    perf_logging: bool
    network_backend: str  # "cpu" | "tpu"
    tpu_lane_queue_capacity: int
    tpu_events_per_round: int
    tpu_round_unroll: int
    tpu_cross_capacity: int
    tpu_mesh_shape: list[int]


class ConfigDict(TypedDict, total=False):
    general: GeneralDict
    network: NetworkDict
    experimental: ExperimentalDict
    host_option_defaults: HostDict
    hosts: dict[str, HostDict]


def make_config(
    stop_time: str | int,
    hosts: dict[str, HostDict],
    seed: int = 1,
    general: Optional[GeneralDict] = None,
    network: Optional[NetworkDict] = None,
    experimental: Optional[ExperimentalDict] = None,
    host_option_defaults: Optional[HostDict] = None,
) -> ConfigDict:
    """Assemble a full config document from parts (stop_time and hosts are
    the only required pieces; everything else has simulator defaults)."""
    gen: GeneralDict = dict(general or {})
    gen.setdefault("stop_time", stop_time)
    gen.setdefault("seed", seed)
    doc: ConfigDict = {"general": gen, "hosts": hosts}
    if network is not None:
        doc["network"] = network
    if experimental is not None:
        doc["experimental"] = experimental
    if host_option_defaults is not None:
        doc["host_option_defaults"] = host_option_defaults
    return doc
