"""One-shot simulation runner + typed results access.

``shadow_exec`` is the shadowtools.shadow_exec analog: run one command (a
real binary under the shim, or a built-in model) in a single-host
simulation and get its output back — e.g. a real ``date`` binary prints
``Sat Jan  1 00:00:00 GMT 2000``, the simulation's epoch, exactly like
the reference's ``shadow-exec date`` example.

``SimData`` wraps a finished run's data directory (the reference's
``shadow.data/``): sim-stats, per-host stdout/strace/pcap/counters.
"""

from __future__ import annotations

import dataclasses
import json
import shlex
import shutil
import tempfile
from pathlib import Path
from typing import Optional

from ..config.options import ConfigOptions


class SimData:
    """Typed access to a simulation data directory."""

    def __init__(self, data_dir: str | Path) -> None:
        self.path = Path(data_dir)

    def stats(self) -> dict:
        return json.loads((self.path / "sim-stats.json").read_text())

    def hosts(self) -> list[str]:
        d = self.path / "hosts"
        return sorted(p.name for p in d.iterdir() if p.is_dir()) if d.exists() else []

    def host_dir(self, hostname: str) -> Path:
        return self.path / "hosts" / hostname

    def stdout(self, hostname: str, process_stem: str) -> str:
        return (self.host_dir(hostname) / f"{process_stem}.stdout").read_text(
            errors="replace"  # managed stdout can carry arbitrary bytes
        )

    def strace(self, hostname: str, process_stem: str) -> str:
        return (self.host_dir(hostname) / f"{process_stem}.strace").read_text()

    def pcap_path(self, hostname: str) -> Path:
        return self.host_dir(hostname) / "eth0.pcap"

    def counters(self, hostname: str) -> dict:
        p = self.host_dir(hostname) / "counters.json"
        return json.loads(p.read_text()) if p.exists() else {}


@dataclasses.dataclass
class ExecResult:
    """What shadow_exec hands back for the single process it ran."""

    stdout: str
    exit_code: Optional[int]
    sim_stats: dict
    data: Optional[SimData]  # None when the temp data dir was discarded

    @property
    def ok(self) -> bool:
        return self.exit_code == 0


def shadow_exec(
    argv: list[str] | str,
    stop_time: str | int = "60s",
    seed: int = 1,
    data_directory: Optional[str | Path] = None,
    environment: Optional[dict[str, str]] = None,
    config_extra: Optional[dict] = None,
) -> ExecResult:
    """Run one command in a single-host simulation and return its output.

    ``argv`` names a real binary (absolute path — runs under the native
    shim) or a built-in model.  The host is named ``host0``.  Without
    ``data_directory`` the run uses a temp dir that is deleted afterwards
    (pass one to keep strace/pcap artifacts, like shadow-exec's
    ``--preserve``)."""
    if isinstance(argv, str):
        argv = shlex.split(argv)
    path, args = argv[0], argv[1:]
    keep = data_directory is not None
    data_dir = Path(data_directory) if keep else Path(tempfile.mkdtemp(prefix="shadow-exec-"))
    doc = {
        "general": {
            "stop_time": stop_time,
            "seed": seed,
            "data_directory": str(data_dir),
            "heartbeat_interval": None,
        },
        "network": {"graph": {"type": "1_gbit_switch"}},
        "hosts": {
            "host0": {
                "network_node_id": 0,
                "processes": [
                    {
                        "path": path,
                        "args": args,
                        **({"environment": environment} if environment else {}),
                    }
                ],
            }
        },
    }
    for key, val in (config_extra or {}).items():
        doc.setdefault(key, {}).update(val)
    cfg = ConfigOptions.from_dict(doc)

    from ..engine.sim import Simulation

    sim = Simulation(cfg)
    sim.run()  # dispatches on experimental.network_backend, writes data

    stem = Path(path).name
    stdout_path = data_dir / "hosts" / "host0" / f"{stem}.stdout"
    stdout = (
        stdout_path.read_text(errors="replace") if stdout_path.exists() else ""
    )
    exit_code: Optional[int] = 0
    host0 = sim.engine.hosts[0] if getattr(sim.engine, "hosts", None) else None
    app = host0.apps[0] if host0 is not None and host0.apps else None
    if app is not None and hasattr(app, "exit_code"):
        exit_code = app.exit_code
    stats = json.loads((data_dir / "sim-stats.json").read_text())
    if keep:
        return ExecResult(stdout, exit_code, stats, SimData(data_dir))
    shutil.rmtree(data_dir, ignore_errors=True)
    return ExecResult(stdout, exit_code, stats, None)


def main(argv: Optional[list[str]] = None) -> int:
    """``python -m shadow_tpu.tools.exec [options] -- CMD [ARGS...]`` —
    the shadow-exec CLI (reference shadowtools/src/shadowtools/shadow_exec.py)."""
    import argparse
    import sys

    p = argparse.ArgumentParser(
        prog="shadow-exec",
        description="Run one command in a single-host simulation and print "
        "its output (a real binary sees the simulated clock/network).",
    )
    p.add_argument("--stop-time", default="60s")
    p.add_argument("--seed", type=int, default=1)
    p.add_argument(
        "--preserve",
        metavar="DIR",
        default=None,
        help="keep the data directory at DIR (strace/pcap/stats)",
    )
    p.add_argument("command", nargs=argparse.REMAINDER, help="-- CMD [ARGS...]")
    ns = p.parse_args(argv)
    cmd = ns.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        p.error("no command given")
    res = shadow_exec(
        cmd,
        stop_time=ns.stop_time,
        seed=ns.seed,
        data_directory=ns.preserve,
    )
    sys.stdout.write(res.stdout)
    return res.exit_code or 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
