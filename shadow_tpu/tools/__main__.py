"""``python -m shadow_tpu.tools [options] -- CMD [ARGS...]`` — shadow-exec."""

import sys

from .exec import main

sys.exit(main())
