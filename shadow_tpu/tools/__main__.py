"""``python -m shadow_tpu.tools [options] -- CMD [ARGS...]`` — shadow-exec,
plus ``python -m shadow_tpu.tools checkpoint-inspect <ckpt> [...]`` — the
STCKPT1 checkpoint validator (docs/robustness.md)."""

import sys

if len(sys.argv) > 1 and sys.argv[1] == "checkpoint-inspect":
    from ..engine.checkpoint import inspect_main

    sys.exit(inspect_main(sys.argv[2:]))

from .exec import main

sys.exit(main())
