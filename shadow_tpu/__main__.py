"""CLI entry point: ``python -m shadow_tpu [options] <config.yaml>``.

Mirrors the reference's CLI layering (src/main/core/configuration.rs:52
CliOptions over src/main/shadow.rs:480): a YAML config file (or ``-`` for
stdin, as the reference supports) with CLI flags merged on top, plus
``--show-config`` to print the merged result and exit.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
import sys

import shadow_tpu


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="shadow_tpu",
        description="TPU-native discrete-event network simulator "
        "(Shadow-capability rebuild)",
    )
    p.add_argument("config", help="YAML simulation config, or '-' for stdin")
    p.add_argument("--version", action="version", version=shadow_tpu.__version__)
    p.add_argument(
        "--show-config", action="store_true", help="print merged config and exit"
    )
    # common flags with dedicated spellings (the reference's CliOptions)
    flag_map = {
        "--seed": "general.seed",
        "--stop-time": "general.stop_time",
        "--bootstrap-end-time": "general.bootstrap_end_time",
        "--parallelism": "general.parallelism",
        "--data-directory": "general.data_directory",
        "--log-level": "general.log_level",
        "--heartbeat-interval": "general.heartbeat_interval",
        "--network-backend": "experimental.network_backend",
        "--runahead": "experimental.runahead",
        "--tpu-mesh-shape": "experimental.tpu_mesh_shape",
        "--resume": "experimental.resume_from",
        "--checkpoint-every-windows": "experimental.checkpoint_every_windows",
        "--checkpoint-dir": "experimental.checkpoint_dir",
    }
    for flag, key in flag_map.items():
        p.add_argument(flag, dest=key, default=None, metavar="V")
    p.add_argument(
        "--progress", action="store_true", help="log heartbeat progress lines"
    )
    p.add_argument(
        "--run-control",
        action="store_true",
        help="interactive pause/step/restart console on stdin "
        "(p / c / cN / n / s / s:<pid> / r / rN at window boundaries)",
    )
    p.add_argument(
        "--perf-logging",
        action="store_true",
        help="print [window-agg]/[host-exec-agg] parallelism telemetry",
    )
    p.add_argument(
        "--obs-metrics",
        action="store_true",
        help="record per-phase wall metrics and write a METRICS_*.json "
        "run report (shadow_tpu/obs/, docs/observability.md)",
    )
    p.add_argument(
        "--obs-trace",
        action="store_true",
        help="record phase spans and export a Chrome-trace/Perfetto JSON "
        "(implies --obs-metrics)",
    )
    p.add_argument(
        "--netobs",
        action="store_true",
        help="record per-host network telemetry (sent/delivered/bytes, "
        "drop-cause accounting, burst-window histogram) and write a "
        "NETOBS_*.json run report (docs/observability.md)",
    )
    p.add_argument(
        "--flowtrace",
        action="store_true",
        help="record per-flow packet-lifecycle events (send, bucket "
        "wait, queue-enter, drop-with-cause, retransmit, delivery) and "
        "write a FLOWS_*.json run report with burst attribution "
        "(docs/observability.md)",
    )
    p.add_argument(
        "--obs-turns",
        action="store_true",
        help="record the device-turn ledger (turn-cause accounting + "
        "fusable-run-length measurement) and write a TURNS_*.json run "
        "report (docs/observability.md)",
    )
    p.add_argument(
        "--set",
        dest="overrides",
        action="append",
        default=[],
        metavar="SECTION.FIELD=VALUE",
        help="generic dotted-key config override (repeatable)",
    )
    p.add_argument(
        "--event-log",
        action="store_true",
        help="write the canonical sorted event log (determinism-diff artifact)",
    )
    p.add_argument(
        "--determinism-check",
        action="store_true",
        help="run the simulation twice and fail unless both runs produce "
        "bit-identical event orderings and counters (the reference's "
        "determinism test, src/test/determinism/, as a CLI mode)",
    )
    return p


def parse_overrides(ns: argparse.Namespace) -> dict[str, object]:
    overrides: dict[str, object] = {}
    for key, val in vars(ns).items():
        if "." in key and val is not None:
            overrides[key] = val
    for item in ns.overrides:
        key, sep, val = item.partition("=")
        if not sep:
            raise SystemExit(f"--set expects SECTION.FIELD=VALUE, got {item!r}")
        overrides[key] = val
    return overrides


def main(argv: list[str] | None = None) -> int:
    from shadow_tpu.config.options import ConfigError, ConfigOptions
    from shadow_tpu.engine.sim import Simulation

    ns = build_parser().parse_args(argv)
    try:
        if ns.config == "-":
            cfg = ConfigOptions.from_yaml(sys.stdin.read())
        else:
            cfg = ConfigOptions.from_yaml_file(ns.config)
        overrides = parse_overrides(ns)
        if ns.run_control:
            overrides["experimental.run_control"] = True
        if ns.perf_logging:
            overrides["experimental.perf_logging"] = True
        if ns.obs_metrics:
            overrides["experimental.obs_metrics"] = True
        if ns.obs_trace:
            overrides["experimental.obs_trace"] = True
        if ns.netobs:
            overrides["experimental.netobs"] = True
        if ns.flowtrace:
            overrides["experimental.flowtrace"] = True
        if ns.obs_turns:
            overrides["experimental.obs_turns"] = True
        cfg.apply_overrides(overrides)
        cfg.validate()
    except (ConfigError, OSError, KeyError) as e:
        print(f"config error: {e}", file=sys.stderr)
        return 2

    # async buffered logging (the reference's logger crate: records are
    # queued by the emitting thread, formatted+written by a listener
    # thread, each line prefixed with the simulated clock)
    from shadow_tpu.utils.shadow_log import install_async_logging

    install_async_logging(
        level=getattr(logging, cfg.general.log_level.upper(), logging.INFO),
        stream=sys.stderr,
    )
    if ns.show_config:
        print(json.dumps(dataclasses.asdict(cfg), indent=2, default=str))
        return 0

    if ns.determinism_check:
        from shadow_tpu.engine.determinism import determinism_check

        try:
            report = determinism_check(cfg)
        except Exception as e:
            print(f"simulation failed: {e}", file=sys.stderr)
            return 1
        print(report.describe(), file=sys.stderr)
        return 0 if report.identical else 1

    from shadow_tpu.engine.checkpoint import GracefulShutdown

    sim = Simulation(cfg)
    try:
        result = sim.run()
    except GracefulShutdown as g:
        # SIGINT/SIGTERM: the run stopped cleanly at a window boundary
        # (final checkpoint written, artifacts flushed, workers reaped);
        # exit 75 (EX_TEMPFAIL) marks the run as resumable
        print(
            f"graceful shutdown (signal {g.signum}): resume with "
            "--resume <checkpoint>",
            file=sys.stderr,
        )
        return GracefulShutdown.EXIT_CODE
    except Exception as e:  # surface backend errors with a nonzero exit
        print(f"simulation failed: {e}", file=sys.stderr)
        return 1
    if ns.event_log:
        path = sim.write_event_log(result)
        print(f"event log: {path}", file=sys.stderr)
    if result.process_errors:
        for err in result.process_errors:
            print(f"process error: {err}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
