from .mesh import (
    HOST_AXIS,
    make_mesh,
    make_sharded_round_fn,
    make_sharded_run_fn,
    shard_state,
    state_shardings,
)

__all__ = [
    "HOST_AXIS",
    "make_mesh",
    "make_sharded_round_fn",
    "make_sharded_run_fn",
    "shard_state",
    "state_shardings",
]
