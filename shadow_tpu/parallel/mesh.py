"""Device-mesh sharding of host lanes.

The reference scales by spreading *hosts* over worker threads with work
stealing (scheduler crate, thread_per_core.rs:17-50); the cross-host packet
push is a mutex-guarded queue insert (worker.rs:603-615).  The TPU-native
equivalent: shard the lane axis of the batched simulation state over a
``jax.sharding.Mesh`` axis (``hosts``), keep the routing tables replicated,
and let XLA turn the cross-lane event exchange (the sort → rank → scatter in
``lanes._append_events``) into ICI collectives.  Host-level data parallelism
becomes SPMD data parallelism; the event exchange is the all-to-all.

Determinism: the sharded program computes the same integer arithmetic and
the same key sorts as the single-device one, so results are bit-identical
regardless of mesh shape (tests/test_parallel.py diffs the event logs).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..backend import lanes

HOST_AXIS = "hosts"

# LaneState fields that are not per-lane arrays and stay replicated.
# The stream matrices are COMPACTED per flow ([S, F], flow order), not
# per lane: S is a few hundred rows, so they replicate — XLA inserts the
# collectives for the lane-indexed gathers/scatters at the tier boundary
_REPLICATED_FIELDS = frozenset(
    ("log", "log_count", "log_lost", "rounds", "iters", "now_we_hi", "now_we_lo",
     "min_used_lat", "stream",
     # netobs scalars/histogram (the sharded driver runs netobs-off —
     # engine/sim.py gates it — but the sharding pytree stays total)
     "nb_hist", "nb_win")
)


def make_mesh(n_devices: Optional[int] = None, axis: str = HOST_AXIS) -> Mesh:
    """1-D mesh over the first ``n_devices`` devices (default: all)."""
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(f"need {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def state_shardings(mesh: Mesh, axis: str = HOST_AXIS) -> lanes.LaneState:
    """A LaneState-shaped pytree of NamedShardings: per-lane arrays split on
    the lane axis, the event log and scalars replicated."""
    lane = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())
    return lanes.LaneState(
        **{
            f: (repl if f in _REPLICATED_FIELDS else lane)
            for f in lanes.LaneState._fields
        }
    )


def shard_state(
    s: lanes.LaneState, mesh: Mesh, axis: str = HOST_AXIS
) -> lanes.LaneState:
    n_lanes = s.q_thi.shape[0]
    if n_lanes % mesh.devices.size:
        raise ValueError(
            f"n_lanes={n_lanes} not divisible by mesh size {mesh.devices.size}"
        )
    return jax.device_put(s, state_shardings(mesh, axis))


def make_sharded_round_fn(
    p: lanes.LaneParams, tb: lanes.LaneTables, mesh: Mesh, axis: str = HOST_AXIS
):
    """Jitted one-round advance, lane axis sharded over ``mesh``."""
    sh = state_shardings(mesh, axis)
    return jax.jit(
        lanes._build_round(p, tb),
        in_shardings=(sh,),
        out_shardings=(sh, NamedSharding(mesh, P())),
    )


def make_sharded_run_fn(
    p: lanes.LaneParams, tb: lanes.LaneTables, mesh: Mesh, axis: str = HOST_AXIS
):
    """Jitted full-simulation run (while_loop over rounds), sharded."""
    sh = state_shardings(mesh, axis)
    return jax.jit(
        lanes._build_full_run(p, tb), in_shardings=(sh,), out_shardings=sh
    )
