"""Device-mesh sharding of host lanes — the multi-chip data plane.

The reference scales by spreading *hosts* over worker threads with work
stealing (scheduler crate, thread_per_core.rs:17-50); the cross-host packet
push is a mutex-guarded queue insert (worker.rs:603-615).  The TPU-native
equivalent: shard the lane axis of the batched simulation state over a
``jax.sharding.Mesh`` axis (``hosts``), keep the routing tables replicated,
and let XLA turn the cross-lane event exchange (the sort → rank → scatter in
``lanes._append_events``) into ICI collectives.  Host-level data parallelism
becomes SPMD data parallelism; the event exchange is the all-to-all.

Sharding law (docs/multichip.md):

* every ``[N]``- or ``[N, C]``-leading LaneState leaf (queues, bucket and
  CoDel state, per-lane counters, the netobs per-host counter block) is
  split on the lane axis — ``NamedSharding(mesh, P("hosts"))``;
* everything else replicates — scalars, the event log (one device-global
  append cursor), the compacted ``[S, F]`` stream tier, the ``[24]`` netobs
  window histogram (shard-then-reduce: per-shard partial sums all-reduce
  into the replicated array), the hybrid egress block, and the flowtrace
  ring;
* the classification is EXHAUSTIVE by construction: ``state_shardings``
  asserts every ``LaneState._fields`` entry is classified exactly once, so
  a future field cannot silently pick up the wrong sharding
  (tests/test_multichip.py plants a fake field to pin this).

Determinism: the sharded program computes the same integer arithmetic and
the same key sorts as the single-device one, so results are bit-identical
regardless of mesh shape (tests/test_parallel.py + test_multichip.py diff
the event logs and NETOBS artifacts at 1/2/4/8 devices).

Fallback semantics: ``negotiate_devices`` never raises — a request that
exceeds the available device count, or that does not divide the lane
count, steps down (with a warning) toward the largest usable mesh, and a
1-device mesh is bypassed entirely by the callers, so every existing
single-device driver keeps working unchanged on any box.
"""

from __future__ import annotations

import functools
import logging
from typing import Iterable, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..backend import lanes

log = logging.getLogger("shadow_tpu.parallel")

HOST_AXIS = "hosts"
SCENARIO_AXIS = "scenarios"

# LaneState fields split on the lane axis: per-lane [N]/[N, C] arrays.
LANE_FIELDS = frozenset((
    "q_thi", "q_tlo", "q_auxh", "q_auxl", "q_size", "q_phi", "q_plo",
    "send_seq", "local_seq", "app_draws",
    "up_tokens", "up_nr_hi", "up_nr_lo", "up_ld_hi", "up_ld_lo",
    "dn_tokens", "dn_nr_hi", "dn_nr_lo", "dn_ld_hi", "dn_ld_lo",
    "cd_fat_hi", "cd_fat_lo", "cd_dnext_hi", "cd_dnext_lo",
    "cd_drop_count", "cd_dropping",
    "m_sent", "m_peer_offset", "n_delivered", "n_loss", "n_codel",
    "n_queue", "recv_bytes", "n_sends", "n_hops",
    # netobs per-host counter block (PR 10): [N] int32 counters travel
    # with their lanes; collect() gathers them for the oracle diff
    "nb_txb", "nb_rxb", "nb_thr", "nb_shed",
))

# LaneState fields that replicate.  The stream matrices are COMPACTED per
# flow ([S, F], flow order), not per lane: S is a few hundred rows, so
# they replicate — XLA inserts the collectives for the lane-indexed
# gathers/scatters at the tier boundary.  The netobs [24] histogram and
# the hybrid egress block are device-global append targets written from
# sharded lanes: GSPMD lowers the scatter-adds as shard-then-reduce,
# which is exact for the integer counters they carry.
REPLICATED_FIELDS = frozenset((
    "log", "log_count", "log_lost", "rounds", "iters",
    "now_we_hi", "now_we_lo", "min_used_lat", "stream",
    "egress", "egress_count", "egress_lost",
    "egress_min_hi", "egress_min_lo",
    "nb_hist", "nb_win",
    "fl_buf", "fl_count", "fl_lost",
))


def check_classification(fields: Optional[Iterable[str]] = None) -> None:
    """Assert LANE_FIELDS/REPLICATED_FIELDS form an exact partition of
    ``fields`` (default: the live ``LaneState._fields``).  Raises
    AssertionError naming the offending fields — a new LaneState field
    MUST be classified here before any sharded driver can run."""
    fset = set(lanes.LaneState._fields if fields is None else fields)
    both = LANE_FIELDS & REPLICATED_FIELDS
    if both:
        raise AssertionError(
            f"LaneState fields classified twice in parallel/mesh.py: "
            f"{sorted(both)}"
        )
    missing = fset - LANE_FIELDS - REPLICATED_FIELDS
    if missing:
        raise AssertionError(
            "unclassified LaneState fields (add them to LANE_FIELDS or "
            f"REPLICATED_FIELDS in parallel/mesh.py): {sorted(missing)}"
        )
    stale = (LANE_FIELDS | REPLICATED_FIELDS) - fset
    if stale:
        raise AssertionError(
            "parallel/mesh.py classifies fields LaneState no longer has: "
            f"{sorted(stale)}"
        )


def negotiate_devices(
    requested: Optional[int],
    n_lanes: int,
    available: Optional[int] = None,
) -> int:
    """The transparent-fallback law: the largest usable device count.

    Picks the biggest ``d <= min(requested, available)`` with
    ``n_lanes % d == 0`` — never raises, warns on every step-down — so a
    config asking for 8 chips runs correctly (just narrower) on a
    1-device box or with an odd host count.  ``requested`` of None/0
    means "all available"."""
    avail = len(jax.devices()) if available is None else int(available)
    want = avail if not requested or requested <= 0 else int(requested)
    d = max(1, min(want, avail, max(n_lanes, 1)))
    if d < want:
        log.warning(
            "mesh: %d device(s) requested, %d usable (available=%d, "
            "n_lanes=%d) — falling back", want, d, avail, n_lanes,
        )
    while n_lanes % d:
        d -= 1
    if d < min(want, avail) and n_lanes % min(want, avail):
        log.warning(
            "mesh: n_lanes=%d not divisible by %d device(s); using %d",
            n_lanes, min(want, avail), d,
        )
    return d


def negotiate_from_config(cfg, n_lanes: int) -> int:
    """Device count for a config: ``experimental.mesh_devices`` (0 = no
    mesh, N = shard over up to N devices), with the 1-D
    ``experimental.tpu_mesh_shape`` tuple as an alias, negotiated against
    the available device count and the lane count.  Returns 1 when no
    multi-device mesh applies (the callers skip attach entirely)."""
    exp = cfg.experimental
    requested = int(getattr(exp, "mesh_devices", 0) or 0)
    if requested <= 0:
        shape = getattr(exp, "tpu_mesh_shape", None)
        if shape is not None and len(shape) == 1:
            requested = int(shape[0])
    if requested <= 1:
        return 1
    return negotiate_devices(requested, n_lanes)


def make_mesh(n_devices: Optional[int] = None, axis: str = HOST_AXIS) -> Mesh:
    """1-D mesh over the first ``n_devices`` devices (default: all)."""
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(f"need {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def state_shardings(mesh: Mesh, axis: str = HOST_AXIS) -> lanes.LaneState:
    """A LaneState-shaped pytree of NamedShardings: per-lane arrays split
    on the lane axis, the event log and scalars replicated.  Exhaustive
    over the live field list (see check_classification)."""
    check_classification()
    lane = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())
    return lanes.LaneState(
        **{
            f: (repl if f in REPLICATED_FIELDS else lane)
            for f in lanes.LaneState._fields
        }
    )


def shard_state(
    s: lanes.LaneState, mesh: Mesh, axis: str = HOST_AXIS
) -> lanes.LaneState:
    n_lanes = s.q_thi.shape[0]
    if n_lanes % mesh.devices.size:
        raise ValueError(
            f"n_lanes={n_lanes} not divisible by mesh size {mesh.devices.size}"
        )
    return jax.device_put(s, state_shardings(mesh, axis))


def _spmd_entry(fn):
    """Wrap a jitted sharded entry point so ``lanes._force_unroll`` is
    live whenever it runs: jit traces on first CALL, and the traced body
    must take the unrolled slot walk (its emits stack [K, N] on the lane
    axis) — GSPMD cannot partition lax.scan's stacked-output updates on
    the lane-sharded axis under x64 (s64 index vs s32 shard-offset
    compare, rejected by the HLO verifier).  The per-flow stream walks
    keep their scan form — their stacks replicate (see
    ``lanes.scan_or_unroll``).  Post-trace calls pay one bool flip."""

    @functools.wraps(fn)
    def wrapped(*args):
        with lanes._force_unroll():
            return fn(*args)

    def lower(*args, **kwargs):
        # AOT path (precompile benches): lowering traces too
        with lanes._force_unroll():
            return fn.lower(*args, **kwargs)

    wrapped.lower = lower
    return wrapped


def _donate(donate: Optional[bool]) -> tuple:
    """Sharded-state donation: the free-run consumes its input state, so
    donating halves peak device memory at scale.  Default on everywhere
    but the CPU backend, where XLA cannot alias the buffers and every
    call would warn about unusable donations."""
    if donate is None:
        donate = jax.default_backend() != "cpu"
    return (0,) if donate else ()


def make_sharded_round_fn(
    p: lanes.LaneParams, tb: lanes.LaneTables, mesh: Mesh, axis: str = HOST_AXIS
):
    """Jitted one-round advance, lane axis sharded over ``mesh`` (the
    step driver's kernel: pausable, host-visible state per boundary — no
    donation, checkpointing re-reads the input state)."""
    sh = state_shardings(mesh, axis)
    return _spmd_entry(jax.jit(
        lanes._build_round(p, tb),
        in_shardings=(sh,),
        out_shardings=(sh, NamedSharding(mesh, P())),
    ))


def make_sharded_run_fn(
    p: lanes.LaneParams,
    tb: lanes.LaneTables,
    mesh: Mesh,
    axis: str = HOST_AXIS,
    donate: Optional[bool] = None,
):
    """Jitted full-simulation run (while_loop over rounds), sharded."""
    sh = state_shardings(mesh, axis)
    return _spmd_entry(jax.jit(
        lanes._build_full_run(p, tb),
        in_shardings=(sh,),
        out_shardings=sh,
        donate_argnums=_donate(donate),
    ))


def make_sharded_hybrid_fns(
    p: lanes.LaneParams,
    tb: lanes.LaneTables,
    mesh: Mesh,
    fuse_k: int = 1,
    ext_slots: int = 0,
    axis: str = HOST_AXIS,
):
    """The hybrid backend's device entry points compiled under ``mesh``:
    ``(turn_fn, inject_fn)`` with the lane state sharded on the host axis
    and everything at the host<->device boundary — the injection block,
    the external-schedule scalars, the packed scalar readback, and the
    (replicated) egress buffer — placed whole on every shard, so the
    ≤2-transfers-per-turn law and the sync_stats byte accounting are
    unchanged by sharding (tests/test_multichip.py pins the counts).

    No donation: the fused walk's rollback re-dispatches from the
    pre-turn state, which must therefore survive the call."""
    sh = state_shardings(mesh, axis)
    repl = NamedSharding(mesh, P())

    def _inject(s: lanes.LaneState, inj):
        return lanes._inject_merge(p, tb, s, inj)

    inject_fn = _spmd_entry(jax.jit(
        _inject, in_shardings=(sh, repl), out_shardings=sh
    ))
    if fuse_k >= 2:
        turn_fn = _spmd_entry(jax.jit(
            lanes._build_hybrid_fused_run(p, tb, fuse_k, ext_slots),
            in_shardings=(sh, repl, repl, repl, repl, repl),
            out_shardings=(sh, repl),
        ))
    else:
        turn_fn = _spmd_entry(jax.jit(
            lanes._build_hybrid_run(p, tb),
            in_shardings=(sh, repl, repl, repl, repl),
            out_shardings=(sh, repl),
        ))
    return turn_fn, inject_fn


def scenario_sharding(mesh: Mesh, axis: str = SCENARIO_AXIS) -> NamedSharding:
    """The sweep composition (ROADMAP item 4 × item 2): when
    hosts-per-scenario is small, shard the STACKED scenario axis instead
    of the host axis — every stacked sweep leaf (state, tables, stop
    bounds) leads with [S], so one NamedSharding broadcast over the
    pytrees splits whole scenarios across devices."""
    return NamedSharding(mesh, P(axis))
