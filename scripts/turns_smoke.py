#!/usr/bin/env python
"""End-to-end device-turn-ledger smoke (``make turns-smoke``, in ``make
gate``).

A gate-scale MANAGED hybrid run (``managed_relay_chains_gate``: 16
managed OS processes over 60 lane hosts, 2-worker syscall servicing, CPU
JAX platform — no TPU time needed) with the ledger on, asserting:

1. a valid ``TURNS_*.json`` artifact (schema keys, per-turn rows);
2. the cause conservation law ``turns == sum(cause_counts)`` and
   ``len(rows) + rows_dropped == turns``;
3. blocking causes actually attributed (host_window/injection > 0 on a
   managed workload) and the ledger row totals agreeing with the
   engine-independent facts (inject rows == staged sends carried);
4. a NON-EMPTY fusable-run histogram — the run must contain at least one
   legal free-run (ROADMAP item 1a's evidence), which the terminal
   device drain guarantees on this scenario.

Exit 0 = all assertions hold; any failure raises (nonzero exit).
"""

from __future__ import annotations

import json
import shutil
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def main() -> int:
    from shadow_tpu.config.scenarios import managed_relay_chains_gate
    from shadow_tpu.engine.sim import Simulation
    from shadow_tpu.obs import turns as tmod

    tmp = Path(tempfile.mkdtemp(prefix="shadow_turns_smoke_"))
    try:
        cfg = managed_relay_chains_gate(
            tmp / "data", hybrid_workers=2, sim_seconds=4
        )
        cfg.experimental.obs_turns = True
        sim = Simulation(cfg)
        result = sim.run(write_data=False)
        assert not result.process_errors, result.process_errors

        arts = sorted((tmp / "data").glob("TURNS_*.json"))
        assert arts, f"no TURNS_*.json in {tmp / 'data'}"
        rep = json.loads(arts[0].read_text())
        for key in ("schema", "run_id", "turns", "cause_counts",
                    "host_rounds", "fusable", "rows", "rows_dropped",
                    "kfusion_headroom", "participation"):
            assert key in rep, f"TURNS report missing {key!r}"

        err = tmod.check_conservation(rep)
        assert err is None, f"conservation violated: {err}"
        assert rep["turns"] > 0, "no device turns recorded"
        causes = rep["cause_counts"]
        assert causes["host_window"] + causes["injection"] > 0, (
            f"no blocking causes on a managed workload: {causes}"
        )
        # ledger vs sync_stats: the same turns, rows, zero extra
        # transfers (the ledger derives from host-held values)
        sync = sim.engine.sync_stats
        assert rep["turns"] == sync["device_turns"], (
            rep["turns"], sync["device_turns"],
        )
        assert rep["inject_rows_total"] == sync["inject_rows"]
        assert rep["egress_rows_total"] == sync["egress_rows"]

        fus = rep["fusable"]
        assert sum(fus["buckets"]) == fus["runs"], "fusable hist drift"
        assert fus["runs"] > 0, (
            "empty fusable-run histogram: the run recorded no legal "
            f"free-run at all (causes: {causes})"
        )
        print(
            f"turns-smoke OK: {rep['turns']} turns "
            + " ".join(f"{k}={v}" for k, v in sorted(causes.items()) if v)
            + f"; fusable runs {fus['runs']} covering "
            f"{fus['windows_total']} window(s), p50={fus['p50']} "
            f"max={fus['max']}; headroom {rep['kfusion_headroom']}x "
            "(conservation holds)"
        )
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
