#!/usr/bin/env python
"""Fleet-sweep driver: batch S whole simulations into ONE compiled
vmapped lane kernel and export the ``SWEEP_<name>-S<k>.json`` artifact
(docs/sweep.md).

The sweep axes come from, in precedence order:

1. ``--spec SPEC.yaml`` (or ``experimental.sweep_spec`` in the config):
   a sweep-spec document with ``seeds`` / ``faults`` / ``overrides``
   axes, expanded as a Cartesian product;
2. ``--sweep-size N`` (or ``experimental.sweep_size``): the seed-grid
   shorthand — seeds ``base .. base + N - 1``, no other axes.

Worked example — the partition/heal fault demo swept over a 4-seed
grid, every scenario batched into one kernel on the lane backend:

    JAX_PLATFORMS=cpu python scripts/sweep.py examples/partition-heal.yaml \\
        --sweep-size 4 --backend tpu --data-directory /tmp/sweep.data

Prints one JSON line with the batch wall time and the headline
``scenarios_per_hour`` throughput key (whole-batch wall divided into S
scenario-completions, scaled to an hour), and writes the SWEEP artifact
through the Recorder lifecycle into ``--data-directory``.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("config", help="base scenario config (YAML)")
    ap.add_argument(
        "--spec",
        help="sweep spec YAML (axes: seeds/faults/overrides); "
        "defaults to experimental.sweep_spec from the config",
    )
    ap.add_argument(
        "--sweep-size", type=int, default=None,
        help="seed-grid shorthand: N seeds from general.seed upward; "
        "defaults to experimental.sweep_size from the config",
    )
    ap.add_argument("--name", default=None, help="sweep/artifact name")
    ap.add_argument(
        "--backend", choices=("cpu", "tpu"), default=None,
        help="override experimental.network_backend for the whole fleet "
        "(tpu = the batched lane kernel; cpu = the serial oracle arm)",
    )
    ap.add_argument(
        "--data-directory", default=None,
        help="artifact output dir (SWEEP_*.json via the Recorder)",
    )
    args = ap.parse_args(argv)

    from shadow_tpu.config.options import ConfigOptions
    from shadow_tpu.obs.recorder import Recorder
    from shadow_tpu.sweep import (
        SweepEngine,
        SweepSpec,
        build_report,
        expand_variants,
    )
    from shadow_tpu.sweep.report import artifact_name

    base = ConfigOptions.from_yaml_file(args.config)
    if args.backend is not None:
        base.experimental.network_backend = args.backend

    spec_path = args.spec or base.experimental.sweep_spec
    if spec_path is not None:
        spec = SweepSpec.from_yaml(Path(spec_path).read_text())
    else:
        size = (
            args.sweep_size
            if args.sweep_size is not None
            else base.experimental.sweep_size
        )
        if size < 1:
            ap.error(
                "no sweep axes: pass --spec/--sweep-size or set "
                "experimental.sweep_spec/sweep_size in the config"
            )
        spec = SweepSpec.seed_grid(base.general.seed, size)
    if args.name is not None:
        spec.name = args.name

    variants = expand_variants(base, spec)
    sweep = SweepEngine(variants)
    results = sweep.run()
    report = build_report(sweep, results, name=spec.name)

    if sweep.backend == "cpu":
        wall = sweep._cpu_wall
    else:
        wall = results[0].wall_seconds
    line = {
        "sweep": spec.name,
        "size": sweep.size,
        "backend": sweep.backend,
        "traces": sweep.traces,
        "wall_seconds": round(wall, 3),
        "scenarios_per_hour": round(sweep.size * 3600.0 / wall, 1),
        "sim_seconds_each": variants[0].cfg.general.stop_time / 1_000_000_000,
    }

    if args.data_directory is not None:
        rec = Recorder(
            run_id=f"sweep_{spec.name}", out_dir=args.data_directory
        )
        rec.add_artifact(artifact_name(report), report)
        fin = rec.finalize(extra={"sweep": line})
        line["artifacts"] = fin.get("artifact_paths", [])

    print(json.dumps(line, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
