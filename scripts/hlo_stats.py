"""Dump op-category counts of the compiled bench while-body (static
analysis — reliable regardless of the shared chip's timing noise).

Usage: python scripts/hlo_stats.py [hosts] [--text out.txt]
"""

import re
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import shadow_tpu  # noqa: F401
from shadow_tpu.backend import lanes
from shadow_tpu.backend.tpu_engine import TpuEngine
from shadow_tpu.config.presets import (
    flagship_mesh_config,
    mixed_flagship_config,
)


def main() -> None:
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    n = int(args[0]) if args else 10000
    if "--mixed" in sys.argv:
        cfg = mixed_flagship_config(n, sim_seconds=5)
    else:
        cfg = flagship_mesh_config(
            n, sim_seconds=5, queue_capacity=16, pops_per_round=2
        )
    eng = TpuEngine(cfg, log_capacity=0)
    run_fn = lanes.make_run_fn(eng.params, eng.tables)
    state = eng.initial_state()
    compiled = run_fn.lower(state).compile()
    txt = compiled.as_text()
    if "--text" in sys.argv:
        out = sys.argv[sys.argv.index("--text") + 1]
        with open(out, "w") as f:
            f.write(txt)
        print(f"wrote {len(txt)} bytes to {out}")

    # count ops inside the while body computation
    lines = txt.splitlines()
    print(f"total HLO lines: {len(lines)}")
    cat = {}
    for ln in lines:
        m = re.match(r"\s*(?:ROOT )?%?[\w.\-]+ = \S+ ([a-z0-9\-]+)\(", ln)
        if not m:
            continue
        op = m.group(1)
        cat[op] = cat.get(op, 0) + 1
    for op, cnt in sorted(cat.items(), key=lambda kv: -kv[1]):
        print(f"{cnt:6d}  {op}")
    # fusion/sort/copy summary
    for key in ("fusion", "sort", "copy", "custom-call", "while"):
        print(f"summary {key}: {cat.get(key, 0)}")


if __name__ == "__main__":
    main()
