#!/usr/bin/env python
"""End-to-end k-window fusion smoke (``make fusion-smoke``, in ``make
gate``) — ISSUE 13's acceptance gate at CI scale.

The SAME gate-scale managed hybrid run as ``make turns-smoke``
(``managed_relay_chains_gate``: 16 managed OS processes over 60 lane
hosts, 2-worker syscall servicing, CPU JAX platform), with k-window
fusion at its default depth, asserting:

1. blocking device turns dropped **>= 2x** vs the PR 11 pinned unfused
   baseline (651 turns at this scale -> <= 325), measured by the turns
   ledger;
2. windows conservation: the participating windows the ledger rows
   cover, plus the remaining host-only rounds, equal the pinned PR 11
   total (651 turns + 127 host-only rounds = 778) — the fusion is a
   pure scheduling change: the SAME windows ran, in fewer dispatches
   (fused dispatches absorb both would-be turns and would-be host-only
   rounds, so the covered total exceeds the turn baseline alone);
3. the fused-turn conservation law ``turns + turns_saved ==
   implied_unfused`` and the classic ``turns == sum(cause_counts)``
   law, on the exported TURNS artifact;
4. the run is byte-identical run-twice with fusion + async dispatch on
   (the determinism contract of docs/hybrid.md).

Exit 0 = all assertions hold; any failure raises (nonzero exit).
"""

from __future__ import annotations

import json
import shutil
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

#: PR 11's measured unfused counts for this exact scenario/scale
#: (make turns-smoke history; re-pin if the scenario changes)
UNFUSED_BASELINE = 651       # blocking device turns
UNFUSED_HOST_ROUNDS = 127    # host-only rounds
TOTAL_WINDOWS = UNFUSED_BASELINE + UNFUSED_HOST_ROUNDS


def _run(tmp: Path):
    from shadow_tpu.config.scenarios import managed_relay_chains_gate
    from shadow_tpu.engine.sim import Simulation

    cfg = managed_relay_chains_gate(
        tmp / "data", hybrid_workers=2, sim_seconds=4
    )
    cfg.experimental.obs_turns = True
    sim = Simulation(cfg)
    result = sim.run(write_data=False)
    assert not result.process_errors, result.process_errors
    arts = sorted((tmp / "data").glob("TURNS_*.json"))
    assert arts, f"no TURNS_*.json in {tmp / 'data'}"
    return json.loads(arts[0].read_text()), arts[0].read_bytes(), sim


def main() -> int:
    from shadow_tpu.obs import turns as tmod

    tmp = Path(tempfile.mkdtemp(prefix="shadow_fusion_smoke_"))
    try:
        rep, raw, sim = _run(tmp / "a")
        err = tmod.check_conservation(rep)
        assert err is None, f"conservation violated: {err}"

        fused = rep["fused"]
        implied = fused["implied_unfused_turns"]
        # non-tautological side of the conservation law: recompute the
        # implied-unfused total from the artifact's cause rows (the
        # aggregate turns + turns_saved == implied holds by construction)
        implied_rows = sum(
            max(r[3], 1) for r in rep["rows"] if r[0] != "rollback"
        )
        assert rep["turns"] + fused["turns_saved"] == implied_rows == implied, (
            rep["turns"], fused["turns_saved"], implied_rows, implied,
        )
        assert implied + rep["host_rounds"] == TOTAL_WINDOWS, (
            f"windows conservation broken: {implied} covered + "
            f"{rep['host_rounds']} host-only != pinned {TOTAL_WINDOWS}: "
            "the fusion changed WHICH windows ran, not just how many "
            "dispatches carried them"
        )
        assert rep["turns"] * 2 <= UNFUSED_BASELINE, (
            f"fusion below the 2x acceptance bar: {rep['turns']} blocking "
            f"turns vs the {UNFUSED_BASELINE}-turn unfused baseline"
        )
        assert fused["turns"] > 0, "no fused dispatch recorded"
        sync = sim.engine.sync_stats
        assert rep["turns"] == sync["device_turns"], (
            rep["turns"], sync["device_turns"],
        )
        assert sync["turns_saved"] == fused["turns_saved"]

        # determinism: byte-identical TURNS artifact run-twice with
        # fusion + async dispatch on
        _rep2, raw2, _sim2 = _run(tmp / "b")
        assert raw == raw2, "TURNS artifact differs run-twice"

        print(
            f"fusion-smoke OK: {rep['turns']} blocking turns vs "
            f"{implied} unfused ({fused['achieved_fusion']}x collapse, "
            f">= 2x bar met); {fused['turns']} fused dispatches covering "
            f"{fused['windows_total']} windows, "
            f"{fused['rollbacks']} rollbacks, "
            f"async hits/misses "
            f"{sync['async_dispatch_hits']}/"
            f"{sync['async_dispatch_misses']}; run-twice byte-identical"
        )
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
