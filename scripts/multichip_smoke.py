#!/usr/bin/env python
"""End-to-end multi-chip smoke (``make multichip-smoke``, wired into
``make gate``).

Forces an 8-virtual-device CPU JAX backend (no TPU pod needed) and
certifies the sharded lane plane (docs/multichip.md):

1. **Device-count invariance, netobs on** — the phold facade run
   produces a bit-identical event log and byte-identical NETOBS
   artifact at 1, 2, 4, and 8 devices.
2. **Mixed-mesh invariance** — the mixed TCP/UDP flagship (stream tier
   + datagram mesh crossing it) is bit-identical at 1 vs 8 devices.
3. **Nonzero per-device work** — every shard of the 8-device phold
   run's per-lane send counters is nonzero: the mesh actually spreads
   the simulation, nobody idles.  (The mixed run's stream-pair sends
   ride the replicated stream tier, so its per-lane counters are the
   wrong probe for this.)
4. **Hybrid transfer invariance** — the managed hybrid run under a
   2-device mesh keeps every ``sync_stats`` transfer count and the
   event log unchanged (the host<->device boundary stays replicated).
5. **Columnar 100k startup** — the columnar factory builds a 100k-host
   engine + initial state in under 30 s (the classic per-host walk is
   the thing this path deletes).

Exit 0 = all assertions hold; any failure raises (nonzero exit).
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time
from pathlib import Path

# BEFORE jax import: 8 virtual CPU devices
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))
BUILD = REPO / "native" / "build"


def _phold_yaml(data_dir: Path, mesh_devices: int) -> str:
    return f"""
general: {{stop_time: 300ms, seed: 11, data_directory: {data_dir},
           heartbeat_interval: null}}
experimental: {{network_backend: tpu, netobs: true,
               tpu_events_per_round: 2, mesh_devices: {mesh_devices}}}
hosts:
  n:
    count: 8
    processes: [{{path: phold, args: --messages 3 --size 600}}]
"""


def _hybrid_yaml(data_dir: Path, mesh_devices: int) -> str:
    mesh = "\n".join(f"""
  zm{i:03d}:
    network_node_id: 0
    processes:
      - path: tgen-mesh
        args: --interval 50ms --size 600
        start_time: 0 s
""" for i in range(4))
    return f"""
general: {{stop_time: 1s, seed: 21, data_directory: {data_dir},
           heartbeat_interval: null}}
network: {{graph: {{type: 1_gbit_switch}}}}
experimental: {{network_backend: tpu, hybrid_workers: 1,
               mesh_devices: {mesh_devices}}}
hosts:
  cli:
    network_node_id: 0
    processes:
      - path: {BUILD / 'pingpong'}
        args: [client, 11.0.0.2, "9000", "3", "100"]
  srv:
    network_node_id: 0
    processes:
      - path: {BUILD / 'pingpong'}
        args: [server, "9000", "3"]
{mesh}
"""


def main() -> int:
    import jax
    import numpy as np

    from shadow_tpu import parallel
    from shadow_tpu.backend.tpu_engine import TpuEngine
    from shadow_tpu.config.columnar import columnar_mesh_config
    from shadow_tpu.config.options import ConfigOptions
    from shadow_tpu.config.presets import mixed_flagship_config
    from shadow_tpu.engine.sim import Simulation

    assert len(jax.devices()) >= 8, (
        f"expected 8 virtual devices, have {len(jax.devices())} "
        "(XLA_FLAGS must be set before jax import)"
    )
    tmp = Path(tempfile.mkdtemp(prefix="multichip-smoke-"))
    try:
        # -- 1. phold facade invariance at 1/2/4/8, netobs on -------------
        runs = {}
        for d in (0, 2, 4, 8):
            dd = tmp / f"phold{d}"
            cfg = ConfigOptions.from_yaml(_phold_yaml(dd, d))
            sim = Simulation(cfg)
            res = sim.run(write_data=False)
            arts = sorted(dd.glob("NETOBS_*.json"))
            assert len(arts) == 1, arts
            runs[d] = (res.log_tuples(), arts[0].read_bytes())
            want = d if d else 1
            got = sim.engine.mesh.devices.size if sim.engine.mesh else 1
            assert got == want, f"mesh size {got} != requested {want}"
        base_log, base_netobs = runs[0]
        assert base_log, "phold run produced an empty event log"
        assert json.loads(base_netobs)["totals"]["sent"] > 0
        for d in (2, 4, 8):
            assert runs[d][0] == base_log, f"event log diverges at {d} dev"
            assert runs[d][1] == base_netobs, f"NETOBS diverges at {d} dev"
        print("multichip-smoke: phold invariant at 1/2/4/8 devices (netobs on)")

        # -- 3. nonzero per-device work (phold: every lane sends) ---------
        ph = TpuEngine(
            ConfigOptions.from_yaml(_phold_yaml(tmp / "pholdw", 0))
        )
        ph.attach_mesh(parallel.make_mesh(8))
        run_fn = parallel.make_sharded_run_fn(ph.params, ph.tables, ph._mesh)
        final = jax.block_until_ready(
            run_fn(ph.place_state(ph.initial_state()))
        )
        per_shard = [
            int(np.asarray(sh.data).sum())
            for sh in final.n_sends.addressable_shards
        ]
        assert len(per_shard) == 8 and all(c > 0 for c in per_shard), (
            f"idle shard in per-device send counts: {per_shard}"
        )
        print(f"multichip-smoke: per-device sends all nonzero {per_shard}")

        # -- 2. mixed-mesh (stream tier + datagram mesh) invariance -------
        single = TpuEngine(mixed_flagship_config(8, sim_seconds=1))
        ref = single.run(mode="device")
        meshed = TpuEngine(mixed_flagship_config(8, sim_seconds=1))
        meshed.attach_mesh(parallel.make_mesh(8))
        got = meshed.run(mode="device")
        assert got.log_tuples() == ref.log_tuples(), (
            "mixed-mesh event log diverges under the 8-device mesh"
        )
        assert got.counters == ref.counters
        print("multichip-smoke: mixed mesh bit-identical at 8 devices")

        # -- 4. hybrid transfer invariance --------------------------------
        s0 = Simulation(ConfigOptions.from_yaml(_hybrid_yaml(tmp / "h0", 0)))
        r0 = s0.run(write_data=False)
        s2 = Simulation(ConfigOptions.from_yaml(_hybrid_yaml(tmp / "h2", 2)))
        r2 = s2.run(write_data=False)
        assert s2.engine.device.mesh is not None
        assert r2.log_tuples() == r0.log_tuples(), (
            "hybrid event log diverges under the mesh"
        )
        keys = ("device_turns", "inject_blocks", "inject_rows",
                "inject_bytes", "egress_reads", "egress_rows",
                "egress_bytes")
        a, b = dict(s0.engine.sync_stats), dict(s2.engine.sync_stats)
        for k in keys:
            assert a.get(k) == b.get(k), (
                f"hybrid sync_stats[{k}]: {a.get(k)} -> {b.get(k)} under mesh"
            )
        print("multichip-smoke: hybrid transfers unchanged under 2-device mesh")

        # -- 5. columnar 100k startup bound -------------------------------
        t0 = time.perf_counter()
        cfg = columnar_mesh_config(100_000, sim_seconds=1)
        eng = TpuEngine(cfg)
        eng.initial_state()
        dt = time.perf_counter() - t0
        assert dt < 30.0, f"100k-host columnar startup took {dt:.1f}s"
        print(f"multichip-smoke: 100k-host columnar startup in {dt:.1f}s")
        print("multichip-smoke: OK")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
