"""Device-utilization report: UTIL_r{N}.json (VERDICT r4 #10).

For the pure and mixed flagship meshes: wall time per while-iteration on
the real device, XLA cost-analysis flops / bytes per iteration, and the
achieved fraction of chip peak (compute and HBM bandwidth) — the ground
truth the per-round optimization commits cite.

Usage: python scripts/util_report.py [out.json]
Env: UTIL_HOSTS (10000), UTIL_SIM_S (5), UTIL_REPEATS (3)
"""

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import shadow_tpu  # noqa: F401
from shadow_tpu.backend.tpu_engine import TpuEngine
from shadow_tpu.config.presets import (
    flagship_mesh_config,
    mixed_flagship_config,
)

# TPU v5e (lite) public peaks; the report records the assumed values so a
# different chip just needs these constants adjusted
PEAK_BF16_FLOPS = 394e12
PEAK_HBM_BPS = 819e9


def calibrated_fraction(est: float, wall_per_iter: float,
                        peak: float) -> dict:
    """Fraction-of-peak from an XLA cost-analysis estimate, calibrated so
    the reported value can never exceed 1.0 (a physical impossibility).

    XLA's HloCostAnalysis counts the while body once but folds in
    prologue/epilogue work, and the peak constants are nominal — so a
    near-peak workload can produce a raw fraction slightly above 1.
    That over-peak reading means "at the ceiling", not "623x under it":
    the fraction clamps to 1.0 and the raw value is reported alongside
    so the calibration stays auditable (and monotone — adjacent
    measurements of the same workload stay comparable across the 1.0
    boundary, unlike re-dividing by the iteration count, which would
    collapse a 1.05 reading to ~0.002).
    """
    if not est or wall_per_iter <= 0 or peak <= 0:
        return {"frac": None, "raw_frac": None, "calibration": "no-data"}
    raw = est / wall_per_iter / peak
    if raw <= 1.0:
        frac, how = raw, "per_iter"
    else:
        frac, how = 1.0, "clamped"
    return {
        "frac": round(frac, 8),
        "raw_frac": round(raw, 8),
        "calibration": how,
    }

N = int(os.environ.get("UTIL_HOSTS", "10000"))
SIM_S = int(os.environ.get("UTIL_SIM_S", "5"))
REPEATS = int(os.environ.get("UTIL_REPEATS", "3"))
SALT = ((os.getpid() << 16) ^ int(time.time())) & 0x3FFFFFFF


def probe(tag: str, cfg) -> dict:
    import jax

    eng = TpuEngine(cfg, log_capacity=0)
    best = eng.run(mode="device", precompile=True, cache_salt=SALT + 1)
    for i in range(REPEATS - 1):
        r = eng.run(mode="device", cache_salt=SALT + 2 + i)
        if r.sim_seconds_per_wall_second > best.sim_seconds_per_wall_second:
            best = r
    # cost analysis from the engine's cached executable (no second
    # compile).  NOTE: XLA's HloCostAnalysis counts a while body ONCE
    # (trip count unknown), so the totals approximate ONE iteration plus
    # prologue/epilogue — they are reported as per-iteration ESTIMATES,
    # not divided by the executed count.
    flops_body = bytes_body = 0.0
    try:
        ca = eng._compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0] if ca else {}
        flops_body = float(ca.get("flops", 0.0))
        bytes_body = float(ca.get("bytes accessed", 0.0))
    except Exception:  # cost analysis unsupported on this runtime
        pass
    # resident device state: a hard lower bound on per-iteration traffic
    # (the while carry is read and written every trip)
    state_bytes = sum(
        x.size * x.dtype.itemsize
        for x in jax.tree_util.tree_leaves(eng.initial_state())
        if hasattr(x, "dtype")
    )
    iters = int(best.counters.get("lane_iters", 0)) or 1
    wall = best.wall_seconds
    wall_per_iter = wall / iters
    out = {
        "hosts": N,
        "sim_seconds": SIM_S,
        "rate_sim_s_per_wall_s": round(best.sim_seconds_per_wall_second, 4),
        "iters": iters,
        "iters_per_sim_s": round(iters / SIM_S, 1),
        "wall_s": round(wall, 4),
        "wall_per_iter_us": round(wall_per_iter * 1e6, 2),
        "state_bytes": int(state_bytes),
        "est_flops_per_iter": round(flops_body, 1),
        "est_bytes_per_iter": round(bytes_body, 1),
        "est_flops_frac_of_peak": calibrated_fraction(
            flops_body, wall_per_iter, PEAK_BF16_FLOPS
        ),
        "est_hbm_bw_frac_of_peak": calibrated_fraction(
            bytes_body, wall_per_iter, PEAK_HBM_BPS
        ),
    }
    print(tag, json.dumps(out))
    return out


def main() -> None:
    # r06: calibrated dict-valued fractions — do not clobber the scalar
    # UTIL_r05.json artifact that docs/tpu-backend.md and VERDICT.md cite
    out_path = sys.argv[1] if len(sys.argv) > 1 else "UTIL_r06.json"
    pure_cfg = flagship_mesh_config(
        N, sim_seconds=SIM_S, queue_capacity=16, pops_per_round=2
    )
    pure_cfg.experimental.tpu_cross_capacity = 8
    report = {
        "assumed_peaks": {
            "bf16_flops": PEAK_BF16_FLOPS,
            "hbm_bytes_per_s": PEAK_HBM_BPS,
        },
        "note": (
            "integer/sort-bound workload: the flops fraction is expected "
            "to be ~0; HBM bandwidth fraction is the meaningful ceiling"
        ),
        "pure": probe("pure", pure_cfg),
        "mixed": probe("mixed", mixed_flagship_config(N, sim_seconds=SIM_S)),
    }
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
    print("wrote", out_path)


if __name__ == "__main__":
    main()
