"""Mixed-mesh rate probe on the current default device.

Usage: python scripts/mixed_probe.py [sim_seconds] [repeats]
Env: PROBE_HOSTS (10000), PROBE_CAP (48), PROBE_K (4), PROBE_PAIRS (hosts/100)
"""

import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import shadow_tpu  # noqa: F401
from shadow_tpu.backend.tpu_engine import TpuEngine
from shadow_tpu.config.presets import mixed_flagship_config

SIM_S = int(sys.argv[1]) if len(sys.argv) > 1 else 5
REPEATS = int(sys.argv[2]) if len(sys.argv) > 2 else 3
N = int(os.environ.get("PROBE_HOSTS", "10000"))
SALT = ((os.getpid() << 16) ^ int(time.time())) & 0x3FFFFFFF

cfg = mixed_flagship_config(N, sim_seconds=SIM_S)
PAIRS = max(N // 100, 1)
if os.environ.get("PROBE_CAP"):
    cfg.experimental.tpu_lane_queue_capacity = int(os.environ["PROBE_CAP"])
if os.environ.get("PROBE_K"):
    cfg.experimental.tpu_events_per_round = int(os.environ["PROBE_K"])
if os.environ.get("PROBE_CROSS"):
    cfg.experimental.tpu_cross_capacity = int(os.environ["PROBE_CROSS"])
if os.environ.get("PROBE_SPOPS"):
    cfg.experimental.tpu_stream_events_per_round = int(
        os.environ["PROBE_SPOPS"]
    )
if os.environ.get("PROBE_SCAP"):
    cfg.experimental.tpu_stream_queue_capacity = int(os.environ["PROBE_SCAP"])
if os.environ.get("PROBE_UNROLL"):
    cfg.experimental.tpu_round_unroll = int(os.environ["PROBE_UNROLL"])

eng = TpuEngine(cfg, log_capacity=0)
t0 = time.perf_counter()
best = eng.run(mode="device", precompile=True, cache_salt=SALT + 1)
compile_s = time.perf_counter() - t0 - best.wall_seconds
rates = [best.sim_seconds_per_wall_second]
for i in range(REPEATS - 1):
    r = eng.run(mode="device", cache_salt=SALT + 2 + i)
    rates.append(r.sim_seconds_per_wall_second)
    if r.sim_seconds_per_wall_second > best.sim_seconds_per_wall_second:
        best = r
iters = best.counters.get("lane_iters", 0)
done = best.counters.get("stream_flows_done", 0)
print(
    f"hosts={N} pairs={PAIRS} sim_s={SIM_S}"
    f" cap={cfg.experimental.tpu_lane_queue_capacity}"
    f" K={cfg.experimental.tpu_events_per_round}"
    f" cross={cfg.experimental.tpu_cross_capacity}"
)
print(f"compile ~{compile_s:.1f}s  iters={iters}  flows_done={done}/{PAIRS}")
print(f"rates: {[round(x, 3) for x in rates]}")
print(
    f"best {best.sim_seconds_per_wall_second:.4f} sim_s/wall_s  "
    f"{best.wall_seconds / max(iters, 1) * 1e3:.3f} ms/iter"
)
