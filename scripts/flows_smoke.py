#!/usr/bin/env python
"""End-to-end flowtrace smoke (``make flows-smoke``, wired into ``make gate``).

One CLI run of a faulted loss-ramp stream scenario (a lane-TCP transfer
over a link whose loss spikes mid-run) with BOTH telemetry planes on:

1. a valid ``FLOWS_*.json`` artifact (schema keys, canonical event
   ordering, per-flow docs, burst-attribution buckets);
2. a sampled flow that exhibits the full lifecycle — send, drop (loss),
   retransmit, delivery — i.e. the loss ramp is visible per packet, not
   just as totals;
3. conservation against the netobs counter plane (sample = 1.0, so the
   two planes observe the same population): flowtrace sends equal the
   netobs ``sent`` total, deliveries equal ``delivered``, and loss/codel
   drop events equal the netobs drop-cause totals.

Exit 0 = all assertions hold; any failure raises (nonzero exit).
"""

from __future__ import annotations

import json
import shutil
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

# a 1 MB lane-TCP stream over a link whose loss ramps to 30% at 200 ms
# and heals at 1.2 s: the transfer (~400 ms clean at 20 Mbit) straddles
# the ramp, so data segments drop mid-flight AND recover (retransmit ->
# delivery) before the run ends
FAULTED_CFG = """
general: {stop_time: 20s, seed: 9, heartbeat_interval: null,
          bootstrap_end_time: 100ms}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "20 Mbit" host_bandwidth_down "20 Mbit" ]
        node [ id 1 host_bandwidth_up "20 Mbit" host_bandwidth_down "20 Mbit" ]
        edge [ source 0 target 1 latency "10 ms" packet_loss 0.02 ]
      ]
experimental: {network_backend: cpu}
faults:
  events:
    - {kind: loss, at: 200ms, source: 0, target: 1, loss: 0.3}
    - {kind: loss, at: 1200ms, source: 0, target: 1, loss: 0.02}
hosts:
  c:
    network_node_id: 0
    processes:
      - path: stream-client
        args: --server s --size 1000000
  s:
    network_node_id: 1
    processes:
      - path: stream-server
"""


def main() -> int:
    from shadow_tpu.__main__ import main as cli_main
    from shadow_tpu.obs import flowtrace as ftr

    tmp = Path(tempfile.mkdtemp(prefix="shadow_flows_smoke_"))
    try:
        cfg_path = tmp / "faulted.yaml"
        cfg_path.write_text(FAULTED_CFG)
        data = tmp / "run"
        rc = cli_main([
            str(cfg_path),
            "--data-directory", str(data),
            "--flowtrace",
            "--netobs",
        ])
        assert rc == 0, f"faulted run exited {rc}"

        arts = sorted(data.glob("FLOWS_*.json"))
        assert arts, f"no FLOWS_*.json in {data}"
        rep = json.loads(arts[0].read_text())
        for key in ("schema", "run_id", "backend", "seed", "events",
                    "events_by_kind", "flows", "burst_attribution",
                    "events_lost", "num_events"):
            assert key in rep, f"FLOWS report missing {key!r}"
        assert rep["events_lost"] == 0, "smoke ring overflowed"
        events = [tuple(e) for e in rep["events"]]
        assert events == sorted(events), "events not in canonical order"

        # 2. the full lifecycle on one sampled flow: some packet was
        # sent, lost to the ramp, re-sent as a new wire unit, delivered
        kinds = rep["events_by_kind"]
        for k in ("send", "tb_wait", "queue_enter", "drop",
                  "retransmit", "delivery"):
            assert kinds.get(k, 0) > 0, f"no {k!r} events: {kinds}"
        fl = rep["flows"]["c->s"]
        assert fl["drops"]["loss"] > 0, f"no loss drops on c->s: {fl}"
        assert fl["retransmits"] > 0, f"no retransmits on c->s: {fl}"
        assert fl["delivered"] > 0
        # a retransmitted wire packet that went on to deliver
        retx = {(e[3], e[4], e[5]) for e in events
                if e[2] == ftr.FT_RETRANSMIT}
        deliv = {(e[3], e[4], e[5]) for e in events
                 if e[2] == ftr.FT_DELIVERY}
        assert retx & deliv, "no retransmit->delivery join"

        # 3. conservation vs the netobs plane (sample=1.0: both planes
        # see every packet)
        nrep = json.loads(next(data.glob("NETOBS_*.json")).read_text())
        tot = nrep["totals"]
        sends = kinds.get("send", 0) + kinds.get("retransmit", 0)
        assert sends == tot["sent"], (sends, tot["sent"])
        assert kinds.get("delivery", 0) == tot["delivered"]
        loss = sum(1 for e in events
                   if e[2] == ftr.FT_DROP and e[7] == ftr.CAUSE_LOSS)
        codel = sum(1 for e in events
                    if e[2] == ftr.FT_DROP and e[7] == ftr.CAUSE_CODEL)
        assert loss == tot["drop_loss"], (loss, tot["drop_loss"])
        assert codel == tot["drop_codel"], (codel, tot["drop_codel"])

        print(
            "flows-smoke OK: "
            f"{rep['num_events']} events / {rep['num_flows']} flows; "
            f"c->s lifecycle sends={fl['sends']} "
            f"loss_drops={fl['drops']['loss']} "
            f"retransmits={fl['retransmits']} delivered={fl['delivered']}"
            " (artifact valid, netobs conservation holds)"
        )
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
