#!/usr/bin/env python
"""End-to-end obs smoke (``make obs-smoke``, wired into ``make gate``).

Runs the examples/phold.yaml classic with metrics + tracing fully
enabled and asserts the run produced:

1. a valid ``METRICS_*.json`` artifact (schema keys, nonzero windows,
   per-phase wall totals);
2. a loadable Chrome-trace JSON whose complete events cover the phases
   the METRICS report attributes — and whose summed span wall per phase
   matches the report's ``phase_wall_s`` totals (the same cross-check
   the acceptance criterion makes on hybrid runs);
3. a JSONL metric stream with one parseable record per line.

Exit 0 = all assertions hold; any failure raises (nonzero exit).
"""

from __future__ import annotations

import json
import shutil
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def main() -> int:
    from shadow_tpu.__main__ import main as cli_main

    tmp = Path(tempfile.mkdtemp(prefix="shadow_obs_smoke_"))
    data = tmp / "data"
    try:
        rc = cli_main([
            str(REPO / "examples" / "phold.yaml"),
            "--stop-time", "2s",
            "--data-directory", str(data),
            "--obs-metrics",
            "--obs-trace",
            "--set", "experimental.obs_jsonl=true",
        ])
        assert rc == 0, f"simulation exited {rc}"

        metrics = sorted(data.glob("METRICS_*.json"))
        assert metrics, f"no METRICS_*.json in {data}"
        rep = json.loads(metrics[0].read_text())
        for key in ("schema", "run_id", "phase_wall_s", "phases",
                    "counters", "histograms", "sim_counters"):
            assert key in rep, f"METRICS report missing {key!r}"
        assert rep["counters"].get("windows", 0) > 0, "no windows recorded"
        assert rep["phase_wall_s"], "no phase wall attribution"
        assert all(v >= 0 for v in rep["phase_wall_s"].values())

        traces = sorted(data.glob("trace_*.json"))
        assert traces, f"no trace_*.json in {data}"
        doc = json.loads(traces[0].read_text())
        events = doc.get("traceEvents")
        assert isinstance(events, list) and events, "empty traceEvents"
        spans = [e for e in events if e.get("ph") == "X"]
        assert spans, "no complete (ph=X) span events"
        for e in spans:
            for key in ("name", "cat", "ts", "dur", "pid", "tid"):
                assert key in e, f"span missing {key!r}: {e}"
        summed: dict[str, float] = {}
        for e in spans:
            summed[e["cat"]] = summed.get(e["cat"], 0.0) + e["dur"] / 1e6
        for phase, wall in rep["phase_wall_s"].items():
            got = summed.get(phase, 0.0)
            assert abs(got - wall) <= max(1e-6, 1e-6 * wall), (
                f"phase {phase}: trace spans sum to {got}, METRICS says {wall}"
            )

        jsonl = sorted(data.glob("metrics_*.jsonl"))
        assert jsonl, f"no metrics_*.jsonl in {data}"
        n = 0
        with open(jsonl[0]) as f:
            for line in f:
                json.loads(line)
                n += 1
        assert n > 0, "empty JSONL stream"

        print(
            f"obs-smoke OK: {rep['counters']['windows']} windows, "
            f"{len(spans)} spans over {sorted(summed)} "
            f"(METRICS/trace/JSONL artifacts all valid)"
        )
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
