#!/usr/bin/env python
"""End-to-end checkpoint smoke (``make checkpoint-smoke``, wired into
``make gate``): the checkpoint -> resume -> byte-compare round trip of
docs/robustness.md, driven through the CLI layer.

1. Run the phold classic on the cpu backend with periodic checkpoints
   and the canonical event log on; keep the final artifacts.
2. Validate every checkpoint with the ``checkpoint-inspect`` tool path
   (magic, version, payload hash, fingerprint).
3. Resume the OLDEST retained checkpoint in a fresh process-state
   (``--resume``) and require the resumed run's event log to
   byte-match the uninterrupted run's — the deterministic-replay law.
4. Repeat the round trip on the tpu (lane) backend under
   JAX_PLATFORMS=cpu, including the NETOBS artifact bytes.

Exit 0 = all assertions hold; any failure raises (nonzero exit).
"""

from __future__ import annotations

import shutil
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

CFG = """
general: {stop_time: 500ms, seed: 7, heartbeat_interval: null}
experimental: {network_backend: %s, netobs: true%s}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        node [ id 1 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        edge [ source 0 target 0 latency "2 ms" ]
        edge [ source 0 target 1 latency "5 ms" ]
        edge [ source 1 target 1 latency "2 ms" ]
      ]
hosts:
  a: {network_node_id: 0, processes: [{path: phold, args: [--messages, "3"]}]}
  b: {network_node_id: 1, processes: [{path: phold, args: [--messages, "3"]}]}
  c: {network_node_id: 1, processes: [{path: phold, args: [--messages, "2"]}]}
  d: {network_node_id: 0, processes: [{path: phold, args: [--messages, "2"]}]}
"""


def _run(tmp: Path, name: str, backend: str, extra: str = "") -> Path:
    """One CLI run; returns its data directory."""
    from shadow_tpu.__main__ import main as cli_main

    data = tmp / name
    cfg_path = tmp / f"{name}.yaml"
    cfg_path.write_text(CFG % (backend, extra))
    rc = cli_main(
        [str(cfg_path), "--data-directory", str(data), "--event-log"]
    )
    assert rc == 0, f"{name}: CLI exited {rc}"
    return data


def _round_trip(tmp: Path, backend: str) -> int:
    from shadow_tpu.engine.checkpoint import inspect_main

    ref = _run(tmp, f"{backend}-ref", backend)
    full = _run(
        tmp, f"{backend}-full", backend,
        ", checkpoint_every_windows: 40",
    )
    ref_log = (ref / "event-log.tsv").read_bytes()
    assert (full / "event-log.tsv").read_bytes() == ref_log, (
        f"{backend}: checkpointing perturbed the run"
    )
    cks = sorted((full / "checkpoints").iterdir())
    assert cks, f"{backend}: no checkpoints written"
    for ck in cks:  # the validator accepts every retained checkpoint
        assert inspect_main([str(ck)]) == 0, f"invalid checkpoint {ck}"
    res = _run(
        tmp, f"{backend}-res", backend,
        f", checkpoint_every_windows: 40, resume_from: '{cks[0]}'",
    )
    assert (res / "event-log.tsv").read_bytes() == ref_log, (
        f"{backend}: resumed event log differs"
    )
    art = f"NETOBS_{backend}-seed7.json"
    assert (res / art).read_bytes() == (full / art).read_bytes(), (
        f"{backend}: resumed {art} differs"
    )
    return len(cks)


def main() -> int:
    tmp = Path(tempfile.mkdtemp(prefix="shadow-tpu-ckpt-smoke-"))
    try:
        n_cpu = _round_trip(tmp, "cpu")
        n_tpu = _round_trip(tmp, "tpu")
        print(
            f"checkpoint-smoke OK: cpu round trip ({n_cpu} checkpoints) "
            f"and tpu round trip ({n_tpu} checkpoints) byte-identical "
            "(event log + NETOBS), all checkpoints validate"
        )
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
