#!/usr/bin/env python
"""Aggregate BENCH_r0*.json into one legible perf-trajectory table.

Every round's driver (or in-container) bench snapshot lands as a
``BENCH_r0N.json`` blob at the repo root, each carrying a ``parsed``
dict of bench.py's JSON line.  This script folds them into a single
key × round table — the repo's perf history — with a delta column
against the reference's 6.38× headline for the rate keys that chase it
(ROADMAP open items 1 and 3).

Usage::

    python scripts/bench_report.py                 # markdown to stdout
    python scripts/bench_report.py --format json   # machine-readable
    python scripts/bench_report.py --write docs/bench-trajectory.md

The committed docs/bench-trajectory.md is this script's output; re-run
with ``--write`` whenever a new BENCH_r0N.json lands.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REFERENCE_SPEEDUP = 6.38  # BASELINE.md: the fork's measured headline

# rate keys measured against the 6.38 target (sim-s / wall-s)
TARGET_KEYS = (
    "value",
    "mixed_sim_s_per_wall_s",
    "managed_sim_s_per_wall_s",
    "hybrid_sim_s_per_wall_s",
)

# stable row order: headline first, then the ladder, then context keys
KEY_ORDER = [
    "value",
    "mixed_sim_s_per_wall_s",
    "managed_sim_s_per_wall_s",
    "hybrid_sim_s_per_wall_s",
    "cpu_sim_s_per_wall_s",
    "speedup_vs_cpu_backend",
    "configs.tgen_mesh_10k_udp",
    "configs.tgen_mesh_10k_mixed",
    "configs.tgen_mesh_1k_mixed",
    "configs.udp_star_100",
    "configs.transfer_2host",
    "configs.managed_relay_chains",
    "configs.managed_relay_chains_large_hybrid",
    "hybrid_phase_wall_s.device_turn",
    "hybrid_phase_wall_s.syscall_service",
    "hybrid_phase_wall_s.worker_pipe",
    "hybrid_phase_wall_s.injection",
    "hybrid_phase_wall_s.egress",
    "hybrid_sync.device_sync_s",
    "hybrid_sync.syscall_service_s",
    "hybrid_sync.device_turns",
    # device-turn ledger keys (obs/turns.py — ROADMAP item 1's
    # instrument: why each blocking turn exists and how many could fuse)
    "turns",
    "empty_injection_turns",
    "fusable_runs",
    "fusable_run_p50",
    "fusable_run_p99",
    "fusable_run_max",
    "kfusion_headroom",
    "kfusion_headroom_freerun",
    # realized k-window fusion (ISSUE 13: backend/hybrid.py fused law)
    "hybrid_fused_runs",
    "hybrid_fused_windows",
    "hybrid_turns_saved",
    "hybrid_fuse_rollbacks",
    "hybrid_achieved_fusion",
    "hybrid_unfused_turns",
    "hybrid_async_hits",
    "hybrid_async_misses",
    # netobs telemetry keys (drop-cause / retransmit totals + the
    # burst-window histogram buckets — open item 3's evidence base;
    # mixed_window_hist.b* buckets follow in the sorted tail)
    "mesh_drops.loss",
    "mesh_drops.codel",
    "mesh_drops.queue",
    "mixed_drops.loss",
    "mixed_drops.codel",
    "mixed_drops.queue",
    "mixed_retransmits",
    "mixed_windows",
    "mixed_throttled",
    # flowtrace burst attribution (obs/flowtrace.py — which flow classes
    # fill the busy mixed_window_hist buckets; the per-bucket class
    # ranking stays machine-readable in the BENCH json's
    # mixed_flow_attribution.buckets list)
    "mixed_flow_attribution.sample",
    "mixed_flow_attribution.num_events",
    "mixed_flow_attribution.num_flows",
    "mixed_flow_attribution.events_lost",
    # fleet-sweep throughput (shadow_tpu/sweep/, docs/sweep.md): an
    # S-scenario seed grid through ONE compiled vmapped kernel —
    # whole-scenario completions per hour plus the compile-amortization
    # ratio (S x serial-with-compile wall over the batch wall)
    "scenarios_per_hour",
    "sweep_compile_amortization",
    "sweep_size",
    "sweep_hosts",
    "sweep_sim_seconds",
    "sweep_batch_wall_s",
    "sweep_serial_wall_s",
    "sweep_traces",
    # multi-chip sharded lane plane (shadow_tpu/parallel/,
    # docs/multichip.md): the columnar 100k-host mesh sharded over the
    # host axis — the sharded rate, the 1-device reference, and the
    # strong-scaling efficiency rate(D) / (D x rate(1))
    "multichip_sim_s_per_wall_s",
    "multichip_1dev_sim_s_per_wall_s",
    "multichip_scaling_efficiency",
    "multichip_devices",
    "multichip_hosts",
    "multichip_sim_seconds",
    "multichip_build_s",
    "configs.columnar_mesh_100k_sharded",
]

KEY_LABEL = {
    "value": "tgen_mesh_10k (headline)",
}

# bucket histograms render as ONE compact sparkline row per group
# instead of a raw b0..bN key explosion (the per-bucket values stay
# machine-readable in --format json)
HIST_GROUPS = ("mixed_window_hist", "fusable_run_hist")
HIST_KEY_RE = re.compile(
    r"^(" + "|".join(HIST_GROUPS) + r")\.b(\d+)$"
)
SPARK_CHARS = "·▁▂▃▄▅▆▇█"  # index 0 = empty bucket, 1..8 = scaled


def sparkline(buckets: list[int]) -> str:
    """Deterministic unicode sparkline: each bucket scales against the
    row's max (empty buckets print the midline dot)."""
    vmax = max(buckets, default=0)
    if vmax <= 0:
        return "—"
    return "".join(
        SPARK_CHARS[0] if v <= 0 else SPARK_CHARS[1 + (7 * int(v)) // vmax]
        for v in buckets
    )


def hist_tables(
    rounds: dict[str, dict[str, object]],
) -> dict[str, dict[str, list[int]]]:
    """group -> round tag -> dense bucket list (width = the max bucket
    index seen for that group across all rounds, so columns align)."""
    width: dict[str, int] = {}
    raw: dict[str, dict[str, dict[int, int]]] = {}
    for tag, flat in rounds.items():
        for key, val in flat.items():
            m = HIST_KEY_RE.match(key)
            if not m:
                continue
            group, idx = m.group(1), int(m.group(2))
            width[group] = max(width.get(group, 0), idx + 1)
            raw.setdefault(group, {}).setdefault(tag, {})[idx] = int(val)
    return {
        group: {
            tag: [cells.get(i, 0) for i in range(width[group])]
            for tag, cells in per_tag.items()
        }
        for group, per_tag in raw.items()
    }


def _flatten(d: dict, prefix: str = "") -> dict[str, object]:
    out: dict[str, object] = {}
    for k, v in d.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "."))
        elif isinstance(v, (int, float)) and not isinstance(v, bool):
            out[key] = v
    return out


def load_rounds(repo: str = REPO) -> dict[str, dict[str, object]]:
    """round tag (``r01``...) -> flattened numeric keys of ``parsed``."""
    rounds: dict[str, dict[str, object]] = {}
    for path in sorted(glob.glob(os.path.join(repo, "BENCH_r*.json"))):
        m = re.search(r"BENCH_(r\d+)\.json$", path)
        if not m:
            continue
        with open(path) as f:
            doc = json.load(f)
        parsed = doc.get("parsed")
        if isinstance(parsed, dict):
            flat = _flatten(parsed)
            # a single-scenario round (e.g. HYBRID_ONLY) aliases its one
            # rate as "value"; the dedicated key already carries it, so
            # drop the alias rather than pollute the headline row
            if not str(parsed.get("metric", "")).startswith(
                "sim_seconds_per_wall_second"
            ):
                flat.pop("value", None)
                flat.pop("vs_baseline", None)
            rounds[m.group(1)] = flat
    return rounds


def build_table(
    rounds: dict[str, dict[str, object]],
) -> tuple[list[str], list[str]]:
    """(ordered round tags, ordered row keys present in any round)."""
    # numeric round order: lexicographic would put r100 before r99
    tags = sorted(rounds, key=lambda t: int(t[1:]))
    seen: set[str] = set()
    for flat in rounds.values():
        seen.update(flat)
    # per-bucket histogram keys collapse into sparkline rows (below)
    seen = {k for k in seen if not HIST_KEY_RE.match(k)}
    keys = [k for k in KEY_ORDER if k in seen]
    # every remaining key follows the curated order — nested (dotted)
    # ones included, so a new phase/sync key can never silently vanish
    # from the history table
    keys += sorted(k for k in seen if k not in KEY_ORDER)
    return tags, keys


def _fmt(v: object) -> str:
    if v is None:
        return "—"
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def render_markdown(rounds: dict[str, dict[str, object]]) -> str:
    tags, keys = build_table(rounds)
    lines = [
        "# Bench trajectory",
        "",
        "Generated by `python scripts/bench_report.py --write "
        "docs/bench-trajectory.md` from the repo-root `BENCH_r0N.json` "
        "artifacts — re-run it when a new round lands.  Rate keys are "
        "sim-seconds per wall-second; `Δ vs 6.38` divides the latest "
        f"value by the reference headline ({REFERENCE_SPEEDUP}×, "
        "BASELINE.md) for the keys that chase it.  Device-tier numbers "
        "move between axon-runtime and CPU-JAX measurement boxes across "
        "rounds (each BENCH file's `source` notes which); per-phase "
        "`hybrid_phase_wall_s.*` keys are the obs-measured wall "
        "attribution (docs/observability.md).  Bucket histograms "
        "(`mixed_window_hist`, `fusable_run_hist`) render as one "
        "sparkline row each — log2 buckets left to right from b0, "
        "scaled per cell; `·` is an empty bucket (raw values: "
        "`--format json`).",
        "",
    ]
    header = "| key | " + " | ".join(tags) + " | Δ vs 6.38 |"
    sep = "|---" * (len(tags) + 2) + "|"
    lines += [header, sep]
    for key in keys:
        cells = [_fmt(rounds[t].get(key)) for t in tags]
        delta = ""
        if key in TARGET_KEYS:
            latest = None
            for t in reversed(tags):
                if rounds[t].get(key) is not None:
                    latest = rounds[t][key]
                    break
            if latest is not None:
                delta = f"{float(latest) / REFERENCE_SPEEDUP:.2%}"
        label = KEY_LABEL.get(key, key)
        lines.append(f"| `{label}` | " + " | ".join(cells) + f" | {delta} |")
    hists = hist_tables(rounds)
    for group in HIST_GROUPS:
        if group not in hists:
            continue
        cells = [
            sparkline(hists[group][t]) if t in hists[group] else "—"
            for t in tags
        ]
        lines.append(
            f"| `{group}` (log2 buckets, b0→) | "
            + " | ".join(cells) + " |  |"
        )
    lines.append("")
    return "\n".join(lines)


def render_json(rounds: dict[str, dict[str, object]]) -> str:
    tags, keys = build_table(rounds)
    return json.dumps(
        {
            "reference_speedup": REFERENCE_SPEEDUP,
            "rounds": tags,
            "table": {
                key: {t: rounds[t].get(key) for t in tags} for key in keys
            },
            # the sparkline rows' raw buckets, machine-readable
            "histograms": hist_tables(rounds),
        },
        indent=2,
    )


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description="aggregate BENCH_r0N.json into a trajectory table"
    )
    p.add_argument("--format", choices=("markdown", "json"), default="markdown")
    p.add_argument(
        "--write", metavar="FILE", default=None,
        help="write the rendered table to FILE instead of stdout",
    )
    p.add_argument(
        "--repo", default=REPO, help="repo root holding BENCH_r0N.json"
    )
    ns = p.parse_args(argv)
    rounds = load_rounds(ns.repo)
    if not rounds:
        print("bench_report: no BENCH_r0N.json artifacts found", file=sys.stderr)
        return 1
    text = (
        render_markdown(rounds) if ns.format == "markdown"
        else render_json(rounds)
    )
    if ns.write:
        with open(ns.write, "w") as f:
            f.write(text if text.endswith("\n") else text + "\n")
        print(f"bench_report: wrote {ns.write}", file=sys.stderr)
    else:
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
