"""Microbenchmark of lane-kernel pieces on the current default device.

Times (a) the full fused bench at small sim duration, (b) isolated device
kernels with the bench's shapes: the cross-lane flat sort, the merge row
sort, one scan-slot's elementwise math, threefry draws.
"""

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import jax
import jax.numpy as jnp
from jax import lax

import shadow_tpu  # noqa: F401
from shadow_tpu.backend.tpu_engine import TpuEngine
from shadow_tpu.config.presets import flagship_mesh_config

N, K, C = 10_000, 4, 16
NEVER = (1 << 62)


def timeit(name, fn, *args, reps=20):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps
    print(f"{name:36s} {dt*1e3:8.3f} ms")
    return dt


def main():
    key = jax.random.PRNGKey(0)
    m = N * K

    # (1) cross-lane flat sort: [m] single-key, 4 operands
    dst = jax.random.randint(key, (m,), 0, N, dtype=jnp.int32)
    t64 = jax.random.randint(key, (m,), 0, 1 << 40, dtype=jnp.int64)
    aux = jax.random.randint(key, (m,), 0, 1 << 60, dtype=jnp.int64)
    sz = jax.random.randint(key, (m,), 0, 1500, dtype=jnp.int32)

    flat_sort = jax.jit(
        lambda d, t, a, s: lax.sort((d, t, a, s), dimension=0, num_keys=1)
    )
    timeit("cross flat sort [40k] 4ops", flat_sort, dst, t64, aux, sz)

    # (2) merge row sort: [N, C+2K+C] 2-key, 3 operands
    w = C + 2 * K + C
    mt = jax.random.randint(key, (N, w), 0, 1 << 40, dtype=jnp.int64)
    ma = jax.random.randint(key, (N, w), 0, 1 << 60, dtype=jnp.int64)
    ms = jax.random.randint(key, (N, w), 0, 1500, dtype=jnp.int32)
    row_sort = jax.jit(
        lambda t, a, s: lax.sort((t, a, s), dimension=1, num_keys=2)
    )
    timeit(f"merge row sort [N,{w}] 3ops", row_sort, mt, ma, ms)

    # (2b) narrower row sort [N, 24]
    mt2, ma2, ms2 = mt[:, :24], ma[:, :24], ms[:, :24]
    timeit("row sort [N,24] 3ops", row_sort, mt2, ma2, ms2)

    # (2c) row sort [N, C] (the no-merge re-sort path)
    timeit("row sort [N,16] 3ops", row_sort, mt[:, :C], ma[:, :C], ms[:, :C])

    # (3) threefry draw [N]
    from shadow_tpu.core import rng as rng_mod

    ctr = jnp.arange(N, dtype=jnp.int64)
    tf = jax.jit(lambda c: rng_mod.rand_u32(7, jnp.uint32(3), c, xp=jnp))
    timeit("threefry [N]", tf, ctr)

    # (4) searchsorted + window gather
    from shadow_tpu.backend.lanes import _window_gather

    srt = jnp.sort(dst)
    gather = jax.jit(
        lambda d, t, a, s: _window_gather(
            [t, a, s],
            jnp.searchsorted(d, jnp.arange(N, dtype=d.dtype)).astype(jnp.int32),
            C,
        )
    )
    timeit("searchsorted+window gather", gather, srt, t64, aux, sz)

    # (5) full bench, 1 sim-second
    cfg = flagship_mesh_config(N, sim_seconds=1, queue_capacity=C, pops_per_round=K)
    eng = TpuEngine(cfg, log_capacity=0)
    res = eng.run(mode="device", precompile=True)
    print(
        f"full bench 1 sim-s: wall={res.wall_seconds:.3f}s rounds={res.rounds} "
        f"-> {res.wall_seconds/max(res.rounds,1)*1e3:.3f} ms/round, "
        f"rate={res.sim_seconds_per_wall_second:.2f} sim-s/s"
    )


def bisect():
    """Time one jitted round and one jitted fused iteration on the pair
    representation (int32 key words; see lanes.py module docs)."""
    import shadow_tpu.backend.lanes as lanes

    cfg = flagship_mesh_config(N, sim_seconds=1, queue_capacity=C, pops_per_round=K)
    eng = TpuEngine(cfg, log_capacity=0)
    p, tb = eng.params, eng.tables
    s0 = eng.initial_state()
    round_fn = jax.jit(lanes._build_round(p, tb))
    s1, _ = round_fn(s0)
    jax.block_until_ready(s1)
    timeit("one full round (jit)", lambda s: round_fn(s)[0], s1)

    iter_fn = lanes._build_iter(p, tb, pure_dataflow=True)

    def one_iter(s):
        we_hi, we_lo = lanes.pair_min_lanes(s.q_thi[:, 0], s.q_tlo[:, 0])
        we_hi, we_lo = lanes.pair_add32(we_hi, we_lo, p.runahead)
        return iter_fn(s._replace(now_we_hi=we_hi, now_we_lo=we_lo))

    fused = jax.jit(one_iter)
    jax.block_until_ready(fused(s1))
    timeit("one fused iteration (jit)", fused, s1)


if __name__ == "__main__":
    main()
    bisect()
