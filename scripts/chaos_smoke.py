#!/usr/bin/env python
"""Kill-a-worker chaos smoke at gate scale (``make chaos-smoke``, wired
into ``make gate``; docs/robustness.md "supervision model").

The flagship tgen mesh on the 4-worker MpCpuEngine, three times:

1. clean — the parallel baseline, checked against the serial oracle
   (the parallelism-invariance law);
2. chaos — a seeded-random worker is SIGKILLed mid-run; the supervisor
   respawns it and replays its round journal, and the event log plus
   counters must byte-match the clean run (``worker_restarts == 1``);
3. escalation — the same worker hangs again after every respawn, the
   restart budget exhausts, and the engine falls back to the serial
   oracle from t=0 — still byte-identical.

Exit 0 = all assertions hold; any failure raises (nonzero exit).
"""

from __future__ import annotations

import os
import random
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

N_HOSTS = 24
SIM_SECONDS = 2
WORKERS = 4


def _cfg():
    from shadow_tpu.config.presets import flagship_mesh_config

    return flagship_mesh_config(
        N_HOSTS, sim_seconds=SIM_SECONDS, backend="cpu"
    )


def main() -> int:
    from shadow_tpu.backend.cpu_engine import CpuEngine
    from shadow_tpu.backend.cpu_mp import MpCpuEngine

    serial = CpuEngine(_cfg()).run()

    clean_eng = MpCpuEngine(_cfg(), workers=WORKERS)
    clean = clean_eng.run()
    assert clean.log_tuples() == serial.log_tuples(), (
        "parallel baseline diverged from the serial oracle"
    )
    assert clean_eng.worker_restarts == 0

    rng = random.Random(16)  # the seeded chaos schedule
    wid = rng.randrange(WORKERS)
    t_kill = rng.randrange(
        SIM_SECONDS * 250, SIM_SECONDS * 750
    ) * 1_000_000  # mid-run, ns
    os.environ["SHADOW_TPU_TEST_WORKER_KILL"] = f"{wid}:{t_kill}"
    try:
        chaos_eng = MpCpuEngine(_cfg(), workers=WORKERS)
        chaos = chaos_eng.run()
    finally:
        del os.environ["SHADOW_TPU_TEST_WORKER_KILL"]
    assert chaos_eng.worker_restarts == 1, chaos_eng.worker_restarts
    assert not chaos_eng.escalated
    assert chaos.log_tuples() == clean.log_tuples(), (
        "SIGKILL recovery diverged from the clean run"
    )
    assert chaos.counters == clean.counters

    os.environ["SHADOW_TPU_TEST_WORKER_HANG"] = f"{wid}:{t_kill}"
    try:
        esc_cfg = _cfg()
        esc_cfg.experimental.worker_restart_max = 1
        # generous deadline: first-round replies at gate scale carry
        # worker spawn + world build and must not trip a false positive
        esc_cfg.experimental.worker_heartbeat_s = 5.0
        esc_eng = MpCpuEngine(esc_cfg, workers=WORKERS)
        esc = esc_eng.run()
    finally:
        del os.environ["SHADOW_TPU_TEST_WORKER_HANG"]
    assert esc_eng.escalated, "hang did not escalate to serial"
    assert esc.log_tuples() == clean.log_tuples(), (
        "escalate-to-serial replay diverged"
    )

    print(
        f"chaos-smoke OK: {N_HOSTS}-host mesh, {WORKERS} workers — "
        f"SIGKILL worker {wid} at {t_kill} ns recovered bit-identically "
        f"(1 respawn); repeated hang escalated to the serial oracle "
        "bit-identically"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
