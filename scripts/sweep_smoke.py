#!/usr/bin/env python
"""End-to-end sweep smoke (``make sweep-smoke``, wired into ``make gate``).

A 4-variant sweep (seed grid x loss-fault grid) on the flagship tgen
mesh, batched through ONE compiled vmapped kernel, asserting the sweep
correctness law (docs/sweep.md):

1. the batched kernel traced exactly ONCE for all 4 scenarios;
2. every scenario's counters and round count are BIT-IDENTICAL to a
   fresh serial ``TpuEngine`` run of the same config (per-scenario
   bit-identity, the law the whole subsystem rests on);
3. the cross-scenario drop statistics show NONZERO variance (the lossy
   fault axis actually diverges the fleet — the sweep measures real
   scenario differences, not S copies of one trajectory);
4. the SWEEP artifact is byte-identical when built twice.

Exit 0 = all assertions hold; any failure raises (nonzero exit).
"""

from __future__ import annotations

import shutil
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

LOSS_EVENT = {
    "at": "500 ms", "kind": "loss", "source": 0, "target": 0, "loss": 0.05,
}


def main() -> int:
    from shadow_tpu.backend.tpu_engine import TpuEngine
    from shadow_tpu.config.presets import flagship_mesh_config
    from shadow_tpu.sweep import (
        SweepEngine,
        SweepSpec,
        build_report,
        expand_variants,
        write_report,
    )

    base = flagship_mesh_config(16, sim_seconds=2, backend="tpu", seed=42)
    spec = SweepSpec(
        name="smoke",
        seeds=[42, 43],
        faults=[[], [LOSS_EVENT]],
    )
    variants = expand_variants(base, spec)
    assert len(variants) == 4, f"expected 4 variants, got {len(variants)}"

    sweep = SweepEngine(variants)
    results = sweep.run()
    assert sweep.traces == 1, (
        f"batched kernel traced {sweep.traces} times, expected exactly 1 "
        "(one XLA compile must serve the whole fleet)"
    )

    # per-scenario bit-identity vs fresh serial reference runs
    for v, r in zip(variants, results):
        ref = TpuEngine(v.cfg).run(mode="device")
        assert int(r.rounds) == int(ref.rounds), (
            f"{v.label}: rounds {int(r.rounds)} != serial {int(ref.rounds)}"
        )
        keys = sorted(set(r.counters) | set(ref.counters))
        diffs = {
            k: (int(r.counters.get(k, 0)), int(ref.counters.get(k, 0)))
            for k in keys
            if int(r.counters.get(k, 0)) != int(ref.counters.get(k, 0))
        }
        assert not diffs, f"{v.label}: batched != serial counters: {diffs}"

    # the loss axis must actually diverge the fleet
    drops = [int(r.counters.get("lane_drop_loss", 0)) for r in results]
    lossy = [d for v, d in zip(variants, drops) if v.fault_axis == 1]
    clean = [d for v, d in zip(variants, drops) if v.fault_axis == 0]
    assert all(d == 0 for d in clean), f"loss drops on clean axis: {drops}"
    assert all(d > 0 for d in lossy), f"no loss drops on lossy axis: {drops}"
    assert len(set(drops)) > 1, f"no cross-scenario drop variance: {drops}"

    report = build_report(sweep, results, name="smoke")
    for metric in ("lane_drop_loss",):
        cross = report["cross"][metric]
        assert cross["max"] > cross["min"], f"flat cross stats for {metric}"

    tmp = Path(tempfile.mkdtemp(prefix="shadow_sweep_smoke_"))
    try:
        p1 = write_report(report, tmp / "a")
        p2 = write_report(build_report(sweep, results, name="smoke"), tmp / "b")
        assert p1.read_bytes() == p2.read_bytes(), "SWEEP artifact not byte-stable"
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    print(
        "sweep-smoke OK: S=4 seed x loss grid, 1 trace, per-scenario "
        f"bit-identity vs serial holds, loss drops {drops} "
        f"(artifact {p1.name} byte-stable)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
