#!/usr/bin/env python
"""End-to-end netobs smoke (``make netobs-smoke``, wired into ``make gate``).

Two runs through the CLI, both with the network telemetry plane on:

1. the examples/phold.yaml classic — asserts a valid ``NETOBS_*.json``
   artifact (schema keys, per-host counter catalog, a window histogram
   whose bucket sum equals its ``windows`` total, sent == delivered +
   drops conservation);
2. a drop-heavy faulted scenario (a loss-ramp fault schedule over a
   lossy low-bandwidth link) — asserts NONZERO drop-cause attribution
   (loss + codel) and that the drop totals agree with the per-host
   breakdown.

Exit 0 = all assertions hold; any failure raises (nonzero exit).
"""

from __future__ import annotations

import json
import shutil
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

FAULTED_CFG = """
general: {stop_time: 2s, seed: 13, heartbeat_interval: null}
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 host_bandwidth_up "4 Mbit" host_bandwidth_down "1 Mbit" ]
        edge [ source 0 target 0 latency "10 ms" packet_loss 0.05 ]
      ]
experimental: {network_backend: cpu}
faults:
  events:
    - {kind: loss, at: 500ms, source: 0, target: 0, loss: 0.3}
hosts:
  srv:
    network_node_id: 0
    processes: [{path: tgen-server}]
  cli:
    count: 5
    network_node_id: 0
    processes:
      - path: tgen-client
        args: --server srv --interval 5ms --size 1300
"""


def _check_report(path: Path) -> dict:
    rep = json.loads(path.read_text())
    for key in ("schema", "run_id", "backend", "seed", "totals",
                "drops_by_cause", "drop_total", "window_hist",
                "top_talkers", "log_lost"):
        assert key in rep, f"NETOBS report missing {key!r}"
    hist = rep["window_hist"]
    assert hist["scheme"] == "log2-packet-arrivals"
    assert sum(hist["buckets"]) == hist["windows"], "histogram sum drift"
    tot = rep["totals"]
    # conservation: every sent packet is delivered, dropped on the wire
    # path (loss at the sender, codel/queue/shed at the receiver), or
    # still in flight at stop_time
    wire_drops = (
        tot["drop_loss"] + tot["drop_codel"] + tot["drop_queue"]
        + tot["drop_cross_shed"]
    )
    assert rep["in_flight"] >= 0, f"negative in_flight: {rep['in_flight']}"
    assert tot["sent"] == tot["delivered"] + wire_drops + rep["in_flight"]
    if "per_host" in rep:
        for k in ("sent", "delivered", "drop_loss", "drop_codel"):
            per = sum(h[k] for h in rep["per_host"].values())
            assert per == tot[k], f"per-host {k} sum != total"
    return rep


def main() -> int:
    from shadow_tpu.__main__ import main as cli_main

    tmp = Path(tempfile.mkdtemp(prefix="shadow_netobs_smoke_"))
    try:
        # 1. phold classic with the telemetry plane on
        data = tmp / "phold"
        rc = cli_main([
            str(REPO / "examples" / "phold.yaml"),
            "--stop-time", "2s",
            "--data-directory", str(data),
            "--netobs",
        ])
        assert rc == 0, f"phold run exited {rc}"
        arts = sorted(data.glob("NETOBS_*.json"))
        assert arts, f"no NETOBS_*.json in {data}"
        rep = _check_report(arts[0])
        assert rep["window_hist"]["windows"] > 0, "no windows recorded"
        assert rep["totals"]["sent"] > 0, "phold sent nothing"

        # 2. faulted drop-heavy scenario: nonzero drop-cause attribution
        cfg_path = tmp / "faulted.yaml"
        cfg_path.write_text(FAULTED_CFG)
        data2 = tmp / "faulted"
        rc = cli_main([
            str(cfg_path),
            "--data-directory", str(data2),
            "--netobs",
        ])
        assert rc == 0, f"faulted run exited {rc}"
        arts2 = sorted(data2.glob("NETOBS_*.json"))
        assert arts2, f"no NETOBS_*.json in {data2}"
        rep2 = _check_report(arts2[0])
        drops = rep2["drops_by_cause"]
        assert drops["loss"] > 0, f"no loss drops attributed: {drops}"
        assert drops["codel"] > 0, f"no codel drops attributed: {drops}"
        assert rep2["drop_total"] == sum(drops.values())

        print(
            "netobs-smoke OK: phold "
            f"{rep['totals']['sent']} sent / "
            f"{rep['window_hist']['windows']} windows; faulted drops "
            f"{drops} (artifacts valid, conservation holds)"
        )
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
