"""shadowlint (shadow_tpu.analysis): per-rule fixtures, jaxpr auditor on
planted-hazard toy kernels, baseline semantics, and CLI exit codes.

Each SL1xx rule gets a positive fixture (must flag) and a negative
fixture (the sanctioned spelling must NOT flag) — the linter's contract
is both halves.  The jaxpr tests plant deliberate hazards (an f64 leak,
an unstable sort, a host callback, a float reduction) in toy kernels and
assert the auditor sees them, plus a clean kernel as the negative.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from shadow_tpu.analysis import lint_source
from shadow_tpu.analysis.baseline import (
    Baseline,
    BaselineError,
    load_baseline,
    write_baseline,
)
from shadow_tpu.analysis.cli import main as cli_main
from shadow_tpu.analysis.findings import RULES, Finding
from shadow_tpu.analysis.jaxpr_audit import audit_jaxpr

pytestmark = pytest.mark.analysis

ENGINE = "engine/mod.py"  # ordering-sensitive + step-path scope
UTILS = "utils/mod.py"  # neither


def rules_of(findings):
    return {f.rule for f in findings}


def test_obs_turns_module_is_in_lint_scope():
    """The device-turn ledger (shadow_tpu/obs/turns.py) sits under both
    shadowlint scopes: SL103-style ordering rules and the SL101/SL106
    step-path rules apply to it from day one, exactly like the rest of
    shadow_tpu/obs/ (docs/analysis.md)."""
    from shadow_tpu.analysis.astlint import _module_flags

    ordering, step = _module_flags("shadow_tpu/obs/turns.py")
    assert ordering and step
    # and an in-scope hazard planted in that path is actually flagged
    src = "import time\n\ndef run_window(self):\n    return time.time()\n"
    assert rules_of(lint_source(src, "shadow_tpu/obs/turns.py")) == {"SL101"}


# -- SL101: wall-clock reads -------------------------------------------------


def test_sl101_flags_time_time():
    src = "import time\n\ndef f():\n    return time.time()\n"
    assert rules_of(lint_source(src, UTILS)) == {"SL101"}


def test_sl101_flags_from_import_and_datetime():
    src = (
        "from time import perf_counter\n"
        "from datetime import datetime\n"
        "def f():\n"
        "    return perf_counter() + datetime.now().timestamp()\n"
    )
    found = lint_source(src, UTILS)
    assert [f.rule for f in found] == ["SL101", "SL101"]


def test_sl101_allows_wall_time_alias():
    src = (
        "import time as wall_time\n"
        "def bench():\n"
        "    return wall_time.perf_counter()\n"
    )
    assert lint_source(src, ENGINE) == []


def test_sl101_allows_sim_time_module():
    src = (
        "from ..core import time as stime\n"
        "def f():\n"
        "    return stime.fmt(0)\n"
    )
    assert lint_source(src, ENGINE) == []


# -- SL102: unseeded global RNG ---------------------------------------------


def test_sl102_flags_global_random():
    src = "import random\n\ndef f():\n    return random.randint(0, 9)\n"
    assert rules_of(lint_source(src, UTILS)) == {"SL102"}


def test_sl102_flags_np_random_and_unseeded_default_rng():
    src = (
        "import numpy as np\n"
        "def f():\n"
        "    np.random.seed(0)\n"
        "    g = np.random.default_rng()\n"
        "    return np.random.uniform()\n"
    )
    found = lint_source(src, UTILS)
    assert [f.rule for f in found] == ["SL102"] * 3


def test_sl102_allows_seeded_instances():
    src = (
        "import random\n"
        "import numpy as np\n"
        "def f(seed):\n"
        "    r = random.Random(seed)\n"
        "    g = np.random.default_rng(seed)\n"
        "    return r.random() + g.uniform()\n"
    )
    assert lint_source(src, UTILS) == []


# -- SL103: unordered set iteration -----------------------------------------


def test_sl103_flags_set_iteration_in_ordering_sensitive_module():
    src = "def f(xs):\n    s = set(xs)\n    for x in s:\n        yield x\n"
    assert rules_of(lint_source(src, ENGINE)) == {"SL103"}


def test_sl103_flags_list_of_set_and_comprehension():
    src = (
        "def f(a, b):\n"
        "    out = list(a | set(b))\n"
        "    return [x for x in frozenset(b)], out\n"
    )
    found = lint_source(src, ENGINE)
    assert [f.rule for f in found] == ["SL103", "SL103"]


def test_sl103_allows_sorted_wrapper_and_order_free_consumers():
    src = (
        "def f(xs, s):\n"
        "    for x in sorted(set(xs)):\n"
        "        yield x\n"
        "    n = len(set(xs))\n"
        "    ok = all(x > 0 for x in set(xs))\n"
        "    lo = min(set(xs))\n"
    )
    assert lint_source(src, ENGINE) == []


def test_sl103_not_applied_outside_ordering_sensitive_modules():
    src = "def f(xs):\n    for x in set(xs):\n        yield x\n"
    assert lint_source(src, UTILS) == []


# -- SL104: id()/hash() ordering --------------------------------------------


def test_sl104_flags_id_sort_key_and_comparison():
    src = (
        "def f(xs, a, b):\n"
        "    xs.sort(key=id)\n"
        "    ys = sorted(xs, key=lambda v: hash(v))\n"
        "    return id(a) < id(b)\n"
    )
    found = lint_source(src, UTILS)
    assert [f.rule for f in found] == ["SL104"] * 3


def test_sl104_allows_value_keys():
    src = "def f(xs):\n    return sorted(xs, key=lambda v: v.name)\n"
    assert lint_source(src, UTILS) == []


# -- SL105: float accumulation ----------------------------------------------


def test_sl105_flags_float_sum_in_ordering_sensitive_module():
    src = "def f(xs):\n    return sum(x / 2 for x in xs)\n"
    assert rules_of(lint_source(src, ENGINE)) == {"SL105"}


def test_sl105_allows_fsum_and_integer_sum():
    src = (
        "from ..core.reduce import fsum\n"
        "def f(xs, ns):\n"
        "    return fsum(x / 2 for x in xs) + sum(n for n in ns)\n"
    )
    assert lint_source(src, ENGINE) == []


# -- SL106: env/filesystem in step paths ------------------------------------


def test_sl106_flags_environ_and_open_in_step_path():
    src = (
        "import os\n"
        "def run_window(self):\n"
        "    mode = os.environ.get('MODE')\n"
        "    data = open('f').read()\n"
        "    return mode, data\n"
    )
    found = lint_source(src, ENGINE)
    assert [f.rule for f in found] == ["SL106"] * 2


def test_sl106_flags_from_import_environ_spelling():
    """`from os import environ` makes every use a bare Name — the
    attribute-chain check alone never sees it."""
    src = (
        "from os import environ\n"
        "def run_window(self):\n"
        "    a = environ.get('MODE')\n"
        "    b = environ['MODE']\n"
        "    return a, b\n"
    )
    found = lint_source(src, ENGINE)
    assert [f.rule for f in found] == ["SL106"] * 2


def test_sl106_allows_setup_scope_and_non_step_modules():
    engine_setup = (
        "import os\n"
        "def __init__(self):\n"
        "    self.mode = os.environ.get('MODE')\n"
    )
    assert lint_source(engine_setup, ENGINE) == []
    step_named_elsewhere = (
        "import os\n"
        "def run_window(self):\n"
        "    return os.environ.get('MODE')\n"
    )
    assert lint_source(step_named_elsewhere, UTILS) == []


# -- inline suppressions -----------------------------------------------------


def test_inline_suppression_same_line_and_line_above():
    src = (
        "import time\n"
        "def f():\n"
        "    a = time.time()  # shadowlint: disable=SL101\n"
        "    # wall deadline for hung children, not sim time\n"
        "    # shadowlint: disable=SL101\n"
        "    b = time.time()\n"
        "    c = time.time()\n"
        "    return a + b + c\n"
    )
    found = lint_source(src, UTILS)
    assert [f.line for f in found] == [7]


def test_suppression_is_rule_specific():
    src = (
        "import time\n"
        "def f():\n"
        "    return time.time()  # shadowlint: disable=SL102\n"
    )
    assert rules_of(lint_source(src, UTILS)) == {"SL101"}


# -- jaxpr auditor on planted-hazard toy kernels -----------------------------


def _jaxpr_of(fn, *args):
    import jax

    return jax.make_jaxpr(fn)(*args)


def test_jaxpr_flags_planted_f64_leak():
    import jax.numpy as jnp

    def kernel(x):  # x: i64 lane clock — 0.5 leaks a weak f64 in x64 mode
        return x * 0.5

    found = audit_jaxpr(
        _jaxpr_of(kernel, jnp.arange(8, dtype=jnp.int64)), "kernel:toy/f64"
    )
    assert "SL201" in rules_of(found)


def test_jaxpr_flags_unstable_sort_and_accepts_stable():
    import jax.numpy as jnp
    from jax import lax

    x = jnp.arange(8, dtype=jnp.int32)

    def unstable(x):
        return lax.sort((x, x), dimension=0, num_keys=1, is_stable=False)

    def stable(x):
        return lax.sort((x, x), dimension=0, num_keys=1, is_stable=True)

    assert "SL203" in rules_of(audit_jaxpr(_jaxpr_of(unstable, x), "k:u"))
    assert "SL203" not in rules_of(audit_jaxpr(_jaxpr_of(stable, x), "k:s"))


def test_jaxpr_flags_host_callback():
    import jax
    import jax.numpy as jnp

    def kernel(x):
        jax.debug.callback(lambda v: None, x)
        return x + 1

    found = audit_jaxpr(
        _jaxpr_of(kernel, jnp.int32(1)), "kernel:toy/callback"
    )
    assert "SL204" in rules_of(found)


def test_jaxpr_flags_float_reduction_not_integer():
    import jax.numpy as jnp

    def float_red(x):
        return jnp.cumsum(x)

    fx = jnp.zeros(8, dtype=jnp.float32)
    ix = jnp.zeros(8, dtype=jnp.int32)
    assert "SL205" in rules_of(audit_jaxpr(_jaxpr_of(float_red, fx), "k:f"))
    assert "SL205" not in rules_of(audit_jaxpr(_jaxpr_of(float_red, ix), "k:i"))


def test_jaxpr_duplicate_signatures_get_distinct_fingerprints():
    """Mirrors the AST pass's occurrence numbering: a SECOND equation
    with an identical primitive/signature is its own hazard and may not
    ride the first one's baseline entry."""
    import jax.numpy as jnp
    from jax import lax

    def kernel(x):
        a = lax.sort(x, is_stable=False)
        return lax.sort(a + 1, is_stable=False)

    found = audit_jaxpr(
        _jaxpr_of(kernel, jnp.zeros(8, jnp.int32)), "kernel:toy/dup"
    )
    sl203 = [f for f in found if f.rule == "SL203"]
    assert len(sl203) == 2
    assert sl203[0].fingerprint != sl203[1].fingerprint


def test_jaxpr_descends_into_while_and_cond():
    import jax.numpy as jnp
    from jax import lax

    def kernel(x):
        def body(c):
            return c * 0.5  # f64 leak inside the while body

        return lax.while_loop(lambda c: c > 1, body, x * 1.0)

    found = audit_jaxpr(
        _jaxpr_of(kernel, jnp.int64(64)), "kernel:toy/while"
    )
    assert "SL201" in rules_of(found)


# -- baseline semantics ------------------------------------------------------


def _finding(rule="SL101", path="m.py", detail="x = time.time()"):
    return Finding(rule=rule, path=path, line=3, col=0,
                   message="msg", detail=detail)


def test_baseline_roundtrip_requires_justification(tmp_path):
    bl = tmp_path / "baseline.json"
    write_baseline(bl, [_finding()])
    with pytest.raises(BaselineError, match="not justified"):
        load_baseline(bl)
    data = json.loads(bl.read_text())
    data["suppressions"][0]["reason"] = "wall deadline, not sim time"
    bl.write_text(json.dumps(data))
    baseline = load_baseline(bl)
    assert baseline.suppresses(_finding())
    assert baseline.stale_entries() == []


def test_baseline_reports_stale_entries(tmp_path):
    bl = tmp_path / "baseline.json"
    write_baseline(bl, [_finding(detail="gone()")])
    data = json.loads(bl.read_text())
    data["suppressions"][0]["reason"] = "justified"
    bl.write_text(json.dumps(data))
    baseline = load_baseline(bl)
    assert not baseline.suppresses(_finding(detail="still here"))
    assert len(baseline.stale_entries()) == 1


def test_baseline_fingerprint_survives_line_moves():
    a = _finding()
    b = Finding(rule="SL101", path="m.py", line=99, col=4,
                message="msg", detail="x = time.time()")
    assert a.fingerprint == b.fingerprint


def test_baseline_rejects_unknown_rule_and_bad_version(tmp_path):
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({"version": 99, "suppressions": []}))
    with pytest.raises(BaselineError, match="version"):
        load_baseline(bl)
    bl.write_text(
        json.dumps(
            {
                "version": 1,
                "suppressions": [
                    {"fingerprint": "ab", "rule": "SL999",
                     "path": "x", "reason": "r"}
                ],
            }
        )
    )
    with pytest.raises(BaselineError, match="unknown rule"):
        load_baseline(bl)


# -- CLI ---------------------------------------------------------------------


def _write_pkg(tmp_path, body):
    mod = tmp_path / "engine"
    mod.mkdir()
    f = mod / "step.py"
    f.write_text(body)
    return mod


def test_cli_exit_codes(tmp_path):
    dirty = _write_pkg(tmp_path, "import time\nt = time.time()\n")
    empty_bl = tmp_path / "bl.json"
    # findings -> 1
    assert cli_main(
        [str(dirty), "--no-jaxpr", "--baseline", str(empty_bl)]
    ) == 1
    # clean tree -> 0
    clean = tmp_path / "clean"
    clean.mkdir()
    (clean / "ok.py").write_text("x = 1\n")
    assert cli_main(
        [str(clean), "--no-jaxpr", "--baseline", str(empty_bl)]
    ) == 0
    # malformed baseline -> 2
    bad_bl = tmp_path / "bad.json"
    bad_bl.write_text("{nope")
    assert cli_main(
        [str(clean), "--no-jaxpr", "--baseline", str(bad_bl)]
    ) == 2


def test_cli_list_rules_covers_registry(capsys):
    assert cli_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in RULES:
        assert rule in out


def test_cli_json_format(tmp_path):
    dirty = _write_pkg(tmp_path, "import time\nt = time.time()\n")
    bl = tmp_path / "bl.json"
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = cli_main(
            [str(dirty), "--no-jaxpr", "--baseline", str(bl),
             "--format", "json"]
        )
    assert rc == 1
    data = json.loads(buf.getvalue())
    assert data["findings"][0]["rule"] == "SL101"


def test_write_baseline_preserves_existing_justifications(tmp_path):
    """Regenerating the baseline to add a finding must not reset the
    hand-written reasons of existing entries to TODO."""
    bl = tmp_path / "baseline.json"
    old = _finding(detail="x = time.time()")
    write_baseline(bl, [old])
    data = json.loads(bl.read_text())
    data["suppressions"][0]["reason"] = "bench wall deadline, not sim time"
    bl.write_text(json.dumps(data))
    new = _finding(rule="SL104", detail="sorted(xs, key=id)")
    write_baseline(bl, [old, new])
    reasons = {
        e["fingerprint"]: e["reason"]
        for e in json.loads(bl.read_text())["suppressions"]
    }
    assert reasons[old.fingerprint] == "bench wall deadline, not sim time"
    assert reasons[new.fingerprint] == "TODO: justify"


def test_sl103_allows_every_sorted_spelling():
    """sorted() is the prescribed remediation — none of its spellings
    may themselves be flagged."""
    src = (
        "def f(xs):\n"
        "    s = set(xs)\n"
        "    a = sorted(s)\n"
        "    b = sorted(x for x in s)\n"
        "    c = sorted(list(s))\n"
        "    d = sorted([x for x in s])\n"
        "    return a, b, c, d\n"
    )
    assert rules_of(lint_source(src, "engine/x.py")) == set()
    # the fixture is live: the unwrapped spellings DO fire
    bad = "def f(xs):\n    s = set(xs)\n    return [x for x in s]\n"
    assert "SL103" in rules_of(lint_source(bad, "engine/x.py"))


def test_cli_unknown_kernel_is_usage_error(tmp_path, capsys, monkeypatch):
    """Exit-code contract: 1 is reserved for findings; a typo'd --kernel
    is tool misuse and must exit 2 — before paying for the AST walk."""
    import shadow_tpu.analysis.cli as cli_mod

    def boom(*a, **k):
        raise AssertionError("AST walk ran before --kernel validation")

    monkeypatch.setattr(cli_mod, "lint_paths", boom)
    with pytest.raises(SystemExit) as exc:
        cli_main(["--kernel", "nope", "--baseline",
                  str(tmp_path / "bl.json")])
    assert exc.value.code == 2
    assert "unknown kernel" in capsys.readouterr().err


def test_duplicate_identical_hazard_lines_get_distinct_fingerprints():
    """A second textually identical hazard line must get its own
    fingerprint, so it cannot ride an existing baseline entry through
    the gate."""
    src = (
        "import time\n"
        "def f():\n"
        "    t = time.time()\n"
        "    return t\n"
        "def g():\n"
        "    t = time.time()\n"
        "    return t\n"
    )
    found = lint_source(src, "m.py")
    assert [f.rule for f in found] == ["SL101", "SL101"]
    assert found[0].fingerprint != found[1].fingerprint
    bl = Baseline(path=Path("x"), suppressions={
        found[0].fingerprint: {"rule": "SL101"},
    })
    assert bl.suppresses(found[0])
    assert not bl.suppresses(found[1])


def test_write_baseline_scoped_run_keeps_out_of_scope_entries(tmp_path):
    """A --no-jaxpr / explicit-path --write-baseline never audited the
    kernels, so their justified entries must survive verbatim."""
    bl = tmp_path / "baseline.json"
    kernel = Finding(rule="SL203", path="kernel:phold/round", line=0,
                     col=0, message="unstable sort", detail="sort(...)")
    write_baseline(bl, [kernel])
    data = json.loads(bl.read_text())
    data["suppressions"][0]["reason"] = "4-word total event key"
    bl.write_text(json.dumps(data))
    ast_f = _finding()
    write_baseline(bl, [ast_f], audited_paths={"m.py"})
    entries = {e["fingerprint"]: e
               for e in json.loads(bl.read_text())["suppressions"]}
    assert entries[kernel.fingerprint]["reason"] == "4-word total event key"
    assert entries[ast_f.fingerprint]["reason"] == "TODO: justify"


def test_cli_explicit_paths_skip_kernel_traces(tmp_path, monkeypatch):
    """An on-the-diff lint of explicit paths must not pay for the
    engine builds + kernel traces of pass 2 (unless --kernel asks)."""
    import shadow_tpu.analysis.jaxpr_audit as ja

    def boom(*a, **k):  # pass 2 entry — must not be reached
        raise AssertionError("kernel tracing ran for an explicit-path lint")

    monkeypatch.setattr(ja, "audit_kernels", boom)
    clean = tmp_path / "ok.py"
    clean.write_text("x = 1\n")
    assert cli_main([str(clean), "--baseline", str(tmp_path / "bl.json")]) == 0


def test_cli_missing_path_and_conflicting_flags_are_usage_errors(
    tmp_path, capsys
):
    """A typo'd path would lint nothing and pass green; --no-jaxpr with
    --kernel would silently skip the requested audit.  Both are usage
    errors (exit 2), reported before any lint work."""
    bl = str(tmp_path / "bl.json")
    with pytest.raises(SystemExit) as exc:
        cli_main([str(tmp_path / "nope.py"), "--baseline", bl])
    assert exc.value.code == 2
    assert "no such path" in capsys.readouterr().err
    with pytest.raises(SystemExit) as exc:
        cli_main(["--no-jaxpr", "--kernel", "phold", "--baseline", bl])
    assert exc.value.code == 2
    assert "--no-jaxpr" in capsys.readouterr().err


def test_write_baseline_refuses_unreadable_existing_file(tmp_path):
    """Regenerating over a mangled baseline (merge-conflict markers,
    truncation) must refuse, not silently replace the hand-written
    justifications with TODOs."""
    bl = tmp_path / "baseline.json"
    bl.write_text('{"version": 1, <<<<<<< HEAD')
    with pytest.raises(BaselineError, match="unreadable"):
        write_baseline(bl, [_finding()])
    assert bl.read_text() == '{"version": 1, <<<<<<< HEAD'  # untouched


def test_write_baseline_without_scope_never_drops_old_entries(tmp_path):
    """A caller that doesn't say what it audited may not drop anything —
    old entries it didn't re-find survive verbatim."""
    bl = tmp_path / "baseline.json"
    kernel = Finding(rule="SL203", path="kernel:phold/round", line=0,
                     col=0, message="unstable sort", detail="sort(...)")
    write_baseline(bl, [kernel])
    data = json.loads(bl.read_text())
    data["suppressions"][0]["reason"] = "total event key"
    bl.write_text(json.dumps(data))
    write_baseline(bl, [_finding()])  # no audited_paths
    entries = {e["fingerprint"]: e
               for e in json.loads(bl.read_text())["suppressions"]}
    assert entries[kernel.fingerprint]["reason"] == "total event key"


def test_stale_scope_covers_deleted_files_and_removed_kernels(tmp_path):
    """_augment_audited: a full run claims scope over every baseline
    entry — deleted files always, kernel:* entries when pass 2 ran
    unfiltered — so orphaned suppressions go stale instead of living
    forever.  Scoped runs claim nothing extra."""
    import argparse

    from shadow_tpu.analysis.cli import _augment_audited

    entries = {
        "aa": {"path": "shadow_tpu/engine/__deleted__.py"},
        "bb": {"path": "kernel:ghost/round"},
    }
    bl = Baseline(path=Path("x"), suppressions=entries)

    def ns(paths=(), no_jaxpr=False, kernel=None):
        return argparse.Namespace(
            paths=list(paths), no_jaxpr=no_jaxpr, kernel=kernel
        )

    full = _augment_audited(ns(), bl, {"kernel:phold/round"})
    assert "shadow_tpu/engine/__deleted__.py" in full
    assert "kernel:ghost/round" in full
    ast_only = _augment_audited(ns(no_jaxpr=True), bl, set())
    assert "shadow_tpu/engine/__deleted__.py" in ast_only
    assert "kernel:ghost/round" not in ast_only  # kernels not audited
    scoped = _augment_audited(ns(paths=["shadow_tpu/engine"]), bl, set())
    assert scoped == set()  # explicit paths claim nothing extra


def test_inline_suppressing_first_duplicate_keeps_second_fingerprint():
    """Occurrence numbering runs before inline-suppression filtering:
    suppressing the first of two identical hazard lines must not shift
    the survivor's fingerprint (its baseline entry stays valid)."""
    line = "    t = time.time()\n"
    src = "import time\ndef f():\n" + line + "def g():\n" + line
    both = lint_source(src, "m.py")
    assert len(both) == 2
    suppressed_first = src.replace(
        line, "    t = time.time()  # shadowlint: disable=SL101\n", 1
    )
    [survivor] = lint_source(suppressed_first, "m.py")
    assert survivor.fingerprint == both[1].fingerprint


def test_cli_default_run_flags_baseline_entry_for_deleted_file(
    tmp_path, capsys
):
    """A default whole-package run audits the whole namespace: a
    baseline entry for a since-deleted file must be reported stale, not
    silently skipped because the file no longer enumerates."""
    bl = tmp_path / "bl.json"
    gone = Finding(rule="SL101", path="shadow_tpu/engine/__deleted__.py",
                   line=3, col=0, message="m", detail="t = time.time()")
    write_baseline(bl, [gone])
    data = json.loads(bl.read_text())
    data["suppressions"][0]["reason"] = "was justified once"
    bl.write_text(json.dumps(data))
    assert cli_main(["--no-jaxpr", "--baseline", str(bl)]) == 1
    assert "stale suppression" in capsys.readouterr().out


def test_cli_in_repo_paths_keep_repo_relative_scope(tmp_path):
    """An explicit CLI path inside the repo must keep its repo-relative
    prefix, so scope-dependent rules (SL103/SL105/SL106) and baseline
    fingerprints match the default whole-package run (regression: a bare
    `shadow_tpu/engine/foo.py` argument used to lint as `foo.py`,
    silently dropping the ordering-sensitive scope)."""
    from shadow_tpu.analysis.astlint import _module_flags, module_paths
    from shadow_tpu.analysis.cli import PACKAGE_ROOT, _rel_base

    eng = PACKAGE_ROOT / "engine"
    base = _rel_base(eng)
    assert base == PACKAGE_ROOT.parent
    rels = [rel for _, rel in module_paths(eng.resolve(), base)]
    assert rels and all(r.startswith("shadow_tpu/engine/") for r in rels)
    assert all(_module_flags(r) == (True, True) for r in rels)
    # a single in-repo FILE keeps the prefix too
    one = PACKAGE_ROOT / "engine" / "sim.py"
    [(_, rel)] = module_paths(one.resolve(), _rel_base(one))
    assert rel == "shadow_tpu/engine/sim.py"
    # outside the repo there is no repo-relative prefix: fall back to the
    # lint root's parent (directory context is still honored)
    out = tmp_path / "engine"
    out.mkdir()
    (out / "bad.py").write_text("pass\n")
    assert _rel_base(out) is None
    [(_, rel)] = module_paths(out)
    assert rel == "engine/bad.py"


@pytest.mark.slow
def test_repo_lint_is_clean():
    """The acceptance gate: the shipped tree + baseline runs clean,
    including the kernel traces (the module-invocation path of
    ``make lint-determinism``)."""
    proc = subprocess.run(
        [sys.executable, "-m", "shadow_tpu.analysis"],
        cwd=Path(__file__).resolve().parents[1],
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_repo_ast_pass_is_clean():
    """Fast tier-1 slice of the gate: the AST pass alone must be clean."""
    assert cli_main(["--no-jaxpr"]) == 0
