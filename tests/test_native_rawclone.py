"""Raw CLONE_VM threads (the Go runtime's newosproc shape) adopted into
turn-taking.

The reference runs Go programs end to end (src/test/golang/: goroutines,
GC, preemption) — those threads are raw clone(CLONE_VM|CLONE_THREAD|...)
from the runtime's own text, NOT pthreads.  No Go toolchain exists in
this image, so the plugin (native/apps/rawthreads.c) reproduces Go's
exact kernel contract: newosproc's flag set, mmap stacks, inline-asm
syscalls, futex join.  The shim adopts such threads via a pthread-backed
context restore (shadow_shim.c: shim_adopt_raw_thread).
"""

import subprocess
from pathlib import Path

import pytest

from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.engine.sim import Simulation

REPO = Path(__file__).resolve().parents[1]
BUILD = REPO / "native" / "build"


@pytest.fixture(scope="module", autouse=True)
def native_build():
    subprocess.run(
        ["make", "-C", str(REPO / "native")], check=True, capture_output=True
    )
    assert (BUILD / "rawthreads").exists()


def _solo_cfg(tmp_path, args, stop="5s", tag="", binary="rawthreads"):
    args_line = f"\n        args: [{args}]" if args else ""
    return ConfigOptions.from_yaml(f"""
general: {{stop_time: {stop}, seed: 7, data_directory: {tmp_path / ('data' + tag)}, heartbeat_interval: null}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  solo:
    network_node_id: 0
    processes:
      - path: {BUILD / binary}{args_line}
""")


def _out(tmp_path, host="solo", tag="", binary="rawthreads"):
    return (tmp_path / ("data" + tag) / "hosts" / host /
            f"{binary}.stdout").read_text()


def test_raw_clone_basic_counter(tmp_path):
    """4 raw CLONE_VM threads x 25 futex-locked increments, with
    mid-flight nanosleeps: no lost updates, all threads complete."""
    result = Simulation(_solo_cfg(tmp_path, "basic, '4'")).run()
    assert "basic counter=100 done=4" in _out(tmp_path)
    assert result.counters["managed_threads"] == 4
    assert result.counters["managed_thread_exits"] == 4


def test_raw_clone_cleartid_join(tmp_path):
    """CLONE_CHILD_SETTID/CLEARTID/PARENT_SETTID: the parent joins by
    futex-waiting the ctid word, which the exit path clears and wakes
    through the EMULATED futex (glibc pthread_join's law)."""
    Simulation(_solo_cfg(tmp_path, "cleartid")).run()
    assert "cleartid joined counter=41 ptid_set=1 tid_match=1" in _out(
        tmp_path
    )


def test_raw_clone_net_pingpong(tmp_path):
    """The Go-HTTP-ping/pong stand-in: raw threads each drive a TCP echo
    round against a real echo server across the simulated network."""
    cfg = ConfigOptions.from_yaml(f"""
general: {{stop_time: 20s, seed: 11, data_directory: {tmp_path / 'data'}, heartbeat_interval: null}}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        node [ id 1 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        edge [ source 0 target 0 latency "1 ms" ]
        edge [ source 0 target 1 latency "10 ms" ]
        edge [ source 1 target 1 latency "1 ms" ]
      ]
hosts:
  gopher:
    network_node_id: 0
    processes:
      - path: {BUILD / 'rawthreads'}
        args: [net, 11.0.0.2, '7000', '3']
        start_time: 200ms
  srv:
    network_node_id: 1
    processes:
      - path: {BUILD / 'tcpecho'}
        args: [server, '7000', '3']
""")
    result = Simulation(cfg).run()
    out = (tmp_path / "data" / "hosts" / "gopher" /
           "rawthreads.stdout").read_text()
    assert "net threads=3 echoed=3072" in out
    assert result.counters["managed_threads"] == 3


def test_raw_clone_determinism(tmp_path):
    """The determinism gate the VERDICT asks for: the raw-thread workload
    twice, bit-identical logs and output."""
    r1 = Simulation(_solo_cfg(tmp_path, "basic, '4'", tag="a")).run()
    o1 = _out(tmp_path, tag="a")
    r2 = Simulation(_solo_cfg(tmp_path, "basic, '4'", tag="b")).run()
    o2 = _out(tmp_path, tag="b")
    assert o1 == o2
    assert r1.log_tuples() == r2.log_tuples()
    assert r1.counters == r2.counters


def test_raw_clone_churn_reclaims(tmp_path):
    """520 create/retire lifetimes (more than the shim's 512-slot thread
    table): slots and backing stacks must be reclaimed on raw SYS_exit,
    or creation starts failing partway."""
    result = Simulation(
        _solo_cfg(tmp_path, "churn, '520'", stop="120s")
    ).run()
    assert "churn counter=520 of 520" in _out(tmp_path)
    assert result.counters["managed_threads"] == 520
    assert result.counters["managed_thread_exits"] == 520


def test_tls_rand_deterministic(tmp_path):
    """OpenSSL's RAND_* (RDRAND-seeded in-process entropy the syscall
    interposition never sees) is overridden at the symbol level — the
    reference's preload-openssl — so TLS-grade randomness is
    deterministic under the simulation."""
    if not (BUILD / "tlsrand").exists():
        pytest.skip("no libcrypto in this image")

    def run(tag):
        Simulation(_solo_cfg(tmp_path, "", stop="1s", tag=tag,
                             binary="tlsrand")).run()
        return _out(tmp_path, tag=tag, binary="tlsrand")

    o1, o2 = run("a"), run("b")
    assert "status=1" in o1 and "rand=" in o1 and "priv=" in o1
    assert o1 == o2, (o1, o2)
    # and it is the SIMULATION's stream, not the library's RDRAND pool:
    # a native (unshimmed) run produces different bytes
    import subprocess as _sp
    native = _sp.run([str(BUILD / "tlsrand")], capture_output=True,
                     text=True).stdout
    assert native.splitlines()[0] != o1.splitlines()[0]
