"""Per-host pcap capture (utility/pcap_writer.rs / interface.rs analog)."""

import struct
import subprocess
from pathlib import Path

import pytest

from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.engine.sim import Simulation

REPO = Path(__file__).resolve().parents[1]


def _parse_pcap(path: Path):
    raw = path.read_bytes()
    magic, vmaj, vmin, _tz, _sf, snaplen, linktype = struct.unpack(
        ">IHHiIII", raw[:24]
    )
    assert magic == 0xA1B2C3D4
    assert (vmaj, vmin) == (2, 4)
    assert linktype == 228  # LINKTYPE_IPV4
    off = 24
    records = []
    while off < len(raw):
        ts_s, ts_us, incl, orig = struct.unpack(">IIII", raw[off : off + 16])
        off += 16
        pkt = raw[off : off + incl]
        off += incl
        records.append((ts_s, ts_us, incl, orig, pkt))
    return snaplen, records


def test_model_traffic_pcap(tmp_path):
    cfg = ConfigOptions.from_yaml(
        f"""
general: {{stop_time: 1s, seed: 6, data_directory: {tmp_path / 'data'}, heartbeat_interval: null}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  a:
    network_node_id: 0
    pcap_enabled: true
    processes: [{{path: ping, args: [--peer, b, --count, "3", --interval, 100ms]}}]
  b:
    network_node_id: 0
    pcap_enabled: true
    processes: [{{path: ping}}]
"""
    )
    Simulation(cfg).run()
    snaplen, recs = _parse_pcap(tmp_path / "data" / "hosts" / "a" / "eth0.pcap")
    # a sends 3 requests (outbound) and receives 3 echoes (inbound)
    assert len(recs) == 6
    ts_s = recs[0][0]
    assert ts_s >= 946684800  # emulated epoch 2000-01-01
    # IPv4 header: proto experimental for model traffic, src/dst = 11.0.0.x
    pkt = recs[0][4]
    assert pkt[0] == 0x45
    assert pkt[9] == 253
    assert pkt[12:15] == bytes([11, 0, 0])


def test_tcp_pcap_has_real_headers_and_payload(tmp_path):
    subprocess.run(
        ["make", "-C", str(REPO / "native")], check=True, capture_output=True
    )
    build = REPO / "native" / "build"
    cfg = ConfigOptions.from_yaml(
        f"""
general: {{stop_time: 10s, seed: 6, data_directory: {tmp_path / 'data'}, heartbeat_interval: null}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  cli:
    network_node_id: 0
    processes:
      - path: {build / 'tcpecho'}
        args: [client, 11.0.0.2, "7000", "2", "700", "5"]
        start_time: 100ms
  srv:
    network_node_id: 0
    pcap_enabled: true
    processes:
      - path: {build / 'tcpecho'}
        args: [server, "7000", "1"]
"""
    )
    Simulation(cfg).run()
    _, recs = _parse_pcap(tmp_path / "data" / "hosts" / "srv" / "eth0.pcap")
    assert len(recs) > 6  # handshake + data + acks + teardown, both directions
    protos = {pkt[9] for *_m, pkt in recs}
    assert protos == {6}  # all TCP
    # find a SYN (flags byte offset: 20 ip + 13)
    flags = [pkt[20 + 13] for *_m, pkt in recs]
    assert any(f == 0x02 for f in flags)  # SYN
    assert any(f & 0x10 for f in flags)  # ACKs
    assert any(f & 0x01 for f in flags)  # FIN
    # a data segment carries the client's 0xA5 fill bytes
    assert any(pkt[40:41] == b"\xa5" for *_m, pkt in recs)


def test_pcap_snaplen_truncates(tmp_path):
    cfg = ConfigOptions.from_yaml(
        f"""
general: {{stop_time: 1s, seed: 6, data_directory: {tmp_path / 'data'}, heartbeat_interval: null}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  a:
    network_node_id: 0
    pcap_enabled: true
    pcap_capture_size: 64
    processes: [{{path: tgen-client, args: [--server, b, --interval, 200ms, --size, "5000"]}}]
  b: {{network_node_id: 0}}
"""
    )
    Simulation(cfg).run()
    snaplen, recs = _parse_pcap(tmp_path / "data" / "hosts" / "a" / "eth0.pcap")
    assert snaplen == 64
    assert all(incl <= 64 for _s, _u, incl, _o, _p in recs)
    assert any(orig == 5000 for _s, _u, _incl, orig, _p in recs)


def test_pcap_rejected_without_device_log(tmp_path):
    # lane pcap rides the device event log: log_capacity=0 cannot carry it
    from shadow_tpu.backend.tpu_engine import LaneCompatError, TpuEngine

    cfg = ConfigOptions.from_yaml(
        f"""
general: {{stop_time: 1s, data_directory: {tmp_path / 'data'}}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  a: {{network_node_id: 0, pcap_enabled: true, processes: [{{path: phold}}]}}
"""
    )
    with pytest.raises(LaneCompatError, match="pcap"):
        TpuEngine(cfg, log_capacity=0)


def test_lane_backend_pcap_matches_cpu(tmp_path):
    """Lane-backend pcap readback (round-2 LaneCompatError lifted): the
    device log's PCAP_TX + DELIVERED records reconstruct per-host capture
    files byte-identical to the CPU backend's."""
    from shadow_tpu.backend.tpu_engine import TpuEngine

    def yaml(tag):
        return f"""
general: {{stop_time: 300ms, seed: 6, data_directory: {tmp_path / tag}}}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "50 Mbit" host_bandwidth_down "50 Mbit" ]
        edge [ source 0 target 0 latency "4 ms" ]
      ]
hosts:
  capt:
    network_node_id: 0
    pcap_enabled: true
    processes: [{{path: tgen-client, args: [--server, sink, --interval, 9ms, --size, "600"]}}]
  sink:
    network_node_id: 0
    pcap_enabled: true
    processes: [{{path: tgen-server}}]
  other:
    network_node_id: 0
    processes: [{{path: tgen-mesh, args: [--interval, 11ms, --size, "300"]}}]
"""

    from shadow_tpu.backend.cpu_engine import CpuEngine

    cpu = CpuEngine(ConfigOptions.from_yaml(yaml("cpu")))
    cpu.run()
    tpu = TpuEngine(ConfigOptions.from_yaml(yaml("tpu")))
    tpu.run(mode="device")
    for host in ("capt", "sink"):
        a = (tmp_path / "cpu" / "hosts" / host / "eth0.pcap").read_bytes()
        b = (tmp_path / "tpu" / "hosts" / host / "eth0.pcap").read_bytes()
        assert len(a) > 100
        assert a == b, f"{host} pcap differs between backends"
    assert not (tmp_path / "tpu" / "hosts" / "other").exists() or not (
        tmp_path / "tpu" / "hosts" / "other" / "eth0.pcap"
    ).exists()


def test_pcap_spill_chunks_byte_identical(tmp_path):
    """The bounded-memory spill path (sorted chunks + external merge)
    writes byte-identical output to the all-in-RAM sort."""
    from shadow_tpu.utils.pcap import PcapWriter

    def write(path, spill_bytes):
        w = PcapWriter(path, snaplen=256)
        if spill_bytes:
            w.spill_bytes = spill_bytes
        # deliberately out of order, with timestamp ties broken by key
        for i in range(500):
            t = ((i * 7919) % 100) * 1_000_000
            w.capture(t, "11.0.0.1", "11.0.0.2", 200 + (i % 3),
                      (1000, 2000, b"x" * (i % 50)),
                      key=(i % 2, 1, 2, i))
        w.close()
        return path.read_bytes()

    plain = write(tmp_path / "plain.pcap", 0)
    spilled = write(tmp_path / "spill.pcap", 2048)  # many tiny chunks
    assert len(plain) > 1000
    assert plain == spilled


def test_stream_tier_pcap_matches_cpu(tmp_path):
    """pcap with stream (lane-TCP) flows: outbound captures ride the
    compacted stream channels at bucket-departure time; both backends
    synthesize stream bodies from sizes alone, so the files are
    byte-identical."""
    from shadow_tpu.backend.cpu_engine import CpuEngine
    from shadow_tpu.backend.tpu_engine import TpuEngine

    def yaml(sub):
        return f"""
general: {{stop_time: 4s, seed: 9, data_directory: {tmp_path / sub}}}
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0  host_bandwidth_up "40 Mbit"  host_bandwidth_down "40 Mbit" ]
        edge [ source 0  target 0  latency "6 ms" ]
      ]
experimental: {{tpu_lane_queue_capacity: 48}}
hosts:
  capc:
    network_node_id: 0
    pcap_enabled: true
    processes: [{{path: stream-client, args: [--server, caps, --size, "200000"]}}]
  caps:
    network_node_id: 0
    pcap_enabled: true
    processes: [{{path: stream-server}}]
  other:
    network_node_id: 0
    processes: [{{path: tgen-mesh, args: [--interval, 9ms, --size, "400"]}}]
"""

    cpu = CpuEngine(ConfigOptions.from_yaml(yaml("cpu")))
    rc = cpu.run()
    tpu = TpuEngine(ConfigOptions.from_yaml(yaml("tpu")))
    rt = tpu.run(mode="device")
    assert rt.log_tuples() == rc.log_tuples()
    assert rt.counters.get("stream_flows_done") == 1
    for host in ("capc", "caps"):
        a = (tmp_path / "cpu" / "hosts" / host / "eth0.pcap").read_bytes()
        b = (tmp_path / "tpu" / "hosts" / host / "eth0.pcap").read_bytes()
        assert len(a) > 1000
        assert a == b, f"{host} pcap differs between backends"
