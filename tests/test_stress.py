"""The determinism STRESS gate (the reference's determinism suite run as a
regression hammer, src/test/determinism/CMakeLists.txt:1-45): repeat the
raciest workloads — fork trees, pthreads, real-software HTTP over the
simulated TCP stack — many times and require bit-identical results on
every repetition.  Any unsynchronized ordering in the futex channels, the
scheduler, or the engine shows up as a diff.

Skipped by default (minutes of wall time); the gate is ONE command:

    SHADOW_TPU_STRESS=1 python -m pytest tests/test_stress.py -q

``SHADOW_TPU_STRESS_REPEATS`` overrides the repetition count (default 20).
"""

import os
import subprocess
from pathlib import Path

import pytest

from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.engine.determinism import determinism_check

REPO = Path(__file__).resolve().parents[1]
BUILD = REPO / "native" / "build"

pytestmark = pytest.mark.skipif(
    not os.environ.get("SHADOW_TPU_STRESS"),
    reason="stress gate: set SHADOW_TPU_STRESS=1 to run",
)

REPEATS = int(os.environ.get("SHADOW_TPU_STRESS_REPEATS", "20"))


@pytest.fixture(scope="module", autouse=True)
def native_build():
    subprocess.run(
        ["make", "-C", str(REPO / "native")], check=True, capture_output=True
    )


def _repeat_identical(yaml: str) -> None:
    first = None
    for i in range(REPEATS):
        report = determinism_check(ConfigOptions.from_yaml(yaml))
        assert report.identical, f"repeat {i}: {report.describe()}"
        if first is None:
            first = report
    assert first is not None


def test_stress_fork_tree(tmp_path):
    _repeat_identical(
        f"""
general: {{stop_time: 30s, seed: 11, data_directory: {tmp_path / 'd'}, heartbeat_interval: null}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  h:
    network_node_id: 0
    processes:
      - path: {BUILD / 'forker'}
        args: ["2", "300"]
"""
    )


def test_stress_threads(tmp_path):
    _repeat_identical(
        f"""
general: {{stop_time: 60s, seed: 5, data_directory: {tmp_path / 'd'}, heartbeat_interval: null}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  h:
    network_node_id: 0
    processes:
      - path: {BUILD / 'threads'}
"""
    )


def test_stress_thread_churn(tmp_path):
    """128 glibc threads in create/join/detach waves with SIGUSR1s in
    flight (the pthread-layer stand-in for the reference's Go gate,
    src/test/golang/): REPEATS identical runs."""
    _repeat_identical(
        f"""
general: {{stop_time: 60s, seed: 5, data_directory: {tmp_path / 'd'}, heartbeat_interval: null}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  h:
    network_node_id: 0
    processes:
      - path: {BUILD / 'threads'}
        args: [churn, "8", "16"]
"""
    )


def test_stress_unix_sockets(tmp_path):
    """Unix-domain IPC ordering (socket/unix.rs analog): the bytes ride a
    native socketpair, but blocking order is engine-scheduled (sim-yield
    polls under strict turn-taking) — REPEATS runs must be identical."""
    _repeat_identical(
        f"""
general: {{stop_time: 10s, seed: 8, data_directory: {tmp_path / 'd'}, heartbeat_interval: null}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  h:
    network_node_id: 0
    processes:
      - path: {BUILD / 'unixchat'}
"""
    )


def test_stress_signals(tmp_path):
    _repeat_identical(
        f"""
general: {{stop_time: 100s, seed: 3, data_directory: {tmp_path / 'd'}, heartbeat_interval: null}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  solo:
    network_node_id: 0
    processes:
      - path: {BUILD / 'sigdemo'}
"""
    )


def test_stress_real_http(tmp_path):
    """The real-software pair (CPython http.server + curl) run-to-run,
    REPEATS times: byte-identical client output every time."""
    import shutil
    import sys

    curl = shutil.which("curl")
    if curl is None:
        pytest.skip("curl not installed")
    py = "/usr/bin/python3" if Path("/usr/bin/python3").exists() else sys.executable
    from shadow_tpu.engine.sim import Simulation

    docroot = tmp_path / "www"
    docroot.mkdir()
    (docroot / "x.txt").write_text("stress\n")
    os.utime(docroot / "x.txt", (946684800, 946684800))

    outs = set()
    for i in range(max(REPEATS // 4, 2)):  # heavier per-rep: fewer reps
        data = tmp_path / f"d{i}"
        cfg = ConfigOptions.from_yaml(
            f"""
general: {{stop_time: 20s, seed: 11, data_directory: {data}, heartbeat_interval: null}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  www:
    network_node_id: 0
    processes:
      - path: {py}
        args: [-m, http.server, "8080", --bind, 0.0.0.0, --directory, {docroot}]
        expected_final_state: running
  client:
    network_node_id: 0
    processes:
      - path: {curl}
        args: [-s, -i, --max-time, "15", http://www:8080/x.txt]
        start_time: 2s
"""
        )
        Simulation(cfg).run()
        outs.add((data / "hosts" / "client" / "curl.stdout").read_text())
    assert len(outs) == 1, f"{len(outs)} distinct outputs across repeats"


def test_stress_raw_clone_threads(tmp_path):
    """Go-style raw CLONE_VM threads under repetition: the adopted-thread
    path (pthread-backed context restore) must be schedule-invariant."""
    _repeat_identical(
        f"""
general: {{stop_time: 10s, seed: 23, data_directory: {tmp_path / 'd'}, heartbeat_interval: null}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  h:
    network_node_id: 0
    processes:
      - path: {BUILD / 'rawthreads'}
        args: [basic, '6']
"""
    )


def test_stress_tor_shaped_chains(tmp_path):
    """The Tor-shaped scale scenario (62 hosts, 22 managed processes in
    relay chains + background mesh) under repetition — the closest
    in-repo analog of the reference's tor-minimal determinism gate."""
    import shutil

    if shutil.which("curl") is None:
        pytest.skip("curl not installed")
    from test_tor_shaped import tor_shaped_yaml

    _repeat_identical(tor_shaped_yaml(tmp_path, "d"))
