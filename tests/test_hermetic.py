"""File-metadata and host-state hermeticity (dual-target test).

VERDICT item: ``stat``/``fstat`` mtimes on the simulated clock,
``getdents`` order pinned, ``/proc/uptime`` + ``sysinfo`` from sim time,
``sched_getaffinity`` reporting the modeled CPU set — no
wall-clock-derived bytes in any observed syscall result.  Reference
capability: the virtualized descriptor layer
(src/main/host/descriptor/regular_file.c) and the 149-entry syscall
dispatch (src/main/host/syscall/handler/mod.rs).

The ``hermetic`` binary prints every observable; the same binary run
natively reports host values (wall-clock mtimes, real uptime, real CPU
count), so the assertions below are exactly the dual-target diff.
"""

import subprocess
import time
from pathlib import Path

import pytest

from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.engine.sim import Simulation

REPO = Path(__file__).resolve().parents[1]
BUILD = REPO / "native" / "build"

SIM_EPOCH = 946_684_800  # 2000-01-01T00:00:00Z


@pytest.fixture(scope="module", autouse=True)
def native_build():
    subprocess.run(
        ["make", "-C", str(REPO / "native")], check=True, capture_output=True
    )
    assert (BUILD / "hermetic").exists()


def _run(tmp_path: Path, tag: str):
    cfg = ConfigOptions.from_yaml(
        f"""
general: {{stop_time: 2s, seed: 21, data_directory: {tmp_path / tag}, heartbeat_interval: null}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  solo:
    network_node_id: 0
    processes:
      - path: {BUILD / 'hermetic'}
"""
    )
    result = Simulation(cfg).run()
    out = (tmp_path / tag / "hosts" / "solo" / "hermetic.stdout").read_text()
    assert not result.process_errors
    return {
        line.split("=", 1)[0]: line.split("=", 1)[1]
        for line in out.splitlines()
        if "=" in line
    }


def test_stat_times_on_sim_clock(tmp_path):
    vals = _run(tmp_path, "a")
    # a file the simulation never wrote reports the simulation epoch
    mtime, atime, ctime = vals["self_mtime"].split(",")
    assert mtime == atime == ctime == f"{SIM_EPOCH}.000000000"
    # the real binary's mtime is recent wall time — assert the simulated
    # value is nowhere near it (the dual-target diff)
    real_mtime = (BUILD / "hermetic").stat().st_mtime
    assert abs(float(mtime) - real_mtime) > 10 * 365 * 86400


def test_written_file_tracks_sim_write_time(tmp_path):
    vals = _run(tmp_path, "b")
    pre = float(vals["write_pre"])
    post = float(vals["write_post"])
    # first write lands at sim start (plus CPU-model latency << 1s);
    # second after the 100 ms usleep
    assert SIM_EPOCH <= pre < SIM_EPOCH + 1
    assert abs((post - pre) - 0.1) < 0.05
    # the path-stat agrees with the fstat
    assert vals["path_mtime"].split(",")[0] == vals["write_post"]


def test_dirent_order_pinned(tmp_path):
    vals = _run(tmp_path, "c")
    assert vals["dirents"] == "a.txt,b.txt,c.txt,w.txt"


def test_utimensat_set_time_is_visible(tmp_path):
    # an explicitly set mtime (tar/rsync style) must be what stat reports
    vals = _run(tmp_path, "u")
    assert vals["utimens_mtime"].split(",")[0] == f"{SIM_EPOCH + 1234}.500000000"


def test_unlink_forgets_write_time(tmp_path):
    # recreating a deleted name starts from the epoch even if the host fs
    # reuses the inode (no resurrection of the old write time)
    vals = _run(tmp_path, "f")
    assert vals["recreated_mtime"].split(",")[0] == f"{SIM_EPOCH}.000000000"


def test_proc_uptime_and_sysinfo_from_sim_clock(tmp_path):
    vals = _run(tmp_path, "d")
    up = float(vals["proc_uptime"].split()[0])
    assert 0 <= up < 2.0  # sim elapsed, not the host's uptime
    si = dict(kv.split(":") for kv in vals["sysinfo"].split(","))
    assert 0 <= int(si["up"]) < 2
    assert si["load"] == "0"
    assert int(si["ram"]) == 16 << 30
    assert si["procs"] == "16"


def test_proc_views_synthesized(tmp_path):
    # loadavg/meminfo/stat/cpuinfo agree with the modeled host (1 CPU,
    # 16 GiB, zero load) and never leak the real machine's figures
    vals = _run(tmp_path, "pv")
    assert vals["proc_loadavg"] == "0.00 0.00 0.00 1/16 2"
    assert vals["proc_meminfo"] == "MemTotal:       16777216 kB"
    assert vals["proc_stat"].startswith("cpu  ")
    ticks = int(vals["proc_stat"].split()[1])
    assert 0 <= ticks < 200  # sim uptime at HZ=100, not host jiffies
    assert vals["proc_cpuinfo"] == "processor\t: 0"


def test_affinity_reports_modeled_cpu_set(tmp_path):
    vals = _run(tmp_path, "e")
    assert vals["cpus"] == "1"


def test_statfs_rusage_times_virtualized(tmp_path):
    vals = _run(tmp_path, "g")
    # fixed modeled filesystem (free space cannot vary run to run)
    assert vals["statfs"] == (
        f"blocks:{(16 << 30) // 4096},bfree:{(8 << 30) // 4096}"
    )
    # rusage/times on the modeled clock: bounded by sim elapsed (< 2 s)
    ut = float(vals["rusage"].split(",")[0].split(":")[1])
    assert 0 <= ut < 2.0
    assert vals["rusage"].endswith("maxrss:16384")
    ticks = int(vals["times"].split(",")[0].split(":")[1])
    assert 0 <= ticks < 200  # HZ=100, < 2 sim-seconds


def test_deterministic_across_wall_time(tmp_path):
    v1 = _run(tmp_path, "r1")
    time.sleep(1.1)  # move wall clock between runs
    v2 = _run(tmp_path, "r2")
    assert v1 == v2
