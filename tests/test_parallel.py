"""Sharded lane engine on the 8-device CPU mesh: bit-identical to
single-device, and the dry-run entry points work.

This is the multi-chip analog of the reference's determinism tests
(src/test/determinism/): the mesh shape must never change results.
"""

import importlib.util
from pathlib import Path

import jax
import numpy as np
import pytest

from shadow_tpu import parallel
from shadow_tpu.backend import lanes
from shadow_tpu.backend.cpu_engine import CpuEngine
from shadow_tpu.backend.tpu_engine import TpuEngine
from shadow_tpu.config.options import ConfigOptions

MESH8 = """
general: {stop_time: 200ms, seed: 11}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "50 Mbit" host_bandwidth_down "50 Mbit" ]
        edge [ source 0 target 0 latency "3 ms" ]
      ]
hosts:
  m: {count: 8, network_node_id: 0, processes: [{path: phold, args: [--messages, "2"]}]}
"""


def _final_state(engine: TpuEngine, mesh=None) -> lanes.LaneState:
    state = engine.initial_state()
    if mesh is None:
        return jax.block_until_ready(
            lanes.make_run_fn(engine.params, engine.tables)(state)
        )
    state = parallel.shard_state(state, mesh)
    run = parallel.make_sharded_run_fn(engine.params, engine.tables, mesh)
    return jax.block_until_ready(run(state))


def _load_graft_entry():
    path = Path(__file__).resolve().parents[1] / "__graft_entry__.py"
    spec = importlib.util.spec_from_file_location("__graft_entry__", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# one sharded compile per device count on a single physical core is
# expensive; tier-1 keeps the 2-device shape, the 8-device shapes run
# slow-marked and at gate scale in `make multichip-smoke`
@pytest.mark.parametrize(
    "n_devices", [2, pytest.param(8, marks=pytest.mark.slow)]
)
def test_sharded_run_bit_identical(n_devices):
    cfg = ConfigOptions.from_yaml(MESH8)
    engine = TpuEngine(cfg)
    single = _final_state(engine)
    mesh = parallel.make_mesh(n_devices)
    sharded = _final_state(engine, mesh)
    for field in lanes.LaneState._fields:
        a, b = np.asarray(getattr(single, field)), np.asarray(getattr(sharded, field))
        if field == "log":
            n = int(single.log_count)
            a, b = a[:n], b[:n]
            # log append order may differ across shardings; content may not
            a = a[np.lexsort(a.T[::-1])]
            b = b[np.lexsort(b.T[::-1])]
        np.testing.assert_array_equal(a, b, err_msg=field)


@pytest.mark.slow
def test_sharded_matches_cpu_reference():
    cfg = ConfigOptions.from_yaml(MESH8)
    cpu = CpuEngine(cfg).run()
    engine = TpuEngine(cfg)
    mesh = parallel.make_mesh(8)
    final = _final_state(engine, mesh)
    tpu = engine.collect(final, wall=0.0)
    assert cpu.log_tuples() == tpu.log_tuples()


def test_graft_entry_single_chip():
    mod = _load_graft_entry()
    fn, args = mod.entry()
    out, done = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert not bool(done)


@pytest.mark.slow
def test_graft_dryrun_multichip():
    mod = _load_graft_entry()
    mod.dryrun_multichip(8)
