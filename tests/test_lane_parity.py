"""TPU lane backend vs CPU reference: bit-identical event logs.

This is the determinism gate the reference enforces with its determinism
test suite (src/test/determinism/CMakeLists.txt) — here applied *across
backends*: the batched JAX lane engine must produce exactly the event log
of the scalar Python engine for every supported workload.
"""

import pytest

from shadow_tpu.backend.cpu_engine import CpuEngine
from shadow_tpu.backend.tpu_engine import LaneCompatError, TpuEngine
from shadow_tpu.config.options import ConfigOptions


def both_logs(yaml: str, mode: str = "step"):
    cpu = CpuEngine(ConfigOptions.from_yaml(yaml)).run()
    tpu = TpuEngine(ConfigOptions.from_yaml(yaml)).run(mode=mode)
    return cpu, tpu


PHOLD_SMALL = """
general: {stop_time: 500ms, seed: 7}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        node [ id 1 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        edge [ source 0 target 0 latency "2 ms" ]
        edge [ source 0 target 1 latency "5 ms" ]
        edge [ source 1 target 1 latency "2 ms" ]
      ]
hosts:
  a: {network_node_id: 0, processes: [{path: phold, args: [--messages, "3"]}]}
  b: {network_node_id: 1, processes: [{path: phold, args: [--messages, "3"]}]}
  c: {network_node_id: 1, processes: [{path: phold, args: [--messages, "2"]}]}
"""


def test_phold_parity():
    cpu, tpu = both_logs(PHOLD_SMALL)
    assert len(cpu.event_log) > 50
    assert cpu.log_tuples() == tpu.log_tuples()


def test_phold_parity_device_mode():
    cpu, tpu = both_logs(PHOLD_SMALL, mode="device")
    assert cpu.log_tuples() == tpu.log_tuples()


TGEN_PAIR = """
general: {stop_time: 300ms, seed: 3}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "10 Mbit" host_bandwidth_down "10 Mbit" ]
        node [ id 1 host_bandwidth_up "10 Mbit" host_bandwidth_down "10 Mbit" ]
        edge [ source 0 target 1 latency "10 ms" packet_loss 0.2 ]
      ]
hosts:
  tx: {network_node_id: 0, processes: [{path: tgen-client, args: [--server, rx, --interval, 5ms, --size, "600"]}]}
  rx: {network_node_id: 1, processes: [{path: tgen-server}]}
"""


def test_tgen_lossy_parity():
    cpu, tpu = both_logs(TGEN_PAIR)
    assert len(cpu.event_log) > 30
    assert any(r.outcome == 1 for r in cpu.event_log)  # some loss happened
    assert cpu.log_tuples() == tpu.log_tuples()
    assert cpu.counters["tgen_recv_bytes"] == tpu.counters["tgen_recv_bytes"]


TGEN_FAULTED = TGEN_PAIR + """
faults:
  events:
    - {at: 50ms, kind: latency, source: 0, target: 1, latency: "25 ms"}
    - {at: 100ms, kind: link_down, source: 0, target: 1}
    - {at: 200ms, kind: link_up, source: 0, target: 1}
"""


@pytest.mark.faults
@pytest.mark.parametrize("mode", ["step", "device"])
def test_fault_schedule_parity(mode):
    """Fault epochs re-upload the device gather tables mid-run; the CPU
    engine mutates its routing in place at the same window-clamp epochs —
    delivered-event ordering must stay bit-identical (docs/faults.md)."""
    cpu, tpu = both_logs(TGEN_FAULTED, mode=mode)
    assert len(cpu.event_log) > 20
    # the schedule actually bit: a latency shift and a dark window
    assert any(r.outcome == 1 for r in cpu.event_log)
    assert cpu.log_tuples() == tpu.log_tuples()
    assert cpu.counters["tgen_recv_bytes"] == tpu.counters["tgen_recv_bytes"]


MESH = """
general: {stop_time: 200ms, seed: 11}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "50 Mbit" host_bandwidth_down "50 Mbit" ]
        edge [ source 0 target 0 latency "3 ms" ]
      ]
hosts:
  m: {count: 5, network_node_id: 0, processes: [{path: tgen-mesh, args: [--interval, 7ms, --size, "400"]}]}
"""


def test_tgen_mesh_parity():
    cpu, tpu = both_logs(MESH)
    assert len(cpu.event_log) > 50
    assert cpu.log_tuples() == tpu.log_tuples()


FAR_TIMER = """
general: {stop_time: 12s, seed: 9}
network: {graph: {type: 1_gbit_switch}}
hosts:
  cli: {network_node_id: 0, processes: [{path: ping, args: [--peer, srv, --count, "2", --interval, 5s]}]}
  srv: {network_node_id: 0, processes: [{path: ping}]}
"""


MESH_UNROLLED = MESH.replace(
    "hosts:", "experimental: {tpu_round_unroll: 2}\nhosts:"
)


MESH_NARROW_CROSS = MESH.replace(
    "hosts:", "experimental: {tpu_cross_capacity: 4}\nhosts:"
)


def test_narrow_cross_block_parity():
    """tpu_cross_capacity narrows the per-iteration receive block below the
    queue capacity (the bench's configuration); logs stay bit-identical
    when fan-in fits, and strict mode still raises when it doesn't."""
    cpu, tpu = both_logs(MESH_NARROW_CROSS, mode="device")
    assert cpu.log_tuples() == tpu.log_tuples()


def test_negative_cross_capacity_rejected():
    cfg = ConfigOptions.from_yaml(
        MESH.replace("hosts:", "experimental: {tpu_cross_capacity: -1}\nhosts:")
    )
    with pytest.raises(LaneCompatError):
        TpuEngine(cfg)


def test_unrolled_device_loop_parity():
    """tpu_round_unroll > 1 runs several window steps per device-loop trip
    (trailing no-op steps past the end included) — logs stay identical.
    (2, not more: XLA CPU compile time grows steeply with body size.)"""
    cpu, tpu = both_logs(MESH_UNROLLED, mode="device")
    assert cpu.log_tuples() == tpu.log_tuples()


def test_far_future_events_parity():
    """Events queued >2.1 s past the window (a 5 s timer here; RTO backoff
    and staggered starts hit the same path) exercise the high word of the
    int32 time split — ordering and logs must stay exact, not saturate."""
    cpu, tpu = both_logs(FAR_TIMER, mode="device")
    assert len(cpu.event_log) >= 4  # two pings + echoes
    assert cpu.log_tuples() == tpu.log_tuples()


PING = """
general: {stop_time: 2s, seed: 5}
network: {graph: {type: 1_gbit_switch}}
hosts:
  cli: {network_node_id: 0, processes: [{path: ping, args: [--peer, srv, --count, "4", --interval, 250ms]}]}
  srv: {network_node_id: 0, processes: [{path: ping}]}
"""


def test_ping_parity():
    cpu, tpu = both_logs(PING)
    assert len(cpu.event_log) == 8  # 4 requests + 4 echoes
    assert cpu.log_tuples() == tpu.log_tuples()


BOTTLENECK = """
general: {stop_time: 400ms, seed: 9}
experimental: {tpu_lane_queue_capacity: 1024}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "20 Mbit" host_bandwidth_down "2 Mbit" ]
        edge [ source 0 target 0 latency "1 ms" ]
      ]
hosts:
  blast: {network_node_id: 0, processes: [{path: tgen-client, args: [--server, sink, --interval, 1ms, --size, "1200"]}]}
  sink: {network_node_id: 0}
"""


def test_codel_bottleneck_parity():
    # saturated downlink: token-bucket queueing + CoDel drops on both backends
    cpu, tpu = both_logs(BOTTLENECK)
    assert any(r.outcome == 2 for r in cpu.event_log)  # codel drops happened
    assert cpu.log_tuples() == tpu.log_tuples()


def test_bootstrap_parity():
    yaml = TGEN_PAIR.replace(
        "general: {stop_time: 300ms, seed: 3}",
        "general: {stop_time: 300ms, seed: 3, bootstrap_end_time: 150ms}",
    )
    cpu, tpu = both_logs(yaml)
    assert cpu.log_tuples() == tpu.log_tuples()


def test_lane_compat_gate():
    # multi-process is lane-compiled only for tgen-trio combinations
    # with a single timer driver; everything else names the cpu backend
    with pytest.raises(LaneCompatError, match="tgen mesh/client/server"):
        TpuEngine(
            ConfigOptions.from_yaml(
                "general: {stop_time: 1s}\n"
                "hosts: {a: {processes: [{path: phold}, {path: phold}]}}"
            )
        )
    with pytest.raises(LaneCompatError, match="at most one timer-driving"):
        TpuEngine(
            ConfigOptions.from_yaml(
                "general: {stop_time: 1s}\n"
                "hosts:\n"
                "  a:\n"
                "    processes:\n"
                "      - {path: tgen-mesh, args: [--interval, 10ms]}\n"
                "      - {path: tgen-mesh, args: [--interval, 20ms]}\n"
            )
        )


MULTIPROC = """
general: {stop_time: 2s, seed: 13}
network: {graph: {type: 1_gbit_switch}}
hosts:
  duplex0:
    network_node_id: 0
    processes:
      - {path: tgen-client, args: [--server, duplex1, --interval, 40ms, --size, "700"]}
      - {path: tgen-server}
  duplex1:
    network_node_id: 0
    processes:
      - {path: tgen-server}
      - {path: tgen-client, args: [--server, duplex0, --interval, 55ms, --size, "500"]}
  sinks:
    network_node_id: 0
    processes:
      - {path: tgen-server}
      - {path: tgen-server}
  mesh0:
    network_node_id: 0
    processes:
      - {path: tgen-mesh, args: [--interval, 30ms, --size, "300"]}
      - {path: tgen-server}
"""


def test_multi_process_host_parity():
    """Multi-process lane hosts (tgen-trio combos, one driver max):
    logs bit-identical and counters equal — including the per-app
    delivery multiplication the CPU oracle performs."""
    cpu, tpu = both_logs(MULTIPROC, mode="device")
    assert len(cpu.event_log) > 50
    assert cpu.log_tuples() == tpu.log_tuples()
    assert cpu.counters.get("tgen_recv_bytes") == \
        tpu.counters.get("tgen_recv_bytes")


def test_phold_hops_counter_parity():
    cpu, tpu = both_logs(PHOLD_SMALL)
    assert cpu.counters["phold_hops"] == tpu.counters["phold_hops"]


SINK_DRAIN = """
general: {stop_time: 100ms, seed: 2}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
        edge [ source 0 target 0 latency "1 ms" ]
      ]
hosts:
  c: {count: 30, network_node_id: 0, processes: [{path: tgen-client, args: [--server, sink, --interval, 20ms, --size, "200"]}]}
  sink: {network_node_id: 0}
"""


def test_passive_sink_drain_parity():
    # regression: a lane popping >K passive DELIVERY events in one window
    # used to skip the merge AND the re-sort, wedging the device while_loop
    cpu, tpu = both_logs(SINK_DRAIN)
    assert len(cpu.event_log) > 100
    assert cpu.log_tuples() == tpu.log_tuples()


def test_non_power_of_two_capacity_parity():
    # regression: the barrel-shift gather assumed power-of-two capacities
    yaml = MESH.replace(
        "general: {stop_time: 200ms, seed: 11}",
        "general: {stop_time: 200ms, seed: 11}\n"
        "experimental: {tpu_lane_queue_capacity: 23}",
    )
    cpu, tpu = both_logs(yaml)
    assert cpu.log_tuples() == tpu.log_tuples()


def test_overflow_raises_loudly():
    # 40 synchronized senders blast one sink: the sink lane receives a
    # >capacity burst in a single window and must raise, not diverge
    yaml = """
general: {stop_time: 100ms, seed: 2}
experimental: {tpu_lane_queue_capacity: 9}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
        edge [ source 0 target 0 latency "1 ms" ]
      ]
hosts:
  c: {count: 40, network_node_id: 0, processes: [{path: tgen-client, args: [--server, sink, --interval, 5ms, --size, "300"]}]}
  sink: {network_node_id: 0}
"""
    from shadow_tpu.backend.tpu_engine import TpuEngine as TE

    with pytest.raises(RuntimeError, match="lane-queue overflow"):
        TE(ConfigOptions.from_yaml(yaml)).run(mode="step")


STREAM_PAIR = """
general: {stop_time: 30s, seed: 5}
experimental: {tpu_lane_queue_capacity: 128}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "20 Mbit" host_bandwidth_down "20 Mbit" ]
        node [ id 1 host_bandwidth_up "20 Mbit" host_bandwidth_down "20 Mbit" ]
        edge [ source 0 target 1 latency "15 ms" ]
      ]
hosts:
  c: {network_node_id: 0, processes: [{path: stream-client, args: [--server, s, --size, 200kB]}]}
  s: {network_node_id: 1, processes: [{path: stream-server}]}
"""


def test_stream_tcp_parity():
    # the vectorized lane-TCP vs the scalar ltcp law: full handshake,
    # slow start, teardown — bit-identical wire traffic
    cpu, tpu = both_logs(STREAM_PAIR)
    assert cpu.counters["stream_complete"] == 1
    assert cpu.counters["stream_rx_bytes"] == 200_000
    assert cpu.log_tuples() == tpu.log_tuples()
    for k in ("stream_complete", "stream_rx_bytes", "stream_rx_segs",
              "stream_tx_segs", "stream_flows_done", "stream_retransmits"):
        assert cpu.counters.get(k) == tpu.counters.get(k), k


def test_stream_tcp_lossy_parity():
    yaml = STREAM_PAIR.replace('latency "15 ms"', 'latency "15 ms" packet_loss 0.03')
    cpu, tpu = both_logs(yaml)
    assert cpu.counters["stream_complete"] == 1
    assert cpu.counters["stream_retransmits"] > 0  # recovery exercised
    assert cpu.log_tuples() == tpu.log_tuples()
    assert cpu.counters.get("stream_retransmits") == tpu.counters.get("stream_retransmits")


STREAM_STAR = """
general: {stop_time: 60s, seed: 9}
experimental: {tpu_lane_queue_capacity: 512}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "50 Mbit" host_bandwidth_down "50 Mbit" ]
        edge [ source 0 target 0 latency "5 ms" packet_loss 0.01 ]
      ]
hosts:
  c: {count: 6, network_node_id: 0, processes: [{path: stream-client, args: [--server, srv, --size, 80kB]}]}
  srv: {network_node_id: 0, processes: [{path: stream-server}]}
"""


def test_stream_star_parity():
    # 6 concurrent flows into one server lane: exercises the per-flow
    # gather/scatter and multi-flow RTO/pump interleaving
    cpu, tpu = both_logs(STREAM_STAR)
    assert cpu.counters["stream_complete"] == 6
    assert cpu.counters["stream_rx_bytes"] == 6 * 80_000
    assert cpu.log_tuples() == tpu.log_tuples()
    assert cpu.counters.get("stream_flows_done") == tpu.counters.get("stream_flows_done")


def test_stream_device_mode_parity():
    cpu, tpu = both_logs(STREAM_PAIR, mode="device")
    assert cpu.log_tuples() == tpu.log_tuples()


def test_vector_law_keeps_ack_rto_arm_through_opened_pump():
    # regression: an ACK that shrinks the RTO (arming a new owner event)
    # AND opens the send window used to lose the arm when the inline pump's
    # emit was merged wholesale — leaving rto_evt naming an event that was
    # never queued (a dead retransmission timer)
    import jax
    import jax.numpy as jnp

    from shadow_tpu.backend import lanes_stream as lstr
    from shadow_tpu.net import ltcp

    def p(v):  # ns value -> (hi, lo) int32 split
        return v >> 31, v & ((1 << 31) - 1)

    segs = jnp.array([50], dtype=jnp.int32)
    mss = jnp.array([1448], dtype=jnp.int32)
    last = jnp.array([1448], dtype=jnp.int32)
    st = lstr.init_stream_state(1)
    cl = st.cl
    for col, val in (
        (lstr.C_STATE, ltcp.ESTAB), (lstr.C_SND_UNA, 5), (lstr.C_SND_NXT, 10),
        (lstr.C_RCV_NXT, 1), (lstr.C_MAX_SENT, 10),
        (lstr.C_CWND, 20 * ltcp.FP),
        (lstr.C_SRTT_HI, -1),  # first RTT sample -> RTO collapses to 200ms
        (lstr.C_SRTT_LO, 0), (lstr.C_RTTVAR_HI, 0), (lstr.C_RTTVAR_LO, 0),
        (lstr.C_RTO_HI, p(900_000_000)[0]), (lstr.C_RTO_LO, p(900_000_000)[1]),
        (lstr.C_RTT_SEQ, 5),
        (lstr.C_RTT_TS_HI, p(970_000_000)[0]),
        (lstr.C_RTT_TS_LO, p(970_000_000)[1]),
        (lstr.C_RTODL_HI, p(1_900_000_000)[0]),
        (lstr.C_RTODL_LO, p(1_900_000_000)[1]),
        (lstr.C_RTOEV_HI, p(1_900_000_000)[0]),
        (lstr.C_RTOEV_LO, p(1_900_000_000)[1]),
    ):
        cl = cl.at[0, col].set(val)
    st = st._replace(cl=cl)
    z1 = jnp.zeros(1, dtype=jnp.int32)
    f = lstr.endpoint_cols(
        st,
        jnp.concatenate([segs, z1]),
        jnp.concatenate([mss, z1]),
        jnp.concatenate([last, z1]),
        jnp.zeros(2, dtype=jnp.int32),  # flow_cc: reno
    )  # [2S]=2 rows: row 0 = the client endpoint, row 1 = its server
    now = 1_000_000_000
    nh = jnp.full(2, p(now)[0], dtype=jnp.int32)
    nl = jnp.full(2, p(now)[1], dtype=jnp.int32)
    # mirror the scalar law on the identical state
    fs = ltcp.FlowState(role=ltcp.SENDER, segs=50, mss=1448, last_bytes=1448,
                        state=ltcp.ESTAB, snd_una=5, snd_nxt=10, rcv_nxt=1,
                        max_sent=10, cwnd_fp=20 * ltcp.FP, srtt=-1,
                        rttvar=0, rto=900_000_000, rtt_seq=5,
                        rtt_ts=970_000_000, rto_deadline=1_900_000_000,
                        rto_evt=1_900_000_000)
    em_ref = ltcp.on_segment(fs, now, ltcp.F_ACK, 0, 6)
    m = jnp.array([True, False])
    f2, em = lstr.on_segment_vec(
        f, nh, nl, m, jnp.full(2, ltcp.F_ACK, dtype=jnp.int32),
        jnp.zeros(2, dtype=jnp.int32), jnp.full(2, 6, dtype=jnp.int32),
        jnp.full(2, ltcp.HDR_BYTES, dtype=jnp.int32),
    )
    # the slot driver runs the transmission-opportunity epilogue after
    # every stimulus — mirror it (the scalar wrapper does the same)
    f2, em, burst = lstr.pump_epilogue_vec(f2, nh, nl, m, em)
    assert em_ref.arm_rto is not None  # the scenario arms a shrunk owner
    assert bool(em.rto_valid[0])
    rto_t = (int(em.rto_thi[0]) << 31) | int(em.rto_tlo[0])
    assert rto_t == em_ref.arm_rto
    evt = (int(f2.rtoev_hi[0]) << 31) | int(f2.rtoev_lo[0])
    assert evt == fs.rto_evt
    # the epilogue pumped the same units the scalar law emitted
    n_burst = int(burst[0].sum())
    assert n_burst == len(em_ref.sends)
    assert [int(x) for x in jnp.stack([b for b in burst[2]])[
        jnp.stack([b for b in burst[0]])]] == [sd[1] for sd in em_ref.sends]


def test_mixed_mesh_stream_parity():
    """BASELINE config #4's shape in miniature: a UDP tgen mesh whose
    round-robin spray crosses lane-TCP stream pairs.  Stream lanes must
    ignore the foreign datagrams exactly like the CPU oracle's isinstance
    gate, and the logs must still diff equal."""
    from shadow_tpu.config.presets import flagship_mesh_config

    from shadow_tpu.backend.cpu_engine import CpuEngine as _Cpu

    cfg = flagship_mesh_config(
        12, sim_seconds=2, stream_pairs=2, stream_bytes=200_000,
        queue_capacity=96, pops_per_round=4,
    )
    import copy

    cpu_cfg = copy.deepcopy(cfg)
    cpu_cfg.experimental.network_backend = "cpu"
    cpu = _Cpu(cpu_cfg).run()
    tpu = TpuEngine(cfg).run(mode="device")
    assert cpu.log_tuples() == tpu.log_tuples()
    assert len(cpu.event_log) > 100
    # the stream tier really ran: segments crossed alongside the mesh
    assert tpu.counters.get("stream_rx_bytes", 0) > 0


def test_dynamic_runahead_parity():
    """use_dynamic_runahead on DEVICE (round-1 review item: it was
    cpu-only): the window widens to the smallest latency actually used —
    while only the slow path carries traffic the windows are wide, and
    the first fast-path send narrows them.  Bit-identical logs against
    the CPU oracle prove the identical law (runahead.rs:44-57)."""
    yaml = """
general: {stop_time: 2s, seed: 13}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        node [ id 1 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        edge [ source 0 target 0 latency "2 ms" ]
        edge [ source 0 target 1 latency "40 ms" ]
        edge [ source 1 target 1 latency "2 ms" ]
      ]
experimental: {use_dynamic_runahead: true}
hosts:
  a: {network_node_id: 0, processes: [{path: tgen-client, args: "--server b --interval 30ms --size 600"}]}
  b: {network_node_id: 1, processes: [{path: tgen-server}]}
  c: {network_node_id: 1, processes: [{path: ping, args: "--peer d --count 5 --interval 100ms"}]}
  d: {network_node_id: 1, processes: [{path: ping}]}
"""
    cpu, tpu = both_logs(yaml, mode="device")
    assert cpu.log_tuples() == tpu.log_tuples()
    assert len(cpu.event_log) > 40


def test_pair_arithmetic_exact():
    """Property check of the int32 pair helpers against Python bignums —
    including the mul carry case where (s<<16) + ll*c wraps past 2**31
    (srtt ≈ 306.8 ms once corrupted RTO timing silently)."""
    import random

    import numpy as np

    from shadow_tpu.backend import lanes_pairs as lp

    rng = random.Random(7)
    cases = [(0, 306839551, 7), (0, 1431699455, 3)]
    for _ in range(20_000):
        c = rng.randint(1, 7)
        v = rng.randint(0, ((1 << 31) // c - 1) << 31 | lp.MASK31)
        cases.append((v >> 31, v & lp.MASK31, c))
    his = np.array([a for a, _b, _c in cases], dtype=np.int32)
    los = np.array([b for _a, b, _c in cases], dtype=np.int32)
    for cval in range(1, 8):
        mask = np.array([c == cval for _a, _b, c in cases])
        if not mask.any():
            continue
        h, l = lp.pair_mul_small(his[mask], los[mask], cval)
        h = np.asarray(h, dtype=np.int64)
        l = np.asarray(l, dtype=np.int64)
        exp = (
            his[mask].astype(np.int64) * (1 << 31) + los[mask].astype(np.int64)
        ) * cval
        got = h * (1 << 31) + l
        assert (got == exp).all() and (l >= 0).all() and (l < 1 << 31).all()
    # div / mod / sub round-trips on the same corpus
    vs = his.astype(np.int64) * (1 << 31) + los.astype(np.int64)
    for k in (1, 2, 3, 8, 30):
        dh, dl = lp.pair_div_pow2(his, los, k)
        got = np.asarray(dh, np.int64) * (1 << 31) + np.asarray(dl, np.int64)
        assert (got == vs >> k).all()
    for m in (3, 1_000_000, (1 << 22) - 1):
        got = np.asarray(lp.pair_mod_small(his, los, m), np.int64)
        assert (got == vs % m).all()
