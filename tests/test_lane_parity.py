"""TPU lane backend vs CPU reference: bit-identical event logs.

This is the determinism gate the reference enforces with its determinism
test suite (src/test/determinism/CMakeLists.txt) — here applied *across
backends*: the batched JAX lane engine must produce exactly the event log
of the scalar Python engine for every supported workload.
"""

import pytest

from shadow_tpu.backend.cpu_engine import CpuEngine
from shadow_tpu.backend.tpu_engine import LaneCompatError, TpuEngine
from shadow_tpu.config.options import ConfigOptions


def both_logs(yaml: str, mode: str = "step"):
    cpu = CpuEngine(ConfigOptions.from_yaml(yaml)).run()
    tpu = TpuEngine(ConfigOptions.from_yaml(yaml)).run(mode=mode)
    return cpu, tpu


PHOLD_SMALL = """
general: {stop_time: 500ms, seed: 7}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        node [ id 1 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        edge [ source 0 target 0 latency "2 ms" ]
        edge [ source 0 target 1 latency "5 ms" ]
        edge [ source 1 target 1 latency "2 ms" ]
      ]
hosts:
  a: {network_node_id: 0, processes: [{path: phold, args: [--messages, "3"]}]}
  b: {network_node_id: 1, processes: [{path: phold, args: [--messages, "3"]}]}
  c: {network_node_id: 1, processes: [{path: phold, args: [--messages, "2"]}]}
"""


def test_phold_parity():
    cpu, tpu = both_logs(PHOLD_SMALL)
    assert len(cpu.event_log) > 50
    assert cpu.log_tuples() == tpu.log_tuples()


def test_phold_parity_device_mode():
    cpu, tpu = both_logs(PHOLD_SMALL, mode="device")
    assert cpu.log_tuples() == tpu.log_tuples()


TGEN_PAIR = """
general: {stop_time: 300ms, seed: 3}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "10 Mbit" host_bandwidth_down "10 Mbit" ]
        node [ id 1 host_bandwidth_up "10 Mbit" host_bandwidth_down "10 Mbit" ]
        edge [ source 0 target 1 latency "10 ms" packet_loss 0.2 ]
      ]
hosts:
  tx: {network_node_id: 0, processes: [{path: tgen-client, args: [--server, rx, --interval, 5ms, --size, "600"]}]}
  rx: {network_node_id: 1, processes: [{path: tgen-server}]}
"""


def test_tgen_lossy_parity():
    cpu, tpu = both_logs(TGEN_PAIR)
    assert len(cpu.event_log) > 30
    assert any(r.outcome == 1 for r in cpu.event_log)  # some loss happened
    assert cpu.log_tuples() == tpu.log_tuples()
    assert cpu.counters["tgen_recv_bytes"] == tpu.counters["tgen_recv_bytes"]


MESH = """
general: {stop_time: 200ms, seed: 11}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "50 Mbit" host_bandwidth_down "50 Mbit" ]
        edge [ source 0 target 0 latency "3 ms" ]
      ]
hosts:
  m: {count: 5, network_node_id: 0, processes: [{path: tgen-mesh, args: [--interval, 7ms, --size, "400"]}]}
"""


def test_tgen_mesh_parity():
    cpu, tpu = both_logs(MESH)
    assert len(cpu.event_log) > 50
    assert cpu.log_tuples() == tpu.log_tuples()


PING = """
general: {stop_time: 2s, seed: 5}
network: {graph: {type: 1_gbit_switch}}
hosts:
  cli: {network_node_id: 0, processes: [{path: ping, args: [--peer, srv, --count, "4", --interval, 250ms]}]}
  srv: {network_node_id: 0, processes: [{path: ping}]}
"""


def test_ping_parity():
    cpu, tpu = both_logs(PING)
    assert len(cpu.event_log) == 8  # 4 requests + 4 echoes
    assert cpu.log_tuples() == tpu.log_tuples()


BOTTLENECK = """
general: {stop_time: 400ms, seed: 9}
experimental: {tpu_lane_queue_capacity: 1024}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "20 Mbit" host_bandwidth_down "2 Mbit" ]
        edge [ source 0 target 0 latency "1 ms" ]
      ]
hosts:
  blast: {network_node_id: 0, processes: [{path: tgen-client, args: [--server, sink, --interval, 1ms, --size, "1200"]}]}
  sink: {network_node_id: 0}
"""


def test_codel_bottleneck_parity():
    # saturated downlink: token-bucket queueing + CoDel drops on both backends
    cpu, tpu = both_logs(BOTTLENECK)
    assert any(r.outcome == 2 for r in cpu.event_log)  # codel drops happened
    assert cpu.log_tuples() == tpu.log_tuples()


def test_bootstrap_parity():
    yaml = TGEN_PAIR.replace(
        "general: {stop_time: 300ms, seed: 3}",
        "general: {stop_time: 300ms, seed: 3, bootstrap_end_time: 150ms}",
    )
    cpu, tpu = both_logs(yaml)
    assert cpu.log_tuples() == tpu.log_tuples()


def test_lane_compat_gate():
    with pytest.raises(LaneCompatError, match="at most one"):
        TpuEngine(
            ConfigOptions.from_yaml(
                "general: {stop_time: 1s}\n"
                "hosts: {a: {processes: [{path: phold}, {path: phold}]}}"
            )
        )


def test_phold_hops_counter_parity():
    cpu, tpu = both_logs(PHOLD_SMALL)
    assert cpu.counters["phold_hops"] == tpu.counters["phold_hops"]


SINK_DRAIN = """
general: {stop_time: 100ms, seed: 2}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
        edge [ source 0 target 0 latency "1 ms" ]
      ]
hosts:
  c: {count: 30, network_node_id: 0, processes: [{path: tgen-client, args: [--server, sink, --interval, 20ms, --size, "200"]}]}
  sink: {network_node_id: 0}
"""


def test_passive_sink_drain_parity():
    # regression: a lane popping >K passive DELIVERY events in one window
    # used to skip the merge AND the re-sort, wedging the device while_loop
    cpu, tpu = both_logs(SINK_DRAIN)
    assert len(cpu.event_log) > 100
    assert cpu.log_tuples() == tpu.log_tuples()


def test_non_power_of_two_capacity_parity():
    # regression: the barrel-shift gather assumed power-of-two capacities
    yaml = MESH.replace(
        "general: {stop_time: 200ms, seed: 11}",
        "general: {stop_time: 200ms, seed: 11}\n"
        "experimental: {tpu_lane_queue_capacity: 23}",
    )
    cpu, tpu = both_logs(yaml)
    assert cpu.log_tuples() == tpu.log_tuples()


def test_overflow_raises_loudly():
    # 40 synchronized senders blast one sink: the sink lane receives a
    # >capacity burst in a single window and must raise, not diverge
    yaml = """
general: {stop_time: 100ms, seed: 2}
experimental: {tpu_lane_queue_capacity: 9}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
        edge [ source 0 target 0 latency "1 ms" ]
      ]
hosts:
  c: {count: 40, network_node_id: 0, processes: [{path: tgen-client, args: [--server, sink, --interval, 5ms, --size, "300"]}]}
  sink: {network_node_id: 0}
"""
    from shadow_tpu.backend.tpu_engine import TpuEngine as TE

    with pytest.raises(RuntimeError, match="lane-queue overflow"):
        TE(ConfigOptions.from_yaml(yaml)).run(mode="step")
