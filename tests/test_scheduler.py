"""Host scheduler (scheduler crate analog): parallel rounds stay
bit-identical to serial execution for any worker count and policy."""

import subprocess
from pathlib import Path

import pytest

from shadow_tpu.backend.cpu_engine import CpuEngine
from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.engine.determinism import compare_results
from shadow_tpu.engine.scheduler import HostScheduler

REPO = Path(__file__).resolve().parents[1]

MESH = """
general: {stop_time: 300ms, seed: 17, parallelism: %d}
experimental: {scheduler: "%s"}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "50 Mbit" host_bandwidth_down "50 Mbit" ]
        edge [ source 0 target 0 latency "3 ms" packet_loss 0.05 ]
      ]
hosts:
  m: {count: 8, network_node_id: 0, processes: [{path: tgen-mesh, args: [--interval, 5ms, --size, "700"]}]}
"""


def _run(parallelism, policy="thread-per-core"):
    return CpuEngine(ConfigOptions.from_yaml(MESH % (parallelism, policy))).run()


def test_worker_counts_bit_identical():
    serial = _run(1)
    assert len(serial.event_log) > 200
    for workers in (2, 4, 8):
        report = compare_results(serial, _run(workers))
        assert report.identical, f"{workers} workers: {report.describe()}"


def test_thread_per_host_policy():
    report = compare_results(_run(1), _run(0, policy="thread-per-host"))
    assert report.identical, report.describe()


def test_scheduler_worker_sizing():
    s = HostScheduler([object()] * 10, parallelism=4)
    assert s.workers == 4
    s.shutdown()
    s = HostScheduler([object()] * 3, parallelism=8)
    assert s.workers == 3  # never more workers than hosts
    s.shutdown()
    s = HostScheduler([object()] * 5, parallelism=0, policy="thread-per-host")
    assert s.workers == 5
    s.shutdown()


@pytest.fixture(scope="module")
def native_build():
    subprocess.run(
        ["make", "-C", str(REPO / "native")], check=True, capture_output=True
    )


def test_managed_processes_parallel_identical(native_build, tmp_path):
    # real OS processes on 4 hosts driven by 4 workers: futex waits release
    # the GIL, so this exercises true concurrency on the managed path
    build = REPO / "native" / "build"
    yaml = f"""
general: {{stop_time: 3s, seed: 23, parallelism: %d, data_directory: {tmp_path}/d%d, heartbeat_interval: null}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  c1:
    network_node_id: 0
    processes:
      - path: {build / 'tcpecho'}
        args: [client, 11.0.0.4, "7000", "3", "1000", "7"]
        start_time: 100ms
  c2:
    network_node_id: 0
    processes:
      - path: {build / 'tcpecho'}
        args: [client, 11.0.0.4, "7000", "2", "500", "11"]
        start_time: 130ms
  p1:
    network_node_id: 0
    processes:
      - path: {build / 'pingpong'}
        args: [client, 11.0.0.4, "9000", "3", "64"]
        start_time: 200ms
  srv:
    network_node_id: 0
    processes:
      - path: {build / 'tcpecho'}
        args: [server, "7000", "2"]
      - path: {build / 'pingpong'}
        args: [server, "9000", "3"]
"""
    serial = CpuEngine(ConfigOptions.from_yaml(yaml % (1, 1))).run()
    par = CpuEngine(ConfigOptions.from_yaml(yaml % (4, 4))).run()
    report = compare_results(serial, par)
    assert report.identical, report.describe()
    assert serial.counters["managed_procs"] == 5


def test_work_stealing_drains_unbalanced_partitions():
    """A worker with an empty partition steals the busy worker's backlog
    (thread_per_core.rs:17-50): every host executes exactly once per
    round, and cross-worker steals actually happen."""
    import threading
    import time

    class SlowHost:
        def __init__(self, hid, log, delay=0.0):
            self.hid = hid
            self.log = log
            self.delay = delay

        def execute(self, until):
            if self.delay:
                time.sleep(self.delay)
            self.log.append((self.hid, until))

    log: list = []
    # 8 hosts, 4 workers: round-robin puts {0,4} on w0 — make host 0 slow
    # so w0 stalls while w1..w3 finish and steal
    hosts = [SlowHost(i, log, delay=0.25 if i == 0 else 0.0) for i in range(8)]
    sched = HostScheduler(hosts, parallelism=4, pin_cpus=False)
    sched.run_round(123)
    sched.shutdown()
    assert sorted(h for h, _ in log) == list(range(8))  # each exactly once
    assert all(u == 123 for _, u in log)
    assert sched.steals >= 1  # host 4 (w0's second) was stolen
