"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh (multi-chip TPU hardware is not
available in CI).  The container's sitecustomize imports jax and pins
``jax_platforms`` to the remote-TPU plugin at interpreter start, so plain env
vars are too late — we override through ``jax.config`` before the first
backend initialization instead.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import shadow_tpu  # noqa: E402,F401  (enables jax x64 mode)
