"""Async buffered logger (the reference's logger crate analog):
emission enqueues, a listener thread writes, lines carry sim-time
prefixes, and shutdown drains everything."""

import io
import logging
import threading

from shadow_tpu.utils import shadow_log


def _fresh(buf):
    return shadow_log.install_async_logging(logging.INFO, stream=buf)


def test_async_emission_and_flush():
    buf = io.StringIO()
    _fresh(buf)
    try:
        log = logging.getLogger("shadow_tpu.test")
        # emission must not do I/O on the caller: the root handler is a
        # QueueHandler, not a StreamHandler
        root = logging.getLogger()
        assert len(root.handlers) == 1
        assert isinstance(root.handlers[0], logging.handlers.QueueHandler)
        for i in range(100):
            log.info("line %d", i)
    finally:
        shadow_log.shutdown()  # drains
    out = buf.getvalue()
    assert out.count("line ") == 100
    assert "line 99" in out


def test_sim_time_prefix():
    buf = io.StringIO()
    _fresh(buf)
    try:
        shadow_log.set_sim_time_provider(lambda: 1_500_000_000)
        logging.getLogger("shadow_tpu.test").info("stamped")
    finally:
        shadow_log.shutdown()
        shadow_log.set_sim_time_provider(None)
    assert "[1.500000000s]" in buf.getvalue()


def test_multithreaded_emission_complete():
    buf = io.StringIO()
    _fresh(buf)
    try:
        log = logging.getLogger("shadow_tpu.test")

        def worker(k):
            for i in range(50):
                log.info("w%d-%d", k, i)

        ts = [threading.Thread(target=worker, args=(k,)) for k in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    finally:
        shadow_log.shutdown()
    assert buf.getvalue().count("w") >= 200


def test_install_is_idempotent():
    b1, b2 = io.StringIO(), io.StringIO()
    _fresh(b1)
    logging.getLogger("shadow_tpu.test").info("first")
    _fresh(b2)  # replaces, flushing the first listener
    try:
        logging.getLogger("shadow_tpu.test").info("second")
    finally:
        shadow_log.shutdown()
    assert "first" in b1.getvalue()
    assert "second" in b2.getvalue()
    assert len(logging.getLogger().handlers) <= 1
