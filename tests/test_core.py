"""Core determinism primitives: time, event order, queue, RNG parity."""

import numpy as np
import pytest

from shadow_tpu.core import time as stime
from shadow_tpu.core.event import Event, EventKind
from shadow_tpu.core.event_queue import EventQueue
from shadow_tpu.core import rng


def test_time_conversions():
    assert stime.from_secs(3) == 3 * stime.NANOS_PER_SEC
    assert stime.from_millis(10) == 10 * stime.NANOS_PER_MILLI
    assert stime.sim_to_emu(0) == stime.SIM_START_EMU
    assert stime.emu_to_sim(stime.sim_to_emu(123)) == 123
    assert stime.sim_to_emu(stime.NEVER) == stime.NEVER
    assert stime.fmt(1_500_000_000) == "1.500000000s"


def test_event_total_order():
    # time > kind > src_host > seq, exactly the reference's order
    # (core/work/event.rs:84-130).
    a = Event(10, EventKind.PACKET, src_host=5, seq=9)
    b = Event(10, EventKind.LOCAL, src_host=0, seq=0)
    c = Event(10, EventKind.PACKET, src_host=6, seq=0)
    d = Event(11, EventKind.PACKET, src_host=0, seq=0)
    e = Event(10, EventKind.PACKET, src_host=5, seq=10)
    order = sorted([d, c, b, e, a])
    assert order == [a, e, c, b, d]


def test_event_queue_pops_in_order_and_until():
    q = EventQueue()
    evs = [
        Event(30, EventKind.LOCAL, 0, 1),
        Event(10, EventKind.PACKET, 2, 0),
        Event(10, EventKind.PACKET, 1, 4),
        Event(20, EventKind.LOCAL, 0, 0),
    ]
    for ev in evs:
        q.push(ev)
    assert q.next_time() == 10
    popped = list(q.pop_until(25))
    assert [e.key() for e in popped] == [
        (10, 0, 1, 4),
        (10, 0, 2, 0),
        (20, 1, 0, 0),
    ]
    assert q.next_time() == 30
    assert len(q) == 1
    q2 = EventQueue()
    assert q2.next_time() == stime.NEVER


def test_threefry_matches_jax_reference():
    # Our generic implementation must match JAX's own threefry2x32 bit-for-bit
    # so jax.random keys and ours share one cipher.
    import jax.numpy as jnp
    from jax._src import prng as jprng

    k = (np.uint32(0x13198A2E), np.uint32(0x03707344))
    counts = np.arange(16, dtype=np.uint32)
    expected = np.asarray(
        jprng.threefry_2x32(jnp.asarray(np.stack(k)), jnp.asarray(counts))
    )
    # jax packs a count vector as (first half -> c0, second half -> c1)
    x0, x1 = rng.threefry2x32(k[0], k[1], counts[:8], counts[8:], xp=np)
    got = np.concatenate([x0, x1])
    np.testing.assert_array_equal(got, expected)


def test_rng_numpy_jax_parity():
    import jax.numpy as jnp

    seed = 0xDEADBEEF_12345678
    streams = np.arange(64, dtype=np.uint32)
    counters = (np.arange(64, dtype=np.uint64) * np.uint64(977)) + np.uint64(2**33)
    a = rng.rand_u32(seed, streams, counters, xp=np)
    b = np.asarray(rng.rand_u32(seed, jnp.asarray(streams), jnp.asarray(counters), xp=jnp))
    np.testing.assert_array_equal(a, b)
    # distinct streams give distinct draws
    assert len(np.unique(a)) == len(a)


def test_u32_below_parity_and_range():
    import jax.numpy as jnp

    u = rng.rand_u32(42, np.uint32(7), np.arange(1000, dtype=np.uint64), xp=np)
    n = 10
    got_np = rng.u32_below(u, n, xp=np)
    got_jnp = np.asarray(rng.u32_below(jnp.asarray(u), n, xp=jnp))
    np.testing.assert_array_equal(got_np, got_jnp)
    assert got_np.max() < n and got_np.min() >= 0
    # roughly uniform
    counts = np.bincount(got_np, minlength=n)
    assert counts.min() > 50


def test_loss_threshold_edges():
    assert rng.loss_threshold(0.0) == 0
    assert rng.loss_threshold(1.0) == 1 << 32
    t = rng.loss_threshold(0.25)
    assert abs(t / 2**32 - 0.25) < 1e-9


def test_host_seed_spread():
    seeds = {rng.host_seed(1, h) for h in range(1000)}
    assert len(seeds) == 1000
