"""The simulated loopback interface (VERDICT r4 #9).

The reference gives every host a localhost + internet interface pair
with their own queues (src/main/host/network/namespace.rs:25-60).  Here
127/8 traffic from managed processes rides a first-class lo lifecycle:
fixed LOOPBACK_LATENCY_NS one-way delay, no token buckets / CoDel /
loss, host-local delivery (never crosses engines or the device), source
addresses reported as 127.0.0.1, and pcap capture of lo packets.
"""

import struct
import subprocess
from pathlib import Path

import pytest

from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.engine.sim import Simulation

REPO = Path(__file__).resolve().parents[1]
BUILD = REPO / "native" / "build"


@pytest.fixture(scope="module", autouse=True)
def native_build():
    subprocess.run(
        ["make", "-C", str(REPO / "native")], check=True, capture_output=True
    )


def _run_tcp(tmp_path: Path, tag: str, pcap: bool = False):
    cfg = ConfigOptions.from_yaml(f"""
general: {{stop_time: 5s, seed: 7, data_directory: {tmp_path / tag}, heartbeat_interval: null}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  solo:
    network_node_id: 0
    pcap_enabled: {str(pcap).lower()}
    processes:
      - path: {BUILD / 'tcpecho'}
        args: [server, "7000", "1"]
        expected_final_state: {{exited: 0}}
      - path: {BUILD / 'tcpecho'}
        args: [client, 127.0.0.1, "7000", "3", "1024", "10"]
        start_time: 100ms
        expected_final_state: {{exited: 0}}
""")
    result = Simulation(cfg).run()
    outs = {}
    hostdir = tmp_path / tag / "hosts" / "solo"
    for f in hostdir.glob("tcpecho*.stdout"):
        outs[f.name] = f.read_text()
    return result, outs, hostdir


def test_tcp_over_loopback(tmp_path):
    result, outs, _ = _run_tcp(tmp_path, "t")
    assert not result.process_errors
    joined = "\n".join(outs.values())
    assert "client done rounds=3 bytes=3072" in joined, outs
    assert "server done conns=1" in joined, outs
    # lo deliveries are logged host-locally (src == dst)
    lo_recs = [r for r in result.log_tuples() if r[1] == r[2]]
    assert lo_recs, "no loopback log records"


def test_udp_over_loopback(tmp_path):
    cfg = ConfigOptions.from_yaml(f"""
general: {{stop_time: 5s, seed: 9, data_directory: {tmp_path / 'u'}, heartbeat_interval: null}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  solo:
    network_node_id: 0
    processes:
      - path: {BUILD / 'pingpong'}
        args: [server, "6000", "4"]
        expected_final_state: {{exited: 0}}
      - path: {BUILD / 'pingpong'}
        args: [client, 127.0.0.1, "6000", "4", "20"]
        start_time: 100ms
        expected_final_state: {{exited: 0}}
""")
    result = Simulation(cfg).run()
    assert not result.process_errors
    out = (tmp_path / "u" / "hosts" / "solo" / "pingpong.1.stdout")
    if not out.exists():
        out = next((tmp_path / "u" / "hosts" / "solo").glob("pingpong*.stdout"))
    assert "ping" in out.read_text() or out.read_text()


def test_loopback_pcap_capture(tmp_path):
    result, _, hostdir = _run_tcp(tmp_path, "p", pcap=True)
    assert not result.process_errors
    pcaps = list(hostdir.glob("*.pcap"))
    assert pcaps, "no pcap written"
    blob = b"".join(p.read_bytes() for p in pcaps)
    # 127.0.0.1 in network byte order appears in captured lo IP headers
    assert struct.pack(">I", 0x7F000001) in blob


def test_loopback_deterministic(tmp_path):
    r1, o1, _ = _run_tcp(tmp_path, "d1")
    r2, o2, _ = _run_tcp(tmp_path, "d2")
    assert r1.log_tuples() == r2.log_tuples()
    assert o1 == o2
