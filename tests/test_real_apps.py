"""Real off-the-shelf software end-to-end (the reference's examples gate,
examples/apps/: curl, nginx, iperf...): an UNMODIFIED CPython http.server
daemon and an unmodified curl client talk HTTP over the SIMULATED TCP
stack, deterministically.

This exercises the whole managed-process surface at once: multi-hundred-
syscall interpreter startup, simulated getaddrinfo resolution, listen/
accept/poll/send/recv on simulated stream sockets, simulated clock (the
HTTP Date header shows year 2000), deterministic entropy (CPython's hash
seed comes from the shim's getrandom), and the raw-syscall backstop for
everything glibc does internally.
"""

import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.engine.sim import Simulation

REPO = Path(__file__).resolve().parents[1]
CURL = shutil.which("curl")
# the system interpreter, NOT the venv one: the venv's sitecustomize
# imports JAX (C++ thread pools, a TPU tunnel dial) at startup, which is
# not a sane guest workload
PY = "/usr/bin/python3" if Path("/usr/bin/python3").exists() else sys.executable


@pytest.fixture(scope="module", autouse=True)
def native_build():
    subprocess.run(
        ["make", "-C", str(REPO / "native")], check=True, capture_output=True
    )


def _run(tmp_path: Path, tag: str):
    import os

    docroot = tmp_path / tag / "www"
    docroot.mkdir(parents=True)
    (docroot / "hello.txt").write_text("simulated internet says hello\n")
    # pin the REAL mtime: the Last-Modified header reflects it, and the
    # determinism check diffs the full client output
    os.utime(docroot / "hello.txt", (946684800, 946684800))
    data = tmp_path / tag / "data"
    cfg = ConfigOptions.from_yaml(
        f"""
general: {{stop_time: 30s, seed: 11, data_directory: {data}, heartbeat_interval: null}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  www:
    network_node_id: 0
    processes:
      - path: {PY}
        args: [-m, http.server, "8080", --bind, 0.0.0.0, --directory, {docroot}]
        expected_final_state: running
  client:
    network_node_id: 0
    processes:
      - path: {CURL}
        args: [-s, -i, --max-time, "20", http://www:8080/hello.txt]
        start_time: 2s
"""
    )
    result = Simulation(cfg).run()
    out = (data / "hosts" / "client" / "curl.stdout").read_text()
    return result, out


@pytest.mark.skipif(CURL is None, reason="curl not installed")
def test_python_httpd_curl_over_simulated_tcp(tmp_path):
    result, out = _run(tmp_path, "a")
    assert "HTTP/1.0 200 OK" in out  # shim warnings share the stream
    assert "simulated internet says hello" in out
    # the HTTP Date header comes from the SIMULATED clock: 2000-01-01
    # plus a couple of simulated seconds, never the real 2026 clock
    assert "Date: Sat, 01 Jan 2000" in out
    assert "Server: SimpleHTTP" in out
    assert not result.process_errors


@pytest.mark.skipif(CURL is None, reason="curl not installed")
def test_python_httpd_curl_deterministic(tmp_path):
    """Run-twice determinism over the real-software stack: byte-identical
    client output including the simulated-time headers."""
    _, out1 = _run(tmp_path, "r1")
    _, out2 = _run(tmp_path, "r2")
    assert out1 == out2


IP_BIN = "/usr/sbin/ip" if Path("/usr/sbin/ip").exists() else shutil.which("ip")


def _run_ip(tmp_path: Path, tag: str):
    data = tmp_path / tag / "data"
    cfg = ConfigOptions.from_yaml(
        f"""
general: {{stop_time: 5s, seed: 4, data_directory: {data}, heartbeat_interval: null}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  router:
    network_node_id: 0
    processes:
      - path: {IP_BIN}
        args: [addr, show]
"""
    )
    result = Simulation(cfg).run()
    return result, (data / "hosts" / "router" / "ip.stdout").read_text()


@pytest.mark.skipif(IP_BIN is None, reason="iproute2 not installed")
def test_iproute2_sees_simulated_interfaces(tmp_path):
    """An UNMODIFIED iproute2 `ip addr show` enumerates the SIMULATED
    interfaces over the emulated AF_NETLINK(NETLINK_ROUTE) dump surface
    (the reference's socket/netlink.rs answers the same requests): lo +
    eth0 with the host's simulated 11.0.0.0/8 address — never the real
    machine's interfaces."""
    result, out = _run_ip(tmp_path, "a")
    assert "1: lo:" in out and "LOOPBACK" in out
    assert "inet 127.0.0.1/8" in out
    assert "2: eth0:" in out
    assert "inet 11.0.0.1/8" in out  # the simulated address, /8 assignment
    assert "state UP" in out
    # deterministic MAC derived from the simulated IP
    assert "link/ether 02:54:0b:00:00:01" in out
    assert not result.process_errors


@pytest.mark.skipif(IP_BIN is None, reason="iproute2 not installed")
def test_iproute2_netlink_deterministic(tmp_path):
    _, out1 = _run_ip(tmp_path, "r1")
    _, out2 = _run_ip(tmp_path, "r2")
    assert out1 == out2


WGET = shutil.which("wget")
GIT = shutil.which("git")


def _run_multihop(tmp_path: Path, tag: str):
    """BASELINE config #5's stand-in (tor isn't installable here): a
    3-hop chain topology with CONCURRENT flows from three distinct real
    client binaries — curl, wget, and a full `git clone` over HTTP (git
    spawns git-remote-http, itself a libcurl app) — against CPython
    http.server daemons at the far end."""
    import os

    base = tmp_path / tag
    docroot = base / "www"
    docroot.mkdir(parents=True)
    (docroot / "a.txt").write_text("multihop says hello\n")
    os.utime(docroot / "a.txt", (946684800, 946684800))
    # a real git repo served over the dumb-http protocol
    src = base / "src"
    src.mkdir()
    subprocess.run(["git", "init", "-q"], cwd=src, check=True)
    (src / "f.txt").write_text("simulated clone payload\n")
    subprocess.run(["git", "add", "f.txt"], cwd=src, check=True)
    subprocess.run(
        ["git", "-c", "user.email=a@b", "-c", "user.name=t",
         "commit", "-qm", "init"],
        cwd=src, check=True,
        env={**os.environ,
             "GIT_AUTHOR_DATE": "2000-01-01T00:00:00Z",
             "GIT_COMMITTER_DATE": "2000-01-01T00:00:00Z"},
    )
    gitroot = base / "gitroot"
    gitroot.mkdir()
    subprocess.run(
        ["git", "clone", "-q", "--bare", str(src), str(gitroot / "repo.git")],
        check=True,
    )
    subprocess.run(
        ["git", "update-server-info"], cwd=gitroot / "repo.git", check=True
    )
    clone_dst = base / "cloned"
    data = base / "data"
    cfg = ConfigOptions.from_yaml(
        f"""
general: {{stop_time: 60s, seed: 17, data_directory: {data}, heartbeat_interval: null}}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        node [ id 1 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        node [ id 2 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        node [ id 3 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        edge [ source 0 target 0 latency "1 ms" ]
        edge [ source 3 target 3 latency "1 ms" ]
        edge [ source 0 target 1 latency "5 ms" ]
        edge [ source 1 target 2 latency "8 ms" ]
        edge [ source 2 target 3 latency "12 ms" ]
      ]
hosts:
  www:
    network_node_id: 0
    processes:
      - path: {PY}
        args: [-m, http.server, "8080", --bind, 0.0.0.0, --directory, {docroot}]
        expected_final_state: running
  gitsrv:
    network_node_id: 0
    processes:
      - path: {PY}
        args: [-m, http.server, "8081", --bind, 0.0.0.0, --directory, {gitroot}]
        expected_final_state: running
  curlc:
    network_node_id: 3
    processes:
      - path: {CURL}
        args: [-s, -i, --max-time, "30", http://www:8080/a.txt]
        start_time: 2s
  wgetc:
    network_node_id: 3
    processes:
      - path: {WGET}
        args: [-q, -O, "-", -T, "30", http://www:8080/a.txt]
        start_time: 2s
  gitc:
    network_node_id: 3
    processes:
      - path: {GIT}
        args: [clone, -q, "http://gitsrv:8081/repo.git", {clone_dst / tag}]
        start_time: 3s
"""
    )
    result = Simulation(cfg).run()
    return result, data, clone_dst / tag


@pytest.mark.skipif(
    CURL is None or WGET is None or GIT is None,
    reason="curl/wget/git not all installed",
)
def test_multihop_concurrent_real_clients(tmp_path):
    result, data, cloned = _run_multihop(tmp_path, "a")
    curl_out = (data / "hosts" / "curlc" / "curl.stdout").read_text()
    wget_out = (data / "hosts" / "wgetc" / "wget.stdout").read_text()
    assert "HTTP/1.0 200 OK" in curl_out
    assert "multihop says hello" in curl_out
    assert wget_out == "multihop says hello\n"
    # the git clone really happened THROUGH the simulated 3-hop network
    assert (cloned / "f.txt").read_text() == "simulated clone payload\n"
    assert not result.process_errors


@pytest.mark.skipif(
    CURL is None or WGET is None or GIT is None,
    reason="curl/wget/git not all installed",
)
def test_multihop_deterministic(tmp_path):
    _, d1, _ = _run_multihop(tmp_path, "r1")
    _, d2, _ = _run_multihop(tmp_path, "r2")
    for host, f in (("curlc", "curl.stdout"), ("wgetc", "wget.stdout")):
        a = (d1 / "hosts" / host / f).read_text()
        b = (d2 / "hosts" / host / f).read_text()
        assert a == b, f"{host}/{f} differs between runs"
