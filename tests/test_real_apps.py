"""Real off-the-shelf software end-to-end (the reference's examples gate,
examples/apps/: curl, nginx, iperf...): an UNMODIFIED CPython http.server
daemon and an unmodified curl client talk HTTP over the SIMULATED TCP
stack, deterministically.

This exercises the whole managed-process surface at once: multi-hundred-
syscall interpreter startup, simulated getaddrinfo resolution, listen/
accept/poll/send/recv on simulated stream sockets, simulated clock (the
HTTP Date header shows year 2000), deterministic entropy (CPython's hash
seed comes from the shim's getrandom), and the raw-syscall backstop for
everything glibc does internally.
"""

import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.engine.sim import Simulation

REPO = Path(__file__).resolve().parents[1]
CURL = shutil.which("curl")
# the system interpreter, NOT the venv one: the venv's sitecustomize
# imports JAX (C++ thread pools, a TPU tunnel dial) at startup, which is
# not a sane guest workload
PY = "/usr/bin/python3" if Path("/usr/bin/python3").exists() else sys.executable


@pytest.fixture(scope="module", autouse=True)
def native_build():
    subprocess.run(
        ["make", "-C", str(REPO / "native")], check=True, capture_output=True
    )


def _run(tmp_path: Path, tag: str):
    import os

    docroot = tmp_path / tag / "www"
    docroot.mkdir(parents=True)
    (docroot / "hello.txt").write_text("simulated internet says hello\n")
    # pin the REAL mtime: the Last-Modified header reflects it, and the
    # determinism check diffs the full client output
    os.utime(docroot / "hello.txt", (946684800, 946684800))
    data = tmp_path / tag / "data"
    cfg = ConfigOptions.from_yaml(
        f"""
general: {{stop_time: 30s, seed: 11, data_directory: {data}, heartbeat_interval: null}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  www:
    network_node_id: 0
    processes:
      - path: {PY}
        args: [-m, http.server, "8080", --bind, 0.0.0.0, --directory, {docroot}]
        expected_final_state: running
  client:
    network_node_id: 0
    processes:
      - path: {CURL}
        args: [-s, -i, --max-time, "20", http://www:8080/hello.txt]
        start_time: 2s
"""
    )
    result = Simulation(cfg).run()
    out = (data / "hosts" / "client" / "curl.stdout").read_text()
    return result, out


@pytest.mark.skipif(CURL is None, reason="curl not installed")
def test_python_httpd_curl_over_simulated_tcp(tmp_path):
    result, out = _run(tmp_path, "a")
    assert "HTTP/1.0 200 OK" in out  # shim warnings share the stream
    assert "simulated internet says hello" in out
    # the HTTP Date header comes from the SIMULATED clock: 2000-01-01
    # plus a couple of simulated seconds, never the real 2026 clock
    assert "Date: Sat, 01 Jan 2000" in out
    assert "Server: SimpleHTTP" in out
    assert not result.process_errors


@pytest.mark.skipif(CURL is None, reason="curl not installed")
def test_python_httpd_curl_deterministic(tmp_path):
    """Run-twice determinism over the real-software stack: byte-identical
    client output including the simulated-time headers."""
    _, out1 = _run(tmp_path, "r1")
    _, out2 = _run(tmp_path, "r2")
    assert out1 == out2


IP_BIN = "/usr/sbin/ip" if Path("/usr/sbin/ip").exists() else shutil.which("ip")


def _run_ip(tmp_path: Path, tag: str):
    data = tmp_path / tag / "data"
    cfg = ConfigOptions.from_yaml(
        f"""
general: {{stop_time: 5s, seed: 4, data_directory: {data}, heartbeat_interval: null}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  router:
    network_node_id: 0
    processes:
      - path: {IP_BIN}
        args: [addr, show]
"""
    )
    result = Simulation(cfg).run()
    return result, (data / "hosts" / "router" / "ip.stdout").read_text()


@pytest.mark.skipif(IP_BIN is None, reason="iproute2 not installed")
def test_iproute2_sees_simulated_interfaces(tmp_path):
    """An UNMODIFIED iproute2 `ip addr show` enumerates the SIMULATED
    interfaces over the emulated AF_NETLINK(NETLINK_ROUTE) dump surface
    (the reference's socket/netlink.rs answers the same requests): lo +
    eth0 with the host's simulated 11.0.0.0/8 address — never the real
    machine's interfaces."""
    result, out = _run_ip(tmp_path, "a")
    assert "1: lo:" in out and "LOOPBACK" in out
    assert "inet 127.0.0.1/8" in out
    assert "2: eth0:" in out
    assert "inet 11.0.0.1/8" in out  # the simulated address, /8 assignment
    assert "state UP" in out
    # deterministic MAC derived from the simulated IP
    assert "link/ether 02:54:0b:00:00:01" in out
    assert not result.process_errors


@pytest.mark.skipif(IP_BIN is None, reason="iproute2 not installed")
def test_iproute2_netlink_deterministic(tmp_path):
    _, out1 = _run_ip(tmp_path, "r1")
    _, out2 = _run_ip(tmp_path, "r2")
    assert out1 == out2
