"""Fleet-sweep subsystem tests (shadow_tpu/sweep/): the batched
S-scenario kernel vs S serial runs, bit-identical per scenario.

The sweep correctness law (docs/sweep.md): stacking S congruent
scenarios on a leading vmap axis and running them through ONE compiled
kernel must reproduce every scenario's serial trajectory exactly —
state, counters, event log, netobs telemetry — with exactly one XLA
trace serving the whole fleet.
"""

import dataclasses
import inspect

import pytest

from shadow_tpu.backend.cpu_engine import CpuEngine
from shadow_tpu.backend.tpu_engine import TpuEngine
from shadow_tpu.config import presets, scenarios
from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.config.presets import flagship_mesh_config
from shadow_tpu.sweep import (
    SweepCongruenceError,
    SweepEngine,
    SweepSpec,
    build_report,
    expand_variants,
    write_report,
)
from shadow_tpu.sweep.variants import check_congruence

pytestmark = pytest.mark.sweep

LOSS_EVENT = {
    "at": "200 ms", "kind": "loss", "source": 0, "target": 0, "loss": 0.1,
}


def _mesh(seed: int = 42, n: int = 8) -> ConfigOptions:
    return flagship_mesh_config(n, sim_seconds=1, backend="tpu", seed=seed)


def _assert_results_equal(batched, serial, label):
    assert int(batched.rounds) == int(serial.rounds), label
    keys = sorted(set(batched.counters) | set(serial.counters))
    for k in keys:
        assert int(batched.counters.get(k, 0)) == int(
            serial.counters.get(k, 0)
        ), f"{label}: counter {k}"
    assert batched.log_tuples() == serial.log_tuples(), f"{label}: log"


# -- batched vs serial bit-identity ---------------------------------------


@pytest.mark.parametrize("size", [1, 2, 4])
def test_seed_grid_matches_serial(size):
    """S in {1, 2, 4} seed grids: every scenario of the batched run is
    bit-identical to its serial device-mode run, under ONE trace."""
    spec = SweepSpec.seed_grid(42, size)
    variants = expand_variants(_mesh(), spec)
    sweep = SweepEngine(variants)
    results = sweep.run()
    assert sweep.traces == 1
    for v, r in zip(variants, results):
        ref = TpuEngine(v.cfg).run(mode="device")
        _assert_results_equal(r, ref, v.label)


def test_fault_grid_matches_serial_with_netobs():
    """seed x fault grid with the netobs telemetry plane on: counters,
    window histograms, and every netobs array bit-identical to serial
    faulted runs."""
    import numpy as np

    base = _mesh()
    base.experimental.netobs = True
    spec = SweepSpec(seeds=[42, 43], faults=[[], [LOSS_EVENT]])
    variants = expand_variants(base, spec)
    sweep = SweepEngine(variants)
    results = sweep.run()
    assert sweep.traces == 1
    for v, r in zip(variants, results):
        eng = TpuEngine(v.cfg)
        ref = eng.run(mode="device")
        _assert_results_equal(r, ref, v.label)
        got = sweep.engines[v.index]._netobs_data
        want = eng._netobs_data
        assert got is not None and want is not None
        assert list(got["window_hist"]) == list(want["window_hist"]), v.label
        for k in sorted(want["arrays"]):
            assert np.array_equal(
                np.asarray(got["arrays"][k]), np.asarray(want["arrays"][k])
            ), f"{v.label}: netobs array {k}"
    # the lossy axis must actually diverge the fleet
    drops = [int(r.counters.get("lane_drop_loss", 0)) for r in results]
    assert any(d > 0 for d in drops) and any(d == 0 for d in drops)


@pytest.mark.parametrize("size", [1, 2, 4])
def test_cpu_backend_arm_matches_serial(size):
    """backend='cpu' runs the scalar oracle serially behind the same
    API: every sweep result equals a fresh CpuEngine run, S in
    {1, 2, 4}."""
    spec = SweepSpec.seed_grid(42, size)
    cpu_sweep = SweepEngine(expand_variants(_mesh(n=6), spec), backend="cpu")
    cpu_results = cpu_sweep.run()
    assert cpu_sweep.traces == 0  # no batched kernel on the oracle arm
    for v, r in zip(cpu_sweep.variants, cpu_results):
        ref = CpuEngine(v.cfg).run()
        _assert_results_equal(r, ref, v.label)


def test_cross_backend_parity():
    """On a parity-safe config the tpu sweep's logs and counters match
    the cpu arm exactly (the cross-backend leg of the correctness law)."""
    base = _mesh(n=6)
    spec = SweepSpec.seed_grid(42, 2)
    cpu_sweep = SweepEngine(expand_variants(base, spec), backend="cpu")
    cpu_results = cpu_sweep.run()
    tpu_sweep = SweepEngine(expand_variants(base, spec))
    tpu_results = tpu_sweep.run()
    assert tpu_sweep.traces == 1
    for v, c, t in zip(cpu_sweep.variants, cpu_results, tpu_results):
        # backend-local counters (lane_*) differ by catalog; the parity
        # law binds the event log and the shared counter keys
        assert t.log_tuples() == c.log_tuples(), f"cross-backend {v.label}"
        for k in sorted(set(t.counters) & set(c.counters)):
            assert int(t.counters[k]) == int(c.counters[k]), (
                f"cross-backend {v.label}: counter {k}"
            )


def test_scenario_axis_sharded_matches_serial():
    """Sweep x mesh composition (docs/multichip.md): with
    ``experimental.mesh_devices`` set, the batch shards WHOLE scenarios
    across devices (the scenario axis, not the 8-host lane axis) — one
    trace, and every scenario still bit-identical to its serial
    single-device run."""
    base = _mesh()
    base.experimental.mesh_devices = 4
    spec = SweepSpec.seed_grid(42, 4)
    variants = expand_variants(base, spec)
    sweep = SweepEngine(variants)
    results = sweep.run()
    assert sweep.traces == 1
    for v, r in zip(variants, results):
        ref = TpuEngine(v.cfg).run(mode="device")
        _assert_results_equal(r, ref, v.label)


def test_scenario_axis_fallback_when_indivisible():
    """S=3 does not divide mesh_devices=2: the negotiation steps down to
    a single device and the sweep still runs (transparent fallback)."""
    base = _mesh()
    base.experimental.mesh_devices = 2
    variants = expand_variants(base, SweepSpec.seed_grid(42, 3))
    sweep = SweepEngine(variants)
    results = sweep.run()
    assert sweep.traces == 1 and len(results) == 3


# -- congruence rejection -------------------------------------------------


def test_latency_override_rejected():
    """Config-level latency changes move the static runahead — the
    override axis must reject them with guidance toward the fault axis."""
    spec = SweepSpec(
        overrides=[{}, {"experimental.runahead": "20 ms"}],
    )
    variants = expand_variants(_mesh(), spec)
    with pytest.raises(SweepCongruenceError, match="fault axis"):
        SweepEngine(variants)


def test_backend_stall_rejected():
    spec = SweepSpec(
        faults=[[{"at": "200 ms", "kind": "backend_stall"}]],
    )
    with pytest.raises(SweepCongruenceError, match="backend_stall"):
        expand_variants(_mesh(), spec)


def test_flowtrace_seed_grid_rejected():
    base = _mesh()
    base.experimental.flowtrace = True
    variants = expand_variants(base, SweepSpec.seed_grid(42, 2))
    with pytest.raises(SweepCongruenceError, match="flowtrace"):
        SweepEngine(variants)


def test_differing_topology_rejected():
    with pytest.raises(SweepCongruenceError, match="not shape-congruent"):
        check_congruence([TpuEngine(_mesh(n=8)), TpuEngine(_mesh(n=12))])


def test_unknown_spec_keys_rejected():
    with pytest.raises(SweepCongruenceError, match="unknown"):
        SweepSpec.from_dict({"seeds": [1], "bogus": 3})


# -- padded fault epochs (satellite: pad-to-static) -----------------------


def test_padded_fault_plan_matches_unpadded():
    """Trailing zero-length pad rows in the segment plan are bit-inert:
    a serial faulted run forced through a padded plan (_fault_pad) is
    identical to the unpadded run (the padded-epoch representation that
    lets unequal-depth schedules share one batch)."""
    cfg = _mesh()
    cfg.faults.events = [dict(LOSS_EVENT)]
    ref = TpuEngine(cfg).run(mode="device")
    eng = TpuEngine(cfg)
    eng._fault_pad = 4
    padded = eng.run(mode="device")
    _assert_results_equal(padded, ref, "padded-vs-unpadded")


def test_segment_plan_padding_shape():
    cfg = _mesh()
    cfg.faults.events = [dict(LOSS_EVENT)]
    eng = TpuEngine(cfg)
    ov = eng._fault_overlay
    stop = cfg.general.stop_time
    plan = ov.segment_plan(stop, pad_to=5)
    assert len(plan) == 5
    # real segments tile [0, stop); pad rows are zero-length at stop
    assert plan[0][0] == 0 and plan[-1] == (stop, stop, plan[1][2])
    for seg_start, seg_end, _ in plan[2:]:
        assert seg_start == seg_end == stop


# -- report aggregation ---------------------------------------------------


def test_report_byte_identical_and_stats(tmp_path):
    spec = SweepSpec(name="rpt", seeds=[42, 43], faults=[[], [LOSS_EVENT]])
    variants = expand_variants(_mesh(), spec)
    sweep = SweepEngine(variants)
    results = sweep.run()
    rep = build_report(sweep, results, name="rpt")
    assert rep["size"] == 4 and len(rep["scenarios"]) == 4
    cross = rep["cross"]["lane_drop_loss"]
    assert cross["max"] > cross["min"]  # the loss axis diverges
    assert set(cross) == {"p50", "p90", "p99", "min", "max", "outliers"}
    for row in rep["scenarios"]:
        assert row["drops"]["loss"] == row["counters"].get(
            "lane_drop_loss", 0
        )
    p1 = write_report(rep, tmp_path / "a")
    p2 = write_report(
        build_report(sweep, results, name="rpt"), tmp_path / "b"
    )
    assert p1.name == "SWEEP_rpt-S4.json"
    assert p1.read_bytes() == p2.read_bytes()


def test_outlier_flags():
    from shadow_tpu.sweep.report import _cross_stats

    st = _cross_stats([100, 100, 100, 250])
    assert st["outliers"] == [3]
    assert _cross_stats([5, 5, 5, 5])["outliers"] == []


# -- seed threading audit (satellite: explicit seed kwargs) ----------------


SEED_FACTORIES = [
    (presets.flagship_mesh_config, {"n_hosts": 4}),
    (presets.transfer_pair_config, {}),
    (presets.udp_star_config, {"n_hosts": 4}),
    (presets.mixed_flagship_config, {"n_hosts": 6}),
    (scenarios.managed_chain_config, {"data_dir": "/tmp/x"}),
    (scenarios.managed_relay_chains_large, {"data_dir": "/tmp/x"}),
    (scenarios.managed_relay_chains_gate, {"data_dir": "/tmp/x"}),
]


@pytest.mark.parametrize(
    "factory,kwargs", SEED_FACTORIES, ids=lambda f: getattr(f, "__name__", "")
)
def test_scenario_factories_thread_seed(factory, kwargs):
    """Every scenario/preset factory accepts an explicit ``seed`` kwarg
    and threads it into ``general.seed`` — the contract the sweep seed
    axis builds on (a factory that pins its own seed would silently
    collapse a seed grid into S copies of one scenario)."""
    sig = inspect.signature(factory)
    assert "seed" in sig.parameters, factory.__name__
    assert sig.parameters["seed"].default is not inspect.Parameter.empty
    cfg = factory(seed=777, **kwargs)
    assert cfg.general.seed == 777, factory.__name__
