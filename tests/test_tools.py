"""shadowtools analog: typed config builders + shadow_exec one-shot runner."""

import subprocess
from pathlib import Path

import pytest

from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.tools import HostDict, ProcessDict, SimData, make_config, shadow_exec

REPO = Path(__file__).resolve().parents[1]


def test_make_config_roundtrip():
    doc = make_config(
        stop_time="2s",
        seed=9,
        hosts={
            "a": HostDict(
                network_node_id=0,
                processes=[ProcessDict(path="ping", args=["--peer", "b"])],
            ),
            "b": HostDict(network_node_id=0, processes=[ProcessDict(path="ping")]),
        },
        experimental={"network_backend": "cpu"},
    )
    cfg = ConfigOptions.from_dict(doc)
    cfg.validate()
    assert cfg.general.seed == 9
    assert [h.hostname for h in cfg.hosts] == ["a", "b"]
    assert cfg.hosts[0].processes[0].args == ["--peer", "b"]


@pytest.fixture(scope="module")
def native_build():
    subprocess.run(
        ["make", "-C", str(REPO / "native")], check=True, capture_output=True
    )


def test_shadow_exec_real_date_sees_simulated_clock(native_build):
    # the reference's README demo: `shadow-exec date` prints the simulated
    # epoch — an unmodified /bin/date under the shim
    date = "/bin/date" if Path("/bin/date").exists() else "/usr/bin/date"
    res = shadow_exec([date, "-u"], stop_time="5s")
    assert res.ok, res.stdout
    assert "2000" in res.stdout  # simulation epoch is 2000-01-01
    assert "Jan" in res.stdout


def test_shadow_exec_sleep_runs_in_simulated_time(native_build):
    # /bin/sleep 500 completes in milliseconds of wall time: the sleep is
    # simulated.  (bash -c 'date; sleep; date' needs fork/child support,
    # which the shim does not have yet — single-process plugins only.)
    sleep = "/bin/sleep" if Path("/bin/sleep").exists() else "/usr/bin/sleep"
    res = shadow_exec([sleep, "500"], stop_time="1000s")
    assert res.ok
    assert res.sim_stats["wall_seconds"] < 5.0
    assert res.sim_stats["counters"]["managed_procs"] == 1
    assert res.sim_stats["counters"]["managed_exit_clean"] == 1


def test_shadow_exec_preserve_data(native_build, tmp_path):
    date = "/bin/date" if Path("/bin/date").exists() else "/usr/bin/date"
    res = shadow_exec([date], stop_time="5s", data_directory=tmp_path / "d")
    assert res.data is not None
    assert isinstance(res.data, SimData)
    assert res.data.hosts() == ["host0"]
    assert "2000" in res.data.stdout("host0", "date")
    assert res.data.stats()["backend"] == "cpu"


def test_shadow_exec_cli(native_build):
    import sys

    proc = subprocess.run(
        [sys.executable, "-m", "shadow_tpu.tools", "--stop-time", "5s", "--",
         "/bin/echo", "hello-sim"],
        capture_output=True,
        text=True,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr
    assert "hello-sim" in proc.stdout
