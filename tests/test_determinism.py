"""Run-twice determinism: the reference's regression gate
(src/test/determinism/CMakeLists.txt) — same config, two fresh runs,
bit-identical event orderings and counters required.
"""

import subprocess
from pathlib import Path

import pytest

from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.engine.determinism import compare_results, determinism_check

REPO = Path(__file__).resolve().parents[1]

PHOLD = """
general: {stop_time: 400ms, seed: 13, heartbeat_interval: null}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        node [ id 1 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        edge [ source 0 target 0 latency "2 ms" ]
        edge [ source 0 target 1 latency "5 ms" packet_loss 0.1 ]
        edge [ source 1 target 1 latency "2 ms" ]
      ]
hosts:
  a: {network_node_id: 0, processes: [{path: phold, args: [--messages, "4"]}]}
  b: {network_node_id: 1, processes: [{path: phold, args: [--messages, "4"]}]}
  c: {network_node_id: 1, processes: [{path: phold, args: [--messages, "3"]}]}
"""


PHOLD_FAULTED = PHOLD + """
faults:
  events:
    - {at: 100ms, kind: loss, source: 0, target: 1, loss: 0.5}
    - {at: 200ms, kind: link_down, source: 0, target: 1}
    - {at: 300ms, kind: link_up, source: 0, target: 1}
"""


def test_phold_cpu_run_twice_identical():
    report = determinism_check(ConfigOptions.from_yaml(PHOLD))
    assert report.identical, report.describe()
    assert report.records > 50
    assert "PASSED" in report.describe()


@pytest.mark.faults
def test_phold_faulted_cpu_run_twice_identical():
    # same seed + same fault schedule -> bit-identical event logs: every
    # fault epoch is a deterministic window-clamp boundary (docs/faults.md)
    report = determinism_check(ConfigOptions.from_yaml(PHOLD_FAULTED))
    assert report.identical, report.describe()
    assert report.records > 20


@pytest.mark.faults
def test_phold_faulted_tpu_run_twice_identical():
    cfg = ConfigOptions.from_yaml(PHOLD_FAULTED)
    cfg.experimental.network_backend = "tpu"
    report = determinism_check(cfg)
    assert report.identical, report.describe()


def test_phold_tpu_run_twice_identical():
    cfg = ConfigOptions.from_yaml(PHOLD)
    cfg.experimental.network_backend = "tpu"
    report = determinism_check(cfg)
    assert report.identical, report.describe()


def test_seed_changes_the_run():
    cfg1 = ConfigOptions.from_yaml(PHOLD)
    cfg2 = ConfigOptions.from_yaml(PHOLD)
    cfg2.general.seed = 14
    from shadow_tpu.backend.cpu_engine import CpuEngine

    r1 = CpuEngine(cfg1).run()
    r2 = CpuEngine(cfg2).run()
    report = compare_results(r1, r2)
    assert not report.identical
    assert "FAILED" in report.describe()


def test_parallelism_does_not_change_the_run():
    # the reference's determinism1 runs with --parallelism 2; ordering must
    # not depend on the worker count
    cfg1 = ConfigOptions.from_yaml(PHOLD)
    cfg2 = ConfigOptions.from_yaml(PHOLD)
    cfg2.general.parallelism = 2
    from shadow_tpu.backend.cpu_engine import CpuEngine

    report = compare_results(CpuEngine(cfg1).run(), CpuEngine(cfg2).run())
    assert report.identical, report.describe()


def test_cli_determinism_check(tmp_path):
    cfg_path = tmp_path / "phold.yaml"
    cfg_path.write_text(PHOLD)
    proc = subprocess.run(
        ["python", "-m", "shadow_tpu", str(cfg_path), "--determinism-check",
         "--data-directory", str(tmp_path / "data")],
        capture_output=True,
        text=True,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr
    assert "determinism check PASSED" in proc.stderr


@pytest.fixture(scope="module")
def native_build():
    subprocess.run(
        ["make", "-C", str(REPO / "native")], check=True, capture_output=True
    )


def test_managed_native_run_twice_identical(native_build, tmp_path):
    build = REPO / "native" / "build"
    cfg = ConfigOptions.from_yaml(
        f"""
general: {{stop_time: 2s, seed: 21, data_directory: {tmp_path / 'data'}, heartbeat_interval: null}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  cli:
    network_node_id: 0
    processes:
      - path: {build / 'pingpong'}
        args: [client, 11.0.0.2, "9000", "4", "100"]
  srv:
    network_node_id: 0
    processes:
      - path: {build / 'pingpong'}
        args: [server, "9000", "4"]
"""
    )
    report = determinism_check(cfg)
    assert report.identical, report.describe()
