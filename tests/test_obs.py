"""Observability subsystem (shadow_tpu/obs/, docs/observability.md).

Four contracts under test:

1. **Golden perf-log line formats** — the docstring promise that
   ``[window-agg]`` / ``[host-exec-agg]`` / ``[hybrid-agg]`` lines are
   fork-parseable is pinned here character for character, and every
   emission rides ONE locked ``emit()`` (whole lines, never interleaved,
   worker-process lines forwarded to the parent sink).
2. **Tracer/metrics correctness** — Chrome-trace export shape, METRICS
   report schema, and the span-sum ↔ ``phase_wall_s`` cross-check (both
   sides are fed from the same clock pair, so they agree exactly).
3. **Determinism with obs fully enabled** — run-twice shadow logs are
   bit-identical on the cpu, cpu_mp (workers 2), and hybrid backends
   with tracing + metrics + perf logging all on.
4. **Zero overhead when disabled** — engines default to ``obs=None``
   and no obs module is touched.
"""

import io
import json
import subprocess
import threading
from pathlib import Path

import pytest

from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.engine.run_control import (
    BufferedPerfLog,
    PerfLog,
    RunControl,
)
from shadow_tpu.engine.sim import Simulation
from shadow_tpu.obs import MetricsRegistry, Recorder, Tracer

pytestmark = pytest.mark.obs

REPO = Path(__file__).resolve().parents[1]
BUILD = REPO / "native" / "build"

SYNC_STATS = {
    "device_turns": 3,
    "device_sync_s": 0.25,
    "syscall_service_s": 0.125,
    "scalar_reads": 3,
    "inject_blocks": 1,
    "inject_rows": 7,
    "inject_bytes": 12800,
    "egress_reads": 2,
    "egress_rows": 9,
    "egress_bytes": 96,
}


# ---------------------------------------------------------------------------
# 1. golden perf-log formats + the locked emit path
# ---------------------------------------------------------------------------


class TestPerfLogGoldenFormats:
    def test_window_agg_format(self):
        out = io.StringIO()
        PerfLog(out=out).window_agg(3, 1000, 2000, 1500)
        assert out.getvalue() == (
            "[window-agg] active_hosts_in_window=3 "
            "window_start_ns=1000 window_end_ns=2000 next_event_ns=1500\n"
        )

    def test_host_exec_agg_format(self):
        out = io.StringIO()
        pl = PerfLog(out=out)
        pl.HOST_EXEC_LOG_EVERY = 2  # instance override: emit on call 2
        pl.host_exec("alpha", 10, 500)
        assert out.getvalue() == ""  # below the every-N threshold
        pl.host_exec("beta", 30, 700)
        assert out.getvalue() == (
            "[host-exec-agg] calls=2 total_ns=40 last_ns=30 "
            "host=beta window_end_abs_ns=700\n"
        )

    def test_hybrid_agg_format(self):
        out = io.StringIO()
        PerfLog(out=out).hybrid_agg("device", 102000000, SYNC_STATS)
        assert out.getvalue() == (
            "[hybrid-agg] kind=device window_end_ns=102000000 "
            "device_turns=3 device_sync_ns=250000000 "
            "syscall_service_ns=125000000 scalar_reads=3 "
            "inject_blocks=1 inject_rows=7 inject_bytes=12800 "
            "egress_reads=2 egress_rows=9 egress_bytes=96\n"
        )

    def test_emit_is_atomic_under_threads(self):
        # the satellite bug: window_agg/hybrid_agg used to print without
        # the lock, so concurrent emitters could interleave fragments.
        # Hammer emit from threads and require every line intact.
        out = io.StringIO()
        pl = PerfLog(out=out)

        def hammer(tag):
            for i in range(200):
                pl.window_agg(tag, i, i + 1, i + 2)

        threads = [
            threading.Thread(target=hammer, args=(t,)) for t in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        lines = out.getvalue().splitlines()
        assert len(lines) == 800
        for line in lines:
            assert line.startswith("[window-agg] active_hosts_in_window=")
            assert line.count("window_start_ns=") == 1

    def test_buffered_perf_log_forwards_through_emit_many(self):
        # the worker side buffers; the parent's locked sink prints —
        # exactly the pipe-forwarding round trip, minus the pipe
        wpl = BufferedPerfLog()
        wpl.window_agg(1, 0, 100, 50)
        wpl.hybrid_agg("host", 100, SYNC_STATS)
        lines = wpl.drain()
        assert len(lines) == 2 and wpl.drain() == []
        out = io.StringIO()
        PerfLog(out=out).emit_many(lines)
        got = out.getvalue().splitlines()
        assert got[0] == PerfLog.format_window_agg(1, 0, 100, 50)
        assert got[1] == PerfLog.format_hybrid_agg("host", 100, SYNC_STATS)


# ---------------------------------------------------------------------------
# 2. tracer / metrics / recorder units
# ---------------------------------------------------------------------------


class TestTracer:
    def test_export_shape(self, tmp_path):
        tr = Tracer()
        tr.complete("w", "window_compute", tr.t0, 0.002, {"we": 5})
        tr.instant("mark", "mark")
        doc = json.loads(tr.export(tmp_path / "t.json").read_text())
        assert doc["displayTimeUnit"] == "ms"
        spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        assert len(spans) == 1
        ev = spans[0]
        assert ev["name"] == "w" and ev["cat"] == "window_compute"
        assert ev["pid"] == 1 and isinstance(ev["tid"], int)
        assert ev["dur"] == pytest.approx(2000.0)
        assert ev["args"] == {"we": 5}
        # thread-name metadata rows for Perfetto
        assert any(e.get("ph") == "M" for e in doc["traceEvents"])

    def test_capacity_bound(self):
        tr = Tracer(capacity=3)
        for i in range(5):
            tr.complete(f"s{i}", "c", tr.t0, 0.001)
        assert tr.span_count() == 3 and tr.dropped == 2

    def test_disable_toggle(self):
        tr = Tracer()
        tr.enabled = False
        tr.complete("s", "c", tr.t0, 0.001)
        assert tr.span_count() == 0


class TestMetricsRegistry:
    def test_counters_gauges_histograms(self):
        m = MetricsRegistry(run_id="t")
        m.count("windows")
        m.count("windows", 2)
        m.gauge("workers", 4)
        for v in (1, 2, 3, 4, 100):
            m.observe("active", v)
        rep = m.report()
        assert rep["counters"] == {"windows": 3}
        assert rep["gauges"] == {"workers": 4}
        h = rep["histograms"]["active"]
        assert h["count"] == 5 and h["min"] == 1 and h["max"] == 100
        assert h["mean"] == pytest.approx(22.0)
        assert h["p50"] == 3

    def test_phase_walls_and_report_schema(self):
        m = MetricsRegistry(run_id="t")
        m.phase_add("device_turn", 0.5)
        m.phase_add("device_turn", 0.25)
        m.phase_add("egress", 0.125)
        rep = m.report(extra={"backend": "tpu"})
        assert rep["phase_wall_s"] == {
            "device_turn": 0.75, "egress": 0.125,
        }
        assert rep["phases"]["device_turn"]["spans"] == 2
        assert rep["phase_wall_total_s"] == pytest.approx(0.875)
        assert rep["backend"] == "tpu"
        assert rep["schema"] == 1

    def test_timer_observes(self):
        m = MetricsRegistry(run_id="t")
        with m.timer("block"):
            pass
        assert m.report()["histograms"]["block"]["count"] == 1

    def test_jsonl_stream(self, tmp_path):
        m = MetricsRegistry(run_id="t", jsonl_path=tmp_path / "m.jsonl")
        m.stream({"ev": "mark", "name": "x"})
        m.close()
        lines = (tmp_path / "m.jsonl").read_text().splitlines()
        assert [json.loads(l)["ev"] for l in lines] == ["mark"]


class TestRecorder:
    def test_phase_span_feeds_metrics_and_trace(self, tmp_path):
        rec = Recorder(run_id="t", out_dir=tmp_path, trace=True)
        with rec.phase("window_compute", window_end=7):
            pass
        rec.record("egress", None, rec.tracer.t0, 0.5, rows=3)
        fin = rec.finalize(extra={"backend": "cpu"})
        rep = json.loads(Path(fin["metrics_path"]).read_text())
        assert set(rep["phase_wall_s"]) == {"window_compute", "egress"}
        assert rep["phase_wall_s"]["egress"] == pytest.approx(0.5)
        doc = json.loads(Path(fin["trace_path"]).read_text())
        spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        # the cross-check: per-phase span sums equal the report totals
        summed = {}
        for e in spans:
            summed[e["cat"]] = summed.get(e["cat"], 0.0) + e["dur"] / 1e6
        for phase, wall in rep["phase_wall_s"].items():
            assert summed[phase] == pytest.approx(wall, abs=1e-9)
        # finalize is idempotent
        assert rec.finalize() is fin

    def test_engines_default_obs_none(self):
        # the zero-overhead contract: nothing enables obs implicitly
        from shadow_tpu.backend.cpu_engine import CpuEngine
        from shadow_tpu.backend.cpu_mp import MpCpuEngine

        cfg = _ping_cfg("/tmp/obs-none", obs="")
        assert CpuEngine(cfg).obs is None
        assert MpCpuEngine(cfg, workers=2).obs is None
        sim = Simulation(cfg)
        assert sim.obs is None  # set per run(); obs_* all default off


# ---------------------------------------------------------------------------
# 3. run-twice determinism with obs fully enabled
# ---------------------------------------------------------------------------

OBS_ALL = (
    "obs_metrics: true, obs_trace: true, obs_jsonl: true, "
    "perf_logging: true"
)


def _ping_cfg(data_dir, obs: str = OBS_ALL, backend: str = "cpu",
              workers: int = 1) -> ConfigOptions:
    extra = f", {obs}" if obs else ""
    return ConfigOptions.from_yaml(f"""
general: {{stop_time: 1s, seed: 7, data_directory: {data_dir},
           heartbeat_interval: null}}
experimental: {{network_backend: {backend}{extra}}}
hosts:
  a: {{processes: [{{path: ping, args: --peer b --count 5 --interval 100ms}}]}}
  b: {{processes: [{{path: ping}}]}}
  c: {{processes: [{{path: ping, args: --peer d --count 5 --interval 100ms}}]}}
  d: {{processes: [{{path: ping}}]}}
""")


def _hybrid_cfg(data_dir) -> ConfigOptions:
    mesh = "\n".join(f"""
  zm{i:03d}:
    network_node_id: 0
    processes:
      - path: tgen-mesh
        args: --interval 50ms --size 600
        start_time: 0 s
""" for i in range(4))
    return ConfigOptions.from_yaml(f"""
general: {{stop_time: 1s, seed: 21, data_directory: {data_dir},
           heartbeat_interval: null}}
network: {{graph: {{type: 1_gbit_switch}}}}
experimental: {{network_backend: tpu, {OBS_ALL}}}
hosts:
  cli:
    network_node_id: 0
    processes:
      - path: {BUILD / 'pingpong'}
        args: [client, 11.0.0.2, "9000", "3", "100"]
  srv:
    network_node_id: 0
    processes:
      - path: {BUILD / 'pingpong'}
        args: [server, "9000", "3"]
{mesh}
""")


class TestObsDeterminism:
    def test_cpu_run_twice_byte_identical(self, tmp_path):
        results = []
        for tag in ("r1", "r2"):
            sim = Simulation(_ping_cfg(tmp_path / tag))
            results.append(sim.run(write_data=False))
        r1, r2 = results
        assert r1.log_tuples() == r2.log_tuples()
        assert r1.counters == r2.counters

    def test_cpu_mp_run_twice_byte_identical(self, tmp_path):
        from shadow_tpu.backend.cpu_mp import MpCpuEngine

        logs = []
        for tag in ("r1", "r2"):
            eng = MpCpuEngine(_ping_cfg(tmp_path / tag), workers=2)
            eng.obs = Recorder(run_id=tag, trace=True)
            eng.perf_log = PerfLog(out=io.StringIO())
            logs.append(eng.run())
        assert logs[0].log_tuples() == logs[1].log_tuples()
        assert logs[0].counters == logs[1].counters

    def test_obs_on_equals_obs_off(self, tmp_path):
        # obs must never feed back into the simulation: the obs-on log
        # diffs EQUAL against a plain run of the same config
        on = Simulation(_ping_cfg(tmp_path / "on")).run(write_data=False)
        off = Simulation(
            _ping_cfg(tmp_path / "off", obs="")
        ).run(write_data=False)
        assert on.log_tuples() == off.log_tuples()
        assert on.counters == off.counters


@pytest.mark.hybrid
class TestObsHybrid:
    @pytest.fixture(scope="class", autouse=True)
    def native_build(self):
        subprocess.run(
            ["make", "-C", str(REPO / "native")],
            check=True, capture_output=True,
        )

    def test_hybrid_run_twice_identical_with_artifacts(self, tmp_path):
        runs = []
        for tag in ("r1", "r2"):
            sim = Simulation(_hybrid_cfg(tmp_path / tag))
            runs.append((sim.run(), sim))
        (r1, s1), (r2, s2) = runs
        assert r1.log_tuples() == r2.log_tuples()
        assert r1.counters == r2.counters
        # the acceptance cross-check: the trace's device-turn, injection,
        # egress, and syscall-service span sums match the METRICS report
        fin = s1.obs.finalized
        rep = json.loads(Path(fin["metrics_path"]).read_text())
        assert {"device_turn", "injection", "egress",
                "syscall_service"} <= set(rep["phase_wall_s"])
        assert "hybrid_sync" in rep
        doc = json.loads(Path(fin["trace_path"]).read_text())
        summed: dict[str, float] = {}
        for e in doc["traceEvents"]:
            if e.get("ph") == "X":
                summed[e["cat"]] = summed.get(e["cat"], 0.0) + e["dur"] / 1e6
        for phase, wall in rep["phase_wall_s"].items():
            assert summed[phase] == pytest.approx(wall, abs=1e-6), phase


# ---------------------------------------------------------------------------
# 4. worker perf-line forwarding (cpu_mp) — end to end over real pipes
# ---------------------------------------------------------------------------


class TestWorkerPerfForwarding:
    def test_mp_cpu_forwards_host_exec_lines(self, tmp_path):
        # 1ms ping cadence => ~1000 rounds; each worker owns 2 of 4
        # hosts, so its host_exec call count crosses the 1000-line
        # threshold and at least one [host-exec-agg] line must ride the
        # pipe to the parent sink
        from shadow_tpu.backend.cpu_mp import MpCpuEngine

        cfg = ConfigOptions.from_yaml(f"""
general: {{stop_time: 600ms, seed: 5, data_directory: {tmp_path / 'd'},
           heartbeat_interval: null}}
experimental: {{perf_logging: true}}
hosts:
  a: {{processes: [{{path: ping, args: --peer b --count 550 --interval 1ms}}]}}
  b: {{processes: [{{path: ping}}]}}
  c: {{processes: [{{path: ping, args: --peer d --count 550 --interval 1ms}}]}}
  d: {{processes: [{{path: ping}}]}}
""")
        eng = MpCpuEngine(cfg, workers=2)
        out = io.StringIO()
        eng.perf_log = PerfLog(out=out)
        eng.run()
        lines = out.getvalue().splitlines()
        agg = [l for l in lines if l.startswith("[host-exec-agg]")]
        assert agg, "no worker perf lines were forwarded to the parent"
        for line in agg:
            assert " host=" in line and " window_end_abs_ns=" in line


# ---------------------------------------------------------------------------
# run-control stats / trace verbs
# ---------------------------------------------------------------------------


class TestRunControlObsVerbs:
    def test_stats_without_obs_reports_disabled(self):
        out = io.StringIO()
        rc = RunControl(out=out)
        rc._apply("stats")
        assert "obs is not enabled" in out.getvalue()

    def test_stats_prints_snapshot(self):
        out = io.StringIO()
        rc = RunControl(out=out)
        rec = Recorder(run_id="t")
        rec.metrics.count("windows", 3)
        rec.metrics.phase_add("window_compute", 0.5)
        rc.set_obs(rec)
        rc._apply("stats")
        text = out.getvalue()
        assert "windows=3" in text and "window_compute" in text

    def test_trace_status_toggle_and_dump(self, tmp_path):
        out = io.StringIO()
        rc = RunControl(out=out)
        rec = Recorder(run_id="t", out_dir=tmp_path, trace=True)
        with rec.phase("window_compute"):
            pass
        rc.set_obs(rec)
        rc._apply("trace")
        assert "1 span(s) recorded" in out.getvalue()
        rc._apply("trace off")
        assert not rec.tracer.enabled
        rc._apply("trace on")
        assert rec.tracer.enabled
        path = tmp_path / "dump.json"
        rc._apply(f"trace dump {path}")
        assert "trace written" in out.getvalue()
        assert json.loads(path.read_text())["traceEvents"]

    def test_trace_without_tracer_reports_disabled(self):
        out = io.StringIO()
        rc = RunControl(out=out)
        rc.set_obs(Recorder(run_id="t"))  # metrics only
        rc._apply("trace")
        assert "tracing is not enabled" in out.getvalue()

    def test_stats_verb_live_at_pause(self, tmp_path):
        # scripted console: pause, ask for stats, resume — the verb
        # answers from the live recorder mid-run
        out = io.StringIO()
        rc = RunControl(out=out, poll_interval=0.01, max_wait=10)
        rc.feed("p", "stats", "c")
        sim = Simulation(_ping_cfg(tmp_path / "d"), run_control=rc)
        sim.run(write_data=False)
        assert "[run-control] stats:" in out.getvalue()
        assert "phase walls:" in out.getvalue()


# ---------------------------------------------------------------------------
# CLI flags
# ---------------------------------------------------------------------------


class TestCliFlags:
    def test_obs_flags_map_to_overrides(self):
        from shadow_tpu.__main__ import build_parser, parse_overrides

        ns = build_parser().parse_args(
            ["cfg.yaml", "--obs-metrics", "--obs-trace"]
        )
        assert ns.obs_metrics and ns.obs_trace
        # parse_overrides only carries dotted keys; the main() shim adds
        # the experimental.* overrides — mirror it here
        overrides = parse_overrides(ns)
        assert "experimental.obs_metrics" not in overrides  # added by main
