"""Managed real-binary execution under the native LD_PRELOAD shim.

The round-1 end-to-end slice of the reference's defining capability
(SURVEY.md §7 step 4): a real, unmodified C binary runs as an OS process,
is co-opted into the simulation via interposed libc (time from the shmem
sim clock, sleep/UDP through the futex channel), and exchanges datagrams
with a peer across the simulated network — bit-deterministically.
"""

import json
import subprocess
from pathlib import Path

import pytest

from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.engine.sim import Simulation

REPO = Path(__file__).resolve().parents[1]
BUILD = REPO / "native" / "build"


@pytest.fixture(scope="module", autouse=True)
def native_build():
    subprocess.run(
        ["make", "-C", str(REPO / "native")], check=True, capture_output=True
    )
    assert (BUILD / "libshadow_shim.so").exists()
    assert (BUILD / "pingpong").exists()


def _config(tmp_path: Path, count: int = 5) -> ConfigOptions:
    # cli sorts before srv: cli = 11.0.0.1, srv = 11.0.0.2
    return ConfigOptions.from_yaml(
        f"""
general: {{stop_time: 2s, seed: 21, data_directory: {tmp_path / 'data'}, heartbeat_interval: null}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  cli:
    network_node_id: 0
    processes:
      - path: {BUILD / 'pingpong'}
        args: [client, 11.0.0.2, "9000", "{count}", "100"]
  srv:
    network_node_id: 0
    processes:
      - path: {BUILD / 'pingpong'}
        args: [server, "9000", "{count}"]
"""
    )


def _run(tmp_path: Path, count: int = 5):
    sim = Simulation(_config(tmp_path, count))
    result = sim.run()
    data = tmp_path / "data"
    cli_out = (data / "hosts" / "cli" / "pingpong.stdout").read_text()
    srv_out = (data / "hosts" / "srv" / "pingpong.stdout").read_text()
    return result, cli_out, srv_out


def test_pingpong_end_to_end(tmp_path):
    result, cli_out, srv_out = _run(tmp_path)
    # 5 pings + 5 echoes, all delivered
    delivered = [r for r in result.event_log if r.outcome == 0]
    assert len(delivered) == 10
    assert result.counters["managed_exit_clean"] == 2
    assert result.counters["udp_tx_bytes"] > 0
    assert "client: done" in cli_out
    assert "server: echoed 5 datagrams" in srv_out
    # RTTs come off the simulated clock: 1 ms each way over the switch
    for line in cli_out.splitlines():
        if line.startswith("client: ping"):
            rtt = int(line.rsplit(" ", 2)[1])
            assert 2_000_000 <= rtt < 10_000_000, line
    stats = json.loads((tmp_path / "data" / "sim-stats.json").read_text())
    assert stats["packet_outcomes"]["delivered"] == 10


def test_pingpong_deterministic(tmp_path):
    r1, cli1, srv1 = _run(tmp_path / "a")
    r2, cli2, srv2 = _run(tmp_path / "b")
    assert r1.log_tuples() == r2.log_tuples()
    # stdout text includes sim-clock timestamps and RTTs: must be identical
    assert cli1 == cli2
    assert srv1 == srv2


def test_stuck_server_reaped_at_stop(tmp_path):
    # server expects 6 datagrams, client sends 5: the server is still parked
    # in recvfrom at stop_time and must be killed/reaped, not orphaned
    cfg = _config(tmp_path, count=5)
    cfg.hosts[1].processes[0].args[-1] = "6"
    result = Simulation(cfg).run()
    assert result.counters["managed_killed_at_stop"] == 1
    assert result.counters["managed_exit_clean"] == 1  # the client


def test_static_binary_rejected(tmp_path):
    from shadow_tpu.native.process import require_dynamic_elf

    with pytest.raises(ValueError, match="not an ELF"):
        p = tmp_path / "script.sh"
        p.write_text("#!/bin/sh\necho hi\n")
        p.chmod(0o755)
        require_dynamic_elf(str(p))


def test_unknown_model_message():
    from shadow_tpu.models.base import create_model

    with pytest.raises(ValueError, match="neither a built-in model"):
        create_model("no-such-model", [])


def test_udp_rcvbuf_drop_tail(tmp_path):
    """Bounded UDP recv buffers (the reference's drop-tail at a full
    socket buffer): a flooder outpaces a lazy reader whose
    socket_recv_buffer holds only a few datagrams — the excess drops and
    is counted; the reader drains exactly what fit over time."""
    cfg = ConfigOptions.from_yaml(f"""
general: {{stop_time: 10s, seed: 3, data_directory: {tmp_path / 'd'}, heartbeat_interval: null}}
experimental: {{socket_recv_buffer: 4000}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  sink:
    network_node_id: 0
    processes:
      - path: {BUILD / 'pingpong'}
        args: [lazysink, "6000", "10", "400"]
        expected_final_state: {{exited: 0}}
  flood:
    network_node_id: 0
    processes:
      - path: {BUILD / 'pingpong'}
        args: [flood, 11.0.0.2, "6000", "60", "100", "1000"]
        start_time: 100ms
        expected_final_state: {{exited: 0}}
""")
    result = Simulation(cfg).run()
    assert not result.process_errors
    # 60 KB offered into a 4 KB buffer drained at 2.5 reads/s: most drop
    assert result.counters.get("udp_rcvbuf_drops", 0) > 20, result.counters
    out = (tmp_path / "d" / "hosts" / "sink" / "pingpong.stdout").read_text()
    assert "lazysink: drained 10 datagrams" in out
