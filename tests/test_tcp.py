"""Sans-I/O TCP state machine tests.

Mirrors the reference TCP crate's in-crate suite (src/lib/tcp/src/tests/
{transitions,send_recv,window_scale}.rs) driven by a simulated-time fake
harness: two endpoints joined by a deterministic wire with explicit latency
and scripted loss, the integer clock advanced event-by-event.
"""

from __future__ import annotations

import pytest

from shadow_tpu.transport.tcp import (
    PollState,
    State,
    TcpConfig,
    TcpError,
    TcpFlags,
    TcpListener,
    TcpState,
    seq_add,
    seq_lt,
    seq_sub,
)

MS = 1_000_000
LATENCY = 5 * MS

A_ADDR = (0x0B000001, 1000)
B_ADDR = (0x0B000002, 2000)


class Wire:
    """Deterministic duplex wire + clock for two endpoints ("a", "b").
    ``loss`` is a set of global segment indices to drop (order of first
    transmission over the wire, both directions)."""

    def __init__(self, a: TcpState, b: TcpState, loss: set[int] | None = None):
        self.now = 0
        self.ends = {"a": a, "b": b}
        self.flight: list[tuple[int, str, object, bytes]] = []
        self.loss = loss or set()
        self.sent = 0
        self.segments: list[tuple[str, object, bytes]] = []  # transmit log

    def _pump_sends(self) -> None:
        for name, ep in self.ends.items():
            while ep.wants_to_send():
                out = ep.pop_packet(self.now)
                if out is None:
                    break
                hdr, payload = out
                idx = self.sent
                self.sent += 1
                self.segments.append((name, hdr, payload))
                if idx in self.loss:
                    continue
                dst = "b" if name == "a" else "a"
                self.flight.append((self.now + LATENCY, dst, hdr, payload))

    def step(self) -> bool:
        """Deliver/fire the earliest pending event; False when idle."""
        self._pump_sends()
        candidates: list[tuple[int, int, str]] = []
        if self.flight:
            t = min(f[0] for f in self.flight)
            candidates.append((t, 0, ""))
        for name, ep in self.ends.items():
            d = ep.next_timeout()
            if d is not None:
                candidates.append((d, 1, name))
        if not candidates:
            return False
        t, kind, who = min(candidates)
        self.now = max(self.now, t)
        if kind == 0:
            due = sorted(
                [f for f in self.flight if f[0] <= self.now], key=lambda f: f[0]
            )
            self.flight = [f for f in self.flight if f[0] > self.now]
            for _, dst, hdr, payload in due:
                self.ends[dst].push_packet(self.now, hdr, payload)
        else:
            self.ends[who].on_timer(self.now)
        self._pump_sends()
        return True

    def run(self, max_steps: int = 100000) -> None:
        for _ in range(max_steps):
            if not self.step():
                return
        raise AssertionError("wire did not go idle")


def handshake(loss: set[int] | None = None, cfg_a=None, cfg_b=None):
    """Client a connects to listener on b; returns (a, b_child, wire)."""
    a = TcpState(cfg_a)
    listener = TcpListener(B_ADDR, config=cfg_b)
    b_holder: dict = {}

    class ListenerAdapter:
        """Routes b-side segments: SYNs to the listener, the rest to the
        accepted child (the socket-layer demux in miniature)."""

        def push_packet(self, now, hdr, payload=b""):
            child = b_holder.get("child")
            if child is not None:
                child.push_packet(now, hdr, payload)
                return
            if hdr.flags & TcpFlags.SYN and not hdr.flags & TcpFlags.ACK:
                child = listener.push_syn(now, hdr, iss=7000)
                if child is not None:
                    b_holder["child"] = child

        def wants_to_send(self):
            c = b_holder.get("child")
            return c.wants_to_send() if c else False

        def pop_packet(self, now):
            c = b_holder.get("child")
            return c.pop_packet(now) if c else None

        def next_timeout(self):
            c = b_holder.get("child")
            return c.next_timeout() if c else None

        def on_timer(self, now):
            c = b_holder.get("child")
            if c:
                c.on_timer(now)

    wire = Wire(a, ListenerAdapter(), loss=loss)
    a.connect(A_ADDR, B_ADDR, iss=3000, now=0)
    wire.run()
    child = b_holder["child"]
    return a, child, wire


def transfer(a: TcpState, b: TcpState, wire: Wire, data: bytes, src="a"):
    """Send ``data`` from src endpoint, pumping until fully received."""
    sender = a if src == "a" else b
    receiver = b if src == "a" else a
    got = bytearray()
    sent = 0
    for _ in range(100000):
        if sent < len(data):
            sent += sender.send(data[sent : sent + 65536])
        wire.run()
        got.extend(receiver.recv(1 << 20))
        if len(got) >= len(data) and sent == len(data):
            break
    return bytes(got)


class TestHandshake:
    def test_three_way(self):
        a, b, wire = handshake()
        assert a.state == State.ESTABLISHED
        assert b.state == State.ESTABLISHED
        assert a.snd_una == seq_add(3000, 1)
        assert a.rcv_nxt == seq_add(7000, 1)
        assert b.rcv_nxt == seq_add(3000, 1)

    def test_syn_loss_retries(self):
        a, b, wire = handshake(loss={0})  # first SYN dropped
        assert a.state == State.ESTABLISHED
        assert b.state == State.ESTABLISHED
        assert wire.now >= TcpConfig().rto_initial  # took an RTO

    def test_synack_loss_retries(self):
        a, b, wire = handshake(loss={1})
        assert a.state == State.ESTABLISHED
        assert b.state == State.ESTABLISHED

    def test_refused_by_rst(self):
        a = TcpState()
        a.connect(A_ADDR, B_ADDR, iss=100, now=0)
        hdr, _ = a.pop_packet(0)
        from shadow_tpu.transport.tcp import TcpHeader

        rst = TcpHeader(
            src_ip=B_ADDR[0], src_port=B_ADDR[1],
            dst_ip=A_ADDR[0], dst_port=A_ADDR[1],
            seq=0, ack=seq_add(100, 1),
            flags=TcpFlags.RST | TcpFlags.ACK, window=0,
        )
        a.push_packet(LATENCY, rst)
        assert a.state == State.RST
        assert a.error == TcpError.REFUSED
        assert a.poll() & PollState.ERROR

    def test_listener_backlog_drops_syn(self):
        listener = TcpListener(B_ADDR, backlog=1)
        from shadow_tpu.transport.tcp import TcpHeader

        syn = lambda port: TcpHeader(
            src_ip=A_ADDR[0], src_port=port,
            dst_ip=B_ADDR[0], dst_port=B_ADDR[1],
            seq=50, ack=0, flags=TcpFlags.SYN, window=1000,
        )
        assert listener.push_syn(0, syn(1), iss=1) is not None
        assert listener.push_syn(0, syn(2), iss=2) is None  # over backlog

    def test_closed_listener_ignores_syn(self):
        listener = TcpListener(B_ADDR)
        listener.close()
        from shadow_tpu.transport.tcp import TcpHeader

        syn = TcpHeader(
            src_ip=A_ADDR[0], src_port=1,
            dst_ip=B_ADDR[0], dst_port=B_ADDR[1],
            seq=50, ack=0, flags=TcpFlags.SYN, window=1000,
        )
        assert listener.push_syn(0, syn, iss=1) is None


class TestTransitions:
    """transitions.rs: the close choreography."""

    def test_active_close(self):
        a, b, wire = handshake()
        a.close(wire.now)
        wire.run()
        # b hasn't closed: a in FIN_WAIT_2, b in CLOSE_WAIT
        assert a.state == State.FIN_WAIT_2
        assert b.state == State.CLOSE_WAIT
        b.close(wire.now)
        seen_time_wait = False
        for _ in range(1000):
            alive = wire.step()
            seen_time_wait = seen_time_wait or a.state == State.TIME_WAIT
            if not alive:
                break
        assert seen_time_wait  # passed through 2MSL
        assert b.state == State.CLOSED
        assert a.state == State.CLOSED
        assert wire.now >= TcpConfig().time_wait

    def test_simultaneous_close(self):
        a, b, wire = handshake()
        a.close(wire.now)
        b.close(wire.now)
        wire.run()
        assert a.state == State.CLOSED
        assert b.state == State.CLOSED
        assert wire.now >= TcpConfig().time_wait

    def test_recv_eof_after_fin(self):
        a, b, wire = handshake()
        a.send(b"bye")
        a.close(wire.now)
        wire.run()
        assert b.recv(100) == b"bye"
        assert b.at_eof()
        assert b.poll() & PollState.RECV_CLOSED

    def test_close_before_connect_is_noop(self):
        t = TcpState()
        t.close(0)
        assert t.state == State.CLOSED

    def test_fin_loss_retransmits(self):
        a, b, wire = handshake()
        n_before = wire.sent
        a.close(wire.now)
        wire.loss.add(n_before)  # drop the first FIN
        wire.run()
        b.close(wire.now)
        wire.run()
        assert a.state in (State.TIME_WAIT, State.CLOSED)
        assert b.state == State.CLOSED


class TestSendRecv:
    """send_recv.rs: integrity, segmentation, loss recovery."""

    def test_small_transfer(self):
        a, b, wire = handshake()
        got = transfer(a, b, wire, b"hello world")
        assert got == b"hello world"

    def test_bulk_transfer_both_ways(self):
        a, b, wire = handshake()
        blob = bytes(i & 0xFF for i in range(200_000))
        assert transfer(a, b, wire, blob) == blob
        blob2 = bytes((i * 7) & 0xFF for i in range(100_000))
        assert transfer(a, b, wire, blob2, src="b") == blob2

    def test_segmentation_respects_mss(self):
        cfg = TcpConfig(mss=500)
        a, b, wire = handshake(cfg_a=cfg, cfg_b=TcpConfig(mss=500))
        transfer(a, b, wire, bytes(5000))
        data_segs = [p for (_, h, p) in wire.segments if p]
        assert data_segs and all(len(p) <= 500 for p in data_segs)

    def test_loss_recovery_fast_retransmit(self):
        a, b, wire = handshake()
        blob = bytes(i & 0xFF for i in range(150_000))
        # drop a mid-stream data segment: dup-acks trigger fast retransmit
        wire.loss.add(wire.sent + 5)
        got = transfer(a, b, wire, blob)
        assert got == blob

    def test_loss_recovery_rto(self):
        a, b, wire = handshake()
        # drop an isolated small send entirely (no dup-acks possible)
        wire.loss.add(wire.sent)
        got = transfer(a, b, wire, b"x" * 100)
        assert got == b"x" * 100
        assert wire.now >= TcpConfig().rto_min

    def test_heavy_periodic_loss(self):
        a, b, wire = handshake()
        blob = bytes((i * 13) & 0xFF for i in range(120_000))
        start = wire.sent
        wire.loss.update(range(start + 7, start + 3000, 13))
        got = transfer(a, b, wire, blob)
        assert got == blob

    def test_send_after_shutdown_raises(self):
        a, b, wire = handshake()
        a.close(wire.now)
        with pytest.raises(BrokenPipeError):
            a.send(b"late")

    def test_reno_fast_retransmit_halves_cwnd(self):
        a, b, wire = handshake()
        blob = bytes(300_000)
        a.send(blob[:131072])
        wire.loss.add(wire.sent + 3)
        pre = a.cwnd
        wire.run()
        b.recv(1 << 20)
        assert a.ssthresh < 1 << 30  # loss event recorded
        assert a.cwnd <= max(pre, a.ssthresh + 3 * a.cfg.mss)

    def test_rtt_estimation(self):
        a, b, wire = handshake()
        transfer(a, b, wire, bytes(20_000))
        # srtt should be near 2*LATENCY (ack round trip)
        assert a.srtt > 0
        assert abs(a.srtt - 2 * LATENCY) < LATENCY


class TestFlowControl:
    """window_scale.rs + zero-window behavior."""

    def test_window_scaling_negotiated(self):
        big = TcpConfig(recv_buffer=1 << 20)
        a, b, wire = handshake(cfg_a=big, cfg_b=TcpConfig(recv_buffer=1 << 20))
        assert a.rcv_wscale > 0
        assert b.snd_wscale == a.rcv_wscale
        assert a.snd_wscale == b.rcv_wscale

    def test_no_scaling_when_disabled(self):
        off = TcpConfig(window_scaling=False, recv_buffer=1 << 20)
        a, b, wire = handshake(cfg_a=off, cfg_b=TcpConfig(window_scaling=False))
        assert a.rcv_wscale == 0 and a.snd_wscale == 0
        # advertised window is clamped to 16 bits
        assert b.snd_wnd <= 0xFFFF

    def test_peer_without_scaling_disables_ours(self):
        a, b, wire = handshake(
            cfg_a=TcpConfig(window_scaling=True),
            cfg_b=TcpConfig(window_scaling=False),
        )
        assert a.snd_wscale == 0
        assert b.snd_wscale == 0

    def test_receiver_stall_blocks_sender(self):
        cfg = TcpConfig(recv_buffer=10_000, send_buffer=1 << 20)
        a, b, wire = handshake(cfg_a=TcpConfig(send_buffer=1 << 20), cfg_b=cfg)
        a.send(bytes(60_000))
        wire.run()
        # receiver never reads: at most recv_buffer bytes cross the wire
        assert len(b._rcv_buf) <= 10_000
        assert seq_sub(a.snd_nxt, a.iss) <= 10_000 + 2
        # reading re-opens the window and the rest flows
        got = bytearray(b.recv(1 << 20))
        for _ in range(200):
            wire.run()
            got.extend(b.recv(1 << 20))
            if len(got) >= 60_000:
                break
        assert len(got) == 60_000

    def test_big_buffer_fills_pipe_beyond_64k(self):
        big = TcpConfig(recv_buffer=1 << 20, send_buffer=1 << 20)
        a, b, wire = handshake(cfg_a=big, cfg_b=big)
        blob = bytes(i & 0xFF for i in range(400_000))
        got = transfer(a, b, wire, blob)
        assert got == blob
        # with scaling, flight exceeded the 16-bit window at some point
        assert max(
            seq_sub(h.seq, a.iss) for (s, h, p) in wire.segments if s == "a"
        ) > 0xFFFF


class TestSeqArithmetic:
    def test_wrapping_compare(self):
        assert seq_lt(0xFFFFFFF0, 0x10)
        assert not seq_lt(0x10, 0xFFFFFFF0)
        assert seq_sub(0x10, 0xFFFFFFF0) == 0x20

    def test_wrap_transfer(self):
        # connection whose sequence space wraps mid-transfer
        a, b, wire = handshake()
        a.snd_una = a.snd_nxt = (a.snd_nxt + 0xFFFFFF00) & 0xFFFFFFFF
        # (simulate by instead picking a high ISS on a fresh pair)
        a2 = TcpState()
        listener = TcpListener(B_ADDR)
        holder = {}

        class Adapter:
            def push_packet(self, now, hdr, payload=b""):
                c = holder.get("c")
                if c is not None:
                    c.push_packet(now, hdr, payload)
                elif hdr.flags & TcpFlags.SYN:
                    holder["c"] = listener.push_syn(now, hdr, iss=0xFFFFFE00)

            def wants_to_send(self):
                return holder.get("c") and holder["c"].wants_to_send()

            def pop_packet(self, now):
                return holder["c"].pop_packet(now)

            def next_timeout(self):
                c = holder.get("c")
                return c.next_timeout() if c else None

            def on_timer(self, now):
                holder["c"].on_timer(now)

        w = Wire(a2, Adapter())
        a2.connect(A_ADDR, B_ADDR, iss=0xFFFFFF00, now=0)
        w.run()
        c = holder["c"]
        blob = bytes(i & 0xFF for i in range(50_000))
        got = transfer(a2, c, w, blob)
        assert got == blob


class TestSack:
    """RFC 2018/6675 selective acknowledgment (the reference tracks SACK
    ranges in tcp_retransmit_tally.cc; its Rust crate has none)."""

    def test_negotiated_on_syn(self):
        a, b, wire = handshake()
        assert a.sack_enabled and b.sack_enabled

    def test_disabled_when_peer_lacks_it(self):
        a, b, wire = handshake(cfg_b=TcpConfig(sack=False))
        assert not a.sack_enabled and not b.sack_enabled

    def test_receiver_reports_blocks(self):
        a, b, wire = handshake()
        blob = bytes(i & 0xFF for i in range(30_000))
        wire.loss.add(wire.sent + 2)  # one mid-stream hole
        a.send(blob[:20_000])
        # run until the receiver stashes past the hole and ACKs
        for _ in range(6):
            wire.step()
        sacked = [
            h.sack for (who, h, _p) in wire.segments if who == "b" and h.sack
        ]
        assert sacked, "receiver never attached SACK blocks"
        s0, e0 = sacked[0][0]
        assert (e0 - s0) % (1 << 32) > 0

    def test_multi_hole_loss_no_spurious_retransmits(self):
        """Several distinct holes in one window: with SACK the sender
        retransmits each lost segment ONCE (plus at most the head), never
        re-walking delivered data go-back-N style."""
        a, b, wire = handshake()
        blob = bytes((i * 7) & 0xFF for i in range(200_000))
        start = wire.sent
        wire.loss.update({start + 3, start + 9, start + 15})
        got = transfer(a, b, wire, blob)
        assert got == blob
        # count data segments by sequence: no sequence retransmitted 3+ times
        from collections import Counter

        seqs = Counter(
            h.seq for (who, h, p) in wire.segments if who == "a" and p
        )
        assert max(seqs.values()) <= 2

    def test_sack_beats_newreno_on_burst_loss(self):
        """A burst of drops in one flight: the SACK sender finishes in
        fewer wire segments than the same transfer without SACK (go-back-N
        re-sends the delivered tail; the scoreboard skips it)."""
        blob = bytes((i * 11) & 0xFF for i in range(150_000))

        def run(sack: bool):
            cfg = TcpConfig(sack=sack)
            a, b, wire = handshake(cfg_a=cfg, cfg_b=TcpConfig(sack=sack))
            start = wire.sent
            wire.loss.update(range(start + 4, start + 16, 3))
            got = transfer(a, b, wire, blob)
            assert got == blob
            return wire.sent, wire.now

        segs_sack, time_sack = run(True)
        segs_gbn, time_gbn = run(False)
        assert segs_sack <= segs_gbn
        assert time_sack <= time_gbn

    def test_heavy_loss_with_sack_completes(self):
        a, b, wire = handshake()
        blob = bytes((i * 13) & 0xFF for i in range(120_000))
        start = wire.sent
        wire.loss.update(range(start + 7, start + 3000, 13))
        got = transfer(a, b, wire, blob)
        assert got == blob
