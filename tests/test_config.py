"""Config parsing: units, YAML document shape, overrides, validation."""

import pytest

from shadow_tpu.config import units
from shadow_tpu.config.options import ConfigError, ConfigOptions
from shadow_tpu.core import time as stime


def test_time_units():
    assert units.parse_time("10s") == 10 * stime.NANOS_PER_SEC
    assert units.parse_time("10 ms") == 10 * stime.NANOS_PER_MILLI
    assert units.parse_time("1500 us") == 1500 * stime.NANOS_PER_MICRO
    assert units.parse_time("250 ns") == 250
    assert units.parse_time("2 min") == 2 * stime.NANOS_PER_MIN
    assert units.parse_time("1h") == stime.NANOS_PER_HOUR
    assert units.parse_time(10) == 10 * stime.NANOS_PER_SEC
    assert units.parse_time("1.5s") == 1_500_000_000
    with pytest.raises(units.UnitError):
        units.parse_time("10 parsecs")


def test_bandwidth_units():
    assert units.parse_bandwidth("1 Gbit") == 10**9
    assert units.parse_bandwidth("100 Mbit") == 100 * 10**6
    assert units.parse_bandwidth("10 Mbps") == 10 * 10**6
    assert units.parse_bandwidth("1 Kibit") == 1024
    assert units.parse_bandwidth(5000) == 5000


def test_byte_units():
    assert units.parse_bytes("16 MiB") == 16 * 2**20
    assert units.parse_bytes("1500 B") == 1500
    assert units.parse_bytes("2 KB") == 2000
    assert units.parse_bytes(42) == 42


BASIC_YAML = """
general:
  stop_time: 10s
  seed: 7

network:
  graph:
    type: 1_gbit_switch

hosts:
  server:
    network_node_id: 0
    processes:
    - path: tgen-server
      args: --port 80
      start_time: 1s
      expected_final_state: running
  client: &client
    network_node_id: 0
    processes:
    - path: tgen-client
      args: [--connect, server]
      start_time: 2s
"""


def test_basic_yaml_roundtrip():
    cfg = ConfigOptions.from_yaml(BASIC_YAML)
    cfg.validate()
    assert cfg.general.stop_time == 10 * stime.NANOS_PER_SEC
    assert cfg.general.seed == 7
    assert [h.hostname for h in cfg.hosts] == ["client", "server"]
    server = cfg.hosts[1]
    assert server.processes[0].path == "tgen-server"
    assert server.processes[0].args == ["--port", "80"]
    assert server.processes[0].start_time == stime.NANOS_PER_SEC
    assert server.processes[0].expected_final_state == "running"
    assert cfg.hosts[0].processes[0].args == ["--connect", "server"]


def test_host_count_expansion():
    cfg = ConfigOptions.from_yaml(
        """
general: {stop_time: 1s}
hosts:
  peer:
    count: 3
    processes: [{path: phold}]
"""
    )
    assert [h.hostname for h in cfg.hosts] == ["peer1", "peer2", "peer3"]


def test_unknown_keys_rejected():
    with pytest.raises(ConfigError, match="unknown general"):
        ConfigOptions.from_yaml(
            "general: {stop_time: 1s, bogus: 1}\nhosts: {a: {processes: []}}"
        )
    with pytest.raises(ConfigError, match="unknown top-level"):
        ConfigOptions.from_yaml("general: {stop_time: 1s}\nhoss: {}")
    with pytest.raises(ConfigError, match="at least one host"):
        ConfigOptions.from_yaml("general: {stop_time: 1s}")


def test_overrides_and_validation():
    cfg = ConfigOptions.from_yaml(BASIC_YAML)
    cfg.apply_overrides(
        {"experimental.network_backend": "tpu", "general.stop_time": "30s"}
    )
    assert cfg.experimental.network_backend == "tpu"
    assert cfg.general.stop_time == 30 * stime.NANOS_PER_SEC
    with pytest.raises(ConfigError):
        cfg.apply_overrides({"general.nope": 1})
    cfg.experimental.network_backend = "gpu"
    with pytest.raises(ConfigError):
        cfg.validate()


def test_host_defaults_inheritance():
    cfg = ConfigOptions.from_yaml(
        """
general: {stop_time: 1s}
host_option_defaults: {pcap_enabled: true, bandwidth_up: "10 Mbit"}
hosts:
  a: {processes: [{path: phold}]}
  b: {pcap_enabled: false, processes: [{path: phold}]}
"""
    )
    a, b = cfg.hosts
    assert a.pcap_enabled and not b.pcap_enabled
    assert a.bandwidth_up == 10**7 and b.bandwidth_up == 10**7


def test_override_type_coercion():
    cfg = ConfigOptions.from_yaml(BASIC_YAML)
    cfg.apply_overrides(
        {
            "general.heartbeat_interval": "2s",
            "general.seed": "9",
            "experimental.socket_send_buffer": "16 KiB",
            "experimental.use_worker_spinning": "false",
            "experimental.runahead": None,
        }
    )
    assert cfg.general.heartbeat_interval == 2 * stime.NANOS_PER_SEC
    assert cfg.general.seed == 9
    assert cfg.experimental.socket_send_buffer == 16 * 1024
    assert cfg.experimental.use_worker_spinning is False
    assert cfg.experimental.runahead is None


def test_graph_doc_unknown_and_conflicting_keys():
    import pytest as _pytest

    with _pytest.raises(ConfigError, match="unknown network.graph"):
        ConfigOptions.from_yaml(
            """
general: {stop_time: 1s}
network: {graph: {type: 1_gbit_switch, bogus: 1}}
hosts: {a: {processes: [{path: x}]}}
"""
        )
    with _pytest.raises(ConfigError, match="conflicting sources"):
        ConfigOptions.from_yaml(
            """
general: {stop_time: 1s}
network: {graph: {type: gml, file: a.gml, inline: "graph []"}}
hosts: {a: {processes: [{path: x}]}}
"""
        )


def test_count_expansion_no_shared_mutables():
    cfg = ConfigOptions.from_yaml(
        """
general: {stop_time: 1s}
hosts:
  peer:
    count: 2
    processes: [{path: phold, args: [--x], environment: {A: "1"}}]
"""
    )
    p0, p1 = cfg.hosts[0].processes[0], cfg.hosts[1].processes[0]
    assert p0.args is not p1.args and p0.environment is not p1.environment


def test_ip_addr_with_count_rejected():
    with pytest.raises(ConfigError, match="count > 1"):
        ConfigOptions.from_yaml(
            "general: {stop_time: 1s}\n"
            "hosts: {relay: {count: 3, ip_addr: 11.0.0.5, processes: []}}"
        )


def test_mesh_shape_override_coercion():
    cfg = ConfigOptions.from_yaml(BASIC_YAML)
    cfg.apply_overrides({"experimental.tpu_mesh_shape": "2,4"})
    assert cfg.experimental.tpu_mesh_shape == (2, 4)
