"""Interposition backstops: seccomp SIGSYS trap for raw syscalls and vDSO
patching for vDSO-direct time reads (the reference's shim_seccomp.c /
patch_vdso.c layers).  A deliberately libc-bypassing binary must still see
only simulated time and deterministic entropy.
"""

import subprocess
from pathlib import Path

import pytest

from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.core import time as stime
from shadow_tpu.engine.sim import Simulation

REPO = Path(__file__).resolve().parents[1]
BUILD = REPO / "native" / "build"

EPOCH_2000_S = stime.SIM_START_EMU // stime.NANOS_PER_SEC


@pytest.fixture(scope="module", autouse=True)
def native_build():
    subprocess.run(
        ["make", "-C", str(REPO / "native")], check=True, capture_output=True
    )
    assert (BUILD / "rawsys").exists()


def _run_mode(tmp_path: Path, mode: str, extra_exp: str = ""):
    cfg = ConfigOptions.from_yaml(
        f"""
general: {{stop_time: 1s, seed: 5, data_directory: {tmp_path / 'data'}, heartbeat_interval: null}}
network: {{graph: {{type: 1_gbit_switch}}}}
experimental: {{{extra_exp}}}
hosts:
  solo:
    network_node_id: 0
    processes:
      - path: {BUILD / 'rawsys'}
        args: [{mode}]
"""
    )
    result = Simulation(cfg).run()
    out = (tmp_path / "data" / "hosts" / "solo" / "rawsys.stdout").read_text()
    return result, out


def test_raw_syscalls_trapped(tmp_path):
    """Raw SYS_clock_gettime/nanosleep/getrandom (bypassing libc symbols)
    are trapped by the seccomp filter and serviced by the simulation: the
    raw clock starts at the 2000-01-01 epoch and a raw nanosleep advances
    it exactly 50 simulated ms."""
    result, out = _run_mode(tmp_path, "raw")
    assert f"t0={EPOCH_2000_S}" in out  # epoch seconds prefix of the ns value
    assert "slept_ms=50" in out
    assert "getrandom_n=8" in out
    assert not result.process_errors


def test_raw_entropy_deterministic(tmp_path):
    """Raw getrandom bytes come from the per-process deterministic stream:
    two runs print identical output."""
    outs = []
    for sub in ("a", "b"):
        _, out = _run_mode(tmp_path / sub, "raw")
        outs.append(out)
    assert outs[0] == outs[1]
    assert "bytes=" in outs[0]


def test_vdso_time_patched(tmp_path):
    """glibc-internal clock_gettime/gettimeofday (resolved past the shim,
    dispatching through the vDSO) return simulated time thanks to the vDSO
    patch."""
    result, out = _run_mode(tmp_path, "vdso")
    assert f"sec={EPOCH_2000_S}" in out
    assert f"usec_sec={EPOCH_2000_S}" in out
    assert not result.process_errors


def test_simulated_interface_identity(tmp_path):
    """getifaddrs presents the SIMULATED interfaces — lo plus eth0 with
    the host's 11.0.0.0/8 address — never the real machine's (the
    reference's netlink/ifaddrs emulation surface)."""
    result, out = _run_mode(tmp_path, "ifaddrs")
    assert "if lo addr=127.0.0.1 mask=255.0.0.0 loop=1 up=1" in out
    assert "if eth0 addr=11.0.0.1 mask=255.0.0.0 loop=0 up=1" in out
    assert "idx eth0=2 lo=1 name2=eth0" in out
    assert out.count("if ") == 2  # nothing real leaked
    assert not result.process_errors


def test_legacy_seccomp_fallback(tmp_path):
    """SHADOW_TPU_SUD=0 forces the pre-5.11 fallback (narrow seccomp
    filter over the time/sleep/entropy set): raw time syscalls still see
    the simulation."""
    cfg = ConfigOptions.from_yaml(
        f"""
general: {{stop_time: 1s, seed: 5, data_directory: {tmp_path / 'data'}, heartbeat_interval: null}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  solo:
    network_node_id: 0
    processes:
      - path: {BUILD / 'rawsys'}
        args: [raw]
        environment: {{SHADOW_TPU_SUD: "0"}}
"""
    )
    result = Simulation(cfg).run()
    out = (tmp_path / "data" / "hosts" / "solo" / "rawsys.stdout").read_text()
    assert f"t0={EPOCH_2000_S}" in out
    assert "slept_ms=50" in out
    assert not result.process_errors


def test_backstops_can_be_disabled(tmp_path):
    """experimental.use_seccomp/use_vdso_patching=false fall back to plain
    LD_PRELOAD: raw time reads then see the REAL clock (not year 2000),
    proving the knob reaches the shim."""
    _, out = _run_mode(
        tmp_path, "raw",
        extra_exp="use_seccomp: false, use_vdso_patching: false",
    )
    t0 = int(out.split("t0=")[1].split()[0])
    assert t0 > stime.SIM_START_EMU * 1.5  # real 2026 clock, not sim epoch


def test_busy_loop_preemption(tmp_path):
    """A clock-polling busy loop (the reference's dominant real-workload
    shape: 96.5% of Prysm's syscalls are clock_gettime) completes instead
    of livelocking the round: with the CPU model on, the shim's CPU-time
    itimer forces yields that charge simulated time (preempt.rs analog).
    Bounded wall time is the whole point of the test."""
    import time as _time

    cfg = ConfigOptions.from_yaml(
        f"""
general: {{stop_time: 10s, seed: 5, data_directory: {tmp_path / 'data'},
  heartbeat_interval: null, model_unblocked_syscall_latency: true}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  solo:
    network_node_id: 0
    processes:
      - path: {BUILD / 'spinner'}
"""
    )
    t0 = _time.monotonic()
    result = Simulation(cfg).run()
    wall = _time.monotonic() - t0
    out = (tmp_path / "data" / "hosts" / "solo" / "spinner.stdout").read_text()
    assert "spun 5" in out  # ~500 simulated ms, quantum granularity
    assert "iters>0=1" in out
    assert not result.process_errors
    assert wall < 30  # preemption bounds the wall time; livelock would hang


def test_rdtsc_emulated(tmp_path):
    """Direct rdtsc/rdtscp instructions observe monotone SIMULATED cycles
    (1 GHz virtual TSC: cycles == sim ns), via PR_SET_TSC trap-and-emulate
    — the reference's shim_insn_emu.c surface.  A raw nanosleep must
    advance the TSC by exactly the simulated interval."""
    result, out = _run_mode(tmp_path, "tsc")
    t0 = int(out.split("t0=")[1].split()[0])
    assert t0 >= stime.SIM_START_EMU  # simulated epoch cycles, not real TSC
    assert t0 < stime.SIM_START_EMU + 10**9
    assert "delta_ms=50" in out
    assert "mono=1" in out
    assert not result.process_errors
