"""Fault-injection subsystem (shadow_tpu/faults/): schedule parsing,
versioned routing tables, deterministic replay, cross-backend parity of
faulted runs, and the TPU->CPU graceful-degradation (failover) path.

The determinism contract under test is docs/faults.md's: every fault
event time is a window-clamp epoch on both backends, so the same config +
seed always yields byte-identical event logs — across repeats AND across
backends — and a failed TPU run recovers by deterministic CPU replay
with the exact event log an unfaulted CPU-only run produces.
"""

from pathlib import Path

import numpy as np
import pytest

from shadow_tpu.backend.cpu_engine import DELIVERED, DROP_LOSS, CpuEngine
from shadow_tpu.config.options import ConfigError, ConfigOptions
from shadow_tpu.engine.determinism import determinism_check
from shadow_tpu.engine.sim import Simulation
from shadow_tpu.faults.overlay import FULL_THRESHOLD, FaultOverlay, build_overlay
from shadow_tpu.faults.schedule import (
    FaultConfigError,
    FaultSchedule,
    parse_console_fault,
    parse_event,
)
from shadow_tpu.faults.watchdog import BackendStallError, RoundWatchdog

pytestmark = pytest.mark.faults

REPO = Path(__file__).resolve().parents[1]

TWO_NODE_GRAPH = """
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        node [ id 1 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        edge [ source 0 target 0 latency "2 ms" ]
        edge [ source 0 target 1 latency "5 ms" ]
        edge [ source 1 target 1 latency "2 ms" ]
      ]
"""

BASE = f"""
general: {{stop_time: 3s, seed: 13, heartbeat_interval: null}}
network:
  graph:
    type: gml
    inline: |
{TWO_NODE_GRAPH}
faults:
  events:
    - {{at: 1s, kind: partition, groups: [[0], [1]]}}
    - {{at: 2s, kind: heal}}
hosts:
  a: {{network_node_id: 0, processes: [{{path: tgen-client, args: [--server, b, --interval, 50ms, --size, "600"]}}]}}
  b: {{network_node_id: 1, processes: [{{path: tgen-server}}]}}
"""


def cfg_of(yaml: str, **overrides) -> ConfigOptions:
    cfg = ConfigOptions.from_yaml(yaml)
    cfg.apply_overrides(overrides)
    return cfg


# -- schedule parsing --------------------------------------------------------


class TestScheduleParse:
    def test_events_sorted_and_typed(self):
        sched = FaultSchedule.parse(
            [
                {"at": "2s", "kind": "heal"},
                {"at": "1s", "kind": "link_down", "source": 0, "target": 1},
            ]
        )
        assert [e.kind for e in sched.events] == ["link_down", "heal"]
        assert sched.epoch_times() == [10**9, 2 * 10**9]

    def test_unknown_kind_rejected(self):
        with pytest.raises(FaultConfigError, match="unknown fault kind"):
            parse_event({"at": "1s", "kind": "meteor_strike"})

    def test_unknown_keys_rejected(self):
        with pytest.raises(FaultConfigError, match="unknown keys"):
            parse_event({"at": "1s", "kind": "heal", "bogus": 1})

    def test_loss_must_be_finite_in_range(self):
        for bad in (float("nan"), float("inf"), -0.1, 1.5):
            with pytest.raises(FaultConfigError, match="finite value in"):
                parse_event(
                    {"at": "1s", "kind": "loss", "source": 0, "target": 1, "loss": bad}
                )

    def test_at_must_be_positive(self):
        with pytest.raises(FaultConfigError, match="must be > 0"):
            parse_event({"at": 0, "kind": "heal"})

    def test_partition_groups_validated(self):
        with pytest.raises(FaultConfigError, match="at least two groups"):
            parse_event({"at": "1s", "kind": "partition", "groups": [[0, 1]]})
        with pytest.raises(FaultConfigError, match="disjoint"):
            parse_event({"at": "1s", "kind": "partition", "groups": [[0], [0, 1]]})

    def test_config_validate_rejects_bad_schedule(self):
        cfg = cfg_of(BASE)
        cfg.faults.events = [{"at": "1s", "kind": "nope"}]
        with pytest.raises(ConfigError, match="faults.events"):
            cfg.validate()

    def test_bootstrap_window_rejected(self):
        cfg = cfg_of(BASE)
        cfg.general.bootstrap_end_time = int(1.5e9)
        with pytest.raises(ConfigError, match="bootstrap"):
            cfg.validate()

    def test_console_grammar(self):
        ev = parse_console_fault(["link_down", "0", "1"], at=7)
        assert (ev.kind, ev.source, ev.target, ev.at) == ("link_down", 0, 1, 7)
        ev = parse_console_fault(["partition", "0|1,2"], at=7)
        assert ev.groups == ((0,), (1, 2))
        ev = parse_console_fault(["crash", "relay1"], at=7)
        assert (ev.kind, ev.host) == ("host_crash", "relay1")
        with pytest.raises(FaultConfigError, match="usage"):
            parse_console_fault(["loss", "0"], at=7)


# -- overlay table compilation ----------------------------------------------


def make_overlay(events, yaml=BASE) -> FaultOverlay:
    cfg = cfg_of(yaml)
    cfg.faults.events = events
    engine = CpuEngine(cfg)
    return build_overlay(cfg, engine.graph, engine.routing)


class TestOverlay:
    def test_link_down_without_reroute_drops_pair(self):
        ov = make_overlay([{"at": "1s", "kind": "link_down", "source": 0, "target": 1}])
        snap = ov.snapshot_at(10**9)
        assert snap is not None
        # cross pair drops everything but keeps the base latency
        assert snap.loss_threshold[0, 1] == FULL_THRESHOLD
        assert snap.loss_threshold[1, 0] == FULL_THRESHOLD
        assert snap.latency_ns[0, 1] == ov.base.latency_ns[0, 1]
        # self-loops untouched
        assert snap.loss_threshold[0, 0] == 0

    def test_link_up_restores_base(self):
        ov = make_overlay(
            [
                {"at": "1s", "kind": "link_down", "source": 0, "target": 1},
                {"at": "2s", "kind": "link_up", "source": 0, "target": 1},
            ]
        )
        snap = ov.snapshot_at(2 * 10**9)
        assert (snap.loss_threshold == ov.base.loss_threshold).all()
        assert (snap.latency_ns == ov.base.latency_ns).all()

    def test_link_down_reroutes_when_alternative_exists(self):
        yaml = BASE.replace(
            'edge [ source 0 target 1 latency "5 ms" ]',
            'edge [ source 0 target 1 latency "5 ms" ]\n'
            '        node [ id 2 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]\n'
            '        edge [ source 0 target 2 latency "30 ms" ]\n'
            '        edge [ source 2 target 1 latency "30 ms" ]',
        )
        ov = make_overlay(
            [{"at": "1s", "kind": "link_down", "source": 0, "target": 1}], yaml
        )
        snap = ov.snapshot_at(10**9)
        # traffic reroutes over the 60 ms detour instead of dropping
        assert snap.latency_ns[0, 1] == 60 * 10**6
        assert snap.loss_threshold[0, 1] == 0

    def test_latency_event_changes_pair(self):
        ov = make_overlay(
            [{"at": "1s", "kind": "latency", "source": 0, "target": 1,
              "latency": "15 ms"}]
        )
        snap = ov.snapshot_at(10**9)
        assert snap.latency_ns[0, 1] == 15 * 10**6

    def test_unknown_edge_rejected(self):
        with pytest.raises(FaultConfigError, match="no edge"):
            make_overlay([{"at": "1s", "kind": "link_down", "source": 0, "target": 9}])

    def test_crash_of_shared_node_rejected(self):
        yaml = BASE.replace("b: {network_node_id: 1,", "b: {network_node_id: 0,")
        with pytest.raises(FaultConfigError, match="shares graph node"):
            make_overlay([{"at": "1s", "kind": "host_crash", "host": "a"}], yaml)

    def test_crash_isolates_and_restart_heals(self):
        ov = make_overlay(
            [
                {"at": "1s", "kind": "host_crash", "host": "a"},
                {"at": "2s", "kind": "host_restart", "host": "a"},
            ]
        )
        down = ov.snapshot_at(10**9)
        assert (down.loss_threshold[0, :] == FULL_THRESHOLD).all()
        assert (down.loss_threshold[:, 0] == FULL_THRESHOLD).all()
        up = ov.snapshot_at(2 * 10**9)
        assert (up.loss_threshold == ov.base.loss_threshold).all()


# -- engine behavior ---------------------------------------------------------


def outcomes_by_second(result):
    out: dict[tuple[int, int], int] = {}
    for r in result.event_log:
        key = (r.time // 10**9, r.outcome)
        out[key] = out.get(key, 0) + 1
    return out


class TestCpuFaultRuns:
    def test_partition_drops_then_heals(self):
        r = CpuEngine(cfg_of(BASE)).run()
        by = outcomes_by_second(r)
        assert by[(0, DELIVERED)] > 0
        assert by[(1, DROP_LOSS)] > 0  # partitioned second: all drops
        assert (1, DELIVERED) not in by
        assert by[(2, DELIVERED)] > 0  # healed

    def test_windows_clamp_at_epochs(self):
        engine = CpuEngine(cfg_of(BASE))
        bounds = []
        engine.run(on_window=lambda s, e, n: bounds.append((s, e)))
        # no window straddles a fault epoch
        for s, e in bounds:
            for t in (10**9, 2 * 10**9):
                assert not (s < t < e), f"window [{s}, {e}) straddles epoch {t}"

    def test_fault_run_deterministic(self):
        report = determinism_check(cfg_of(BASE))
        assert report.identical, report.describe()

    def test_dead_path_aborts_stream_and_surfaces_retry_drop(self):
        """A permanent link_down with no reroute mid-transfer: the lTCP
        sender exhausts MAX_RTO_BACKOFFS and gives up; the abandonment is
        surfaced as `retry_drop` next to the wire outcomes."""
        yaml = """
general: {stop_time: 300s, seed: 3, heartbeat_interval: null}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "50 Mbit" host_bandwidth_down "50 Mbit" ]
        node [ id 1 host_bandwidth_up "50 Mbit" host_bandwidth_down "50 Mbit" ]
        edge [ source 0 target 1 latency "10 ms" ]
      ]
faults:
  events:
    - {at: 50ms, kind: link_down, source: 0, target: 1}
hosts:
  c1: {network_node_id: 0, processes: [{path: stream-client, args: [--server, s1, --size, "5 MB"]}]}
  s1: {network_node_id: 1, processes: [{path: stream-server}]}
"""
        sim = Simulation(cfg_of(yaml))
        result = sim.run(write_data=False)
        assert result.counters.get("stream_retry_drops", 0) > 0
        assert result.counters.get("stream_complete", 0) == 0
        out = sim._outcome_counts(result)
        assert out["retry_drop"] == result.counters["stream_retry_drops"]

    def test_example_partition_heal_deterministic(self):
        cfg = ConfigOptions.from_yaml(
            (REPO / "examples" / "partition-heal.yaml").read_text()
        )
        cfg.general.data_directory = "/tmp/shadow-tpu-test-faults.data"
        report = determinism_check(cfg)
        assert report.identical, report.describe()
        assert report.records > 50


class TestBackendParity:
    """Same schedule, both backends: byte-identical event logs."""

    @pytest.mark.parametrize("mode", ["step", "device"])
    def test_partition_heal_parity(self, mode):
        from shadow_tpu.backend.tpu_engine import TpuEngine

        cpu = CpuEngine(cfg_of(BASE)).run()
        tpu = TpuEngine(cfg_of(BASE)).run(mode=mode)
        assert cpu.log_tuples() == tpu.log_tuples()
        assert cpu.counters["tgen_recv_bytes"] == tpu.counters["tgen_recv_bytes"]

    def test_crash_restart_parity(self):
        from shadow_tpu.backend.tpu_engine import TpuEngine

        yaml = BASE
        events = [
            {"at": "1s", "kind": "host_crash", "host": "a"},
            {"at": "1400ms", "kind": "latency", "source": 0, "target": 1,
             "latency": "15 ms"},
            {"at": "2s", "kind": "host_restart", "host": "a"},
        ]
        c1, c2 = cfg_of(yaml), cfg_of(yaml)
        c1.faults.events = events
        c2.faults.events = list(events)
        cpu = CpuEngine(c1).run()
        tpu = TpuEngine(c2).run(mode="device")
        assert cpu.log_tuples() == tpu.log_tuples()

    def test_mid_flow_loss_ramp_stream_parity(self):
        """Stream (lTCP) flows keep bit-parity through a loss ramp that
        forces real retransmissions mid-transfer."""
        from shadow_tpu.backend.tpu_engine import TpuEngine

        yaml = f"""
general: {{stop_time: 2s, seed: 5, heartbeat_interval: null}}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "50 Mbit" host_bandwidth_down "50 Mbit" ]
        node [ id 1 host_bandwidth_up "50 Mbit" host_bandwidth_down "50 Mbit" ]
        edge [ source 0 target 1 latency "10 ms" ]
      ]
faults:
  events:
    - {{at: 20ms, kind: loss, source: 0, target: 1, loss: 0.25}}
    - {{at: 60ms, kind: loss, source: 0, target: 1, loss: 0.0}}
hosts:
  c1: {{network_node_id: 0, processes: [{{path: stream-client, args: [--server, s1, --size, "300 kB"]}}]}}
  s1: {{network_node_id: 1, processes: [{{path: stream-server}}]}}
"""
        cpu = CpuEngine(cfg_of(yaml)).run()
        tpu = TpuEngine(cfg_of(yaml)).run(mode="device")
        assert cpu.counters["stream_retransmits"] > 0  # the ramp bit
        assert cpu.log_tuples() == tpu.log_tuples()
        for k in ("stream_complete", "stream_rx_bytes", "stream_rx_segs",
                  "stream_tx_segs", "stream_flows_done", "stream_retransmits"):
            assert cpu.counters.get(k) == tpu.counters.get(k), k


class TestFailover:
    def test_injected_stall_fails_over_to_identical_cpu_run(self):
        """The acceptance contract: a simulated TPU-round failure mid-run
        triggers CPU failover that completes the run with the same final
        event log as an unfaulted CPU-only run of the same schedule."""
        yaml = BASE.replace(
            "  events:",
            "  events:\n    - {at: 1500ms, kind: backend_stall}",
        )
        sim = Simulation(cfg_of(yaml, **{"experimental.network_backend": "tpu"}))
        r_tpu = sim.run(write_data=False)
        assert sim.failovers == 1
        r_cpu = Simulation(cfg_of(yaml)).run(write_data=False)
        assert r_tpu.log_tuples() == r_cpu.log_tuples()
        assert r_tpu.counters == r_cpu.counters

    def test_failover_disabled_raises(self):
        yaml = BASE.replace(
            "  events:",
            "  failover: false\n  events:\n    - {at: 1500ms, kind: backend_stall}",
        )
        sim = Simulation(cfg_of(yaml, **{"experimental.network_backend": "tpu"}))
        with pytest.raises(BackendStallError):
            sim.run(write_data=False)

    def test_stall_event_is_noop_on_cpu(self):
        """The CPU engine (the failover target) treats backend_stall as a
        pure window-clamp epoch."""
        yaml = BASE.replace(
            "  events:",
            "  events:\n    - {at: 1500ms, kind: backend_stall}",
        )
        with_stall = CpuEngine(cfg_of(yaml)).run()
        without = CpuEngine(cfg_of(BASE)).run()
        assert with_stall.log_tuples() == without.log_tuples()

    def test_hybrid_with_faults_degrades_to_cpu(self, tmp_path):
        """A managed-host (hybrid) config with a fault schedule cannot run
        on the device; the failover boundary degrades it to the CPU engine,
        where managed hosts run natively."""
        build = REPO / "native" / "build"
        if not (build / "pingpong").exists():
            pytest.skip("native test binaries not built")
        yaml = f"""
general: {{stop_time: 2s, seed: 21, data_directory: {tmp_path / 'data'}, heartbeat_interval: null}}
network: {{graph: {{type: 1_gbit_switch}}}}
experimental: {{network_backend: tpu}}
faults:
  events: [{{at: 1s, kind: heal}}]
hosts:
  cli:
    network_node_id: 0
    processes:
      - path: {build / 'pingpong'}
        args: [client, 11.0.0.2, "9000", "4", "100"]
  srv:
    network_node_id: 0
    processes:
      - path: {build / 'pingpong'}
        args: [server, "9000", "4"]
"""
        sim = Simulation(ConfigOptions.from_yaml(yaml))
        result = sim.run(write_data=False)
        assert sim.failovers == 1
        assert result.counters  # the cpu replay actually ran

    def test_watchdog_raises_on_slow_round(self):
        wd = RoundWatchdog(timeout_seconds=0.01)
        wd.observe(0.005)
        with pytest.raises(BackendStallError, match="watchdog_timeout"):
            wd.observe(0.02)
        assert wd.rounds == 2


class TestRunControlFaults:
    def test_console_fault_injection_drops_traffic(self):
        """`fault link_down 0 1` at a pause kills cross traffic for the
        rest of the run."""
        import io

        from shadow_tpu.engine.run_control import RunControl

        out = io.StringIO()
        rc = RunControl(out=out, poll_interval=0.01, max_wait=10)
        rc.feed("c1", "fault link_down 0 1", "c")
        result = Simulation(cfg_of(BASE.replace(
            "faults:\n  events:\n    - {at: 1s, kind: partition, groups: [[0], [1]]}\n    - {at: 2s, kind: heal}\n",
            "",
        )), run_control=rc).run(write_data=False)
        assert "fault link_down scheduled" in out.getvalue()
        by = outcomes_by_second(result)
        assert by[(0, DELIVERED)] > 0
        assert by.get((2, DELIVERED), 0) == 0  # link stays dark
        assert by[(2, DROP_LOSS)] > 0

    def test_bad_console_fault_reports_not_crashes(self):
        import io

        from shadow_tpu.engine.run_control import RunControl

        out = io.StringIO()
        rc = RunControl(out=out, poll_interval=0.01, max_wait=10)
        rc.feed("p", "fault link_down 0 9", "c")
        Simulation(cfg_of(BASE), run_control=rc).run(write_data=False)
        assert "fault rejected" in out.getvalue()

    def test_failover_verb_on_cpu_reports(self):
        import io

        from shadow_tpu.engine.run_control import RunControl

        out = io.StringIO()
        rc = RunControl(out=out, poll_interval=0.01, max_wait=10)
        rc.feed("p", "failover", "c")
        Simulation(cfg_of(BASE), run_control=rc).run(write_data=False)
        assert "already on the cpu engine" in out.getvalue()
