"""k-window free-run fusion + double-buffered async dispatch
(backend/hybrid.py, docs/hybrid.md "k-window fusion law" — ISSUE 13).

The contracts under test:

1. **Pure scheduling change** — with fusion + async dispatch at their
   defaults, the event log, rounds, and workload counters are
   bit-identical to the ``hybrid_fuse_k=1`` (PR 7) law.  The single
   intentional exception is the ``lane_iters`` diagnostic: a fused
   dispatch visits absorbed ext-only windows with no-op device
   iterations the one-window law never ran, so the iteration *count*
   (not any event, log byte, or netobs counter) legitimately differs.
2. **Degenerate law** — ``hybrid_fuse_k=1`` takes the PR 7 code path
   verbatim: no fused rows, no rollbacks, ``turns_saved == 0``, and (at
   the SHADOW_TPU_SCALE gate) the pinned 651-turn gate-scale count.
3. **Late injection falls back** — the pingpong cadence stages sends
   whose arrivals land inside fused spans, forcing validation failures:
   rollback rebuilds and discarded eager dispatches both occur, and the
   results stay oracle-bit-identical (the blocking-path fallback).
4. **Ledger accounting** — ``turns == sum(cause_counts)`` with
   ``free_run``/``rollback`` rows present, ``turns == device_turns``,
   ``turns + turns_saved == implied_unfused``, and the covered-windows
   invariant across fused/unfused runs.

Worker-count invariance and oracle bit-parity with fusion ON ride the
existing suite (tests/test_hybrid_mp.py, tests/test_turns.py — fusion is
the default there); this file pins the fusion-specific laws.
"""

import os
import subprocess
from pathlib import Path

import pytest

from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.engine.sim import Simulation
from shadow_tpu.obs import TurnLedger
from shadow_tpu.obs import turns as tmod

pytestmark = pytest.mark.hybrid

REPO = Path(__file__).resolve().parents[1]
BUILD = REPO / "native" / "build"

SCALE = pytest.mark.skipif(
    not os.environ.get("SHADOW_TPU_SCALE"),
    reason="scale gate: set SHADOW_TPU_SCALE=1 to run",
)


@pytest.fixture(scope="module", autouse=True)
def native_build():
    subprocess.run(
        ["make", "-C", str(REPO / "native")], check=True,
        capture_output=True,
    )


def _cfg(data_dir: Path, workers: int = 1, fuse_k: int = 8,
         async_dispatch: bool = True) -> ConfigOptions:
    """The test_hybrid_mp mixed scenario (managed pingpong + tcpecho
    pairs over a tgen lane mesh): pingpong's per-round request/response
    cadence stages sends whose arrivals land one window out — the
    forced late-injection workload that exercises rollback and eager-
    dispatch misses alongside clean fused spans."""
    mesh = "\n".join(
        f"""
  zm{i:03d}:
    network_node_id: 0
    processes:
      - path: tgen-mesh
        args: --interval 50ms --size 600
        start_time: 0 s
"""
        for i in range(4)
    )
    return ConfigOptions.from_yaml(
        f"""
general: {{stop_time: 2s, seed: 21, data_directory: {data_dir}, heartbeat_interval: null}}
network: {{graph: {{type: 1_gbit_switch}}}}
experimental: {{network_backend: tpu, hybrid_workers: {workers},
                hybrid_fuse_k: {fuse_k},
                hybrid_async_dispatch: {str(async_dispatch).lower()},
                obs_turns: true}}
hosts:
  cli:
    network_node_id: 0
    processes:
      - path: {BUILD / 'pingpong'}
        args: [client, 11.0.0.4, "9000", "4", "100"]
  srv:
    network_node_id: 0
    processes:
      - path: {BUILD / 'pingpong'}
        args: [server, "9000", "4"]
  ecli:
    network_node_id: 0
    processes:
      - path: {BUILD / 'tcpecho'}
        args: [hclient, esrv, "7000", "2", "400", "5"]
        start_time: 200ms
  esrv:
    network_node_id: 0
    processes:
      - path: {BUILD / 'tcpecho'}
        args: [server, "7000", "1"]
{mesh}
"""
    )


def _congested_cfg(data_dir: Path, fuse_k: int = 8) -> ConfigOptions:
    """Bulk echo traffic into a 10 Mbit node queues deliveries in the
    device down-buckets, pushing their ``t_deliver`` past the fused
    window they were generated in, while the short-latency pingpong
    cadence keeps forcing rollbacks — the combination that loses
    validated-prefix deliveries if a rollback discards the unapplied
    egress rows instead of re-reading them from the rebuild."""
    bulk = "\n".join(
        f"""
  bcli{i}:
    network_node_id: 0
    processes:
      - path: {BUILD / 'tcpecho'}
        args: [hclient, bsrv{i}, "{7000 + i}", "6", "8192", "0"]
        start_time: {100 + 40 * i}ms
  bsrv{i}:
    network_node_id: 1
    processes:
      - path: {BUILD / 'tcpecho'}
        args: [server, "{7000 + i}", "1"]
"""
        for i in range(3)
    )
    return ConfigOptions.from_yaml(
        f"""
general: {{stop_time: 2s, seed: 7, data_directory: {data_dir}, heartbeat_interval: null}}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
        node [ id 1 host_bandwidth_up "10 Mbit" host_bandwidth_down "10 Mbit" ]
        edge [ source 0 target 0 latency "100 us" ]
        edge [ source 1 target 1 latency "100 us" ]
        edge [ source 0 target 1 latency "300 us" ]
      ]
experimental: {{network_backend: tpu, hybrid_fuse_k: {fuse_k},
                obs_turns: true}}
hosts:
  acli:
    network_node_id: 0
    processes:
      - path: {BUILD / 'pingpong'}
        args: [client, 11.0.0.2, "9000", "4", "100"]
  asrv:
    network_node_id: 0
    processes:
      - path: {BUILD / 'pingpong'}
        args: [server, "9000", "4"]
{bulk}
"""
    )


def _run(cfg):
    sim = Simulation(cfg)
    result = sim.run(write_data=False)
    assert not result.process_errors, result.process_errors
    return result, sim.engine, sim.obs.turns


@pytest.fixture(scope="module")
def fused(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("fusion_on")
    return _run(_cfg(tmp / "d"))


@pytest.fixture(scope="module")
def unfused(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("fusion_off")
    return _run(_cfg(tmp / "d", fuse_k=1))


def _counters_mod_iters(r):
    # lane_iters counts device iterations: the fused schedule runs no-op
    # iterations for absorbed windows the one-window law never visited —
    # a diagnostic of work scheduling, not an observable output
    return {k: v for k, v in r.counters.items() if k != "lane_iters"}


class TestPureSchedulingChange:
    def test_fusion_engages(self, fused):
        _r, eng, _led = fused
        s = eng.sync_stats
        assert s["fused_dispatches"] > 0
        assert s["fused_windows"] > s["fused_dispatches"]
        assert s["turns_saved"] > 0
        assert s["device_turns"] < s["fused_windows"] + s["fuse_rollbacks"]

    def test_bit_parity_with_unfused_law(self, fused, unfused):
        rf, _ef, _lf = fused
        ru, _eu, _lu = unfused
        assert rf.log_tuples() == ru.log_tuples()
        assert rf.rounds == ru.rounds
        assert _counters_mod_iters(rf) == _counters_mod_iters(ru)
        assert rf.per_host_counters == ru.per_host_counters

    def test_late_injection_falls_back_to_blocking(self, fused):
        """The pingpong cadence forces mispredictions: rollback rebuilds
        and discarded eager dispatches both happen, and (per the parity
        test above) the results are unchanged — the async/fused paths
        degrade to the blocking law instead of corrupting it."""
        _r, eng, led = fused
        s = eng.sync_stats
        assert s["fuse_rollbacks"] > 0
        assert led.cause_counts["rollback"] == s["fuse_rollbacks"]
        # the eager double-buffer resolved BOTH ways at least once
        assert s["async_dispatch_hits"] > 0
        assert s["async_dispatch_misses"] > 0

    def test_run_twice_byte_identical_with_fusion_and_async(
        self, tmp_path, fused
    ):
        rf, ef, _led = fused
        r2, e2, _led2 = _run(_cfg(tmp_path / "d"))
        assert r2.log_tuples() == rf.log_tuples()
        assert r2.counters == rf.counters
        assert {k: v for k, v in e2.sync_stats.items()
                if not isinstance(v, float)} == \
               {k: v for k, v in ef.sync_stats.items()
                if not isinstance(v, float)}


class TestRollbackEgressParity:
    def test_congested_rollback_bit_parity(self, tmp_path):
        """Validated-prefix deliveries whose down-bucket queueing delays
        ``t_deliver`` past the last validated window end must survive a
        rollback (re-read from the rebuild's egress buffer) — without
        that, the fused law silently drops them and diverges from the
        ``hybrid_fuse_k=1`` law under congestion."""
        rf, ef, _lf = _run(_congested_cfg(tmp_path / "f"))
        ru, eu, _lu = _run(_congested_cfg(tmp_path / "u", fuse_k=1))
        # the scenario is only probative while it actually rolls back
        assert ef.sync_stats["fuse_rollbacks"] > 0
        assert eu.sync_stats["fuse_rollbacks"] == 0
        assert rf.log_tuples() == ru.log_tuples()
        assert rf.rounds == ru.rounds
        assert _counters_mod_iters(rf) == _counters_mod_iters(ru)
        assert rf.per_host_counters == ru.per_host_counters


class TestDegenerateLaw:
    def test_fuse1_has_no_fusion_artifacts(self, unfused):
        _r, eng, led = unfused
        s = eng.sync_stats
        assert s["fused_dispatches"] == 0
        assert s["fused_windows"] == 0
        assert s["turns_saved"] == 0
        assert s["fuse_rollbacks"] == 0
        assert s["async_dispatch_hits"] == 0
        assert s["async_dispatch_misses"] == 0
        assert led.cause_counts["rollback"] == 0
        assert all(row[3] == 1 for row in led.rows)  # every row: 1 window
        assert led.turns_saved() == 0

    def test_fused_turn_count_drops(self, fused, unfused):
        _rf, ef, _lf = fused
        _ru, eu, _lu = unfused
        assert ef.sync_stats["device_turns"] < eu.sync_stats["device_turns"]


class TestLedgerAccounting:
    def test_conservation_with_free_run_rows(self, fused):
        _r, eng, led = fused
        rep = led.report("t")
        assert tmod.check_conservation(rep) is None
        assert rep["cause_counts"]["free_run"] > 0
        assert rep["turns"] == eng.sync_stats["device_turns"]
        fus = rep["fused"]
        assert rep["turns"] + fus["turns_saved"] == (
            fus["implied_unfused_turns"]
        )
        assert fus["turns_saved"] == eng.sync_stats["turns_saved"]
        # the engine-level cross-check (run at end-of-run too) agrees
        tmod.check_fusion_accounting(led, eng.sync_stats, 0.5)

    def test_covered_windows_invariant(self, fused, unfused):
        """The fusion changes how many dispatches carry the windows,
        never which windows run: covered participating windows plus
        remaining host-only rounds is invariant across the two laws."""
        _rf, _ef, lf = fused
        _ru, _eu, lu = unfused
        assert (
            lf.windows_covered_total + lf.host_rounds
            == lu.windows_covered_total + lu.host_rounds
        )

    def test_snapshot_lines_report_fused_stats(self, fused):
        _r, _eng, led = fused
        text = "\n".join(led.snapshot_lines())
        assert "fused runs:" in text
        assert "turn(s) saved" in text and "rollback(s)" in text


class TestLedgerUnitLaws:
    def test_fused_row_accounting(self):
        led = TurnLedger()
        led.turn("injection", 0, 10, inject_rows=2)          # 1 window
        led.turn("free_run", 10, 50, windows=4)              # fused
        led.turn("rollback", 10, 50, windows=0)              # rebuild
        led.finish()
        assert led.turns == 3 == sum(led.cause_counts.values())
        assert led.windows_covered_total == 5
        assert led.fused_turns == 1
        assert led.fused_windows_total == 4
        assert led.turns_saved() == 2  # 5 implied - 3 dispatches
        assert led.achieved_fusion() == round(5 / 3, 4)
        # rollback rows are neither fusable evidence nor primary
        assert led.empty_injection_turns == 1  # the free_run row only
        s = led.summary()
        assert s["rollbacks"] == 1 and s["turns_saved"] == 2

    def test_check_fusion_accounting_detects_drift(self):
        led = TurnLedger()
        led.turn("free_run", 0, 10, windows=3)
        tmod.check_fusion_accounting(led, {"turns_saved": 2})
        with pytest.raises(AssertionError):
            tmod.check_fusion_accounting(led, {"turns_saved": 1})

    def test_fuse_knob_validation(self):
        cfg = _cfg(Path("/tmp/x"), fuse_k=0)
        with pytest.raises(Exception):
            cfg.validate()


@SCALE
class TestGateScale:
    def test_fuse1_reproduces_pr7_pinned_turns(self, tmp_path):
        """The degenerate law at the gate scale: the exact 651 blocking
        turns PR 7/PR 11 pinned for managed_relay_chains_gate at 4 sim-s
        (make turns-smoke history)."""
        from shadow_tpu.config.scenarios import managed_relay_chains_gate

        cfg = managed_relay_chains_gate(
            tmp_path / "d", hybrid_workers=2, sim_seconds=4
        )
        cfg.experimental.hybrid_fuse_k = 1
        sim = Simulation(cfg)
        r = sim.run(write_data=False)
        assert not r.process_errors
        assert sim.engine.sync_stats["device_turns"] == 651

    def test_fused_gate_meets_2x_bar(self, tmp_path):
        from shadow_tpu.config.scenarios import managed_relay_chains_gate

        cfg = managed_relay_chains_gate(
            tmp_path / "d", hybrid_workers=2, sim_seconds=4
        )
        sim = Simulation(cfg)
        r = sim.run(write_data=False)
        assert not r.process_errors
        assert sim.engine.sync_stats["device_turns"] * 2 <= 651
