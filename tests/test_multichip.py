"""Multi-chip sharded lane plane (shadow_tpu/parallel/, docs/multichip.md).

The contracts under test (conftest.py forces 8 virtual CPU devices, so
every mesh shape here runs on any box):

1. **Device-count invariance** — the full Simulation facade with
   ``experimental.mesh_devices`` set produces a bit-identical event log
   AND a byte-identical ``NETOBS_*.json`` artifact at every mesh shape,
   netobs ON (the per-host counter block shards with its lanes, the
   [24] window histogram shard-then-reduces).
2. **Classification exhaustiveness** — ``parallel.check_classification``
   rejects unclassified, stale, and double-classified LaneState fields,
   so a future field cannot silently pick up the wrong sharding.
3. **Negotiation fallback law** — ``negotiate_devices`` never raises:
   over-asks and indivisible lane counts step down to the largest
   usable mesh.
4. **Columnar = classic** — the columnar 100k-host factory builds the
   same engine tables/params/initial events as the classic per-host
   walk, runs identically, and is rejected on the hybrid path.
5. **Hybrid transfer invariance** — the hybrid backend under a mesh
   keeps its ``sync_stats`` transfer counts and results unchanged
   (the host<->device boundary stays replicated).
"""

import copy
import json
import logging

import numpy as np
import pytest

from shadow_tpu import parallel
from shadow_tpu.backend import lanes
from shadow_tpu.backend.tpu_engine import LaneCompatError, TpuEngine
from shadow_tpu.config.columnar import columnar_mesh_config
from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.config.presets import flagship_mesh_config
from shadow_tpu.engine.sim import Simulation

pytestmark = pytest.mark.multichip


def _phold_cfg(data_dir, mesh_devices: int = 0) -> ConfigOptions:
    """8 phold hosts with netobs on — cheap to compile (2 pops/round)
    and divisible by every mesh shape up to 8."""
    return ConfigOptions.from_yaml(f"""
general: {{stop_time: 300ms, seed: 11, data_directory: {data_dir},
           heartbeat_interval: null}}
experimental: {{network_backend: tpu, netobs: true,
               tpu_events_per_round: 2,
               mesh_devices: {mesh_devices}}}
hosts:
  n:
    count: 8
    processes: [{{path: phold, args: --messages 3 --size 600}}]
""")


def _facade_run(tmp_path, d: int):
    """(log tuples, NETOBS bytes, engine) for a facade run at mesh
    request ``d`` (0 = single-device)."""
    sim = Simulation(_phold_cfg(tmp_path / f"d{d}", mesh_devices=d))
    res = sim.run(write_data=False)
    arts = sorted((tmp_path / f"d{d}").glob("NETOBS_*.json"))
    assert len(arts) == 1
    return res.log_tuples(), arts[0].read_bytes(), sim.engine


# -- 1. device-count invariance, netobs on --------------------------------


def test_facade_invariant_2dev(tmp_path):
    """Tier-1 slice of the invariance law (one box-affordable sharded
    compile); the 4/8-device shapes run below (slow) and at gate scale
    in ``make multichip-smoke``."""
    log1, netobs1, eng1 = _facade_run(tmp_path, 0)
    assert eng1.mesh is None
    assert log1  # a silent empty log would vacuously pass
    rep = json.loads(netobs1)
    assert rep["totals"]["sent"] > 0
    log2, netobs2, eng2 = _facade_run(tmp_path, 2)
    assert eng2.mesh is not None and eng2.mesh.devices.size == 2
    assert log2 == log1, "event log diverges at 2 devices"
    assert netobs2 == netobs1, "NETOBS diverges at 2 devices"


@pytest.mark.slow
def test_facade_invariant_4_and_8_dev(tmp_path):
    log1, netobs1, _ = _facade_run(tmp_path, 0)
    for d in (4, 8):
        log_d, netobs_d, eng_d = _facade_run(tmp_path, d)
        assert eng_d.mesh is not None
        assert eng_d.mesh.devices.size == d
        assert log_d == log1, f"event log diverges at {d} devices"
        assert netobs_d == netobs1, f"NETOBS diverges at {d} devices"


@pytest.mark.slow
def test_mesh_step_driver_matches_device(tmp_path):
    """The pausable step driver under a mesh (one sharded round per
    call) ends bit-identical to the fused sharded free-run."""
    eng_a = TpuEngine(_phold_cfg(tmp_path / "a"))
    eng_a.attach_mesh(parallel.make_mesh(2))
    ra = eng_a.run(mode="device")
    eng_b = TpuEngine(_phold_cfg(tmp_path / "b"))
    eng_b.attach_mesh(parallel.make_mesh(2))
    rb = eng_b.run(mode="step")
    assert ra.log_tuples() == rb.log_tuples()
    assert ra.counters == rb.counters


# -- 2. classification exhaustiveness -------------------------------------


def test_classification_covers_live_lanestate():
    parallel.check_classification()  # must not raise on the live fields


def test_classification_rejects_planted_field():
    fields = list(lanes.LaneState._fields) + ["planted_future_field"]
    with pytest.raises(AssertionError, match="planted_future_field"):
        parallel.check_classification(fields)


def test_classification_rejects_stale_field():
    fields = [f for f in lanes.LaneState._fields if f != "q_thi"]
    with pytest.raises(AssertionError, match="q_thi"):
        parallel.check_classification(fields)


def test_classification_partition_is_disjoint():
    assert not (parallel.LANE_FIELDS & parallel.REPLICATED_FIELDS)


# -- 3. negotiation fallback law ------------------------------------------


def test_negotiate_steps_down_to_divisor(caplog):
    with caplog.at_level(logging.WARNING, logger="shadow_tpu.parallel"):
        assert parallel.negotiate_devices(4, 6, available=8) == 3
    assert any("falling back" in r.message or "not divisible" in r.message
               for r in caplog.records)


def test_negotiate_caps_at_available():
    assert parallel.negotiate_devices(8, 8, available=2) == 2


def test_negotiate_never_exceeds_lanes():
    assert parallel.negotiate_devices(8, 1, available=8) == 1


def test_negotiate_all_available_default():
    assert parallel.negotiate_devices(None, 16, available=8) == 8


def test_negotiate_from_config_mesh_shape_alias():
    cfg = flagship_mesh_config(8, sim_seconds=1, backend="tpu")
    cfg.experimental.tpu_mesh_shape = (4,)
    assert parallel.negotiate_from_config(cfg, 8) == 4
    cfg.experimental.mesh_devices = 2  # explicit knob wins
    assert parallel.negotiate_from_config(cfg, 8) == 2


def test_engine_rejects_indivisible_mesh():
    cfg = flagship_mesh_config(6, sim_seconds=1, backend="tpu")
    eng = TpuEngine(cfg)
    with pytest.raises(LaneCompatError, match="divisible"):
        eng.attach_mesh(parallel.make_mesh(4))


# -- 4. columnar factory ---------------------------------------------------


def test_columnar_constants_match_lanes():
    from shadow_tpu.config import columnar as cmod

    assert cmod.M_TGEN_MESH == lanes.M_TGEN_MESH
    assert cmod.EV_LOCAL == lanes.LOCAL


def test_columnar_tables_equal_classic():
    import jax

    ea = TpuEngine(flagship_mesh_config(32, sim_seconds=1, backend="tpu"))
    eb = TpuEngine(columnar_mesh_config(32, sim_seconds=1))
    assert ea.params == eb.params
    la = jax.tree_util.tree_leaves(ea.tables)
    lb = jax.tree_util.tree_leaves(eb.tables)
    assert len(la) == len(lb)
    for a, b in zip(la, lb):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(ea._init_cols, eb._init_cols):
        assert np.array_equal(a, b)


def test_columnar_run_matches_classic(tmp_path):
    ra = TpuEngine(
        flagship_mesh_config(16, sim_seconds=1, backend="tpu")
    ).run(mode="device")
    rb = TpuEngine(columnar_mesh_config(16, sim_seconds=1)).run(
        mode="device"
    )
    assert ra.log_tuples() == rb.log_tuples()
    assert ra.counters == rb.counters


def test_columnar_hosts_match_classic_expansion():
    ca = flagship_mesh_config(5, sim_seconds=1, backend="tpu")
    cb = columnar_mesh_config(5, sim_seconds=1)
    assert [h.hostname for h in cb.hosts] == [
        h.hostname for h in ca.hosts
    ]
    assert len(cb.hosts) == 5 and cb.hosts[-1].processes[0].path == "tgen-mesh"


def test_columnar_rejected_on_hybrid():
    cfg = columnar_mesh_config(8, sim_seconds=1)
    ext = np.zeros(8, dtype=bool)
    ext[0] = True
    with pytest.raises(LaneCompatError, match="columnar"):
        TpuEngine(cfg, external=ext)


def test_columnar_100k_scale_builds_fast():
    """The acceptance bound, at 1/10 scale to keep tier-1 lean: table
    construction is vectorized, so 10k hosts must build in well under
    3 s (100k measured ~2 s end to end; scripts/multichip_smoke.py and
    the bench run the full 100k point)."""
    import time

    t0 = time.perf_counter()
    cfg = columnar_mesh_config(10_000, sim_seconds=1)
    eng = TpuEngine(cfg)
    eng.initial_state()
    assert time.perf_counter() - t0 < 3.0
    assert len(cfg.hosts) == 10_000
    assert int(eng.tables.model[0]) == lanes.M_TGEN_MESH


# -- 5. hybrid transfer invariance under mesh ------------------------------


TRANSFER_KEYS = ("device_turns", "inject_blocks", "inject_rows",
                 "inject_bytes", "egress_reads", "egress_rows",
                 "egress_bytes")


@pytest.fixture(scope="module")
def native_build():
    import subprocess
    from pathlib import Path

    repo = Path(__file__).resolve().parents[1]
    subprocess.run(
        ["make", "-C", str(repo / "native")], check=True,
        capture_output=True,
    )


@pytest.mark.hybrid
@pytest.mark.slow
def test_hybrid_sync_stats_unchanged_under_mesh(tmp_path, native_build):
    from tests.test_turns import _hybrid_cfg

    base = Simulation(_hybrid_cfg(tmp_path / "h0", workers=1, turns=False))
    r0 = base.run(write_data=False)
    s0 = dict(base.engine.sync_stats)
    cfg = _hybrid_cfg(tmp_path / "h2", workers=1, turns=False)
    cfg.experimental.mesh_devices = 2
    meshed = Simulation(cfg)
    r2 = meshed.run(write_data=False)
    s2 = dict(meshed.engine.sync_stats)
    assert meshed.engine.device.mesh is not None
    assert meshed.engine.device.mesh.devices.size == 2
    assert r2.log_tuples() == r0.log_tuples()
    for k in TRANSFER_KEYS:
        assert s2.get(k) == s0.get(k), f"sync_stats[{k}] changed under mesh"
