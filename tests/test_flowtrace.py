"""Per-flow packet-lifecycle tracing (obs/flowtrace.py,
docs/observability.md).

Contracts under test:

1. **Device ↔ oracle event parity** — the canonical flowtrace event
   stream (send, token-bucket wait, queue-enter, drop-with-cause,
   retransmit, delivery; each stamped with sim-time/window/src/dst/
   seq/size) bit-identical between the TPU/lane path and the CPU oracle
   on a drop-heavy scenario, a lossy stream-flow scenario (retransmit
   coverage), and the mixed flagship mesh, on fused and step drivers.
2. **Run-twice / worker-count determinism** — byte-identical
   ``FLOWS_*.json`` on the cpu backend; the cpu_mp engine's merged
   stream equals the serial oracle at any worker count.
3. **Sampling determinism** — the device flow hash equals the Python
   hash bit-for-bit, so device and oracle select the same flows; a
   sampled run's stream is a strict subset and still bit-identical.
4. **Ring-overflow law** — a full device ring stops recording (never
   wraps), counts the excess into ``events_lost``, and the kept+lost
   total conserves against the oracle; the loss surfaces as the
   ``flow_events_lost`` metrics counter.
5. **Zero overhead when off** — engines default flowtrace-off with no
   state allocated; the LaneParams guards pin the untiered-only law.
6. **Console ``flows`` verb** — run-control answers live at a paused
   boundary; ``stats`` folds the one-line summary.
7. **Hybrid** — byte-identical run-twice FLOWS artifacts, the merged
   host/device split covers the stream, worker-count invariance, and
   ZERO new host↔device transfers (sync_stats unchanged vs off).
"""

import copy
import json
import subprocess
from pathlib import Path

import numpy as np
import pytest

from shadow_tpu.config.options import ConfigError, ConfigOptions
from shadow_tpu.engine.sim import Simulation
from shadow_tpu.obs import flowtrace as ftr

pytestmark = pytest.mark.obs

REPO = Path(__file__).resolve().parents[1]
BUILD = REPO / "native" / "build"


# ---------------------------------------------------------------------------
# configs (the netobs scenario family, flowtrace plane on)
# ---------------------------------------------------------------------------


def _drop_heavy_cfg(data_dir="/tmp/flowtrace-droppy", seed=11,
                    backend="cpu", stop="1500ms",
                    sample=1.0) -> ConfigOptions:
    """Loss on the link + oversubscribed buckets: loss drops, codel
    drops, and token-bucket waits all nonzero."""
    return ConfigOptions.from_yaml(f"""
general: {{stop_time: {stop}, seed: {seed}, data_directory: {data_dir},
           heartbeat_interval: null}}
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 host_bandwidth_up "2 Mbit" host_bandwidth_down "1 Mbit" ]
        edge [ source 0 target 0 latency "10 ms" packet_loss 0.05 ]
      ]
experimental: {{network_backend: {backend}, flowtrace: true,
               flowtrace_sample: {sample},
               tpu_lane_queue_capacity: 2048}}
hosts:
  srv:
    network_node_id: 0
    processes: [{{path: tgen-server}}]
  cli:
    count: 6
    network_node_id: 0
    processes:
      - path: tgen-client
        args: --server srv --interval 5ms --size 1400
""")


def _lossy_stream_cfg(data_dir="/tmp/flowtrace-stream",
                      backend="tpu") -> ConfigOptions:
    """Two-host lane-TCP transfer over a lossy link: the retransmit
    lifecycle stage (FT_RETRANSMIT joins on the NEW wire seq)."""
    return ConfigOptions.from_yaml(f"""
general: {{stop_time: 6s, seed: 5, data_directory: {data_dir},
           heartbeat_interval: null, bootstrap_end_time: 100ms}}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "20 Mbit" host_bandwidth_down "20 Mbit" ]
        node [ id 1 host_bandwidth_up "20 Mbit" host_bandwidth_down "20 Mbit" ]
        edge [ source 0 target 1 latency "10 ms" packet_loss 0.02 ]
      ]
experimental: {{network_backend: {backend}, flowtrace: true,
               tpu_lane_queue_capacity: 128}}
hosts:
  c:
    network_node_id: 0
    processes:
      - path: stream-client
        args: --server s --size 400000
  s:
    network_node_id: 1
    processes:
      - path: stream-server
""")


def _phold_cfg(data_dir="/tmp/flowtrace-phold", backend="tpu",
               capacity=65536) -> ConfigOptions:
    """Small phold ring: cheap lane program for overflow/artifact tests."""
    return ConfigOptions.from_yaml(f"""
general: {{stop_time: 1s, seed: 3, data_directory: {data_dir},
           heartbeat_interval: null}}
experimental: {{network_backend: {backend}, flowtrace: true,
               flowtrace_capacity: {capacity}}}
hosts:
  n:
    count: 8
    processes: [{{path: phold, args: --messages 3 --size 600}}]
""")


def _canon(snap, capacity=1 << 20):
    ev, lost = ftr.canonical_events(snap["raw"], capacity)
    return ev, lost + snap["ring_lost"]


def _streams(cfg_tpu, mode="device"):
    """(cpu events, tpu events) for the same config, with the log
    parity precondition asserted and no event loss on either side."""
    from shadow_tpu.backend.cpu_engine import CpuEngine
    from shadow_tpu.backend.tpu_engine import TpuEngine

    cfg_cpu = copy.deepcopy(cfg_tpu)
    cfg_cpu.experimental.network_backend = "cpu"
    ce = CpuEngine(cfg_cpu)
    r1 = ce.run()
    te = TpuEngine(cfg_tpu)
    r2 = te.run(mode=mode)
    assert r1.log_tuples() == r2.log_tuples()
    ec, lc = _canon(ce.flowtrace_snapshot())
    et, lt = _canon(te.flowtrace_snapshot())
    assert lc == 0 and lt == 0  # parity is asserted at zero loss only
    return ec, et


# ---------------------------------------------------------------------------
# 1. device <-> oracle event parity
# ---------------------------------------------------------------------------


class TestDeviceOracleParity:
    def test_drop_heavy_parity_fused(self):
        ec, et = _streams(_drop_heavy_cfg(backend="tpu"))
        assert ec == et
        kinds = {e[2] for e in ec}
        # the scenario exercises the lifecycle: sends, bucket waits,
        # queue entries, loss AND codel drops, deliveries
        assert {ftr.FT_SEND, ftr.FT_TB_WAIT, ftr.FT_QUEUE_ENTER,
                ftr.FT_DROP, ftr.FT_DELIVERY} <= kinds
        causes = {e[7] for e in ec if e[2] == ftr.FT_DROP}
        assert {ftr.CAUSE_LOSS, ftr.CAUSE_CODEL} <= causes

    def test_drop_heavy_parity_step_driver(self):
        ec, et = _streams(
            _drop_heavy_cfg(backend="tpu", seed=12, stop="600ms"),
            mode="step",
        )
        assert ec == et

    def test_lossy_stream_parity_retransmits(self):
        ec, et = _streams(_lossy_stream_cfg(backend="tpu"))
        assert ec == et
        retx = [e for e in ec if e[2] == ftr.FT_RETRANSMIT]
        assert retx  # the lossy link forced retries
        # every retransmit is a full wire packet: its (src, dst, seq)
        # either delivers or drops downstream, same as a first send
        seqs = {(e[3], e[4], e[5]) for e in ec
                if e[2] in (ftr.FT_DELIVERY, ftr.FT_DROP)}
        assert any((e[3], e[4], e[5]) in seqs for e in retx)

    def test_mixed_mesh_parity_tier_fallback(self):
        from shadow_tpu.backend.tpu_engine import TpuEngine
        from shadow_tpu.config.presets import mixed_flagship_config

        cfg = mixed_flagship_config(40, sim_seconds=1)
        cfg.experimental.flowtrace = True
        # flowtrace instruments the untiered path only: the engine falls
        # back (equivalent execution) — queue headroom for the flat path
        cfg.experimental.tpu_lane_queue_capacity = 4096
        assert TpuEngine(cfg).params.stream_tiered is False
        ec, et = _streams(cfg)
        assert ec == et
        assert len(ec) > 0

    def test_sampled_subset_parity(self):
        full, _ = _streams(_drop_heavy_cfg(backend="tpu"))
        ec, et = _streams(_drop_heavy_cfg(backend="tpu", sample=0.5))
        assert ec == et
        assert 0 < len(ec) < len(full)
        # the sampled stream is exactly the full stream restricted to
        # the selected pairs (no event mutation, pure flow selection)
        pairs = {(e[3], e[4]) for e in ec}
        assert ec == [e for e in full if (e[3], e[4]) in pairs]


# ---------------------------------------------------------------------------
# 2. run-twice byte-identical FLOWS artifacts; worker invariance
# ---------------------------------------------------------------------------


class TestFlowsDeterminism:
    def test_cpu_flows_artifact_byte_identical(self, tmp_path):
        blobs = []
        for tag in ("r1", "r2"):
            sim = Simulation(_drop_heavy_cfg(tmp_path / tag))
            sim.run(write_data=False)
            arts = sorted((tmp_path / tag).glob("FLOWS_*.json"))
            assert len(arts) == 1
            blobs.append(arts[0].read_bytes())
        assert blobs[0] == blobs[1]
        rep = json.loads(blobs[0])
        assert rep["schema"] == ftr.SCHEMA_VERSION
        assert rep["events_lost"] == 0
        assert rep["num_events"] == len(rep["events"])
        assert rep["events_by_kind"]["drop"] > 0
        assert rep["num_flows"] == len(rep["flows"])
        # per-flow conservation: sends == delivered + drops + in flight
        for fl in rep["flows"].values():
            assert fl["sends"] >= fl["delivered"] + sum(fl["drops"].values())
        # burst attribution names flow classes per occupancy bucket
        buckets = rep["burst_attribution"]["buckets"]
        assert buckets and all(b["top_classes"] for b in buckets)

    def test_cpu_mp_worker_invariance(self, tmp_path):
        from shadow_tpu.backend.cpu_engine import CpuEngine
        from shadow_tpu.backend.cpu_mp import MpCpuEngine

        names = [h.hostname for h in _drop_heavy_cfg(tmp_path / "n").hosts]

        def report(snap):
            ev, lost = _canon(snap, 65536)
            return json.dumps(
                ftr.build_report("t", "cpu", 11, names, ev, lost, 0,
                                 True, 65536),
                sort_keys=True,
            )

        ser = CpuEngine(_drop_heavy_cfg(tmp_path / "ser"))
        ser.run()
        rs = report(ser.flowtrace_snapshot())
        for w in (2, 4):
            eng = MpCpuEngine(_drop_heavy_cfg(tmp_path / f"w{w}"),
                              workers=w)
            eng.run()
            snap = eng.flowtrace_snapshot()
            assert snap is not None
            assert report(snap) == rs, f"workers={w}"

    def test_tpu_flows_artifact_via_facade(self, tmp_path):
        sim = Simulation(_phold_cfg(tmp_path / "r1", capacity=131072))
        sim.run(write_data=False)
        arts = sorted((tmp_path / "r1").glob("FLOWS_*.json"))
        assert len(arts) == 1
        rep = json.loads(arts[0].read_text())
        assert rep["backend"] == "tpu"
        assert rep["num_events"] > 0
        counters = sim.obs.metrics.counters()
        assert counters["flow_events"] == rep["num_events"]
        assert counters.get("flow_events_lost", 0) == 0


# ---------------------------------------------------------------------------
# 3. sampling determinism (device hash == python hash)
# ---------------------------------------------------------------------------


class TestSamplingDeterminism:
    def test_device_hash_matches_python(self):
        import jax.numpy as jnp

        from shadow_tpu.backend import lanes

        n = 24
        for seed in (0, 1, 11, 12345):
            py = np.array(
                [[ftr.flow_hash(s, d, 0, seed) for d in range(n)]
                 for s in range(n)],
                dtype=np.uint32,
            )
            dev = np.asarray(lanes.flow_hash_lane(
                jnp.asarray(np.repeat(np.arange(n, dtype=np.int32), n)),
                jnp.asarray(np.tile(np.arange(n, dtype=np.int32), n)),
                jnp.int32(seed),
            )).astype(np.uint32).reshape(n, n)
            assert np.array_equal(py, dev), f"seed={seed}"

    def test_sample_thresh_edges(self):
        assert ftr.sample_thresh(1.0) == (0, True)   # all flows record
        assert ftr.sample_thresh(0.0) == (0, False)  # none record
        thresh, all_pass = ftr.sample_thresh(0.5)
        assert not all_pass and 0 < thresh < (1 << 32)

    def test_sampled_selection_is_seed_stable(self):
        ft1 = ftr.FlowTrace(16, seed=7, sample=0.5, capacity=64)
        ft2 = ftr.FlowTrace(16, seed=7, sample=0.5, capacity=64)
        sel1 = {(s, d) for s in range(16) for d in range(16)
                if ft1.sampled(s, d)}
        assert sel1 == {(s, d) for s in range(16) for d in range(16)
                        if ft2.sampled(s, d)}
        assert 0 < len(sel1) < 256
        # a different seed picks a different subset
        ft3 = ftr.FlowTrace(16, seed=8, sample=0.5, capacity=64)
        assert sel1 != {(s, d) for s in range(16) for d in range(16)
                        if ft3.sampled(s, d)}


# ---------------------------------------------------------------------------
# 4. ring-overflow law
# ---------------------------------------------------------------------------


class TestOverflowLaw:
    def test_device_ring_never_wraps_and_conserves(self):
        import copy as _copy

        from shadow_tpu.backend.cpu_engine import CpuEngine
        from shadow_tpu.backend.tpu_engine import TpuEngine

        cfg = _phold_cfg("/tmp/flowtrace-ovf", capacity=32)
        te = TpuEngine(cfg)
        te.run(mode="device")
        snap = te.flowtrace_snapshot()
        # full ring: exactly `capacity` rows kept, the rest counted
        assert len(snap["raw"]) == 32
        assert snap["ring_lost"] > 0
        cfg_c = _copy.deepcopy(cfg)
        cfg_c.experimental.network_backend = "cpu"
        ce = CpuEngine(cfg_c)
        ce.run()
        total = len(ce.flowtrace_snapshot()["raw"])
        # conservation: device kept + lost == the oracle's full stream
        assert len(snap["raw"]) + snap["ring_lost"] == total
        # the oracle's canonical truncation mirrors the law
        ev, lost = ftr.canonical_events(ce.flowtrace_snapshot()["raw"], 32)
        assert len(ev) == 32 and lost == total - 32

    def test_overflow_surfaces_as_metric(self, tmp_path):
        sim = Simulation(_phold_cfg(tmp_path / "ovf", capacity=32))
        sim.run(write_data=False)
        counters = sim.obs.metrics.counters()
        assert counters["flow_events_lost"] > 0
        rep = json.loads(
            next((tmp_path / "ovf").glob("FLOWS_*.json")).read_text()
        )
        assert rep["events_lost"] == counters["flow_events_lost"]
        assert rep["num_events"] <= 32


# ---------------------------------------------------------------------------
# 5. off = zero overhead; config + LaneParams guards
# ---------------------------------------------------------------------------


class TestOffPathAndGuards:
    def test_engines_default_flowtrace_off(self):
        from shadow_tpu.backend.cpu_engine import CpuEngine
        from shadow_tpu.backend.tpu_engine import TpuEngine

        cfg = _drop_heavy_cfg("/tmp/flowtrace-off")
        cfg.experimental.flowtrace = False
        assert CpuEngine(cfg).flowtrace is None
        te = TpuEngine(cfg)
        assert te.params.flowtrace is False
        state = te.initial_state()
        # the whole plane compiles away: no ring, no cursor, no counter
        assert state.fl_buf == () and state.fl_count == ()
        assert state.fl_lost == ()
        assert te.flowtrace_snapshot() is None

    def test_config_validation(self):
        cfg = _drop_heavy_cfg("/tmp/flowtrace-val")
        cfg.experimental.flowtrace_capacity = 0
        with pytest.raises(ConfigError, match="flowtrace_capacity"):
            cfg.validate()
        cfg = _drop_heavy_cfg("/tmp/flowtrace-val")
        cfg.experimental.flowtrace_sample = 1.5
        with pytest.raises(ConfigError, match="flowtrace_sample"):
            cfg.validate()

    def test_laneparams_untiered_only_guard(self):
        from shadow_tpu.backend import lanes

        base = dict(
            n_lanes=2, capacity=8, pops_per_iter=2, log_capacity=0,
            seed=1, stop_time=1000, bootstrap_end=0, runahead=100,
        )
        with pytest.raises(ValueError, match="stream_tiered"):
            lanes.LaneParams(
                **base, flowtrace=True, flow_capacity=16,
                stream_tiered=True,
            )
        with pytest.raises(ValueError, match="flow_capacity"):
            lanes.LaneParams(**base, flowtrace=True, flow_capacity=0)


# ---------------------------------------------------------------------------
# 6. console `flows` verb (run-control)
# ---------------------------------------------------------------------------


class TestFlowsVerb:
    def test_flows_verb_not_enabled(self):
        import io

        from shadow_tpu.engine.run_control import RunControl

        out = io.StringIO()
        rc = RunControl(out=out)
        rc._apply("flows")
        assert "flowtrace is not enabled" in out.getvalue()

    def test_flows_verb_with_sink(self):
        import io

        from shadow_tpu.engine.run_control import RunControl

        events = [
            (1000, 10_000_000, ftr.FT_SEND, 0, 1, 5, 1400, 0),
            (2000, 10_000_000, ftr.FT_DELIVERY, 0, 1, 5, 1400, 0),
        ]
        out = io.StringIO()
        rc = RunControl(out=out)
        rc.set_flows_sink(
            lambda host: ftr.snapshot_lines(events, 0, ["a", "b"], host)
        )
        rc._apply("flows")
        text = out.getvalue()
        assert "events=2" in text
        assert "a->b" in text

    def test_flows_live_at_pause_and_stats_fold(self, tmp_path):
        import io

        from shadow_tpu.engine.run_control import RunControl

        out = io.StringIO()
        rc = RunControl(out=out, poll_interval=0.01, max_wait=10)
        rc.feed("p", "flows", "stats", "c")
        sim = Simulation(_drop_heavy_cfg(tmp_path / "d"), run_control=rc)
        sim.run(write_data=False)
        text = out.getvalue()
        assert "[run-control] flows:" in text
        # `stats` folds the one-line flow summary next to the metrics
        assert "flows: sampled_pairs=" in text


# ---------------------------------------------------------------------------
# 7. hybrid: determinism + zero new syncs (native binaries required)
# ---------------------------------------------------------------------------


def _hybrid_cfg(data_dir, ft=True) -> ConfigOptions:
    mesh = "\n".join(f"""
  zm{i:03d}:
    network_node_id: 0
    processes:
      - path: tgen-mesh
        args: --interval 50ms --size 600
        start_time: 0 s
""" for i in range(4))
    return ConfigOptions.from_yaml(f"""
general: {{stop_time: 1s, seed: 21, data_directory: {data_dir},
           heartbeat_interval: null}}
network: {{graph: {{type: 1_gbit_switch}}}}
experimental: {{network_backend: tpu, flowtrace: {str(ft).lower()}}}
hosts:
  cli:
    network_node_id: 0
    processes:
      - path: {BUILD / 'pingpong'}
        args: [client, 11.0.0.2, "9000", "3", "100"]
  srv:
    network_node_id: 0
    processes:
      - path: {BUILD / 'pingpong'}
        args: [server, "9000", "3"]
{mesh}
""")


@pytest.mark.hybrid
class TestFlowsHybrid:
    @pytest.fixture(scope="class", autouse=True)
    def native_build(self):
        subprocess.run(
            ["make", "-C", str(REPO / "native")],
            check=True, capture_output=True,
        )

    def test_hybrid_flows_byte_identical_and_sync_invariant(
        self, tmp_path
    ):
        blobs, syncs = [], []
        for tag in ("r1", "r2"):
            sim = Simulation(_hybrid_cfg(tmp_path / tag))
            sim.run(write_data=False)
            arts = sorted((tmp_path / tag).glob("FLOWS_*.json"))
            assert len(arts) == 1
            blobs.append(arts[0].read_bytes())
            syncs.append(dict(sim.engine.sync_stats))
        assert blobs[0] == blobs[1]
        rep = json.loads(blobs[0])
        # the split covers the stream: host-emitted sends (managed +
        # loopback) join device-emitted arrivals in one canonical order
        assert rep["events_by_kind"]["send"] > 0
        assert rep["events_by_kind"]["delivery"] > 0
        assert rep["num_flows"] > 0

        # zero new per-window host syncs: the flowtrace-OFF run moves
        # exactly the same transfers (the ring drains at collect only)
        cfg_off = _hybrid_cfg(tmp_path / "off", ft=False)
        sim_off = Simulation(cfg_off)
        sim_off.run(write_data=False)
        off = sim_off.engine.sync_stats
        for key in ("scalar_reads", "inject_blocks", "egress_reads",
                    "device_turns"):
            assert off[key] == syncs[0][key] == syncs[1][key], key

    def test_hybrid_worker_invariance(self, tmp_path):
        blobs = {}
        for hw in (1, 2):
            cfg = _hybrid_cfg(tmp_path / f"hw{hw}")
            cfg.experimental.hybrid_workers = hw
            sim = Simulation(cfg)
            sim.run(write_data=False)
            arts = sorted((tmp_path / f"hw{hw}").glob("FLOWS_*.json"))
            assert len(arts) == 1
            blobs[hw] = arts[0].read_bytes()
        assert blobs[1] == blobs[2]
