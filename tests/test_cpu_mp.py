"""The fork-based parallel CPU backend produces results identical to the
serial engine for every pure-model workload — the parallelism-invariance
law the reference's determinism suite enforces across worker counts."""

import pytest

from shadow_tpu.backend.cpu_engine import CpuEngine
from shadow_tpu.backend.cpu_mp import MpCpuEngine
from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.config.presets import flagship_mesh_config

PHOLD = """
general: {stop_time: 500ms, seed: 7}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        node [ id 1 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        edge [ source 0 target 0 latency "2 ms" ]
        edge [ source 0 target 1 latency "5 ms" ]
        edge [ source 1 target 1 latency "2 ms" ]
      ]
hosts:
  a: {network_node_id: 0, processes: [{path: phold, args: [--messages, "3"]}]}
  b: {network_node_id: 1, processes: [{path: phold, args: [--messages, "3"]}]}
  c: {network_node_id: 1, processes: [{path: phold, args: [--messages, "2"]}]}
  d: {network_node_id: 0, processes: [{path: phold, args: [--messages, "2"]}]}
"""


@pytest.mark.parametrize("workers", [2, 3])
def test_phold_parallel_matches_serial(workers):
    serial = CpuEngine(ConfigOptions.from_yaml(PHOLD)).run()
    par = MpCpuEngine(ConfigOptions.from_yaml(PHOLD), workers=workers).run()
    assert len(serial.event_log) > 50
    assert par.log_tuples() == serial.log_tuples()
    assert par.counters == serial.counters
    assert par.rounds == serial.rounds


def test_mesh_parallel_matches_serial():
    cfg = flagship_mesh_config(24, sim_seconds=2, backend="cpu")
    serial = CpuEngine(cfg).run()
    cfg2 = flagship_mesh_config(24, sim_seconds=2, backend="cpu")
    par = MpCpuEngine(cfg2, workers=4).run()
    assert par.log_tuples() == serial.log_tuples()
    assert par.counters == serial.counters


def test_mixed_stream_parallel_matches_serial():
    cfg = flagship_mesh_config(20, sim_seconds=2, queue_capacity=48,
                               stream_pairs=2, stream_bytes=150_000,
                               backend="cpu")
    serial = CpuEngine(cfg).run()
    cfg2 = flagship_mesh_config(20, sim_seconds=2, queue_capacity=48,
                                stream_pairs=2, stream_bytes=150_000,
                                backend="cpu")
    par = MpCpuEngine(cfg2, workers=3).run()
    assert par.log_tuples() == serial.log_tuples()
    assert par.counters == serial.counters
    assert par.counters.get("stream_flows_done") == 2


def test_managed_processes_accepted(tmp_path):
    """Managed hosts are supported since round 5 (each OS process
    launches in its owning worker; see tests/test_managed_scale.py for
    the parity and scale gates) — construction must NOT raise."""
    import subprocess
    from pathlib import Path

    repo = Path(__file__).resolve().parents[1]
    subprocess.run(["make", "-C", str(repo / "native")], check=True,
                   capture_output=True)
    cfg = ConfigOptions.from_yaml(f"""
general: {{stop_time: 1s, seed: 7, data_directory: {tmp_path}}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  solo:
    network_node_id: 0
    processes: [{{path: {repo / 'native' / 'build' / 'spinner'}}}]
""")
    eng = MpCpuEngine(cfg, workers=2)
    assert eng.workers >= 1  # construction succeeded; pcap stays refused
